#!/usr/bin/env python
"""NCHW vs NHWC conv layout experiment (VERDICT r1 weak 6).

Hypothesis under test: the flagship step keeps NCHW at the API and
"trusts XLA relayout"; an NHWC-native path might cut HBM bytes.  This
script runs an identical conv+bn+relu training tower in both logical
layouts on the real device, and prints wall time plus the compiled
module's cost analysis (bytes accessed / flops) for each.

Usage: python tools/bench_layout_experiment.py [--batch 128] [--steps 20]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_tower(channel_last, depth=16, width=64):
    """conv3x3 + batchnorm-ish (per-channel scale/shift) + relu tower
    with a downsample every 4 layers — the ResNet trunk's byte/flop
    profile without the Gluon layer."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rs = np.random.RandomState(0)
    params = []
    cin = 3
    for i in range(depth):
        cout = width * (1 + i // 4)
        w = rs.randn(cout, cin, 3, 3).astype(np.float32) * 0.05
        if channel_last:
            w = w.transpose(2, 3, 1, 0)  # HWIO
        params.append((jnp.asarray(w),
                       jnp.ones((cout,), jnp.float32),
                       jnp.zeros((cout,), jnp.float32)))
        cin = cout

    if channel_last:
        dn = lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1),
                                        ("NHWC", "HWIO", "NHWC"))
        def scale(x, g, b):
            return x * g + b
    else:
        dn = lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1),
                                        ("NCHW", "OIHW", "NCHW"))
        def scale(x, g, b):
            return x * g[:, None, None] + b[:, None, None]

    def forward(params, x):
        for i, (w, g, b) in enumerate(params):
            stride = 2 if (i % 4 == 3) else 1
            x = lax.conv_general_dilated(
                x, w, (stride, stride), [(1, 1), (1, 1)],
                dimension_numbers=dn)
            x = jax.nn.relu(scale(x, g, b))
        return jnp.mean(x)

    def train_step(params, x):
        loss, grads = jax.value_and_grad(forward)(params, x)
        return loss, jax.tree_util.tree_map(
            lambda p, gr: p - 0.01 * gr, params, grads)

    return params, train_step


def run(channel_last, batch, steps, hw=112):
    import jax
    import jax.numpy as jnp

    params, train_step = build_tower(channel_last)
    shape = (batch, hw, hw, 3) if channel_last else (batch, 3, hw, hw)
    x = jnp.asarray(np.random.RandomState(1).rand(*shape)
                    .astype(np.float32))
    jitted = jax.jit(train_step)
    lowered = jitted.lower(params, x)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    loss, params = jitted(params, x)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params = jitted(params, x)
    float(loss)
    dt = time.perf_counter() - t0
    return {
        "layout": "NHWC" if channel_last else "NCHW",
        "img_s": round(steps * batch / dt, 1),
        "bytes_accessed_GB": round(cost.get("bytes accessed", 0) / 1e9, 3),
        "gflops": round(cost.get("flops", 0) / 1e9, 1),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args(argv)
    for channel_last in (False, True):
        print(json.dumps(run(channel_last, args.batch, args.steps)))


if __name__ == "__main__":
    main()
