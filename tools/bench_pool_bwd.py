"""Benchmark: Pallas max-pool backward vs XLA's select-and-scatter
(BENCH_ROOFLINE.md: 765 us at 0.1% MXU in the flagship step).

Same chained fetch-barrier method as tools/bench_conv_dw.py (whose
bench_impl this reuses).  The flagship shape is the ResNet stem pool:
bs=128, 112x112x64 -> 56x56x64, 3x3/s2/p1.

Usage: python tools/bench_pool_bwd.py [--batch 128] [--depths 8,24]
       [--out table.md]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_conv_dw import bench_impl  # noqa: E402

SHAPES = [
    ("stem.pool.112-56", (112, 112, 64), (3, 3), (2, 2), (1, 1)),
    ("pool.56-28", (56, 56, 128), (3, 3), (2, 2), (1, 1)),
    ("pool.2x2.56-28", (56, 56, 64), (2, 2), (2, 2), (0, 0)),
]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--depths", default="8,24")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mxnet_tpu.ops.pallas_pool import maxpool_bwd_nhwc

    depths = tuple(int(d) for d in args.depths.split(","))
    dtype = jnp.dtype(args.dtype)
    rs = np.random.RandomState(0)

    lines = ["| shape | impl | ms/iter | GB/s moved | vs XLA |",
             "|---|---|---|---|---|"]

    def emit(line):
        print(line, flush=True)
        lines.append(line)

    for name, (h, w, c), k, s, p in SHAPES:
        oh = (h + 2 * p[0] - k[0]) // s[0] + 1
        ow = (w + 2 * p[1] - k[1]) // s[1] + 1
        x = jnp.asarray(rs.rand(args.batch, h, w, c), dtype)
        dy = jnp.asarray(rs.rand(args.batch, oh, ow, c), dtype)
        # minimal HBM bytes: read x + dy, write dx
        gb = (2 * x.size + dy.size) * x.dtype.itemsize / 1e9

        def xla_bwd(xv, dyv, k=k, s=s, p=p):
            def pool(v):
                return lax.reduce_window(
                    v, -jnp.inf, lax.max, (1,) + k + (1,),
                    (1,) + s + (1,),
                    [(0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0)])

            _, vjp = jax.vjp(pool, xv)
            return vjp(dyv)[0]

        t_xla = bench_impl(xla_bwd, x, dy, depths)
        emit("| %s | xla | %.3f | %.1f | 1.00x |"
             % (name, t_xla * 1e3, gb / t_xla))
        try:
            t_pal = bench_impl(
                lambda xv, dyv: maxpool_bwd_nhwc(xv, dyv, k, s, p),
                x, dy, depths)
            emit("| %s | pallas | %.3f | %.1f | %.2fx |"
                 % (name, t_pal * 1e3, gb / t_pal, t_xla / t_pal))
        except Exception as e:
            emit("| %s | pallas | FAILED: %s | | |" % (name, str(e)[:80]))

    if args.out:
        with open(args.out, "w") as f:
            f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
