#!/usr/bin/env python
"""Pipeline-fed training benchmark (VERDICT r1 weak-spot 5).

Measures three things on the same ResNet-50 config so the data-path
cost is attributable (reference methodology: train_imagenet.py measures
end-to-end, docs/faq/perf.md):

1. ``pipeline``  — native RecordIO pipeline alone (chunked reads,
   shuffle buffer, worker decode; mxnet_tpu/native/src/pipeline.cc).
2. ``e2e``       — pipeline feeding GluonTrainStep with async overlap:
   jax dispatch is non-blocking, so the device executes step N while
   the host decodes batch N+1; the only sync is the final loss fetch.
3. ``synthetic`` — device-resident batch (bench.py's configuration),
   the device-compute ceiling.

Usage: python tools/bench_pipeline.py [--batch 128] [--steps 16]
       [--hw 224] [--mode all|pipeline|e2e|synthetic]
Prints one JSON line per mode.
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "example", "image-classification"))


def make_iter(batch, hw, nthreads, num=1024):
    from common import data as common_data

    import mxnet_tpu as mx

    path = os.path.join(tempfile.gettempdir(),
                        "bench_pipeline_%d_%d.rec" % (hw, num))
    if not os.path.exists(path):
        common_data.synthetic_rec_file(path, num=num, classes=10, hw=hw)
    return mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=(3, hw, hw), batch_size=batch,
        shuffle=True, rand_mirror=True, preprocess_threads=nthreads)


def make_raw_iter(batch, hw, nthreads, num=256):
    """Raw float32 records: the C++ pipeline's built-in decoder path
    (pipeline.cc DecodeRaw) — no Python/PIL in the loop, so this is the
    IO+shuffle+assembly machinery's own ceiling."""
    import mxnet_tpu as mx
    from mxnet_tpu.recordio import IRHeader, MXRecordIO, pack

    path = os.path.join(tempfile.gettempdir(),
                        "bench_pipeline_raw_%d.rec" % hw)
    if not os.path.exists(path):
        rs = np.random.RandomState(0)
        rec = MXRecordIO(path, "w")
        for i in range(num):
            arr = rs.rand(3, hw, hw).astype(np.float32)
            rec.write(pack(IRHeader(0, float(i % 10), i, 0), arr.tobytes()))
        rec.close()
    return mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=(3, hw, hw), batch_size=batch,
        shuffle=True, preprocess_threads=nthreads, raw_records=True)


def _warm_epoch(it):
    """One full pass: fills the OS page cache and the pipeline's
    prefetch/shuffle machinery so the measurement sees steady state."""
    for _ in it:
        pass
    it.reset()


def bench_pipeline(batch, steps, hw, nthreads, raw=False, epochs=2):
    """Whole-epoch measurement (incl. reset/shuffle-refill) — what a
    training loop actually sees; `steps` is ignored in favor of epochs."""
    it = make_raw_iter(batch, hw, nthreads) if raw \
        else make_iter(batch, hw, nthreads)
    _warm_epoch(it)
    # measure at the HOST boundary (numpy batches out of the C++ pipe):
    # wrapping into device NDArrays belongs to the e2e number — on a
    # tunneled dev chip it costs a relay round-trip per batch and would
    # hide the pipeline's own rate
    if it._pipe is None:
        raise RuntimeError(
            "pipeline mode measures the native C++ pipe at the host "
            "boundary; the Python fallback would wrap every batch in a "
            "device NDArray and measure the upload link instead")
    t0 = time.perf_counter()
    done = 0
    for _ in range(epochs):
        while it._pipe.has_next():
            it._pipe.next()
            done += 1
        it.reset()
    dt = time.perf_counter() - t0
    return done * batch / dt


def make_det_rec(hw=300, num=512, max_boxes=4):
    """Synthetic packed-label detection .rec (VOC-style: JPEG scenes +
    [header, obj_width, (cls x1 y1 x2 y2)*] labels, the im2rec
    --pack-label wire format)."""
    import mxnet_tpu as mx  # noqa: F401  (registers recordio deps)
    from mxnet_tpu.recordio import (IRHeader, MXIndexedRecordIO,
                                    pack_img)

    path = os.path.join(tempfile.gettempdir(),
                        "bench_det_%d_%d.rec" % (hw, num))
    idx_path = os.path.splitext(path)[0] + ".idx"
    if os.path.exists(path) and os.path.exists(idx_path):
        return path
    # write to temp names + atomic rename: a run killed mid-write must
    # not leave a truncated cache a later run trips over
    tmp_rec, tmp_idx = path + ".tmp", idx_path + ".tmp"
    rec = MXIndexedRecordIO(tmp_idx, tmp_rec, "w")
    rs = np.random.RandomState(0)
    for i in range(num):
        img = rs.randint(0, 255, (hw, hw, 3), dtype=np.uint8)
        n = rs.randint(1, max_boxes + 1)
        label = [2.0, 5.0]
        for _ in range(n):
            x1, y1 = rs.uniform(0, 0.5, 2)
            w, h = rs.uniform(0.2, 0.5, 2)
            label += [float(rs.randint(0, 20)), x1, y1,
                      min(x1 + w, 1.0), min(y1 + h, 1.0)]
        rec.write_idx(i, pack_img(
            IRHeader(2, np.asarray(label, np.float32), i, 0), img,
            quality=90))
    rec.close()
    os.rename(tmp_rec, path)
    os.rename(tmp_idx, idx_path)
    return path


def bench_det(batch, hw, epochs=2):
    """Detection pipeline: packed .rec -> ImageDetIter (decode + joint
    image/bbox augment + fixed-shape label batching).  Also reports the
    decode-only and geometry-only rates so 'does host-numpy bbox
    geometry bind before the decode?' (VERDICT r3 task #6) has a
    measured answer."""
    import mxnet_tpu as mx
    from mxnet_tpu.image_detection import CreateDetAugmenter
    from mxnet_tpu.image import _imdecode_np
    from mxnet_tpu.recordio import MXIndexedRecordIO, unpack

    rec_path = make_det_rec(hw=300)

    def run_iter(threads):
        it = mx.image.ImageDetIter(
            batch_size=batch, data_shape=(3, hw, hw),
            path_imgrec=rec_path, rand_crop=1, rand_pad=1,
            rand_mirror=True, shuffle=True,
            preprocess_threads=threads)
        for _ in it:   # warm epoch (page cache, label-shape scan done)
            pass
        it.reset()
        t0 = time.perf_counter()
        done = 0
        for _ in range(epochs):
            for b in it:
                done += b.data[0].shape[0] - b.pad
            it.reset()
        return done / (time.perf_counter() - t0)

    full = run_iter(0)
    full4 = run_iter(4)

    # decode-only rate over the same records
    idx_path = os.path.splitext(rec_path)[0] + ".idx"
    rr = MXIndexedRecordIO(idx_path, rec_path, "r")
    bufs = [unpack(rr.read_idx(k))[1] for k in list(rr.keys)[:256]]
    t0 = time.perf_counter()
    for buf in bufs:
        _imdecode_np(buf)
    decode = len(bufs) / (time.perf_counter() - t0)

    # augment-only rate: det augmenters on a resident decoded image
    # (pixel + bbox work together)
    img = _imdecode_np(bufs[0])
    label = np.array([[3, 0.2, 0.2, 0.7, 0.8],
                      [1, 0.1, 0.5, 0.4, 0.9]], np.float32)

    def aug_rate(image, shape, n):
        augs = CreateDetAugmenter(shape, rand_crop=1, rand_pad=1,
                                  rand_mirror=True)
        t0 = time.perf_counter()
        for _ in range(n):
            im, lb = image, label
            for aug in augs:
                im, lb = aug(im, lb)
        return n / (time.perf_counter() - t0)

    augment = aug_rate(img, (3, hw, hw), 2000)
    # bbox geometry alone: an 8x8 image makes the pixel work ~free, so
    # this isolates the host-numpy box arithmetic — the number that
    # answers "should geometry move into the C++ workers?"
    tiny = np.zeros((8, 8, 3), np.uint8)
    geometry = aug_rate(tiny, (3, 8, 8), 20000)
    return {"det_pipeline": full, "det_pipeline_4threads": full4,
            "det_decode_only": decode, "det_augment_only": augment,
            "det_bbox_geometry_only": geometry}


def _train_step(batch, hw):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    mesh = create_mesh({"dp": 1}, devices=jax.devices()[:1])
    net = vision.resnet50_v1(classes=10)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    with ctx:
        net.initialize(ctx=ctx)
        net(mx.nd.zeros((1, 3, 32, 32), ctx=ctx))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    return GluonTrainStep(net, loss, mesh=mesh, lr=0.1, momentum=0.9,
                          wd=1e-4, compute_dtype="bfloat16")


def bench_e2e(batch, steps, hw, nthreads, raw=False, prefetch_depth=2):
    """Double-buffered: a feeder thread runs decode + host->device
    upload while the main thread dispatches device steps — the analog
    of the reference's PrefetcherIter (iter_prefetcher.h:47) at the
    device boundary."""
    import queue
    import threading

    step = _train_step(batch, hw)
    it = make_raw_iter(batch, hw, nthreads) if raw \
        else make_iter(batch, hw, nthreads)
    first = next(it)

    def put(b):
        return step.put_batch(b.data[0].asnumpy(),
                              b.label[0].asnumpy().astype(np.int32).ravel())

    x, y = put(first)
    l = step(x, y)  # compile
    float(np.asarray(l))
    _warm_epoch(it)

    q = queue.Queue(maxsize=prefetch_depth)

    def feeder():
        produced = 0
        while produced < steps:
            try:
                b = next(it)
            except StopIteration:
                it.reset()
                continue
            q.put(put(b))
            produced += 1
        q.put(None)

    th = threading.Thread(target=feeder, daemon=True)
    t0 = time.perf_counter()
    th.start()
    losses = []
    while True:
        item = q.get()
        if item is None:
            break
        losses.append(step(*item))
    float(np.asarray(losses[-1]))  # completion barrier
    dt = time.perf_counter() - t0
    th.join()
    return steps * batch / dt


def bench_upload(batch, steps, hw):
    """Host->device transfer alone: one pre-decoded numpy batch,
    re-uploaded per step (isolates the PCIe/relay link cost)."""
    import jax

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, hw, hw).astype(np.float32)
    dev = jax.devices()[0]
    jax.device_put(x, dev).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        jax.device_put(x, dev).block_until_ready()
    dt = time.perf_counter() - t0
    return steps * batch / dt


def bench_synthetic(batch, steps, hw):
    step = _train_step(batch, hw)
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, hw, hw).astype(np.float32)
    y = rng.randint(0, 10, (batch,)).astype(np.int32)
    x, y = step.put_batch(x, y)
    for _ in range(3):
        l = step(x, y)
    float(np.asarray(l))
    t0 = time.perf_counter()
    for _ in range(steps):
        l = step(x, y)
    float(np.asarray(l))
    dt = time.perf_counter() - t0
    return steps * batch / dt


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--hw", type=int, default=224)
    p.add_argument("--nthreads", type=int, default=4)
    p.add_argument("--mode", default="all",
                   choices=["all", "pipeline", "pipeline_raw", "e2e",
                            "e2e_raw", "synthetic", "upload", "det"])
    args = p.parse_args(argv)

    results = {}
    if args.mode == "det":
        results.update(bench_det(args.batch, args.hw))
    if args.mode in ("all", "pipeline"):
        results["pipeline"] = bench_pipeline(args.batch, args.steps,
                                             args.hw, args.nthreads)
    if args.mode in ("all", "pipeline_raw"):
        results["pipeline_raw"] = bench_pipeline(
            args.batch, args.steps, args.hw, args.nthreads, raw=True)
    if args.mode in ("all", "upload"):
        results["upload"] = bench_upload(args.batch, args.steps, args.hw)
    if args.mode in ("all", "synthetic"):
        results["synthetic"] = bench_synthetic(args.batch, args.steps,
                                               args.hw)
    if args.mode in ("all", "e2e"):
        results["e2e"] = bench_e2e(args.batch, args.steps, args.hw,
                                   args.nthreads)
    if args.mode in ("all", "e2e_raw"):
        results["e2e_raw"] = bench_e2e(args.batch, args.steps, args.hw,
                                       args.nthreads, raw=True)
    for mode, img_s in results.items():
        print(json.dumps({
            "metric": "resnet50 %s img/s (bs=%d, %dx%d)"
                      % (mode, args.batch, args.hw, args.hw),
            "value": round(img_s, 2), "unit": "img/s"}))
    return results


if __name__ == "__main__":
    main()
