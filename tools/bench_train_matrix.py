#!/usr/bin/env python
"""Training throughput for any model-zoo network, device-only.

Fills the training half of the reference's published perf matrix
(docs/faq/perf.md:219-236: V100 training img/s for Alexnet,
Inception-v3, ResNet-50 via train_imagenet.py).  The whole train step
(fwd+bwd+SGD momentum+BN stats) is GluonTrainStep's one jitted
computation; ``--chain`` steps are chained into a single dispatch
(GluonTrainStep.make_chained) with a host fetch as the completion
barrier, so the relay's per-call overhead amortizes below 1% — the
same device-only methodology as bench.py's gated metric.

Image size is chosen per network (tools/bench_common.NETWORK_HW:
inception_v3 trains at its canonical 299, everything else at 224), so
one invocation reproduces the whole published matrix; --image-shape
overrides it for every network when set.

Usage: python tools/bench_train_matrix.py [--networks a,b,c]
       [--batches 64,128] [--chain 30] [--image-shape 3,299,299]
       [--dtype bfloat16] [--layout NHWC]
Prints one JSON line per (network, batch).
"""

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from bench_common import build_train_step  # noqa: E402


def measure(network, batch, chain, hw, dtype, layout, reps=3):
    from mxnet_tpu import random as mxrandom

    step, x, y, layout, hw = build_train_step(
        network, batch, hw=hw, dtype=dtype, layout=layout)
    chained = step.make_chained(chain)
    key = mxrandom.next_key()
    float(np.asarray(chained(x, y, key)))  # compile + warm
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(chained(x, y, key)))
        rates.append(chain * batch / (time.perf_counter() - t0))
    return statistics.median(rates), layout, hw


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--networks", default="alexnet,inception_v3,resnet50_v1")
    p.add_argument("--batches", default="64,128")
    p.add_argument("--chain", type=int, default=30)
    p.add_argument("--image-shape", default=None,
                   help="override the per-network default (e.g. 3,299,299)")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--layout", default="NHWC")
    args = p.parse_args(argv)
    hw = int(args.image_shape.split(",")[-1]) if args.image_shape else None
    results = []
    for net in args.networks.split(","):
        for bs in (int(b) for b in args.batches.split(",")):
            img_s, layout, used_hw = measure(net, bs, args.chain, hw,
                                             args.dtype, args.layout)
            rec = {"metric": "%s training img/s (bs=%d, %dx%d, %s, %s, "
                             "device-only %d-chain)"
                             % (net, bs, used_hw, used_hw, args.dtype,
                                layout, args.chain),
                   "value": round(img_s, 1), "unit": "img/s"}
            print(json.dumps(rec))
            results.append(rec)
    return results


if __name__ == "__main__":
    main()
