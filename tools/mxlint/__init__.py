"""mxlint — trace-safety and op-registry static analyzer for mxnet_tpu.

The framework's whole performance premise is that every op is a pure
jax function whose eager path hits a cached ``jax.jit`` executable.
One accidental host sync (``.item()``, ``float()`` on a traced value,
``np.asarray`` on a jax array) or one unhashable value leaking into
``static_argnames`` silently turns the async dependency-engine analog
into a blocking, recompile-storming slow path.  mxlint proves the op
compute paths stay inside the traceable subset — statically (per-file
AST rules plus the interprocedural host-sync-reachability pass in
``callgraph.py``) and at runtime (``registry_audit.py``: registry
tables, eval_shape traceability, and vjp/vmap transform conformance —
the per-op capability matrix is generated into docs/OP_CAPABILITIES.md
by ``capabilities.py``).

Since PR 16 the same call graph also covers the threaded runtime:
``threads.py`` (static race detector: thread-root discovery, held-lock
sets, cross-root shared-state races, lock-order inversions),
``donation.py`` (rebind-after-call and pin-before-capture around the
``donate_argnums`` sites), and ``conformance.py`` (guard-first
telemetry feeds, docs/ENV_VARS.md two-way env registry).

Usage::

    python -m tools.mxlint mxnet_tpu/          # gate against baseline
    python -m tools.mxlint --update-baseline   # re-grandfather
    python -m tools.mxlint --no-baseline       # full report

In-process (how tests/test_lint_clean.py rides tier-1)::

    from tools.mxlint import lint_paths, load_baseline, apply_baseline
    findings, errors = lint_paths(["mxnet_tpu"])

See docs/LINTING.md for the rule catalogue.
"""

from .checkers import ALL_RULES, Config, lint_paths, lint_sources  # noqa: F401
from .findings import (Finding, apply_baseline, fingerprint,  # noqa: F401
                       load_baseline, save_baseline)
from .cli import DEFAULT_BASELINE, main  # noqa: F401
from .graph import collect_findings, verify_zoo  # noqa: F401

__all__ = ["ALL_RULES", "Config", "lint_paths", "lint_sources", "Finding",
           "apply_baseline", "fingerprint", "load_baseline",
           "save_baseline", "DEFAULT_BASELINE", "main", "verify_zoo",
           "collect_findings"]
