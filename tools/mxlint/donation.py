"""Static donation-safety analysis — the ``donation-safety`` rule.

The compiled-step/parallel paths jit with ``donate_argnums``: XLA may
reuse the donated input buffers for outputs, so after the call the
donated arrays are INVALID.  Correctness therefore rests on two
disciplines this pass proves instead of remembers:

1. **Rebind-after-call.**  Every direct call of a donating jitted
   callable must consume its result and rebind the donated inputs —
   either functionally (the call is a ``return`` expression: ownership
   transfers to the caller) or imperatively (the donated ``self.x`` /
   local appears as an assignment target of the call's own statement,
   or is rebound later in the function).  Flagged: a discarded result
   (``jitted(a, b)`` as a bare statement), a donated local read after
   the call without rebinding, a donated ``self.x`` never rebound.
   Metadata reads (``.shape``/``.dtype``/``.ndim``/``.size``/``.aval``)
   are exempt — donation invalidates the buffer, not the aval.

2. **Pin-before-capture.**  In modules that interact with donation
   (they call ``donation_active()`` or contain a donating jit site), a
   by-reference capture of an NDArray's ``_data`` that ESCAPES the
   function (stored into ``self``/a global, or passed into a method
   that stores it) must be guarded by the materialization seam: a call
   consuming the captured value under an ``if`` whose condition is
   (derived from) ``donation_active()`` — the PR 11
   donation-vs-async-checkpoint race class.

Donating callables are tracked through the bindings the runtime
actually uses: ``self._step = jax.jit(..., donate_argnums=...)``,
``fn = jax.jit(...)`` locals (including enclosing-function closures),
and one-hop factories (``return jax.jit(...)`` → ``self._step =
make_train_step(...)``).  ``donate_argnums`` values resolve through
literal tuples/ints and single-assignment locals of literal
conditionals (``donate = (0, 1) if donate_params else ()``).  Call
sites with ``*args`` are conservatively skipped — the argument mapping
is not statically provable (compiled_step's ``entry.fn(*args)``).

Suppression: ``# mxlint: disable=donation-safety`` on the finding's
line."""

from __future__ import annotations

import ast

from .checkers import _Loc
from .callgraph import _module_name, resolve_callable

__all__ = ["check_donation", "find_donation_sites", "RULE"]

RULE = "donation-safety"

# aval metadata stays valid after donation (only the buffer dies)
_METADATA_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "aval",
                             "sharding"})
_SINK_MUTATORS = frozenset({"append", "add", "put", "update", "insert",
                            "setdefault"})


def _literal_argnums(node):
    """(0, 1, 2) / 0 / () -> frozenset of ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.add(e.value)
        return frozenset(out)
    return None


def _resolve_argnums(value, fn_node):
    """donate_argnums expression -> frozenset of possible argnums, or
    None (unresolvable).  Resolves literals, IfExp of literals, and a
    single same-scope ``name = <literal-or-ifexp>`` assignment."""
    lit = _literal_argnums(value)
    if lit is not None:
        return lit
    if isinstance(value, ast.IfExp):
        a = _resolve_argnums(value.body, fn_node)
        b = _resolve_argnums(value.orelse, fn_node)
        if a is not None and b is not None:
            return a | b
        return None
    if isinstance(value, ast.Name) and fn_node is not None:
        assigns = [n for n in ast.walk(fn_node)
                   if isinstance(n, ast.Assign)
                   and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)
                   and n.targets[0].id == value.id]
        if len(assigns) == 1:
            return _resolve_argnums(assigns[0].value, None)
    return None


class _Site:
    """One ``jax.jit(..., donate_argnums=<non-empty>)`` call."""

    __slots__ = ("ctx", "call", "argnums", "fn")

    def __init__(self, ctx, call, argnums, fn):
        self.ctx = ctx
        self.call = call
        self.argnums = argnums  # frozenset of ints, or None (unknown)
        self.fn = fn            # enclosing FnNode (None: module level)


def _enclosing_fn_map(graph, ctx, module):
    """{id(ast node): innermost enclosing FnNode or None}."""
    by_ast = {id(fn.ast_node): fn
              for fn in graph.by_module.get(module, {}).values()
              if fn.path == ctx.path}
    out = {}

    def rec(node, owner):
        for child in ast.iter_child_nodes(node):
            fn = by_ast.get(id(child))
            out[id(child)] = fn if fn is not None else owner
            rec(child, fn if fn is not None else owner)

    rec(ctx.tree, None)
    return out


def find_donation_sites(contexts, graph=None):
    """Every donating-jit call site: [(path, lineno, argnums)].
    Argnums=() sites (donation disabled) are excluded; non-literal but
    resolvable conditionals count with their union."""
    sites = []
    for ctx in contexts:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not ctx.aliases.is_jax_jit(node.func):
                continue
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            if "donate_argnums" not in kw:
                continue
            enclosing = None
            for anc in ast.walk(ctx.tree):
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    if any(sub is node for sub in ast.walk(anc)):
                        enclosing = anc  # innermost wins: keep walking
            argnums = _resolve_argnums(kw["donate_argnums"], enclosing)
            if argnums == frozenset():
                continue  # provably donation-free
            sites.append((ctx.path, node.lineno, argnums))
    return sites


def check_donation(contexts, config, graph):
    """Run the donation-safety rule; appends findings to contexts."""
    if RULE not in config.rules:
        return
    for ctx in contexts:
        module = _module_name(ctx.path)
        if module not in graph.imports:
            continue
        fn_map = _enclosing_fn_map(graph, ctx, module)
        donating = _collect_donating_bindings(ctx, module, graph, fn_map)
        _check_call_sites(ctx, module, graph, fn_map, donating)
        if _module_touches_donation(ctx, donating):
            _check_unpinned_captures(ctx, module, graph)


# ------------------------------------------------- donating bindings


def _donate_kw(call, ctx, fn_node):
    """jax.jit call -> argnums frozenset / None-unknown, or False when
    not a donating jit call."""
    if not (isinstance(call, ast.Call)
            and ctx.aliases.is_jax_jit(call.func)):
        return False
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    if "donate_argnums" not in kw:
        return False
    argnums = _resolve_argnums(kw["donate_argnums"], fn_node)
    if argnums == frozenset():
        return False
    return argnums if argnums is not None else None


def _collect_donating_bindings(ctx, module, graph, fn_map):
    """All names/attrs provably bound to donating jitted callables.

    Returns {"attr": {(cls, name): argnums},
             "local": {(fn qualname, name): argnums},
             "global": {name: argnums}}."""
    out = {"attr": {}, "local": {}, "global": {}}
    factories = {}  # FnNode key -> argnums (fn returns a donating jit)

    def ast_fn(fn):
        return fn.ast_node if fn is not None else None

    # pass 1: direct jit bindings + factory returns
    for node in ast.walk(ctx.tree):
        fn = fn_map.get(id(node))
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            argnums = _donate_kw(node.value, ctx, ast_fn(fn))
            if argnums is False or argnums is None:
                continue  # unresolvable argnums: not statically provable
            t = node.targets[0]
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and fn is not None \
                    and fn.cls is not None:
                out["attr"][(fn.cls, t.attr)] = argnums
            elif isinstance(t, ast.Name):
                if fn is None:
                    out["global"][t.id] = argnums
                else:
                    out["local"][(fn.qualname, t.id)] = argnums
        elif isinstance(node, ast.Return) and node.value is not None:
            argnums = _donate_kw(node.value, ctx, ast_fn(fn))
            if argnums is not False and argnums is not None \
                    and fn is not None:
                factories[fn.key] = argnums

    # pass 2: one-hop factory bindings (self._step = make_train_step())
    if factories:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            fn = fn_map.get(id(node))
            target = resolve_callable(graph, module, fn,
                                      node.value.func, ctx.aliases)
            if not isinstance(target, tuple) or target not in factories:
                continue
            argnums = factories[target]
            t = node.targets[0]
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self" and fn is not None \
                    and fn.cls is not None:
                out["attr"][(fn.cls, t.attr)] = argnums
            elif isinstance(t, ast.Name) and fn is not None:
                out["local"][(fn.qualname, t.id)] = argnums
    return out


# --------------------------------------------- rebind-after-call rule


def _lookup_donating(call, fn, donating, mod_fns):
    """The donating argnums for this call's callee, or None."""
    fnx = call.func
    if isinstance(fnx, ast.Attribute) and isinstance(fnx.value, ast.Name) \
            and fnx.value.id == "self" and fn is not None \
            and fn.cls is not None:
        return donating["attr"].get((fn.cls, fnx.attr))
    if isinstance(fnx, ast.Name):
        cur = fn
        while cur is not None:
            hit = donating["local"].get((cur.qualname, fnx.id))
            if hit is not None:
                return hit
            cur = mod_fns.get(cur.parent) if cur.parent else None
        return donating["global"].get(fnx.id)
    return None


def _check_call_sites(ctx, module, graph, fn_map, donating):
    if not (donating["attr"] or donating["local"] or donating["global"]):
        return
    mod_fns = graph.by_module.get(module, {})
    for fn in mod_fns.values():
        if fn.path != ctx.path:
            continue
        fn_node = fn.ast_node
        if isinstance(fn_node, ast.Lambda):
            continue
        for call in ast.walk(fn_node):
            if not isinstance(call, ast.Call):
                continue
            if fn_map.get(id(call)) is not fn:
                continue
            argnums = _lookup_donating(call, fn, donating, mod_fns)
            if argnums is None:
                continue
            if any(isinstance(a, ast.Starred) for a in call.args):
                continue  # *args mapping not statically provable
            stmt = _innermost_stmt(fn_node, call)
            if stmt is None:
                continue
            if isinstance(stmt, ast.Return):
                continue  # functional transfer: caller owns the result
            if isinstance(stmt, ast.Expr):
                ctx.add(RULE, call,
                        "donating call discards its result — "
                        "donate_argnums invalidated the input buffers "
                        "but nothing rebinds them; assign the outputs "
                        "back (rebind-after-call) or drop donation",
                        fn.qualname)
                continue
            if not isinstance(stmt, ast.Assign):
                continue
            targets = _flat_targets(stmt)
            for i in sorted(argnums):
                if i >= len(call.args):
                    continue
                arg = call.args[i]
                if isinstance(arg, ast.Name):
                    _check_local_arg(ctx, fn, fn_node, call, stmt, arg,
                                     targets)
                elif isinstance(arg, ast.Attribute) \
                        and isinstance(arg.value, ast.Name) \
                        and arg.value.id == "self":
                    _check_attr_arg(ctx, fn, fn_node, call, stmt, arg,
                                    targets)


def _innermost_stmt(fn_node, call):
    hit = None
    for node in ast.walk(fn_node):
        if isinstance(node, ast.stmt) \
                and any(sub is call for sub in ast.walk(node)):
            hit = node
    return hit


def _flat_targets(stmt):
    """('name', n) / ('attr', obj, attr) ids the statement rebinds."""
    out = set()
    stack = list(stmt.targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        elif isinstance(t, ast.Name):
            out.add(("name", t.id))
        elif isinstance(t, ast.Attribute) \
                and isinstance(t.value, ast.Name):
            out.add(("attr", t.value.id, t.attr))
    return out


def _check_local_arg(ctx, fn, fn_node, call, stmt, arg, targets):
    if ("name", arg.id) in targets:
        return  # rebound by this very statement
    # the rebind window: reads past the call but before any reassignment
    rebind = None
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and node.lineno > stmt.lineno \
                and ("name", arg.id) in _flat_targets(node):
            if rebind is None or node.lineno < rebind:
                rebind = node.lineno
    parents = {id(c): p for p in ast.walk(fn_node)
               for c in ast.iter_child_nodes(p)}
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Name) and node.id == arg.id
                and isinstance(node.ctx, ast.Load)
                and node.lineno > stmt.lineno
                and (rebind is None or node.lineno < rebind)):
            continue
        parent = parents.get(id(node))
        if isinstance(parent, ast.Attribute) \
                and parent.attr in _METADATA_ATTRS:
            continue  # aval metadata survives donation
        ctx.add(RULE, node,
                "donated argument %r is read after the donating call "
                "(line %d) — donation invalidated its buffer; rebind "
                "it from the call's outputs first" % (arg.id,
                                                      call.lineno),
                fn.qualname)
        return


def _check_attr_arg(ctx, fn, fn_node, call, stmt, arg, targets):
    if ("attr", "self", arg.attr) in targets:
        return
    # rebound anywhere later in the function?
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and node.lineno >= stmt.lineno:
            for t in _flat_targets(node):
                if t == ("attr", "self", arg.attr):
                    return
    ctx.add(RULE, call,
            "donating call passes self.%s but never rebinds it — the "
            "donated buffer is invalid after the call; assign the "
            "matching output back to self.%s (rebind-after-call)"
            % (arg.attr, arg.attr), fn.qualname)


# --------------------------------------------- pin-before-capture rule


def _module_touches_donation(ctx, donating):
    if donating["attr"] or donating["local"] or donating["global"]:
        return True
    return "donation_active" in ctx.source


def _check_unpinned_captures(ctx, module, graph):
    """Flag `_data` captures that escape without the donation_active()
    materialization seam."""
    for fn in graph.by_module.get(module, {}).values():
        if fn.path != ctx.path or isinstance(fn.ast_node, ast.Lambda):
            continue
        _scan_captures(ctx, module, graph, fn)


def _contains_data_capture(node):
    return any(isinstance(sub, ast.Attribute) and sub.attr == "_data"
               and isinstance(sub.ctx, ast.Load)
               for sub in ast.walk(node))


def _contains_name(node, names):
    return any(isinstance(sub, ast.Name) and sub.id in names
               and isinstance(sub.ctx, ast.Load)
               for sub in ast.walk(node))


def _scan_captures(ctx, module, graph, fn):
    fn_node = fn.ast_node
    own = _own_stmts(fn_node)
    tainted = set()    # locals holding by-reference _data captures
    pin_names = set()  # locals derived from donation_active()
    sanitized = set()
    finding_site = {}  # name -> first capture node (anchor)

    def is_pin_test(test):
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                name = getattr(sub.func, "attr",
                               getattr(sub.func, "id", None))
                if name == "donation_active":
                    return True
            if isinstance(sub, ast.Name) and sub.id in pin_names:
                return True
        return False

    def scan_stmt(node, under_pin):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Assign):
            val = node.value
            taints = _contains_data_capture(val) \
                or _contains_name(val, tainted)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if taints:
                        tainted.add(t.id)
                        finding_site.setdefault(t.id, node)
                    else:
                        tainted.discard(t.id)
                        sanitized.discard(t.id)
                    for sub in ast.walk(val):
                        if isinstance(sub, ast.Call):
                            nm = getattr(sub.func, "attr",
                                         getattr(sub.func, "id", None))
                            if nm == "donation_active":
                                pin_names.add(t.id)
                elif isinstance(t, ast.Subscript):
                    base = t.value
                    if taints and isinstance(base, ast.Name):
                        tainted.add(base.id)
                        finding_site.setdefault(base.id, node)
                    if _is_escape_target(base) and taints \
                            and not under_pin:
                        _flag(node)
                if isinstance(t, ast.Attribute) and taints:
                    if _is_escape_target(t):
                        _flag(node)
        elif isinstance(node, ast.Expr) \
                and isinstance(node.value, ast.Call):
            call = node.value
            names = _call_tainted_args(call)
            if names:
                if under_pin:
                    sanitized.update(names)
                elif _is_storing_call(call, fn, names):
                    for n in sorted(names - sanitized):
                        _flag(node, via=n)
                        sanitized.add(n)  # one finding per value
        elif isinstance(node, ast.Return) and node.value is not None:
            names = set()
            if isinstance(node.value, ast.Call):
                names = _call_tainted_args(node.value)
                if names - sanitized and _is_storing_call(node.value,
                                                          fn, names):
                    for n in sorted(names - sanitized):
                        _flag(node, via=n)
                        sanitized.add(n)
        elif isinstance(node, ast.If):
            pin = is_pin_test(node.test)
            for stmt in node.body:
                scan_stmt(stmt, under_pin or pin)
            for stmt in node.orelse:
                scan_stmt(stmt, under_pin)
            return
        for child in _stmt_children(node):
            scan_stmt(child, under_pin)

    def _call_tainted_args(call):
        out = set()
        for a in list(call.args) + [k.value for k in call.keywords]:
            for sub in ast.walk(a):
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    out.add(sub.id)
        return out

    def _is_escape_target(t):
        return (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self")

    def _is_storing_call(call, fn, tainted_names):
        """self.method(x) whose body stores the PARAM RECEIVING the
        tainted value into self state, or a mutator (.append/.put) on
        self state — the capture outlives this frame.  Only the params
        the tainted arguments map onto are considered: a callee storing
        some other argument does not leak the capture."""
        fnx = call.func
        if isinstance(fnx, ast.Attribute) \
                and fnx.attr in _SINK_MUTATORS:
            return True
        target = resolve_callable(graph, module, fn, fnx, ctx.aliases)
        if not isinstance(target, tuple):
            return False
        callee = graph.nodes.get(target)
        if callee is None or isinstance(callee.ast_node, ast.Lambda):
            return False
        params = [a.arg for a in callee.ast_node.args.args]
        if callee.cls:
            params = params[1:]
        # map tainted argument positions/keywords -> callee params
        hot = set()
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                if _contains_name(a, tainted_names):
                    hot.update(params)  # mapping unknown: all params
            elif _contains_name(a, tainted_names) and i < len(params):
                hot.add(params[i])
        for k in call.keywords:
            if _contains_name(k.value, tainted_names):
                if k.arg is None:
                    hot.update(params)
                elif k.arg in params:
                    hot.add(k.arg)
        if not hot:
            return False
        for node in ast.walk(callee.ast_node):
            if isinstance(node, ast.Assign):
                stores = _contains_name(node.value, hot)
                if stores and any(
                        isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in node.targets):
                    return True
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SINK_MUTATORS \
                    and _contains_name(node, hot):
                return True
        return False

    def _flag(node, via=None):
        anchor = finding_site.get(via, node) if via else node
        ctx.add(RULE, anchor,
                "by-reference `_data` capture escapes this call frame "
                "without the donation seam — a later donating step "
                "invalidates the captured buffer; materialize under "
                "`if donation_active():` (the pin=True contract) "
                "before it escapes", fn.qualname)

    for stmt in own:
        scan_stmt(stmt, False)


def _own_stmts(fn_node):
    return list(fn_node.body)


def _stmt_children(node):
    out = []
    for field in ("body", "orelse", "finalbody", "handlers"):
        for child in getattr(node, field, ()) or ():
            if isinstance(child, ast.ExceptHandler):
                out.extend(child.body)
            elif isinstance(child, ast.stmt):
                out.append(child)
    return out
