"""Runtime op-registry audit — the importing half of registry-consistency.

The AST rule (checkers.py) proves what it can without importing; this
module imports ``mxnet_tpu.ops`` and audits the *actual* registry:

- every ``OP_INPUT_NAMES`` key (including entries added dynamically by
  quantization/extended/contrib modules) names a registered op;
- ``OP_AUX_INPUTS`` / ``OP_LABEL_INPUTS`` are consistent subsets;
- every op in ``OP_INPUT_NAMES`` traces under ``jax.eval_shape`` on a
  canonical input spec — proof the op stays inside the traceable
  subset with zero FLOPs and zero device memory;
- every registered op function carries a docstring (doc-less ops are
  reported; the tier-1 gate grandfathers the pre-existing ones via
  tools/mxlint/baseline.json).

Used by tests/test_lint_clean.py; also runnable standalone::

    python -m tools.mxlint.registry_audit
"""

from __future__ import annotations

__all__ = ["audit_registry", "canonical_spec", "AuditResult"]

_F32 = "float32"


def _rnn_param_len(input_size, state_size, num_layers, dirs, gates):
    """Total packed RNN parameter length (matches ops/rnn.py _unpack)."""
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            total += gates * state_size * in_sz       # w_i2h
            total += gates * state_size * state_size  # w_h2h
            total += 2 * gates * state_size           # b_i2h + b_h2h
    return total


def canonical_spec(name):
    """(input_specs, attrs) for one table op, or None if unknown.

    input_specs: list of (shape, dtype) matching OP_INPUT_NAMES[name]
    order.  Shapes are minimal-but-representative: conv-like ops get
    NCHW images, sequence ops get (T, B, C), etc.
    """
    f = _F32
    i32 = "int32"
    specs = {
        "Convolution": ([((2, 3, 8, 8), f), ((4, 3, 3, 3), f), ((4,), f)],
                        {"kernel": (3, 3), "num_filter": 4}),
        "Deconvolution": ([((2, 4, 8, 8), f), ((4, 3, 3, 3), f),
                           ((3,), f)],
                          {"kernel": (3, 3), "num_filter": 3,
                           "no_bias": False}),
        "FullyConnected": ([((2, 8), f), ((4, 8), f), ((4,), f)],
                           {"num_hidden": 4}),
        "BatchNorm": ([((2, 3, 4, 4), f)] + [((3,), f)] * 4, {}),
        "LayerNorm": ([((2, 8), f), ((8,), f), ((8,), f)], {}),
        "InstanceNorm": ([((2, 3, 4, 4), f), ((3,), f), ((3,), f)], {}),
        "L2Normalization": ([((2, 8), f)], {}),
        "Embedding": ([((2, 3), i32), ((10, 4), f)],
                      {"input_dim": 10, "output_dim": 4}),
        "LeakyReLU": ([((2, 3, 4, 4), f), ((3,), f)],
                      {"act_type": "prelu"}),
        "SoftmaxOutput": ([((2, 5), f), ((2,), f)], {}),
        "choose_element_0index": ([((2, 5), f), ((2,), f)], {}),
        "fill_element_0index": ([((2, 5), f), ((2,), f), ((2,), f)], {}),
        "SVMOutput": ([((2, 5), f), ((2,), f)], {}),
        "LinearRegressionOutput": ([((2, 5), f), ((2, 5), f)], {}),
        "MAERegressionOutput": ([((2, 5), f), ((2, 5), f)], {}),
        "LogisticRegressionOutput": ([((2, 5), f), ((2, 5), f)], {}),
        "CTCLoss": ([((10, 2, 5), f), ((2, 4), f), ((2,), i32),
                     ((2,), i32)],
                    {"use_data_lengths": True, "use_label_lengths": True}),
        "SequenceMask": ([((4, 2, 3), f), ((2,), i32)],
                         {"use_sequence_length": True}),
        "SequenceLast": ([((4, 2, 3), f), ((2,), i32)],
                         {"use_sequence_length": True}),
        "SequenceReverse": ([((4, 2, 3), f), ((2,), i32)],
                            {"use_sequence_length": True}),
        "dot": ([((2, 3), f), ((3, 4), f)], {}),
        "batch_dot": ([((2, 3, 4), f), ((2, 4, 5), f)], {}),
        "where": ([((2, 3), f), ((2, 3), f), ((2, 3), f)], {}),
        "take": ([((5, 3), f), ((2,), i32)], {}),
        "ROIPooling": ([((1, 3, 8, 8), f), ((2, 5), f)],
                       {"pooled_size": (2, 2), "spatial_scale": 1.0}),
        "BilinearSampler": ([((1, 3, 8, 8), f), ((1, 2, 4, 4), f)], {}),
        "GridGenerator": ([((1, 6), f)],
                          {"transform_type": "affine",
                           "target_shape": (4, 4)}),
        "SpatialTransformer": ([((1, 3, 8, 8), f), ((1, 6), f)],
                               {"target_shape": (4, 4)}),
        "RNN": ([((4, 2, 3), f),
                 ((_rnn_param_len(3, 4, 1, 1, 1),), f),
                 ((1, 2, 4), f), ((1, 2, 4), f)],
                {"state_size": 4, "num_layers": 1, "mode": "rnn_tanh"}),
        "_contrib_quantize": ([((2, 3), f), ((1,), f), ((1,), f)], {}),
        "_contrib_quantize_v2": ([((2, 3), f)],
                                 {"min_calib_range": -1.0,
                                  "max_calib_range": 1.0}),
        "_contrib_dequantize": ([((2, 3), "int8"), ((1,), f),
                                 ((1,), f)], {}),
        "_contrib_requantize": ([((2, 3), "int32"), ((1,), f), ((1,), f)],
                                {"min_calib_range": -1.0,
                                 "max_calib_range": 1.0}),
        "_contrib_quantized_fully_connected": (
            [((2, 8), "uint8"), ((4, 8), "int8"), ((4,), "int8")]
            + [((1,), f)] * 6,
            {"num_hidden": 4}),
        "_contrib_quantized_conv": (
            [((1, 3, 8, 8), "uint8"), ((4, 3, 3, 3), "int8"),
             ((4,), "int8")] + [((1,), f)] * 6,
            {"kernel": (3, 3), "num_filter": 4, "stride": (1, 1),
             "pad": (0, 0), "dilate": (1, 1)}),
        "_contrib_quantized_pooling": (
            [((1, 3, 8, 8), "uint8"), ((1,), f), ((1,), f)],
            {"kernel": (2, 2), "stride": (2, 2), "pad": (0, 0),
             "pool_type": "max"}),
        "_contrib_quantized_flatten": (
            [((2, 3, 4), "uint8"), ((1,), f), ((1,), f)], {}),
        "_contrib_Proposal": (
            [((1, 24, 4, 4), f), ((1, 48, 4, 4), f), ((1, 3), f)],
            {"rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4}),
        "_contrib_PSROIPooling": (
            [((1, 12, 8, 8), f), ((2, 5), f)],
            {"output_dim": 3, "pooled_size": 2, "group_size": 2}),
        "_contrib_DeformableConvolution": (
            [((1, 3, 8, 8), f), ((1, 18, 6, 6), f), ((4, 3, 3, 3), f),
             ((4,), f)],
            {"kernel": (3, 3), "num_filter": 4}),
        "Correlation": ([((1, 3, 8, 8), f), ((1, 3, 8, 8), f)],
                        {"kernel_size": 1, "max_displacement": 1}),
        "group_adagrad_update": ([((4, 3), f), ((4, 3), f), ((4,), f)],
                                 {}),
    }
    return specs.get(name)


class AuditResult:
    """Outcome of audit_registry(): lists of problem strings."""

    __slots__ = ("table_errors", "shape_errors", "missing_docstrings")

    def __init__(self):
        self.table_errors = []       # table <-> registry inconsistencies
        self.shape_errors = []       # eval_shape failures / missing specs
        self.missing_docstrings = []  # (op_name, fn_name) doc-less ops

    @property
    def ok(self):
        return not (self.table_errors or self.shape_errors)


def audit_registry(eval_shapes=True):
    """Audit the live registry; importing mxnet_tpu.ops as needed."""
    import jax

    from mxnet_tpu.ops import registry as R

    res = AuditResult()
    registered = set(R._OP_REGISTRY)

    # --- table cross-checks (authoritative: includes dynamic entries)
    for key in R.OP_INPUT_NAMES:
        if key not in registered:
            res.table_errors.append(
                "OP_INPUT_NAMES key %r is not a registered op" % key)
    for key, aux in R.OP_AUX_INPUTS.items():
        if key not in R.OP_INPUT_NAMES:
            res.table_errors.append(
                "OP_AUX_INPUTS key %r missing from OP_INPUT_NAMES" % key)
            continue
        extra = [n for n in aux if n not in R.OP_INPUT_NAMES[key]]
        if extra:
            res.table_errors.append(
                "OP_AUX_INPUTS[%r] names %r not in OP_INPUT_NAMES[%r]"
                % (key, extra, key))
    for key in R.OP_LABEL_INPUTS:
        if key not in R.OP_INPUT_NAMES:
            res.table_errors.append(
                "OP_LABEL_INPUTS key %r missing from OP_INPUT_NAMES" % key)

    # --- docstring coverage over canonical ops
    seen = set()
    for op in R._OP_REGISTRY.values():
        if op.name in seen:
            continue
        seen.add(op.name)
        if not (op.fn.__doc__ or "").strip():
            res.missing_docstrings.append((op.name, op.fn.__name__))
    res.missing_docstrings.sort()

    # --- eval_shape: every table op must trace on its canonical spec
    if eval_shapes:
        from mxnet_tpu.ndarray.ndarray import RANDOM_OPS

        for name in sorted(R.OP_INPUT_NAMES):
            if name not in registered:
                continue  # already a table error above
            spec = canonical_spec(name)
            if spec is None:
                res.shape_errors.append(
                    "no canonical eval_shape spec for table op %r — add "
                    "one to tools/mxlint/registry_audit.py" % name)
                continue
            input_specs, attrs = spec
            op = R.get(name)
            attrs = op.canonicalize_attrs(attrs)
            args = [jax.ShapeDtypeStruct(s, d) for s, d in input_specs]
            if name in RANDOM_OPS:
                args = [jax.random.PRNGKey(0)] + args
            try:
                jax.eval_shape(op.bind_attrs(attrs), *args)
            except Exception as e:  # any trace failure is a finding
                msg = str(e).split("\n")[0][:200]
                res.shape_errors.append(
                    "eval_shape(%s) failed: %s: %s"
                    % (name, type(e).__name__, msg))
    return res


def main(argv=None):
    import argparse
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    p = argparse.ArgumentParser(
        prog="python -m tools.mxlint.registry_audit",
        description="Runtime audit of the mxnet_tpu op registry.")
    p.add_argument("--update-baseline", action="store_true",
                   help="grandfather the current doc-less ops into "
                        "tools/mxlint/baseline.json (registry section)")
    args = p.parse_args(argv)
    res = audit_registry()
    for e in res.table_errors + res.shape_errors:
        print("audit: %s" % e)
    print("registry audit: %d table error(s), %d eval_shape error(s), "
          "%d op(s) without docstrings"
          % (len(res.table_errors), len(res.shape_errors),
             len(res.missing_docstrings)))
    if args.update_baseline:
        from .cli import DEFAULT_BASELINE
        from .findings import save_registry_grandfather

        save_registry_grandfather(
            DEFAULT_BASELINE, [n for n, _ in res.missing_docstrings])
        print("baseline registry section updated: %d op name(s) -> %s"
              % (len(res.missing_docstrings), DEFAULT_BASELINE))
    return 0 if res.ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
