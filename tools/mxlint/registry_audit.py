"""Runtime op-registry audit — the importing half of registry-consistency.

The AST rule (checkers.py) proves what it can without importing; this
module imports ``mxnet_tpu.ops`` and audits the *actual* registry:

- every ``OP_INPUT_NAMES`` key (including entries added dynamically by
  quantization/extended/contrib modules) names a registered op;
- ``OP_AUX_INPUTS`` / ``OP_LABEL_INPUTS`` are consistent subsets;
- every op in ``OP_INPUT_NAMES`` traces under ``jax.eval_shape`` on a
  canonical input spec — proof the op stays inside the traceable
  subset with zero FLOPs and zero device memory;
- every registered op function carries a docstring (doc-less ops are
  reported; the tier-1 gate grandfathers the pre-existing ones via
  tools/mxlint/baseline.json).

**Transform conformance** (:func:`transform_audit`): beyond plain
tracing, every canonical-spec op is abstractly pushed through the two
jax transforms the rest of the stack depends on — ``jax.vjp``
(autograd/executor backward; differentiability over the non-aux float
inputs, with cotangent shapes checked against the primals) and
``jax.vmap`` (batching; the future sharding work composes through it) —
still under ``jax.eval_shape``, so the whole audit costs zero FLOPs and
zero device memory.  The per-op trace/grad/vmap verdicts form the
capability matrix rendered into docs/OP_CAPABILITIES.md by
``tools/mxlint/capabilities.py``; by-design exemptions live in
:data:`TRANSFORM_PRAGMAS`, and pre-existing failures are grandfathered
(shrink-only) in the baseline's ``transforms`` section.

Used by tests/test_lint_clean.py; also runnable standalone::

    python -m tools.mxlint.registry_audit
"""

from __future__ import annotations

__all__ = ["audit_registry", "canonical_spec", "AuditResult",
           "transform_audit", "TRANSFORM_PRAGMAS", "TRANSFORMS"]

_F32 = "float32"


def _rnn_param_len(input_size, state_size, num_layers, dirs, gates):
    """Total packed RNN parameter length (matches ops/rnn.py _unpack)."""
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            total += gates * state_size * in_sz       # w_i2h
            total += gates * state_size * state_size  # w_h2h
            total += 2 * gates * state_size           # b_i2h + b_h2h
    return total


def canonical_spec(name):
    """(input_specs, attrs) for one table op, or None if unknown.

    input_specs: list of (shape, dtype) matching OP_INPUT_NAMES[name]
    order.  Shapes are minimal-but-representative: conv-like ops get
    NCHW images, sequence ops get (T, B, C), etc.
    """
    f = _F32
    i32 = "int32"
    specs = {
        "Convolution": ([((2, 3, 8, 8), f), ((4, 3, 3, 3), f), ((4,), f)],
                        {"kernel": (3, 3), "num_filter": 4}),
        "Deconvolution": ([((2, 4, 8, 8), f), ((4, 3, 3, 3), f),
                           ((3,), f)],
                          {"kernel": (3, 3), "num_filter": 3,
                           "no_bias": False}),
        "FullyConnected": ([((2, 8), f), ((4, 8), f), ((4,), f)],
                           {"num_hidden": 4}),
        "BatchNorm": ([((2, 3, 4, 4), f)] + [((3,), f)] * 4, {}),
        "LayerNorm": ([((2, 8), f), ((8,), f), ((8,), f)], {}),
        "InstanceNorm": ([((2, 3, 4, 4), f), ((3,), f), ((3,), f)], {}),
        "L2Normalization": ([((2, 8), f)], {}),
        "Embedding": ([((2, 3), i32), ((10, 4), f)],
                      {"input_dim": 10, "output_dim": 4}),
        "LeakyReLU": ([((2, 3, 4, 4), f), ((3,), f)],
                      {"act_type": "prelu"}),
        "SoftmaxOutput": ([((2, 5), f), ((2,), f)], {}),
        "choose_element_0index": ([((2, 5), f), ((2,), f)], {}),
        "fill_element_0index": ([((2, 5), f), ((2,), f), ((2,), f)], {}),
        "SVMOutput": ([((2, 5), f), ((2,), f)], {}),
        "LinearRegressionOutput": ([((2, 5), f), ((2, 5), f)], {}),
        "MAERegressionOutput": ([((2, 5), f), ((2, 5), f)], {}),
        "LogisticRegressionOutput": ([((2, 5), f), ((2, 5), f)], {}),
        "CTCLoss": ([((10, 2, 5), f), ((2, 4), f), ((2,), i32),
                     ((2,), i32)],
                    {"use_data_lengths": True, "use_label_lengths": True}),
        "SequenceMask": ([((4, 2, 3), f), ((2,), i32)],
                         {"use_sequence_length": True}),
        "SequenceLast": ([((4, 2, 3), f), ((2,), i32)],
                         {"use_sequence_length": True}),
        "SequenceReverse": ([((4, 2, 3), f), ((2,), i32)],
                            {"use_sequence_length": True}),
        "dot": ([((2, 3), f), ((3, 4), f)], {}),
        "batch_dot": ([((2, 3, 4), f), ((2, 4, 5), f)], {}),
        "where": ([((2, 3), f), ((2, 3), f), ((2, 3), f)], {}),
        "take": ([((5, 3), f), ((2,), i32)], {}),
        "ROIPooling": ([((1, 3, 8, 8), f), ((2, 5), f)],
                       {"pooled_size": (2, 2), "spatial_scale": 1.0}),
        "BilinearSampler": ([((1, 3, 8, 8), f), ((1, 2, 4, 4), f)], {}),
        "GridGenerator": ([((1, 6), f)],
                          {"transform_type": "affine",
                           "target_shape": (4, 4)}),
        "SpatialTransformer": ([((1, 3, 8, 8), f), ((1, 6), f)],
                               {"target_shape": (4, 4)}),
        "RNN": ([((4, 2, 3), f),
                 ((_rnn_param_len(3, 4, 1, 1, 1),), f),
                 ((1, 2, 4), f), ((1, 2, 4), f)],
                {"state_size": 4, "num_layers": 1, "mode": "rnn_tanh"}),
        "_contrib_quantize": ([((2, 3), f), ((1,), f), ((1,), f)], {}),
        "_contrib_quantize_v2": ([((2, 3), f)],
                                 {"min_calib_range": -1.0,
                                  "max_calib_range": 1.0}),
        "_contrib_dequantize": ([((2, 3), "int8"), ((1,), f),
                                 ((1,), f)], {}),
        "_contrib_requantize": ([((2, 3), "int32"), ((1,), f), ((1,), f)],
                                {"min_calib_range": -1.0,
                                 "max_calib_range": 1.0}),
        "_contrib_quantized_fully_connected": (
            [((2, 8), "uint8"), ((4, 8), "int8"), ((4,), "int8")]
            + [((1,), f)] * 6,
            {"num_hidden": 4}),
        "_contrib_quantized_conv": (
            [((1, 3, 8, 8), "uint8"), ((4, 3, 3, 3), "int8"),
             ((4,), "int8")] + [((1,), f)] * 6,
            {"kernel": (3, 3), "num_filter": 4, "stride": (1, 1),
             "pad": (0, 0), "dilate": (1, 1)}),
        "_contrib_quantized_pooling": (
            [((1, 3, 8, 8), "uint8"), ((1,), f), ((1,), f)],
            {"kernel": (2, 2), "stride": (2, 2), "pad": (0, 0),
             "pool_type": "max"}),
        "_contrib_quantized_flatten": (
            [((2, 3, 4), "uint8"), ((1,), f), ((1,), f)], {}),
        "_contrib_Proposal": (
            [((1, 24, 4, 4), f), ((1, 48, 4, 4), f), ((1, 3), f)],
            {"rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4}),
        "_contrib_PSROIPooling": (
            [((1, 12, 8, 8), f), ((2, 5), f)],
            {"output_dim": 3, "pooled_size": 2, "group_size": 2}),
        "_contrib_DeformableConvolution": (
            [((1, 3, 8, 8), f), ((1, 18, 6, 6), f), ((4, 3, 3, 3), f),
             ((4,), f)],
            {"kernel": (3, 3), "num_filter": 4}),
        "Correlation": ([((1, 3, 8, 8), f), ((1, 3, 8, 8), f)],
                        {"kernel_size": 1, "max_displacement": 1}),
        "group_adagrad_update": ([((4, 3), f), ((4, 3), f), ((4,), f)],
                                 {}),
    }
    return specs.get(name)


class AuditResult:
    """Outcome of audit_registry(): lists of problem strings."""

    __slots__ = ("table_errors", "shape_errors", "missing_docstrings")

    def __init__(self):
        self.table_errors = []       # table <-> registry inconsistencies
        self.shape_errors = []       # eval_shape failures / missing specs
        self.missing_docstrings = []  # (op_name, fn_name) doc-less ops

    @property
    def ok(self):
        return not (self.table_errors or self.shape_errors)


def audit_registry(eval_shapes=True, matrix=None):
    """Audit the live registry; importing mxnet_tpu.ops as needed.

    ``matrix``: an already-computed :func:`transform_audit` result to
    derive the eval_shape verdicts from — callers running both audits
    (the tier-1 gate, :func:`main`) pass it so each op is traced once,
    not once per audit.  When omitted and ``eval_shapes`` is true, the
    transform audit is computed here."""
    from mxnet_tpu.ops import registry as R

    res = AuditResult()
    registered = set(R._OP_REGISTRY)

    # --- table cross-checks (authoritative: includes dynamic entries)
    for key in R.OP_INPUT_NAMES:
        if key not in registered:
            res.table_errors.append(
                "OP_INPUT_NAMES key %r is not a registered op" % key)
    for key, aux in R.OP_AUX_INPUTS.items():
        if key not in R.OP_INPUT_NAMES:
            res.table_errors.append(
                "OP_AUX_INPUTS key %r missing from OP_INPUT_NAMES" % key)
            continue
        extra = [n for n in aux if n not in R.OP_INPUT_NAMES[key]]
        if extra:
            res.table_errors.append(
                "OP_AUX_INPUTS[%r] names %r not in OP_INPUT_NAMES[%r]"
                % (key, extra, key))
    for key in R.OP_LABEL_INPUTS:
        if key not in R.OP_INPUT_NAMES:
            res.table_errors.append(
                "OP_LABEL_INPUTS key %r missing from OP_INPUT_NAMES" % key)

    # --- docstring coverage over canonical ops
    seen = set()
    for op in R._OP_REGISTRY.values():
        if op.name in seen:
            continue
        seen.add(op.name)
        if not (op.fn.__doc__ or "").strip():
            res.missing_docstrings.append((op.name, op.fn.__name__))
    res.missing_docstrings.sort()

    # --- eval_shape: every table op must trace on its canonical spec.
    # The actual tracing lives in transform_audit (whose "trace"
    # verdict is exactly this check); missing specs are reported here.
    if eval_shapes:
        if matrix is None:
            matrix = transform_audit()
        for name in sorted(R.OP_INPUT_NAMES):
            if name not in registered:
                continue  # already a table error above
            if canonical_spec(name) is None:
                res.shape_errors.append(
                    "no canonical eval_shape spec for table op %r — add "
                    "one to tools/mxlint/registry_audit.py" % name)
                continue
            verdict, detail = matrix.get(name, {}).get(
                "trace", ("fail", "op not audited"))
            if verdict == "fail":
                res.shape_errors.append(
                    "eval_shape(%s) failed: %s" % (name, detail))
    return res


# ------------------------------------------------- transform conformance

TRANSFORMS = ("trace", "grad", "vmap")

# By-design transform exemptions: {op: {"grad"|"vmap": one-line reason}}.
# A pragma here is the runtime analog of `# mxlint: disable=...` — it
# renders as "pragma" in the capability matrix instead of ✗ and is NOT
# grandfathering: the reason must hold by construction, not by history.
TRANSFORM_PRAGMAS = {}


def _diff_argnums(name, input_specs, key_offset):
    """Positions (into the full arg list) the vjp differentiates:
    non-aux, float-dtype tensor inputs.  The PRNG key (when present)
    and integer inputs (indices, lengths) are never gradient targets,
    matching the executor's grad_req handling."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import registry as R

    names = R.OP_INPUT_NAMES[name]
    aux = set(R.OP_AUX_INPUTS.get(name, ()))
    nums = []
    for i, (_shape, dtype) in enumerate(input_specs):
        if names[i] in aux:
            continue
        if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            continue
        nums.append(key_offset + i)
    return nums


def _check_grad(fn, args, argnums):
    """eval_shape the op's vjp over `argnums`; cotangent shapes must
    round-trip to the primal shapes.  Returns None or an error string."""
    import jax
    import jax.numpy as jnp

    def run(*all_args):
        def f(*diff):
            full = list(all_args)
            for j, d in zip(argnums, diff):
                full[j] = d
            return fn(*full)

        out, vjp_fn = jax.vjp(f, *[all_args[i] for i in argnums])
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
        return vjp_fn(cot)

    try:
        grads = jax.eval_shape(run, *args)
    except Exception as e:
        return "%s: %s" % (type(e).__name__, str(e).split("\n")[0][:200])
    for j, g in zip(argnums, grads):
        if tuple(g.shape) != tuple(args[j].shape):
            return ("cotangent shape %s does not match primal %s for "
                    "input %d" % (tuple(g.shape), tuple(args[j].shape), j))
    return None


def _check_vmap(fn, args, batch=2):
    """eval_shape the op under jax.vmap on a leading batch axis; every
    output must carry the batch dimension."""
    import jax

    batched = [jax.ShapeDtypeStruct((batch,) + tuple(a.shape), a.dtype)
               for a in args]
    try:
        out = jax.eval_shape(jax.vmap(fn), *batched)
    except Exception as e:
        return "%s: %s" % (type(e).__name__, str(e).split("\n")[0][:200])
    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        if not leaf.shape or leaf.shape[0] != batch:
            return ("output %s lost the batch axis (expected leading %d)"
                    % (tuple(leaf.shape), batch))
    return None


def transform_audit():
    """Trace/grad/vmap conformance for every canonical-spec table op.

    Returns ``{op_name: {"trace"|"grad"|"vmap": (verdict, detail)}}``
    with verdict one of ``"ok"`` / ``"fail"`` / ``"pragma"`` / ``"n/a"``
    (no differentiable inputs).  Abstract-only: zero FLOPs, zero device
    memory — cheap enough to ride tier-1 on CPU.
    """
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ndarray.ndarray import RANDOM_OPS
    from mxnet_tpu.ops import registry as R

    matrix = {}
    registered = set(R._OP_REGISTRY)
    for name in sorted(R.OP_INPUT_NAMES):
        if name not in registered:
            continue  # a table error, reported by audit_registry()
        spec = canonical_spec(name)
        if spec is None:
            continue  # a shape error, reported by audit_registry()
        input_specs, attrs = spec
        op = R.get(name)
        attrs = op.canonicalize_attrs(attrs)
        fn = op.bind_attrs(attrs)
        args = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                for s, d in input_specs]
        key_offset = 0
        if name in RANDOM_OPS:
            k = jax.random.PRNGKey(0)
            args = [jax.ShapeDtypeStruct(tuple(k.shape), k.dtype)] + args
            key_offset = 1
        caps = {}
        pragmas = TRANSFORM_PRAGMAS.get(name, {})
        # trace
        try:
            jax.eval_shape(fn, *args)
            caps["trace"] = ("ok", "")
            traced = True
        except Exception as e:
            caps["trace"] = ("fail", "%s: %s"
                             % (type(e).__name__,
                                str(e).split("\n")[0][:200]))
            traced = False
        # grad
        if "grad" in pragmas:
            caps["grad"] = ("pragma", pragmas["grad"])
        elif not traced:
            caps["grad"] = ("fail", "op does not trace")
        else:
            argnums = _diff_argnums(name, input_specs, key_offset)
            if not argnums:
                caps["grad"] = ("n/a", "no differentiable inputs")
            else:
                err = _check_grad(fn, args, argnums)
                caps["grad"] = ("ok", "") if err is None else ("fail", err)
        # vmap
        if "vmap" in pragmas:
            caps["vmap"] = ("pragma", pragmas["vmap"])
        elif not traced:
            caps["vmap"] = ("fail", "op does not trace")
        else:
            err = _check_vmap(fn, args)
            caps["vmap"] = ("ok", "") if err is None else ("fail", err)
        matrix[name] = caps
    return matrix


def main(argv=None):
    import argparse
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    p = argparse.ArgumentParser(
        prog="python -m tools.mxlint.registry_audit",
        description="Runtime audit of the mxnet_tpu op registry.")
    p.add_argument("--update-baseline", action="store_true",
                   help="grandfather the current doc-less ops into "
                        "tools/mxlint/baseline.json (registry section) "
                        "and the current transform failures "
                        "(transforms section)")
    args = p.parse_args(argv)
    matrix = transform_audit()
    res = audit_registry(matrix=matrix)  # ops traced once, not twice
    for e in res.table_errors + res.shape_errors:
        print("audit: %s" % e)
    tfails = {"grad": [], "vmap": []}
    for name, caps in sorted(matrix.items()):
        for t in ("grad", "vmap"):
            verdict, detail = caps[t]
            if verdict != "fail":
                continue
            print("transform: %s under %s: %s" % (name, t, detail))
            # a trace-collapsed op is a shape error (gated above), not
            # a grad/vmap grandfather candidate — once its trace bug is
            # fixed, genuine transform defects must still surface
            if detail != "op does not trace":
                tfails[t].append(name)
    print("registry audit: %d table error(s), %d eval_shape error(s), "
          "%d op(s) without docstrings, %d transform failure(s) over "
          "%d op(s)"
          % (len(res.table_errors), len(res.shape_errors),
             len(res.missing_docstrings),
             sum(len(v) for v in tfails.values()), len(matrix)))
    from .cli import DEFAULT_BASELINE

    if args.update_baseline:
        from .findings import (save_registry_grandfather,
                               save_transform_grandfather)

        save_registry_grandfather(
            DEFAULT_BASELINE, [n for n, _ in res.missing_docstrings])
        save_transform_grandfather(DEFAULT_BASELINE, tfails)
        print("baseline registry section updated: %d op name(s), "
              "transforms section: %d grad / %d vmap failure(s) -> %s"
              % (len(res.missing_docstrings), len(tfails["grad"]),
                 len(tfails["vmap"]), DEFAULT_BASELINE))
        return 0 if res.ok else 1
    # exit code mirrors the tier-1 gate: non-grandfathered transform
    # failures fail the standalone run too (rc-checking CI pipelines
    # must not need the pytest gate to catch a grad/vmap regression)
    tnew = 0
    allowed = {}
    if os.path.exists(DEFAULT_BASELINE):
        from .findings import load_transform_grandfather

        allowed = load_transform_grandfather(DEFAULT_BASELINE)
    for t in ("grad", "vmap"):
        tnew += len(set(tfails[t]) - allowed.get(t, set()))
    return 0 if (res.ok and tnew == 0) else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
