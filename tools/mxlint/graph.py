"""mxlint --graph: verify Symbol DAGs from a curated model zoo.

Source linting (checkers.py) proves the *op implementations* stay
traceable; this module points the graph verifier
(``mxnet_tpu.symbol.verify``) at whole Symbol graphs — every builder
surface the repo exercises (symbol API, multi-output grouping,
integer-input embedding lookups, random-op key plumbing, gluon
hybrid traces) plus the output of every production graph pass
(subgraph partitioning, int8 quantization, AMP).  The zoo is the
zero-false-positive contract for the graph rules: every entry must
verify clean, with no baseline — a finding here is a bug in either a
builder, a pass, or the verifier itself, and all three are ours.

Riding tier-1 via tests/test_lint_clean.py (wall-time budgeted);
``python -m tools.mxlint --graph`` runs the same zoo from the command
line with text/json/github output.
"""

from __future__ import annotations

import time


def build_zoo():
    """[(name, symbol, input_shapes, input_dtypes)] — one entry per
    builder surface worth proving."""
    import mxnet_tpu as mx
    import numpy as np

    sym = mx.sym
    entries = []

    # 1. plain symbol-API MLP (the executor's bread and butter)
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="zoo_fc1")
    act = sym.Activation(fc1, act_type="relu", name="zoo_relu1")
    fc2 = sym.FullyConnected(act, num_hidden=8, name="zoo_fc2")
    mlp = sym.SoftmaxOutput(fc2, name="zoo_softmax")
    entries.append(("mlp", mlp, {"data": (4, 32)}, {}))

    # 2. convnet: Conv -> BatchNorm (aux state) -> Act -> Pool ->
    #    Flatten -> FC -> loss head
    data = sym.var("data")
    conv = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="zoo_conv1")
    bn = sym.BatchNorm(conv, name="zoo_bn1")
    act = sym.Activation(bn, act_type="relu", name="zoo_crelu")
    pool = sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="zoo_pool1")
    flat = sym.Flatten(pool, name="zoo_flat")
    fc = sym.FullyConnected(flat, num_hidden=10, name="zoo_cfc")
    convnet = sym.SoftmaxOutput(fc, name="zoo_csoftmax")
    entries.append(("convnet", convnet, {"data": (2, 3, 8, 8)}, {}))

    # 3. multi-output: SliceChannel fan-out regrouped (out_index
    #    plumbing through Group)
    data = sym.var("data")
    parts = sym.SliceChannel(data, num_outputs=3, axis=1, name="zoo_slice")
    merged = parts[0] + parts[1] * parts[2]
    grouped = mx.sym.Group([merged, parts[1]])
    entries.append(("multi_output", grouped, {"data": (2, 6)}, {}))

    # 4. embedding lookup: int32 indices (canonical-spec dtype hints —
    #    f32 would be a verifier false positive here)
    data = sym.var("data")
    emb = sym.Embedding(data, input_dim=16, output_dim=8, name="zoo_embed")
    pooled = sym.mean(emb, axis=1, name="zoo_embmean")
    eout = sym.FullyConnected(pooled, num_hidden=2, name="zoo_efc")
    entries.append(("embedding", eout, {"data": (4, 12)},
                    {"data": np.int32}))

    # 5. random ops: Dropout consumes the executor's PRNG key (the
    #    verifier must prepend the key aval exactly as make_eval_fn
    #    prepends the key)
    data = sym.var("data")
    drop = sym.Dropout(data, p=0.5, name="zoo_drop")
    rsum = sym.sum(drop, name="zoo_dropsum")
    entries.append(("dropout", rsum, {"data": (4, 8)}, {}))

    # 6. gluon hybrid trace (the other big Symbol producer)
    from mxnet_tpu import gluon, nd

    net = gluon.nn.HybridSequential(prefix="zoo_g_")
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.Dense(4))
    net.initialize()
    net(nd.zeros((2, 8)))  # materialize params
    gsym = net(sym.var("data"))
    entries.append(("gluon_mlp", gsym, {"data": (2, 8)}, {}))

    return entries


def build_pass_outputs(entries):
    """Run each production pass on a zoo graph and return the outputs
    as further zoo entries — the pass manager already verified them
    once; the zoo re-verifies standalone (no pass context) to prove
    the artifacts hold up under fresh seeds too."""
    from mxnet_tpu.contrib.quantization import quantize_graph
    from mxnet_tpu.symbol.amp import amp_convert
    from mxnet_tpu.symbol.passes import PassContext
    from mxnet_tpu.symbol.subgraph import (SubgraphProperty,
                                           SubgraphSelector,
                                           partition_graph)

    by_name = {name: (s, shapes, dtypes)
               for name, s, shapes, dtypes in entries}
    out = []

    class _FCChainSelector(SubgraphSelector):
        def select(self, node):
            return node.op == "FullyConnected"

        def select_output(self, cur_node, output_node):
            return output_node.op == "Activation"

    class _FCChainProperty(SubgraphProperty):
        def create_selector(self):
            return _FCChainSelector()

    mlp, mlp_shapes, mlp_dtypes = by_name["mlp"]
    ctx = PassContext(input_shapes=mlp_shapes, input_dtypes=mlp_dtypes)
    part = partition_graph(mlp, _FCChainProperty, ctx)
    out.append(("pass:partition(mlp)", part, mlp_shapes, mlp_dtypes))
    qsym = quantize_graph(mlp, ctx=ctx)
    out.append(("pass:quantize(mlp)", qsym, mlp_shapes, mlp_dtypes))

    conv, conv_shapes, conv_dtypes = by_name["convnet"]
    amp = amp_convert(conv, input_shapes=conv_shapes,
                      input_dtypes=conv_dtypes)
    out.append(("pass:amp(convnet)", amp, conv_shapes, conv_dtypes))
    return out


def verify_zoo(include_passes=True):
    """Verify every zoo graph; returns ``(results, seconds)`` with
    ``results`` = [(graph name, VerifyResult)]."""
    from mxnet_tpu.symbol.verify import verify_graph

    t0 = time.perf_counter()
    entries = build_zoo()
    if include_passes:
        entries = entries + build_pass_outputs(entries)
    results = [(name, verify_graph(s, input_shapes=shapes,
                                   input_dtypes=dtypes))
               for name, s, shapes, dtypes in entries]
    return results, time.perf_counter() - t0


def collect_findings(results):
    """Flatten to [(graph name, GraphFinding)] — no baseline: a graph
    finding in the zoo is always a bug."""
    return [(name, f) for name, r in results for f in r.findings]


def run_graph_mode(fmt="text"):
    """CLI entry for ``python -m tools.mxlint --graph``; returns the
    process exit code (0 clean, 1 findings)."""
    import json as _json

    from .cli import _gh_msg, _gh_prop

    results, seconds = verify_zoo()
    flat = collect_findings(results)
    graphs = len(results)
    nodes = sum(r.nodes for _, r in results)

    if fmt == "github":
        for gname, f in flat:
            print("::error file=tools/mxlint/graph.py,title=%s::%s"
                  % (_gh_prop("mxlint graph:" + f.rule),
                     _gh_msg("%s: %s" % (gname, f.format()))))
    elif fmt == "json":
        print(_json.dumps({
            "findings": [dict(f.to_dict(), graph=gname)
                         for gname, f in flat],
            "graphs": graphs, "nodes": nodes, "seconds": seconds,
        }, indent=1))
        return 1 if flat else 0
    else:
        for gname, f in flat:
            print("%s: %s" % (gname, f.format()))
    print("mxlint --graph: %d finding(s) over %d graph(s) / %d node(s) "
          "in %.1fs" % (len(flat), graphs, nodes, seconds))
    return 1 if flat else 0
