"""Finding model + baseline mechanics for mxlint.

A finding is one rule violation at one source location.  The baseline
file grandfathers pre-existing findings: each entry is a *fingerprint*
(rule + file + enclosing symbol + normalized source line) with a count,
so findings survive unrelated line-number drift but a fingerprint whose
code is fixed or deleted goes *stale* and is reported for removal.
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import Counter

__all__ = ["Finding", "fingerprint", "load_baseline", "save_baseline",
           "apply_baseline", "BaselineResult"]


class Finding:
    """One rule violation at one source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "symbol",
                 "code_line")

    def __init__(self, rule, path, line, col, message, symbol="",
                 code_line=""):
        self.rule = rule
        self.path = path.replace(os.sep, "/")
        self.line = line
        self.col = col
        self.message = message
        self.symbol = symbol
        self.code_line = code_line.strip()

    def __repr__(self):
        return "Finding(%s, %s:%d)" % (self.rule, self.path, self.line)

    def format(self):
        loc = "%s:%d:%d" % (self.path, self.line, self.col + 1)
        sym = (" [%s]" % self.symbol) if self.symbol else ""
        return "%s: %s: %s%s" % (loc, self.rule, self.message, sym)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "symbol": self.symbol, "code_line": self.code_line,
                "fingerprint": fingerprint(self)}


def fingerprint(finding):
    """Stable identity for baselining: deliberately excludes the line
    number so unrelated edits above a finding don't un-grandfather it."""
    key = "\x1f".join([finding.rule, finding.path, finding.symbol,
                       " ".join(finding.code_line.split())])
    return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]


def load_baseline(path):
    """baseline.json -> {fingerprint: {"count": n, ...meta}}."""
    with open(path) as f:
        data = json.load(f)
    entries = {}
    for e in data.get("findings", []):
        entries[e["fingerprint"]] = e
    return entries


def load_registry_grandfather(path):
    """The runtime-audit grandfather list: op names registered before
    the docstring rule existed (tests/test_lint_clean.py holds new ops
    to zero)."""
    with open(path) as f:
        data = json.load(f)
    return set(data.get("registry", {}).get("missing_docstrings", []))


def save_registry_grandfather(path, op_names):
    """Rewrite only the registry section, preserving findings."""
    data = {"version": 1, "findings": []}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    data["registry"] = {"missing_docstrings": sorted(op_names)}
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def load_transform_grandfather(path):
    """The transform-conformance grandfather lists: ops registered
    before the vjp/vmap audit existed that fail a transform.  New ops
    are held to zero failures (or an explicit TRANSFORM_PRAGMAS entry);
    these sets only ever shrink."""
    with open(path) as f:
        data = json.load(f)
    t = data.get("transforms", {})
    return {k: set(v) for k, v in t.items()}


def save_transform_grandfather(path, failures):
    """Rewrite only the transforms section, preserving everything else.

    `failures`: {"grad": [op, ...], "vmap": [op, ...]} (trace failures
    are never grandfathered — a non-tracing op fails the eval_shape
    gate outright)."""
    data = {"version": 1, "findings": []}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    data["transforms"] = {k: sorted(set(v))
                          for k, v in sorted(failures.items())}
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def save_baseline(path, findings, keep_entries=()):
    """Write a baseline that grandfathers exactly `findings` (the
    registry and transforms sections, if present, are preserved).

    `keep_entries`: existing entry dicts to carry over verbatim —
    used by partial-scope --update-baseline runs so entries the run
    could not re-observe are not silently erased."""
    counts = Counter(fingerprint(f) for f in findings)
    seen = set()
    entries = []
    for e in keep_entries:
        seen.add(e["fingerprint"])
        entries.append({k: v for k, v in e.items() if k != "unmatched"})
    for f in findings:
        fp = fingerprint(f)
        if fp in seen:
            continue
        seen.add(fp)
        entries.append({"fingerprint": fp, "count": counts[fp],
                        "rule": f.rule, "path": f.path,
                        "symbol": f.symbol, "code_line": f.code_line})
    entries.sort(key=lambda e: (e["path"], e["rule"], e["code_line"]))
    data = {"version": 1, "findings": entries}
    if os.path.exists(path):
        with open(path) as f:
            try:
                old = json.load(f)
            except ValueError:
                old = {}
        for section in ("registry", "transforms"):
            if section in old:
                data[section] = old[section]
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


class BaselineResult:
    """Split of a lint run against a baseline."""

    __slots__ = ("new", "suppressed", "stale")

    def __init__(self, new, suppressed, stale):
        self.new = new                # findings not covered by baseline
        self.suppressed = suppressed  # findings absorbed by baseline
        self.stale = stale            # baseline entries matching nothing


def _in_scope(entry, linted_paths, rules):
    """Whether a partial run (subset of paths/rules) can judge this
    baseline entry stale at all."""
    if rules is not None and entry.get("rule") not in rules:
        return False
    if linted_paths is None:
        return True
    path = entry.get("path", "")
    for root in linted_paths:
        root = root.replace(os.sep, "/").rstrip("/")
        if root in (".", "") or path == root \
                or path.startswith(root + "/"):
            return True
    return False


def apply_baseline(findings, baseline, linted_paths=None, rules=None):
    """Match findings against baseline entries (count-aware).

    A baseline entry absorbs up to `count` findings with its
    fingerprint; extra occurrences of the same fingerprint are NEW
    (copy-pasting a baselined violation is still a violation).

    `linted_paths` / `rules`: the scope this run actually covered.
    Entries outside it are never reported stale — a partial run
    (single file, rule subset) must not demand a baseline rewrite for
    findings it could not have re-observed.
    """
    budget = {fp: e.get("count", 1) for fp, e in baseline.items()}
    new, suppressed = [], []
    for f in findings:
        fp = fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    # leftover budget = grandfathered findings that no longer exist.
    # Counting (not just presence) keeps the baseline shrink-only: a
    # half-fixed count-2 entry goes stale until --update-baseline
    # lowers it, so the fixed slot can't silently absorb a
    # reintroduced violation later.
    stale = [dict(e, unmatched=budget[fp])
             for fp, e in baseline.items()
             if budget[fp] > 0 and _in_scope(e, linted_paths, rules)]
    return BaselineResult(new, suppressed, stale)
