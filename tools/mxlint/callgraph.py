"""Interprocedural host-sync reachability — the ``host-sync-reachability``
rule.

The per-function ``trace-host-sync`` rule (checkers.py) only sees syncs
written *inside* a compute-path function.  A helper that calls
``.item()`` is invisible the moment it is wrapped in another function:

    def _to_scalar(v):          # mxnet_tpu/util.py — not a compute path
        return v.item()         # <- never linted by the per-function rule

    def dispatch(x):            # mxnet_tpu/executor.py — compute path
        return _to_scalar(x)    # <- silent device->host sync per call

This module builds a module-level call graph over every linted file,
classifies each function as **host-syncing** (contains a non-pragma'd
sync, or transitively reaches one), **pure** (no sync, every callee
resolved and clean) or **unknown** (at least one unresolvable callee),
and flags every call site in a compute-path function whose callee
*transitively* reaches a host sync — printing the offending path
(``dispatch → _to_scalar → .item()``).

Resolution is deliberately conservative — zero false positives over
completeness: a call becomes a graph edge only when the target is
statically resolvable (nested defs in enclosing function scopes,
module-level functions, literal ``name = lambda ...`` bindings,
``self.``/``cls.`` methods of the enclosing class, ``from .mod import
fn`` names, ``mod.fn`` where ``mod`` aliases a linted module, and
one-hop re-exports through a linted package ``__init__``).  Everything
else is *unknown* and propagates nothing.

Sink catalogue (a function is directly host-syncing when its own scope
has any of these, not pragma-suppressed):

- ``.item()`` / ``.tolist()`` / ``.asnumpy()`` / ``.asscalar()`` calls;
- ``.block_until_ready()`` / ``.wait_to_read()`` / ``.wait_to_write()``;
- ``jax.device_get(...)``;
- ``float()/int()/bool()/complex()`` on tensor-typed names;
- ``np.asarray``/``np.array``/``np.ascontiguousarray`` on tensor values;
- host-side branching on a tensor value (``if mask:`` — ``__bool__``
  copies to host eagerly and raises under jit tracing).

Functions whose *contract* is the sync (checkers.SYNC_WHITELIST names:
``asnumpy``, ``wait_to_read``, ``save``, ``__repr__``, ...) are exempt
inside, but a resolved call into one from a compute path is still an
edge into a sync (reported as ``(sync by contract)``).  A ``# mxlint:
disable=trace-host-sync`` (or ``=host-sync-reachability``) pragma on a
sink line keeps that sink out of the graph — by-design host bridges are
pragma'd once at the source instead of at every transitive call site.
"""

from __future__ import annotations

import ast

from .checkers import (SYNC_WHITELIST, _Loc, _collect_tensor_names,
                       _is_tensor_expr, _pragma_disabled, _tensor_params)

__all__ = ["build_graph", "check_reachability", "classify", "FnNode",
           "RULE", "resolve_callable"]

RULE = "host-sync-reachability"

# attribute-call sync verbs; per-function trace-host-sync already owns
# the first set in compute scope, so call EDGES never re-report them —
# sink detection here is what makes the *containing* helper syncing
_DIRECT_SYNC_ATTRS = frozenset({"item", "asnumpy", "tolist", "asscalar",
                                "block_until_ready"})
_SYNC_VERB_ATTRS = frozenset({"wait_to_read", "wait_to_write"})

CLASS_SYNC = "host-syncing"
CLASS_PURE = "pure"
CLASS_UNKNOWN = "unknown"

_ROOT_PKG = "mxnet_tpu"


def _module_name(path):
    """mxnet_tpu/ops/nn.py -> mxnet_tpu.ops.nn (anchored at the LAST
    path component named like the root package, so absolute and
    repo-relative paths agree); package __init__ maps to the package."""
    parts = path.replace("\\", "/").split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == _ROOT_PKG:
            parts = parts[i:]
            break
    return ".".join(parts)


def _resolve_relative(module, level, target):
    """('mxnet_tpu.ops.nn', 1, 'registry') -> 'mxnet_tpu.ops.registry'."""
    base = module.split(".")
    if len(base) < level:
        return None
    base = base[:len(base) - level]
    if target:
        base += target.split(".")
    return ".".join(base) if base else None


class _Imports:
    """Name-resolution tables for one module."""

    def __init__(self, module, tree):
        self.module_alias = {}   # local name -> dotted module path
        self.from_import = {}    # local name -> (module, attr)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.module_alias[a.asname] = a.name
                    else:
                        # `import mxnet_tpu.ops.nn` binds `mxnet_tpu`
                        root = a.name.split(".")[0]
                        self.module_alias[root] = root
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    mod = _resolve_relative(module, node.level,
                                            node.module)
                else:
                    mod = node.module
                if mod is None:
                    continue
                for a in node.names:
                    local = a.asname or a.name
                    self.from_import[local] = (mod, a.name)


def _binding_names(target):
    """Names a target expression BINDS: bare names, recursing only
    through tuple/list/starred destructuring.  ``x[0] = v`` and
    ``x.a = v`` mutate an object — they bind nothing, so the base name
    must NOT be treated as shadowing a module-level name."""
    out = set()
    stack = [target]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.Tuple, ast.List)):
            stack.extend(n.elts)
        elif isinstance(n, ast.Starred):
            stack.append(n.value)
        elif isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _local_bindings(fn_node):
    """Names bound in `fn_node`'s own scope (parameters, assignment /
    loop / with / except / walrus targets, in-function imports, nested
    def and class names).  Python scoping: any of these shadows a
    module-level name, so a call through one must NOT resolve to the
    module-level def of the same name."""
    bound = set()
    a = fn_node.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        bound.add(arg.arg)
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
            continue  # its body is its own scope
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.ClassDef):
            bound.add(node.name)
        elif isinstance(node, (ast.Assign, ast.For, ast.AsyncFor)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                bound.update(_binding_names(t))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr)):
            if isinstance(node.target, ast.Name):
                bound.add(node.target.id)
        elif isinstance(node, ast.withitem):
            if node.optional_vars is not None:
                bound.update(_binding_names(node.optional_vars))
        elif isinstance(node, ast.ExceptHandler):
            if node.name:
                bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for al in node.names:
                bound.add((al.asname or al.name).split(".")[0])
        stack.extend(ast.iter_child_nodes(node))
    return bound


class FnNode:
    """One function (def or ``name = lambda``) in the call graph."""

    __slots__ = ("module", "qualname", "path", "lineno", "whitelisted",
                 "parent", "cls", "sinks", "calls", "unresolved",
                 "witness", "ast_node", "_bound")

    def __init__(self, module, qualname, path, lineno, whitelisted,
                 parent, cls, ast_node):
        self.module = module
        self.qualname = qualname
        self.path = path
        self.lineno = lineno
        self.whitelisted = whitelisted
        self.parent = parent   # qualname of enclosing function, or None
        self.cls = cls         # qualname prefix of enclosing class, or None
        self.ast_node = ast_node
        self.sinks = []        # (lineno, desc, kind) direct host syncs;
                               # kind is "sync" or "branch"
        self.calls = []        # (callee (module, qualname), ast.Call)
        self.unresolved = 0    # unresolvable call targets seen
        self.witness = None    # key of first syncing callee (set by BFS)
        self._bound = None     # lazy _local_bindings cache

    @property
    def bound(self):
        if self._bound is None:
            self._bound = _local_bindings(self.ast_node)
        return self._bound

    @property
    def key(self):
        return (self.module, self.qualname)

    @property
    def display(self):
        return self.qualname

    def __repr__(self):
        return "FnNode(%s:%s)" % (self.module, self.qualname)


class _Graph:
    def __init__(self):
        self.nodes = {}        # (module, qualname) -> FnNode
        self.by_module = {}    # module -> {qualname: FnNode}
        self.imports = {}      # module -> _Imports

    def lookup_attr(self, module, attr, _depth=0):
        """Find def `attr` in `module`, chasing one-hop re-exports
        through linted ``__init__`` / facade modules (bounded)."""
        hit = self.by_module.get(module, {}).get(attr)
        if hit is not None:
            return hit.key
        imp = self.imports.get(module)
        if imp is not None and _depth < 3:
            tgt = imp.from_import.get(attr)
            if tgt is not None:
                return self.lookup_attr(tgt[0], tgt[1], _depth + 1)
            alias = imp.module_alias.get(attr)
            if alias is not None:
                return None  # `mod.attr` names a module, not a function
        if module in self.by_module:
            return False  # linted module without such a def: benign
        return None if module.split(".")[0] == _ROOT_PKG else False


class _Collector(ast.NodeVisitor):
    """Pass 1: register every function def / ``name = lambda``."""

    def __init__(self, graph, module, path, tree):
        self.graph = graph
        self.module = module
        self.path = path
        self.scope = []        # qualname components (classes + fns)
        self.fn_stack = []     # enclosing FnNode qualnames
        self.cls_stack = []    # enclosing class qualname prefixes
        self.whitelist_depth = 0
        self.graph.imports[module] = _Imports(module, tree)

    def visit_ClassDef(self, node):
        self.scope.append(node.name)
        self.cls_stack.append(".".join(self.scope))
        self.generic_visit(node)
        self.cls_stack.pop()
        self.scope.pop()

    def _register(self, name, node):
        whitelisted = (name in SYNC_WHITELIST or self.whitelist_depth > 0)
        qualname = ".".join(self.scope + [name])
        fn = FnNode(self.module, qualname, self.path, node.lineno,
                    whitelisted,
                    self.fn_stack[-1] if self.fn_stack else None,
                    self.cls_stack[-1] if self.cls_stack else None,
                    node)
        self.graph.nodes[fn.key] = fn
        self.graph.by_module.setdefault(self.module, {})[qualname] = fn
        return fn, whitelisted

    def _visit_fn(self, node, name):
        fn, whitelisted = self._register(name, node)
        self.scope.append(name)
        self.fn_stack.append(fn.qualname)
        if whitelisted:
            self.whitelist_depth += 1
        self.generic_visit(node)
        if whitelisted:
            self.whitelist_depth -= 1
        self.fn_stack.pop()
        self.scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_fn(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        # `name = lambda ...` is a function definition in disguise
        if isinstance(node.value, ast.Lambda) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            self._visit_fn(node.value, node.targets[0].id)
            return
        self.generic_visit(node)


def _attr_root(node):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


class _FnScanner:
    """Pass 2: sinks + call edges for one FnNode's own scope."""

    _BENIGN_BUILTINS = frozenset({
        "len", "isinstance", "issubclass", "getattr", "setattr", "hasattr",
        "tuple", "list", "dict", "set", "frozenset", "sorted", "reversed",
        "zip", "map", "filter", "enumerate", "range", "min", "max", "sum",
        "abs", "repr", "str", "type", "id", "print", "super", "iter",
        "next", "all", "any", "callable", "vars", "round", "divmod",
        "slice", "hash", "format", "float", "int", "bool", "complex",
        "bytes", "object", "ValueError", "TypeError", "KeyError",
        "IndexError", "RuntimeError", "NotImplementedError",
        "AttributeError", "StopIteration", "OverflowError", "Exception",
        "ImportError", "OSError", "ZeroDivisionError",
    })

    def __init__(self, graph, ctx, module, fn):
        self.graph = graph
        self.ctx = ctx
        self.module = module
        self.imports = graph.imports[module]
        self.fn = fn
        node = fn.ast_node
        if isinstance(node, ast.Lambda):
            self.tensors = set()
        else:
            self.tensors = _collect_tensor_names(
                node, _tensor_params(node), ctx.aliases)

    def _pragmad(self, lineno):
        text = self.ctx.line(lineno)
        return (_pragma_disabled(text, RULE)
                or _pragma_disabled(text, "trace-host-sync"))

    def _sink(self, node, desc, kind="sync"):
        if self.fn.whitelisted or self._pragmad(node.lineno):
            return
        self.fn.sinks.append((node.lineno, desc, kind))

    def _own_scope(self):
        """Own-scope nodes; nested defs and ``name = lambda`` are their
        OWN graph nodes, anonymous lambdas fold into this scope."""
        out = []
        stack = list(ast.iter_child_nodes(self.fn.ast_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Lambda) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                continue
            out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out

    def run(self):
        for node in self._own_scope():
            if isinstance(node, ast.Call):
                self._scan_call(node)
            elif isinstance(node, (ast.If, ast.While)):
                self._scan_branch(node)

    # -- sinks ----------------------------------------------------------

    def _scan_branch(self, node):
        keyword = "while" if isinstance(node, ast.While) else "if"
        tests = node.test.values if isinstance(node.test, ast.BoolOp) \
            else [node.test]
        for t in tests:
            negated = ""
            if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
                t = t.operand
                negated = "not "
            if isinstance(t, ast.Name) and t.id in self.tensors:
                self._sink(node, "%s %s%s:" % (keyword, negated, t.id),
                           kind="branch")
                return

    def _scan_call(self, node):
        al = self.ctx.aliases
        fnx = node.func
        if isinstance(fnx, ast.Attribute):
            if fnx.attr in _DIRECT_SYNC_ATTRS \
                    or fnx.attr in _SYNC_VERB_ATTRS:
                self._sink(node, ".%s()" % fnx.attr)
                return
        if al.is_device_get(fnx):
            self._sink(node, "jax.device_get()")
            return
        if (isinstance(fnx, ast.Name)
                and fnx.id in ("float", "int", "bool", "complex")
                and len(node.args) == 1 and not node.keywords
                and _is_tensor_expr(node.args[0], self.tensors, al)):
            self._sink(node, "%s(<tensor>)" % fnx.id)
            return
        if (al.is_np_attr(fnx, ("asarray", "array", "ascontiguousarray"))
                and node.args
                and _is_tensor_expr(node.args[0], self.tensors, al)):
            self._sink(node, "np.%s(<tensor>)" % fnx.attr)
            return
        self._resolve_edge(node)

    # -- call edges -----------------------------------------------------

    def _resolve_edge(self, node):
        target = self._resolve_target(node.func)
        if target is None:
            self.fn.unresolved += 1
        elif target is not False:
            self.fn.calls.append((target, node))

    def _resolve_target(self, fnx):
        """FnNode key, False (provably benign), or None (unknown)."""
        return resolve_callable(self.graph, self.module, self.fn, fnx,
                                self.ctx.aliases)


def resolve_callable(graph, module, fn, fnx, aliases):
    """Resolve a callee expression to a FnNode key, False (provably
    benign), or None (unknown).  `fn` is the enclosing FnNode, or None
    when the call sits in module-level code.  Shared by the
    thread-topology and donation passes so every rule resolves targets
    with identical (conservative) semantics."""
    imports = graph.imports[module]
    mod_fns = graph.by_module.get(module, {})
    if isinstance(fnx, ast.Name):
        name = fnx.id
        # enclosing FUNCTION scopes, innermost first (class bodies
        # are not name scopes in python).  At each level a nested
        # def wins; any OTHER local binding of the name (parameter,
        # assignment, loop/with target, in-function import) shadows
        # outer scopes with something we cannot resolve -> unknown,
        # NEVER the module-level def of the same name
        cur = fn
        while cur is not None:
            qn = cur.qualname + "." + name
            if qn in mod_fns:
                return (module, qn)
            if name in cur.bound:
                return None
            cur = mod_fns.get(cur.parent) if cur.parent else None
        if name in mod_fns:
            return (module, name)
        if name in imports.from_import:
            mod, attr = imports.from_import[name]
            return graph.lookup_attr(mod, attr)
        if name in _FnScanner._BENIGN_BUILTINS:
            return False
        if name in imports.module_alias:
            return False  # calling a module object: not a call
        return None
    if isinstance(fnx, ast.Attribute):
        root = _attr_root(fnx)
        if not isinstance(root, ast.Name):
            return None
        # self.method() / cls.method() -> same-class method
        if root.id in ("self", "cls") \
                and isinstance(fnx.value, ast.Name):
            if fn is not None and fn.cls is not None:
                qn = fn.cls + "." + fnx.attr
                if qn in mod_fns:
                    return (module, qn)
            return None
        # jnp./jax./np. math is device-side (or host-numpy) compute;
        # the sync-prone members were already handled as sinks
        if aliases.is_jnp_call_root(fnx) \
                or (isinstance(fnx.value, ast.Name)
                    and fnx.value.id in aliases.numpy):
            return False
        # mod.fn() where mod aliases a module
        if isinstance(fnx.value, ast.Name):
            target_mod = None
            if root.id in imports.module_alias:
                target_mod = imports.module_alias[root.id]
            elif root.id in imports.from_import:
                m, a = imports.from_import[root.id]
                target_mod = m + "." + a
            if target_mod is not None:
                return graph.lookup_attr(target_mod, fnx.attr)
        return None
    return None  # computed callee expression


# ----------------------------------------------------------- public API


def build_graph(contexts):
    """contexts (checkers._FileCtx list) -> populated graph with
    sync-ness propagated."""
    graph = _Graph()
    ordered = sorted(contexts, key=lambda c: c.path)
    for ctx in ordered:
        module = _module_name(ctx.path)
        _Collector(graph, module, ctx.path, ctx.tree).visit(ctx.tree)
    for ctx in ordered:
        module = _module_name(ctx.path)
        for fn in list(graph.by_module.get(module, {}).values()):
            if fn.path == ctx.path:
                _FnScanner(graph, ctx, module, fn).run()
    _propagate(graph)
    return graph


def _propagate(graph):
    """Reverse BFS from syncing nodes: callers of a syncing function
    sync too.  BFS keeps witness chains shortest and terminates on
    call-graph cycles for free."""
    callers = {}
    for fn in graph.nodes.values():
        for key, _call in fn.calls:
            callers.setdefault(key, []).append(fn)
    frontier = [fn for fn in graph.nodes.values()
                if fn.sinks or fn.whitelisted]
    seen = {fn.key for fn in frontier}
    while frontier:
        nxt = []
        for callee in frontier:
            for caller in callers.get(callee.key, ()):
                if caller.key in seen or caller.whitelisted:
                    continue
                seen.add(caller.key)
                caller.witness = callee.key
                nxt.append(caller)
        frontier = nxt


def _syncing(fn):
    return bool(fn.sinks) or fn.whitelisted or fn.witness is not None


def classify(graph):
    """{(module, qualname): 'host-syncing' | 'pure' | 'unknown'}."""
    out = {}
    for key, fn in graph.nodes.items():
        if _syncing(fn):
            out[key] = CLASS_SYNC
        elif fn.unresolved:
            out[key] = CLASS_UNKNOWN
        else:
            out[key] = CLASS_PURE
    changed = True
    while changed:  # pure is only pure if every callee is pure
        changed = False
        for key, fn in graph.nodes.items():
            if out[key] != CLASS_PURE:
                continue
            if any(out.get(k) == CLASS_UNKNOWN for k, _ in fn.calls):
                out[key] = CLASS_UNKNOWN
                changed = True
    return out


def _path_of(graph, fn):
    """fn -> callee -> ... -> sink description, rendered with arrows."""
    chain = [fn.display]
    cur = fn
    guard = 0
    while cur.witness is not None and guard < 64:
        cur = graph.nodes[cur.witness]
        chain.append(cur.display)
        guard += 1
    if cur.sinks:
        chain.append(cur.sinks[0][1])
    elif cur.whitelisted:
        chain.append("(sync by contract)")
    return " → ".join(chain)


def check_reachability(contexts, config, graph=None):
    """The cross-file rule pass: flag compute-path call sites whose
    callee transitively host-syncs, and compute-path functions that
    host-branch on tensor values.  Appends findings to each ctx's
    findings list; returns the graph (for classification consumers).

    `graph`: a pre-built call graph over the same contexts (the driver
    builds one and shares it with the thread/donation passes)."""
    by_path = {ctx.path: ctx for ctx in contexts}
    if graph is None:
        graph = build_graph(contexts)
    for fn in graph.nodes.values():
        ctx = by_path.get(fn.path)
        if ctx is None or fn.whitelisted:
            continue
        if not config.matches(config.compute_path_globs, fn.path):
            continue
        # own host-branch sinks: the per-function rule does not cover
        # tensor truthiness, so this rule owns them outright
        for lineno, desc, kind in fn.sinks:
            if kind == "branch":
                ctx.add(RULE, _Loc(lineno),
                        "host-side branch on a tensor value (`%s` "
                        "triggers __bool__: an eager device->host copy, "
                        "and a TracerBoolConversionError under jit); "
                        "use jnp.where / lax.cond instead" % desc,
                        fn.qualname)
        reported = set()
        for key, call in fn.calls:
            callee = graph.nodes.get(key)
            if callee is None or not _syncing(callee):
                continue
            if key in reported:
                continue  # one finding per (caller, callee) pair
            reported.add(key)
            path = "%s → %s" % (fn.display, _path_of(graph, callee))
            ctx.add(RULE, call,
                    "call into %r which transitively reaches a host "
                    "sync: %s — keep compute paths device-only, or "
                    "pragma the sync at its source if it is a "
                    "by-design host bridge" % (callee.display, path),
                    fn.qualname)
    return graph
