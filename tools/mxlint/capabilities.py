"""Generator for docs/OP_CAPABILITIES.md — the per-op transform
capability matrix.

Renders :func:`tools.mxlint.registry_audit.transform_audit` (trace /
grad / vmap verdicts for every canonical-spec registry op) as a
deterministic markdown table: sorted rows, no timestamps, no
environment-dependent error text — regenerating on any machine must be
byte-identical or the tier-1 gate fails (tests/test_lint_clean.py
``test_capability_matrix_up_to_date``).

Usage::

    python -m tools.mxlint.capabilities            # rewrite the doc
    python -m tools.mxlint.capabilities --check    # exit 1 if stale
"""

from __future__ import annotations

import os

__all__ = ["generate", "DOC_PATH", "main"]

DOC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "docs", "OP_CAPABILITIES.md")

_SYMBOL = {"ok": "✓", "fail": "✗", "n/a": "–"}

_HEADER = """\
# Op transform capabilities

<!-- GENERATED FILE — do not edit.  Regenerate with:
     python -m tools.mxlint.capabilities -->

Per-op conformance of every registry table op (`OP_INPUT_NAMES`) under
the three jax transforms the framework's layers depend on, proven
abstractly (`jax.eval_shape` — zero FLOPs, zero device memory) on the
op's canonical spec by `tools/mxlint/registry_audit.py`:

- **trace** — the op stays inside the jax-traceable subset (eager
  dispatch can cache a `jax.jit` executable for it);
- **grad** — `jax.vjp` over the non-aux float inputs traces and every
  cotangent matches its primal's shape (autograd/executor backward);
- **vmap** — the op composes with `jax.vmap` on a leading batch axis
  and no output loses the batch dimension (batching, and the
  cross-replica sharding work layers on this).

Legend: ✓ conforms · ✗ fails (grandfathered in
`tools/mxlint/baseline.json`, shrink-only) · – not applicable (no
differentiable inputs) · `pragma` exempt by design
(`TRANSFORM_PRAGMAS`, reason footnoted).

New table ops must be ✓ (or explicitly pragma'd) on all three — the
tier-1 gate (`tests/test_lint_clean.py`) holds grandfather lists to
shrink-only.  See `docs/LINTING.md` ("Transform conformance").

| op | trace | grad | vmap |
|---|:---:|:---:|:---:|
"""


def _cell(verdict, detail, notes):
    if verdict == "pragma":
        notes.append(detail)
        return "pragma[^%d]" % len(notes)
    return _SYMBOL.get(verdict, verdict)


def generate(matrix=None):
    """The full markdown document as a string (deterministic)."""
    if matrix is None:
        from .registry_audit import transform_audit

        matrix = transform_audit()
    notes = []
    lines = [_HEADER]
    for name in sorted(matrix):
        caps = matrix[name]
        cells = [_cell(*caps[t], notes=notes)
                 for t in ("trace", "grad", "vmap")]
        lines.append("| `%s` | %s | %s | %s |\n"
                     % (name, cells[0], cells[1], cells[2]))
    counts = {"ok": 0, "fail": 0, "pragma": 0, "n/a": 0}
    for caps in matrix.values():
        for verdict, _ in caps.values():
            counts[verdict] = counts.get(verdict, 0) + 1
    lines.append("\n%d ops audited — %d ✓ · %d ✗ · %d pragma · %d –\n"
                 % (len(matrix), counts["ok"], counts["fail"],
                    counts["pragma"], counts["n/a"]))
    if notes:
        lines.append("\n")
        for i, reason in enumerate(notes, 1):
            lines.append("[^%d]: %s\n" % (i, reason))
    return "".join(lines)


def main(argv=None):
    import argparse

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    p = argparse.ArgumentParser(
        prog="python -m tools.mxlint.capabilities",
        description="(Re)generate docs/OP_CAPABILITIES.md.")
    p.add_argument("--check", action="store_true",
                   help="exit 1 if the committed doc is stale instead "
                        "of rewriting it")
    p.add_argument("--out", default=DOC_PATH)
    args = p.parse_args(argv)
    text = generate()
    if args.check:
        try:
            with open(args.out, encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != text:
            print("stale: %s does not match the live registry — run "
                  "python -m tools.mxlint.capabilities" % args.out)
            return 1
        print("up to date: %s" % args.out)
        return 0
    with open(args.out, "w", encoding="utf-8") as f:
        f.write(text)
    print("wrote %s (%d ops)" % (args.out, text.count("\n| `")))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
