"""Guard-first and env-registry conformance — the ``guard-first`` and
``env-registry`` rules.

guard-first
-----------
Every telemetry feed's overhead contract is "ONE dict read and nothing
else while disabled" — `tests/test_bench_gate.py` pins it dynamically
per feed; this rule proves it statically for EVERY feed in the
registry below: the first non-docstring statement must be an ``if``
that reads the feed's state object and only returns.  A registry row
whose function no longer exists is itself a finding (registry drift),
so the proved set can't silently rot.

env-registry
------------
Every ``MXNET_TPU_*`` / ``MXTPU_*`` environment read in the linted
tree must have a row in ``docs/ENV_VARS.md`` (finding at the read
site), and every documented row must correspond to a real read
somewhere in the repo — linted sources, tools/, tests/, or the native
C++ sources, which are regex-scanned as auxiliary evidence (finding
anchored at the stale doc row).  The stale-row direction is only sound
when the whole ``mxnet_tpu`` package was linted; ``lint_paths``
enables it for complete runs (``Config.check_env_doc_stale``), exactly
like the registry table cross-check.

Suppression: ``# mxlint: disable=guard-first`` on the def line /
``# mxlint: disable=env-registry`` on the read line.  Doc rows have no
pragma — a stale row is deleted, not suppressed.
"""

from __future__ import annotations

import ast
import functools
import os
import re

from .findings import Finding
from .checkers import _pragma_disabled

__all__ = ["check_conformance", "DEFAULT_FEEDS", "RULE_GUARD",
           "RULE_ENV"]

RULE_GUARD = "guard-first"
RULE_ENV = "env-registry"

# (module, function qualname, state object read by the guard).  The
# dynamically-pinned feeds from tests/test_bench_gate.py; stepstats
# ``begin`` is deliberately absent (caller-guarded by contract).
DEFAULT_FEEDS = (
    ("mxnet_tpu.histogram", "observe", "_state"),
    ("mxnet_tpu.stepstats", "add", "_state"),
    ("mxnet_tpu.stepstats", "end", "_state"),
    ("mxnet_tpu.stepstats", "end_step", "_state"),
    ("mxnet_tpu.metrics_timeline", "on_step", "_state"),
    ("mxnet_tpu.checkpoint", "on_step", "_state"),
    ("mxnet_tpu.health", "observe", "_state"),
    ("mxnet_tpu.xray", "scope", "_state"),
    ("mxnet_tpu.device_memory", "track", "_state"),
    ("mxnet_tpu.autopilot", "on_step", "_state"),
    ("mxnet_tpu.autopilot", "on_serve", "_state"),
    ("mxnet_tpu.reqtrace", "on_submit", "_state"),
    ("mxnet_tpu.reqtrace", "on_submitted", "_state"),
    ("mxnet_tpu.reqtrace", "on_reject", "_state"),
    ("mxnet_tpu.reqtrace", "on_join", "_state"),
    ("mxnet_tpu.reqtrace", "on_exec", "_state"),
    ("mxnet_tpu.reqtrace", "on_done", "_state"),
    ("mxnet_tpu.slo", "on_request", "_state"),
)

_ENV_RE = re.compile(r"\b(?:MXNET_TPU|MXTPU)_[A-Z0-9_]+\b")

# repo root: tools/mxlint/conformance.py -> three dirname hops
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DOCS_REL = "docs/ENV_VARS.md"
# extra trees regex-scanned as evidence a documented var is real (they
# are not linted by default, so rows for their vars must not go stale)
_AUX_TREES = ("tools", "tests", os.path.join("mxnet_tpu", "native"))


def check_conformance(contexts, config):
    """Run both rules.  Per-file findings go onto each ctx; findings
    anchored in docs/ENV_VARS.md are RETURNED (no ctx owns that file)."""
    extra = []
    if RULE_GUARD in config.rules:
        _check_guard_first(contexts, config)
    if RULE_ENV in config.rules:
        extra.extend(_check_env_registry(contexts, config))
    return extra


# ----------------------------------------------------------- guard-first


def _first_real_stmt(fn_node):
    body = list(fn_node.body)
    if body and isinstance(body[0], ast.Expr) \
            and isinstance(body[0].value, ast.Constant) \
            and isinstance(body[0].value.value, str):
        body = body[1:]
    return body[0] if body else None


def _reads_state(test, state_name):
    """The guard test touches the feed's state object (``not
    _state["on"]``, ``_state.get(...)`` — possibly one arm of a
    BoolOp)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Name) and sub.id == state_name \
                and isinstance(sub.ctx, ast.Load):
            return True
    return False


def _guard_shape_ok(stmt, state_name):
    """``if <reads state>: return/pass`` and nothing heavier."""
    if not isinstance(stmt, ast.If):
        return False
    if not _reads_state(stmt.test, state_name):
        return False
    if stmt.orelse:
        return False
    return all(isinstance(s, (ast.Return, ast.Pass)) for s in stmt.body)


def _check_guard_first(contexts, config):
    from .callgraph import _module_name

    feeds = getattr(config, "guard_feeds", None) or DEFAULT_FEEDS
    by_module = {}
    for ctx in contexts:
        by_module.setdefault(_module_name(ctx.path), ctx)
    for module, qualname, state_name in feeds:
        ctx = by_module.get(module)
        if ctx is None:
            continue  # partial run: module not in scope
        fn_node = _find_def(ctx.tree, qualname)
        if fn_node is None:
            ctx.add(RULE_GUARD, _Loc0(),
                    "feed registry row %s.%s names no function in this "
                    "module — update tools/mxlint/conformance.py's "
                    "DEFAULT_FEEDS (registry drift)" % (module,
                                                        qualname))
            continue
        stmt = _first_real_stmt(fn_node)
        if stmt is None or not _guard_shape_ok(stmt, state_name):
            ctx.add(RULE_GUARD, fn_node,
                    "telemetry feed %s() must begin with its enabled "
                    "guard (`if not %s[...]: return`) before any other "
                    "work — the one-dict-read-when-disabled contract "
                    "test_bench_gate.py pins dynamically"
                    % (qualname, state_name), qualname)


class _Loc0:
    lineno = 1
    col_offset = 0


def _find_def(tree, qualname):
    parts = qualname.split(".")
    body = tree.body
    node = None
    for i, part in enumerate(parts):
        node = None
        for child in body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and child.name == part:
                node = child
                break
        if node is None:
            return None
        body = getattr(node, "body", [])
    return node if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) else None


# ---------------------------------------------------------- env-registry


def _env_reads(ctx):
    """[(var, ast node)] for every literal MXNET_TPU_*/MXTPU_* access:
    os.environ.get/[]/in/pop/setdefault, os.getenv, from-os environ."""
    # cheap source-text prefilter: a file with no environ/getenv token
    # cannot contain an env read — skip the AST walks entirely
    if "environ" not in ctx.source and "getenv" not in ctx.source:
        return []
    reads = []
    environ_names = {"environ"} if _from_os(ctx, "environ") else set()
    getenv_names = {"getenv"} if _from_os(ctx, "getenv") else set()

    def is_environ(node):
        if isinstance(node, ast.Attribute) and node.attr == "environ" \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "os":
            return True
        return isinstance(node, ast.Name) and node.id in environ_names

    def lit(node):
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                         str):
            m = _ENV_RE.search(node.value)
            if m and m.group(0) == node.value:
                return node.value
        return None

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            fnx = node.func
            if isinstance(fnx, ast.Attribute) \
                    and fnx.attr in ("get", "pop", "setdefault") \
                    and is_environ(fnx.value) and node.args:
                var = lit(node.args[0])
                if var:
                    reads.append((var, node))
            elif ((isinstance(fnx, ast.Attribute)
                   and fnx.attr == "getenv"
                   and isinstance(fnx.value, ast.Name)
                   and fnx.value.id == "os")
                  or (isinstance(fnx, ast.Name)
                      and fnx.id in getenv_names)) and node.args:
                var = lit(node.args[0])
                if var:
                    reads.append((var, node))
        elif isinstance(node, ast.Subscript) and is_environ(node.value):
            var = lit(node.slice)
            if var:
                reads.append((var, node))
        elif isinstance(node, ast.Compare) \
                and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and is_environ(node.comparators[0]):
            var = lit(node.left)
            if var:
                reads.append((var, node))
    return reads


def _from_os(ctx, attr):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == attr:
                    return True
    return False


def _documented_rows(docs_path):
    """{var: (lineno, row text)} — the FIRST env-var token in each
    markdown table row's first cell is the documented variable; tokens
    later in the row are prose cross-references, not rows."""
    rows = {}
    try:
        with open(docs_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    for i, line in enumerate(lines, 1):
        if not line.lstrip().startswith("|"):
            continue
        cells = line.split("|")
        first = cells[1] if len(cells) > 1 else ""
        m = _ENV_RE.search(first)
        if m and m.group(0) not in rows:
            rows[m.group(0)] = (i, line.strip())
    return rows


@functools.lru_cache(maxsize=4)
def _aux_mentions(repo_root):
    """Env-var tokens appearing anywhere in the auxiliary (non-linted)
    trees — evidence that a doc row is not stale.  Cached per root: the
    aux trees don't change within one lint process (the gate and the
    CLI tests run several full-package lints back to back)."""
    seen = set()
    for tree in _AUX_TREES:
        top = os.path.join(repo_root, tree)
        for root, dirs, files in os.walk(top):
            dirs[:] = [d for d in dirs if d not in ("__pycache__",
                                                    ".git")]
            for fname in files:
                if not fname.endswith((".py", ".cc", ".h", ".cpp",
                                       ".sh", ".md")):
                    continue
                try:
                    with open(os.path.join(root, fname),
                              encoding="utf-8", errors="replace") as f:
                        seen.update(_ENV_RE.findall(f.read()))
                except OSError:
                    pass
    return seen


def _check_env_registry(contexts, config):
    repo_root = getattr(config, "repo_root", None) or REPO_ROOT
    docs_path = getattr(config, "env_docs_path", None) \
        or os.path.join(repo_root, DOCS_REL)
    rows = _documented_rows(docs_path)
    if rows is None:
        return []  # no registry in this tree: nothing to cross-check
    read_vars = set()
    mentioned = set()  # literal tokens anywhere in linted sources:
    # helper-wrapped reads (`_env_int("MXNET_TPU_X", d)`) are real
    # reads even though no os.environ access names the var directly
    for ctx in contexts:
        mentioned.update(_ENV_RE.findall(ctx.source))
        for var, node in _env_reads(ctx):
            read_vars.add(var)
            if var not in rows:
                ctx.add(RULE_ENV, node,
                        "env var %r is read here but has no "
                        "docs/ENV_VARS.md row — every MXNET_TPU_* "
                        "knob must be documented (add a row, or "
                        "rename onto an existing knob)" % var)
    extra = []
    if getattr(config, "check_env_doc_stale", False):
        aux = _aux_mentions(repo_root)
        docs_rel = os.path.relpath(docs_path, repo_root) \
            if os.path.isabs(docs_path) else docs_path
        for var in sorted(rows):
            if var in read_vars or var in mentioned or var in aux:
                continue
            lineno, text = rows[var]
            if _pragma_disabled(text, RULE_ENV):
                continue
            extra.append(Finding(
                RULE_ENV, docs_rel.replace(os.sep, "/"), lineno, 0,
                "documented env var %r is read nowhere in the repo — "
                "stale row; delete it (or restore the knob)" % var,
                symbol=var, code_line=text))
    return extra
