"""Static thread-topology + lock-discipline analysis — the
``thread-shared-state`` and ``thread-lock-order`` rules.

The runtime grew a real thread population (checkpoint writer, serving
batcher/worker pool, PS accept/heartbeat threads, metrics HTTP daemon,
signal/atexit dump paths, weakref finalizers, engine FFI trampolines);
this pass makes their synchronization discipline a *proved* property
instead of a remembered one.

Model
-----
1. **Roots.**  Every statically-visible asynchronous entry point:

   - ``threading.Thread(target=f)`` / ``threading.Timer(t, f)``
   - ``atexit.register(f)`` and ``signal.signal(sig, f)``
   - ``weakref.finalize(obj, f, ...)``
   - ``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses (each
     request runs them on a ``ThreadingHTTPServer`` worker thread)
   - ``ctypes.CFUNCTYPE``-trampoline wrappers (``ENGINE_OP_FN(f)``):
     the wrapped python callable runs on native worker threads

   plus the implicit **api** root: every function reachable from
   outside the discovered thread cones (public entry points — what the
   importing/training thread can run).  Functions named ``*_locked``
   are never api entries of their own: the suffix is this codebase's
   caller-holds-the-lock convention, so they are only analyzed through
   their real (lock-holding) callers.

2. **Reachability + held locks.**  Per root, a DFS over the PR 4 call
   graph (statically-resolved edges only) carries the set of locks
   *provably held* at each point: ``with <lock>:`` scopes where the
   lock expression resolves to a module-global or ``self.<attr>``
   assigned from ``threading.Lock/RLock/Condition/Semaphore``.  A
   ``with`` on anything else (per-key lock dicts, arbitrary context
   managers) poisons the held-set with an *unknown* marker — accesses
   under it are never judged (conservative silence, zero false
   positives over completeness).

3. **Shared state.**  Module globals and ``self.<attr>`` slots
   (``__init__`` writes excluded: construction happens-before
   ``start()``).  A finding needs a *write* under one root and any
   access under a different root whose guaranteed lock sets are
   **inconsistent** — disjoint, with at least one side actually
   holding a lock.  Two lock-free accesses are NOT flagged: the
   GIL-atomic single-dict-op idiom (``_state["on"]`` guard flags) is
   this codebase's documented convention.  Unlocked read-modify-write
   (``x += 1`` / ``x[k] += 1`` with *no* lock held) on multi-root
   state is flagged separately — increments are not atomic.

4. **Lock order.**  Every acquisition of lock B while lock A is held
   (syntactic nesting or through resolved calls) records an A→B edge
   with its root + call path.  An A→B *and* B→A pair is a potential
   deadlock; the finding prints both acquisition paths
   (``batcher → _pack → stats_lock ; scraper → snapshot →
   metrics_lock``) and is a hard error class: ``--update-baseline``
   refuses to grandfather it.

Suppression: a ``# mxlint: disable=thread-shared-state`` pragma on the
conflicting *write* line or on the shared variable's definition line
(the module-level assignment, or the first ``self.x = ...`` in the
class) clears every finding for that variable (source clears
transitive sites); ``thread-lock-order`` pragmas work on either
acquisition line.
"""

from __future__ import annotations

import ast

from .checkers import _Loc, _pragma_disabled
from .callgraph import _module_name, resolve_callable

__all__ = ["check_threads", "discover_roots", "RULE_STATE", "RULE_ORDER"]

RULE_STATE = "thread-shared-state"
RULE_ORDER = "thread-lock-order"

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})
# mutating container methods: calling one through a shared ref writes it
_MUTATORS = frozenset({"append", "extend", "insert", "remove", "pop",
                       "popitem", "popleft", "appendleft", "clear",
                       "update", "add", "discard", "setdefault"})
_UNKNOWN = ("?", "?", "?")  # poison lock id: unanalyzable acquisition
_HANDLER_BASES = ("BaseHTTPRequestHandler", "SimpleHTTPRequestHandler")
# constructors that run before any thread exists (happens-before start)
_INIT_METHODS = ("__init__", "__new__", "__post_init__")


class Root:
    """One asynchronous entry point: (kind, target FnNode)."""

    __slots__ = ("kind", "key", "path", "lineno")

    def __init__(self, kind, key, path, lineno):
        self.kind = kind      # thread | timer | atexit | signal |
        self.key = key        # finalizer | http-handler | ffi | api
        self.path = path
        self.lineno = lineno

    @property
    def name(self):
        return "%s:%s" % (self.kind, self.key[1])

    def __repr__(self):
        return "Root(%s)" % self.name


# ------------------------------------------------------------ discovery


def _scope_map(graph, ctx, module):
    """{id(ast node): FnNode-or-None} for every node, attributing each
    to its innermost enclosing function (None = module/class level)."""
    by_ast = {id(fn.ast_node): fn
              for fn in graph.by_module.get(module, {}).values()
              if fn.path == ctx.path}
    out = {}

    def rec(node, owner):
        for child in ast.iter_child_nodes(node):
            fn = by_ast.get(id(child))
            out[id(child)] = fn if fn is not None else owner
            rec(child, fn if fn is not None else owner)

    out[id(ctx.tree)] = None
    rec(ctx.tree, None)
    return out


def _is_module_attr(expr, names):
    """expr is ``<alias>.<attr>`` with alias in `names` -> attr."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in names):
        return expr.attr
    return None


def _stdlib_aliases(imports, stdmod):
    """Local names that alias stdlib module `stdmod` in this file."""
    return {local for local, target in imports.module_alias.items()
            if target == stdmod}


def _from_names(imports, stdmod):
    """Local names from-imported from `stdmod`: {local: attr}."""
    return {local: attr for local, (mod, attr)
            in imports.from_import.items() if mod == stdmod}


def _collect_cfunc_types(contexts, graph):
    """{(module, name)} of module-level ``X = ctypes.CFUNCTYPE(...)``
    assignments — calls ``X(py_fn)`` build FFI trampolines whose
    wrapped callable runs on native threads."""
    out = set()
    for ctx in contexts:
        module = _module_name(ctx.path)
        imports = graph.imports.get(module)
        if imports is None:
            continue
        ct_aliases = _stdlib_aliases(imports, "ctypes")
        ct_from = _from_names(imports, "ctypes")
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            fnx = node.value.func
            hit = (_is_module_attr(fnx, ct_aliases) == "CFUNCTYPE"
                   or (isinstance(fnx, ast.Name)
                       and ct_from.get(fnx.id) == "CFUNCTYPE"))
            if hit:
                out.add((module, node.targets[0].id))
    return out


def discover_roots(graph, contexts):
    """All statically-provable asynchronous entry points."""
    roots = {}
    cfunc_types = _collect_cfunc_types(contexts, graph)

    def add(kind, key, ctx, lineno):
        if key is None or not isinstance(key, tuple):
            return
        fn = graph.nodes.get(key)
        if fn is None:
            return
        roots.setdefault((kind, key), Root(kind, key, ctx.path, lineno))

    # tokens a file must literally contain to possibly declare a root;
    # the source-text prefilter skips the per-node walk for the many
    # files that spawn nothing
    _root_tokens = ("Thread", "Timer", "atexit", "signal", "finalize",
                    "CFUNCTYPE") + _HANDLER_BASES

    for ctx in contexts:
        module = _module_name(ctx.path)
        imports = graph.imports.get(module)
        if imports is None:
            continue
        if not any(tok in ctx.source for tok in _root_tokens):
            continue
        scope = _scope_map(graph, ctx, module)
        th_aliases = _stdlib_aliases(imports, "threading")
        th_from = _from_names(imports, "threading")
        sig_aliases = _stdlib_aliases(imports, "signal")
        ax_aliases = _stdlib_aliases(imports, "atexit")
        ax_from = _from_names(imports, "atexit")
        wr_aliases = _stdlib_aliases(imports, "weakref")
        wr_from = _from_names(imports, "weakref")

        def resolve(expr, at):
            return resolve_callable(graph, module, scope.get(id(at)),
                                    expr, ctx.aliases)

        for node in ast.walk(ctx.tree):
            # do_* methods of HTTP request-handler subclasses
            if isinstance(node, ast.ClassDef):
                base_names = {getattr(b, "attr", getattr(b, "id", ""))
                              for b in node.bases}
                if base_names & set(_HANDLER_BASES):
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef) \
                                and item.name.startswith("do_"):
                            for fn in graph.by_module.get(
                                    module, {}).values():
                                if fn.ast_node is item:
                                    add("http-handler", fn.key, ctx,
                                        item.lineno)
                continue
            if not isinstance(node, ast.Call):
                continue
            fnx = node.func
            attr = _is_module_attr(fnx, th_aliases)
            local = fnx.id if isinstance(fnx, ast.Name) else None
            # threading.Thread(target=f) / Thread(target=f)
            if attr == "Thread" or th_from.get(local) == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        add("thread", resolve(kw.value, node), ctx,
                            node.lineno)
            # threading.Timer(t, f)
            elif attr == "Timer" or th_from.get(local) == "Timer":
                if len(node.args) >= 2:
                    add("timer", resolve(node.args[1], node), ctx,
                        node.lineno)
            # atexit.register(f, ...)
            elif (_is_module_attr(fnx, ax_aliases) == "register"
                  or ax_from.get(local) == "register"):
                if node.args:
                    add("atexit", resolve(node.args[0], node), ctx,
                        node.lineno)
            # signal.signal(sig, f)
            elif _is_module_attr(fnx, sig_aliases) == "signal":
                if len(node.args) >= 2:
                    add("signal", resolve(node.args[1], node), ctx,
                        node.lineno)
            # weakref.finalize(obj, f, ...)
            elif (_is_module_attr(fnx, wr_aliases) == "finalize"
                  or wr_from.get(local) == "finalize"):
                if len(node.args) >= 2:
                    add("finalizer", resolve(node.args[1], node), ctx,
                        node.lineno)
            # CFUNCTYPE trampoline: WRAPPER(f) -> f runs on C threads
            else:
                target = None
                if isinstance(fnx, ast.Name) \
                        and local in imports.from_import:
                    target = imports.from_import[local]
                elif isinstance(fnx, ast.Attribute) \
                        and isinstance(fnx.value, ast.Name) \
                        and fnx.value.id in imports.module_alias:
                    target = (imports.module_alias[fnx.value.id],
                              fnx.attr)
                if target in cfunc_types and node.args:
                    add("ffi", resolve(node.args[0], node), ctx,
                        node.lineno)
    return list(roots.values())


# -------------------------------------------------------- lock identity


def _collect_locks(contexts):
    """Provable lock objects: module-global / self-attr names assigned
    ``threading.Lock()`` (or RLock/Condition/Semaphore).  Returns
    ({("global", module, name)} | {("attr", module, cls, name)},
    {lock_id: definition lineno})."""
    locks, def_lines = set(), {}
    for ctx in contexts:
        module = _module_name(ctx.path)

        def is_ctor(value):
            if not isinstance(value, ast.Call):
                return False
            fnx = value.func
            name = getattr(fnx, "attr", getattr(fnx, "id", None))
            if name not in _LOCK_CTORS:
                return False
            # Condition(lock) wraps; bare Name ctor must come from
            # threading (from-import) — attribute form checks the root
            if isinstance(fnx, ast.Attribute):
                return (isinstance(fnx.value, ast.Name)
                        and fnx.value.id == "threading")
            return True

        cls_stack = []

        def rec(node):
            if isinstance(node, ast.ClassDef):
                cls_stack.append(node.name)
                for c in ast.iter_child_nodes(node):
                    rec(c)
                cls_stack.pop()
                return
            if isinstance(node, ast.Assign) and is_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name) and not cls_stack:
                        lid = ("global", module, t.id)
                    elif (isinstance(t, ast.Attribute)
                          and isinstance(t.value, ast.Name)
                          and t.value.id == "self" and cls_stack):
                        lid = ("attr", module, ".".join(cls_stack),
                               t.attr)
                    else:
                        continue
                    locks.add(lid)
                    def_lines.setdefault(lid, (ctx, node.lineno))
            for c in ast.iter_child_nodes(node):
                rec(c)

        rec(ctx.tree)
    return locks, def_lines


def _lock_display(lid):
    if lid is _UNKNOWN:
        return "<unknown>"
    if lid[0] == "global":
        return "%s.%s" % (lid[1].rsplit(".", 1)[-1], lid[2])
    return "%s.%s" % (lid[2], lid[3])


# ----------------------------------------------------- per-fn summaries


class _Access:
    __slots__ = ("var", "kind", "lineno", "locks")

    def __init__(self, var, kind, lineno, locks):
        self.var = var        # ("global", mod, name) | ("attr", mod, cls, a)
        self.kind = kind      # "read" | "write" | "rmw"
        self.lineno = lineno
        self.locks = locks    # tuple of lock ids held *within* the fn


class _Summary:
    """One function's lock/shared-state behaviour, lock context
    attached syntactically (``with`` nesting inside this function)."""

    __slots__ = ("fn", "accesses", "acquires", "calls")

    def __init__(self, fn):
        self.fn = fn
        self.accesses = []    # [_Access]
        self.acquires = []    # (lock_id, lineno, locks_before)
        self.calls = []       # (callee_key, lineno, locks)


def _build_summary(fn, ctx, module, graph, module_globals, locks):
    """Scan `fn`'s own scope once, tracking the with-lock context."""
    s = _Summary(fn)
    imports = graph.imports[module]
    in_init = fn.qualname.rsplit(".", 1)[-1] in _INIT_METHODS
    call_sites = {id(call) for _key, call in fn.calls}
    call_locks = {}  # id(ast.Call) -> locks tuple held at the site
    global_decls = set()  # names this fn rebinds via `global x`
    for node in ast.walk(fn.ast_node):
        if isinstance(node, ast.Global):
            global_decls.update(node.names)

    def lock_of(expr):
        """Lock id for a with-context expression, _UNKNOWN when the
        acquisition cannot be modelled, None when provably not a lock.
        A ``with <call>:`` is normally not one of our lock objects
        (open(), scope()) — EXCEPT calls whose name says lock
        (``self._key_lock(k)``): those return per-key locks we cannot
        identify, so they poison the held-set instead of silently
        reading as lock-free."""
        if isinstance(expr, ast.Call):
            name = getattr(expr.func, "attr",
                           getattr(expr.func, "id", "")) or ""
            if any(s in name.lower() for s in ("lock", "cond", "sem",
                                               "mutex")):
                return _UNKNOWN
            return None
        if isinstance(expr, ast.Name):
            if expr.id in fn.bound:
                return _UNKNOWN  # local rebind: unmodelled
            lid = ("global", module, expr.id)
            return lid if lid in locks else _UNKNOWN
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            if expr.value.id in ("self", "cls") and fn.cls:
                lid = ("attr", module, fn.cls, expr.attr)
                return lid if lid in locks else _UNKNOWN
            alias = imports.module_alias.get(expr.value.id)
            if alias is not None:
                lid = ("global", alias, expr.attr)
                return lid if lid in locks else _UNKNOWN
        return _UNKNOWN

    def shared_var(expr):
        """expr (a Name/Attribute base being accessed) -> var id."""
        if isinstance(expr, ast.Name):
            if expr.id in global_decls \
                    and expr.id in module_globals.get(module, {}):
                return ("global", module, expr.id)
            if expr.id in fn.bound or expr.id not in \
                    module_globals.get(module, {}):
                return None
            return ("global", module, expr.id)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and fn.cls and not in_init:
                return ("attr", module, fn.cls, expr.attr)
            alias = imports.module_alias.get(expr.value.id)
            if alias is not None and expr.attr in \
                    module_globals.get(alias, {}):
                return ("global", alias, expr.attr)
        return None

    def record(var, kind, node, held):
        if var is not None:
            s.accesses.append(_Access(var, kind, node.lineno, held))

    def base_of(target):
        """Peel Subscript/Attribute chains: the object mutated."""
        while isinstance(target, (ast.Subscript, ast.Attribute)):
            inner = target.value
            if isinstance(inner, ast.Name):
                return inner
            if isinstance(inner, ast.Attribute) \
                    and isinstance(inner.value, ast.Name) \
                    and inner.value.id in ("self", "cls"):
                return inner
            target = inner
        return None

    def store_target(t, kind, node, held):
        if isinstance(t, ast.Name):
            if t.id in global_decls:
                record(shared_var(t), kind, node, held)
        elif isinstance(t, ast.Attribute):
            record(shared_var(t), kind, node, held)
        elif isinstance(t, ast.Subscript):
            record(shared_var(base_of(t)), kind, node, held)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                store_target(e, kind, node, held)
        elif isinstance(t, ast.Starred):
            store_target(t.value, kind, node, held)

    def rec(node, held):
        # nested defs / name=lambda are their own graph nodes
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Lambda) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                rec(item.context_expr, inner)
                lid = lock_of(item.context_expr)
                if lid is not None:
                    s.acquires.append((lid, node.lineno, inner))
                    inner = inner + (lid,)
            for stmt in node.body:
                rec(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                store_target(t, "write", node, held)
        elif isinstance(node, ast.AugAssign):
            store_target(node.target, "rmw", node, held)
        elif isinstance(node, ast.Call):
            fnx = node.func
            if isinstance(fnx, ast.Attribute) and fnx.attr in _MUTATORS:
                record(shared_var(fnx.value), "write", node, held)
            if id(node) in call_sites:
                call_locks[id(node)] = held
        elif isinstance(node, ast.Name) \
                and isinstance(node.ctx, ast.Load):
            record(shared_var(node), "read", node, held)
            return
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            var = shared_var(node)
            if var is not None:
                record(var, "read", node, held)
                return  # don't double-count the inner Name
        for child in ast.iter_child_nodes(node):
            rec(child, held)

    body = fn.ast_node.body if not isinstance(fn.ast_node, ast.Lambda) \
        else [fn.ast_node.body]
    for stmt in body:
        rec(stmt, ())
    for key, call in fn.calls:
        s.calls.append((key, call.lineno,
                        call_locks.get(id(call), ())))
    return s


# ------------------------------------------------------------ the pass


def _module_global_map(contexts):
    """{module: {name: (ctx, def lineno)}} for module-level assigns."""
    out = {}
    for ctx in contexts:
        module = _module_name(ctx.path)
        table = out.setdefault(module, {})
        for node in ctx.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                    and isinstance(node.target, ast.Name):
                targets = [node.target]
            for t in targets:
                table.setdefault(t.id, (ctx, node.lineno))
    return out


def _attr_def_map(contexts):
    """{("attr", module, cls, attr): (ctx, lineno)} — the FIRST
    ``self.<attr> = ...`` assignment inside each class body (the slot's
    definition line, where a disable pragma clears every finding)."""
    out = {}
    for ctx in contexts:
        module = _module_name(ctx.path)

        def rec(node, cls_stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    rec(child, cls_stack + [child.name])
                    continue
                if isinstance(child, (ast.Assign,
                                      ast.AnnAssign)) and cls_stack:
                    targets = child.targets \
                        if isinstance(child, ast.Assign) \
                        else [child.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            out.setdefault(
                                ("attr", module, ".".join(cls_stack),
                                 t.attr), (ctx, child.lineno))
                rec(child, cls_stack)

        rec(ctx.tree, [])
    return out


class _RootWalk:
    """DFS from one root carrying held-lock sets and the call path."""

    def __init__(self, graph, summaries, root_name):
        self.graph = graph
        self.summaries = summaries
        self.root = root_name
        self.accesses = []     # (root, _Access-like with absolute locks,
                               # fn, path tuple)
        self.edges = {}        # (lockA, lockB) -> (root, path, ctxfn,
                               # lineno)
        self.memo = set()

    def walk(self, key, held=frozenset(), path=(), depth=0):
        fn = self.graph.nodes.get(key)
        s = self.summaries.get(key)
        if fn is None or s is None or depth > 48:
            return
        mkey = (key, held)
        if mkey in self.memo:
            return
        self.memo.add(mkey)
        path = path + (fn.display,)
        for a in s.accesses:
            self.accesses.append((self.root, a.var, a.kind, a.lineno,
                                  frozenset(held | set(a.locks)), fn,
                                  path))
        for lid, lineno, before in s.acquires:
            now_held = held | set(before)
            for h in now_held:
                if h is _UNKNOWN or lid is _UNKNOWN or h == lid:
                    continue
                self.edges.setdefault(
                    (h, lid), (self.root, path, fn, lineno))
        for callee, lineno, locks in s.calls:
            self.walk(callee, frozenset(held | set(locks)), path,
                      depth + 1)


def check_threads(contexts, config, graph):
    """Run both thread rules; appends findings to ctx.findings."""
    want_state = RULE_STATE in config.rules
    want_order = RULE_ORDER in config.rules
    if not (want_state or want_order):
        return []
    roots = discover_roots(graph, contexts)
    locks, _lock_defs = _collect_locks(contexts)
    module_globals = _module_global_map(contexts)
    by_path = {ctx.path: ctx for ctx in contexts}

    # one summary per function, built once
    summaries = {}
    for key, fn in graph.nodes.items():
        ctx = by_path.get(fn.path)
        if ctx is None:
            continue
        summaries[key] = _build_summary(fn, ctx, fn.module, graph,
                                        module_globals, locks)

    # the api root: everything not exclusively inside a thread cone
    root_keys = {r.key for r in roots}
    cone = set()
    frontier = list(root_keys)
    while frontier:
        key = frontier.pop()
        if key in cone:
            continue
        cone.add(key)
        s = summaries.get(key)
        if s:
            frontier.extend(k for k, _l, _h in s.calls)
    callers = {}
    for key, s in summaries.items():
        for callee, _l, _h in s.calls:
            callers.setdefault(callee, set()).add(key)
    api_entries = [key for key in summaries
                   if key not in root_keys
                   # *_locked: caller-holds-the-lock convention — only
                   # reachable through callers that took the lock
                   and not key[1].rsplit(".", 1)[-1].endswith("_locked")
                   and (key not in cone
                        or any(c not in cone
                               for c in callers.get(key, ())))]

    walks = []
    for r in roots:
        w = _RootWalk(graph, summaries, r.name)
        w.walk(r.key)
        walks.append(w)
    api_walk = _RootWalk(graph, summaries, "api")
    for key in api_entries:
        api_walk.walk(key)
    walks.append(api_walk)

    if want_state:
        attr_defs = _attr_def_map(contexts)
        _check_shared_state(walks, module_globals, attr_defs, by_path)
    if want_order:
        _check_lock_order(walks, by_path)
    return roots


def _var_display(var):
    if var[0] == "global":
        return "%s.%s" % (var[1], var[2])
    return "%s.%s" % (var[2], var[3])


def _def_line_pragma(var, module_globals, attr_defs, rule):
    """Pragma on the shared variable's definition line — or on a pure
    comment line directly above it, where a one-line justification
    fits — clears every finding for it (pragma at the source clears
    transitive sites)."""
    if var[0] == "global":
        entry = module_globals.get(var[1], {}).get(var[2])
    else:
        entry = attr_defs.get(var)
    if entry is None:
        return False
    ctx, lineno = entry
    if _pragma_disabled(ctx.line(lineno), rule):
        return True
    above = ctx.line(lineno - 1).strip() if lineno > 1 else ""
    return above.startswith("#") and _pragma_disabled(above, rule)


def _fmt_locks(locks):
    real = sorted(_lock_display(x) for x in locks if x is not _UNKNOWN)
    return "{%s}" % ", ".join(real) if real else "no lock"


def _check_shared_state(walks, module_globals, attr_defs, by_path):
    by_var = {}
    for w in walks:
        for root, var, kind, lineno, held, fn, path in w.accesses:
            by_var.setdefault(var, []).append(
                (root, kind, lineno, held, fn, path))
    for var in sorted(by_var):
        accs = [a for a in by_var[var] if _UNKNOWN not in a[3]]
        roots = {a[0] for a in accs}
        if len(roots) < 2:
            continue
        writes = [a for a in accs if a[1] in ("write", "rmw")]
        if not writes:
            continue
        if _def_line_pragma(var, module_globals, attr_defs, RULE_STATE):
            continue
        hit = None
        for w_ in writes:
            for a in accs:
                if a[0] == w_[0]:
                    continue
                if w_[3] & a[3]:
                    continue  # common lock: consistent
                if not (w_[3] | a[3]):
                    continue  # both lock-free: GIL-atomic idiom
                hit = (w_, a, "inconsistent")
                break
            if hit:
                break
        if hit is None:
            for w_ in writes:
                if w_[1] == "rmw" and not w_[3]:
                    hit = (w_, None, "rmw")
                    break
        if hit is None:
            continue
        w_, a, why = hit
        ctx = by_path.get(w_[4].path)
        if ctx is None:
            continue
        if why == "inconsistent":
            msg = ("shared %s written under root '%s' holding %s but "
                   "accessed under root '%s' holding %s (%s:%d in %s) "
                   "— the lock sets never intersect, so the two sides "
                   "race; take one common lock or pragma the variable "
                   "definition if the disagreement is by design"
                   % (_var_display(var), w_[0], _fmt_locks(w_[3]),
                      a[0], _fmt_locks(a[3]), by_path[a[4].path].path,
                      a[2], a[4].display))
        else:
            msg = ("unlocked read-modify-write on shared %s under root "
                   "'%s' — increments are LOAD/ADD/STORE, not atomic; "
                   "another root accesses this variable concurrently"
                   % (_var_display(var), w_[0]))
        ctx.add(RULE_STATE, _Loc(w_[2]), msg, w_[4].qualname)


def _check_lock_order(walks, by_path):
    edges = {}
    for w in walks:
        for pair, witness in w.edges.items():
            edges.setdefault(pair, witness)
    reported = set()
    for (a, b), (root1, path1, fn1, line1) in sorted(
            edges.items(), key=lambda kv: (kv[1][2].path, kv[1][3])):
        inv = edges.get((b, a))
        if inv is None:
            continue
        pair_key = frozenset(((a, b), (b, a)))
        if pair_key in reported:
            continue
        reported.add(pair_key)
        root2, path2, fn2, line2 = inv
        ctx1 = by_path.get(fn1.path)
        ctx2 = by_path.get(fn2.path)
        if ctx1 is None or ctx2 is None:
            continue
        # pragma on EITHER acquisition line clears the pair
        if _pragma_disabled(ctx2.line(line2), RULE_ORDER):
            continue
        msg = ("lock-order inversion between %s and %s: %s → %s "
               "acquires %s then %s (%s:%d); %s → %s acquires %s then "
               "%s (%s:%d) — two threads interleaving these paths "
               "deadlock"
               % (_lock_display(a), _lock_display(b),
                  root1, " → ".join(path1), _lock_display(a),
                  _lock_display(b), ctx1.path, line1,
                  root2, " → ".join(path2), _lock_display(b),
                  _lock_display(a), ctx2.path, line2))
        ctx1.add(RULE_ORDER, _Loc(line1), msg, fn1.qualname)
