"""AST checkers: the per-file mxlint rules (plus the driver that also
runs the interprocedural pass in callgraph.py).

Rules
-----
trace-host-sync
    Implicit device->host syncs in op compute paths: ``.item()`` /
    ``.tolist()`` / ``.asnumpy()`` / ``.block_until_ready()`` calls,
    ``jax.device_get``, ``float()/int()/bool()`` applied to
    tensor-typed names, and ``np.asarray``/``np.array`` on jax values.
    Allowed inside the explicit sync points (``wait_to_read``,
    ``asnumpy``, ``__bool__``, ...) whose whole purpose is to sync.

static-argnames
    ``jax.jit(..., static_argnames=...)`` hygiene: every name must be a
    real parameter of the jitted function and must be
    hashable-by-construction (no list/dict/set/ndarray defaults) — an
    unhashable static arg raises at call time, and an array-valued one
    recompiles per step.

registry-consistency
    The hand-maintained tables in ops/registry.py (OP_INPUT_NAMES,
    OP_AUX_INPUTS, OP_LABEL_INPUTS) must agree with the ops actually
    registered via ``@register(...)``/``alias(...)``, and every
    registered op function must carry a docstring.

dtype-default
    Bare ``np.float64`` (or dtype="float64") and dtype-less numpy
    array creation (``np.zeros`` & friends default to float64) in op
    code — silently upcasts, then XLA truncates on TPU.

host-sync-reachability
    Interprocedural: a compute-path function whose callee
    *transitively* reaches a host sync through any chain of statically
    resolvable calls, plus host-side branching on tensor values.
    Implemented in callgraph.py (module-level call graph, reverse-BFS
    reachability, full offending path in the message).

thread-shared-state / thread-lock-order
    Interprocedural thread-topology pass (threads.py): discovers
    thread roots (Thread targets, timers, atexit/signal hooks, weakref
    finalizers, HTTP handlers, ctypes trampolines), walks each root's
    call cone tracking held ``with <lock>:`` sets, and flags shared
    state written under one root and touched under another with
    inconsistent locks, unlocked RMW on shared counters, and
    cross-root lock-order inversions (both acquisition paths printed).

donation-safety
    From every ``jax.jit(..., donate_argnums=...)`` binding
    (donation.py): donating call sites must rebind their donated
    arguments (rebind-after-call), and ``._data`` captured before a
    donating region must flow through the pin/materialize seam before
    it can outlive the call.

guard-first / env-registry
    Conformance pass (conformance.py): every registered telemetry feed
    statically begins with its one-dict-read enabled guard; every
    literal MXNET_TPU_*/MXTPU_* environ read has a docs/ENV_VARS.md
    row, and (on full-tree runs) every documented row has a real read.

Suppression: a ``# mxlint: disable`` or ``# mxlint: disable=rule[,rule]``
comment on the finding's line silences it at the source; the baseline
file (findings.py) grandfathers whole findings instead.
"""

from __future__ import annotations

import ast
import fnmatch
import os

from .findings import Finding

__all__ = ["Config", "lint_paths", "lint_sources", "ALL_RULES"]

ALL_RULES = ("trace-host-sync", "static-argnames", "registry-consistency",
             "dtype-default", "host-sync-reachability",
             "thread-shared-state", "thread-lock-order",
             "donation-safety", "guard-first", "env-registry")

# rules that need the cross-file call graph from callgraph.py
_GRAPH_RULES = frozenset({"host-sync-reachability", "thread-shared-state",
                          "thread-lock-order", "donation-safety"})

# functions whose contract IS the device->host sync (reference parity:
# WaitToRead/asnumpy are the documented engine sync points)
SYNC_WHITELIST = frozenset({
    "asnumpy", "asscalar", "item", "tolist", "wait_to_read",
    "wait_to_write", "waitall", "save", "debug_str",
    "__bool__", "__repr__", "__str__", "__array__", "__float__",
    "__int__", "__index__", "__len__", "__format__",
})

# numpy creation routines whose dtype defaults to float64
_NP_F64_CREATORS = frozenset({
    "zeros", "ones", "empty", "full", "arange", "linspace", "logspace",
    "eye", "identity", "geomspace",
})

_REGISTRY_TABLES = ("OP_INPUT_NAMES", "OP_AUX_INPUTS", "OP_LABEL_INPUTS")


class _Loc:
    """Bare line anchor for findings not tied to one AST node."""

    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0


class Config:
    """What to lint and where each rule applies."""

    def __init__(self, rules=ALL_RULES, compute_path_globs=None,
                 ops_globs=None, registry_globs=None,
                 sync_whitelist=SYNC_WHITELIST):
        self.rules = tuple(rules)
        # trace-host-sync scope: the op compute paths
        self.compute_path_globs = tuple(compute_path_globs or (
            "*mxnet_tpu/ops/*.py",
            "*mxnet_tpu/ndarray/ndarray.py",
            "*mxnet_tpu/executor.py",
            "*mxnet_tpu/autograd.py",
        ))
        # dtype-default scope: op kernel code
        self.ops_globs = tuple(ops_globs or ("*mxnet_tpu/ops/*.py",))
        # files whose registry tables / @register sites feed the
        # registry-consistency cross-check
        self.registry_globs = tuple(registry_globs or
                                    ("*mxnet_tpu/ops/*.py",))
        self.sync_whitelist = frozenset(sync_whitelist)
        # the table-key-vs-registered-op cross-check needs the WHOLE op
        # package in scope to be sound; lint_paths turns this off for
        # partial runs (table-internal checks still run)
        self.check_unregistered_table_keys = True
        # guard-first feed registry override (None -> conformance.py's
        # DEFAULT_FEEDS) and env-registry anchors; the stale-doc-row
        # direction is only sound when the whole package was linted, so
        # lint_paths enables it for complete runs only
        self.guard_feeds = None
        self.env_docs_path = None
        self.repo_root = None
        self.check_env_doc_stale = False

    def matches(self, globs, path):
        p = path.replace(os.sep, "/")
        return any(fnmatch.fnmatch(p, g) for g in globs)


# --------------------------------------------------------------- helpers


def _iter_py_files(paths, errors=None):
    for p in paths:
        if not os.path.exists(p):
            # a mistyped path must not read as a clean lint
            if errors is not None:
                errors.append("%s: path does not exist" % p)
            continue
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        elif p.endswith(".py"):
            yield p
        elif errors is not None:
            # an existing non-.py file must not read as a clean lint
            errors.append("%s: not a python file" % p)


def _pragma_disabled(line_text, rule):
    """`# mxlint: disable` / `# mxlint: disable=a,b` on the line."""
    marker = "# mxlint:"
    idx = line_text.find(marker)
    if idx < 0:
        return False
    directive = line_text[idx + len(marker):].strip()
    if not directive.startswith("disable"):
        return False
    rest = directive[len("disable"):]
    if rest.startswith("="):
        names = rest[1:].split("--")[0]
        return rule in [n.strip()
                        for n in names.replace(";", ",").split(",")]
    # bare disable-all only when nothing (or just a reason) follows —
    # 'disable-next-line=x' / 'disabled' must not suppress everything
    rest = rest.strip()
    return rest == "" or rest.startswith("--")


class _Aliases:
    """Import-name resolution for numpy / jax / jax.numpy / functools."""

    def __init__(self, tree):
        self.numpy = set()
        self.jnp = set()
        self.jax = set()
        self.functools = set()
        self.from_jax = {}  # local name -> jax attr (e.g. jit, device_get)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name
                    if a.name == "numpy":
                        self.numpy.add(name)
                    elif a.name in ("jax.numpy", "jax.numpy.linalg"):
                        self.jnp.add(name)
                    elif a.name == "jax":
                        self.jax.add(name)
                    elif a.name == "functools":
                        self.functools.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        local = a.asname or a.name
                        if a.name == "numpy":
                            self.jnp.add(local)
                        else:
                            self.from_jax[local] = a.name
                elif node.module == "numpy":
                    pass  # from numpy import X — not alias-tracked

    def is_np_attr(self, node, attr_names):
        """node is `np.<attr>` for a numpy alias and attr in attr_names."""
        return (isinstance(node, ast.Attribute)
                and node.attr in attr_names
                and isinstance(node.value, ast.Name)
                and node.value.id in self.numpy)

    def is_jnp_call_root(self, node):
        """node's dotted root is a jax.numpy / jax.lax / jax.nn alias."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return isinstance(node, ast.Name) and (node.id in self.jnp
                                               or node.id in self.jax)

    def is_jax_jit(self, node):
        """node is `jax.jit` / a from-jax `jit` name."""
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return (isinstance(node.value, ast.Name)
                    and node.value.id in self.jax)
        if isinstance(node, ast.Name):
            return self.from_jax.get(node.id) == "jit"
        return False

    def is_device_get(self, node):
        if isinstance(node, ast.Attribute) and node.attr == "device_get":
            return (isinstance(node.value, ast.Name)
                    and node.value.id in self.jax)
        if isinstance(node, ast.Name):
            return self.from_jax.get(node.id) == "device_get"
        return False


def _is_register_decorated(fn_node):
    for dec in fn_node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = getattr(target, "id", getattr(target, "attr", None))
        if name == "register":
            return True
    return False


# ------------------------------------- shared tensor-ness inference
# (used by the per-function trace-host-sync visitor below and by the
# interprocedural pass in callgraph.py)


def _tensor_params(fn):
    """For @register ops the calling convention is
    ``fn(*tensor_inputs, **attrs)``: positional params with no
    default are tensor inputs, defaulted params are attrs."""
    if not _is_register_decorated(fn):
        return set()
    args = fn.args
    pos = list(args.posonlyargs) + list(args.args)
    n_tensor = len(pos) - len(args.defaults)
    return {a.arg for a in pos[:n_tensor]}


def _own_scope_nodes(fn):
    """All nodes of `fn` except bodies of nested function defs —
    a nested scope's local names must not leak into this one."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _is_tensor_expr(node, tensor_names, aliases):
    if isinstance(node, ast.Name):
        return node.id in tensor_names
    if isinstance(node, ast.Attribute):
        return node.attr == "_data"
    if isinstance(node, ast.BinOp):
        return (_is_tensor_expr(node.left, tensor_names, aliases)
                or _is_tensor_expr(node.right, tensor_names, aliases))
    if isinstance(node, ast.UnaryOp):
        return _is_tensor_expr(node.operand, tensor_names, aliases)
    if isinstance(node, ast.Subscript):
        return _is_tensor_expr(node.value, tensor_names, aliases)
    if isinstance(node, ast.Call):
        return aliases.is_jnp_call_root(node.func)
    return False


def _collect_tensor_names(fn, seed, aliases):
    """Fixpoint over simple assignments: names bound to tensor
    expressions (x._data, jnp calls, arithmetic on tensors)."""
    names = set(seed)
    scope = _own_scope_nodes(fn)
    for _ in range(3):
        before = len(names)
        for node in scope:
            if isinstance(node, ast.Assign):
                if _is_tensor_expr(node.value, names, aliases):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
            elif isinstance(node, ast.AnnAssign) and node.value:
                if (isinstance(node.target, ast.Name)
                        and _is_tensor_expr(node.value, names, aliases)):
                    names.add(node.target.id)
        if len(names) == before:
            break
    return names


def _has_docstring(fn_node):
    return bool(fn_node.body
                and isinstance(fn_node.body[0], ast.Expr)
                and isinstance(fn_node.body[0].value, ast.Constant)
                and isinstance(fn_node.body[0].value.value, str))


def _literal_str_seq(node):
    """['a', 'b'] / ('a', 'b') / 'a' -> list of strings, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return out
    return None


# ------------------------------------------------------- per-file state


class _FileCtx:
    def __init__(self, path, source, config):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = _Aliases(self.tree)
        self.config = config
        self.findings = []
        # registry-consistency collection (aggregated across files)
        self.registered = []     # (name, fn_node, has_doc, lineno)
        self.alias_calls = []    # (name, target, lineno)
        self.tables = {}         # table name -> {key: (lineno, values)}

    def line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def add(self, rule, node, message, symbol=""):
        lineno = getattr(node, "lineno", 1)
        text = self.line(lineno)
        if _pragma_disabled(text, rule):
            return
        self.findings.append(Finding(
            rule, self.path, lineno, getattr(node, "col_offset", 0),
            message, symbol=symbol, code_line=text))


# ------------------------------------------------- rule: trace-host-sync


class _TraceSafetyVisitor(ast.NodeVisitor):
    """Walks one module; checks every function on the compute path."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.stack = []       # (name, tensor_names, whitelisted)

    def _is_tensor_expr(self, node, tensor_names):
        return _is_tensor_expr(node, tensor_names, self.ctx.aliases)

    # -- traversal -------------------------------------------------------
    def _visit_function(self, node):
        whitelisted = (node.name in self.ctx.config.sync_whitelist
                       or any(w for _, _, w in self.stack))
        tensors = _collect_tensor_names(
            node, _tensor_params(node), self.ctx.aliases)
        self.stack.append((node.name, tensors, whitelisted))
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _qualname(self):
        return ".".join(n for n, _, _ in self.stack)

    def _in_whitelisted(self):
        return any(w for _, _, w in self.stack)

    def _tensors(self):
        return self.stack[-1][1] if self.stack else set()

    def visit_Call(self, node):
        self.generic_visit(node)
        if self._in_whitelisted():
            return
        ctx, al = self.ctx, self.ctx.aliases
        qual = self._qualname()
        fn = node.func
        # .item() / .tolist() / .asnumpy() / .block_until_ready()
        if isinstance(fn, ast.Attribute):
            if fn.attr in ("item", "asnumpy", "tolist"):
                ctx.add("trace-host-sync", node,
                        ".%s() forces a device->host copy; keep values "
                        "on device or sync via asnumpy() at an explicit "
                        "sync point" % fn.attr, qual)
                return
            if fn.attr == "block_until_ready":
                ctx.add("trace-host-sync", node,
                        ".block_until_ready() blocks the dispatch "
                        "thread; only wait_to_read/waitall may sync",
                        qual)
                return
        # jax.device_get(...)
        if al.is_device_get(fn):
            ctx.add("trace-host-sync", node,
                    "jax.device_get() is an implicit host sync", qual)
            return
        # float/int/bool/complex on tensor-typed names
        if (isinstance(fn, ast.Name)
                and fn.id in ("float", "int", "bool", "complex")
                and len(node.args) == 1 and not node.keywords
                and self._is_tensor_expr(node.args[0], self._tensors())):
            ctx.add("trace-host-sync", node,
                    "%s() on a tensor value materializes it on host "
                    "(and fails under jit tracing); use jnp casts or "
                    "keep the value symbolic" % fn.id, qual)
            return
        # np.asarray / np.array on tensor values
        if (al.is_np_attr(fn, ("asarray", "array", "ascontiguousarray"))
                and node.args
                and self._is_tensor_expr(node.args[0], self._tensors())):
            ctx.add("trace-host-sync", node,
                    "np.%s() on a jax value copies it to host; use "
                    "jnp.asarray to stay on device" % fn.attr, qual)


def _check_trace_safety(ctx):
    _TraceSafetyVisitor(ctx).visit(ctx.tree)


# ----------------------------------------------- rule: static-argnames


def _check_static_argnames(ctx):
    # map: function name -> FunctionDef (module level), for jit(fn, ...)
    module_fns = {n.name: n for n in ctx.tree.body
                  if isinstance(n, ast.FunctionDef)}
    decorated = {}  # id(call node) -> FunctionDef it decorates
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                for sub in ast.walk(dec):
                    decorated[id(sub)] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if "static_argnames" not in kw:
            continue
        al = ctx.aliases
        is_jit = al.is_jax_jit(node.func)
        # functools.partial(jax.jit, static_argnames=...)
        if (not is_jit and isinstance(node.func, ast.Attribute)
                and node.func.attr == "partial"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in al.functools
                and node.args and al.is_jax_jit(node.args[0])):
            is_jit = True
        if not is_jit:
            continue
        names = _literal_str_seq(kw["static_argnames"])
        if names is None:
            ctx.add("static-argnames", node,
                    "static_argnames is not a literal list of strings; "
                    "mxlint cannot prove the cache key is hashable")
            continue
        # find the target function: decorator site, or jit(fn, ...)
        fn_node = decorated.get(id(node))
        if fn_node is None:
            cand = None
            for a in node.args:
                if isinstance(a, ast.Name) and a.id in module_fns:
                    cand = module_fns[a.id]
                    break
            fn_node = cand
        if fn_node is None:
            continue  # dynamic target: signature not statically known
        args = fn_node.args
        pos = list(args.posonlyargs) + list(args.args)
        kwonly = list(args.kwonlyargs)
        all_params = {a.arg for a in pos + kwonly}
        defaults = dict(zip([a.arg for a in pos[len(pos)
                                                - len(args.defaults):]],
                            args.defaults))
        defaults.update({a.arg: d for a, d in zip(kwonly,
                                                  args.kw_defaults) if d})
        for name in names:
            if name not in all_params:
                if args.kwarg is not None:
                    continue  # absorbed by **kwargs; not provable
                ctx.add("static-argnames", node,
                        "static_argnames names %r which is not a "
                        "parameter of %s() — it will never be treated "
                        "as static" % (name, fn_node.name))
                continue
            d = defaults.get(name)
            if d is None:
                continue
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                ctx.add("static-argnames", fn_node,
                        "static arg %r of %s() defaults to an "
                        "unhashable %s literal — jit raises on it, and "
                        "per-call containers recompile every step"
                        % (name, fn_node.name, type(d).__name__.lower()))
            elif (isinstance(d, ast.Call)
                  and (ctx.aliases.is_jnp_call_root(d.func)
                       or ctx.aliases.is_np_attr(
                           d.func, _NP_F64_CREATORS | {"array",
                                                       "asarray"}))):
                ctx.add("static-argnames", fn_node,
                        "static arg %r of %s() defaults to an array "
                        "value — arrays as static args hash by id and "
                        "recompile every call" % (name, fn_node.name))


# ------------------------------------------- rule: registry-consistency


def _collect_registry_info(ctx):
    """Per-file collection: registrations, alias() calls, tables."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names = _registered_names(node)
            if not names and _is_register_decorated(node):
                # registered under a computed name (factory loops);
                # the runtime audit resolves the real name
                names = ["<%s>" % node.name]
            for n in names:
                ctx.registered.append((n, node, _has_docstring(node),
                                       node.lineno))
        elif isinstance(node, ast.Call):
            target = node.func
            cname = getattr(target, "id", getattr(target, "attr", None))
            if cname == "alias" and len(node.args) >= 2:
                a0 = _literal_str_seq(node.args[0])
                a1 = _literal_str_seq(node.args[1])
                # non-literal alias loops (linalg.py) are covered by the
                # runtime audit instead
                if a0 and a1 and len(a0) == 1 and len(a1) == 1:
                    ctx.alias_calls.append((a0[0], a1[0], node.lineno))
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id in _REGISTRY_TABLES):
                    ctx.tables[t.id] = _parse_table(node.value, ctx, t.id)


def _registered_names(fn_node):
    """All op names this def registers: register("name", aliases=[...])."""
    out = []
    for dec in fn_node.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        target = dec.func
        name = getattr(target, "id", getattr(target, "attr", None))
        if name != "register":
            continue
        if dec.args:
            lit = _literal_str_seq(dec.args[0])
            if lit:
                out.extend(lit)
        for k in dec.keywords:
            if k.arg == "aliases":
                lit = _literal_str_seq(k.value)
                if lit:
                    out.extend(lit)
    return out


def _parse_table(value_node, ctx, tname):
    """Dict/set literal -> {key: (lineno, tuple-of-value-strings)};
    flags duplicate keys within the literal (later wins at runtime,
    silently shadowing the first entry)."""
    table = {}

    def put(key, lineno, vals):
        if key in table:
            ctx.add("registry-consistency", _Loc(lineno),
                    "%s key %r appears twice in the same literal; the "
                    "second entry silently shadows the first"
                    % (tname, key))
            return
        table[key] = (lineno, tuple(vals))

    if isinstance(value_node, ast.Dict):
        for k, v in zip(value_node.keys, value_node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                put(k.value, k.lineno, _literal_str_seq(v) or ())
    elif isinstance(value_node, ast.Set):
        for e in value_node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                put(e.value, e.lineno, ())
    return table


def _check_registry_consistency(contexts):
    """Cross-file pass over everything collected from registry files."""
    registered = set()
    by_target = {}
    reg_ctxs = []
    for ctx in contexts:
        if not ctx.config.matches(ctx.config.registry_globs, ctx.path):
            continue
        reg_ctxs.append(ctx)
        flagged_defs = set()
        for name, fn_node, has_doc, lineno in ctx.registered:
            registered.add(name)
            if not has_doc and id(fn_node) not in flagged_defs:
                flagged_defs.add(id(fn_node))
                ctx.add("registry-consistency", fn_node,
                        "registered op %r has no docstring (op docs "
                        "drive list_ops()/help introspection)" % name,
                        fn_node.name)
        for name, target, _lineno in ctx.alias_calls:
            by_target.setdefault(target, []).append(name)
    # resolve literal alias() chains
    frontier = True
    while frontier:
        frontier = False
        for target, names in by_target.items():
            if target in registered:
                for n in names:
                    if n not in registered:
                        registered.add(n)
                        frontier = True
    if not reg_ctxs:
        return
    config = reg_ctxs[0].config

    # merge tables across every registry file (duplicate keys flagged)
    merged = {t: {} for t in _REGISTRY_TABLES}
    any_tables = False
    for ctx in reg_ctxs:
        for tname, table in ctx.tables.items():
            any_tables = True
            for key, (lineno, vals) in table.items():
                if key in merged[tname]:
                    ctx.add("registry-consistency", _Loc(lineno),
                            "%s key %r is defined in more than one "
                            "file; one definition silently wins at "
                            "import time" % (tname, key))
                    continue
                merged[tname][key] = (ctx, lineno, vals)
    if not any_tables:
        return
    input_table = merged["OP_INPUT_NAMES"]

    # the cross-check against @register sites needs those sites in
    # scope; table-INTERNAL checks below run regardless
    if config.check_unregistered_table_keys and registered:
        for key, (ctx, lineno, _vals) in input_table.items():
            if key not in registered:
                ctx.add("registry-consistency", _Loc(lineno),
                        "OP_INPUT_NAMES key %r does not name a "
                        "registered op (stale table entry?)" % key)
    for tname in ("OP_AUX_INPUTS", "OP_LABEL_INPUTS"):
        for key, (ctx, lineno, vals) in merged[tname].items():
            if key not in input_table:
                ctx.add("registry-consistency", _Loc(lineno),
                        "%s key %r is missing from OP_INPUT_NAMES"
                        % (tname, key))
                continue
            in_names = set(input_table[key][2])
            for v in vals:
                if v not in in_names:
                    ctx.add(
                        "registry-consistency", _Loc(lineno),
                        "%s[%r] names input %r which is not in "
                        "OP_INPUT_NAMES[%r]" % (tname, key, v, key))


# ------------------------------------------------- rule: dtype-default


class _DtypeVisitor(ast.NodeVisitor):
    def __init__(self, ctx):
        self.ctx = ctx
        self.stack = []

    def _visit_function(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _qual(self):
        return ".".join(self.stack)

    def visit_Attribute(self, node):
        self.generic_visit(node)
        if self.ctx.aliases.is_np_attr(node, ("float64", "double")):
            self.ctx.add("dtype-default", node,
                         "np.%s silently upcasts op math to 64-bit; "
                         "TPUs have no f64 — use float32/bfloat16"
                         % node.attr, self._qual())

    def visit_Call(self, node):
        self.generic_visit(node)
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        dtype = kw.get("dtype")
        if (isinstance(dtype, ast.Constant)
                and isinstance(dtype.value, str)
                and dtype.value in ("float64", "double", "f8", ">f8",
                                    "<f8")):
            self.ctx.add("dtype-default", node,
                         "dtype=%r requests 64-bit floats; TPUs have "
                         "no f64" % dtype.value, self._qual())
            return
        if (self.ctx.aliases.is_np_attr(node.func, _NP_F64_CREATORS)
                and "dtype" not in kw):
            self.ctx.add("dtype-default", node,
                         "np.%s() without dtype= defaults to float64 "
                         "on host and upcasts downstream math; pass an "
                         "explicit dtype" % node.func.attr, self._qual())


def _check_dtype_default(ctx):
    _DtypeVisitor(ctx).visit(ctx.tree)


# --------------------------------------------------------------- driver


def lint_sources(named_sources, config=None):
    """Lint {path: source} mappings; returns (findings, errors)."""
    config = config or Config()
    contexts, errors = [], []
    for path in sorted(named_sources):
        try:
            contexts.append(_FileCtx(path, named_sources[path], config))
        except SyntaxError as e:
            errors.append("%s: syntax error: %s" % (path, e))
    for ctx in contexts:
        if ("trace-host-sync" in config.rules
                and config.matches(config.compute_path_globs, ctx.path)):
            _check_trace_safety(ctx)
        if "static-argnames" in config.rules:
            _check_static_argnames(ctx)
        if "dtype-default" in config.rules \
                and config.matches(config.ops_globs, ctx.path):
            _check_dtype_default(ctx)
        if "registry-consistency" in config.rules \
                and config.matches(config.registry_globs, ctx.path):
            _collect_registry_info(ctx)
    if "registry-consistency" in config.rules:
        _check_registry_consistency(contexts)
    extra = []
    if _GRAPH_RULES & set(config.rules):
        # interprocedural passes share ONE call graph spanning every
        # linted file (building it dominates their cost)
        from .callgraph import build_graph, check_reachability

        graph = build_graph(contexts)
        if "host-sync-reachability" in config.rules:
            check_reachability(contexts, config, graph=graph)
        if ("thread-shared-state" in config.rules
                or "thread-lock-order" in config.rules):
            from .threads import check_threads

            check_threads(contexts, config, graph)
        if "donation-safety" in config.rules:
            from .donation import check_donation

            check_donation(contexts, config, graph)
    if "guard-first" in config.rules or "env-registry" in config.rules:
        from .conformance import check_conformance

        extra = check_conformance(contexts, config)
    findings = list(extra)
    for ctx in contexts:
        findings.extend(ctx.findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def lint_paths(paths, config=None, base=None):
    """Lint files/directories on disk; returns (findings, errors).

    Findings carry paths relative to `base` (default: cwd) so baseline
    fingerprints are stable no matter where mxlint is invoked from.
    """
    import copy

    base = base or os.getcwd()
    config = config or Config()
    sources, errors = {}, []
    abs_linted = set()
    for path in _iter_py_files(paths, errors):
        ap = os.path.abspath(path)
        abs_linted.add(ap)
        rel = os.path.relpath(ap, base)
        try:
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError as e:
            errors.append("%s: %s" % (path, e))
    # the unregistered-table-key cross-check is only sound when every
    # on-disk sibling of a linted registry file is linted too — a
    # partial run (one ops file) must not flag keys whose @register
    # sites it never saw
    if config.check_unregistered_table_keys:
        complete = True
        for ap in abs_linted:
            rel = os.path.relpath(ap, base)
            if not config.matches(config.registry_globs, rel):
                continue
            d = os.path.dirname(ap)
            for fn in os.listdir(d):
                if fn.endswith(".py") \
                        and os.path.join(d, fn) not in abs_linted:
                    complete = False
                    break
            if not complete:
                break
        if not complete:
            config = copy.copy(config)
            config.check_unregistered_table_keys = False
    # the stale-doc-row direction of env-registry claims a documented
    # var is read NOWHERE — only provable when the whole mxnet_tpu
    # package is in this run's scope
    if "env-registry" in config.rules and not config.check_env_doc_stale:
        pkg_roots = set()
        for ap in abs_linted:
            parts = ap.replace(os.sep, "/").split("/")
            if "mxnet_tpu" in parts[:-1]:
                idx = parts.index("mxnet_tpu")
                pkg_roots.add(os.sep.join(parts[:idx + 1]))
        for pkg in pkg_roots:
            whole = set()
            for root, dirs, files in os.walk(pkg):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                whole.update(os.path.join(root, fn) for fn in files
                             if fn.endswith(".py"))
            if whole and whole <= abs_linted:
                config = copy.copy(config)
                config.check_env_doc_stale = True
                if config.repo_root is None:
                    config.repo_root = os.path.dirname(pkg)
                break
    findings, perrors = lint_sources(sources, config)
    return findings, errors + perrors
