"""mxlint command line: ``python -m tools.mxlint [paths...]``.

Exit codes: 0 clean (or everything baselined), 1 new findings or stale
baseline entries, 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .checkers import ALL_RULES, Config, lint_paths
from .findings import apply_baseline, load_baseline, save_baseline

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _gh_msg(s):
    """Escape a github workflow-command message value."""
    return s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")


def _gh_prop(s):
    """Escape a github workflow-command property value."""
    return (_gh_msg(s).replace(":", "%3A").replace(",", "%2C"))
# fingerprint paths are always repo-relative, no matter the invoking cwd
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="Trace-safety and op-registry static analyzer for "
                    "the mxnet_tpu op compute paths.")
    p.add_argument("paths", nargs="*", default=["mxnet_tpu"],
                   help="files/directories to lint (default: mxnet_tpu)")
    p.add_argument("--rules", default=",".join(ALL_RULES),
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file grandfathering old findings "
                        "(default: tools/mxlint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to grandfather the "
                        "current findings (drops stale entries)")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text",
                   help="'github' emits ::error workflow-command "
                        "annotations (one per new finding / stale "
                        "baseline entry) for inline PR surfacing")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings the baseline suppressed")
    p.add_argument("--graph", action="store_true",
                   help="verify Symbol graphs (model zoo + production "
                        "pass outputs) with the graph verifier instead "
                        "of linting source; no baseline — any finding "
                        "fails")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.graph:
        from .graph import run_graph_mode

        return run_graph_mode(fmt=args.format)
    rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
    unknown = [r for r in rules if r not in ALL_RULES]
    if unknown:
        print("unknown rule(s): %s (known: %s)"
              % (", ".join(unknown), ", ".join(ALL_RULES)),
              file=sys.stderr)
        return 2
    findings, errors = lint_paths(args.paths, Config(rules=rules),
                                  base=REPO_ROOT)
    for e in errors:
        print("error: %s" % e, file=sys.stderr)
    if errors:
        return 2
    linted = [os.path.relpath(os.path.abspath(p), REPO_ROOT)
              for p in args.paths]

    if args.update_baseline:
        # a lock-order inversion is a latent deadlock, never a legacy
        # wart: refuse to grandfather it (fix the ordering or pragma
        # the acquisition site with a justification)
        inversions = [f for f in findings if f.rule == "thread-lock-order"]
        if inversions:
            for f in inversions:
                print("error: refusing to baseline a lock-order "
                      "inversion: %s" % f.format(), file=sys.stderr)
            return 2
        # a partial-scope run must not erase entries it could not
        # have re-observed: carry out-of-scope entries over verbatim
        kept = []
        if os.path.exists(args.baseline):
            from .findings import _in_scope

            kept = [e for e in load_baseline(args.baseline).values()
                    if not _in_scope(e, [os.path.relpath(
                        os.path.abspath(p), REPO_ROOT)
                        for p in args.paths], rules)]
        save_baseline(args.baseline, findings, keep_entries=kept)
        print("baseline updated: %d finding(s) grandfathered (%d "
              "out-of-scope entr(y/ies) kept) -> %s"
              % (len(findings), len(kept), args.baseline))
        return 0

    baseline = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print("error: unreadable baseline %s: %s"
                  % (args.baseline, e), file=sys.stderr)
            return 2
    result = apply_baseline(findings, baseline, linted_paths=linted,
                            rules=rules)

    if args.format == "github":
        for f in result.new:
            print("::error file=%s,line=%d,col=%d,title=%s::%s"
                  % (_gh_prop(f.path), f.line, f.col + 1,
                     _gh_prop("mxlint " + f.rule), _gh_msg(f.message)))
        if args.show_baselined:
            for f in result.suppressed:
                print("::notice file=%s,line=%d,col=%d,title=%s::%s"
                      % (_gh_prop(f.path), f.line, f.col + 1,
                         _gh_prop("mxlint baselined " + f.rule),
                         _gh_msg(f.message)))
        for e in result.stale:
            print("::error file=%s,title=%s::%s"
                  % (_gh_prop(e.get("path", "")),
                     _gh_prop("mxlint stale-baseline"),
                     _gh_msg("stale baseline entry (code fixed or "
                             "moved — run --update-baseline): %s %r"
                             % (e.get("rule"), e.get("code_line")))))
        print("mxlint: %d new finding(s), %d baselined, %d stale "
              "baseline entr(y/ies)"
              % (len(result.new), len(result.suppressed),
                 len(result.stale)))
        return 1 if (result.new or result.stale) else 0

    if args.format == "json":
        print(json.dumps({
            "new": [f.to_dict() for f in result.new],
            "baselined": [f.to_dict() for f in result.suppressed],
            "stale_baseline": result.stale,
        }, indent=1))
        return 1 if (result.new or result.stale) else 0

    for f in result.new:
        print(f.format())
    if args.show_baselined:
        for f in result.suppressed:
            print("[baselined] " + f.format())
    for e in result.stale:
        print("stale baseline entry (code fixed or moved — run "
              "--update-baseline): %s %s %r"
              % (e.get("rule"), e.get("path"), e.get("code_line")))
    print("mxlint: %d new finding(s), %d baselined, %d stale baseline "
          "entr(y/ies)" % (len(result.new), len(result.suppressed),
                           len(result.stale)))
    return 1 if (result.new or result.stale) else 0


if __name__ == "__main__":
    sys.exit(main())
