#!/usr/bin/env python
"""Kill stray training processes on every host of a cluster
(reference: tools/kill-mxnet.py — the cleanup tool for launch.py jobs).

Usage: python tools/kill-mxnet.py <hostfile> <user> <prog>
A hostfile of "localhost" lines (or a missing file) kills locally.
"""

import os
import shlex
import subprocess
import sys


def kill_command(user, prog):
    # quote user input (it rides a shell pipeline, locally and over
    # ssh) and exclude this script itself from the match
    return (
        "ps aux | grep -v grep | grep -v kill-mxnet | grep %s | "
        "awk -v u=%s '{if($1==u) print $2}' | xargs -r kill -9"
        % (shlex.quote(prog), shlex.quote(user)))


def main(argv):
    if len(argv) != 4:
        print("usage: %s <hostfile> <user> <prog>" % argv[0])
        return 1
    host_file, user, prog = argv[1:4]
    cmd = kill_command(user, prog)

    hosts = ["localhost"]
    if os.path.exists(host_file):
        with open(host_file) as f:
            hosts = [h.strip() for h in f if h.strip()] or hosts

    for host in hosts:
        if host in ("localhost", "127.0.0.1"):
            subprocess.call(cmd, shell=True)
        else:
            subprocess.call(["ssh", "-o", "StrictHostKeyChecking=no",
                             host, cmd])
        print("killed %r processes of %s on %s" % (prog, user, host))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
