#!/usr/bin/env python
"""Flash-attention benchmark on the live chip: Pallas kernel vs the
XLA-fused reference attention, fwd and fwd+bwd, across sequence
lengths.  Beyond-parity evidence for BENCH_NOTES (the reference has no
fused attention; its transformer path materializes the full (seq, seq)
score matrix via interleaved_matmul_selfatt_*).

Device-only timing: K iterations chained inside one jit (output fed
back) so per-call dispatch overhead is excluded, same methodology as
bench_device_latency.py.
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp

from mxnet_tpu.ops.attention import flash_attention, mha_reference


def bench(fn, args, iters):
    """Device-only: chain `iters` calls inside ONE jit, feeding the
    output back into q so iterations cannot be elided.  The completion
    barrier is a scalar HOST FETCH — through the axon relay,
    ``block_until_ready`` returns before the device finishes, so only
    materializing a value actually waits (the relay round trip is
    amortized over the chained iterations)."""
    q0 = args[0]

    @jax.jit
    def chained(q, *rest):
        def body(_, q):
            out = fn(q, *rest)
            if isinstance(out, tuple):
                out = out[0]
            return (out.astype(q.dtype) * 1e-6 + q).astype(q.dtype)
        return jax.lax.fori_loop(0, iters, body, q)

    def run():
        return float(jnp.sum(chained(q0, *args[1:]).astype(jnp.float32)))

    run()                                              # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best / iters


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--seqs", type=str, default="1024,2048,4096")
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--causal", action="store_true")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args(argv)

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rows = []
    for seq in (int(s) for s in args.seqs.split(",")):
        shape = (args.batch, args.heads, seq, args.head_dim)
        q, k, v = (jnp.asarray(rng.randn(*shape), dt) for _ in range(3))

        # fwd FLOPs: 2 matmuls of (seq x d) @ (d x seq) and (seq x seq) @ (seq x d)
        flops = 4.0 * args.batch * args.heads * seq * seq * args.head_dim
        if args.causal:
            flops /= 2

        def fwd_flash(q, k, v):
            return flash_attention(q, k, v, causal=args.causal)

        def fwd_ref(q, k, v):
            return mha_reference(q, k, v, causal=args.causal)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, causal=args.causal).sum()

        def loss_ref(q, k, v):
            return mha_reference(q, k, v, causal=args.causal).sum()

        t_flash = bench(fwd_flash, (q, k, v), args.iters)
        t_ref = bench(fwd_ref, (q, k, v), args.iters)
        g_flash = bench(jax.grad(loss_flash, argnums=(0, 1, 2)),
                        (q, k, v), args.iters)
        g_ref = bench(jax.grad(loss_ref, argnums=(0, 1, 2)),
                      (q, k, v), args.iters)
        rows.append((seq, t_flash, t_ref, g_flash, g_ref, flops))
        print("seq %5d | fwd: flash %7.3f ms (%.1f TFLOP/s)  xla %7.3f ms"
              " | fwd+bwd: flash %7.3f ms  xla %7.3f ms | speedup "
              "fwd %.2fx bwd %.2fx"
              % (seq, t_flash * 1e3, flops / t_flash / 1e12,
                 t_ref * 1e3, g_flash * 1e3, g_ref * 1e3,
                 t_ref / t_flash, g_ref / g_flash))
    return rows


if __name__ == "__main__":
    main()
