"""Per-shape benchmark: Pallas conv-dW kernel vs XLA's backward-filter
lowering (VERDICT r4 task #2 / BENCH_ROOFLINE.md headroom).

Method (BENCH_NOTES rules — all device claims must survive the relay):
each measurement chains `depth` dW computations inside ONE jit via
lax.fori_loop, rolls the input every iteration (defeats LICM), and
accumulates a reduced scalar that is host-fetched as the completion
barrier.  Per-iteration time comes from the difference of two depths,
cancelling the single dispatch+fetch overhead.

Shapes: the ResNet-50 NHWC bs=128 conv zoo (the model bench.py
measures).  Output: one markdown table; wins feed the
MXTPU_PALLAS_CONV_DW integration, losses get recorded in BENCH_NOTES
as measured negative results.

Usage: python tools/bench_conv_dw.py [--batch 128] [--depths 8,24]
       [--out table.md] [--shapes all|3x3|1x1]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (name, (H, W, I), kernel, stride, pad, O) at the bench batch size
RESNET50_SHAPES = [
    ("c2.3x3.64",    (56, 56, 64),   (3, 3), (1, 1), (1, 1), 64),
    ("c3.3x3.128",   (28, 28, 128),  (3, 3), (1, 1), (1, 1), 128),
    ("c4.3x3.256",   (14, 14, 256),  (3, 3), (1, 1), (1, 1), 256),
    ("c5.3x3.512",   (7, 7, 512),    (3, 3), (1, 1), (1, 1), 512),
    ("c2.1x1.64-256", (56, 56, 64),  (1, 1), (1, 1), (0, 0), 256),
    ("c2.1x1.256-64", (56, 56, 256), (1, 1), (1, 1), (0, 0), 64),
    ("c4.1x1.1024-256", (14, 14, 1024), (1, 1), (1, 1), (0, 0), 256),
    ("c3.3x3s2.128", (56, 56, 128),  (3, 3), (2, 2), (1, 1), 128),
    ("c4.1x1s2.512-1024", (28, 28, 512), (1, 1), (2, 2), (0, 0), 1024),
]


def _flops(batch, oh, ow, kernel, ci, co):
    return 2.0 * batch * oh * ow * kernel[0] * kernel[1] * ci * co


def bench_impl(fn, x, dy, depths, reps=3):
    """Median per-iteration seconds via chained depths (see module
    docstring).  fn(x, dy) -> dW."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chained(depth):
        @jax.jit
        def run(x, dy):
            def body(i, carry):
                acc, xv = carry
                xv = jnp.roll(xv, 1, axis=1)  # new bytes every iteration
                dw = fn(xv, dy)
                return acc + jnp.sum(dw).astype(jnp.float32), xv

            acc, _ = lax.fori_loop(0, depth, body,
                                   (jnp.float32(0.0), x))
            return acc

        return run

    d1, d2 = depths
    f1, f2 = chained(d1), chained(d2)
    float(np.asarray(f1(x, dy)))  # compile+warm
    float(np.asarray(f2(x, dy)))
    t1s, t2s = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(f1(x, dy)))  # fetch = completion barrier
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        float(np.asarray(f2(x, dy)))
        t2s.append(time.perf_counter() - t0)
    t1 = sorted(t1s)[len(t1s) // 2]
    t2 = sorted(t2s)[len(t2s) // 2]
    return (t2 - t1) / (d2 - d1)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--depths", default="8,24")
    ap.add_argument("--shapes", default="all")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--formulations", default="auto")
    ap.add_argument("--out", default=None,
                    help="also write the markdown table to this file")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas_conv import conv_dw_nhwc, conv_dw_xla

    depths = tuple(int(d) for d in args.depths.split(","))
    dtype = jnp.dtype(args.dtype)
    rs = np.random.RandomState(0)

    rows = []
    lines = ["| shape | impl | ms/iter | TFLOP/s | vs XLA |",
             "|---|---|---|---|---|"]

    def emit(line):
        print(line, flush=True)
        lines.append(line)
    for (name, (h, w, ci), kernel, stride, pad, co) in RESNET50_SHAPES:
        if args.shapes != "all" and args.shapes not in name:
            continue
        oh = (h + 2 * pad[0] - kernel[0]) // stride[0] + 1
        ow = (w + 2 * pad[1] - kernel[1]) // stride[1] + 1
        x = jnp.asarray(rs.rand(args.batch, h, w, ci), dtype)
        dy = jnp.asarray(rs.rand(args.batch, oh, ow, co), dtype)
        fl = _flops(args.batch, oh, ow, kernel, ci, co)

        t_xla = bench_impl(
            lambda xv, dyv: conv_dw_xla(xv, dyv, kernel, stride, pad),
            x, dy, depths)
        emit("| %s | xla | %.3f | %.2f | 1.00x |"
             % (name, t_xla * 1e3, fl / t_xla / 1e12))
        forms = (["pertap", "im2col"] if args.formulations == "both"
                 else [None])
        for form in forms:
            label = "pallas" if form is None else "pallas-" + form
            try:
                t_pal = bench_impl(
                    lambda xv, dyv: conv_dw_nhwc(xv, dyv, kernel, stride,
                                                 pad, formulation=form),
                    x, dy, depths)
                emit("| %s | %s | %.3f | %.2f | %.2fx |"
                     % (name, label, t_pal * 1e3, fl / t_pal / 1e12,
                        t_xla / t_pal))
                rows.append((name, label, t_xla, t_pal))
            except Exception as e:
                emit("| %s | %s | FAILED: %s | | |"
                     % (name, label, str(e)[:80]))
    if args.out:
        with open(args.out, "w") as f:
            f.write("\n".join(lines) + "\n")
    return rows


if __name__ == "__main__":
    main()
