#!/usr/bin/env python
"""Collective-bandwidth micro-benchmark (reference: tools/bandwidth/
measure.py — measured kvstore push/pull GB/s across devices).

TPU-native: times an all-reduce (psum) of a large buffer over the device
mesh — the operation gradients ride during data-parallel training — and
reports algorithmic bandwidth per chip.
"""

import argparse
import time

import numpy as np


def measure(size_mb=64, iters=10, dtype="float32"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # pre-0.8 location
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("dp",))
    elems = int(size_mb * (1 << 20) / np.dtype(dtype).itemsize)
    x = jnp.ones((n, elems), dtype=dtype)

    @jax.jit
    def allreduce(x):
        return shard_map(lambda s: jax.lax.psum(s, "dp"), mesh=mesh,
                         in_specs=P("dp", None), out_specs=P("dp", None))(x)

    def _wait(arr):
        # through the axon relay block_until_ready can resolve before
        # the device finishes; a scalar host fetch is the true barrier
        return float(jnp.sum(arr[:, :1].astype(jnp.float32)))

    _wait(allreduce(x))               # compile + warmup
    tic = time.time()
    for _ in range(iters):
        out = allreduce(x)
    _wait(out)
    dt = (time.time() - tic) / iters
    # ring all-reduce moves 2*(n-1)/n of the buffer per chip
    bytes_moved = 2 * (n - 1) / max(n, 1) * elems * np.dtype(dtype).itemsize
    return {"devices": n, "size_mb": size_mb, "time_s": dt,
            "gbps_per_chip": bytes_moved / dt / 1e9}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--size-mb", type=float, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--dtype", type=str, default="float32")
    args = parser.parse_args(argv)
    r = measure(args.size_mb, args.iters, args.dtype)
    print("devices=%d size=%.0fMB time=%.4fs bandwidth=%.2f GB/s/chip"
          % (r["devices"], r["size_mb"], r["time_s"], r["gbps_per_chip"]))
    return r


if __name__ == "__main__":
    main()
