#!/usr/bin/env python
"""Cluster/process launcher (reference: tools/launch.py over the
dmlc-core trackers — ssh/mpi/local).

TPU-native shape: for `dist_sync` there are no parameter-server
processes — workers form a jax.distributed process group (DCN
collectives), so `-n N` launches N worker processes with the same DMLC_*
env contract the reference sets (DMLC_ROLE/DMLC_WORKER_ID/
DMLC_NUM_WORKER/DMLC_PS_ROOT_*), which DistKVStore reads
(mxnet_tpu/kvstore/kvstore.py).  For `dist_async`, `-s N` additionally
spawns N host-side PS server processes (mxnet_tpu/kvstore_server.py);
their ports are handed to workers via MXTPU_PS_PORTS.  Only the local
launcher is implemented; ssh/mpi cluster modes are host-scheduling
concerns outside this container.
"""

import argparse
import os
import socket
import subprocess
import sys


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# observability env vars whose value is a FILE PATH: every spawned
# process gets its own rank-suffixed copy, so a distributed run is
# traceable end-to-end without manual env plumbing (per-rank trace /
# diag-dump / flight-dump files merge later via
# `tools/diagnose.py --cluster` / `--merge-traces`)
_PATH_ENVS = ("MXNET_TPU_PROFILE", "MXNET_TPU_DIAG",
              "MXNET_TPU_HEALTH_DUMP")


def rank_suffix_observability(env, role, rank):
    """Rewrite the path-valued observability vars in ``env`` to
    ``<base>.<role><rank><ext>`` (flag-valued vars like
    MXNET_TPU_HEALTH=1 are inherited untouched)."""
    for var in _PATH_ENVS:
        val = env.get(var)
        if val:
            base, ext = os.path.splitext(val)
            env[var] = "%s.%s%d%s" % (base, role, rank, ext)
    return env


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job locally",
        usage="launch.py -n 4 python train.py ...")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="parameter-server processes to spawn "
                             "(dist_async; dist_sync needs none)")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local"])
    parser.add_argument("--sync-dst-dir", type=str, default=None)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")

    port = free_port()
    common = {
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
    }

    server_procs = []
    if args.num_servers > 0:
        ports = [free_port() for _ in range(args.num_servers)]
        common["MXTPU_PS_PORTS"] = ",".join(str(p) for p in ports)
        for sid in range(args.num_servers):
            env = dict(os.environ)
            env.update(common)
            env.update({"DMLC_ROLE": "server",
                        "MXTPU_PS_SERVER_ID": str(sid),
                        # the PS is numpy/host-side; keep jax off any
                        # accelerator the workers may be using
                        "JAX_PLATFORMS": "cpu"})
            rank_suffix_observability(env, "server", sid)
            server_procs.append(subprocess.Popen(
                [sys.executable, "-m", "mxnet_tpu.kvstore_server"], env=env))

    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update(common)
        env.update({"DMLC_ROLE": "worker", "DMLC_WORKER_ID": str(rank)})
        rank_suffix_observability(env, "worker", rank)
        procs.append(subprocess.Popen(args.command, env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    for p in server_procs:
        # servers exit on the workers' stop command; reap stragglers so
        # no zombies outlive the launcher
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.terminate()
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        else:
            # a server that failed on its own (port bind, bad optimizer)
            # is the real fault even when workers also errored
            if p.returncode > 0:
                rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
