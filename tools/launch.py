#!/usr/bin/env python
"""Cluster/process launcher (reference: tools/launch.py over the
dmlc-core trackers — ssh/mpi/local).

TPU-native shape: there are no parameter-server processes; workers form a
jax.distributed process group (DCN collectives), so `-n N` launches N
worker processes with the same DMLC_* env contract the reference sets
(DMLC_ROLE/DMLC_WORKER_ID/DMLC_NUM_WORKER/DMLC_PS_ROOT_*), which
DistKVStore reads (mxnet_tpu/kvstore/kvstore.py).  Only the local
launcher is implemented; ssh/mpi cluster modes are host-scheduling
concerns outside this container.
"""

import argparse
import os
import socket
import subprocess
import sys


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job locally",
        usage="launch.py -n 4 python train.py ...")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference CLI parity; the TPU "
                             "backend has no server processes")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local"])
    parser.add_argument("--sync-dst-dir", type=str, default=None)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")

    port = free_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_WORKER_ID": str(rank),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        procs.append(subprocess.Popen(args.command, env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
