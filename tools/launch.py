#!/usr/bin/env python
"""Cluster/process launcher (reference: tools/launch.py over the
dmlc-core trackers — ssh/mpi/local).

TPU-native shape: for `dist_sync` there are no parameter-server
processes — workers form a jax.distributed process group (DCN
collectives), so `-n N` launches N worker processes with the same DMLC_*
env contract the reference sets (DMLC_ROLE/DMLC_WORKER_ID/
DMLC_NUM_WORKER/DMLC_PS_ROOT_*), which DistKVStore reads
(mxnet_tpu/kvstore/kvstore.py).  For `dist_async`, `-s N` additionally
spawns N host-side PS server processes (mxnet_tpu/kvstore_server.py);
their ports are handed to workers via MXTPU_PS_PORTS.  Only the local
launcher is implemented; ssh/mpi cluster modes are host-scheduling
concerns outside this container.

Supervisor mode (`MXNET_TPU_SUPERVISE=N`): while workers are still
running, a parameter-server process that exits NONZERO (crash, fault
drill, signal) is relaunched on the same port, up to N times per
server — exit 0 is the clean stop-command path and is left alone (a
worker's final `stop` racing the supervisor poll must not burn a
restart on a finished job).  The revived
server self-restores its store from its durable shard checkpoint
(`MXNET_TPU_PS_CKPT`, docs/CHECKPOINTING.md "Server-side durability") —
when supervision is requested without a checkpoint dir, one is
defaulted (with a per-mutation interval) so revival actually recovers
state.  `MXNET_TPU_FAULT` is stripped from a relaunched server's env:
the injected fault already simulated the crash it was scripted for, and
re-arming it would just crash-loop the drill to the restart bound.

The supervisor also honors WORKER relaunch requests: the observability
autopilot's kv-straggler reflex parks `restart_rank` commands on PS
shard 0 (mxnet_tpu/kvstore/ps.py reserved heads); the loop polls the
shard's `restart_poll` head (~1 s cadence, raw sockets — the launcher
never imports mxnet_tpu, so it stays jax-free) and relaunches the named
worker with its original env, bounded by the same per-process restart
budget.  The relaunched worker resumes through the normal
`checkpoint.auto_resume` path.
"""

import argparse
import json
import os
import pickle
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import time


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _poll_restart_requests(port, timeout=1.0):
    """Drain parked worker-relaunch requests from the PS shard on
    ``port`` (the ``restart_poll`` reserved head) and return them as a
    list of ``{"rank", "reason", "t"}`` dicts.  The wire format mirrors
    mxnet_tpu/kvstore/ps.py's length-prefixed pickle (reimplemented
    inline: the launcher must stay importable without jax); ANY failure
    — server busy, mid-restart, protocol surprise — returns [] and the
    next poll tries again."""
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=timeout) as s:
            payload = pickle.dumps(("command", "restart_poll", ""),
                                   protocol=pickle.HIGHEST_PROTOCOL)
            s.sendall(struct.pack(">Q", len(payload)) + payload)
            head = b""
            while len(head) < 8:
                chunk = s.recv(8 - len(head))
                if not chunk:
                    return []
                head += chunk
            (n,) = struct.unpack(">Q", head)
            buf = b""
            while len(buf) < n:
                chunk = s.recv(min(1 << 16, n - len(buf)))
                if not chunk:
                    return []
                buf += chunk
        reply = pickle.loads(buf)
        if not (isinstance(reply, tuple) and len(reply) == 2
                and reply[0] == "ok"):
            return []
        reqs = json.loads(reply[1] or "[]")
        return [r for r in reqs if isinstance(r, dict)
                and isinstance(r.get("rank"), int)]
    except (OSError, ValueError, pickle.PickleError, EOFError):
        return []


# observability env vars whose value is a FILE PATH: every spawned
# process gets its own rank-suffixed copy, so a distributed run is
# traceable end-to-end without manual env plumbing (per-rank trace /
# diag-dump / flight-dump / metrics-JSONL files merge later via
# `tools/diagnose.py --cluster` / `--merge-traces` / `--timeline`)
_PATH_ENVS = ("MXNET_TPU_PROFILE", "MXNET_TPU_DIAG",
              "MXNET_TPU_HEALTH_DUMP", "MXNET_TPU_METRICS")


def rank_suffix_observability(env, role, rank):
    """Rewrite the path-valued observability vars in ``env`` to
    ``<base>.<role><rank><ext>`` (flag-valued vars like
    MXNET_TPU_HEALTH=1 are inherited untouched)."""
    for var in _PATH_ENVS:
        val = env.get(var)
        if val:
            base, ext = os.path.splitext(val)
            env[var] = "%s.%s%d%s" % (base, role, rank, ext)
    return env


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job locally",
        usage="launch.py -n 4 python train.py ...")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="parameter-server processes to spawn "
                             "(dist_async; dist_sync needs none)")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local"])
    parser.add_argument("--sync-dst-dir", type=str, default=None)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")

    port = free_port()
    default_ckpt_dir = None
    common = {
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
    }
    try:
        supervise = int(os.environ.get("MXNET_TPU_SUPERVISE", "0") or 0)
    except ValueError:
        supervise = 0

    def server_env(sid, fault=True):
        env = dict(os.environ)
        env.update(common)
        env.update({"DMLC_ROLE": "server",
                    "MXTPU_PS_SERVER_ID": str(sid),
                    # the PS is numpy/host-side; keep jax off any
                    # accelerator the workers may be using
                    "JAX_PLATFORMS": "cpu"})
        if not fault:
            env.pop("MXNET_TPU_FAULT", None)
        rank_suffix_observability(env, "server", sid)
        return env

    def spawn_server(sid, fault=True):
        return subprocess.Popen(
            [sys.executable, "-m", "mxnet_tpu.kvstore_server"],
            env=server_env(sid, fault=fault))

    server_procs = []
    if args.num_servers > 0:
        ports = [free_port() for _ in range(args.num_servers)]
        common["MXTPU_PS_PORTS"] = ",".join(str(p) for p in ports)
        if supervise > 0 and not os.environ.get("MXNET_TPU_PS_CKPT"):
            # a revived server can only self-restore if its shard is
            # durable: default a checkpoint dir (per-mutation interval,
            # so no acknowledged mutation can be lost across a restart)
            default_ckpt_dir = tempfile.mkdtemp(prefix="mxtpu-ps-ckpt-")
            common["MXNET_TPU_PS_CKPT"] = default_ckpt_dir
            common.setdefault("MXNET_TPU_PS_CKPT_INTERVAL",
                              os.environ.get("MXNET_TPU_PS_CKPT_INTERVAL",
                                             "1"))
            print("launch.py: MXNET_TPU_SUPERVISE without "
                  "MXNET_TPU_PS_CKPT — defaulting server durability to "
                  "%s (interval %s)"
                  % (common["MXNET_TPU_PS_CKPT"],
                     common["MXNET_TPU_PS_CKPT_INTERVAL"]), flush=True)
        for sid in range(args.num_servers):
            server_procs.append(spawn_server(sid))

    def spawn_worker(rank):
        env = dict(os.environ)
        env.update(common)
        env.update({"DMLC_ROLE": "worker", "DMLC_WORKER_ID": str(rank)})
        rank_suffix_observability(env, "worker", rank)
        return subprocess.Popen(args.command, env=env)

    procs = [spawn_worker(rank) for rank in range(args.num_workers)]
    rc = 0
    if supervise > 0 and server_procs:
        # supervisor loop: while any worker is still running, relaunch
        # dead server processes (bounded restarts per server); the
        # revived server self-restores from its durable checkpoint.
        # Worker relaunches are REQUEST-driven: the autopilot's
        # straggler reflex parks restart_rank on shard 0, polled here.
        restarts = [0] * len(server_procs)
        w_restarts = [0] * len(procs)
        last_poll = 0.0
        while any(p.poll() is None for p in procs):
            for sid, sp in enumerate(server_procs):
                code = sp.poll()
                # code 0 = the clean stop-command exit: not a failure
                # (and possibly racing the workers' own shutdown)
                if code is None or code == 0 or \
                        restarts[sid] >= supervise:
                    continue
                restarts[sid] += 1
                print("launch.py supervisor: server %d exited rc=%s — "
                      "restart %d/%d" % (sid, code, restarts[sid],
                                         supervise), flush=True)
                server_procs[sid] = spawn_server(sid, fault=False)
            now = time.monotonic()
            if now - last_poll >= 1.0:
                last_poll = now
                for req in _poll_restart_requests(ports[0]):
                    rank = req["rank"]
                    if not 0 <= rank < len(procs):
                        print("launch.py supervisor: restart_rank %r "
                              "out of range — ignored" % (rank,),
                              flush=True)
                        continue
                    if w_restarts[rank] >= supervise:
                        print("launch.py supervisor: worker %d restart "
                              "budget (%d) exhausted — request ignored"
                              % (rank, supervise), flush=True)
                        continue
                    w_restarts[rank] += 1
                    print("launch.py supervisor: restart_rank worker "
                          "%d (%s) — restart %d/%d"
                          % (rank, req.get("reason") or "no reason",
                             w_restarts[rank], supervise), flush=True)
                    wp = procs[rank]
                    if wp.poll() is None:
                        wp.terminate()
                        try:
                            wp.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            wp.kill()
                            wp.wait()
                    procs[rank] = spawn_worker(rank)
            time.sleep(0.2)
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    for p in server_procs:
        # servers exit on the workers' stop command; reap stragglers so
        # no zombies outlive the launcher
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.terminate()
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        else:
            # a server that failed on its own (port bind, bad optimizer)
            # is the real fault even when workers also errored
            if p.returncode > 0:
                rc = rc or p.returncode
    if default_ckpt_dir is not None:
        if rc == 0:
            # we created it, the job finished cleanly: per-mutation
            # full-store snapshots must not pile up in /tmp
            shutil.rmtree(default_ckpt_dir, ignore_errors=True)
        else:
            # the shards' durable state IS the resume point — keep it
            print("launch.py: job failed (rc=%d); server checkpoints "
                  "kept at %s (MXNET_TPU_PS_CKPT)" % (rc,
                                                      default_ckpt_dir),
                  flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
