"""Collective scaling report: how the compiled sharded programs scale
with device count (VERDICT r4 task #5 / SCALING.md).

For each n in --devices, a child process with n virtual CPU devices
(`--xla_force_host_platform_device_count=n`) builds two training steps
on tiny shapes —

- **dp**: the flagship ResNet-50 v1 data-parallel GluonTrainStep
  (what bench.py measures at n=1), params replicated, GSPMD inserting
  the gradient all-reduce; and
- **dp2 x tp2 x pp(n/4)**: the 3-axis composition from
  `__graft_entry__._dryrun_dp_tp_pp` — GPipe collective-permute ring
  over 'pp', Megatron row-parallel psum over 'tp', dp grad all-reduce —

compiles them, and reads off the *post-SPMD-partitioning* HLO:
collective op counts and total per-device collective payload bytes
(sum of every collective instruction's output shape — shapes after
partitioning are per-shard, so this is the traffic one device sends
per step, the quantity that must fit the ICI budget), plus measured
per-device parameter/optimizer bytes from the live sharded arrays.

This is the closest a 1-host container gets to the 256-chip
scaling-efficiency north star (BASELINE.md): hardware can't be
simulated, but the *collective structure* — what rides the
interconnect and how it grows with n — is exactly what the compiled
HLO pins.  Reference analog: tools/bandwidth/ measures its kvstore
traffic empirically; here the compiler's program IS the spec.

Usage:
    python tools/scaling_report.py                  # writes SCALING_TABLE.md
        (SCALING.md is the committed narrative AROUND these tables —
         refresh its numbers from the regenerated SCALING_TABLE.md)
    python tools/scaling_report.py --devices 8,16   # subset
    python tools/scaling_report.py --child 8        # (internal)
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8,
                "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"= ((?:\([^)]*\))|(?:\w+\[[^\]]*\](?:\{[^}]*\})?)) "
    r"(" + "|".join(_COLLECTIVES) + r")(?:-start)?\(")


def _shape_bytes(shape_text):
    """Bytes of 'f32[128,64]{1,0}' or a '(tuple, of, shapes)'."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        total += count * _DTYPE_BYTES[dtype]
    return total


def collective_stats(hlo_text):
    """{op: {'count': N, 'bytes': per-device payload}} over the HLO."""
    stats = {op: {"count": 0, "bytes": 0} for op in _COLLECTIVES}
    for m in _INSTR_RE.finditer(hlo_text):
        shape_text, op = m.group(1), m.group(2)
        stats[op]["count"] += 1
        stats[op]["bytes"] += _shape_bytes(shape_text)
    return stats


def _sharded_bytes(vals):
    return sum(int(v.addressable_shards[0].data.nbytes) for v in vals)


def _child(n):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import random as mxrandom
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    out = {"n": n}

    # ---- zero: ZeRO weight-update sharding (MLP + Adam) -------------
    # params + Adam state born sharded 1/n (parallel/gluon_step.py
    # zero=True, docs/ZERO.md); the compiled HLO shows the grad
    # reduce-scatter + param all-gather replacing the dp all-reduce.
    # A BN-free MLP keeps n=256 lowering cheap — the shrink evidence
    # is model-independent.
    from mxnet_tpu import optimizer as _opt
    from mxnet_tpu.gluon import nn

    mesh_z = create_mesh({"dp": n})
    mlp = nn.HybridSequential()
    mlp.add(nn.Dense(512, activation="relu"),
            nn.Dense(512, activation="relu"), nn.Dense(100))
    mlp.initialize(ctx=mx.cpu())
    mlp(mx.nd.zeros((2, 256), ctx=mx.cpu()))
    zstep = GluonTrainStep(mlp, gluon.loss.SoftmaxCrossEntropyLoss(),
                           mesh=mesh_z, zero=True,
                           optimizer=_opt.create("adam",
                                                 learning_rate=1e-3))
    xz, yz = zstep.put_batch(np.zeros((n, 256), np.float32),
                             np.zeros((n,), np.int32))
    hloz = zstep._step.lower(
        zstep.train_vals, zstep.opt_state, zstep.aux_vals, xz, yz,
        mxrandom.next_key(),
        tuple(0.0 for _ in zstep._opt_update.slots)).compile().as_text()
    out["zero"] = {
        "param_bytes_per_dev": _sharded_bytes(zstep.train_vals),
        "opt_bytes_per_dev": _sharded_bytes(zstep.opt_state),
        "replicated_param_bytes":
            zstep.zero_layout["replicated_param_bytes"],
        "collectives": collective_stats(hloz),
    }
    if n > 64:
        # the ResNet-50 dp / 3-axis sections compile minutes-slow at
        # SPMD widths past 64; the zero table is what scales to 256
        json.dump(out, sys.stdout)
        return

    # ---- dp: flagship ResNet-50 step --------------------------------
    mesh = create_mesh({"dp": n})
    net = vision.resnet50_v1(classes=10)
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((1, 3, 32, 32), ctx=mx.cpu()))
    step = GluonTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, lr=0.1, momentum=0.9)
    x, y = step.put_batch(
        np.zeros((2 * n, 3, 32, 32), np.float32),
        np.zeros((2 * n,), np.int32))
    hlo = step._step.lower(step.train_vals, step.opt_state, step.aux_vals,
                           x, y, mxrandom.next_key()).compile().as_text()
    out["dp"] = {
        "param_bytes_per_dev": _sharded_bytes(step.train_vals),
        "opt_bytes_per_dev": _sharded_bytes(
            [s for s in step.opt_state if hasattr(s, "addressable_shards")]),
        "collectives": collective_stats(hlo),
    }

    # ---- dp2 x tp2 x pp(n/4): 3-axis composition --------------------
    if n >= 8 and n % 4 == 0:
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mxnet_tpu.parallel.pp import GPipe

        pp = n // 4
        mesh3 = create_mesh({"dp": 2, "tp": 2, "pp": pp})
        d, h = 64, 128
        rs = np.random.RandomState(0)
        params = {
            "w1": jnp.asarray(rs.randn(pp, d, h).astype(np.float32) * .3),
            "w2": jnp.asarray(rs.randn(pp, h, d).astype(np.float32) * .3),
        }
        gb = 4 * pp
        xx = jnp.asarray(rs.randn(gb, d).astype(np.float32))
        tt = jnp.asarray(rs.randn(gb, d).astype(np.float32))

        def stage_fn(p, cur):
            return lax.psum(jnp.tanh(cur @ p["w1"]) @ p["w2"], "tp")

        pipe = GPipe(stage_fn, mesh3, n_microbatches=pp,
                     batch_spec=P("dp", None),
                     param_specs={"w1": P("pp", None, "tp"),
                                  "w2": P("pp", "tp", None)})

        @jax.jit
        def train_step(ps):
            def loss_fn(q):
                return ((pipe(q, xx) - tt) ** 2).mean()

            loss, grads = jax.value_and_grad(loss_fn)(ps)
            return loss, jax.tree_util.tree_map(
                lambda w, g: w - 0.05 * g, ps, grads)

        hlo3 = train_step.lower(params).compile().as_text()
        out["dp_tp_pp"] = {"pp": pp, "collectives": collective_stats(hlo3)}

    json.dump(out, sys.stdout)


def _spawn(n):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # session site hook dials the TPU relay
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=%d" % n).strip()
    env["PYTHONPATH"] = REPO
    r = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--child", str(n)],
                       capture_output=True, text=True, timeout=3600,
                       env=env)
    if r.returncode != 0:
        raise RuntimeError("child n=%d failed:\n%s" % (n, r.stderr[-4000:]))
    return json.loads(r.stdout)


def _fmt_bytes(b):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if b < 1024 or unit == "GiB":
            return "%.1f %s" % (b, unit) if unit != "B" else "%d B" % b
        b /= 1024.0


def main(device_counts):
    results = [_spawn(n) for n in device_counts]
    lines = []
    w = lines.append
    w("# SCALING_TABLE.md — collective structure vs device count")
    w("")
    w("Generated by `python tools/scaling_report.py` (virtual CPU mesh, "
      "post-SPMD HLO; see the tool docstring for method).  'bytes' = "
      "per-device collective payload per training step — the traffic "
      "each chip puts on the interconnect.")
    w("")
    w("## Data-parallel ResNet-50 training step (bs=2/device)")
    w("")
    w("| n | param B/dev | opt B/dev | all-reduce (count / bytes) | "
      "other collectives |")
    w("|---|---|---|---|---|")
    for r in results:
        if "dp" not in r:
            continue
        dp = r["dp"]
        c = dp["collectives"]
        other = ", ".join("%s %d/%s" % (op, c[op]["count"],
                                        _fmt_bytes(c[op]["bytes"]))
                          for op in _COLLECTIVES
                          if op != "all-reduce" and c[op]["count"])
        w("| %d | %s | %s | %d / %s | %s |" % (
            r["n"], _fmt_bytes(dp["param_bytes_per_dev"]),
            _fmt_bytes(dp["opt_bytes_per_dev"]),
            c["all-reduce"]["count"], _fmt_bytes(c["all-reduce"]["bytes"]),
            other or "—"))
    w("")
    w("## ZeRO weight-update sharding (MLP 256-512×2-100 + Adam, "
      "`zero=True`)")
    w("")
    w("Params and Adam moments live sharded 1/n from step 0; the grad "
      "all-reduce becomes reduce-scatter + param all-gather "
      "(docs/ZERO.md).  'shrink' = replicated param bytes / measured "
      "per-device param bytes (padding makes it slightly under n).")
    w("")
    w("| n | param B/dev | opt B/dev | shrink | all-gather | "
      "reduce-scatter / all-reduce |")
    w("|---|---|---|---|---|---|")
    for r in results:
        if "zero" not in r:
            continue
        z = r["zero"]
        c = z["collectives"]
        shrink = z["replicated_param_bytes"] / max(
            1, z["param_bytes_per_dev"])
        rs_cell = ", ".join(
            "%s %d/%s" % (op, c[op]["count"], _fmt_bytes(c[op]["bytes"]))
            for op in ("reduce-scatter", "all-reduce")
            if c[op]["count"]) or "—"
        ag = c["all-gather"]
        w("| %d | %s | %s | %.2f× | %s | %s |" % (
            r["n"], _fmt_bytes(z["param_bytes_per_dev"]),
            _fmt_bytes(z["opt_bytes_per_dev"]), shrink,
            ("%d/%s" % (ag["count"], _fmt_bytes(ag["bytes"])))
            if ag["count"] else "—", rs_cell))
    w("")
    w("## dp2 × tp2 × pp(n/4) composition (GPipe ring + Megatron psum)")
    w("")
    w("| n | pp | all-reduce | collective-permute | all-gather | "
      "reduce-scatter |")
    w("|---|---|---|---|---|---|")
    for r in results:
        if "dp_tp_pp" not in r:
            continue
        c = r["dp_tp_pp"]["collectives"]

        def cell(op):
            return ("%d / %s" % (c[op]["count"], _fmt_bytes(c[op]["bytes"]))
                    if c[op]["count"] else "—")

        w("| %d | %d | %s | %s | %s | %s |" % (
            r["n"], r["dp_tp_pp"]["pp"], cell("all-reduce"),
            cell("collective-permute"), cell("all-gather"),
            cell("reduce-scatter")))
    w("")
    return results, "\n".join(lines)


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child(int(sys.argv[sys.argv.index("--child") + 1]))
    else:
        counts = [8, 16, 32, 64, 128, 256]
        if "--devices" in sys.argv:
            counts = [int(x) for x in
                      sys.argv[sys.argv.index("--devices") + 1].split(",")]
        results, md = main(counts)
        print(md)
        with open(os.path.join(REPO, "SCALING_TABLE.md"), "w") as f:
            f.write(md + "\n")
