"""Shared device-benchmark harness for the perf tools.

One implementation of "build a GluonTrainStep on the single-chip mesh
with a synthetic device-resident batch" so bench_train_matrix.py and
profile_step.py measure exactly the computation bench.py gates — a
methodology change lands in one place and every published number stays
comparable.
"""

import numpy as np

# inception_v3 ends in a fixed AvgPool2D(8): its canonical (and only
# valid) input is 299x299.  Everything else in the zoo trains at 224.
NETWORK_HW = {"inception_v3": 299}


def build_train_step(network, batch, hw=None, dtype="bfloat16",
                     layout="NHWC", classes=1000, lr=0.1, momentum=0.9,
                     wd=1e-4):
    """-> (step, x, y, layout, hw): a compiled-on-first-call
    GluonTrainStep over {'dp': 1} with a device-resident synthetic
    batch.  Falls back to NCHW for nets without a layout option."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    hw = hw or NETWORK_HW.get(network, 224)
    mesh = create_mesh({"dp": 1}, devices=jax.devices()[:1])
    ctor = getattr(vision, network)
    try:
        net = ctor(classes=classes, layout=layout)
    except TypeError as e:
        # only the "no layout option" signature error falls back to
        # NCHW (alexnet etc.); any other TypeError from a
        # layout-supporting constructor must surface, not be silently
        # rebuilt and mislabeled as NCHW
        if "layout" not in str(e):
            raise
        net = ctor(classes=classes)
        layout = "NCHW"
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    # probe at FULL size: flatten-tailed nets (alexnet, vgg) resolve
    # their Dense in_units from the probe's spatial dims, and
    # inception_v3's fixed AvgPool2D(8) rejects small inputs — only
    # global-pool nets tolerate a small probe, so don't special-case
    probe = (1, 3, hw, hw) if layout == "NCHW" else (1, hw, hw, 3)
    with ctx:
        net.initialize(ctx=ctx)
        net(mx.nd.zeros(probe, ctx=ctx))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=lr, momentum=momentum,
                          wd=wd, compute_dtype=dtype)
    rng = np.random.RandomState(0)
    shape = (batch, 3, hw, hw) if layout == "NCHW" else (batch, hw, hw, 3)
    x = rng.rand(*shape).astype(np.float32)
    y = rng.randint(0, classes, (batch,)).astype(np.int32)
    x, y = step.put_batch(x, y)
    return step, x, y, layout, hw
