#!/usr/bin/env python
"""Hardware-measured roofline audit of the flagship training step.

VERDICT r3 weak-spot 1: the r2/r3 perf narrative rested on
``compiled.cost_analysis()["bytes accessed"]``, which counts bytes that
never cross HBM (fusion-internal reads) — at the r3 headline the
implied bandwidth exceeded the chip's physical peak, so the "we're
bandwidth-bound, nothing left" conclusion was unproven.

This tool replaces that instrument with the real one: a device trace
(``jax.profiler``, which the axon relay supports) of N flagship
training steps.  Every device event carries its measured
``device_duration_ps``, the HLO instruction (operand shapes → an
*analytic lower bound* on HBM bytes: each operand read once + output
written once), the cost-model ``bytes_accessed`` for comparison, and
``model_flops``.  Per fused region we report:

- measured time (µs/step, averaged over the traced steps)
- analytic min HBM bytes and the implied GB/s (cannot exceed physics)
- the roofline bound: max(min_bytes/BW_PEAK, flops/MXU_PEAK) — the
  fastest this fusion could possibly run; headroom = time − bound
- the Python source line the fusion traces to (per-layer attribution)

Output: a JSON summary + markdown table (``--md``), sorted by
headroom, so "where does the remaining time go" has a measured answer.

Usage: python tools/profile_step.py [--batch 128] [--steps 4]
       [--top 40] [--md BENCH_ROOFLINE.md] [--trace-dir DIR]
"""

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

# v5e (TPU v5 lite) public peaks: 394 TFLOP/s bf16, 197 fp32-equivalent
# via bf16x3 passes; 819 GB/s HBM.
BW_PEAK = 819e9
MXU_PEAK_BF16 = 394e12

_DTYPE_BYTES = {
    "f32": 4, "f16": 2, "bf16": 2, "f64": 8, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "pred": 1,
}

# a typed shape literal with its layout braces, e.g.
#   bf16[128,56,56,256]{3,0,2,1:T(8,128)(2,1)S(1)}
# S(1) in the layout = memory space 1 (VMEM): XLA's memory-space
# assignment pre-staged that buffer with an async copy-start/copy-done
# pair, so reading it inside the fusion does NOT cross HBM — counting
# it is exactly the overcounting that made the r3 cost-model roofline
# exceed the chip's physical bandwidth.
_SHAPE_RE = re.compile(r"\b(f32|f16|bf16|f64|s32|u32|s64|u64|s8|u8|s16|"
                       r"u16|pred)\[([0-9,]*)\](\{[^}]*\})?")


def shapes_bytes(text, hbm_only=True):
    """Analytic bytes of the typed shape literals in an HLO string;
    ``hbm_only`` skips buffers laid out in memory space 1 (VMEM)."""
    return split_bytes(text)[0] if hbm_only else sum(split_bytes(text))


def split_bytes(text):
    """(space0_bytes, space1_bytes) over the shape literals in text."""
    s0 = s1 = 0
    for dt, dims, layout in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        if layout and "S(" in layout:
            s1 += n * _DTYPE_BYTES[dt]
        else:
            s0 += n * _DTYPE_BYTES[dt]
    return s0, s1


def moved_bytes(long_name):
    """HBM bytes moved by an async copy/slice: a copy moves its full
    buffer (src space0 == dst S(1) size); a sliced prefetch reads only
    the slice (the S(1) side), not the full space-0 source.  min() of
    the two sides is both at once."""
    s0, s1 = split_bytes(long_name)
    return min(s0, s1) if s1 else s0


def min_hbm_bytes(long_name):
    """Lower bound on this instruction's own HBM traffic: every
    HBM-resident (space 0) operand read once + every space-0 output
    written once.  VMEM-resident (S(1)) operands were paid for by an
    earlier overlapped prefetch copy — their HBM crossing is accounted
    on that copy, not here."""
    return shapes_bytes(long_name, hbm_only=True)


def step_cost_model(step, x, y):
    """Whole-step XLA cost/memory analysis (flops, cost-model bytes,
    output/temp footprint) of the compiled train-step executable — the
    same capture the dispatch layer performs per jit-cache entry
    (mxnet_tpu.ops.registry.compiled_cost), surfaced here so the
    summary carries the cost-model columns next to the measured ones.
    Backends without the analyses just yield no columns."""
    try:
        from mxnet_tpu import random as mxrandom
        from mxnet_tpu.ops.registry import compiled_cost

        compiled = step._step.lower(
            step.train_vals, step.opt_state, step.aux_vals, x, y,
            mxrandom.next_key()).compile()
        return compiled_cost(compiled) or {}
    except Exception:
        return {}


def capture(batch, steps, trace_dir, want_cost=True):
    import jax

    from bench_common import build_train_step

    step, x, y, _, _ = build_train_step("resnet50_v1", batch)
    for _ in range(3):
        l = step(x, y)
    float(np.asarray(l))
    # the AOT lower().compile() behind the cost columns re-compiles the
    # whole step once — skippable (--cost 0) when only the measured
    # trace matters
    cost = step_cost_model(step, x, y) if want_cost else {}

    jax.profiler.start_trace(trace_dir)
    for _ in range(steps):
        l = step(x, y)
    float(np.asarray(l))
    jax.profiler.stop_trace()
    return sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))[-1], cost


def parse(trace_path, steps):
    with gzip.open(trace_path) as f:
        t = json.load(f)
    events = t["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tids = {(e["pid"], e["tid"]): e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"}
    rows = collections.defaultdict(lambda: {
        "us": 0.0, "n": 0, "xla_bytes": 0, "flops": 0, "min_bytes": 0,
        "source": "", "long_name": ""})
    step_us = 0.0
    prefetch_bytes = 0
    prefetch_us = 0.0
    for e in events:
        if e.get("ph") != "X" or not pids.get(e["pid"], "").startswith(
                "/device"):
            continue
        name, args = e.get("name", ""), e.get("args") or {}
        line = tids.get((e["pid"], e["tid"]), "")
        if name.startswith("jit_step"):
            step_us += e["dur"]
            continue
        if "long_name" not in args:
            continue  # grouping spans (step markers), not HLO leaves
        if line == "Async XLA Ops" or name.startswith(
                ("copy-start", "copy-done", "slice-start", "slice-done",
                 "dynamic-slice-start", "dynamic-slice-done")):
            # memory-space-assignment prefetches: HBM<->VMEM transfers
            # that OVERLAP compute.  These bytes belong to the
            # whole-step HBM floor but not to any one fusion's bound.
            # *-start events are counted ( *-done pairs carry the same
            # long_name; counting both would double the traffic).
            if name.split(".")[0].endswith("-start"):
                prefetch_bytes += moved_bytes(args.get("long_name", ""))
            prefetch_us += e["dur"]
            continue
        r = rows[name]
        r["us"] += e["dur"]
        r["n"] += 1
        r["xla_bytes"] += int(args.get("raw_bytes_accessed",
                                       args.get("bytes_accessed", 0)))
        r["flops"] += int(args.get("model_flops", 0) or 0)
        if not r["long_name"]:
            r["long_name"] = args.get("long_name", "")
            r["source"] = args.get("source", "")
            r["min_bytes"] = min_hbm_bytes(r["long_name"])
    out = []
    for name, r in rows.items():
        us = r["us"] / steps
        calls = r["n"] / steps
        # min_bytes is per CALL (parsed once from the instruction);
        # scale by calls/step so rows invoked multiple times per step
        # (e.g. inside a loop) keep bytes, flops and time in the same
        # per-step units
        mb = r["min_bytes"] * calls
        fl = r["flops"] / steps
        # a row whose operand-sum implies more than the physical
        # bandwidth is a strided conv (1x1 stride-2 downsamples read a
        # quarter of the operand the instruction lists) — clamp its
        # byte estimate to what the measured time could move and FLAG
        # it, so no row and no aggregate can claim impossible traffic
        phys = BW_PEAK * us * 1e-6
        strided = us > 0 and mb > phys
        eff = min(mb, phys)
        bound_us = max(eff / BW_PEAK, fl / MXU_PEAK_BF16) * 1e6
        out.append({
            "name": name,
            "us_per_step": round(us, 1),
            "min_hbm_mb": round(eff / 1e6, 2),
            "strided_clamp": strided,
            "implied_gbps": round(eff / (us * 1e-6) / 1e9, 1) if us else 0,
            "xla_gbps": round((r["xla_bytes"] / steps) / (us * 1e-6) / 1e9,
                              1) if us else 0,
            "gflops": round(fl / 1e9, 2),
            "mxu_pct": round(fl / (us * 1e-6) / MXU_PEAK_BF16 * 100, 1)
            if us else 0,
            "bound_us": round(bound_us, 1),
            "headroom_us": round(us - bound_us, 1),
            "calls_per_step": calls,
            "source": r["source"],
        })
    out.sort(key=lambda r: -r["headroom_us"])
    prefetch = {"bytes_per_step": prefetch_bytes / steps,
                "us_per_step": prefetch_us / steps}
    return out, step_us / steps, prefetch


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--top", type=int, default=40)
    p.add_argument("--md", default=None)
    p.add_argument("--trace-dir",
                   default=os.path.join(tempfile.gettempdir(),
                                        "mxtpu_roofline_trace"))
    p.add_argument("--parse-only", default=None,
                   help="parse an existing trace.json.gz instead of "
                        "capturing")
    p.add_argument("--cost", type=int, default=1,
                   help="also capture the whole-step XLA cost model "
                        "(one extra compile); 0 skips it")
    args = p.parse_args(argv)

    if args.parse_only:
        trace, cost = args.parse_only, {}
    else:
        trace, cost = capture(args.batch, args.steps, args.trace_dir,
                              want_cost=bool(args.cost))
    rows, step_us, prefetch = parse(trace, args.steps)
    total_us = sum(r["us_per_step"] for r in rows)
    total_bound = sum(r["bound_us"] for r in rows)
    hbm_gb = (sum(r["min_hbm_mb"] for r in rows) / 1000
              + prefetch["bytes_per_step"] / 1e9)
    summary = {
        "batch": args.batch,
        "jit_step_ms": round(step_us / 1000, 2),
        "sum_hlo_ms": round(total_us / 1000, 2),
        "roofline_bound_ms": round(total_bound / 1000, 2),
        "headroom_pct": round((total_us - total_bound) / total_us * 100, 1),
        "img_s_device": round(args.batch / (step_us * 1e-6), 1),
        "hbm_gb_per_step": round(hbm_gb, 2),
        "prefetch_gb_per_step": round(prefetch["bytes_per_step"] / 1e9, 2),
        # the physics check the r3 instrument failed: must be <= 819
        "implied_gbps_whole_step": round(
            hbm_gb * 1e9 / (step_us * 1e-6) / 1e9, 1),
    }
    if cost.get("flops"):
        summary["cost_model_gflops"] = round(cost["flops"] / 1e9, 2)
    if cost.get("bytes_accessed"):
        # cost-model bytes overcount HBM (fusion-internal reads — the
        # r3 lesson); reported for comparison against the measured floor
        summary["cost_model_gb"] = round(cost["bytes_accessed"] / 1e9, 2)
    if cost.get("temp_bytes") is not None:
        # temp + output combined: the executable's working set beyond
        # its arguments — named to say so
        summary["cost_model_temp_out_gb"] = round(
            (cost.get("temp_bytes", 0) + cost.get("output_bytes", 0))
            / 1e9, 2)
    # the measured step anatomy, in the SAME shape/names/units as the
    # host-side attribution (mxnet_tpu/stepstats.py): device compute is
    # the one phase a whole-step-jitted trace can attribute, with the
    # remainder explicit — so this summary, report()'s "Step anatomy"
    # table, and diagnose.py --doctor findings read identically
    from mxnet_tpu import stepstats
    summary["step_anatomy"] = stepstats.device_anatomy_ms(
        summary["jit_step_ms"],
        {"device_compute": summary["sum_hlo_ms"],
         # overlapped HBM<->VMEM prefetch: reported as its own phase;
         # any sum past the wall surfaces as overlap_ms, never hidden
         "hbm_prefetch": prefetch["us_per_step"] / 1e3})
    print(json.dumps(summary))
    for r in rows[:args.top]:
        print("%8.1f us  bound %7.1f  %6.1f GB/s  mxu %5.1f%%  %-28s %s"
              % (r["us_per_step"], r["bound_us"], r["implied_gbps"],
                 r["mxu_pct"], r["name"][:28],
                 (r["source"] or "").split("/")[-1]))
    if args.md:
        with open(args.md, "w") as f:
            f.write("# Measured roofline: flagship step (bs=%d)\n\n"
                    % args.batch)
            f.write("`%s`\n\n" % json.dumps(summary))
            f.write("| region | us/step | bound us | min HBM MB | "
                    "implied GB/s | MXU % | headroom us | source |\n")
            f.write("|---|---|---|---|---|---|---|---|\n")
            for r in rows[:args.top]:
                f.write("| %s | %.1f | %.1f | %.2f | %.1f | %.1f | %.1f "
                        "| %s |\n"
                        % (r["name"], r["us_per_step"], r["bound_us"],
                           r["min_hbm_mb"], r["implied_gbps"],
                           r["mxu_pct"], r["headroom_us"],
                           (r["source"] or "").split("/")[-1]))
    return summary, rows


if __name__ == "__main__":
    main()
