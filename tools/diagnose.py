#!/usr/bin/env python
"""Diagnose script: OS / hardware / python / pip / framework / TPU / network.

Role parity with the reference's ``tools/diagnose.py`` (180 lines: prints
platform, pip, mxnet build features, CPU info, and timed URL reachability
so bug reports carry the environment).  This version reports the things
that matter for a TPU/XLA deployment instead of a CUDA one: the jax
backend and device inventory, XLA/JAX environment flags, and the
framework's own feature set from ``mxnet_tpu.runtime``.

Usage::

    python tools/diagnose.py                 # everything except network
    python tools/diagnose.py --network 1     # include URL timing checks
"""
import argparse
import os
import platform
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), '..'))

URLS = {
    'PYPI': 'https://pypi.python.org/pypi/pip',
    'JAX releases': 'https://storage.googleapis.com/jax-releases/jax_releases.html',
}


def parse_args():
    p = argparse.ArgumentParser(
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
        description='Diagnose the current system for bug reports.')
    for choice in ('python', 'pip', 'framework', 'os', 'hardware', 'environment',
                   'telemetry'):
        p.add_argument('--' + choice, default=1, type=int,
                       help='Diagnose {}.'.format(choice))
    p.add_argument('--diag', default=None,
                   help='Pretty-print this MXNET_TPU_DIAG dump in the telemetry '
                        'section (default: $MXNET_TPU_DIAG, else live counters).')
    p.add_argument('--health', action='store_true',
                   help='Render only the numerics-health / flight-recorder '
                        'section of the dump (works on full diag dumps and on '
                        'standalone flight-recorder dumps).')
    p.add_argument('--serving', action='store_true',
                   help='Render only the inference-serving section (per-'
                        'bucket occupancy, rejection counts, serve:* latency '
                        'percentiles) from a MXNET_TPU_DIAG dump (--diag / '
                        '$MXNET_TPU_DIAG) or from this live process.')
    p.add_argument('--requests', action='store_true',
                   help='Render only the request x-ray section (the tail-'
                        'sampled per-request lifecycle ring: every slow / '
                        'rejected / NaN-sentinel request with its seam-by-'
                        'seam timings) from a MXNET_TPU_DIAG dump (--diag / '
                        '$MXNET_TPU_DIAG) or from this live process.')
    p.add_argument('--slo', action='store_true',
                   help='Render only the SLO / error-budget section (per-'
                        'objective good/bad counts, budget remaining, multi-'
                        'window burn rates) plus any slo-fast-burn / slo-'
                        'budget-exhausted doctor findings, from a '
                        'MXNET_TPU_DIAG dump (--diag / $MXNET_TPU_DIAG) or '
                        'from this live process.')
    p.add_argument('--xray', action='store_true',
                   help='Render only the fused-step x-ray tables (per-scope '
                        'flops/bytes attribution inside the compiled whole-'
                        'step programs, with the unattributed remainder) '
                        'from a MXNET_TPU_DIAG dump (--diag / '
                        '$MXNET_TPU_DIAG) or from this live process.')
    p.add_argument('--autopilot', action='store_true',
                   help='Render only the observability-autopilot section '
                        '(gates, decision counters, and the action ledger '
                        'of fired/dry-run/suppressed reflexes) from a '
                        'MXNET_TPU_DIAG dump (--diag / $MXNET_TPU_DIAG) '
                        'or from this live process.')
    p.add_argument('--cluster', nargs='+', metavar='DUMP',
                   help='Merge several per-rank MXNET_TPU_DIAG dumps (files '
                        'or a directory of *.json) into one cluster report: '
                        'per-rank latency table, merged histograms, and the '
                        'straggler callout with p99/median skew.')
    p.add_argument('--merge-traces', nargs='+', metavar='TRACE',
                   help='Merge per-rank MXNET_TPU_PROFILE chrome traces into '
                        'one clock-aligned file (see --out).')
    p.add_argument('--out', default='merged_trace.json',
                   help='Output path for --merge-traces.')
    p.add_argument('--doctor', nargs='+', metavar='FILE',
                   help='Perf doctor: rank bottlenecks (idle gaps, recompile '
                        'storms, data wait, host syncs, roofline headroom, '
                        'shard stragglers, dead-shard / duplicate-'
                        'suppression incidents) and timeline trends (leaks, '
                        'throughput decay, step-time spikes, kv-RTT drift) '
                        'from a chrome trace, a MXNET_TPU_DIAG dump, and/or '
                        'a MXNET_TPU_METRICS timeline, with evidence and a '
                        'next action per finding.  Files are classified by '
                        'content; pass several kinds for full coverage.')
    p.add_argument('--timeline', metavar='FILE',
                   help='Render a MXNET_TPU_METRICS JSONL timeline (or a '
                        'diag dump embedding one) as a per-step table; '
                        'trend analysis runs via --doctor.')
    p.add_argument('--compare', nargs=2, metavar=('A', 'B'),
                   help='Dump-diff regression report: diff two diag dumps '
                        '(baseline A vs candidate B) — step-anatomy phases, '
                        'latency histograms, per-op dispatch rates, '
                        'compile/miss counters, memory peak — and print '
                        'regressions/improvements past --threshold plus a '
                        'machine-readable JSON verdict line.  Exit code 1 '
                        'on regression.')
    p.add_argument('--threshold', type=float, default=0.2,
                   help='Relative change that counts as a regression/'
                        'improvement for --compare (0.2 = 20%%).')
    p.add_argument('--format', choices=('text', 'github'), default='text',
                   help="'github' adds ::error/::notice workflow-command "
                        'annotations for --doctor/--compare findings '
                        '(the tools/mxlint convention).')
    p.add_argument('--json', action='store_true',
                   help='For --doctor/--compare: print the machine-readable '
                        'JSON (findings list / verdict) instead of only the '
                        'human report.')
    p.add_argument('--top', type=int, default=20,
                   help='Max findings for --doctor.')
    p.add_argument('--network', default=0, type=int,
                   help='Diagnose network (off by default: many TPU pods have no egress).')
    p.add_argument('--timeout', default=10, type=int,
                   help='Connection test timeout in seconds, 0 to disable.')
    return p.parse_args()


def _section(title):
    print('----------' + title + '----------')


def check_python():
    _section('Python Info')
    print('Version      :', platform.python_version())
    print('Compiler     :', platform.python_compiler())
    print('Build        :', platform.python_build())
    print('Arch         :', platform.architecture())


def check_pip():
    _section('Pip Info')
    try:
        import pip
        print('Version      :', pip.__version__)
        print('Directory    :', os.path.dirname(pip.__file__))
    except ImportError:
        print('No corresponding pip install for current python.')


def check_framework():
    _section('Framework Info')
    try:
        t0 = time.time()
        import mxnet_tpu as mx
        print('Version      :', getattr(mx, '__version__', 'unknown'))
        print('Directory    :', os.path.dirname(mx.__file__))
        print('Import time  : %.3f s' % (time.time() - t0,))
        try:
            from mxnet_tpu.runtime import Features
            feats = Features()
            enabled = sorted(n for n in feats.keys() if feats.is_enabled(n))
            print('Features     :', ', '.join(enabled))
        except Exception as e:  # pragma: no cover - informational only
            print('Features     : <unavailable: %s>' % (e,))
    except ImportError as e:
        print('No framework installed:', e)
        return
    try:
        import jax
        print('jax          :', jax.__version__)
        print('Backend      :', jax.default_backend())
        devs = jax.devices()
        print('Devices      : %d x %s' % (len(devs), devs[0].platform if devs else '?'))
        for d in devs[:8]:
            print('  -', d)
        if len(devs) > 8:
            print('  ... and %d more' % (len(devs) - 8,))
    except Exception as e:
        print('jax          : <unavailable: %s>' % (e,))


def check_telemetry(diag_path=None, health_only=False):
    """Telemetry view: pretty-print a MXNET_TPU_DIAG dump when given
    (or found in the environment), else this process's live counters —
    so a bug report carries the memory/cost picture, not just versions
    (docs/OBSERVABILITY.md 'Memory & cost analytics').  With
    ``health_only`` only the numerics-health / flight-recorder section
    renders (docs/OBSERVABILITY.md 'Numerics health'); standalone
    flight-recorder dumps are accepted too."""
    _section('Telemetry Info' if not health_only else 'Numerics Health')
    diag_path = diag_path or os.environ.get('MXNET_TPU_DIAG')
    try:
        from mxnet_tpu import runtime_stats
    except ImportError as e:
        print('No framework installed:', e)
        return
    # diagnose is a pure reader: an inherited MXNET_TPU_DIAG must not
    # make our exit overwrite the training run's dump (same disarm the
    # runtime_stats CLI performs)
    runtime_stats._DIAG_STATE['armed'] = False
    if diag_path and os.path.exists(diag_path):
        print('Diag dump    :', os.path.abspath(diag_path))
        if health_only:
            import json
            with open(diag_path) as f:
                data = json.load(f)
            if data.get('reason'):
                print('Dump reason  :', data['reason'])
            health = data.get('health') \
                or data.get('snapshot', {}).get('health') or {}
            print('\n'.join(runtime_stats._render_health(health)))
            return
        runtime_stats.main([diag_path])
        return
    if diag_path:
        print('Diag dump    : %s (not written yet — send SIGUSR1 to the '
              'training pid or wait for exit)' % diag_path)
    if health_only:
        from mxnet_tpu import health
        print('\n'.join(runtime_stats._render_health(health.snapshot())))
        return
    print(runtime_stats.report())


def check_serving(diag_path=None):
    """Serving view: the continuous-batching section (per-bucket
    occupancy, rejection counts, serve:* latency percentiles) of a
    MXNET_TPU_DIAG dump, or of this live process when no dump is given
    (docs/SERVING.md).  Returns 0, or 2 when the dump names no serving
    run — a load test asserting on this view must not silently pass on
    an empty section."""
    _section('Inference Serving')
    import json
    from mxnet_tpu import runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    diag_path = diag_path or os.environ.get('MXNET_TPU_DIAG')
    if diag_path and os.path.exists(diag_path):
        print('Diag dump    :', os.path.abspath(diag_path))
        with open(diag_path) as f:
            data = json.load(f)
        snap = data.get('snapshot', data)
    else:
        if diag_path:
            print('Diag dump    : %s (not written yet)' % diag_path)
        snap = runtime_stats.snapshot()
    serving = snap.get('serving') or {}
    if not serving.get('enabled'):
        print('(no serving run in this %s — construct an '
              'InferenceServer, or point --diag at a load run\'s dump)'
              % ('dump' if diag_path else 'process'))
        return 2
    print('\n'.join(runtime_stats._render_serving(
        serving, snap.get('histograms') or {})))
    return 0


def check_requests(diag_path=None):
    """Request x-ray view: the tail-sampled per-request lifecycle ring
    (every slow / rejected / NaN-sentinel request with its seam-by-seam
    timings) of a MXNET_TPU_DIAG dump, or of this live process when no
    dump is given (docs/OBSERVABILITY.md "Request x-ray & SLOs").
    Returns 0, or 2 when no request was ever traced — a soak drill
    asserting on this view must not silently pass on an empty
    section."""
    _section('Request X-ray')
    import json
    from mxnet_tpu import runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    diag_path = diag_path or os.environ.get('MXNET_TPU_DIAG')
    if diag_path and os.path.exists(diag_path):
        print('Diag dump    :', os.path.abspath(diag_path))
        with open(diag_path) as f:
            data = json.load(f)
        snap = data.get('snapshot', data)
    else:
        if diag_path:
            print('Diag dump    : %s (not written yet)' % diag_path)
        snap = runtime_stats.snapshot()
    req = snap.get('requests') or {}
    if not (req.get('enabled') or req.get('seen')):
        print('(no request x-ray in this %s — enable per-request '
              'tracing with MXNET_TPU_REQTRACE=1 and run traffic '
              'through an InferenceServer; docs/OBSERVABILITY.md '
              '"Request x-ray & SLOs")'
              % ('dump' if diag_path else 'process'))
        return 2
    print('\n'.join(runtime_stats._render_requests(req)).lstrip('\n'))
    return 0


def check_slo(diag_path=None):
    """SLO view: per-objective good/bad counts, error-budget remaining,
    and multi-window burn rates of a MXNET_TPU_DIAG dump, or of this
    live process when no dump is given, plus any slo-fast-burn /
    slo-budget-exhausted doctor findings rendered with their window
    evidence (docs/OBSERVABILITY.md "Request x-ray & SLOs").  Returns
    0, or 2 when no objective was ever declared — an SLO drill
    asserting on this view must not silently pass on an empty
    section."""
    _section('SLO / Error Budgets')
    import json
    from mxnet_tpu import perfdoctor, runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    diag_path = diag_path or os.environ.get('MXNET_TPU_DIAG')
    if diag_path and os.path.exists(diag_path):
        print('Diag dump    :', os.path.abspath(diag_path))
        with open(diag_path) as f:
            data = json.load(f)
        snap = data.get('snapshot', data)
    else:
        if diag_path:
            print('Diag dump    : %s (not written yet)' % diag_path)
        snap = runtime_stats.snapshot()
    slo_sec = snap.get('slo') or {}
    if not (slo_sec.get('enabled') or slo_sec.get('objectives')):
        print('(no SLO objectives in this %s — declare them with '
              'MXNET_TPU_SLO=name:25ms:99.9 and run traffic through '
              'an InferenceServer; docs/OBSERVABILITY.md "Request '
              'x-ray & SLOs")'
              % ('dump' if diag_path else 'process'))
        return 2
    print('\n'.join(runtime_stats._render_slo(slo_sec)).lstrip('\n'))
    findings = perfdoctor._check_slo({'snapshot': snap})
    if findings:
        print()
        print(perfdoctor.render(findings))
    return 0


def check_autopilot(diag_path=None):
    """Autopilot view: the reflex engine's gates, decision counters,
    and action ledger from a MXNET_TPU_DIAG dump (the ledger rides the
    dump TOP-LEVEL, beside the timeline), or from this live process
    when no dump is given (docs/OBSERVABILITY.md "Autopilot").  Returns
    0, or 2 when no ledger was recorded — an autopilot drill asserting
    on this view must not silently pass on an empty section."""
    _section('Observability Autopilot')
    import json
    from mxnet_tpu import runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    diag_path = diag_path or os.environ.get('MXNET_TPU_DIAG')
    if diag_path and os.path.exists(diag_path):
        print('Diag dump    :', os.path.abspath(diag_path))
        with open(diag_path) as f:
            data = json.load(f)
        ap = data.get('autopilot') or {}
    else:
        if diag_path:
            print('Diag dump    : %s (not written yet)' % diag_path)
        from mxnet_tpu import autopilot
        ap = autopilot.ledger_section()
    if not ap.get('entries'):
        print('(no autopilot ledger in this %s — enable the engine '
              'with MXNET_TPU_AUTOPILOT=1 (reflexes dry-run by '
              'default) and let a reflex trip; docs/OBSERVABILITY.md '
              '"Autopilot")'
              % ('dump' if diag_path else 'process'))
        return 2
    print('\n'.join(runtime_stats._render_autopilot(ap)).lstrip('\n'))
    return 0


def check_xray(diag_path=None):
    """Fused-step x-ray view: the per-scope cost-attribution tables
    (xray.py) of a MXNET_TPU_DIAG dump, or of this live process when no
    dump is given (docs/OBSERVABILITY.md "Fused-step X-ray").  Returns
    0, or 2 when no x-ray was captured — a gate asserting on this view
    must not silently pass on an empty section."""
    _section('Fused-step X-ray')
    import json
    from mxnet_tpu import runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    diag_path = diag_path or os.environ.get('MXNET_TPU_DIAG')
    if diag_path and os.path.exists(diag_path):
        print('Diag dump    :', os.path.abspath(diag_path))
        with open(diag_path) as f:
            data = json.load(f)
        snap = data.get('snapshot', data)
    else:
        if diag_path:
            print('Diag dump    : %s (not written yet)' % diag_path)
        snap = runtime_stats.snapshot()
    xr = snap.get('xray') or {}
    if not xr.get('programs'):
        print('(no x-ray captured in this %s — compile a whole-step '
              'program with cost capture active: MXNET_TPU_DIAG, '
              'MXNET_TPU_COST_ANALYSIS=1, or the profiler running; '
              'MXNET_TPU_XRAY=0 disables the annotation)'
              % ('dump' if diag_path else 'process'))
        return 2
    print('\n'.join(runtime_stats._render_xray(xr)).lstrip('\n'))
    return 0


def check_os():
    _section('Platform Info')
    print('Platform     :', platform.platform())
    print('system       :', platform.system())
    print('node         :', platform.node())
    print('release      :', platform.release())
    print('version      :', platform.version())


def check_hardware():
    _section('Hardware Info')
    print('machine      :', platform.machine())
    print('processor    :', platform.processor())
    try:
        if sys.platform.startswith('linux'):
            subprocess.call(['lscpu'])
        elif sys.platform.startswith('darwin'):
            subprocess.call(['sysctl', '-n', 'machdep.cpu.brand_string'])
    except OSError as e:
        print('CPU info     : <unavailable: %s>' % (e,))


def check_environment():
    _section('Environment')
    for k, v in sorted(os.environ.items()):
        if k.startswith(('MXNET_', 'MXTPU_', 'XLA_', 'JAX_', 'TPU_', 'OMP_',
                         'KMP_', 'LD_LIBRARY_PATH', 'DMLC_')):
            print('%-32s: %s' % (k, v))


def test_connection(name, url, timeout=10):
    from urllib.request import urlopen
    from urllib.parse import urlparse
    urlinfo = urlparse(url)
    start = time.time()
    try:
        socket.gethostbyname(urlinfo.hostname)
    except Exception as e:
        print('Error resolving DNS for {}: {}, {}'.format(name, url, e))
        return
    dns_elapsed = time.time() - start
    start = time.time()
    try:
        urlopen(url, timeout=timeout if timeout > 0 else None)
    except Exception as e:
        print('Error open {}: {}, {}, DNS finished in {} sec.'.format(
            name, url, e, dns_elapsed))
        return
    load_elapsed = time.time() - start
    print('Timing for {}: {}, DNS: {:.4f} sec, LOAD: {:.4f} sec.'.format(
        name, url, dns_elapsed, load_elapsed))


def check_network(timeout):
    _section('Network Test')
    if timeout > 0:
        print('Setting timeout: {}'.format(timeout))
        socket.setdefaulttimeout(timeout)
    for name, url in sorted(URLS.items()):
        test_connection(name, url, timeout)


def check_cluster(paths):
    """Merged multi-rank view: fold per-rank diag dumps into one report
    naming the slowest rank and quantifying the p99/median latency skew
    (docs/OBSERVABILITY.md 'Distributed telemetry')."""
    _section('Cluster Telemetry')
    from mxnet_tpu import runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    try:
        dumps = runtime_stats.load_dumps(paths)
    except ValueError as e:
        print('error: %s' % e, file=sys.stderr)
        return
    if not dumps:
        print('no diag dumps found in: %s' % ' '.join(paths))
        return
    print(runtime_stats.render_cluster(runtime_stats.cluster_report(dumps)))


def merge_traces(paths, out):
    from mxnet_tpu import profiler
    merged = profiler.merge_traces(paths, out=out)
    print('Merged trace :', merged)


def run_doctor(paths, top=20, fmt='text', as_json=False):
    """Perf doctor over a chrome trace and/or diag dump: ranked
    findings with evidence + next actions (docs/OBSERVABILITY.md
    'Step anatomy & perf doctor').  Returns 0 (findings are advice,
    not failures)."""
    import json as _json

    from mxnet_tpu import perfdoctor, runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    trace = dump = timeline = None
    for p in paths:
        try:
            kind, data = perfdoctor.classify(p)
        except (ValueError, OSError) as e:
            print('error: %s' % e, file=sys.stderr)
            return 2
        if kind == 'trace':
            if trace is not None:
                print('error: --doctor takes at most one chrome trace '
                      '(got a second: %s)' % p, file=sys.stderr)
                return 2
            trace = data
        elif kind == 'timeline':
            if timeline is not None:
                print('error: --doctor takes at most one metrics '
                      'timeline (got a second: %s)' % p,
                      file=sys.stderr)
                return 2
            timeline = data
        else:
            if dump is not None:
                print('error: --doctor takes at most one diag dump '
                      '(got a second: %s); for a multi-rank view use '
                      '--cluster' % p, file=sys.stderr)
                return 2
            dump = data
    findings = perfdoctor.diagnose(trace=trace, dump=dump,
                                   timeline=timeline, top=top)
    if as_json:
        print(_json.dumps(findings, indent=1))
    else:
        print(perfdoctor.render(findings, inputs=paths))
    if fmt == 'github' and findings:
        print(perfdoctor.render_github(findings))
    return 0


def run_compare(a_path, b_path, threshold=0.2, fmt='text',
                as_json=False):
    """Dump-diff regression report between two diag dumps; always ends
    with one machine-readable JSON verdict line.  Exit code 1 on
    regression (so a perf PR's CI can gate on it)."""
    import json as _json

    from mxnet_tpu import perfdoctor, runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    for p in (a_path, b_path):
        if os.path.isdir(p):
            print('error: --compare diffs exactly two dump FILES '
                  '(%s is a directory)' % p, file=sys.stderr)
            return 2
    try:
        dumps = runtime_stats.load_dumps([a_path, b_path])
    except ValueError as e:
        print('error: %s' % e, file=sys.stderr)
        return 2
    for p, d in zip((a_path, b_path), dumps):
        if 'timeline' in d and 'snapshot' not in d and 'ops' not in d:
            # a metrics JSONL / sample-array operand has no comparable
            # counter sections: comparing would report a vacuous
            # 'flat' (rc 0) no matter how badly perf moved
            print('error: --compare diffs diag DUMPS; %s is a metrics '
                  'timeline (trend analysis: --doctor)' % p,
                  file=sys.stderr)
            return 2
    result = runtime_stats.compare(dumps[0], dumps[1],
                                   threshold=threshold)
    if as_json:
        print(_json.dumps(result, indent=1))
    else:
        print(runtime_stats.render_compare(result))
        # the one-line machine-readable verdict (grep-able from CI logs
        # even in text mode)
        print(_json.dumps({'verdict': result['verdict'],
                           'regressions': len(result['regressions']),
                           'improvements': len(result['improvements']),
                           'threshold': result['threshold']}))
    if fmt == 'github':
        for e in result['regressions']:
            print(perfdoctor.gh_annotation(
                'error', 'perf regression: %s %.3f -> %.3f %s (%+.0f%%)'
                % (e['metric'], e['before'], e['after'], e['unit'],
                   (e['ratio'] - 1.0) * 100)))
        for e in result['improvements']:
            print(perfdoctor.gh_annotation(
                'notice', 'perf improvement: %s %.3f -> %.3f %s (%+.0f%%)'
                % (e['metric'], e['before'], e['after'], e['unit'],
                   (e['ratio'] - 1.0) * 100)))
    return 1 if result['regressions'] else 0


def run_timeline(path):
    """Per-step table of a metrics timeline (JSONL file, JSON sample
    array, or a diag dump embedding a ``timeline`` section)."""
    _section('Metrics Timeline')
    from mxnet_tpu import metrics_timeline, runtime_stats
    runtime_stats._DIAG_STATE['armed'] = False
    try:
        samples = metrics_timeline.load(path)
    except (ValueError, OSError) as e:
        print('error: %s' % e, file=sys.stderr)
        return 2
    if not samples:
        print('no timeline samples in: %s' % path, file=sys.stderr)
        return 2
    print(metrics_timeline.render(samples))
    return 0


def main():
    args = parse_args()
    if args.timeline or args.doctor or args.compare:
        # focused analysis views: skip the platform sections; the
        # flags chain and the WORST exit code wins (2 usage > 1
        # regression > 0), so --timeline never swallows a gate and a
        # usage error is never misreported as a perf regression
        rc = 0
        if args.timeline:
            rc = max(rc, run_timeline(args.timeline))
        if args.doctor:
            rc = max(rc, run_doctor(args.doctor, top=args.top,
                                    fmt=args.format, as_json=args.json))
        if args.compare:
            rc = max(rc, run_compare(args.compare[0], args.compare[1],
                                     threshold=args.threshold,
                                     fmt=args.format, as_json=args.json))
        sys.exit(rc)
    if args.cluster or args.merge_traces:
        # focused distributed-telemetry views: skip the platform sections
        if args.cluster:
            check_cluster(args.cluster)
        if args.merge_traces:
            merge_traces(args.merge_traces, args.out)
        return
    if args.serving:
        # focused serving view: skip the platform sections
        sys.exit(check_serving(args.diag))
    if args.requests:
        # focused request-lifecycle view: skip the platform sections
        sys.exit(check_requests(args.diag))
    if args.slo:
        # focused error-budget view: skip the platform sections
        sys.exit(check_slo(args.diag))
    if args.xray:
        # focused fused-step attribution view: skip the platform sections
        sys.exit(check_xray(args.diag))
    if args.autopilot:
        # focused reflex-ledger view: skip the platform sections
        sys.exit(check_autopilot(args.diag))
    if args.health:
        # focused view for numerics triage: skip the platform sections
        check_telemetry(args.diag, health_only=True)
        return
    if args.hardware:
        check_hardware()
    if args.os:
        check_os()
    if args.environment:
        check_environment()
    if args.python:
        check_python()
    if args.pip:
        check_pip()
    if args.framework:
        check_framework()
    if args.telemetry:
        check_telemetry(args.diag)
    if args.network:
        check_network(args.timeout)


if __name__ == '__main__':
    main()
