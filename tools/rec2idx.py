#!/usr/bin/env python
"""Rebuild the .idx for an existing RecordIO .rec file (reference:
tools/rec2idx.py — sequential scan recording each record's byte
offset, so indexed/partitioned readers work on .rec files that shipped
without their index)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.recordio import MXRecordIO


def rec2idx(rec_path, idx_path):
    reader = MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as idx:
        while True:
            pos = reader.tell()
            buf = reader.read()
            if buf is None:
                break
            idx.write("%d\t%d\n" % (n, pos))
            n += 1
    reader.close()
    return n


def main(argv=None):
    p = argparse.ArgumentParser(
        description="generate an index file for a RecordIO file")
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", nargs="?", default=None,
                   help="output .idx path (default: alongside the .rec)")
    args = p.parse_args(argv)
    idx = args.index or os.path.splitext(args.record)[0] + ".idx"
    n = rec2idx(args.record, idx)
    print("wrote %s (%d records)" % (idx, n))
    return n


if __name__ == "__main__":
    main()
