#!/usr/bin/env python
"""Run one test repeatedly under fresh seeds to expose flakiness
(reference: tools/flakiness_checker.py, adapted from nose to pytest).

Usage:
    python tools/flakiness_checker.py tests/test_operator.py::test_softmax
    python tools/flakiness_checker.py test_operator.test_softmax -n 100
"""

import argparse
import os
import random
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def normalize_target(spec):
    """Accept pytest (file::test) or nose (module[.Class].test) specs."""
    if "::" in spec or spec.endswith(".py"):
        return spec
    parts = spec.split(".")
    # the module is the longest leading prefix whose file exists; the
    # rest (Class and/or test) becomes pytest :: selectors
    for i in range(len(parts) - 1, 0, -1):
        path = os.path.join("tests", os.sep.join(parts[:i]) + ".py")
        if os.path.exists(os.path.join(REPO, path)):
            return path + "".join("::" + q for q in parts[i:])
    return spec


def run_trials(target, trials, seed=None, verbose=False):
    rng = random.Random(seed)
    failures = 0
    for trial in range(trials):
        trial_seed = rng.randrange(2 ** 31)
        env = dict(os.environ)
        env["MXNET_TEST_SEED"] = str(trial_seed)
        env.setdefault("JAX_PLATFORMS", "cpu")
        res = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-x", target],
            cwd=REPO, env=env, capture_output=True, text=True)
        if res.returncode != 0:
            failures += 1
            print("trial %d FAILED (seed %d)" % (trial, trial_seed))
            if verbose:
                print(res.stdout[-2000:])
        elif verbose:
            print("trial %d passed (seed %d)" % (trial, trial_seed))
    return failures


def main(argv=None):
    p = argparse.ArgumentParser(description="check a test for flakiness")
    p.add_argument("test", help="pytest file::test or module.test spec")
    p.add_argument("-n", "--num-trials", type=int, default=20)
    p.add_argument("-s", "--seed", type=int, default=None)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    target = normalize_target(args.test)
    failures = run_trials(target, args.num_trials, args.seed, args.verbose)
    print("%d/%d trials failed for %s"
          % (failures, args.num_trials, target))
    return failures


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
