"""Line coverage with zero external dependencies.

This container has neither coverage.py nor pytest-cov, so the thin-spot
detector the test strategy needs (VERDICT r4 task #8) is built on
``sys.monitoring`` (PEP 669, CPython 3.12): a LINE callback that
records the first hit of every (file, line) and then returns
``sys.monitoring.DISABLE`` for that location, so steady-state overhead
is zero — unlike sys.settrace, which pays per executed line forever.

Usage:
    MXTPU_COV=/path/out.json python -m pytest tests/ ...
        (tests/conftest.py starts the collector when the env var is set;
         the JSON maps abs filename -> sorted hit line numbers)
    python tools/coverage_lite.py report out.json [out2.json ...]
        (merges runs, compares against the statically-computed
         executable lines of every mxnet_tpu source file, prints a
         per-file table and writes COVERAGE_TABLE.md; COVERAGE.md is
         the committed narrative around it)

Executable lines are derived by compiling each source file and walking
``code.co_lines()`` over all nested code objects — the same universe
the interpreter reports LINE events for, so hit/total are consistent
by construction.
"""

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def start(package_dir, out_path):
    """Begin collecting line hits for files under package_dir; the JSON
    is written at interpreter exit (atexit)."""
    import atexit

    mon = sys.monitoring
    tool = mon.COVERAGE_ID
    mon.use_tool_id(tool, "mxtpu-coverage-lite")
    hits = {}
    pkg = os.path.abspath(package_dir) + os.sep

    def on_line(code, lineno):
        fn = code.co_filename
        if fn.startswith(pkg):
            hits.setdefault(fn, set()).add(lineno)
        # first hit recorded; never pay for this location again
        return mon.DISABLE

    mon.register_callback(tool, mon.events.LINE, on_line)
    mon.set_events(tool, mon.events.LINE)

    def dump():
        try:
            mon.set_events(tool, 0)
        except Exception:
            pass
        with open(out_path, "w") as f:
            json.dump({fn: sorted(ls) for fn, ls in hits.items()}, f)

    atexit.register(dump)


def executable_lines(path):
    """Line numbers the interpreter can emit LINE events for, over the
    module and every nested code object."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        top = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines = set()
    stack = [top]
    while stack:
        co = stack.pop()
        for _start, _end, lineno in co.co_lines():
            if lineno is not None:
                lines.add(lineno)
        for const in co.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def report(hit_files, package_dir=None, out_md=None):
    package_dir = package_dir or os.path.join(_REPO, "mxnet_tpu")
    merged = {}
    for hf in hit_files:
        with open(hf) as f:
            for fn, ls in json.load(f).items():
                merged.setdefault(fn, set()).update(ls)

    rows = []
    tot_hit = tot_exec = 0
    for dirpath, _dirs, files in os.walk(package_dir):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            ex = executable_lines(path)
            if not ex:
                continue
            hit = merged.get(os.path.abspath(path), set()) & ex
            rows.append((os.path.relpath(path, _REPO), len(hit), len(ex)))
            tot_hit += len(hit)
            tot_exec += len(ex)

    rows.sort(key=lambda r: r[1] / r[2])
    lines = ["| file | lines | covered | % |", "|---|---|---|---|"]
    for rel, hit, ex in rows:
        lines.append("| %s | %d | %d | %.1f%% |" % (rel, ex, hit,
                                                    100.0 * hit / ex))
    lines.append("| **total** | **%d** | **%d** | **%.1f%%** |"
                 % (tot_exec, tot_hit, 100.0 * tot_hit / tot_exec))
    table = "\n".join(lines)
    if out_md:
        with open(out_md, "w") as f:
            f.write(table + "\n")
    return rows, table


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "report":
        rows, table = report(sys.argv[2:],
                             out_md=os.path.join(_REPO, "COVERAGE_TABLE.md"))
        print(table)
    else:
        print(__doc__)
