#!/usr/bin/env python
"""Parse training logs into a table (reference: tools/parse_log.py).

Understands the Speedometer/fit log lines:
  Epoch[3] Batch [20]  Speed: 1234.5 samples/sec  accuracy=0.87
  Epoch[3] Train-accuracy=0.91
  Epoch[3] Time cost=12.3
  Epoch[3] Validation-accuracy=0.88
"""

import argparse
import re
import sys


def parse(lines):
    rows = {}
    for line in lines:
        m = re.search(r"Epoch\[(\d+)\]", line)
        if not m:
            continue
        ep = int(m.group(1))
        row = rows.setdefault(ep, {})
        m2 = re.search(r"Speed: ([\d.]+)", line)
        if m2:
            row.setdefault("speed", []).append(float(m2.group(1)))
        m2 = re.search(r"Train-([\w-]+)=([\d.]+)", line)
        if m2:
            row["train-" + m2.group(1)] = float(m2.group(2))
        m2 = re.search(r"Validation-([\w-]+)=([\d.]+)", line)
        if m2:
            row["val-" + m2.group(1)] = float(m2.group(2))
        m2 = re.search(r"Time cost=([\d.]+)", line)
        if m2:
            row["time"] = float(m2.group(1))
    out = []
    for ep in sorted(rows):
        row = rows[ep]
        speed = sum(row.get("speed", [0])) / max(len(row.get("speed", [1])), 1)
        out.append((ep, row.get("train-accuracy"), row.get("val-accuracy"),
                    speed, row.get("time")))
    return out


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("logfile", nargs="?", default="-")
    args = parser.parse_args(argv)
    lines = sys.stdin if args.logfile == "-" else open(args.logfile)
    table = parse(lines)
    print("epoch\ttrain-acc\tval-acc\tspeed\ttime")
    for ep, tr, va, sp, t in table:
        print("%d\t%s\t%s\t%.1f\t%s" % (ep, tr, va, sp, t))
    return table


if __name__ == "__main__":
    main()
