#!/usr/bin/env python
"""Build RecordIO datasets from image folders (reference: tools/im2rec.py).

Two modes, same CLI shape as the reference:
  --list: scan a directory -> .lst file (index \t label \t relpath)
  default: .lst + image root -> .rec (+ .idx) via recordio.pack_img
"""

import argparse
import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack_img

EXTS = (".jpg", ".jpeg", ".png")


def make_list(args):
    image_list = []
    label = 0
    labels = {}
    for root, dirs, files in os.walk(args.root):
        dirs.sort()  # deterministic traversal (and streaming, no buffering)
        cat = os.path.relpath(root, args.root)
        for f in sorted(files):
            if f.lower().endswith(EXTS):
                if cat not in labels:
                    labels[cat] = label
                    label += 1
                image_list.append((os.path.join(cat, f), labels[cat]))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n_total = len(image_list)
    chunk = n_total // args.chunks
    for c in range(args.chunks):
        suffix = "" if args.chunks == 1 else "_%d" % c
        part = image_list[c * chunk:(c + 1) * chunk
                          if c + 1 < args.chunks else n_total]
        n_train = int(len(part) * args.train_ratio)
        splits = [("train", part[:n_train]), ("val", part[n_train:])] \
            if args.train_ratio < 1.0 else [("", part)]
        for split_name, items in splits:
            tag = (suffix + "_" + split_name) if split_name else suffix
            path = args.prefix + tag + ".lst"
            with open(path, "w") as f:
                for i, (rel, lab) in enumerate(items):
                    f.write("%d\t%f\t%s\n" % (i, lab, rel))
            print("wrote", path, len(items), "items")


def read_list(path, pack_label=False):
    """Yield (index, label, relpath) from a .lst file.  With pack_label
    the label is the full float vector of the middle columns (detection
    lists carry [header_width, obj_width, header..., objects...] there);
    otherwise it is the single scalar in column 1."""
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            if pack_label:
                label = np.array(parts[1:-1], dtype=np.float32)
            else:
                label = float(parts[1])
            yield int(parts[0]), label, parts[-1]


def im2rec(args):
    try:
        from PIL import Image
    except ImportError:
        raise SystemExit("im2rec needs PIL for image decode")
    lst = args.prefix + ".lst"
    rec = MXIndexedRecordIO(args.prefix + ".idx", args.prefix + ".rec", "w")
    n = 0
    for idx, label, rel in read_list(lst, pack_label=args.pack_label):
        img = Image.open(os.path.join(args.root, rel)).convert("RGB")
        if args.resize:
            w, h = img.size
            scale = args.resize / min(w, h)
            img = img.resize((int(w * scale), int(h * scale)))
        arr = np.asarray(img)
        rec.write_idx(idx, pack_img(IRHeader(0, label, idx, 0), arr,
                                    quality=args.quality))
        n += 1
    rec.close()
    print("wrote %s.rec (%d records)" % (args.prefix, n))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="create RecordIO image datasets")
    parser.add_argument("prefix", help="output prefix (or .lst prefix)")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true")
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--chunks", type=int, default=1)
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--pack-label", action="store_true",
                        help="pack the full multi-column .lst label vector "
                             "(detection lists) instead of a scalar")
    args = parser.parse_args(argv)
    if args.list:
        make_list(args)
    else:
        im2rec(args)


if __name__ == "__main__":
    main()
