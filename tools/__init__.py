"""Repo tooling namespace — makes ``python -m tools.mxlint`` work."""
