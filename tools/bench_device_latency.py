#!/usr/bin/env python
"""Pure device latency vs host-dispatched latency (VERDICT r1 weak 3).

Separates the per-call host/relay overhead from true device time by
running K chained forwards inside ONE jitted computation
(``lax.fori_loop``; the output feeds back into the next input so XLA
cannot elide iterations), then comparing with the one-call-per-step
host loop.

Usage: python tools/bench_device_latency.py [--network resnet50_v1]
       [--batch 1] [--inner 50] [--dtype float32]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--inner", default="50",
                   help="chain depth, or comma list for a least-squares fit")
    p.add_argument("--outer", type=int, default=20)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--dtype", default="float32")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import _StagingScope
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.parameter import param_override
    from mxnet_tpu.ndarray import NDArray

    net = getattr(vision, args.network)()
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    with ctx:
        net.initialize(ctx=ctx)
        if args.dtype != "float32":
            net.cast(args.dtype)
        net(mx.nd.zeros((1, 3, 224, 224), ctx=ctx,
                        dtype=args.dtype))
    params = list(net.collect_params().values())
    pvals = tuple(p.data().data_jax for p in params)

    def forward(pvals, x):
        override = {p: NDArray(v) for p, v in zip(params, pvals)}
        with param_override(override), _StagingScope():
            out = net(NDArray(x))
        return out.data_jax

    x = jnp.asarray(np.random.RandomState(0)
                    .rand(args.batch, 3, 224, 224)
                    .astype(args.dtype if args.dtype != "float32"
                            else np.float32))

    def _wait(arr):
        # through the axon relay block_until_ready returns EARLY; only
        # a host fetch is a true completion barrier (BENCH_NOTES r3)
        return float(jnp.sum(arr.astype(jnp.float32)))

    # --- host-dispatched: one call per forward
    jf = jax.jit(forward)
    _wait(jf(pvals, x))
    t0 = time.perf_counter()
    for _ in range(args.outer):
        out = jf(pvals, x)
    _wait(out)
    host_ms = (time.perf_counter() - t0) / args.outer * 1000

    # --- device-only: K chained forwards in one computation.  Two
    # properties make the chain elision-proof (r4 hardening): (1) every
    # iteration's output feeds a scalar accumulator that is RETURNED
    # and fetched, so no forward is dead code; (2) the input is rolled
    # one pixel per iteration, so the forward is not loop-invariant and
    # cannot be hoisted out and computed once.  The earlier `x + 0*out`
    # trick kept the forwards live only if the compiler declined two
    # legal rewrites — this version does not rely on the compiler's
    # restraint.
    def make_chained(inner):
        @jax.jit
        def chained(pvals, x):
            def body(_, carry):
                xc, acc = carry
                out = forward(pvals, xc)
                acc = acc + jnp.mean(out).astype(jnp.float32)
                return (jnp.roll(xc, 1, axis=-1), acc)
            _, acc = lax.fori_loop(
                0, inner, body, (x, jnp.zeros((), jnp.float32)))
            return acc
        return chained

    depths = [int(d) for d in str(args.inner).split(",")]
    walls = []
    for inner in depths:
        chained = make_chained(inner)
        _wait(chained(pvals, x))  # compile + warm
        best = None
        for _ in range(args.reps):
            t0 = time.perf_counter()
            _wait(chained(pvals, x))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        walls.append(best)

    rec = {
        "network": args.network, "batch": args.batch, "dtype": args.dtype,
        "host_dispatched_ms_per_forward": round(host_ms, 3),
        "host_img_s": round(args.batch / host_ms * 1000, 1),
        "depths": depths,
        "wall_ms": [round(w * 1000, 2) for w in walls],
    }
    if len(depths) >= 2:
        # least-squares fit wall = overhead + t_fwd * depth: the
        # multi-depth fit (VERDICT r3 weak 6) divides the relay's ±ms
        # call-time noise by the depth span, so bs=1 resolves to ~us
        # instead of hitting the relay noise floor
        t_fwd, overhead = np.polyfit(depths, walls, 1)
        rec["device_ms_per_forward"] = round(t_fwd * 1000, 4)
        rec["fit_overhead_ms"] = round(overhead * 1000, 2)
        rec["device_img_s"] = round(args.batch / (t_fwd * 1000) * 1000, 1)
        # the deepest single chain is also a hard upper bound on t_fwd
        # (index by max depth: --inner need not be sorted ascending)
        deepest = depths.index(max(depths))
        rec["upper_bound_ms"] = round(
            walls[deepest] / depths[deepest] * 1000, 4)
    else:
        dev_ms = walls[0] / depths[0] * 1000
        rec["device_ms_per_forward"] = round(dev_ms, 3)
        rec["device_img_s"] = round(args.batch / dev_ms * 1000, 1)
    rec["per_call_overhead_ms"] = round(
        host_ms - rec["device_ms_per_forward"], 3)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
