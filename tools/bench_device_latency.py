#!/usr/bin/env python
"""Pure device latency vs host-dispatched latency (VERDICT r1 weak 3).

Separates the per-call host/relay overhead from true device time by
running K chained forwards inside ONE jitted computation
(``lax.fori_loop``; the output feeds back into the next input so XLA
cannot elide iterations), then comparing with the one-call-per-step
host loop.

Usage: python tools/bench_device_latency.py [--network resnet50_v1]
       [--batch 1] [--inner 50] [--dtype float32]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--inner", type=int, default=50)
    p.add_argument("--outer", type=int, default=20)
    p.add_argument("--dtype", default="float32")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import _StagingScope
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.parameter import param_override
    from mxnet_tpu.ndarray import NDArray

    net = getattr(vision, args.network)()
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    with ctx:
        net.initialize(ctx=ctx)
        if args.dtype != "float32":
            net.cast(args.dtype)
        net(mx.nd.zeros((1, 3, 224, 224), ctx=ctx,
                        dtype=args.dtype))
    params = list(net.collect_params().values())
    pvals = tuple(p.data().data_jax for p in params)

    def forward(pvals, x):
        override = {p: NDArray(v) for p, v in zip(params, pvals)}
        with param_override(override), _StagingScope():
            out = net(NDArray(x))
        return out.data_jax

    x = jnp.asarray(np.random.RandomState(0)
                    .rand(args.batch, 3, 224, 224)
                    .astype(args.dtype if args.dtype != "float32"
                            else np.float32))

    def _wait(arr):
        # through the axon relay block_until_ready returns EARLY; only
        # a host fetch is a true completion barrier (BENCH_NOTES r3)
        return float(jnp.sum(arr.astype(jnp.float32)))

    # --- host-dispatched: one call per forward
    jf = jax.jit(forward)
    _wait(jf(pvals, x))
    t0 = time.perf_counter()
    for _ in range(args.outer):
        out = jf(pvals, x)
    _wait(out)
    host_ms = (time.perf_counter() - t0) / args.outer * 1000

    # --- device-only: K chained forwards in one computation; feed a
    # scalar function of the output back into the input so every
    # iteration depends on the previous one
    @jax.jit
    def chained(pvals, x):
        def body(_, carry):
            out = forward(pvals, carry)
            bump = (jnp.sum(out) * 0).astype(carry.dtype)
            return carry + bump
        return lax.fori_loop(0, args.inner, body, x)

    _wait(chained(pvals, x))
    t0 = time.perf_counter()
    _wait(chained(pvals, x))
    dev_ms = (time.perf_counter() - t0) / args.inner * 1000

    print(json.dumps({
        "network": args.network, "batch": args.batch, "dtype": args.dtype,
        "device_ms_per_forward": round(dev_ms, 3),
        "host_dispatched_ms_per_forward": round(host_ms, 3),
        "per_call_overhead_ms": round(host_ms - dev_ms, 3),
        "device_img_s": round(args.batch / dev_ms * 1000, 1),
        "host_img_s": round(args.batch / host_ms * 1000, 1),
    }))


if __name__ == "__main__":
    main()
