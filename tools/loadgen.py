#!/usr/bin/env python
"""Synthetic open-loop load generator for the serving layer.

The bench of the continuous-batching subsystem (``mxnet_tpu/serving.py``,
docs/SERVING.md): Poisson arrivals at an offered request rate (open
loop — arrivals do NOT wait for completions, so queueing delay is
measured honestly instead of being absorbed by a slow client), mixed
request shapes (each request carries 1..k samples), p50/p99/p99.9
latency per offered-QPS level, and a serial one-at-a-time
``Predictor.forward`` baseline for the speedup headline.  One JSON
report on stdout; per-batch serving samples optionally land in a JSONL
timeline whose soak is gated through the perf-doctor trend rules
(leak slope / throughput decay), the ROADMAP's serving contract.

Usage::

    python tools/loadgen.py                         # default sweep
    python tools/loadgen.py --qps 200,400,800 --duration 3 \
        --out loadgen_report.json --metrics serve_timeline.jsonl

Also reachable as ``python bench.py --serve`` (the bench artifact
path).  Methodology: docs/SERVING.md "Latency SLOs".
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

# requests carry 1..4 samples by default (the "mixed shapes" axis: the
# batcher packs them into one bucketed batch regardless)
DEFAULT_SIZES = (1, 2, 4)
# the bench ladder tops out at 32: on a small host the per-batch fixed
# cost dominates, and a taller ladder is precisely the perf doctor's
# "raise max bucket" lever
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)
# a level is "sustained" when the achieved rate keeps up with this
# fraction of the offered rate
SUSTAIN_FRACTION = 0.9


def build_demo_predictor(in_dim=64, hidden=64, out_dim=8, seed=7):
    """A small exported MLP loaded back through the Predictor — the
    same deployment path a real model takes (export → symbol JSON +
    params blob → ``Predictor``).  Returns ``(predictor, input_shape)``
    with the predictor bound at batch 1."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import random as mxrandom
    from mxnet_tpu.predictor import Predictor

    mxrandom.seed(seed)
    np.random.seed(seed)
    block = gluon.nn.HybridSequential()
    block.add(gluon.nn.Dense(hidden, activation="relu"))
    block.add(gluon.nn.Dense(out_dim))
    block.hybridize()
    block.initialize()
    block(mx.nd.zeros((1, in_dim)))
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "loadgen_model")
        block.export(path)
        sym_json = open(path + "-symbol.json").read()
        params = open(path + "-0000.params", "rb").read()
    pred = Predictor(sym_json, params, {"data": (1, in_dim)})
    return pred, (in_dim,)


def _latency_summary(lat_s):
    if not lat_s:
        return {"p50_ms": None, "p99_ms": None, "p999_ms": None,
                "mean_ms": None}
    ordered = sorted(lat_s)  # once; the percentiles index into it

    def pick(q):
        idx = min(len(ordered) - 1,
                  int(round(q / 100.0 * (len(ordered) - 1))))
        return ordered[idx] * 1e3

    return {"p50_ms": pick(50), "p99_ms": pick(99),
            "p999_ms": pick(99.9),
            "mean_ms": sum(ordered) / len(ordered) * 1e3}


# fixed quantile ladder for the per-request CDF — enough points to
# chart the tail shape, few enough to stay a one-line JSON object
CDF_QUANTILES = (10.0, 25.0, 50.0, 75.0, 90.0, 95.0, 99.0, 99.9)


def _latency_cdf(lat_s):
    """Per-request latency CDF (ms) at fixed quantiles plus the max —
    a tail chart needs more than three points, and the request x-ray's
    slow-tail triage starts from exactly this curve."""
    if not lat_s:
        return None
    ordered = sorted(lat_s)

    def pick(q):
        idx = min(len(ordered) - 1,
                  int(round(q / 100.0 * (len(ordered) - 1))))
        return ordered[idx] * 1e3

    cdf = {"p%g" % q: pick(q) for q in CDF_QUANTILES}
    cdf["max"] = ordered[-1] * 1e3
    return cdf


def slo_verdict():
    """Per-objective verdict from the live ``mxnet_tpu.slo`` counters
    (they saw every request the sweep pushed through the server):
    achieved good fraction vs target, budget burned.  None when no
    objective is declared (``MXNET_TPU_SLO`` unset)."""
    from mxnet_tpu import slo

    objs = slo.snapshot().get("objectives") or []
    if not objs:
        return None
    out = []
    for ob in objs:
        total = ob["total"]
        achieved = (ob["good"] / total) if total else None
        out.append({"objective": ob["name"], "kind": ob["kind"],
                    "threshold_ms": ob["threshold_ms"],
                    "target": ob["target"], "events": total,
                    "achieved": achieved,
                    "budget_burned": 1.0 - ob["budget_remaining"],
                    "met": bool(achieved is not None
                                and achieved >= ob["target"])})
    return out


def serial_baseline(pred, sample_shape, sizes=DEFAULT_SIZES,
                    n_requests=200, seed=0):
    """One-at-a-time ``Predictor.forward``: the pre-serving deployment
    path, closed loop.  One weight-sharing clone per request size (the
    fairest serial setup — no rebinding inside the loop); returns the
    sustained request rate and its latency percentiles."""
    rng = np.random.RandomState(seed)
    clones = {k: pred._reshape_clone({"data": (k,) + sample_shape})
              for k in sizes}
    pool = {k: rng.rand(k, *sample_shape).astype(np.float32)
            for k in sizes}
    for k in sizes:  # warm every clone's executable
        clones[k].forward(data=pool[k]).get_output(0)
    ks = [sizes[i % len(sizes)] for i in range(n_requests)]
    lat = []
    t_start = time.perf_counter()
    for k in ks:
        t0 = time.perf_counter()
        clones[k].forward(data=pool[k]).get_output(0)
        lat.append(time.perf_counter() - t0)
    span = time.perf_counter() - t_start
    out = {"requests": n_requests, "qps": n_requests / span,
           "samples_per_s": sum(ks) / span}
    out.update(_latency_summary(lat))
    return out


def run_open_loop(server, qps, duration, sample_shape,
                  sizes=DEFAULT_SIZES, seed=0, timeout=30.0):
    """One offered-QPS level: Poisson arrivals (exponential gaps) for
    ``duration`` seconds, submissions never waiting on completions.
    The arrival schedule is precomputed so the client loop stays cheap
    — on small hosts the loadgen shares cores with the server it
    drives.  Returns the level report (offered/achieved rates, latency
    percentiles, rejection count)."""
    from mxnet_tpu.serving import RequestRejected

    rng = np.random.RandomState(seed)
    pool = {k: [rng.rand(k, *sample_shape).astype(np.float32)
                for _ in range(8)]
            for k in sizes}
    # open loop: the schedule is fixed up front and never waits on the
    # server — a slow server faces growing queues, not a slowing client
    gaps = rng.exponential(1.0 / qps, size=int(qps * duration * 2) + 16)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration]
    futures = []
    rejected = 0
    i = 0
    t_start = time.perf_counter()
    t_end = t_start + duration
    n = len(arrivals)
    while i < n:
        now = time.perf_counter()
        if now >= t_end:
            break
        due = t_start + arrivals[i]
        if now < due:
            time.sleep(min(due - now, 5e-4))
            continue
        k = sizes[i % len(sizes)]
        try:
            futures.append(server.submit(pool[k][i % 8]))
        except RequestRejected:
            rejected += 1
        i += 1
    lat = []
    errors = 0
    last_done = t_start
    for f in futures:
        try:
            f.result(timeout)
        except Exception:
            errors += 1
            continue
        lat.append(f.t_done - f.t_submit)
        if f.t_done > last_done:
            last_done = f.t_done
    span = max(last_done - t_start, 1e-9)
    out = {"offered_qps": qps, "submitted": i, "rejected": rejected,
           "errors": errors, "served": len(lat),
           "achieved_qps": len(lat) / span,
           "sustained": len(lat) / span >= SUSTAIN_FRACTION * qps}
    out.update(_latency_summary(lat))
    out["cdf_ms"] = _latency_cdf(lat)
    return out


# the throughput trend rule uses mean windows sized for training-step
# timelines; a short serving soak on a loaded CI box sees enough
# scheduler jitter that a couple of slow batches shift a mean window.
# Before a timeline-throughput finding may fail the soak gate it must
# be CONFIRMED on medians over enough samples (leak findings pass
# through untouched — a leak slope is monotonic, not jitter).
TREND_CONFIRM_MIN_SAMPLES = 16
TREND_QUIET_FLOOR_MS = 2.0


def _throughput_confirmed(samples):
    """Median-window recheck of the throughput-decay verdict."""
    from mxnet_tpu import perfdoctor

    walls = [s["wall_ms"] for s in samples
             if s.get("wall_ms") is not None]
    if len(walls) < TREND_CONFIRM_MIN_SAMPLES:
        return False  # too few batches to call a trend under load
    k = max(3, len(walls) // 4)

    def med(xs):
        s = sorted(xs)
        return s[len(s) // 2]

    e_med, l_med = med(walls[:k]), med(walls[-k:])
    if e_med < TREND_QUIET_FLOOR_MS and l_med < TREND_QUIET_FLOOR_MS:
        return False  # sub-floor batches: pure noise territory
    return l_med > (1.0 + perfdoctor.TREND_SLOWDOWN) * e_med


def trend_doctor(metrics_path):
    """Perf-doctor trend rules over the serving JSONL timeline (the
    soak gate: no leak slope, no throughput decay).  Returns the
    finding list (possibly empty); a missing/empty timeline returns
    None — the caller decides whether that fails the gate."""
    from mxnet_tpu import metrics_timeline, perfdoctor

    if not metrics_path or not os.path.exists(metrics_path):
        return None
    samples = metrics_timeline.parse_jsonl(open(metrics_path).read())
    if not samples:
        return None
    findings = perfdoctor.diagnose(timeline=samples)
    kept = []
    for f in findings:
        if f["rule"] == "timeline-leak":
            kept.append(f)
        elif f["rule"] == "timeline-throughput" \
                and _throughput_confirmed(samples):
            kept.append(f)
    return kept


def serial_server_level(pred, qps, duration, sample_shape,
                        sizes=DEFAULT_SIZES, seed=0):
    """The one-at-a-time counterfactual under the SAME offered load: a
    FIFO replay of the identical Poisson arrival schedule through
    serial ``Predictor.forward`` calls — real measured service times,
    M/G/1 queueing arithmetic (``start = max(arrival, prev
    completion)``), zero thread contention (deliberately flattering to
    the serial side).  Past the serial capacity its queue — and p99 —
    grows with the run length, which is exactly the failure mode
    continuous batching removes."""
    rng = np.random.RandomState(seed)
    pool = {k: [rng.rand(k, *sample_shape).astype(np.float32)
                for _ in range(8)]
            for k in sizes}
    gaps = rng.exponential(1.0 / qps, size=int(qps * duration * 2) + 16)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration]
    clones = {k: pred._reshape_clone({"data": (k,) + sample_shape})
              for k in sizes}
    for k in sizes:
        clones[k].forward(data=pool[k][0]).get_output(0)
    completion = 0.0
    lat = []
    for i, a in enumerate(arrivals):
        k = sizes[i % len(sizes)]
        t0 = time.perf_counter()
        clones[k].forward(data=pool[k][i % 8]).get_output(0)
        svc = time.perf_counter() - t0
        a = float(a)
        start = a if a > completion else completion
        completion = start + svc
        lat.append(completion - a)
    span = max(completion, 1e-9)
    out = {"offered_qps": qps, "submitted": len(arrivals),
           "served": len(lat), "achieved_qps": len(lat) / span,
           "sustained": len(lat) / span >= SUSTAIN_FRACTION * qps,
           "mode": "serial-replay"}
    out.update(_latency_summary(lat))
    return out


def sweep(qps_levels=None, duration=2.0, sizes=DEFAULT_SIZES,
          buckets=DEFAULT_BUCKETS, serial_requests=200,
          metrics_path=None, workers=None, seed=0, model=None,
          serial_at_load=True):
    """The full bench: closed-loop serial ``Predictor.forward``
    baseline, one open-loop level per offered QPS (auto-derived from
    the serial rate when not given: 1x/2x/4x/6x), the serial-server
    counterfactual at the highest sustained level (same offered load,
    no batching), and the trend-doctor soak gate over the serving
    timeline.  Returns the JSON-ready report."""
    from mxnet_tpu.serving import InferenceServer

    if model is None:
        pred, sample_shape = build_demo_predictor()
    else:
        pred, sample_shape = model
    serial = serial_baseline(pred, sample_shape, sizes=sizes,
                             n_requests=serial_requests, seed=seed)
    if not qps_levels:
        base = serial["qps"]
        qps_levels = [round(base * m, 1) for m in (1, 2, 4, 6)]
    server = InferenceServer(pred, buckets=buckets, workers=workers)
    levels = []
    with server as srv:
        srv.warmup()
        for qps in qps_levels:
            levels.append(run_open_loop(srv, qps, duration,
                                        sample_shape, sizes=sizes,
                                        seed=seed))
        serving_snap = srv.snapshot()
    sustained = [lv for lv in levels if lv["sustained"]]
    best = max(sustained, key=lambda lv: lv["achieved_qps"]) \
        if sustained else None
    # the soak gate runs at ONE steady operating point (the best
    # sustained level) with the per-batch timeline on — gating across
    # the escalating sweep would read the load ramp itself as a
    # throughput regression
    doctor = soak = None
    if metrics_path and best is not None:
        if os.path.exists(metrics_path):
            # a stale timeline from a prior run would feed the trend
            # doctor someone else's regression
            os.remove(metrics_path)
        soak_server = InferenceServer(pred, buckets=buckets,
                                      workers=workers,
                                      metrics_path=metrics_path,
                                      name="serve-soak")
        with soak_server as srv:
            srv.warmup()
            soak = run_open_loop(srv, best["offered_qps"],
                                 max(duration * 2, 1.0), sample_shape,
                                 sizes=sizes, seed=seed + 1)
        doctor = trend_doctor(metrics_path)
    serial_best = None
    if serial_at_load and best is not None:
        serial_best = serial_server_level(pred, best["offered_qps"],
                                          duration, sample_shape,
                                          sizes=sizes, seed=seed)
    report = {
        "metric": "serving open-loop sweep (Poisson arrivals, request "
                  "sizes %s, buckets %s, %.1fs/level)"
                  % (list(sizes), list(buckets), duration),
        "serial": serial,
        "levels": levels,
        "soak": soak,
        "serial_server_at_best_load": serial_best,
        "serving": {k: serving_snap.get(k) for k in
                    ("batches", "samples", "requests", "mean_occupancy",
                     "bucket_compiles", "qps", "rejected")},
        "max_sustained_qps": best["achieved_qps"] if best else None,
        "speedup_vs_serial": (best["achieved_qps"] / serial["qps"])
        if best else None,
        # tail comparison at the SAME offered load: batching vs the
        # one-at-a-time server (<= 1.0 means equal-or-better p99)
        "p99_vs_serial_at_load": (best["p99_ms"] / serial_best["p99_ms"])
        if best and serial_best and best.get("p99_ms")
        and serial_best.get("p99_ms") else None,
        # and vs the closed-loop serial baseline at ITS OWN pace (the
        # latency a lone client saw before any load existed)
        "p99_vs_serial_closed_loop": (best["p99_ms"] / serial["p99_ms"])
        if best and best.get("p99_ms") and serial.get("p99_ms")
        else None,
        "trend_doctor_findings": doctor,
        "soak_clean": (not doctor) if doctor is not None else None,
        # per-objective SLO verdict over EVERY request of the sweep
        # (declared via MXNET_TPU_SLO; None when no objective is on)
        "slo": slo_verdict(),
    }
    return report


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Open-loop load generator for the continuous-"
                    "batching inference server (docs/SERVING.md).")
    p.add_argument("--qps", default=None,
                   help="comma list of offered request rates (default: "
                        "1x/2x/4x/6x the measured serial baseline)")
    p.add_argument("--duration", type=float, default=2.0,
                   help="seconds per offered-QPS level")
    p.add_argument("--sizes", default="1,2,4",
                   help="comma list of request sample counts (mixed "
                        "request shapes)")
    p.add_argument("--buckets", default="1,2,4,8,16",
                   help="server bucket ladder")
    p.add_argument("--workers", type=int, default=None,
                   help="server pipeline workers "
                        "(default MXNET_TPU_SERVE_WORKERS or 2)")
    p.add_argument("--metrics", default=None,
                   help="serving JSONL timeline path (enables the "
                        "trend-doctor soak gate)")
    p.add_argument("--out", default=None,
                   help="also write the JSON report here")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    qps_levels = [float(q) for q in args.qps.split(",")] \
        if args.qps else None
    report = sweep(
        qps_levels=qps_levels, duration=args.duration,
        sizes=tuple(int(s) for s in args.sizes.split(",")),
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        metrics_path=args.metrics, workers=args.workers,
        seed=args.seed)
    print(json.dumps(report))
    # human-readable SLO verdict lines ride stderr: stdout stays the
    # one-JSON-report contract bench.py and CI parsers rely on
    for v in report.get("slo") or []:
        ach = ("%.4f%%" % (v["achieved"] * 100.0)
               if v["achieved"] is not None else "n/a")
        print("SLO %s (%s): target %.4f%%, achieved %s over %d "
              "requests, budget burned %.1f%% -> %s"
              % (v["objective"], v["kind"], v["target"] * 100.0, ach,
                 v["events"], v["budget_burned"] * 100.0,
                 "met" if v["met"] else "MISSED"), file=sys.stderr)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    # the sweep is informational; the soak gate is the pass/fail bit —
    # and a REQUESTED gate that never ran (no sustained level, or the
    # timeline export went dark) must not pass vacuously
    if args.metrics:
        return 0 if report["soak_clean"] is True else 1
    return 0 if report["soak_clean"] in (True, None) else 1


if __name__ == "__main__":
    sys.exit(main())
