"""Visualizing what a CNN looks at — Grad-CAM saliency.

Runnable tutorial (reference: docs/tutorials/vision/cnn_visualization.md,
which applies Grad-CAM to VGG on real photos; here a tiny convnet on a
synthetic two-class image task so it runs in seconds).

Grad-CAM: the class score's gradient w.r.t. a conv layer's activations,
spatially pooled, weights those activation maps — highlighting the
pixels that drove the prediction.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

# --- a task where the evidence has a location ---------------------------
# Class 1 images carry a bright square in the TOP-LEFT quadrant; class 0
# in the BOTTOM-RIGHT.  A faithful saliency map must light up the
# correct quadrant.
def make_batch(n, rng):
    x = rng.uniform(0, 0.1, (n, 1, 16, 16)).astype(np.float32)
    y = rng.randint(0, 2, n)
    for i, lbl in enumerate(y):
        if lbl == 1:
            x[i, 0, 2:6, 2:6] += 1.0
        else:
            x[i, 0, 10:14, 10:14] += 1.0
    return mx.nd.array(x), mx.nd.array(y)


rng = np.random.RandomState(7)
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
        gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
        gluon.nn.GlobalAvgPool2D(),
        gluon.nn.Dense(2))
net.initialize(mx.init.Xavier())

loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.005})
for _ in range(40):
    x, y = make_batch(64, rng)
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(64)

# --- Grad-CAM ------------------------------------------------------------
# Split the net at the last conv: features = conv part, head = the rest.
features = gluon.nn.HybridSequential()
head = gluon.nn.HybridSequential()
features.add(net[0], net[1])
head.add(net[2], net[3])

x, y = make_batch(8, rng)
acts = features(x)
acts.attach_grad()
with autograd.record():
    score = head(acts).pick(y)  # the true-class logit per image
score.backward()

# channel weights = spatial mean of the gradients; CAM = weighted sum
weights = acts.grad.mean(axis=(2, 3), keepdims=True)
cam = mx.nd.relu((weights * acts).sum(axis=1)).asnumpy()  # (n, 16, 16)

correct_side = 0
for i, lbl in enumerate(y.asnumpy().astype(int)):
    tl = cam[i, :8, :8].sum()
    br = cam[i, 8:, 8:].sum()
    if (lbl == 1 and tl > br) or (lbl == 0 and br > tl):
        correct_side += 1
assert correct_side >= 6, correct_side  # saliency points at the evidence
print("OK Grad-CAM localized the evidence in %d/8 images" % correct_side)
