"""Generative adversarial networks — the two-player training loop.

Runnable tutorial (reference: docs/tutorials/unsupervised_learning/
gan.md, which trains a DCGAN on MNIST; here the real distribution is a
2-D Gaussian mixture so the adversarial dynamics run in seconds and the
generator's fit is checkable numerically).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

rng = np.random.RandomState(0)
BATCH, LATENT = 128, 4

# real data: mixture of two Gaussians at (+2,+2) and (-2,-2)
def real_batch():
    c = rng.randint(0, 2, BATCH)[:, None].astype(np.float32)
    x = rng.randn(BATCH, 2).astype(np.float32) * 0.3 + (2 * (2 * c - 1))
    return mx.nd.array(x)


generator = gluon.nn.HybridSequential()
generator.add(gluon.nn.Dense(32, activation="relu"),
              gluon.nn.Dense(32, activation="relu"),
              gluon.nn.Dense(2))
discriminator = gluon.nn.HybridSequential()
discriminator.add(gluon.nn.Dense(32, activation="relu"),
                  gluon.nn.Dense(32, activation="relu"),
                  gluon.nn.Dense(1))
generator.initialize(mx.init.Xavier())
discriminator.initialize(mx.init.Xavier())

# SigmoidBCE with logits is the numerically stable GAN loss
loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
g_tr = gluon.Trainer(generator.collect_params(), "adam",
                     {"learning_rate": 2e-3})
d_tr = gluon.Trainer(discriminator.collect_params(), "adam",
                     {"learning_rate": 2e-3})

ones = mx.nd.ones((BATCH,))
zeros = mx.nd.zeros((BATCH,))

for step in range(400):
    # --- discriminator step: real -> 1, fake -> 0 -----------------------
    z = mx.nd.array(rng.randn(BATCH, LATENT).astype(np.float32))
    fake = generator(z).detach()   # detach: G is frozen in the D step
    with autograd.record():
        d_loss = (loss_fn(discriminator(real_batch()), ones) +
                  loss_fn(discriminator(fake), zeros))
    d_loss.backward()
    d_tr.step(BATCH)

    # --- generator step: fool D into saying 1 ---------------------------
    z = mx.nd.array(rng.randn(BATCH, LATENT).astype(np.float32))
    with autograd.record():
        g_loss = loss_fn(discriminator(generator(z)), ones)
    g_loss.backward()
    g_tr.step(BATCH)

# the generator should now emit points near the two modes
z = mx.nd.array(rng.randn(512, LATENT).astype(np.float32))
samples = generator(z).asnumpy()
dist_to_mode = np.minimum(
    np.linalg.norm(samples - np.array([2.0, 2.0]), axis=1),
    np.linalg.norm(samples - np.array([-2.0, -2.0]), axis=1))
frac_near = (dist_to_mode < 1.5).mean()
assert frac_near > 0.6, frac_near
print("OK GAN: %.0f%% of samples near a real mode" % (100 * frac_near))
