"""Text classification with a CNN — embeddings, 1-D convolutions over
tokens, max-over-time pooling (Kim 2014).

Runnable tutorial (reference: docs/tutorials/nlp/cnn.md, which trains
the same architecture on movie reviews; here the corpus is synthetic so
the tutorial runs in seconds with no downloads).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

# --- a synthetic sentiment corpus ---------------------------------------
# Vocabulary of 50 tokens; class 1 sentences draw from the "positive"
# half (ids 0-24), class 0 from ids 25-49.  A real pipeline would use
# a tokenizer + vocabulary; the model is identical.
VOCAB, SEQ_LEN, N = 50, 20, 256
rng = np.random.RandomState(0)
labels = rng.randint(0, 2, N)
tokens = np.where(labels[:, None] == 1,
                  rng.randint(0, 25, (N, SEQ_LEN)),
                  rng.randint(25, VOCAB, (N, SEQ_LEN)))

# --- the model -----------------------------------------------------------
# Embedding -> parallel Conv1D branches (widths 3,4,5) -> global max pool
# -> concat -> dense.  Conv1D expects (batch, channels, width), so the
# embedded (batch, seq, emb) tensor is transposed.
class TextCNN(gluon.HybridBlock):
    def __init__(self, vocab, emb=16, widths=(3, 4, 5), feats=8, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embedding = gluon.nn.Embedding(vocab, emb)
            self.convs = []
            for i, w in enumerate(widths):
                conv = gluon.nn.Conv1D(feats, w, activation="relu")
                self.register_child(conv)
                self.convs.append(conv)
            self.pool = gluon.nn.GlobalMaxPool1D()
            self.out = gluon.nn.Dense(2)

    def hybrid_forward(self, F, x):
        e = self.embedding(x).transpose((0, 2, 1))
        branches = [self.pool(c(e)).flatten() for c in self.convs]
        return self.out(F.concat(*branches, dim=1))


net = TextCNN(VOCAB)
net.initialize(mx.init.Xavier())
net.hybridize()

# --- train ---------------------------------------------------------------
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.01})
x_all = mx.nd.array(tokens)
y_all = mx.nd.array(labels)

first = last = None
for epoch in range(15):
    with autograd.record():
        loss = loss_fn(net(x_all), y_all)
    loss.backward()
    trainer.step(N)
    cur = float(loss.mean().asnumpy())
    first = cur if first is None else first
    last = cur

acc = (net(x_all).argmax(axis=1).asnumpy() == labels).mean()
assert last < first * 0.5, (first, last)
assert acc > 0.9, acc
print("OK TextCNN: loss %.3f -> %.3f, train accuracy %.2f" % (first, last, acc))
