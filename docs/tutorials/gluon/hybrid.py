"""Hybridize — imperative flexibility, compiled-graph speed.

Runnable tutorial (reference: docs/tutorials/gluon/hybrid.md).  A
HybridBlock's hybrid_forward(F, x) is written against the dual-headed
`F` namespace (nd eagerly, sym when traced); `hybridize()` stages the
whole forward into one cached XLA computation keyed on input
signature.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


class Net(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.fc1 = nn.Dense(16)
            self.fc2 = nn.Dense(4)

    def hybrid_forward(self, F, x):
        # F is mx.nd eagerly, mx.sym when traced: write it once.
        h = F.relu(self.fc1(x))
        return self.fc2(h)


net = Net()
net.initialize()
x = mx.nd.array(np.random.RandomState(0).rand(2, 8).astype(np.float32))

eager = net(x).asnumpy()          # imperative execution
net.hybridize()
staged = net(x).asnumpy()         # first call traces + compiles
again = net(x).asnumpy()          # cache hit: no retrace
assert np.allclose(eager, staged, atol=1e-5)
assert np.allclose(staged, again)

# Hybridized blocks export to (symbol.json, params) for the symbolic
# serving path / other language bindings.
import tempfile, os
prefix = os.path.join(tempfile.mkdtemp(), "net")
net.export(prefix)
assert os.path.exists(prefix + "-symbol.json")
assert os.path.exists(prefix + "-0000.params")

# And can be reloaded as a SymbolBlock:
sym = mx.sym.load(prefix + "-symbol.json")
sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                               prefix + "-0000.params")
assert np.allclose(sb(x).asnumpy(), staged, atol=1e-5)

# The gotcha the reference documents: hybrid_forward must stay
# symbolically traceable — no .asnumpy()/shape branching on traced
# values inside it.  Use F.where / F.broadcast_* instead.
print("hybrid tutorial: OK")
