"""Custom layers — parameters, initialization, composition.

Runnable tutorial (reference: docs/tutorials/gluon/custom_layer.md).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


# A parameter-free layer needs only hybrid_forward.
class CenteredLayer(gluon.HybridBlock):
    def hybrid_forward(self, F, x):
        return x - F.mean(x)


c = CenteredLayer()
out = c(mx.nd.array([1.0, 2.0, 3.0, 4.0, 5.0]))
assert abs(out.asnumpy().mean()) < 1e-6


# Layers with parameters declare them via self.params.get; deferred
# shape (-1/0 dims) resolves at the first forward.  Registered params
# arrive in hybrid_forward as keyword arguments.
class MyDense(gluon.HybridBlock):
    def __init__(self, units, in_units=0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.weight = self.params.get("weight",
                                          shape=(units, in_units))
            self.bias = self.params.get("bias", shape=(units,))

    def hybrid_forward(self, F, x, weight, bias):
        return F.FullyConnected(x, weight, bias,
                                num_hidden=weight.shape[0])


layer = MyDense(3, in_units=5)
layer.initialize(mx.init.Xavier())
y = layer(mx.nd.random.uniform(shape=(2, 5)))
assert y.shape == (2, 3)
assert layer.weight.data().shape == (3, 5)

# Custom layers compose with built-ins transparently.
net = nn.HybridSequential()
net.add(MyDense(8, in_units=5), nn.Activation("relu"), CenteredLayer())
net.initialize()
net.hybridize()
out = net(mx.nd.random.uniform(shape=(4, 5)))
assert out.shape == (4, 8)

# And they train: gradients flow through the registered Parameters.
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1})
with mx.autograd.record():
    loss = (net(mx.nd.ones((2, 5))) ** 2).sum()
loss.backward()
g = net[0].weight.grad()
assert float(np.abs(g.asnumpy()).sum()) > 0
trainer.step(2)

print("custom_layer tutorial: OK")
