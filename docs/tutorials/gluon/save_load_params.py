"""Saving and loading parameters and models.

Runnable tutorial (reference: docs/tutorials/gluon/save_load_params.md).
Three levels: (1) save_parameters/load_parameters for a known
architecture; (2) export/SymbolBlock.imports for
architecture+weights; (3) raw mx.nd.save/load for arbitrary arrays.
"""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn

tmp = tempfile.mkdtemp()
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(2, 6).astype(np.float32))


def build():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    return net


# (1) parameters only — rebuild the same architecture in code, load.
net = build()
net.initialize()
want = net(x).asnumpy()
pfile = os.path.join(tmp, "net.params")
net.save_parameters(pfile)

net2 = build()
net2.load_parameters(pfile)
assert np.allclose(net2(x).asnumpy(), want)

# (2) architecture + weights — hybridize, run once, export; reload
# WITHOUT the Python class via SymbolBlock.
net.hybridize()
net(x)
prefix = os.path.join(tmp, "exported")
net.export(prefix)
loaded = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0000.params")
assert np.allclose(loaded(x).asnumpy(), want, atol=1e-5)

# (3) raw arrays — the ndarray save/load format.
afile = os.path.join(tmp, "arrays.nd")
mx.nd.save(afile, {"a": mx.nd.ones((2, 2)), "b": mx.nd.zeros((3,))})
back = mx.nd.load(afile)
assert set(back) == {"a", "b"} and (back["a"].asnumpy() == 1).all()

print("save_load_params tutorial: OK")
