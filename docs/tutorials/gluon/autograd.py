"""Autograd — automatic differentiation of imperative code.

Runnable tutorial (reference: docs/tutorials/gluon/autograd.md).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd

# --- the basic recipe ----------------------------------------------------
# attach_grad marks a leaf; record() traces; backward() fills .grad.
x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
x.attach_grad()
with autograd.record():
    y = 2 * x * x          # dy/dx = 4x
y.backward()
assert (x.grad.asnumpy() == 4 * x.asnumpy()).all()

# --- scalar losses and head gradients ------------------------------------
x.attach_grad()
with autograd.record():
    z = (x ** 2).sum()
z.backward()
assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())

# For non-scalar heads, pass the output gradient explicitly.
x.attach_grad()
with autograd.record():
    y = x * 3
y.backward(mx.nd.ones_like(y) * 0.5)
assert np.allclose(x.grad.asnumpy(), 1.5)

# --- control flow differentiates naturally -------------------------------
def f(a):
    b = a * 2
    # Python control flow on VALUES is fine in the imperative API
    while float(b.norm().asscalar()) < 10:
        b = b * 2
    return b.sum()

a = mx.nd.array([0.5])
a.attach_grad()
with autograd.record():
    out = f(a)
out.backward()
assert a.grad.asscalar() != 0

# --- train vs predict mode ----------------------------------------------
# record() implies train_mode (Dropout active); pause() stops taping.
with autograd.record():
    assert autograd.is_training() and autograd.is_recording()
    with autograd.pause():
        assert not autograd.is_recording()
assert not autograd.is_recording()

# --- higher-level: grad() returns gradients functionally -----------------
w = mx.nd.array([2.0])
w.attach_grad()
with autograd.record():
    loss = (w * w * w).sum()     # d/dw = 3w^2 = 12
grads = autograd.grad(loss, [w])
assert np.allclose(grads[0].asnumpy(), 12.0)

print("autograd tutorial: OK")

# --- higher-order gradients (r5) -----------------------------------------
# grad(create_graph=True) returns first-order grads that are THEMSELVES
# differentiable: the tape is replayed as a pure function and the
# gradient computation is recorded back.  Works for the registry-op
# subset (elemwise/FC/conv/...); PRNG ops (Dropout) raise with a
# redirect to hybridize() + jax.grad composition.
x = mx.nd.array([1.0, 2.0, 3.0])
x.attach_grad()
with autograd.record():
    y = x * x * x                         # x^3
    (dx,) = autograd.grad(y, [x], create_graph=True)
    assert np.allclose(dx.asnumpy(), 3 * x.asnumpy() ** 2)
    dx.backward()                         # d(3x^2)/dx = 6x
assert np.allclose(x.grad.asnumpy(), 6 * x.asnumpy())

# Third order by chaining grad calls:
w = mx.nd.array([2.0])
w.attach_grad()
with autograd.record():
    out = w * w * w * w                   # w^4
    (d1,) = autograd.grad(out, [w], create_graph=True)   # 4w^3
    (d2,) = autograd.grad(d1, [w], create_graph=True)    # 12w^2
    (d3,) = autograd.grad(d2, [w])                       # 24w
assert np.allclose(d3.asnumpy(), [48.0])

