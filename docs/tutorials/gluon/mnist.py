"""Handwritten-digit style classification with Gluon, end to end.

Runnable tutorial (reference: docs/tutorials/gluon/mnist.md; the real
MNIST download is replaced by a synthetic drop-in so the tutorial runs
hermetically — swap `synthetic_mnist()` for
`gluon.data.vision.MNIST()` when the dataset is available).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def synthetic_mnist(n=512, seed=0):
    """10-class 28x28 images whose class is encoded as a bright patch
    position — learnable by a small CNN in a few epochs."""
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.2
    ys = rng.randint(0, 10, n).astype(np.int32)
    for i, y in enumerate(ys):
        r, c = divmod(int(y), 5)
        xs[i, 0, 4 + r * 12:14 + r * 12, 2 + c * 5:7 + c * 5] += 0.8
    return xs, ys


x, y = synthetic_mnist()
split = 384
train = gluon.data.DataLoader(
    gluon.data.ArrayDataset(mx.nd.array(x[:split]), mx.nd.array(y[:split])),
    batch_size=64, shuffle=True)
val_x, val_y = mx.nd.array(x[split:]), y[split:]

# The classic LeNet-ish tower.
net = nn.HybridSequential()
net.add(nn.Conv2D(8, kernel_size=3, activation="relu"),
        nn.MaxPool2D(pool_size=2, strides=2),
        nn.Conv2D(16, kernel_size=3, activation="relu"),
        nn.MaxPool2D(pool_size=2, strides=2),
        nn.Flatten(),
        nn.Dense(64, activation="relu"),
        nn.Dense(10))
net.initialize(mx.init.Xavier())
net.hybridize()

loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 2e-3})

for epoch in range(4):
    cum = 0.0
    for bx, by in train:
        with mx.autograd.record():
            loss = loss_fn(net(bx), by)
        loss.backward()
        trainer.step(bx.shape[0])
        cum += loss.mean().asscalar()

pred = net(val_x).asnumpy().argmax(axis=1)
acc = (pred == val_y).mean()
assert acc > 0.7, acc
print("mnist tutorial: OK (val acc=%.3f)" % acc)
