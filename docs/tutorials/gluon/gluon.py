"""Gluon — define, initialize, and train a network imperatively.

Runnable tutorial (reference: docs/tutorials/gluon/gluon.md).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn

rng = np.random.RandomState(0)

# --- define --------------------------------------------------------------
# Sequential stacks layers; shapes are deferred until the first batch.
net = nn.Sequential()
net.add(nn.Dense(16, activation="relu"),
        nn.Dense(8, activation="relu"),
        nn.Dense(2))
net.initialize(mx.init.Xavier())

# --- data ----------------------------------------------------------------
n = 256
x = rng.randn(n, 6).astype(np.float32)
y = (x.sum(axis=1) > 0).astype(np.int32)
dataset = gluon.data.ArrayDataset(mx.nd.array(x), mx.nd.array(y))
loader = gluon.data.DataLoader(dataset, batch_size=32, shuffle=True)

# --- train ---------------------------------------------------------------
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.2})

for epoch in range(3):
    total = 0.0
    for bx, by in loader:
        with mx.autograd.record():
            out = net(bx)
            loss = loss_fn(out, by)
        loss.backward()
        trainer.step(bx.shape[0])
        total += loss.mean().asscalar()

# --- evaluate ------------------------------------------------------------
pred = net(mx.nd.array(x)).asnumpy().argmax(axis=1)
acc = (pred == y).mean()
assert acc > 0.85, acc

# Parameters are inspectable by name.
params = net.collect_params()
assert any(k.endswith("weight") for k in params)

print("gluon tutorial: OK (acc=%.3f)" % acc)
