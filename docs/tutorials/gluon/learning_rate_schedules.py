"""Learning-rate schedules — built-ins, warmup, and custom shapes.

Runnable tutorial (reference:
docs/tutorials/gluon/learning_rate_schedules.md).
"""
import math

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, lr_scheduler
from mxnet_tpu.gluon import nn

# --- built-in schedules --------------------------------------------------
# The decay applies after each COMPLETE period of `step` updates
# (num_update counts from 1, matching the reference's semantics):
fs = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
assert fs(1) == 1.0 and fs(10) == 1.0
assert fs(11) == 0.5 and fs(21) == 0.25

ms = lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1,
                                       base_lr=1.0)
assert ms(4) == 1.0 and abs(ms(6) - 0.1) < 1e-9 and abs(ms(16) - 0.01) < 1e-9

ps = lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0, pwr=2)
assert ps(0) == 1.0 and ps(100) < 1e-6

cs = lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0,
                                  final_lr=0.1)
assert abs(cs(50) - (0.1 + 0.9 * (1 + math.cos(math.pi / 2)) / 2)) < 1e-6

# Warmup ramps from warmup_begin_lr to base_lr over warmup_steps.
ws = lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0,
                                  warmup_steps=10, warmup_begin_lr=0.0)
assert ws(0) == 0.0 and ws(5) == 0.5 and abs(ws(10) - 1.0) < 1e-9


# --- custom schedules are just callables ---------------------------------
class TriangularSchedule:
    def __init__(self, min_lr, max_lr, cycle_length):
        self.min_lr, self.max_lr = min_lr, max_lr
        self.cycle = cycle_length

    def __call__(self, t):
        t = t % self.cycle
        half = self.cycle / 2
        frac = t / half if t < half else (self.cycle - t) / half
        return self.min_lr + (self.max_lr - self.min_lr) * frac


tri = TriangularSchedule(0.1, 1.0, 20)
assert tri(0) == 0.1 and tri(10) == 1.0 and abs(tri(15) - 0.55) < 1e-9

# --- wiring a schedule into training -------------------------------------
net = nn.Dense(2)
net.initialize()
net(mx.nd.zeros((1, 4)))
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 1.0,
                         "lr_scheduler": lr_scheduler.FactorScheduler(
                             step=2, factor=0.5, base_lr=1.0)})
x = mx.nd.array(np.random.RandomState(0).rand(4, 4).astype(np.float32))
for step in range(5):
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(4)
# update 5 starts the third period of 2: the lr has halved twice
assert abs(trainer.learning_rate - 0.25) < 1e-9

print("learning_rate_schedules tutorial: OK")
