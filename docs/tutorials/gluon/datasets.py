"""Datasets and DataLoader — the Gluon data pipeline.

Runnable tutorial (reference: docs/tutorials/gluon/datasets.md).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.data.vision import transforms

rng = np.random.RandomState(0)

# --- Dataset: indexable samples ------------------------------------------
x = mx.nd.array(rng.rand(20, 3, 8, 8).astype(np.float32))
y = mx.nd.array(rng.randint(0, 4, 20).astype(np.float32))
ds = gluon.data.ArrayDataset(x, y)
assert len(ds) == 20
sample_x, sample_y = ds[5]
assert sample_x.shape == (3, 8, 8)

# --- transforms: composable per-sample functions -------------------------
tf = transforms.Compose([
    transforms.Cast("float32"),
    transforms.Normalize(mean=0.5, std=0.25),
])
tds = ds.transform_first(tf)
tx, _ = tds[0]
assert abs(float(tx.asnumpy().mean())) < 2.0

# --- DataLoader: batching + shuffling ------------------------------------
loader = gluon.data.DataLoader(tds, batch_size=8, shuffle=True,
                               last_batch="keep")
shapes = [bx.shape[0] for bx, _ in loader]
assert sorted(shapes) == [4, 8, 8]     # 20 = 8 + 8 + 4 with keep

# Samplers customize iteration order.
seq = list(gluon.data.SequentialSampler(5))
assert seq == [0, 1, 2, 3, 4]
rnd = list(gluon.data.RandomSampler(5))
assert sorted(rnd) == seq

batched = list(gluon.data.BatchSampler(
    gluon.data.SequentialSampler(10), batch_size=4, last_batch="discard"))
assert batched == [[0, 1, 2, 3], [4, 5, 6, 7]]

# --- a custom Dataset -----------------------------------------------------
class SquaresDataset(gluon.data.Dataset):
    def __len__(self):
        return 6

    def __getitem__(self, i):
        return mx.nd.full((1,), float(i)), mx.nd.full((1,), float(i * i))


sq = SquaresDataset()
xs, ys = zip(*[sq[i] for i in range(len(sq))])
assert ys[3].asscalar() == 9.0

print("datasets tutorial: OK")
