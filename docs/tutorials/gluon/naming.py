"""Parameter and block naming.

Runnable tutorial (reference: docs/tutorials/gluon/naming.md).  Names
are the checkpoint contract: save/load and export match parameters BY
NAME, so understanding prefixes avoids the classic
"Parameter not found" surprises.
"""
import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn

# Every block gets a unique auto-prefix ("dense0_", "dense1_", ...).
d0, d1 = nn.Dense(2), nn.Dense(2)
assert d0.prefix != d1.prefix

# Child blocks created inside name_scope() nest their parent's prefix.
class Model(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.encoder = nn.Dense(8)
            self.head = nn.Dense(2)

    def hybrid_forward(self, F, x):
        return self.head(self.encoder(x))


m = Model(prefix="model_")
assert m.encoder.prefix.startswith("model_")
m.initialize()
m(mx.nd.zeros((1, 4)))
names = sorted(m.collect_params().keys())
assert all(n.startswith("model_") for n in names)

# Two instances with the SAME explicit prefix share parameter NAMES —
# which is what lets a checkpoint from one load into the other.
import os, tempfile
a = Model(prefix="shared_")
b = Model(prefix="shared_")
a.initialize()
a(mx.nd.zeros((1, 4)))
pfile = os.path.join(tempfile.mkdtemp(), "m.params")
a.save_parameters(pfile)
b.load_parameters(pfile)   # names line up exactly
assert (b.encoder.weight.data().asnumpy()
        == a.encoder.weight.data().asnumpy()).all()

# params.get() inside name_scope applies the full prefix chain.
assert m.encoder.weight.name == m.encoder.prefix + "weight"

print("naming tutorial: OK")
