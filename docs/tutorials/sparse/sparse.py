"""Sparse NDArrays — row_sparse and CSR.

Runnable tutorial (reference: docs/tutorials/sparse/*.md).
row_sparse holds (indices, values) for a few touched rows of a huge
logical array (embedding gradients); CSR holds (data, indices, indptr)
for general sparsity (bag-of-words features).  Neither materializes
its dense form unless a dense consumer forces it.
"""
import numpy as np

import mxnet_tpu as mx

# --- row_sparse ----------------------------------------------------------
dense_shape = (1000, 4)
idx = mx.nd.array([3, 497], dtype="int64")
vals = mx.nd.array([[1, 1, 1, 1], [2, 2, 2, 2]], dtype="float32")
rs = mx.nd.sparse.row_sparse_array((vals, idx), shape=dense_shape)
assert rs.stype == "row_sparse"
assert (rs.indices.asnumpy() == [3, 497]).all()

# retain() selects a subset of rows without densifying.
kept = mx.nd.sparse.retain(rs, mx.nd.array([497], dtype="int64"))
assert kept.indices.asnumpy().tolist() == [497]

# Conversion to dense happens only on demand.
dense = rs.tostype("default")
assert dense.shape == dense_shape and dense[3, 0].asscalar() == 1.0

# Optimizers consume row_sparse gradients lazily: with
# lazy_update=True, SGD touches ONLY the gradient's rows — the
# embedding-table update path (Trainer does this automatically for
# Embedding(sparse_grad=True)).
w = mx.nd.ones(dense_shape)
g = mx.nd.sparse.row_sparse_array(
    (mx.nd.ones((1, 4)), mx.nd.array([3], dtype="int64")),
    shape=dense_shape)
opt = mx.optimizer.SGD(learning_rate=0.5, lazy_update=True)
state = opt.create_state(0, w)
opt.update(0, w, g, state)
assert w[3, 0].asscalar() == 0.5 and w[4, 0].asscalar() == 1.0

# --- CSR -----------------------------------------------------------------
# (data, indices, indptr): row i's nonzeros live at data[indptr[i]:
# indptr[i+1]] in columns indices[...].
data = np.array([10, 20, 30], np.float32)
indices = np.array([0, 2, 1], np.int64)
indptr = np.array([0, 2, 2, 3], np.int64)
csr = mx.nd.sparse.csr_matrix((data, indices, indptr), shape=(3, 3))
assert csr.stype == "csr"
want = np.array([[10, 0, 20], [0, 0, 0], [0, 30, 0]], np.float32)
assert (csr.tostype("default").asnumpy() == want).all()

# Sparse-dense dot runs O(nnz * k) gather + segment-sum kernels — the
# dense (m, n) product is never materialized.
rhs = mx.nd.array(np.arange(6).reshape(3, 2).astype(np.float32))
prod = mx.nd.sparse.dot(csr, rhs)
assert np.allclose(prod.asnumpy(), want @ rhs.asnumpy())

# Round-trip through scipy-style construction from a dense array:
csr2 = mx.nd.array(want).tostype("csr")
assert (csr2.indptr.asnumpy() == indptr).all()

print("sparse tutorial: OK")
