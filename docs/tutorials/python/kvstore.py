"""KVStore — the shared parameter store behind data parallelism.

Runnable tutorial (reference: docs/tutorials/python/kvstore.md).  On
TPU meshes, gradient aggregation usually rides GSPMD all-reduces
(docs/faq/distributed_training.md); the KVStore API remains for
reference-style training loops and the dist_* process modes.
"""
import numpy as np

import mxnet_tpu as mx

# --- init / push / pull --------------------------------------------------
kv = mx.kv.create("local")
shape = (2, 3)
kv.init(3, mx.nd.ones(shape))

out = mx.nd.zeros(shape)
kv.pull(3, out=out)
assert (out.asnumpy() == 1).all()

# push aggregates (sums) what workers send before the next pull.
kv.push(3, mx.nd.ones(shape) * 8)
kv.pull(3, out=out)
assert (out.asnumpy() == 8).all()

# A list push aggregates all entries: the data-parallel gradient sum.
kv.push(3, [mx.nd.ones(shape) * w for w in (1, 2, 3)])
kv.pull(3, out=out)
assert (out.asnumpy() == 6).all()

# --- updaters ------------------------------------------------------------
# set_updater installs the merge rule applied at push time — this is
# where a server-side optimizer hooks in.
kv2 = mx.kv.create("local")
kv2.init("w", mx.nd.zeros(shape))


def sgd_update(key, grad, weight):
    weight[:] = weight - 0.1 * grad


kv2.set_updater(sgd_update)
kv2.push("w", mx.nd.ones(shape))
kv2.pull("w", out=out)
assert np.allclose(out.asnumpy(), -0.1)

# --- string keys and multiple tensors -----------------------------------
kv3 = mx.kv.create("local")
kv3.init(["a", "b"], [mx.nd.ones((2,)), mx.nd.zeros((2,))])
outs = [mx.nd.zeros((2,)), mx.nd.zeros((2,))]
kv3.pull(["a", "b"], out=outs)
assert outs[0].asnumpy().sum() == 2

# Gradient compression (2-bit with error feedback) switches on per
# kvstore: kv.set_gradient_compression({"type": "2bit", "threshold": .5})
print("kvstore tutorial: OK")
