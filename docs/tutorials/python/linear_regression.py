"""Linear regression — the smallest end-to-end symbolic model.

Runnable tutorial (reference: docs/tutorials/python/linear-regression.md).
"""
import numpy as np

import mxnet_tpu as mx

rng = np.random.RandomState(0)

# y = 2*x0 - 3.4*x1 + 4.2 + noise
n = 400
x = rng.rand(n, 2).astype(np.float32)
w_true, b_true = np.array([2.0, -3.4], np.float32), 4.2
y = x @ w_true + b_true + rng.randn(n).astype(np.float32) * 0.01

train_iter = mx.io.NDArrayIter(x[:300], y[:300], batch_size=25,
                               shuffle=True, label_name="lin_reg_label")
eval_iter = mx.io.NDArrayIter(x[300:], y[300:], batch_size=25,
                              label_name="lin_reg_label")

# The model: one FullyConnected(1) + an L2 regression head.
data = mx.sym.Variable("data")
label = mx.sym.Variable("lin_reg_label")
pred = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
net = mx.sym.LinearRegressionOutput(pred, label, name="lro")

mod = mx.mod.Module(net, data_names=["data"],
                    label_names=["lin_reg_label"], context=mx.cpu())
mod.fit(train_iter, eval_data=eval_iter, optimizer="sgd",
        optimizer_params={"learning_rate": 0.3, "momentum": 0.9},
        eval_metric="mse", num_epoch=20)

# The learned parameters recover the generating ones.
args, _ = mod.get_params()
w = args["fc_weight"].asnumpy().ravel()
b = args["fc_bias"].asnumpy()[0]
assert np.allclose(w, w_true, atol=0.1), w
assert abs(b - b_true) < 0.1, b

eval_iter.reset()
mse = mod.score(eval_iter, mx.metric.MSE())[0][1]
assert mse < 1e-2, mse

print("linear_regression tutorial: OK (w=%s b=%.2f mse=%.4f)"
      % (np.round(w, 2), b, mse))
