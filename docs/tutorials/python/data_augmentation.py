"""Image data augmentation — the mx.image augmenter toolbox.

Runnable tutorial (reference: docs/tutorials/python/
data_augmentation.md), on a synthetic image so it runs hermetically.
"""
import numpy as np

import mxnet_tpu as mx

rng = np.random.RandomState(0)
img = mx.nd.array(rng.randint(0, 255, (64, 48, 3)).astype(np.uint8))

# --- positional augmenters ----------------------------------------------
resized = mx.image.imresize(img, 32, 32)
assert resized.shape == (32, 32, 3)

crop, rect = mx.image.random_crop(img, (24, 24))
assert crop.shape == (24, 24, 3) and rect[2:] == (24, 24)

center, _ = mx.image.center_crop(img, (24, 24))
assert center.shape == (24, 24, 3)

# --- color augmenters ----------------------------------------------------
f = img.astype(np.float32)
bright = mx.image.BrightnessJitterAug(brightness=0.3)(f)
contrast = mx.image.ContrastJitterAug(contrast=0.3)(f)
sat = mx.image.SaturationJitterAug(saturation=0.3)(f)
for out in (bright, contrast, sat):
    assert out.shape == f.shape

# --- composing a standard training pipeline ------------------------------
# CreateAugmenter builds the reference's usual chain: resize, crop,
# mirror, color jitter, mean/std normalize, CHW cast.
augs = mx.image.CreateAugmenter(
    data_shape=(3, 32, 32), rand_crop=True, rand_mirror=True,
    brightness=0.1, contrast=0.1, saturation=0.1,
    mean=np.array([123.68, 116.28, 103.53]),
    std=np.array([58.395, 57.12, 57.375]))
out = f
for aug in augs:
    out = aug(out)
# channel-last float output at the target spatial size, normalized
arr = out.asnumpy() if hasattr(out, "asnumpy") else np.asarray(out)
assert arr.shape == (32, 32, 3)
assert abs(arr.mean()) < 3.0  # roughly zero-centered after normalize

# Detection-aware augmenters (joint image+bbox transforms) are the
# same idea with labels threaded through: see
# docs/faq/detection_workflow.md and mx.image.CreateDetAugmenter.
print("data_augmentation tutorial: OK")
