"""Matrix factorization — embeddings for recommendation.

Runnable tutorial (reference:
docs/tutorials/python/matrix_factorization.md), on a synthetic
low-rank rating matrix.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn

rng = np.random.RandomState(0)

# Ground truth: users x items ratings from rank-4 factors.
n_users, n_items, k_true = 40, 30, 4
U = rng.randn(n_users, k_true).astype(np.float32) * 0.5
V = rng.randn(n_items, k_true).astype(np.float32) * 0.5
ratings = U @ V.T

# Observed triples (u, i, r): 60% of the matrix.
mask = rng.rand(n_users, n_items) < 0.6
users, items = np.nonzero(mask)
r = ratings[users, items]


class MF(gluon.HybridBlock):
    """score(u, i) = <user_embed[u], item_embed[i]>"""

    def __init__(self, k=8, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = nn.Embedding(n_users, k)
            self.item = nn.Embedding(n_items, k)

    def hybrid_forward(self, F, u, i):
        return F.sum(self.user(u) * self.item(i), axis=-1)


net = MF()
net.initialize(mx.init.Normal(0.1))
net.hybridize()
loss_fn = gluon.loss.L2Loss()
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.02})

u_nd = mx.nd.array(users, dtype="int32")
i_nd = mx.nd.array(items, dtype="int32")
r_nd = mx.nd.array(r)

first = last = None
for epoch in range(60):
    with mx.autograd.record():
        loss = loss_fn(net(u_nd, i_nd), r_nd).mean()
    loss.backward()
    trainer.step(len(users))
    val = loss.asscalar()
    first = val if first is None else first
    last = val
assert last < 0.25 * first, (first, last)

# Held-out reconstruction correlates with the truth.
hu, hi = np.nonzero(~mask)
pred = net(mx.nd.array(hu, dtype="int32"),
           mx.nd.array(hi, dtype="int32")).asnumpy()
corr = np.corrcoef(pred, ratings[hu, hi])[0, 1]
assert corr > 0.5, corr
print("matrix_factorization tutorial: OK (held-out corr=%.2f)" % corr)
