"""ONNX — exporting and importing models.

Runnable tutorial (reference: docs/tutorials/onnx/*.md).  The codec is
self-contained (no onnx package needed): export a trained Gluon net,
inspect the model metadata, re-import it, and check numerical
equality.
"""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.quantization import _trace_block
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.block import SymbolBlock

tmp = tempfile.mkdtemp()
rng = np.random.RandomState(0)

# A small convnet, as if just trained.
net = nn.HybridSequential()
net.add(nn.Conv2D(6, kernel_size=3, padding=1, activation="relu"),
        nn.MaxPool2D(2, 2), nn.Flatten(), nn.Dense(4))
net.initialize(mx.init.Xavier())
x = rng.rand(1, 3, 8, 8).astype(np.float32)
want = net(mx.nd.array(x)).asnumpy()

# --- export --------------------------------------------------------------
# Trace the block to (symbol, params), then export_model writes the
# .onnx file.
sym, params = _trace_block(net, [mx.sym.Variable("data")], [x.shape])
onnx_path = os.path.join(tmp, "convnet.onnx")
onnx_mxnet.export_model(sym, params, [x.shape], np.float32, onnx_path)
assert os.path.getsize(onnx_path) > 0

# --- metadata ------------------------------------------------------------
meta = onnx_mxnet.get_model_metadata(onnx_path)
assert meta["input_tensor_data"][0][1] == x.shape

# --- import --------------------------------------------------------------
sym2, arg2, aux2 = onnx_mxnet.import_model(onnx_path)
allp = dict(arg2)
allp.update(aux2)
net2 = SymbolBlock(sym2, [mx.sym.Variable("data")], params=allp)
got = net2(mx.nd.array(x))
got = (got[0] if isinstance(got, (list, tuple)) else got).asnumpy()
assert np.allclose(got, want, atol=1e-4), np.abs(got - want).max()

# Full model-zoo round-trips (resnet50/mobilenet/squeezenet) are pinned
# in tests/test_onnx.py::test_onnx_roundtrip_model_zoo_full.
print("onnx export/import tutorial: OK")
