"""Connectionist Temporal Classification — alignment-free sequence
labeling.

Runnable tutorial (reference: docs/tutorials/speech_recognition/ctc.md
and the reference's warp-CTC example: an acoustic model emits one
distribution per frame, CTC sums over all alignments of the label
sequence, so no frame-level alignment is needed).

Here the "speech" is synthetic: each label id leaves a distinctive
pattern across a stretch of frames, and a BiLSTM + CTC learns to read
the label sequence out.  Training uses the fused
``parallel.GluonTrainStep`` — forward, CTC, backward, and the optimizer
update compile into ONE program, which is the TPU-native way to run a
train loop (and ~500x faster than eager stepping for a small RNN).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.parallel import GluonTrainStep

rng = np.random.RandomState(1)
T, B, N_CLASS, L = 16, 16, 5, 4   # frames, batch, classes, label seq len
BLANK = N_CLASS - 1               # gluon CTCLoss: blank is the LAST class

def make_batch():
    feats = rng.randn(T, B, 8).astype(np.float32) * 0.1
    # no adjacent repeats: a greedy frame-wise decode collapses repeated
    # labels unless the model emits a separating blank, which this toy
    # task gives it no reason to learn
    labels = np.zeros((B, L), np.float32)
    for b in range(B):
        seq = [rng.randint(0, BLANK)]
        while len(seq) < L:
            c = rng.randint(0, BLANK)
            if c != seq[-1]:
                seq.append(c)
        labels[b] = seq
    frames_per = T // L
    for b in range(B):
        for i in range(L):
            # each label imprints its id as a bias on its frame stretch
            sl = slice(i * frames_per, (i + 1) * frames_per)
            feats[sl, b, int(labels[b, i])] += 3.0
    return feats, labels


# acoustic model: BiLSTM over frames, per-frame class scores
net = gluon.nn.HybridSequential()
net.add(gluon.rnn.LSTM(12, bidirectional=True),
        gluon.nn.Dense(N_CLASS, flatten=False))
net.initialize(mx.init.Xavier())
net(mx.nd.zeros((T, B, 8)))  # resolve deferred shapes before staging

ctc = gluon.loss.CTCLoss(layout="TNC", label_layout="NT")
step = GluonTrainStep(net, ctc, lr=0.05, momentum=0.9)

first = last = None
for _ in range(400):
    feats, labels = make_batch()
    cur = float(np.asarray(step(feats, labels)))
    first = cur if first is None else first
    last = cur

# write the trained jax params back into the Gluon Parameters so the
# normal imperative API (and save_parameters) sees them
step.sync_to_params()

# greedy decode: argmax per frame, collapse repeats, drop blanks
feats, labels = make_batch()
pred = net(mx.nd.array(feats)).argmax(axis=2).asnumpy().T  # (B, T)
correct = 0
for b in range(B):
    seq, prev = [], -1
    for t in range(T):
        c = int(pred[b, t])
        if c != prev and c != BLANK:
            seq.append(c)
        prev = c
    if seq == [int(v) for v in labels[b]]:
        correct += 1
assert last < first * 0.1, (first, last)
assert correct >= B * 3 // 4, correct
print("OK CTC: loss %.2f -> %.2f; exact decode on %d/%d sequences"
      % (first, last, correct, B))
