"""Data iterators — NDArrayIter, RecordIO, and custom iterators.

Runnable tutorial (reference: docs/tutorials/basic/data.md).
"""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx

rng = np.random.RandomState(0)

# --- NDArrayIter: in-memory arrays -> batches ----------------------------
x = rng.rand(10, 3).astype(np.float32)
y = np.arange(10, dtype=np.float32)
it = mx.io.NDArrayIter(x, y, batch_size=4, shuffle=False,
                       last_batch_handle="pad")
batches = list(it)
assert len(batches) == 3
assert batches[0].data[0].shape == (4, 3)
assert batches[-1].pad == 2           # 10 % 4 -> last batch pads 2

# --- RecordIO: the packed on-disk format ---------------------------------
# pack() frames (header, payload) records; MXIndexedRecordIO adds an
# .idx for random access — the format im2rec.py produces at scale.
from mxnet_tpu.recordio import (IRHeader, MXIndexedRecordIO, pack, unpack)

tmp = tempfile.mkdtemp()
rec_path = os.path.join(tmp, "toy.rec")
rec = MXIndexedRecordIO(os.path.join(tmp, "toy.idx"), rec_path, "w")
for i in range(5):
    payload = rng.rand(6).astype(np.float32).tobytes()
    rec.write_idx(i, pack(IRHeader(0, float(i), i, 0), payload))
rec.close()

reader = MXIndexedRecordIO(os.path.join(tmp, "toy.idx"), rec_path, "r")
hdr, payload = unpack(reader.read_idx(3))
assert hdr.label == 3.0 and len(payload) == 24
reader.close()

# --- custom iterators ----------------------------------------------------
# Any object with provide_data/provide_label and __next__ returning
# DataBatch plugs into Module.fit and Gluon loops alike.
class EvenNumbersIter(mx.io.DataIter):
    def __init__(self, batch_size=4, total=16):
        super().__init__(batch_size)
        self.total, self.cur = total, 0

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", (self.batch_size, 1))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("label", (self.batch_size,))]

    def reset(self):
        self.cur = 0

    def next(self):
        if self.cur >= self.total:
            raise StopIteration
        base = np.arange(self.cur, self.cur + self.batch_size) * 2.0
        self.cur += self.batch_size
        return mx.io.DataBatch(
            data=[mx.nd.array(base[:, None])],
            label=[mx.nd.array(base % 4 == 0)], pad=0)

count = sum(1 for _ in EvenNumbersIter())
assert count == 4

print("data tutorial: OK")
