"""NDArray indexing — slices, steps, fancy and boolean indexing.

Runnable tutorial (reference: docs/tutorials/basic/ndarray_indexing.md).
"""
import numpy as np

import mxnet_tpu as mx

x = mx.nd.arange(24).reshape((2, 3, 4))

# Basic slicing mirrors numpy, including negative indices and steps.
assert x[1].shape == (3, 4)
assert x[1, 2].shape == (4,)
assert x[-1, -1, -1].asscalar() == 23.0
assert (x[0, :, 1::2].asnumpy() == np.arange(24).reshape(2, 3, 4)[0, :, 1::2]).all()

# Slice assignment writes through.
y = x.copy()
y[0, 0] = -1
assert (y[0, 0].asnumpy() == -1).all()
y[1, :, ::2] = 0
assert y[1, 2, 2].asscalar() == 0.0

# Integer-array (fancy) indexing gathers rows.
idx = mx.nd.array([1, 0], dtype="int32")
taken = mx.nd.take(x, idx, axis=0)
assert (taken[0].asnumpy() == x[1].asnumpy()).all()

# Boolean masks select elements (flattened result, like numpy).
v = mx.nd.array([1.0, -2.0, 3.0, -4.0])
mask = v > 0
positives = v.asnumpy()[mask.asnumpy().astype(bool)]
assert (positives == [1.0, 3.0]).all()

# where() keeps everything on-device for conditional selection.
clipped = mx.nd.where(v > 0, v, mx.nd.zeros_like(v))
assert (clipped.asnumpy() == [1.0, 0.0, 3.0, 0.0]).all()

print("ndarray_indexing tutorial: OK")
