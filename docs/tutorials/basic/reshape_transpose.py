"""Reshape vs transpose — layout changes that are (and aren't) free.

Runnable tutorial (reference: docs/tutorials/basic/reshape_transpose.md).
reshape reinterprets the same row-major buffer; transpose permutes
axes and therefore reorders data.  Under XLA both become layout
operations the compiler can often fuse away — but semantically they
are different functions, easy to confuse.
"""
import numpy as np

import mxnet_tpu as mx

x = mx.nd.arange(6).reshape((2, 3))

# reshape: same element ORDER, new shape.
r = x.reshape((3, 2))
assert (r.asnumpy().ravel() == np.arange(6)).all()

# transpose: rows become columns — different element order.
t = x.T
assert t.shape == (3, 2)
assert not (t.asnumpy() == r.asnumpy()).all()
assert (t.asnumpy() == np.arange(6).reshape(2, 3).T).all()

# Special reshape codes from the reference API:
#   0  copy the input dimension
#  -1  infer from the remaining elements
y = mx.nd.zeros((4, 5, 6))
assert y.reshape((0, -1)).shape == (4, 30)
assert y.reshape((-1, 6)).shape == (20, 6)

# A common real case: NCHW <-> NHWC needs transpose, NOT reshape.
img = mx.nd.random.uniform(shape=(1, 3, 4, 4))       # NCHW
nhwc = img.transpose((0, 2, 3, 1))
assert nhwc.shape == (1, 4, 4, 3)
back = nhwc.transpose((0, 3, 1, 2))
assert np.allclose(back.asnumpy(), img.asnumpy())
wrong = img.reshape((1, 4, 4, 3))                     # legal, but scrambled
assert not np.allclose(wrong.asnumpy(), nhwc.asnumpy())

print("reshape_transpose tutorial: OK")
