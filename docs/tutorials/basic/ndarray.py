"""NDArray — imperative tensors on CPU/TPU.

Runnable tutorial (reference: docs/tutorials/basic/ndarray.md).  The
NDArray is the imperative workhorse: create, compute, inspect — every
op dispatches to a jit-cached XLA executable, so a steady-state loop
runs compiled code even without `hybridize()`.
"""
import numpy as np

import mxnet_tpu as mx

# --- creating arrays -----------------------------------------------------
# From Python lists / numpy, or with fill constructors.
a = mx.nd.array([[1, 2, 3], [4, 5, 6]])
b = mx.nd.ones((2, 3))
c = mx.nd.full((2, 3), 7.0)
z = mx.nd.zeros((2, 3))
assert a.shape == (2, 3) and a.dtype == np.float32

# Random constructors mirror the reference's mx.nd.random namespace.
r = mx.nd.random.uniform(0, 1, shape=(2, 3))
n = mx.nd.random.normal(0, 1, shape=(2, 3))

# --- arithmetic ----------------------------------------------------------
# Operators are elementwise; broadcasting follows numpy rules.
d = a * b + c
assert (d.asnumpy() == a.asnumpy() + 7).all()
e = a * mx.nd.array([10.0, 100.0, 1000.0])   # broadcast over rows
assert e[1, 2].asscalar() == 6000.0

# Matrix product via nd.dot:
f = mx.nd.dot(a, a.T)
assert f.shape == (2, 2)

# --- dtype control -------------------------------------------------------
# astype converts; float16/bfloat16 are first-class on TPU.
h = a.astype("float16")
assert h.dtype == np.float16

# --- device context ------------------------------------------------------
# Arrays live on a Context: mx.cpu() or mx.tpu(i).  copyto / as_in_context
# move data; ops run where their inputs live.
x_cpu = mx.nd.ones((2, 2), ctx=mx.cpu())
assert x_cpu.context == mx.cpu()
if mx.context.num_tpus():
    x_tpu = x_cpu.as_in_context(mx.tpu())
    assert x_tpu.context.device_type == "tpu"

# --- conversion ----------------------------------------------------------
# .asnumpy() materializes on the host (a synchronization point);
# .asscalar() for size-1 arrays.
assert isinstance(d.asnumpy(), np.ndarray)
assert mx.nd.array([3.5]).asscalar() == 3.5

# --- in-place and views --------------------------------------------------
g = mx.nd.zeros((3,))
g[:] = 5          # in-place assign
g += 1
assert (g.asnumpy() == 6).all()

print("ndarray tutorial: OK")
