"""Symbol — the declarative graph API.

Runnable tutorial (reference: docs/tutorials/basic/symbol.md).  A
Symbol describes computation without running it; `bind` pairs it with
argument arrays into an Executor.  On TPU the whole bound graph
compiles to ONE XLA computation — the reference's GraphExecutor
machinery (memory planning, fusion) is owned by the compiler.
"""
import numpy as np

import mxnet_tpu as mx

# --- composing symbols ---------------------------------------------------
a = mx.sym.Variable("a")
b = mx.sym.Variable("b")
c = a + b * 2
assert sorted(c.list_arguments()) == ["a", "b"]

# A small MLP; layer ops auto-create their weight/bias variables.
data = mx.sym.Variable("data")
h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
h = mx.sym.Activation(h, act_type="relu", name="relu1")
net = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
assert "fc1_weight" in net.list_arguments()

# --- shape/type inference ------------------------------------------------
arg_shapes, out_shapes, _ = net.infer_shape(data=(4, 10))
assert out_shapes[0] == (4, 3)

# --- binding and running -------------------------------------------------
rng = np.random.RandomState(0)
exe = net.simple_bind(ctx=mx.cpu(), data=(4, 10))
exe.arg_dict["data"][:] = rng.rand(4, 10)
for name in ("fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"):
    exe.arg_dict[name][:] = rng.rand(*exe.arg_dict[name].shape) * 0.1
out = exe.forward(is_train=False)[0]
assert out.shape == (4, 3)

# --- gradients through the executor -------------------------------------
exe2 = net.simple_bind(ctx=mx.cpu(), data=(4, 10), grad_req="write")
for k, v in exe.arg_dict.items():
    v.copyto(exe2.arg_dict[k])
exe2.forward(is_train=True)
exe2.backward(mx.nd.ones((4, 3)))
assert exe2.grad_dict["fc1_weight"].shape == exe2.arg_dict["fc1_weight"].shape

# --- serialization -------------------------------------------------------
js = net.tojson()
net2 = mx.sym.load_json(js)
assert net2.list_arguments() == net.list_arguments()

print("symbol tutorial: OK")
