"""Module — the high-level symbolic training loop.

Runnable tutorial (reference: docs/tutorials/basic/module.md).
Module wraps a Symbol with bind / init / fit / predict / score, the
reference's classic training interface.
"""
import logging

import numpy as np

import mxnet_tpu as mx

rng = np.random.RandomState(0)

# A separable toy problem: 2 classes split by a hyperplane.
n = 512
x = rng.randn(n, 10).astype(np.float32)
w_true = rng.randn(10).astype(np.float32)
y = (x @ w_true > 0).astype(np.float32)

train_iter = mx.io.NDArrayIter(x[:384], y[:384], batch_size=32,
                               shuffle=True, label_name="softmax_label")
val_iter = mx.io.NDArrayIter(x[384:], y[384:], batch_size=32,
                             label_name="softmax_label")

data = mx.sym.Variable("data")
h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
h = mx.sym.Activation(h, act_type="relu")
h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(h, name="softmax")

mod = mx.mod.Module(net, data_names=["data"],
                    label_names=["softmax_label"], context=mx.cpu())
mod.fit(train_iter, eval_data=val_iter, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        eval_metric="acc", num_epoch=8)

# predict returns stacked outputs; score runs a metric over a dataset.
val_iter.reset()
probs = mod.predict(val_iter)
assert probs.shape == (128, 2)
val_iter.reset()
acc = mod.score(val_iter, mx.metric.Accuracy())[0][1]
assert acc > 0.8, acc

# Checkpointing: save_checkpoint / load_checkpoint round-trip.
import tempfile, os
prefix = os.path.join(tempfile.mkdtemp(), "mlp")
mod.save_checkpoint(prefix, 8)
sym2, args2, auxs2 = mx.model.load_checkpoint(prefix, 8)
assert "fc1_weight" in args2

logging.info("module tutorial accuracy: %.3f", acc)
print("module tutorial: OK")
