"""Control-flow operators — cond, while_loop, foreach.

Runnable tutorial (reference: docs/tutorials/control_flow/
ControlFlowTutorial.md).  Python `if`/`while` on traced values cannot
be staged into one XLA graph; the control-flow OPERATORS express the
same logic as graph nodes (lowering to lax.cond / lax.while_loop /
lax.scan), so hybridized models keep data-dependent logic on-device.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon

# --- cond: data-dependent branching --------------------------------------
x = mx.nd.array([2.0])
out = mx.nd.contrib.cond(
    lambda: mx.nd.sum(x) > 1,
    lambda: x * 10,
    lambda: x - 1)
assert out.asscalar() == 20.0

# --- while_loop: iterate while a traced predicate holds ------------------
# Carries are (i, acc); max_iterations bounds the trace.
steps, (i_fin, acc_fin) = mx.nd.contrib.while_loop(
    cond=lambda i, acc: i < 5,
    func=lambda i, acc: (None, [i + 1, acc + i]),
    loop_vars=[mx.nd.array([0.0]), mx.nd.array([0.0])],
    max_iterations=10)
assert acc_fin.asscalar() == 0 + 1 + 2 + 3 + 4

# --- foreach: scan over the leading axis --------------------------------
seq = mx.nd.array(np.arange(6).reshape(3, 2).astype(np.float32))


def body(xi, state):
    new = state + xi
    return new, new        # (output_t, new_state)


outs, final = mx.nd.contrib.foreach(body, seq, mx.nd.zeros((2,)))
assert np.allclose(final.asnumpy(), seq.asnumpy().sum(axis=0))
assert outs.shape == (3, 2)

# --- inside a HybridBlock ------------------------------------------------
class CumulRNN(gluon.HybridBlock):
    """A toy recurrent block: state_t = tanh(state + x_t)."""

    def hybrid_forward(self, F, seq):
        def step(xi, state):
            new = F.tanh(state + xi)
            return new, new

        outs, _ = F.contrib.foreach(step, seq,
                                    F.zeros_like(F.slice_axis(
                                        seq, axis=0, begin=0, end=1)
                                    ).reshape((-1,)))
        return outs


net = CumulRNN()
net.initialize()
eager = net(seq).asnumpy()
net.hybridize()
staged = net(seq).asnumpy()
assert np.allclose(eager, staged, atol=1e-6)

print("control_flow tutorial: OK")
