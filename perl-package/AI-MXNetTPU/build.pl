#!/usr/bin/env perl
# Build AI::MXNetTPU's XS library with the flags this perl was built
# with (ExtUtils::Embed) — no Makefile.PL/xsubpp round-trip, the XSUBs
# in xs/mxnettpu_xs.c are written directly against the XS macros.
#
# Usage: perl build.pl   (from this directory; needs gcc + libmxtpu)

use strict;
use warnings;
use Config;
use ExtUtils::Embed ();
use File::Basename qw(dirname);
use File::Spec;

my $here = dirname(File::Spec->rel2abs($0));
my $repo = File::Spec->rel2abs(File::Spec->catdir($here, '..', '..'));
my $native = File::Spec->catdir($repo, 'mxnet_tpu', 'native');
my $inc = File::Spec->catdir($native, 'include');
my $src = File::Spec->catfile($here, 'xs', 'mxnettpu_xs.c');
my $out = File::Spec->catfile($here, 'xs', 'MXNetTPU.so');

my $ccopts = ExtUtils::Embed::ccopts();
chomp $ccopts;

my $cmd = join(' ',
    $Config{cc}, '-shared', '-fPIC', '-O2',
    $ccopts,
    "-I$inc",
    $src,
    "-L$native", '-lmxtpu', "-Wl,-rpath,$native",
    '-o', $out);
print "$cmd\n";
system($cmd) == 0 or die "build failed: $?\n";
print "built $out\n";
