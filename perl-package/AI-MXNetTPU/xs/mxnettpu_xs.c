/* AI::MXNetTPU XS glue — hand-written XSUBs over the tensor-runtime C
 * ABI (mxtpu/c_api.h).
 *
 * Reference analog: perl-package/AI-MXNetCAPI (the SWIG layer under
 * AI::MXNet).  This binding projects the same seam — every call goes
 * through the public MXTPU* C functions, so Perl semantics can never
 * drift from the Python package's (the ABI is one embedded
 * implementation, native/src/embed.cc).
 *
 * Conventions:
 *   - handles cross into Perl as plain UVs;
 *   - any non-zero rc croaks with MXTPUGetLastError() — Perl callers
 *     get exceptions, never silent failures;
 *   - bulk data moves as packed strings (pack "f*"), element counts
 *     follow the ABI's SyncCopy contract.
 *
 * Built by build.pl with the compiler flags ExtUtils::Embed reports;
 * no Makefile.PL/xsubpp needed (the XSUBs are written directly against
 * the XS macros).
 */

#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <stdint.h>
#include <mxtpu/c_api.h>

#define CROAK_ON(rc) do { if ((rc) != 0) \
    croak("mxtpu: %s", MXTPUGetLastError()); } while (0)

#define MAX_DIMS 16
#define MAX_IO 64

static uint32_t read_shape(pTHX_ SV* aref, uint32_t* shape) {
  AV* av;
  I32 i, n;
  if (!SvROK(aref) || SvTYPE(SvRV(aref)) != SVt_PVAV)
    croak("shape must be an array reference");
  av = (AV*)SvRV(aref);
  n = av_len(av) + 1;
  if (n > MAX_DIMS) croak("too many dimensions: %d", (int)n);
  for (i = 0; i < n; i++) {
    SV** e = av_fetch(av, i, 0);
    shape[i] = e ? (uint32_t)SvUV(*e) : 0;
  }
  return (uint32_t)n;
}

XS(xs_nd_create); XS(xs_nd_create) {
  dXSARGS;
  uint32_t shape[MAX_DIMS];
  uint32_t nd;
  MXTPUHandle out;
  if (items != 2) croak("_nd_create(shape_aref, dtype)");
  nd = read_shape(aTHX_ ST(0), shape);
  CROAK_ON(MXTPUNDArrayCreateEx(shape, nd, 1, 0, 0, (int)SvIV(ST(1)),
                                &out));
  ST(0) = sv_2mortal(newSVuv((UV)out));
  XSRETURN(1);
}

XS(xs_nd_free); XS(xs_nd_free) {
  dXSARGS;
  if (items != 1) croak("_nd_free(h)");
  CROAK_ON(MXTPUNDArrayFree((MXTPUHandle)SvUV(ST(0))));
  XSRETURN_EMPTY;
}

XS(xs_nd_shape); XS(xs_nd_shape) {
  dXSARGS;
  uint32_t ndim = 0, i;
  const uint32_t* dims = NULL;
  AV* out;
  if (items != 1) croak("_nd_shape(h)");
  CROAK_ON(MXTPUNDArrayGetShape((MXTPUHandle)SvUV(ST(0)), &ndim, &dims));
  out = newAV();
  for (i = 0; i < ndim; i++) av_push(out, newSVuv(dims[i]));
  ST(0) = sv_2mortal(newRV_noinc((SV*)out));
  XSRETURN(1);
}

XS(xs_nd_set_f32); XS(xs_nd_set_f32) {
  dXSARGS;
  STRLEN len;
  const char* buf;
  if (items != 2) croak("_nd_set_f32(h, packed)");
  buf = SvPVbyte(ST(1), len);
  CROAK_ON(MXTPUNDArraySyncCopyFromCPU((MXTPUHandle)SvUV(ST(0)), buf,
                                       (uint64_t)(len / 4)));
  XSRETURN_EMPTY;
}

XS(xs_nd_get_f32); XS(xs_nd_get_f32) {
  dXSARGS;
  uint32_t ndim = 0, i;
  const uint32_t* dims = NULL;
  uint64_t n = 1;
  SV* out;
  MXTPUHandle h;
  if (items != 1) croak("_nd_get_f32(h)");
  h = (MXTPUHandle)SvUV(ST(0));
  CROAK_ON(MXTPUNDArrayGetShape(h, &ndim, &dims));
  for (i = 0; i < ndim; i++) n *= dims[i];
  out = newSV(n * 4 ? n * 4 : 1);
  SvPOK_on(out);
  CROAK_ON(MXTPUNDArraySyncCopyToCPU(h, SvPVX(out), n));
  SvCUR_set(out, n * 4);
  ST(0) = sv_2mortal(out);
  XSRETURN(1);
}

XS(xs_op_handle); XS(xs_op_handle) {
  dXSARGS;
  MXTPUHandle out;
  if (items != 1) croak("_op_handle(name)");
  CROAK_ON(MXTPUGetOpHandle(SvPVbyte_nolen(ST(0)), &out));
  ST(0) = sv_2mortal(newSVuv((UV)out));
  XSRETURN(1);
}

/* _invoke(op, inputs_aref, keys_aref, vals_aref) -> aref of out handles */
XS(xs_invoke); XS(xs_invoke) {
  dXSARGS;
  AV *in_av, *k_av, *v_av, *out_av;
  MXTPUHandle ins[MAX_IO];
  const char* keys[MAX_IO];
  const char* vals[MAX_IO];
  I32 i, nin, np;
  int n_out = 0;
  MXTPUHandle* outs = NULL;
  if (items != 4) croak("_invoke(op, inputs, keys, vals)");
  in_av = (AV*)SvRV(ST(1));
  k_av = (AV*)SvRV(ST(2));
  v_av = (AV*)SvRV(ST(3));
  nin = av_len(in_av) + 1;
  np = av_len(k_av) + 1;
  if (nin > MAX_IO || np > MAX_IO) croak("too many inputs/params");
  if (np != av_len(v_av) + 1) croak("keys/vals length mismatch");
  for (i = 0; i < nin; i++)
    ins[i] = (MXTPUHandle)SvUV(*av_fetch(in_av, i, 0));
  for (i = 0; i < np; i++) {
    keys[i] = SvPVbyte_nolen(*av_fetch(k_av, i, 0));
    vals[i] = SvPVbyte_nolen(*av_fetch(v_av, i, 0));
  }
  CROAK_ON(MXTPUImperativeInvoke((MXTPUHandle)SvUV(ST(0)), (int)nin, ins,
                                 &n_out, &outs, (int)np, keys, vals));
  out_av = newAV();
  for (i = 0; i < n_out; i++) av_push(out_av, newSVuv((UV)outs[i]));
  ST(0) = sv_2mortal(newRV_noinc((SV*)out_av));
  XSRETURN(1);
}

XS(xs_set_recording); XS(xs_set_recording) {
  dXSARGS;
  int prev = 0;
  if (items != 1) croak("_set_recording(flag)");
  CROAK_ON(MXTPUAutogradSetIsRecording((int)SvIV(ST(0)), &prev));
  ST(0) = sv_2mortal(newSViv(prev));
  XSRETURN(1);
}

XS(xs_set_training); XS(xs_set_training) {
  dXSARGS;
  int prev = 0;
  if (items != 1) croak("_set_training(flag)");
  CROAK_ON(MXTPUAutogradSetIsTraining((int)SvIV(ST(0)), &prev));
  ST(0) = sv_2mortal(newSViv(prev));
  XSRETURN(1);
}

XS(xs_mark_variable); XS(xs_mark_variable) {
  dXSARGS;
  MXTPUHandle var, grad;
  uint32_t req;
  if (items != 3) croak("_mark_variable(h, grad_h, req)");
  var = (MXTPUHandle)SvUV(ST(0));
  grad = (MXTPUHandle)SvUV(ST(1));
  req = (uint32_t)SvUV(ST(2));
  CROAK_ON(MXTPUAutogradMarkVariables(1, &var, &req, &grad));
  XSRETURN_EMPTY;
}

XS(xs_backward); XS(xs_backward) {
  dXSARGS;
  MXTPUHandle h;
  if (items != 2) croak("_backward(h, retain)");
  h = (MXTPUHandle)SvUV(ST(0));
  CROAK_ON(MXTPUAutogradBackward(1, &h, NULL, (int)SvIV(ST(1))));
  XSRETURN_EMPTY;
}

XS(xs_grad); XS(xs_grad) {
  dXSARGS;
  MXTPUHandle out = 0;
  if (items != 1) croak("_grad(h)");
  CROAK_ON(MXTPUNDArrayGetGrad((MXTPUHandle)SvUV(ST(0)), &out));
  ST(0) = sv_2mortal(newSVuv((UV)out));
  XSRETURN(1);
}

XS(xs_wait_all); XS(xs_wait_all) {
  dXSARGS;
  PERL_UNUSED_VAR(items);
  CROAK_ON(MXTPUNDArrayWaitAll());
  XSRETURN_EMPTY;
}

XS(xs_kv_create); XS(xs_kv_create) {
  dXSARGS;
  MXTPUHandle out;
  if (items != 1) croak("_kv_create(type)");
  CROAK_ON(MXTPUKVStoreCreate(SvPVbyte_nolen(ST(0)), &out));
  ST(0) = sv_2mortal(newSVuv((UV)out));
  XSRETURN(1);
}

XS(xs_kv_init); XS(xs_kv_init) {
  dXSARGS;
  int key;
  MXTPUHandle val;
  if (items != 3) croak("_kv_init(kv, key, h)");
  key = (int)SvIV(ST(1));
  val = (MXTPUHandle)SvUV(ST(2));
  CROAK_ON(MXTPUKVStoreInit((MXTPUHandle)SvUV(ST(0)), 1, &key, &val));
  XSRETURN_EMPTY;
}

XS(xs_kv_push); XS(xs_kv_push) {
  dXSARGS;
  int key;
  MXTPUHandle val;
  if (items != 3) croak("_kv_push(kv, key, h)");
  key = (int)SvIV(ST(1));
  val = (MXTPUHandle)SvUV(ST(2));
  CROAK_ON(MXTPUKVStorePush((MXTPUHandle)SvUV(ST(0)), 1, &key, &val, 0));
  XSRETURN_EMPTY;
}

XS(xs_kv_pull); XS(xs_kv_pull) {
  dXSARGS;
  int key;
  MXTPUHandle val;
  if (items != 3) croak("_kv_pull(kv, key, h)");
  key = (int)SvIV(ST(1));
  val = (MXTPUHandle)SvUV(ST(2));
  CROAK_ON(MXTPUKVStorePull((MXTPUHandle)SvUV(ST(0)), 1, &key, &val, 0));
  XSRETURN_EMPTY;
}

XS(xs_last_error); XS(xs_last_error) {
  dXSARGS;
  PERL_UNUSED_VAR(items);
  ST(0) = sv_2mortal(newSVpv(MXTPUGetLastError(), 0));
  XSRETURN(1);
}

XS_EXTERNAL(boot_AI__MXNetTPU);
XS_EXTERNAL(boot_AI__MXNetTPU) {
  dXSARGS;
  PERL_UNUSED_VAR(items);
  newXS("AI::MXNetTPU::_nd_create", xs_nd_create, __FILE__);
  newXS("AI::MXNetTPU::_nd_free", xs_nd_free, __FILE__);
  newXS("AI::MXNetTPU::_nd_shape", xs_nd_shape, __FILE__);
  newXS("AI::MXNetTPU::_nd_set_f32", xs_nd_set_f32, __FILE__);
  newXS("AI::MXNetTPU::_nd_get_f32", xs_nd_get_f32, __FILE__);
  newXS("AI::MXNetTPU::_op_handle", xs_op_handle, __FILE__);
  newXS("AI::MXNetTPU::_invoke", xs_invoke, __FILE__);
  newXS("AI::MXNetTPU::_set_recording", xs_set_recording, __FILE__);
  newXS("AI::MXNetTPU::_set_training", xs_set_training, __FILE__);
  newXS("AI::MXNetTPU::_mark_variable", xs_mark_variable, __FILE__);
  newXS("AI::MXNetTPU::_backward", xs_backward, __FILE__);
  newXS("AI::MXNetTPU::_grad", xs_grad, __FILE__);
  newXS("AI::MXNetTPU::_wait_all", xs_wait_all, __FILE__);
  newXS("AI::MXNetTPU::_kv_create", xs_kv_create, __FILE__);
  newXS("AI::MXNetTPU::_kv_init", xs_kv_init, __FILE__);
  newXS("AI::MXNetTPU::_kv_push", xs_kv_push, __FILE__);
  newXS("AI::MXNetTPU::_kv_pull", xs_kv_pull, __FILE__);
  newXS("AI::MXNetTPU::_last_error", xs_last_error, __FILE__);
  XSRETURN_YES;
}
