package AI::MXNetTPU;

# AI::MXNetTPU — Perl binding for the mxnet_tpu framework.
#
# Reference analog: perl-package/AI-MXNet (lib/AI/MXNet.pm NDArray /
# AutoGrad / KVStore surfaces).  This projects the same API shapes over
# the tensor-runtime C ABI (mxtpu/c_api.h) through hand-written XS
# (xs/mxnettpu_xs.c) — the ABI's semantics come from the one embedded
# implementation, so Perl, C, C++ and Python can never disagree.
#
# Build once with `perl build.pl`, then:
#
#   use AI::MXNetTPU qw(nd);
#   my $x = AI::MXNetTPU::NDArray->array([[1,2],[3,4]]);
#   my $y = $x * $x + 1;         # overloaded elementwise ops
#   print "@{$y->aslist}\n";
#
# Autograd:
#   $x->attach_grad;
#   my $loss = AI::MXNetTPU::AutoGrad::record(sub { ($x * $x)->sum });
#   $loss->backward;
#   my $g = $x->grad;            # 2x

use strict;
use warnings;
use File::Basename qw(dirname);
use File::Spec;
use DynaLoader ();

our $VERSION = '0.1.0';

sub _boot {
    my $here = dirname(File::Spec->rel2abs(__FILE__));
    my $so = File::Spec->catfile($here, '..', '..', 'xs', 'MXNetTPU.so');
    die "AI::MXNetTPU: XS library not built; run perl build.pl ($so)\n"
        unless -e $so;
    # RTLD_GLOBAL (0x01): libmxtpu's embedded interpreter must see the
    # process's libpython symbols once it dlopens them
    my $libref = DynaLoader::dl_load_file($so, 0x01)
        or die 'AI::MXNetTPU: ', DynaLoader::dl_error();
    my $bootsym = DynaLoader::dl_find_symbol($libref, 'boot_AI__MXNetTPU')
        or die 'AI::MXNetTPU: no boot symbol: ', DynaLoader::dl_error();
    my $xs = DynaLoader::dl_install_xsub('AI::MXNetTPU::_bootstrap',
                                         $bootsym, __FILE__);
    &$xs();
}

_boot();

my %OPCACHE;

sub op {
    my ($name) = @_;
    $OPCACHE{$name} //= _op_handle($name);
    return $OPCACHE{$name};
}

sub invoke {
    # invoke('broadcast_add', [$nd1, $nd2], key => val, ...) -> NDArray(s)
    my ($name, $inputs, %attrs) = @_;
    my @ins = map { $_->handle } @$inputs;
    my @keys = keys %attrs;
    my @vals = map { "$attrs{$_}" } @keys;
    my $outs = _invoke(op($name), \@ins, \@keys, \@vals);
    my @nds = map { AI::MXNetTPU::NDArray->_from_handle($_) } @$outs;
    return wantarray ? @nds : $nds[0];
}

# ---------------------------------------------------------------- NDArray

package AI::MXNetTPU::NDArray;

use strict;
use warnings;
use overload
    '+' => sub { AI::MXNetTPU::NDArray::_binop('broadcast_add', @_) },
    '-' => sub { AI::MXNetTPU::NDArray::_binop('broadcast_sub', @_) },
    '*' => sub { AI::MXNetTPU::NDArray::_binop('broadcast_mul', @_) },
    '/' => sub { AI::MXNetTPU::NDArray::_binop('broadcast_div', @_) },
    '""' => sub { $_[0]->stringify },
    '==' => sub {    # handle identity, not elementwise (use invoke
                     # 'broadcast_equal' for the elementwise form)
        my ($a, $b) = @_;
        return ref($b) && $b->isa(__PACKAGE__) && $a->{h} == $b->{h};
    };

sub _from_handle {
    my ($class, $h) = @_;
    return bless { h => $h, owned => 1 }, $class;
}

sub handle { $_[0]->{h} }

sub zeros {
    my ($class, $shape) = @_;
    my $h = AI::MXNetTPU::_nd_create($shape, 0);    # dtype 0 = float32
    my $self = $class->_from_handle($h);
    my $n = 1; $n *= $_ for @$shape;
    AI::MXNetTPU::_nd_set_f32($h, pack('f*', (0) x $n));
    return $self;
}

sub ones {
    my ($class, $shape) = @_;
    my $self = $class->zeros($shape);
    my $n = 1; $n *= $_ for @$shape;
    AI::MXNetTPU::_nd_set_f32($self->{h}, pack('f*', (1) x $n));
    return $self;
}

sub _flatten {
    my ($data, $out, $shape, $depth) = @_;
    if (ref $data eq 'ARRAY') {
        $shape->[$depth] //= scalar @$data;
        die "ragged array\n" if $shape->[$depth] != scalar @$data;
        _flatten($_, $out, $shape, $depth + 1) for @$data;
    } else {
        push @$out, $data;
    }
}

sub array {
    my ($class, $data) = @_;
    my (@flat, @shape);
    _flatten($data, \@flat, \@shape, 0);
    @shape = (scalar @flat) unless @shape;
    my $h = AI::MXNetTPU::_nd_create(\@shape, 0);
    AI::MXNetTPU::_nd_set_f32($h, pack('f*', @flat));
    return $class->_from_handle($h);
}

sub shape { AI::MXNetTPU::_nd_shape($_[0]->{h}) }

sub aslist { [unpack('f*', AI::MXNetTPU::_nd_get_f32($_[0]->{h}))] }

sub asscalar {
    my @v = unpack('f*', AI::MXNetTPU::_nd_get_f32($_[0]->{h}));
    die "asscalar on non-scalar\n" if @v != 1;
    return $v[0];
}

sub stringify {
    my ($self) = @_;
    return sprintf("NDArray(%s)<%s>", join(',', @{$self->shape}),
                   join(',', map { sprintf('%g', $_) }
                        @{$self->aslist}[0 .. _min(5, scalar(@{$self->aslist}) - 1)]));
}

sub _min { $_[0] < $_[1] ? $_[0] : $_[1] }

sub _coerce {
    my ($v) = @_;
    return $v if ref $v;
    return AI::MXNetTPU::NDArray->array([$v + 0]);
}

sub _binop {
    my ($op, $a, $b, $swap) = @_;
    $b = _coerce($b);
    ($a, $b) = ($b, $a) if $swap;
    return AI::MXNetTPU::invoke($op, [$a, $b]);
}

sub dot   { AI::MXNetTPU::invoke('dot', [$_[0], $_[1]]) }
sub relu  { AI::MXNetTPU::invoke('relu', [$_[0]]) }
sub sum   { AI::MXNetTPU::invoke('sum', [$_[0]]) }
sub mean  { AI::MXNetTPU::invoke('mean', [$_[0]]) }
sub square { AI::MXNetTPU::invoke('square', [$_[0]]) }

sub attach_grad {
    my ($self, $req) = @_;
    $req //= 1;                               # 1 = write
    my $grad = AI::MXNetTPU::NDArray->zeros($self->shape);
    AI::MXNetTPU::_mark_variable($self->{h}, $grad->{h}, $req);
    $self->{_grad} = $grad;                   # keep the buffer alive
    return $self;
}

sub grad {
    my ($self) = @_;
    my $h = AI::MXNetTPU::_grad($self->{h});
    return undef unless $h;
    return AI::MXNetTPU::NDArray->_from_handle($h);
}

sub backward {
    my ($self, %kw) = @_;
    AI::MXNetTPU::_backward($self->{h}, $kw{retain_graph} ? 1 : 0);
    return;
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::_nd_free($self->{h})
        if $self->{owned} && defined $self->{h};
}

# --------------------------------------------------------------- autograd

package AI::MXNetTPU::AutoGrad;

use strict;
use warnings;

sub record {
    my ($code, %kw) = @_;
    my $train = exists $kw{train_mode} ? ($kw{train_mode} ? 1 : 0) : 1;
    my $prev_rec = AI::MXNetTPU::_set_recording(1);
    my $prev_train = AI::MXNetTPU::_set_training($train);
    my @out = eval { $code->() };
    my $err = $@;
    AI::MXNetTPU::_set_recording($prev_rec);
    AI::MXNetTPU::_set_training($prev_train);
    die $err if $err;
    return wantarray ? @out : $out[0];
}

# ---------------------------------------------------------------- kvstore

package AI::MXNetTPU::KVStore;

use strict;
use warnings;

sub create {
    my ($class, $type) = @_;
    $type //= 'local';
    return bless { h => AI::MXNetTPU::_kv_create($type) }, $class;
}

sub init { AI::MXNetTPU::_kv_init($_[0]->{h}, $_[1], $_[2]->handle); return }
sub push_ { AI::MXNetTPU::_kv_push($_[0]->{h}, $_[1], $_[2]->handle); return }

sub pull {
    my ($self, $key, $out) = @_;
    AI::MXNetTPU::_kv_pull($self->{h}, $key, $out->handle);
    return $out;
}

package AI::MXNetTPU;

1;
