#!/usr/bin/env perl
# AI::MXNetTPU end-to-end: tensors, imperative ops, autograd, a real
# SGD training loop, and a local KVStore round-trip — the same proof
# shape as the reference's perl-package/AI-MXNet/t tests, driven over
# the C ABI.

use strict;
use warnings;
use File::Basename qw(dirname);
use File::Spec;
use lib File::Spec->catdir(dirname(File::Spec->rel2abs($0)), '..', 'lib');

use Test::More;
use AI::MXNetTPU;

# ---- tensor round-trip + overloaded ops ------------------------------
my $x = AI::MXNetTPU::NDArray->array([[1, 2], [3, 4]]);
is_deeply($x->shape, [2, 2], 'shape');
is_deeply($x->aslist, [1, 2, 3, 4], 'round-trip values');

my $y = $x * $x + 1;
is_deeply($y->aslist, [2, 5, 10, 17], 'x*x + 1 (overloads + broadcast)');

my $z = ($x - 1) / 2;
is_deeply($z->aslist, [0, 0.5, 1, 1.5], 'sub/div with scalar coercion');

my $m = $x->dot(AI::MXNetTPU::NDArray->array([[1, 0], [0, 1]]));
is_deeply($m->aslist, [1, 2, 3, 4], 'dot identity');

# attr-carrying op through the generic invoke surface
my $fc = AI::MXNetTPU::invoke('FullyConnected',
    [$x, AI::MXNetTPU::NDArray->ones([3, 2])],
    num_hidden => 3, no_bias => 1);
is_deeply($fc->shape, [2, 3], 'FullyConnected with attrs');
is_deeply($fc->aslist, [3, 3, 3, 7, 7, 7], 'FullyConnected values');

# '==' is handle identity (not recursion, not elementwise)
ok($x == $x, 'identity == self');
ok(!($x == $m), 'distinct handles differ');
ok(!($x == 5), 'non-NDArray rhs is false');

# ---- error surface ----------------------------------------------------
eval { AI::MXNetTPU::invoke('NoSuchOperator', [$x]) };
like($@, qr/mxtpu:/, 'unknown op croaks with a diagnostic');

# ---- autograd ---------------------------------------------------------
my $a = AI::MXNetTPU::NDArray->array([1, 2, 3]);
$a->attach_grad;
my $loss = AI::MXNetTPU::AutoGrad::record(sub { ($a * $a)->sum });
$loss->backward;
is_deeply($a->grad->aslist, [2, 4, 6], 'd(sum x^2)/dx = 2x');

# ---- train a linear model with SGD in pure Perl ----------------------
# data: y = 2*x0 - 3*x1 + 1 (+ the model must recover it)
my (@X, @Y);
srand(7);
for my $i (1 .. 64) {
    my ($x0, $x1) = (rand(2) - 1, rand(2) - 1);
    push @X, [$x0, $x1];
    push @Y, [2 * $x0 - 3 * $x1 + 1];
}
my $Xn = AI::MXNetTPU::NDArray->array(\@X);
my $Yn = AI::MXNetTPU::NDArray->array(\@Y);
my $W = AI::MXNetTPU::NDArray->zeros([1, 2]);   # (out, in) FC convention
my $b = AI::MXNetTPU::NDArray->zeros([1]);
$W->attach_grad;
$b->attach_grad;

my ($first, $last);
for my $step (1 .. 60) {
    my $l = AI::MXNetTPU::AutoGrad::record(sub {
        my $pred = AI::MXNetTPU::invoke('FullyConnected', [$Xn, $W, $b],
                                        num_hidden => 1);
        (($pred - $Yn)->square)->mean;
    });
    $l->backward;
    $first //= $l->asscalar;
    $last = $l->asscalar;
    # SGD: w -= lr * grad (host-side update through the ABI)
    for my $pair ([$W, $W->grad], [$b, $b->grad]) {
        my ($p, $g) = @$pair;
        my @pv = @{$p->aslist};
        my @gv = @{$g->aslist};
        my @nv = map { $pv[$_] - 0.5 * $gv[$_] } 0 .. $#pv;
        AI::MXNetTPU::_nd_set_f32($p->handle, pack('f*', @nv));
    }
}
cmp_ok($last, '<', $first / 100, "SGD converged ($first -> $last)");
my @w = @{$W->aslist};
cmp_ok(abs($w[0] - 2),  '<', 0.1, 'learned w0 ~ 2');
cmp_ok(abs($w[1] + 3),  '<', 0.1, 'learned w1 ~ -3');
cmp_ok(abs($b->aslist->[0] - 1), '<', 0.1, 'learned bias ~ 1');

# ---- kvstore ----------------------------------------------------------
my $kv = AI::MXNetTPU::KVStore->create('local');
$kv->init(3, AI::MXNetTPU::NDArray->array([1, 1]));
$kv->push_(3, AI::MXNetTPU::NDArray->array([4, 6]));
my $out = AI::MXNetTPU::NDArray->zeros([2]);
$kv->pull(3, $out);
is_deeply($out->aslist, [4, 6], 'kvstore local push/pull');

AI::MXNetTPU::_wait_all();
done_testing();
