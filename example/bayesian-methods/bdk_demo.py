#!/usr/bin/env python
"""Bayesian learning demos: SGLD, HMC, and Bayesian Dark Knowledge
(reference: example/bayesian-methods/{algos.py,bdk_demo.py,utils.py} —
[ICML2011] Stochastic Gradient Langevin Dynamics and [NIPS2015]
Bayesian Dark Knowledge).

Four modes, mirroring the reference demo's flows on its two datasets:

* ``toy-sgld``       — SGLD posterior sampling of an MLP on the BDK toy
                       regression; predictive mean averaged over thinned
                       post-burn-in samples.
* ``toy-hmc``        — full-batch Hamiltonian Monte Carlo with leapfrog
                       integration and Metropolis correction on the same
                       model (reference algos.py:52 step_HMC).
* ``toy-distilled``  — DistilledSGLD: a student MLP distills the
                       teacher's SGLD predictive mean at perturbed
                       inputs (reference algos.py:231).
* ``synthetic``      — the Welling–Teh bimodal mixture posterior.  The
                       reference runs a 1,000,000-iteration Python loop
                       (bdk_demo.py:316 run_synthetic_SGLD); here the
                       whole chain is ONE ``mx.nd.contrib.foreach`` scan
                       — minibatch indices, injected noise, and the
                       polynomial step-size schedule are precomputed
                       arrays scanned over, so the chain compiles to a
                       single XLA While loop (TPU-idiomatic: no
                       per-iteration dispatch).

Data is generated in-process (zero-egress container): the toy set is
the BDK paper's ``y = x + 0.3 sin(2 pi x) + eps``.
"""

import argparse
import math
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


class SGLDScheduler(mx.lr_scheduler.LRScheduler):
    """Polynomial decay eps_t = a (b + t)^-factor hitting begin/end rates
    (reference utils.py:29)."""

    def __init__(self, begin_rate, end_rate, total_iter_num, factor):
        super().__init__()
        if factor >= 1.0:
            raise ValueError("factor must be < 1 to make lr decay")
        self.b = (total_iter_num - 1.0) / (
            (begin_rate / end_rate) ** (1.0 / factor) - 1.0)
        self.a = begin_rate / (self.b ** (-factor))
        self.factor = factor

    def __call__(self, num_update):
        return self.a * ((self.b + num_update) ** (-self.factor))


def load_toy(rng, n_train=400, n_test=200):
    def f(x):
        return x + 0.3 * np.sin(2 * np.pi * x)

    x = rng.uniform(0.0, 1.0, (n_train, 1))
    y = f(x) + rng.normal(0, 0.05, x.shape)
    x_test = np.linspace(0.0, 1.0, n_test).reshape(n_test, 1)
    return (x.astype(np.float32), y.astype(np.float32),
            x_test.astype(np.float32), f(x_test).astype(np.float32))


def make_mlp(hidden=64):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(hidden, activation="relu"), nn.Dense(1))
    return net


def _rmse(pred, truth):
    return float(np.sqrt(np.mean((pred - truth) ** 2)))


NOISE_PRECISION = 1.0 / (0.05 ** 2)     # matches load_toy's noise sd


def _make_sgld_teacher(args):
    """MLP + SGLD trainer shared by the toy-sgld and distilled modes."""
    net = make_mlp()
    net.initialize(mx.init.Uniform(0.07))
    sched = SGLDScheduler(args.lr, args.lr / 10, args.iters, 0.55)
    trainer = gluon.Trainer(
        net.collect_params(), "sgld",
        {"learning_rate": args.lr, "lr_scheduler": sched,
         "wd": args.prior_precision})
    return net, trainer


def _sgld_step(net, trainer, X, Y, idx, n, batch_size):
    """One SGLD draw: grad of U(w) = noise_prec/2 * N/m * minibatch SE
    (prior enters via wd); the SGLD updater adds eps/2 * grad and the
    N(0, eps) injected noise."""
    data, label = mx.nd.array(X[idx]), mx.nd.array(Y[idx])
    with autograd.record():
        out = net(data)
        loss = (NOISE_PRECISION / 2.0) * (n / batch_size) \
            * ((out - label) ** 2).sum()
    loss.backward()
    trainer.step(1)


def _predictive_mean(pred_sum, n_samples):
    if n_samples == 0:
        raise ValueError("no posterior samples collected: "
                         "burn-in >= iters")
    return pred_sum / n_samples


def run_toy_SGLD(args, rng):
    """SGLD over MLP weights; returns predictive-mean RMSE vs the true
    function (reference algos.py:171 SGLD, 'regression' task)."""
    X, Y, X_test, Y_truth = load_toy(rng)
    n = len(X)
    net, trainer = _make_sgld_teacher(args)

    pred_sum = np.zeros_like(Y_truth)
    n_samples = 0
    for it in range(args.iters):
        idx = rng.randint(0, n, args.batch_size)
        _sgld_step(net, trainer, X, Y, idx, n, args.batch_size)
        if it >= args.burn_in and (it - args.burn_in) % args.thin == 0:
            pred_sum += net(mx.nd.array(X_test)).asnumpy()
            n_samples += 1
    rmse = _rmse(_predictive_mean(pred_sum, n_samples), Y_truth)
    print("toy-sgld: %d posterior samples, predictive RMSE %.4f"
          % (n_samples, rmse))
    return rmse


def _potential(net, params, X, Y, noise_precision, prior_precision):
    out = net(X)
    nll = (noise_precision / 2.0) * ((out - Y) ** 2).sum()
    prior = sum((prior_precision / 2.0) * (p.data() ** 2).sum()
                for p in params)
    return nll + prior


def run_toy_HMC(args, rng):
    """Full-batch HMC with L leapfrog steps + Metropolis correction
    (reference algos.py:52 step_HMC / :103 HMC)."""
    X, Y, X_test, Y_truth = load_toy(rng)
    noise_precision = NOISE_PRECISION
    prior_precision = 1.0
    net = make_mlp(hidden=32)
    net.initialize(mx.init.Uniform(0.07))
    data, label = mx.nd.array(X), mx.nd.array(Y)
    net(data)                       # materialize deferred-init shapes
    params = list(net.collect_params().values())
    L, eps = args.hmc_L, args.hmc_eps

    def grads():
        with autograd.record():
            U = _potential(net, params, data, label,
                           noise_precision, prior_precision)
        U.backward()
        return U

    accepted = 0
    pred_sum = np.zeros_like(Y_truth)
    n_samples = 0
    for it in range(args.iters):
        w0 = [p.data().copy() for p in params]
        mom = [mx.nd.array(rng.normal(0, 1, p.shape).astype(np.float32))
               for p in params]
        K0 = sum(float((m ** 2).sum().asscalar()) for m in mom) / 2.0
        # leapfrog: half-step momentum, L full position steps; each
        # grads() call also returns U at the current position, giving
        # U0 (start) and U1 (end) without extra potential evaluations
        U0 = float(grads().asscalar())
        mom = [m - (eps / 2) * p.grad() for m, p in zip(mom, params)]
        U1 = U0
        for l in range(L):
            for p, m in zip(params, mom):
                p.set_data(p.data() + eps * m)
            U1 = float(grads().asscalar())
            if l < L - 1:
                mom = [m - eps * p.grad() for m, p in zip(mom, params)]
        mom = [m - (eps / 2) * p.grad() for m, p in zip(mom, params)]
        K1 = sum(float((m ** 2).sum().asscalar()) for m in mom) / 2.0
        dH = (U0 + K0) - (U1 + K1)
        # divergent (non-finite) proposals are always rejected
        if math.isfinite(dH) and rng.rand() < math.exp(min(0.0, dH)):
            accepted += 1
        else:
            for p, w in zip(params, w0):
                p.set_data(w)
        if it >= args.burn_in:
            pred_sum += net(mx.nd.array(X_test)).asnumpy()
            n_samples += 1
    rate = accepted / float(args.iters)
    rmse = _rmse(_predictive_mean(pred_sum, n_samples), Y_truth)
    print("toy-hmc: accept rate %.2f, predictive RMSE %.4f" % (rate, rmse))
    return rmse, rate


def run_toy_DistilledSGLD(args, rng):
    """Teacher SGLD chain distilled online into a student MLP evaluated
    at Gaussian-perturbed minibatch inputs (reference algos.py:231)."""
    X, Y, X_test, Y_truth = load_toy(rng)
    n = len(X)
    teacher, t_trainer = _make_sgld_teacher(args)
    student = make_mlp()
    student.initialize(mx.init.Uniform(0.07))
    s_trainer = gluon.Trainer(student.collect_params(), "adam",
                              {"learning_rate": 1e-2})
    s_loss = gluon.loss.L2Loss()

    for it in range(args.iters):
        idx = rng.randint(0, n, args.batch_size)
        _sgld_step(teacher, t_trainer, X, Y, idx, n, args.batch_size)
        if it >= args.burn_in:
            # student regresses on the teacher sample's prediction at
            # perturbed inputs (perturb_deviation=0.1 in the reference)
            pdata = mx.nd.array(
                X[idx] + rng.normal(0, 0.1, (args.batch_size, 1))
                .astype(np.float32))
            t_pred = teacher(pdata)
            with autograd.record():
                l = s_loss(student(pdata), t_pred)
            l.backward()
            s_trainer.step(args.batch_size)
    rmse = _rmse(student(mx.nd.array(X_test)).asnumpy(), Y_truth)
    print("toy-distilled: student predictive RMSE %.4f" % rmse)
    return rmse


# The two modes of p(theta|X): (0, 1) and roughly (1, -1).
SYN_MODES = np.array([[0.0, 1.0], [1.0, -1.0]])


def run_synthetic_SGLD(args, rng):
    """Welling–Teh mixture posterior, the WHOLE chain as one foreach
    scan (reference bdk_demo.py:316 loops 1e6 times in Python and
    recomputes the analytic gradient in numpy each step;
    bdk_demo.py:121 synthetic_grad)."""
    theta1, theta2 = 0.0, 1.0
    sigma1, sigma2, sigmax = math.sqrt(10), 1.0, math.sqrt(2)
    n = 100
    flag = rng.randint(0, 2, n)
    X_np = (flag * rng.normal(theta1, sigmax, n)
            + (1 - flag) * rng.normal(theta1 + theta2, sigmax, n))

    T = args.iters
    sched = SGLDScheduler(0.01, 0.0001, T, 0.55)
    lrs = np.array([sched(t) for t in range(T)], np.float32)
    idxs = rng.randint(0, n, T).astype(np.float32)
    noise = rng.normal(0, 1, (T, 2)).astype(np.float32)

    Xd = mx.nd.array(X_np.astype(np.float32))
    v1, v2, vx = sigma1 ** 2, sigma2 ** 2, sigmax ** 2

    def body(step, states):
        lr_t, ind, eta = step
        theta = states[0]
        x = mx.nd.take(Xd, ind)                      # minibatch of one
        t1 = mx.nd.slice_axis(theta, axis=0, begin=0, end=1)
        t2 = mx.nd.slice_axis(theta, axis=0, begin=1, end=2)
        e1 = mx.nd.exp(-((x - t1) ** 2) / (2 * vx))
        e2 = mx.nd.exp(-((x - t1 - t2) ** 2) / (2 * vx))
        den = e1 + e2
        # d/dtheta of -log p, minibatch-rescaled by n (reference math)
        g1 = -float(n) * ((e1 * (x - t1) / vx
                           + e2 * (x - t1 - t2) / vx) / den) + t1 / v1
        g2 = -float(n) * ((e2 * (x - t1 - t2) / vx) / den) + t2 / v2
        grad = mx.nd.concat(g1, g2, dim=0)
        new_theta = theta - lr_t / 2 * grad + mx.nd.sqrt(lr_t) * eta
        return new_theta, [new_theta]

    theta0 = mx.nd.array(rng.normal(0, 1, 2).astype(np.float32))
    samples, _ = mx.nd.contrib.foreach(
        body,
        [mx.nd.array(lrs), mx.nd.array(idxs), mx.nd.array(noise)],
        [theta0])
    samples = samples.asnumpy()[args.burn_in:]
    d = np.minimum(
        ((samples - SYN_MODES[0]) ** 2).sum(1),
        ((samples - SYN_MODES[1]) ** 2).sum(1))
    mean_mode_dist = float(np.sqrt(d).mean())
    print("synthetic: %d samples, mean distance to nearest mode %.3f, "
          "theta std (%.3f, %.3f)"
          % (len(samples), mean_mode_dist,
             samples[:, 0].std(), samples[:, 1].std()))
    return mean_mode_dist, samples


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", default="toy-sgld",
                   choices=["toy-sgld", "toy-hmc", "toy-distilled",
                            "synthetic"])
    p.add_argument("--iters", type=int, default=2000)
    p.add_argument("--burn-in", type=int, default=300)
    p.add_argument("--thin", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=4e-6)
    p.add_argument("--prior-precision", type=float, default=1.0)
    p.add_argument("--hmc-L", type=int, default=10)
    p.add_argument("--hmc-eps", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=100)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    if args.mode == "toy-sgld":
        return run_toy_SGLD(args, rng)
    if args.mode == "toy-hmc":
        return run_toy_HMC(args, rng)
    if args.mode == "toy-distilled":
        return run_toy_DistilledSGLD(args, rng)
    return run_synthetic_SGLD(args, rng)


if __name__ == "__main__":
    main()
