"""DCGAN-style generative adversarial training (reference:
example/gan/dcgan.py) on an intrinsic 2-D Gaussian-mixture task so it
runs anywhere without datasets.

Usage: python train_gan.py [--epochs 30] [--batch-size 64]
Prints D/G losses per epoch; ends with the generator's mode coverage.
"""

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import ndarray as nd


def real_batch(rs, n):
    """Mixture of 4 Gaussians at (+-2, +-2)."""
    centers = np.array([[2, 2], [2, -2], [-2, 2], [-2, -2]], np.float32)
    idx = rs.randint(0, 4, n)
    return centers[idx] + 0.2 * rs.randn(n, 2).astype(np.float32)


def build_nets():
    gen = gluon.nn.HybridSequential(prefix="gen_")
    with gen.name_scope():
        gen.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(2))
    disc = gluon.nn.HybridSequential(prefix="disc_")
    with disc.name_scope():
        disc.add(gluon.nn.Dense(32, activation="relu"),
                 gluon.nn.Dense(32, activation="relu"),
                 gluon.nn.Dense(1))
    return gen, disc


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--latent", type=int, default=8)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    rs = np.random.RandomState(args.seed)
    gen, disc = build_nets()
    gen.initialize(mx.init.Xavier())
    disc.initialize(mx.init.Xavier())
    gen.hybridize()
    disc.hybridize()
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})

    ones = nd.array(np.ones((args.batch_size,), np.float32))
    zeros = nd.array(np.zeros((args.batch_size,), np.float32))
    for epoch in range(args.epochs):
        d_losses, g_losses = [], []
        for _ in range(20):
            z = nd.array(rs.randn(args.batch_size, args.latent)
                         .astype(np.float32))
            real = nd.array(real_batch(rs, args.batch_size))
            # --- discriminator step
            with autograd.record():
                fake = gen(z)
                d_loss = (loss_fn(disc(real), ones).mean() +
                          loss_fn(disc(fake.detach()), zeros).mean())
            d_loss.backward()
            d_tr.step(args.batch_size)
            # --- generator step
            with autograd.record():
                g_loss = loss_fn(disc(gen(z)), ones).mean()
            g_loss.backward()
            g_tr.step(args.batch_size)
            d_losses.append(float(d_loss.asnumpy()))
            g_losses.append(float(g_loss.asnumpy()))
        print("epoch %d  d_loss %.3f  g_loss %.3f"
              % (epoch, np.mean(d_losses), np.mean(g_losses)))

    # mode coverage: fraction of quadrants the generator reaches
    z = nd.array(rs.randn(512, args.latent).astype(np.float32))
    samples = gen(z).asnumpy()
    quads = {(int(sx > 0), int(sy > 0)) for sx, sy in samples
             if abs(sx) > 0.5 and abs(sy) > 0.5}
    print("mode coverage: %d/4 quadrants" % len(quads))
    return len(quads)


if __name__ == "__main__":
    main()
