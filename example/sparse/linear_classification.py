#!/usr/bin/env python
"""Sparse linear classification on LibSVM data (reference:
example/sparse/linear_classification/train.py — CSR batches through
LibSVMIter, sparse dot forward, row_sparse gradients, lazy SGD).

Data is a synthetic LibSVM file (zero-egress container): each sample
activates a handful of features whose signed weights decide the label.
The design matrix batch stays a CSR triple end-to-end — the dense
(batch, num_features) form is never materialized (csr.densified is
asserted False in the test) — and the gradient is row_sparse, so the
optimizer touches only the features present in the batch.
"""

import argparse
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu.io.io import LibSVMIter
from mxnet_tpu.ndarray import sparse as sp


def write_libsvm(path, n, num_features, rng, nnz=8):
    """Synthetic separable data: label = sign of the active features'
    ground-truth weight sum."""
    w_true = rng.randn(num_features).astype(np.float32)
    with open(path, "w") as f:
        for _ in range(n):
            idx = np.sort(rng.choice(num_features, nnz, replace=False))
            val = rng.rand(nnz).astype(np.float32) + 0.1
            y = 1.0 if float(val @ w_true[idx]) > 0 else 0.0
            f.write("%d %s\n" % (y, " ".join(
                "%d:%.6f" % (i, v) for i, v in zip(idx, val))))
    return path


def train(args, path):
    it = LibSVMIter(data_libsvm=path, data_shape=(args.num_features,),
                    batch_size=args.batch_size)
    rng = np.random.RandomState(1)
    w = mx.nd.array(rng.randn(args.num_features, 1).astype(np.float32) * 0.01)
    b = mx.nd.zeros((1,))
    opt = mx.optimizer.SGD(learning_rate=args.lr, lazy_update=True)
    updater = mx.optimizer.get_updater(opt)

    for epoch in range(args.epochs):
        it.reset()
        n_correct = n_total = 0
        for batch in it:
            X, y = batch.data[0], batch.label[0]
            # forward: CSR x dense — O(nnz) work, no dense X
            z = sp.dot(X, w) + b
            p = mx.nd.sigmoid(z).reshape((-1,))
            # logistic-loss gradient dL/dz = p - y, pushed back through
            # the CSR: csr^T x dense -> row_sparse over active features
            err = (p - y).reshape((-1, 1)) / args.batch_size
            gw = sp.dot(X, err, transpose_a=True)
            gb = err.sum(axis=0)
            updater(0, gw, w)
            updater(1, gb, b)
            n_correct += int(((p.asnumpy() > 0.5) ==
                              (y.asnumpy() > 0.5)).sum())
            n_total += args.batch_size
        acc = n_correct / n_total
        print("epoch %d: train accuracy %.4f" % (epoch, acc))
    return acc


def main(argv=None):
    p = argparse.ArgumentParser(description="sparse linear classification")
    p.add_argument("--num-features", type=int, default=1000)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=25)
    p.add_argument("--lr", type=float, default=2.0)
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history
    rng = np.random.RandomState(0)
    path = write_libsvm(os.path.join(tempfile.mkdtemp(), "train.libsvm"),
                        args.num_examples, args.num_features, rng)
    return train(args, path)


if __name__ == "__main__":
    main()
