#!/usr/bin/env python
"""Matrix factorization with row_sparse embedding gradients (reference:
example/sparse/matrix_factorization/train.py — MovieLens ALS-style
factorization where each batch touches a few users/items, so gradients
are row_sparse and the optimizer updates only the touched rows).

Ratings come from a synthetic low-rank ground truth.  Both embedding
tables use sparse_grad=True: the backward produces row_sparse
gradients and SGD's lazy_update path scatters into just the touched
rows — the table-sized dense gradient never exists.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


class MFNet(gluon.Block):
    def __init__(self, num_users, num_items, dim, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = nn.Embedding(num_users, dim, sparse_grad=True)
            self.item = nn.Embedding(num_items, dim, sparse_grad=True)

    def forward(self, users, items):
        return (self.user(users) * self.item(items)).sum(axis=1)


def synthetic_ratings(rng, n, num_users, num_items, rank=4):
    u_true = rng.randn(num_users, rank).astype(np.float32)
    v_true = rng.randn(num_items, rank).astype(np.float32)
    users = rng.randint(0, num_users, n).astype(np.int32)
    items = rng.randint(0, num_items, n).astype(np.int32)
    ratings = (u_true[users] * v_true[items]).sum(axis=1)
    return users, items, ratings.astype(np.float32)


def main(argv=None):
    p = argparse.ArgumentParser(description="sparse matrix factorization")
    p.add_argument("--num-users", type=int, default=300)
    p.add_argument("--num-items", type=int, default=200)
    p.add_argument("--dim", type=int, default=8)
    p.add_argument("--num-examples", type=int, default=8192)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history

    rng = np.random.RandomState(0)
    users, items, ratings = synthetic_ratings(
        rng, args.num_examples, args.num_users, args.num_items)

    net = MFNet(args.num_users, args.num_items, args.dim)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr, "lazy_update": True})
    l2 = gluon.loss.L2Loss()

    B = args.batch_size
    rmses = []
    for epoch in range(args.epochs):
        tot = nb = 0.0
        for i in range(0, args.num_examples - B + 1, B):
            u = mx.nd.array(users[i:i + B], dtype="int32")
            v = mx.nd.array(items[i:i + B], dtype="int32")
            r = mx.nd.array(ratings[i:i + B])
            with mx.autograd.record():
                pred = net(u, v)
                L = l2(pred, r)
            L.backward()
            # Trainer casts the sparse_grad=True embedding grads to
            # row_sparse before the update (gluon/trainer.py), so the
            # optimizer's lazy path touches only this batch's rows
            trainer.step(B)
            tot += float(L.mean().asnumpy()) * 2  # L2Loss halves
            nb += 1
        rmses.append((tot / nb) ** 0.5)
        print("epoch %d: rmse %.4f" % (epoch, rmses[-1]))
    return rmses


if __name__ == "__main__":
    main()
