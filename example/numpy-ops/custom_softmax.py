"""Train an MNIST MLP whose softmax loss is a user-defined CustomOp
(reference: example/numpy-ops/custom_softmax.py — the canonical
custom-op-bridge example).

The op runs numpy on the host inside the training graph: forward is a
stable softmax, backward implements d(CE)/dx = p - onehot(label)
directly (need_top_grad=False, loss-style op).
"""

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


class NumpySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0],
                    mx.nd.array(e / e.sum(axis=1, keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        label = in_data[1].asnumpy().ravel().astype(np.int64)
        p = out_data[0].asnumpy().copy()
        p[np.arange(label.shape[0]), label] -= 1.0
        # no batch division here: the optimizer's rescale_grad handles
        # it (reference custom_softmax.py does the same)
        self.assign(in_grad[0], req[0], mx.nd.array(p))


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        return [data_shape, (data_shape[0],)], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def build_mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc3")
    return mx.sym.Custom(data=h, name="softmax", op_type="numpy_softmax")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args(argv)

    from mxnet_tpu.io.io import MNISTIter

    logging.basicConfig(level=logging.INFO)
    train = MNISTIter(image="train", batch_size=args.batch_size)
    val = MNISTIter(image="val", batch_size=args.batch_size, shuffle=False)

    mod = mx.mod.Module(build_mlp(), context=mx.context.current_context())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-5},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))

    metric = mx.metric.Accuracy()
    mod.score(val, metric)
    acc = metric.get()[1]
    print("custom-softmax val accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
