#!/usr/bin/env python
"""Word embeddings with NCE loss (reference: example/nce-loss/wordvec.py
— word2vec-style training where the full-vocab softmax is replaced by
noise-contrastive estimation against K sampled negatives).

Synthetic corpus (zero-egress container): the vocabulary is split into
topical clusters and sentences draw words from one cluster, so
co-occurrence structure is known.  Training maximizes
log sigma(s(center, ctx)) + sum_k log sigma(-s(center, noise_k)) — the
NCE objective — with all K+1 scores batched into one MXU matmul.  The
test asserts the learned geometry: intra-cluster cosine similarity
must beat inter-cluster by a margin.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


class NCEEmbedding(gluon.Block):
    """center/context embedding pair scored by dot product."""

    def __init__(self, vocab, dim, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.center = nn.Embedding(vocab, dim)
            self.context = nn.Embedding(vocab, dim)

    def forward(self, center, ctx_and_noise):
        """center: (B,); ctx_and_noise: (B, 1+K) — column 0 is the true
        context, the rest are noise samples.  Returns (B, 1+K) scores."""
        c = self.center(center)                 # (B, D)
        w = self.context(ctx_and_noise)         # (B, 1+K, D)
        return (w * c.reshape((c.shape[0], 1, c.shape[1]))).sum(axis=2)


def nce_loss(scores):
    """-log sigma(s_pos) - sum log sigma(-s_neg) (reference:
    example/nce-loss/nce.py NceOutput semantics)."""
    pos = scores[:, 0:1]
    neg = scores[:, 1:]
    eps = 1e-7
    lp = mx.nd.log(mx.nd.sigmoid(pos) + eps)
    ln = mx.nd.log(1.0 - mx.nd.sigmoid(neg) + eps).sum(axis=1, keepdims=True)
    return -(lp + ln).reshape((-1,))


def make_corpus(rng, n_pairs, vocab, n_clusters):
    """(center, context) pairs drawn within clusters."""
    per = vocab // n_clusters
    centers = np.empty(n_pairs, np.int32)
    contexts = np.empty(n_pairs, np.int32)
    for i in range(n_pairs):
        c = rng.randint(n_clusters)
        centers[i] = c * per + rng.randint(per)
        contexts[i] = c * per + rng.randint(per)
    return centers, contexts


def cluster_similarity(emb, vocab, n_clusters):
    """(mean intra-cluster cosine, mean inter-cluster cosine)."""
    w = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-9)
    sims = w @ w.T
    per = vocab // n_clusters
    cluster = np.arange(vocab) // per
    same = cluster[:, None] == cluster[None, :]
    off_diag = ~np.eye(vocab, dtype=bool)
    return (float(sims[same & off_diag].mean()),
            float(sims[~same].mean()))


def main(argv=None):
    p = argparse.ArgumentParser(description="NCE word embeddings")
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--clusters", type=int, default=8)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--num-negatives", type=int, default=8)
    p.add_argument("--num-pairs", type=int, default=8192)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history

    rng = np.random.RandomState(0)
    centers, contexts = make_corpus(rng, args.num_pairs, args.vocab,
                                    args.clusters)

    net = NCEEmbedding(args.vocab, args.dim)
    net.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    B, K = args.batch_size, args.num_negatives
    for epoch in range(args.epochs):
        tot = nb = 0.0
        for i in range(0, args.num_pairs - B + 1, B):
            noise = rng.randint(0, args.vocab, (B, K))  # unigram noise
            cn = np.concatenate([contexts[i:i + B, None], noise], axis=1)
            c = mx.nd.array(centers[i:i + B], dtype="int32")
            w = mx.nd.array(cn, dtype="int32")
            with mx.autograd.record():
                L = nce_loss(net(c, w))
            L.backward()
            trainer.step(B)
            tot += float(L.mean().asnumpy())
            nb += 1
        intra, inter = cluster_similarity(
            net.center.weight.data().asnumpy(), args.vocab, args.clusters)
        print("epoch %d: nce loss %.4f, cosine intra %.3f vs inter %.3f"
              % (epoch, tot / nb, intra, inter))
    return intra, inter


if __name__ == "__main__":
    main()
