#!/usr/bin/env python
"""Sequence tagging with a BiLSTM (reference:
example/named_entity_recognition — token-level classification with
padded variable-length sentences, per-timestep softmax and masked
loss/metrics).

Synthetic NER (zero-egress container): sentences draw filler tokens
plus entity spans from a designated vocab range; an entity token is
tagged B/I by position in its span, everything else O.  Variable
lengths are padded to one static shape and masked — the TPU-idiomatic
bucketing alternative (docs/faq/bucketing.md).
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn, rnn

VOCAB = 50
ENTITY_LO = 40            # tokens >= ENTITY_LO form entity spans
TAGS = 3                  # O=0, B=1, I=2
SEQ = 20


def make_data(rng, n):
    x = np.zeros((n, SEQ), np.int32)
    tags = np.zeros((n, SEQ), np.float32)
    lengths = rng.randint(SEQ // 2, SEQ + 1, n).astype(np.float32)
    for i in range(n):
        L = int(lengths[i])
        x[i, :L] = rng.randint(1, ENTITY_LO, L)
        t = 0
        while t < L:
            if rng.rand() < 0.2:                  # start an entity span
                span = min(rng.randint(1, 4), L - t)
                x[i, t:t + span] = rng.randint(ENTITY_LO, VOCAB, span)
                tags[i, t] = 1                     # B
                tags[i, t + 1:t + span] = 2        # I
                t += span
            else:
                t += 1
    return x, tags, lengths


class Tagger(gluon.Block):
    def __init__(self, hidden=32, emb=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, emb)
            self.lstm = rnn.LSTM(hidden, layout="NTC", bidirectional=True)
            self.out = nn.Dense(TAGS, flatten=False)

    def forward(self, tokens):
        return self.out(self.lstm(self.embed(tokens)))  # (N, T, TAGS)


def masked_loss(logits, tags, lengths):
    logp = mx.nd.log_softmax(logits, axis=-1)
    ce = -mx.nd.pick(logp, tags, axis=-1)               # (N, T)
    # valid-position mask from lengths (the SequenceMask semantics)
    steps = mx.nd.arange(0, SEQ).reshape((1, SEQ))
    mask = (steps < lengths.reshape((-1, 1))).astype("float32")
    return (ce * mask).sum() / mask.sum()


def tag_f1(net, x, tags, lengths):
    pred = net(mx.nd.array(x, dtype="int32")).asnumpy().argmax(-1)
    steps = np.arange(SEQ)[None, :]
    mask = steps < lengths[:, None]
    tp = ((pred > 0) & (tags > 0) & (pred == tags) & mask).sum()
    fp = ((pred > 0) & ((tags == 0) | (pred != tags)) & mask).sum()
    fn = ((tags > 0) & ((pred == 0) | (pred != tags)) & mask).sum()
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def main(argv=None):
    p = argparse.ArgumentParser(description="BiLSTM sequence tagger")
    p.add_argument("--num-examples", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--lr", type=float, default=1e-2)
    args = p.parse_args(argv)
    args.batch_size = min(args.batch_size, args.num_examples)
    mx.random.seed(7)

    rng = np.random.RandomState(0)
    x, tags, lengths = make_data(rng, args.num_examples)
    xv, tagv, lenv = make_data(np.random.RandomState(99), 128)

    net = Tagger()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    B = args.batch_size
    for epoch in range(args.epochs):
        tot = nb = 0.0
        for i in range(0, args.num_examples - B + 1, B):
            tok = mx.nd.array(x[i:i + B], dtype="int32")
            tg = mx.nd.array(tags[i:i + B])
            ln = mx.nd.array(lengths[i:i + B])
            with mx.autograd.record():
                L = masked_loss(net(tok), tg, ln)
            L.backward()
            trainer.step(B)
            tot += float(L.asnumpy())
            nb += 1
        f1 = tag_f1(net, xv, tagv, lenv)
        print("epoch %d: masked ce %.4f, val entity F1 %.3f"
              % (epoch, tot / nb, f1))
    return f1


if __name__ == "__main__":
    main()
