#!/usr/bin/env python
"""Generate the plankton image corpus + stratified train/val lists
(reference: example/kaggle-ndsb1/gen_img_list.py — walks the class
directories, writes shuffled .lst files with a per-class split).

The National Data Science Bowl corpus cannot be downloaded in this
zero-egress container, so the class directories are synthesized:
each of the 6 "plankton taxa" is a distinct silhouette (disc, ring,
rod, cross, blob pair, crescent) rendered with rotation/scale jitter
on a noisy background — shape-only classes, like real plankton.
"""

import argparse
import os

import numpy as np

SIZE = 24
CLASSES = ["disc", "ring", "rod", "cross", "pair", "crescent"]


def draw(cls, rng):
    img = rng.normal(0.12, 0.05, (SIZE, SIZE))
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    cy, cx = SIZE / 2 + rng.uniform(-3, 3, 2)
    r = rng.uniform(5, 8)
    th = rng.uniform(0, np.pi)
    u = (yy - cy) * np.cos(th) + (xx - cx) * np.sin(th)
    v = -(yy - cy) * np.sin(th) + (xx - cx) * np.cos(th)
    d2 = u ** 2 + v ** 2
    if cls == "disc":
        m = d2 <= r * r
    elif cls == "ring":
        m = (d2 <= r * r) & (d2 >= (0.55 * r) ** 2)
    elif cls == "rod":
        m = (np.abs(u) <= r) & (np.abs(v) <= 1.6)
    elif cls == "cross":
        m = ((np.abs(u) <= r) & (np.abs(v) <= 1.6)) | \
            ((np.abs(v) <= r) & (np.abs(u) <= 1.6))
    elif cls == "pair":
        m = ((u - r / 2) ** 2 + v ** 2 <= (0.45 * r) ** 2) | \
            ((u + r / 2) ** 2 + v ** 2 <= (0.45 * r) ** 2)
    else:                                   # crescent
        m = (d2 <= r * r) & ((u - 0.4 * r) ** 2 + v ** 2 >= (0.75 * r) ** 2)
    img[m] = rng.uniform(0.7, 1.0)
    return (np.clip(img, 0, 1) * 255).astype(np.uint8)


def main(argv=None):
    from PIL import Image

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-dir", required=True)
    p.add_argument("--per-class", type=int, default=80)
    p.add_argument("--train-frac", type=float, default=0.8)
    p.add_argument("--seed", type=int, default=8)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    root = os.path.join(args.out_dir, "train")
    entries = []                             # (relpath, label)
    for label, cls in enumerate(CLASSES):
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(args.per_class):
            name = "%s_%03d.png" % (cls, i)
            Image.fromarray(draw(cls, rng)).convert("RGB").save(
                os.path.join(d, name))
            entries.append((os.path.join(cls, name), label))

    # stratified shuffled split, one line per image: idx \t label \t path
    train_lines, val_lines = [], []
    for label in range(len(CLASSES)):
        rows = [e for e in entries if e[1] == label]
        rng.shuffle(rows)
        cut = int(len(rows) * args.train_frac)
        train_lines += rows[:cut]
        val_lines += rows[cut:]
    rng.shuffle(train_lines)
    rng.shuffle(val_lines)
    for split, rows in (("train", train_lines), ("val", val_lines)):
        with open(os.path.join(args.out_dir, "%s.lst" % split), "w") as f:
            for i, (path, label) in enumerate(rows):
                f.write("%d\t%d\t%s\n" % (i, label, path))
    print("wrote %d train / %d val entries under %s"
          % (len(train_lines), len(val_lines), args.out_dir))
    return root


if __name__ == "__main__":
    main()
