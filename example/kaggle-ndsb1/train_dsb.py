#!/usr/bin/env python
"""Train the plankton classifier from packed records (reference:
example/kaggle-ndsb1/{train_dsb.py,symbol_dsb.py} — the full Kaggle
workflow: gen_img_list -> im2rec -> ImageIter with augmentation ->
Module.fit on the plankton conv net).

This script runs the WHOLE file pipeline: renders the corpus, writes
the stratified .lst files, packs train/val .rec with tools/im2rec.py,
and trains from ImageIter with random-mirror augmentation — the same
chain a reference user runs by hand.
"""

import argparse
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx

import gen_img_list


def get_symbol(num_classes):
    """Downscaled symbol_dsb.py plankton net."""
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=16,
                             pad=(2, 2))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(3, 3),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=32,
                             pad=(1, 1))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(3, 3),
                         stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=128)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Dropout(net, p=0.25)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--per-class", type=int, default=80)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--work-dir", default=None,
                   help="where to render/pack (default: a temp dir)")
    p.add_argument("--seed", type=int, default=8)
    args = p.parse_args(argv)

    mx.random.seed(args.seed)
    work = args.work_dir or tempfile.mkdtemp(prefix="ndsb1_")
    gen_img_list.main(["--out-dir", work,
                       "--per-class", str(args.per_class)])

    import im2rec
    root = os.path.join(work, "train")
    for split in ("train", "val"):
        im2rec.main([os.path.join(work, split), root])

    shape = (3, gen_img_list.SIZE, gen_img_list.SIZE)
    train_iter = mx.image.ImageIter(
        batch_size=args.batch_size, data_shape=shape,
        path_imgrec=os.path.join(work, "train.rec"), shuffle=True,
        rand_mirror=True)
    val_iter = mx.image.ImageIter(
        batch_size=args.batch_size, data_shape=shape,
        path_imgrec=os.path.join(work, "val.rec"))

    module = mx.mod.Module(get_symbol(len(gen_img_list.CLASSES)),
                           data_names=("data",),
                           label_names=("softmax_label",))
    module.fit(train_iter, eval_data=val_iter, eval_metric="acc",
               optimizer="adam",
               optimizer_params={"learning_rate": args.lr},
               initializer=mx.init.Xavier(),
               num_epoch=args.epochs)

    val_iter.reset()
    metric = mx.metric.Accuracy()
    module.score(val_iter, metric)
    acc = metric.get()[1]
    print("Validation accuracy %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
