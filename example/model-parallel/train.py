#!/usr/bin/env python
"""Model parallelism (reference: example/model-parallel — manual layer
placement via group2ctx; here the TPU-native form: per-parameter
PartitionSpecs over a device mesh, GSPMD inserting the collectives).
See docs/faq/model_parallel.md."""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.gluon_step import GluonTrainStep
from mxnet_tpu.parallel.mesh import create_mesh


def main(argv=None):
    p = argparse.ArgumentParser(description="tensor/model parallel example")
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel ranks (default: all visible "
                        "devices; run under XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8 "
                        "JAX_PLATFORMS=cpu to simulate a mesh)")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--steps", type=int, default=25)
    p.add_argument("--lr", type=float, default=0.2)
    args = p.parse_args(argv)
    mx.random.seed(7)

    import jax
    from jax.sharding import PartitionSpec as P

    if not args.tp:
        args.tp = len(jax.devices())
    mesh = create_mesh({"tp": args.tp})
    net = nn.HybridSequential(prefix="mp_")
    with net.name_scope():
        net.add(nn.Dense(args.hidden, activation="relu", in_units=16),
                nn.Dense(args.hidden, activation="relu",
                         in_units=args.hidden),
                nn.Dense(4, in_units=args.hidden))
    net.initialize(mx.init.Xavier())

    def spec_fn(name, shape):
        # row-shard every big (out, in) weight over 'tp'; GSPMD inserts
        # the all-gathers (the group2ctx analog)
        if name.endswith("weight") and len(shape) == 2 \
                and shape[0] % args.tp == 0:
            return P("tp", None)
        return P()

    step = GluonTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mesh=mesh, lr=args.lr, momentum=0.9,
                          param_spec_fn=spec_fn, data_spec=P())
    sharded = [p_ for p_, v in zip(step.trainable, step.train_vals)
               if "tp" in str(getattr(v.sharding, "spec", ""))]
    print("tp-sharded params:", [q.name for q in sharded])

    rng = np.random.RandomState(0)
    x = rng.randn(64, 16).astype(np.float32)
    y = (x @ rng.randn(16, 4)).argmax(1).astype(np.int32)
    losses = []
    for _ in range(args.steps):
        losses.append(float(np.asarray(step(x, y))))
    print("loss %.4f -> %.4f" % (losses[0], losses[-1]))
    step.sync_to_params()   # checkpoint through the normal Gluon API
    return losses


if __name__ == "__main__":
    main()
