#!/usr/bin/env python
"""Module API walkthrough (reference: example/module/mnist_mlp.py —
the symbolic bind/init/fit workflow, plus manual forward/backward and
checkpointing)."""

import argparse
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx


def build_sym():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def main(argv=None):
    p = argparse.ArgumentParser(description="Module API example")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.2)
    args = p.parse_args(argv)
    mx.random.seed(7)

    from mxnet_tpu.io.io import MNISTIter

    train = MNISTIter(image="train", batch_size=args.batch_size)
    val = MNISTIter(image="val", batch_size=args.batch_size, shuffle=False)

    # 1. the high-level fit loop
    mod = mx.mod.Module(build_sym(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="sgd", optimizer_params={"learning_rate": args.lr})
    acc = mx.metric.Accuracy()
    val.reset()
    mod.score(val, acc)
    print("fit(): val accuracy %.4f" % acc.get()[1])

    # 2. the manual loop the fit sugar expands to
    mod2 = mx.mod.Module(build_sym(), data_names=("data",),
                         label_names=("softmax_label",))
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_params(mx.init.Xavier())
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": args.lr})
    metric = mx.metric.Accuracy()
    for _ in range(args.epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod2.forward(batch, is_train=True)
            mod2.update_metric(metric, batch.label)
            mod2.backward()
            mod2.update()
    print("manual loop: train accuracy %.4f" % metric.get()[1])

    # 3. checkpoint round trip
    prefix = os.path.join(tempfile.mkdtemp(), "mlp")
    mod.save_checkpoint(prefix, args.epochs)
    mod3 = mx.mod.Module.load(prefix, args.epochs, data_names=("data",),
                              label_names=("softmax_label",))
    mod3.bind(data_shapes=val.provide_data, label_shapes=val.provide_label)
    acc3 = mx.metric.Accuracy()
    val.reset()
    mod3.score(val, acc3)
    print("reloaded: val accuracy %.4f" % acc3.get()[1])
    assert abs(acc3.get()[1] - acc.get()[1]) < 1e-6
    return acc.get()[1]


if __name__ == "__main__":
    main()
