#!/usr/bin/env python
"""Character-level CNN text classification with pre-trained embeddings
and a highway layer (reference:
example/cnn_chinese_text_classification/text_cnn.py — the Chinese
variant of Kim 2014: sentences tokenized to characters, embedded by a
pre-trained word2vec table fed to the net as DENSE VECTORS, multi-width
convolutions, then a highway network before the classifier).

The two API-distinct pieces vs example/cnn_text_classification:

* ``pre_trained_word2vec`` path: data enters as (N, 1, T, E) float
  vectors — no Embedding layer in the graph (reference
  build_input_data_with_word2vec / sym_gen's pre_trained_word2vec
  branch);
* ``highway()``: g = relu(W_h x + b_h); t = sigmoid(W_t x + b_t);
  out = g * t + x * (1 - t) (reference text_cnn.py:79).

The corpus is synthetic (zero-egress): a fixed random embedding table
over a 500-"character" vocabulary; a sentence's class is decided by
which character cluster dominates it.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx

VOCAB = 500
SEQ_LEN = 32
NUM_EMBED = 24
N_CLASSES = 4
CLUSTER = VOCAB // N_CLASSES


def make_corpus(rng, n):
    """Class c's sentences oversample characters from cluster c."""
    table = rng.normal(0, 1, (VOCAB, NUM_EMBED)).astype(np.float32)
    y = rng.randint(0, N_CLASSES, n)
    x_ids = rng.randint(0, VOCAB, (n, SEQ_LEN))
    for i in range(n):
        k = rng.randint(8, 16)
        pos = rng.choice(SEQ_LEN, k, replace=False)
        x_ids[i, pos] = rng.randint(y[i] * CLUSTER,
                                    (y[i] + 1) * CLUSTER, k)
    # the pre-trained-word2vec input path: embed on the host, feed vectors
    x_vec = table[x_ids].reshape(n, 1, SEQ_LEN, NUM_EMBED)
    return x_vec.astype(np.float32), y.astype(np.float32)


def highway(data, num_hidden):
    """Highway network block (reference text_cnn.py:79); num_hidden
    must equal the input width so the carry gate can mix identity."""
    g = mx.sym.FullyConnected(data, num_hidden=num_hidden,
                              name="highway_g")
    g = mx.sym.Activation(g, act_type="relu")
    t = mx.sym.FullyConnected(data, num_hidden=num_hidden,
                              name="highway_t")
    t = mx.sym.Activation(t, act_type="sigmoid")
    return g * t + data * (1.0 - t)


def sym_gen(filter_widths=(2, 3, 4), num_filter=64, dropout=0.3):
    data = mx.sym.Variable("data")          # (N, 1, T, E) vectors
    label = mx.sym.Variable("softmax_label")
    pooled = []
    for width in filter_widths:
        conv = mx.sym.Convolution(data, kernel=(width, NUM_EMBED),
                                  num_filter=num_filter)
        act = mx.sym.Activation(conv, act_type="relu")
        pooled.append(mx.sym.Pooling(
            act, pool_type="max",
            kernel=(SEQ_LEN - width + 1, 1)))
    concat = mx.sym.Concat(*pooled, dim=1)
    h_pool = mx.sym.Reshape(concat,
                            shape=(-1, num_filter * len(filter_widths)))
    h_pool = highway(h_pool, num_filter * len(filter_widths))
    if dropout > 0:
        h_pool = mx.sym.Dropout(h_pool, p=dropout)
    fc = mx.sym.FullyConnected(h_pool, num_hidden=N_CLASSES)
    return mx.sym.SoftmaxOutput(fc, label=label, name="softmax")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n-train", type=int, default=2048)
    p.add_argument("--n-test", type=int, default=512)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--dropout", type=float, default=0.3)
    p.add_argument("--seed", type=int, default=2)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    X, y = make_corpus(rng, args.n_train + args.n_test)
    Xt, yt = X[args.n_train:], y[args.n_train:]
    X, y = X[:args.n_train], y[:args.n_train]

    train_iter = mx.io.NDArrayIter(data=X, label=y,
                                   batch_size=args.batch_size,
                                   shuffle=True)
    module = mx.mod.Module(sym_gen(dropout=args.dropout),
                           data_names=("data",),
                           label_names=("softmax_label",))
    module.fit(train_iter, eval_metric="acc", optimizer="adam",
               optimizer_params={"learning_rate": args.lr},
               initializer=mx.init.Xavier(),
               num_epoch=args.epochs)

    test_iter = mx.io.NDArrayIter(data=Xt, label=yt,
                                  batch_size=args.batch_size)
    pred = module.predict(test_iter).asnumpy()[:len(yt)].argmax(1)
    acc = float((pred == yt).mean())
    print("Test accuracy %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
