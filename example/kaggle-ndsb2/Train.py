#!/usr/bin/env python
"""Second National Data Science Bowl: cardiac volume estimation
(reference: example/kaggle-ndsb2/Train.py — 30-frame short-axis MRI
sequences; the net differences consecutive frames with SliceChannel,
runs a small conv net, and regresses the 600-bin volume CDF with
LogisticRegressionOutput, scored by CRPS).

API-distinct pieces exercised here: SliceChannel frame differencing
inside the Symbol, a 600-way sigmoid CDF head, the numpy custom metric
bridge (mx.metric.np(CRPS)), and the reference's label CDF encoding.

Data is synthetic (zero-egress): each "study" is a 30-frame sequence of
a pulsating disc; end-systolic/diastolic volumes derive from the disc's
min/max area, so the CDF target is physically meaningful.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx

FRAMES = 30
SIZE = 32
BINS = 600


def make_studies(rng, n):
    """Pulsating discs: radius r(t) = r0 * (1 + a sin(2 pi t/T + phi))."""
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    X = np.zeros((n, FRAMES, SIZE, SIZE), np.float32)
    vol_sys = np.zeros(n, np.float32)
    vol_dia = np.zeros(n, np.float32)
    for i in range(n):
        r0 = rng.uniform(4, 9)
        a = rng.uniform(0.1, 0.35)
        phi = rng.uniform(0, 2 * np.pi)
        cy, cx = rng.uniform(12, 20, 2)
        for t in range(FRAMES):
            r = r0 * (1 + a * np.sin(2 * np.pi * t / FRAMES + phi))
            disc = ((yy - cy) ** 2 + (xx - cx) ** 2 <= r * r)
            X[i, t] = disc * rng.uniform(0.85, 1.0) \
                + rng.normal(0.08, 0.04, (SIZE, SIZE))
        rmin, rmax = r0 * (1 - a), r0 * (1 + a)
        # "volume" in ml-like units from the disc areas
        vol_sys[i] = np.pi * rmin ** 2 * 0.5
        vol_dia[i] = np.pi * rmax ** 2 * 0.5
    return X, vol_sys, vol_dia


def encode_label(vols):
    """Volume -> 600-step CDF target (reference Train.py encode_label)."""
    y = np.zeros((len(vols), BINS), np.float32)
    for i, v in enumerate(vols):
        y[i, int(min(max(v, 0), BINS - 1)):] = 1.0
    return y


def get_lenet():
    """Frame-differencing conv net (reference Train.py get_lenet)."""
    source = mx.sym.Variable("data")
    frames = mx.sym.SliceChannel(source, num_outputs=FRAMES)
    diffs = [frames[t + 1] - frames[t] for t in range(FRAMES - 1)]
    source = mx.sym.Concat(*diffs)
    net = mx.sym.Convolution(source, kernel=(5, 5), num_filter=40)
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=40)
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    flatten = mx.sym.Flatten(net)
    flatten = mx.sym.Dropout(flatten)
    fc1 = mx.sym.FullyConnected(data=flatten, num_hidden=BINS)
    return mx.sym.LogisticRegressionOutput(data=fc1, name="softmax")


def CRPS(label, pred):
    """Continuous Ranked Probability Score over the CDF bins
    (reference Train.py:57)."""
    pred = np.maximum.accumulate(np.asarray(pred), axis=1)  # monotone CDF
    return np.sum(np.square(label - pred)) / label.size


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--n-train", type=int, default=256)
    p.add_argument("--n-test", type=int, default=64)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--seed", type=int, default=21)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    X, vs, vd = make_studies(rng, args.n_train + args.n_test)
    Xt, vst = X[args.n_train:], vs[args.n_train:]
    X, vs = X[:args.n_train], vs[:args.n_train]

    # train the systole model (the reference trains systole + diastole
    # with the same code; one suffices to pin the workflow)
    train_iter = mx.io.NDArrayIter(
        data=X, label=encode_label(vs),
        batch_size=args.batch_size, shuffle=True)
    module = mx.mod.Module(get_lenet(), data_names=("data",),
                           label_names=("softmax_label",))
    module.fit(train_iter, eval_metric=mx.metric.np(CRPS),
               optimizer="adam",
               optimizer_params={"learning_rate": args.lr},
               initializer=mx.init.Xavier(),
               num_epoch=args.epochs)

    test_iter = mx.io.NDArrayIter(data=Xt, label=encode_label(vst),
                                  batch_size=args.batch_size)
    pred = module.predict(test_iter).asnumpy()[:len(vst)]
    score = CRPS(encode_label(vst), pred)
    # predicted volume = number of bins with CDF < 0.5
    vol_pred = (pred < 0.5).sum(axis=1)
    mae = float(np.abs(vol_pred - vst).mean())
    print("Test CRPS %.4f, volume MAE %.1f ml (mean true %.1f)"
          % (score, mae, vst.mean()))
    return score, mae


if __name__ == "__main__":
    main()
