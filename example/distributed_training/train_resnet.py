#!/usr/bin/env python
"""Data-parallel ResNet training over a device mesh (reference:
example/distributed_training — Gluon ResNet with kvstore/horovod;
BASELINE.json config 5: kvstore='nccl' -> 'tpu').

TPU-native shape: ONE jitted SPMD train step over a jax.sharding.Mesh —
the batch is sharded over the 'dp' axis, GSPMD inserts the gradient
all-reduce over ICI, and the optimizer update runs in-graph (the analog
of the reference's push/pull + server-side optimizer, SURVEY §3.4).

Run single-host multi-device as-is (all local devices), or test without
TPUs: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8.
"""

import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.parallel.gluon_step import GluonTrainStep
from mxnet_tpu.parallel.mesh import create_mesh


def main(argv=None):
    import jax

    parser = argparse.ArgumentParser(description="data-parallel resnet")
    parser.add_argument("--network", type=str, default="resnet50_v1")
    parser.add_argument("--batch-size", type=int, default=128,
                        help="GLOBAL batch (split across the dp mesh)")
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--dtype", type=str, default="bfloat16")
    parser.add_argument("--num-devices", type=int, default=0,
                        help="0 = all devices")
    args = parser.parse_args(argv)

    devices = jax.devices()
    if args.num_devices:
        devices = devices[:args.num_devices]
    n = len(devices)
    assert args.batch_size % n == 0, "global batch must divide the mesh"
    mesh = create_mesh({"dp": n}, devices=devices)
    print("mesh: %d devices (%s)" % (n, devices[0].platform))

    shape = tuple(int(x) for x in args.image_shape.split(","))
    net = getattr(vision, args.network)(classes=args.num_classes)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    with ctx:
        net.initialize(ctx=ctx)
        net(mx.nd.zeros((1,) + shape, ctx=ctx))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=args.lr, momentum=0.9,
                          wd=1e-4,
                          compute_dtype=None if args.dtype == "float32"
                          else args.dtype)

    rng = np.random.RandomState(0)
    x = rng.rand(args.batch_size, *shape).astype(np.float32)
    y = rng.randint(0, args.num_classes, (args.batch_size,)).astype(np.int32)
    x, y = step.put_batch(x, y)

    l = None
    for _ in range(3):  # compile + warmup
        l = step(x, y)
    first = float(np.asarray(l))

    t0 = time.perf_counter()
    for _ in range(args.steps):
        l = step(x, y)
    last = float(np.asarray(l))
    dt = time.perf_counter() - t0
    ips = args.steps * args.batch_size / dt
    print("loss %.4f -> %.4f | %.1f img/s global (%.1f per device)"
          % (first, last, ips, ips / n))
    # memorizing a fixed batch: loss must drop if grads flow end-to-end
    assert last < first, (first, last)
    return ips


if __name__ == "__main__":
    main()
