"""MNIST classification with a margin loss head — SVMOutput
(reference: example/svm_mnist/svm_mnist.py).

API family: the SVMOutput op (L1/L2 hinge loss on one-vs-rest margins)
instead of softmax cross-entropy, with predictions taken as the argmax
of the raw scores.
"""

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


def build_net(use_linear=False):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=256, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SVMOutput(h, name="svm",
                            use_linear=bool(use_linear))


class ScoreAccuracy(mx.metric.EvalMetric):
    """argmax over raw margins (SVM scores are not probabilities)."""

    def __init__(self):
        super().__init__("score_acc")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            hit = (pred.asnumpy().argmax(1) ==
                   label.asnumpy().ravel()).sum()
            self.sum_metric += hit / label.shape[0]
            self.num_inst += 1


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--l1-svm", action="store_true",
                   help="linear (L1) hinge instead of squared (L2)")
    args = p.parse_args(argv)

    from mxnet_tpu.io.io import MNISTIter

    logging.basicConfig(level=logging.INFO)

    def relabeled(which, shuffle):
        # the SVM head's label variable is 'svm_label'
        inner = MNISTIter(image=which, batch_size=args.batch_size,
                          shuffle=shuffle, flat=True)
        inner.reset()
        datas, labs = [], []
        for b in inner:  # one pass: collect then rewrap under svm_label
            datas.append(b.data[0].asnumpy())
            labs.append(b.label[0].asnumpy())
        data, lab = np.concatenate(datas), np.concatenate(labs)
        return mx.io.NDArrayIter(data, lab, batch_size=args.batch_size,
                                 shuffle=shuffle, label_name="svm_label")

    train = relabeled("train", True)
    val = relabeled("val", False)

    mod = mx.mod.Module(build_net(args.l1_svm),
                        context=mx.context.current_context(),
                        label_names=("svm_label",))
    metric = ScoreAccuracy()
    mod.fit(train, eval_data=val, eval_metric=metric, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-5},
            num_epoch=args.num_epochs)
    mod.score(val, metric)  # score() resets the metric itself
    acc = metric.get()[1]
    print("svm-mnist val accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
