#!/usr/bin/env python
"""SSD object detection (reference: example/ssd — SSD-VGG16 on VOC,
BASELINE.json config 4: multibox + NMS custom ops end-to-end).

A scaled SSD: conv backbone + two feature scales, anchors from
MultiBoxPrior, training targets from MultiBoxTarget, inference through
MultiBoxDetection (decode + NMS).  Trains on synthetic single-object
scenes (zero-egress container — no VOC); the op pipeline is exactly the
reference's.  Anchors are static and the whole loss is jit-staged, so
the hot path is MXU matmuls/convs.
"""

import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import contrib as ndc


class TinySSD(gluon.Block):
    """Backbone + per-scale class/box heads (reference:
    example/ssd/symbol/symbol_builder.py structure, scaled down)."""

    SIZES = [(0.2, 0.27), (0.45, 0.55)]
    RATIOS = [(1.0, 2.0, 0.5)] * 2

    def __init__(self, num_classes=3, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.num_anchors = len(self.SIZES[0]) + len(self.RATIOS[0]) - 1
        self.backbone = nn.Sequential()
        for f in (16, 32):
            self.backbone.add(nn.Conv2D(f, 3, padding=1),
                              nn.BatchNorm(), nn.Activation("relu"),
                              nn.MaxPool2D(2))
        self.scale1 = nn.Sequential()
        self.scale1.add(nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                        nn.Activation("relu"))
        self.down = nn.Sequential()
        self.down.add(nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                      nn.Activation("relu"), nn.MaxPool2D(2))
        a, c = self.num_anchors, num_classes
        self.cls1 = nn.Conv2D(a * (c + 1), 3, padding=1)
        self.loc1 = nn.Conv2D(a * 4, 3, padding=1)
        self.cls2 = nn.Conv2D(a * (c + 1), 3, padding=1)
        self.loc2 = nn.Conv2D(a * 4, 3, padding=1)

    def forward(self, x):
        feats = []
        x = self.backbone(x)
        f1 = self.scale1(x)
        feats.append((f1, self.cls1, self.loc1, self.SIZES[0],
                      self.RATIOS[0]))
        f2 = self.down(f1)
        feats.append((f2, self.cls2, self.loc2, self.SIZES[1],
                      self.RATIOS[1]))
        anchors, cls_preds, loc_preds = [], [], []
        for f, cls_head, loc_head, sizes, ratios in feats:
            anchors.append(ndc.MultiBoxPrior(f, sizes=sizes, ratios=ratios))
            cp = cls_head(f)  # (B, A*(C+1), H, W)
            b = cp.shape[0]
            cp = cp.transpose((0, 2, 3, 1)).reshape(
                (b, -1, self.num_classes + 1))
            cls_preds.append(cp)
            lp = loc_head(f).transpose((0, 2, 3, 1)).reshape((b, -1))
            loc_preds.append(lp)
        anchor = mx.nd.concat(*anchors, dim=1)          # (1, N, 4)
        cls_pred = mx.nd.concat(*cls_preds, dim=1)       # (B, N, C+1)
        loc_pred = mx.nd.concat(*loc_preds, dim=1)       # (B, N*4)
        return anchor, cls_pred, loc_pred


def synthetic_scene(rng, n, hw=64, num_classes=3):
    """Images with ONE solid axis-aligned box; class = channel colour."""
    x = rng.rand(n, 3, hw, hw).astype(np.float32) * 0.1
    labels = np.full((n, 1, 5), -1.0, dtype=np.float32)
    for i in range(n):
        cls = rng.randint(num_classes)
        w, h = rng.randint(hw // 4, hw // 2, 2)
        x0 = rng.randint(0, hw - w)
        y0 = rng.randint(0, hw - h)
        x[i, cls, y0:y0 + h, x0:x0 + w] += 0.8
        labels[i, 0] = [cls, x0 / hw, y0 / hw, (x0 + w) / hw, (y0 + h) / hw]
    return x, labels


def train(args):
    rng = np.random.RandomState(0)
    net = TinySSD(num_classes=args.num_classes)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    l1 = gluon.loss.L1Loss()

    def cls_loss_fn(cls_pred, cls_t):
        """CE over valid anchors only (reference: SoftmaxOutput with
        ignore_label=-1, normalization='valid').  Targets come from
        hard-negative mining, so backgrounds don't drown positives."""
        log_p = mx.nd.log_softmax(cls_pred, axis=-1)
        ce = -mx.nd.pick(log_p, mx.nd.clip(cls_t, 0, 1e9), axis=-1)
        valid = (cls_t >= 0).astype("float32")
        return (ce * valid).sum() / mx.nd.clip(valid.sum(), 1.0, 1e18)

    x_all, y_all = synthetic_scene(rng, args.num_examples, args.data_shape,
                                   args.num_classes)
    B = args.batch_size
    for epoch in range(args.epochs):
        tot_cls = tot_loc = nb = 0.0
        tic = time.time()
        for i in range(0, args.num_examples - B + 1, B):
            data = mx.nd.array(x_all[i:i + B])
            label = mx.nd.array(y_all[i:i + B])
            with mx.autograd.record():
                anchor, cls_pred, loc_pred = net(data)
                loc_t, loc_m, cls_t = ndc.MultiBoxTarget(
                    anchor, label, cls_pred.transpose((0, 2, 1)),
                    negative_mining_ratio=3.0)
                Lc = cls_loss_fn(cls_pred, cls_t)
                Ll = l1(loc_pred * loc_m, loc_t * loc_m)
                L = Lc + args.loc_weight * Ll
            L.backward()
            trainer.step(B)
            tot_cls += float(Lc.mean().asnumpy())
            tot_loc += float(Ll.mean().asnumpy())
            nb += 1
        print("epoch %d: cls %.4f loc %.4f (%.1fs)"
              % (epoch, tot_cls / nb, tot_loc / nb, time.time() - tic))
    return net


def evaluate(net, args, n=32):
    """Fraction of scenes whose top detection matches class @ IoU>=0.5."""
    rng = np.random.RandomState(99)
    x, y = synthetic_scene(rng, n, args.data_shape, args.num_classes)
    anchor, cls_pred, loc_pred = net(mx.nd.array(x))
    probs = mx.nd.softmax(cls_pred, axis=-1).transpose((0, 2, 1))
    det = ndc.MultiBoxDetection(probs, loc_pred, anchor,
                                nms_threshold=0.45)
    det = det.asnumpy()  # (B, N, 6): [cls, score, x1, y1, x2, y2]
    hits = 0
    for i in range(n):
        rows = det[i]
        rows = rows[rows[:, 0] >= 0]
        if not len(rows):
            continue
        best = rows[rows[:, 1].argmax()]
        gt = y[i, 0]
        ix1, iy1 = max(best[2], gt[1]), max(best[3], gt[2])
        ix2, iy2 = min(best[4], gt[3]), min(best[5], gt[4])
        inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
        a1 = (best[4] - best[2]) * (best[5] - best[3])
        a2 = (gt[3] - gt[1]) * (gt[4] - gt[2])
        iou = inter / max(a1 + a2 - inter, 1e-9)
        if int(best[0]) == int(gt[0]) and iou >= 0.5:
            hits += 1
    return hits / n


def main(argv=None):
    parser = argparse.ArgumentParser(description="train SSD")
    parser.add_argument("--num-classes", type=int, default=3)
    parser.add_argument("--num-examples", type=int, default=256)
    parser.add_argument("--data-shape", type=int, default=64)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--loc-weight", type=float, default=5.0)
    args = parser.parse_args(argv)
    net = train(args)
    acc = evaluate(net, args)
    print("detection accuracy (top-1 class @ IoU>=0.5): %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
