#!/usr/bin/env python
"""SSD object detection (reference: example/ssd — SSD-VGG16 on VOC,
BASELINE.json config 4: multibox + NMS custom ops end-to-end).

A scaled SSD: conv backbone + two feature scales, anchors from
MultiBoxPrior, training targets from MultiBoxTarget, inference through
MultiBoxDetection (decode + NMS).  The data path is the reference's
real workflow (example/ssd/train.py + image/detection.py): scenes are
written to disk as JPEG files with a VOC-style detection .lst, packed
into a .rec by tools/im2rec.py --pack-label, and consumed through
ImageDetIter with label-aware augmentation.  (Zero-egress container —
the scenes themselves are synthetic single/two-object images, but every
byte flows through the record + det-augmenter pipeline.)  Anchors are
static and the whole loss is jit-staged, so the hot path is MXU
matmuls/convs.
"""

import argparse
import importlib.util
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.image import ImageDetIter
from mxnet_tpu.ndarray import contrib as ndc

class TinySSD(gluon.Block):
    """Backbone + per-scale class/box heads (reference:
    example/ssd/symbol/symbol_builder.py structure, scaled down)."""

    SIZES = [(0.2, 0.27), (0.45, 0.55)]
    RATIOS = [(1.0, 2.0, 0.5)] * 2

    def __init__(self, num_classes=3, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.num_anchors = len(self.SIZES[0]) + len(self.RATIOS[0]) - 1
        self.backbone = nn.Sequential()
        for f in (16, 32):
            self.backbone.add(nn.Conv2D(f, 3, padding=1),
                              nn.BatchNorm(), nn.Activation("relu"),
                              nn.MaxPool2D(2))
        self.scale1 = nn.Sequential()
        self.scale1.add(nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                        nn.Activation("relu"))
        self.down = nn.Sequential()
        self.down.add(nn.Conv2D(32, 3, padding=1), nn.BatchNorm(),
                      nn.Activation("relu"), nn.MaxPool2D(2))
        a, c = self.num_anchors, num_classes
        self.cls1 = nn.Conv2D(a * (c + 1), 3, padding=1)
        self.loc1 = nn.Conv2D(a * 4, 3, padding=1)
        self.cls2 = nn.Conv2D(a * (c + 1), 3, padding=1)
        self.loc2 = nn.Conv2D(a * 4, 3, padding=1)

    def forward(self, x):
        feats = []
        x = self.backbone(x)
        f1 = self.scale1(x)
        feats.append((f1, self.cls1, self.loc1, self.SIZES[0],
                      self.RATIOS[0]))
        f2 = self.down(f1)
        feats.append((f2, self.cls2, self.loc2, self.SIZES[1],
                      self.RATIOS[1]))
        anchors, cls_preds, loc_preds = [], [], []
        for f, cls_head, loc_head, sizes, ratios in feats:
            anchors.append(ndc.MultiBoxPrior(f, sizes=sizes, ratios=ratios))
            cp = cls_head(f)  # (B, A*(C+1), H, W)
            b = cp.shape[0]
            cp = cp.transpose((0, 2, 3, 1)).reshape(
                (b, -1, self.num_classes + 1))
            cls_preds.append(cp)
            lp = loc_head(f).transpose((0, 2, 3, 1)).reshape((b, -1))
            loc_preds.append(lp)
        anchor = mx.nd.concat(*anchors, dim=1)          # (1, N, 4)
        cls_pred = mx.nd.concat(*cls_preds, dim=1)       # (B, N, C+1)
        loc_pred = mx.nd.concat(*loc_preds, dim=1)       # (B, N*4)
        return anchor, cls_pred, loc_pred


# ------------------------------------------------------------ data path


def make_scenes(rng, n, hw, num_classes, max_objs=1):
    """Synthetic scenes as uint8 HWC images + [cls,x1,y1,x2,y2] rows.
    Class = which colour channel the solid box brightens."""
    scenes = []
    for _ in range(n):
        img = (rng.rand(hw, hw, 3) * 40).astype(np.uint8)
        rows = []
        placed = []
        for _ in range(rng.randint(1, max_objs + 1)):
            cls = rng.randint(num_classes)
            w, h = rng.randint(hw // 4, hw // 2, 2)
            x0 = rng.randint(0, hw - w)
            y0 = rng.randint(0, hw - h)
            # keep boxes disjoint so class colours stay unambiguous
            if any(x0 < px1 and px0 < x0 + w and y0 < py1 and py0 < y0 + h
                   for px0, py0, px1, py1 in placed):
                continue
            img[y0:y0 + h, x0:x0 + w, cls] = 230
            placed.append((x0, y0, x0 + w, y0 + h))
            rows.append([cls, x0 / hw, y0 / hw, (x0 + w) / hw, (y0 + h) / hw])
        scenes.append((img, rows))
    return scenes


def write_rec(dirpath, prefix, scenes, quality=95):
    """JPEG files + detection .lst -> .rec via tools/im2rec.py
    --pack-label (the reference's real packing workflow)."""
    from PIL import Image

    root = os.path.join(dirpath, prefix + "_images")
    os.makedirs(root, exist_ok=True)
    lst_prefix = os.path.join(dirpath, prefix)
    with open(lst_prefix + ".lst", "w") as lst:
        for i, (img, rows) in enumerate(scenes):
            fname = "%s_%05d.jpg" % (prefix, i)
            Image.fromarray(img).save(os.path.join(root, fname),
                                      quality=quality)
            flat = [2, 5]  # header_width, obj_width
            for row in rows:
                flat.extend(row)
            cols = "\t".join("%.6f" % v for v in flat)
            lst.write("%d\t%s\t%s\n" % (i, cols, fname))
    spec = importlib.util.spec_from_file_location(
        "im2rec_tool", os.path.join(REPO, "tools", "im2rec.py"))
    im2rec = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(im2rec)
    im2rec.main([lst_prefix, root, "--pack-label"])
    return lst_prefix + ".rec"


def det_iter(rec_path, batch_size, hw, train):
    kwargs = dict(rand_mirror=True, shuffle=True) if train else {}
    return ImageDetIter(batch_size=batch_size, data_shape=(3, hw, hw),
                        path_imgrec=rec_path, mean=True, std=True, **kwargs)


# ------------------------------------------------------------ training


def train(args, train_rec):
    net = TinySSD(num_classes=args.num_classes)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    l1 = gluon.loss.L1Loss()

    def cls_loss_fn(cls_pred, cls_t):
        """CE over valid anchors only (reference: SoftmaxOutput with
        ignore_label=-1, normalization='valid').  Targets come from
        hard-negative mining, so backgrounds don't drown positives."""
        log_p = mx.nd.log_softmax(cls_pred, axis=-1)
        ce = -mx.nd.pick(log_p, mx.nd.clip(cls_t, 0, 1e9), axis=-1)
        valid = (cls_t >= 0).astype("float32")
        return (ce * valid).sum() / mx.nd.clip(valid.sum(), 1.0, 1e18)

    it = det_iter(train_rec, args.batch_size, args.data_shape, train=True)
    for epoch in range(args.epochs):
        tot_cls = tot_loc = nb = 0.0
        tic = time.time()
        it.reset()
        for batch in it:
            if batch.pad:
                continue
            data, label = batch.data[0], batch.label[0]
            with mx.autograd.record():
                anchor, cls_pred, loc_pred = net(data)
                loc_t, loc_m, cls_t = ndc.MultiBoxTarget(
                    anchor, label, cls_pred.transpose((0, 2, 1)),
                    negative_mining_ratio=3.0)
                Lc = cls_loss_fn(cls_pred, cls_t)
                Ll = l1(loc_pred * loc_m, loc_t * loc_m)
                L = Lc + args.loc_weight * Ll
            L.backward()
            trainer.step(args.batch_size)
            tot_cls += float(Lc.mean().asnumpy())
            tot_loc += float(Ll.mean().asnumpy())
            nb += 1
        print("epoch %d: cls %.4f loc %.4f (%.1fs)"
              % (epoch, tot_cls / nb, tot_loc / nb, time.time() - tic))
    return net


def evaluate(net, args, val_rec, n=32):
    """Fraction of scenes whose top detection matches class @ IoU>=0.5;
    ground truth read back through the same ImageDetIter."""
    it = det_iter(val_rec, n, args.data_shape, train=False)
    batch = next(iter(it))
    data, labels = batch.data[0], batch.label[0].asnumpy()
    anchor, cls_pred, loc_pred = net(data)
    probs = mx.nd.softmax(cls_pred, axis=-1).transpose((0, 2, 1))
    det = ndc.MultiBoxDetection(probs, loc_pred, anchor,
                                nms_threshold=0.45)
    det = det.asnumpy()  # (B, N, 6): [cls, score, x1, y1, x2, y2]
    hits = 0
    for i in range(n):
        rows = det[i]
        rows = rows[rows[:, 0] >= 0]
        if not len(rows):
            continue
        best = rows[rows[:, 1].argmax()]
        gt = labels[i, 0]  # single-object val scenes: row 0 is the object
        ix1, iy1 = max(best[2], gt[1]), max(best[3], gt[2])
        ix2, iy2 = min(best[4], gt[3]), min(best[5], gt[4])
        inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
        a1 = (best[4] - best[2]) * (best[5] - best[3])
        a2 = (gt[3] - gt[1]) * (gt[4] - gt[2])
        iou = inter / max(a1 + a2 - inter, 1e-9)
        if int(best[0]) == int(gt[0]) and iou >= 0.5:
            hits += 1
    return hits / n


def main(argv=None):
    parser = argparse.ArgumentParser(description="train SSD")
    parser.add_argument("--num-classes", type=int, default=3)
    parser.add_argument("--num-examples", type=int, default=256)
    parser.add_argument("--data-shape", type=int, default=64)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=2e-3)
    parser.add_argument("--loc-weight", type=float, default=5.0)
    parser.add_argument("--max-objs", type=int, default=2,
                        help="max objects per training scene")
    parser.add_argument("--data-dir", default=None,
                        help="where to build the .rec dataset "
                             "(default: a fresh temp dir)")
    args = parser.parse_args(argv)

    rng = np.random.RandomState(0)
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="ssd_data_")
    train_rec = write_rec(data_dir, "train",
                          make_scenes(rng, args.num_examples,
                                      args.data_shape, args.num_classes,
                                      max_objs=args.max_objs))
    val_rec = write_rec(data_dir, "val",
                        make_scenes(np.random.RandomState(99), 32,
                                    args.data_shape, args.num_classes,
                                    max_objs=1))
    net = train(args, train_rec)
    acc = evaluate(net, args, val_rec)
    print("detection accuracy (top-1 class @ IoU>=0.5): %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
