"""Fast Gradient Sign Method adversarial examples on MNIST
(reference: example/adversary/adversary_generation.ipynb).

The API this family exercises: gradients **with respect to the input
data**, not the parameters — `x.attach_grad()` + `autograd.record` +
`x.grad` — then perturbing along sign(grad) and measuring the accuracy
drop.
"""

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def train_classifier(train_iter, epochs=2, lr=0.1):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(128, activation="relu"))
        net.add(gluon.nn.Dense(64, activation="relu"))
        net.add(gluon.nn.Dense(10))
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(epochs):
        train_iter.reset()
        for batch in train_iter:
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
    return net, loss_fn


def accuracy(net, x, y):
    pred = net(x).asnumpy().argmax(1)
    return float(np.mean(pred == y.asnumpy().ravel()))


def fgsm_attack(net, loss_fn, x, y, epsilon):
    """Perturb x by epsilon * sign(dL/dx)."""
    x = x.copy() if hasattr(x, "copy") else x
    x.attach_grad()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    return mx.nd.clip(x + epsilon * mx.nd.sign(x.grad), 0.0, 1.0)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--epsilon", type=float, default=0.15)
    p.add_argument("--batch-size", type=int, default=128)
    args = p.parse_args(argv)

    from mxnet_tpu.io.io import MNISTIter

    train = MNISTIter(image="train", batch_size=args.batch_size, flat=True)
    net, loss_fn = train_classifier(train, epochs=args.epochs)

    val = MNISTIter(image="val", batch_size=256, shuffle=False, flat=True)
    batch = next(iter(val))
    x, y = batch.data[0], batch.label[0]

    clean_acc = accuracy(net, x, y)
    x_adv = fgsm_attack(net, loss_fn, x, y, args.epsilon)
    adv_acc = accuracy(net, x_adv, y)
    # perturbation is bounded by epsilon in L-inf
    linf = float(np.abs((x_adv - x).asnumpy()).max())
    print("clean acc %.3f -> adversarial acc %.3f (eps=%.2f, Linf=%.3f)"
          % (clean_acc, adv_acc, args.epsilon, linf))
    assert linf <= args.epsilon + 1e-5
    return clean_acc, adv_acc


if __name__ == "__main__":
    main()
