"""Fully-convolutional segmentation with skip connections
(reference: example/fcn-xs — FCN-32s/16s/8s, Long et al. 2015).

API family: Deconvolution upsampling + Crop alignment + per-pixel
SoftmaxOutput (multi_output=True), trained on a synthetic blob-mask
task so the pipeline is self-contained.
"""

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


HW = 32
CLASSES = 3


def synthetic_blobs(n, seed=0):
    """Images with bright square blobs; mask = class of covering blob."""
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 1, HW, HW).astype(np.float32) * 0.2
    y = np.zeros((n, HW, HW), np.float32)
    for i in range(n):
        for cls in (1, 2):
            r, c = rs.randint(0, HW - 10, 2)
            size = rs.randint(6, 12)
            x[i, 0, r:r + size, c:c + size] += 0.4 * cls
            y[i, r:r + size, c:c + size] = cls
    return x, y


def build_fcn():
    data = mx.sym.Variable("data")
    # encoder: two pooled conv stages
    c1 = mx.sym.Activation(mx.sym.Convolution(
        data, kernel=(3, 3), pad=(1, 1), num_filter=16, name="c1"),
        act_type="relu")
    p1 = mx.sym.Pooling(c1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    c2 = mx.sym.Activation(mx.sym.Convolution(
        p1, kernel=(3, 3), pad=(1, 1), num_filter=32, name="c2"),
        act_type="relu")
    p2 = mx.sym.Pooling(c2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    # per-scale class scores
    score2 = mx.sym.Convolution(p2, kernel=(1, 1), num_filter=CLASSES,
                                name="score2")            # HW/4
    score1 = mx.sym.Convolution(p1, kernel=(1, 1), num_filter=CLASSES,
                                name="score1")            # HW/2
    # FCN-16s-style fusion: upsample deep scores, crop, add skip
    up2 = mx.sym.Deconvolution(score2, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=CLASSES,
                               no_bias=True, name="up2")  # -> HW/2
    up2 = mx.sym.Crop(up2, score1, num_args=2)
    fused = up2 + score1
    up1 = mx.sym.Deconvolution(fused, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=CLASSES,
                               no_bias=True, name="up1")  # -> HW
    up1 = mx.sym.Crop(up1, data, num_args=2)
    return mx.sym.SoftmaxOutput(up1, multi_output=True, name="softmax")


def pixel_accuracy(mod, it):
    it.reset()
    hit = tot = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(1)
        lab = batch.label[0].asnumpy()
        hit += (pred == lab).sum()
        tot += lab.size
    return hit / tot


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    xtr, ytr = synthetic_blobs(320)
    xva, yva = synthetic_blobs(96, seed=1)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(xva, yva, batch_size=args.batch_size)

    mod = mx.mod.Module(build_fcn(), context=mx.context.current_context())
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier(),
            num_epoch=args.num_epochs)

    acc = pixel_accuracy(mod, val)
    print("fcn pixel accuracy: %.3f (all-background would be ~0.86)" % acc)
    return acc


if __name__ == "__main__":
    main()
