"""Bernoulli RBM trained with contrastive divergence (CD-1)
(reference: example/restricted-boltzmann-machine/binary_rbm*.py).

API family: a training paradigm with NO autograd and no loss symbol —
parameters update from the difference of data-phase and model-phase
statistics, built from raw NDArray ops and the explicit-seed sampler.
"""

import argparse

import numpy as np

import mxnet_tpu as mx


class BinaryRBM:
    def __init__(self, n_visible, n_hidden, lr=0.05, seed=0):
        mx.random.seed(seed)  # the Gibbs sampler draws from this stream
        rs = np.random.RandomState(seed)
        self.w = mx.nd.array(
            rs.normal(0, 0.05, (n_visible, n_hidden)).astype(np.float32))
        self.bv = mx.nd.zeros((n_visible,))
        self.bh = mx.nd.zeros((n_hidden,))
        self.lr = lr

    def _h_given_v(self, v):
        return mx.nd.sigmoid(mx.nd.dot(v, self.w) + self.bh)

    def _v_given_h(self, h):
        return mx.nd.sigmoid(mx.nd.dot(h, self.w.T) + self.bv)

    @staticmethod
    def _sample(p):
        return (mx.nd.random.uniform(shape=p.shape) < p).astype("float32")

    def cd1_update(self, v0):
        """One CD-1 step; returns the batch reconstruction error."""
        batch = v0.shape[0]
        ph0 = self._h_given_v(v0)
        h0 = self._sample(ph0)
        v1 = self._v_given_h(h0)  # mean-field reconstruction
        ph1 = self._h_given_v(v1)

        pos = mx.nd.dot(v0.T, ph0)
        neg = mx.nd.dot(v1.T, ph1)
        self.w += self.lr / batch * (pos - neg)
        self.bv += self.lr * mx.nd.mean(v0 - v1, axis=0)
        self.bh += self.lr * mx.nd.mean(ph0 - ph1, axis=0)
        err = mx.nd.mean(mx.nd.square(v0 - v1))
        return float(err.asnumpy())


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--n-hidden", type=int, default=64)
    args = p.parse_args(argv)

    from mxnet_tpu.io.io import MNISTIter

    train = MNISTIter(image="train", batch_size=args.batch_size, flat=True)
    rbm = BinaryRBM(28 * 28, args.n_hidden)

    first_err = last_err = None
    for epoch in range(args.epochs):
        train.reset()
        errs = []
        for batch in train:
            v = (batch.data[0] > 0.5).astype("float32")
            errs.append(rbm.cd1_update(v))
        if first_err is None:
            first_err = errs[0]
        last_err = float(np.mean(errs[-10:]))
        print("epoch %d: recon error %.4f" % (epoch, last_err))

    print("reconstruction error %.4f -> %.4f" % (first_err, last_err))
    return first_err, last_err


if __name__ == "__main__":
    main()
