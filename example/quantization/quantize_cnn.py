#!/usr/bin/env python
"""INT8 quantization with calibration (reference: example/quantization/
imagenet_gen_qsym.py + imagenet_inference.py — quantize a trained FP32
net to int8 with naive/entropy calibration and compare accuracy).

Zero-egress scaling: a small CNN is trained on synthetic separable
images (class = which quadrant is bright), then quantized through the
full calibration flow — forward stats collection over a calibration
iterator, threshold selection (naive min/max or KL-divergence entropy),
graph rewrite to int8 ops with int32 accumulation (MXU-native), and a
SymbolBlock you run like any Gluon model.  FP32 vs int8 accuracy is
reported; int8 must stay within a small margin.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def make_data(rng, n, hw=16):
    """Class = which image quadrant carries the bright blob."""
    x = (rng.rand(n, 3, hw, hw) * 0.3).astype(np.float32)
    y = rng.randint(0, 4, n).astype(np.int32)
    h = hw // 2
    for i in range(n):
        r, c = divmod(int(y[i]), 2)
        x[i, :, r * h:(r + 1) * h, c * h:(c + 1) * h] += 1.0
    return x, y


def build_cnn():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Dense(4))
    return net


def accuracy(net, x, y, batch=64):
    hits = 0
    for i in range(0, len(x), batch):
        out = net(mx.nd.array(x[i:i + batch])).asnumpy()
        hits += int((out.argmax(axis=1) == y[i:i + batch]).sum())
    return hits / len(x)


def main(argv=None):
    p = argparse.ArgumentParser(description="int8 quantization flow")
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--calib-mode", choices=("naive", "entropy"),
                   default="naive")
    p.add_argument("--num-calib-examples", type=int, default=128)
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history

    rng = np.random.RandomState(0)
    x, y = make_data(rng, args.num_examples)
    xv, yv = make_data(np.random.RandomState(99), 256)

    # -- FP32 training
    net = build_cnn()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    B = args.batch_size
    for epoch in range(args.epochs):
        for i in range(0, args.num_examples - B + 1, B):
            data = mx.nd.array(x[i:i + B])
            label = mx.nd.array(y[i:i + B])
            with mx.autograd.record():
                L = ce(net(data), label)
            L.backward()
            trainer.step(B)
        print("epoch %d: loss %.4f" % (epoch, float(L.mean().asnumpy())))
    fp32_acc = accuracy(net, xv, yv)

    # -- calibrated INT8 quantization (the reference's gen_qsym flow)
    calib = mx.io.NDArrayIter(data=x[:args.num_calib_examples],
                              label=y[:args.num_calib_examples],
                              batch_size=B)
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode=args.calib_mode,
                           num_calib_examples=args.num_calib_examples)
    int8_acc = accuracy(qnet, xv, yv)
    print("fp32 accuracy %.4f | int8(%s) accuracy %.4f"
          % (fp32_acc, args.calib_mode, int8_acc))
    return fp32_acc, int8_acc


if __name__ == "__main__":
    main()
