#!/usr/bin/env python
"""VAE-GAN: autoencoding beyond pixels with a learned similarity metric
(reference: example/vae-gan/vaegan_mxnet.py — Larsen et al. 2016).

Three networks trained jointly, as in the reference:

* encoder E:    conv net -> (mu, log_var); z sampled by the
                reparameterization trick.
* generator G:  transposed-conv net decoding z to an image.
* discriminator D: split like the reference's discriminator1 /
                discriminator2 — a conv feature trunk l(x) and a
                real/fake head on top of it.

Losses (reference vaegan_mxnet.py:161-211):

* KL(q(z|x) || N(0,1))                               -> E
* Gaussian log-density of l(x) under l(G(E(x)))      -> E, G
  (the "learned similarity" feature-matching term)
* standard GAN BCE on real / G(E(x)) / G(z_prior)    -> D, G

Data is an in-process shapes corpus (zero-egress container): 16x16
one-channel images of axis-aligned bright rectangles on dark noise, so
reconstruction quality is measurable against a known structure.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

IMG = 16


def make_shapes(rng, n):
    """Bright rectangles on dark noise."""
    x = rng.uniform(0.0, 0.15, (n, 1, IMG, IMG)).astype(np.float32)
    for i in range(n):
        h, w = rng.randint(4, 10, 2)
        r, c = rng.randint(0, IMG - h), rng.randint(0, IMG - w)
        x[i, 0, r:r + h, c:c + w] = rng.uniform(0.75, 1.0)
    return x


class Encoder(gluon.HybridBlock):
    def __init__(self, nef=16, z_dim=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.trunk = nn.HybridSequential()
            self.trunk.add(
                nn.Conv2D(nef, 4, strides=2, padding=1, activation="relu"),
                nn.Conv2D(nef * 2, 4, strides=2, padding=1,
                          activation="relu"),
                nn.Flatten())
            self.mu = nn.Dense(z_dim)
            self.log_var = nn.Dense(z_dim)

    def hybrid_forward(self, F, x):
        h = self.trunk(x)
        return self.mu(h), self.log_var(h)


def make_generator(ngf=16):
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        net.add(nn.Dense(ngf * 2 * 4 * 4),
                nn.HybridLambda(
                    lambda F, x: F.reshape(x, (-1, ngf * 2, 4, 4))),
                nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                   activation="relu"),
                nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                   activation="sigmoid"))
    return net


class Discriminator(gluon.HybridBlock):
    """Feature trunk l(x) + real/fake head, mirroring the reference's
    discriminator1/discriminator2 split so the feature-matching loss
    can read l(x)."""

    def __init__(self, ndf=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential()
            self.features.add(
                nn.Conv2D(ndf, 4, strides=2, padding=1,
                          activation="relu"),
                nn.Conv2D(ndf * 2, 4, strides=2, padding=1,
                          activation="relu"),
                nn.Flatten(), nn.Dense(64, activation="relu"))
            self.head = nn.Dense(1)

    def hybrid_forward(self, F, x):
        l = self.features(x)
        return self.head(l), l


def kl_loss(mu, log_var):
    """KL(q(z|x)||N(0,1)) (reference KLDivergenceLoss)."""
    return -0.5 * (1 + log_var - mu ** 2
                   - mx.nd.exp(log_var)).sum(axis=1).mean()


def gaussian_ll_loss(feat_real, feat_recon):
    """-log N(l(x); l(G(z)), I) up to a constant (reference
    GaussianLogDensity with unit variance)."""
    return 0.5 * ((feat_real - feat_recon) ** 2).sum(axis=1).mean()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n-train", type=int, default=1024)
    p.add_argument("--z-dim", type=int, default=16)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--g-dl-weight", type=float, default=0.1,
                   help="weight of the GAN term against the "
                        "feature-matching term in the G update")
    p.add_argument("--seed", type=int, default=3)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    X = make_shapes(rng, args.n_train)

    enc = Encoder(z_dim=args.z_dim)
    gen = make_generator()
    disc = Discriminator()
    for net in (enc, gen, disc):
        net.initialize(mx.init.Xavier())
    opts = {"learning_rate": args.lr, "beta1": args.beta1}
    t_enc = gluon.Trainer(enc.collect_params(), "adam", opts)
    t_gen = gluon.Trainer(gen.collect_params(), "adam", opts)
    t_disc = gluon.Trainer(disc.collect_params(), "adam", opts)
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    def recon_mse():
        data = mx.nd.array(X[:256])
        mu, _ = enc(data)
        return float(((gen(mu) - data) ** 2).mean().asscalar())

    mse0 = recon_mse()
    nb = args.n_train // args.batch_size
    for epoch in range(args.epochs):
        perm = rng.permutation(args.n_train)
        d_sum = g_sum = kl_sum = 0.0
        for b in range(nb):
            data = mx.nd.array(X[perm[b * args.batch_size:
                                      (b + 1) * args.batch_size]])
            eps = mx.nd.array(rng.normal(
                0, 1, (args.batch_size, args.z_dim)).astype(np.float32))
            zp = mx.nd.array(rng.normal(
                0, 1, (args.batch_size, args.z_dim)).astype(np.float32))
            ones = mx.nd.ones((args.batch_size, 1))
            zeros = mx.nd.zeros((args.batch_size, 1))

            # --- D step: real vs reconstruction vs prior sample
            mu, log_var = enc(data)
            z = mu + mx.nd.exp(0.5 * log_var) * eps
            recon, prior = gen(z), gen(zp)
            with autograd.record():
                out_r, _ = disc(data)
                out_f, _ = disc(recon)
                out_p, _ = disc(prior)
                d_loss = (bce(out_r, ones) + bce(out_f, zeros)
                          + bce(out_p, zeros)).mean()
            d_loss.backward()
            t_disc.step(1)

            # --- G step: fool D + match D features of the real batch
            _, feat_real = disc(data)
            with autograd.record():
                recon = gen(z)
                prior = gen(zp)
                out_f, feat_recon = disc(recon)
                out_p, _ = disc(prior)
                g_gan = (bce(out_f, ones) + bce(out_p, ones)).mean()
                g_dl = gaussian_ll_loss(feat_real, feat_recon)
                g_loss = args.g_dl_weight * g_gan + g_dl
            g_loss.backward()
            t_gen.step(1)

            # --- E step: KL + feature-matching through the sampler
            with autograd.record():
                mu, log_var = enc(data)
                z = mu + mx.nd.exp(0.5 * log_var) * eps
                recon = gen(z)
                _, feat_recon = disc(recon)
                kl = kl_loss(mu, log_var)
                e_loss = kl + gaussian_ll_loss(feat_real, feat_recon)
            e_loss.backward()
            t_enc.step(1)

            d_sum += float(d_loss.asscalar())
            g_sum += float(g_loss.asscalar())
            kl_sum += float(kl.asscalar())
        print("Epoch [%d] D %.3f G %.3f KL %.3f"
              % (epoch, d_sum / nb, g_sum / nb, kl_sum / nb))

    mse1 = recon_mse()
    print("Reconstruction MSE %.4f -> %.4f" % (mse0, mse1))
    return mse0, mse1


if __name__ == "__main__":
    main()
