"""Text CNN for sentence classification
(reference: example/cnn_text_classification/text_cnn.py, Kim 2014).

API family: Embedding → parallel Convolution branches with different
kernel widths over the token axis → max-pool-over-time → Concat →
classifier, all as one Symbol.  Data is a synthetic sentiment task
(presence of "positive" token ids near the front decides the label) so
the pipeline is self-contained.
"""

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


VOCAB = 60
SEQ_LEN = 24
POS_TOKENS = set(range(5, 15))


def synthetic_sentences(n, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randint(15, VOCAB, size=(n, SEQ_LEN)).astype(np.float32)
    y = rs.randint(0, 2, size=n).astype(np.float32)
    for i in range(n):
        if y[i] == 1:  # plant positive tokens
            pos = rs.choice(SEQ_LEN, 3, replace=False)
            x[i, pos] = rs.choice(sorted(POS_TOKENS), 3)
    return x, y


def build_text_cnn(num_embed=16, filter_widths=(2, 3, 4), num_filter=8):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=num_embed,
                             name="embed")
    # (B, T, E) -> (B, 1, T, E): one "image" channel, conv over time
    x = mx.sym.Reshape(embed, shape=(0, 1, SEQ_LEN, num_embed))
    branches = []
    for w in filter_widths:
        conv = mx.sym.Convolution(x, kernel=(w, num_embed),
                                  num_filter=num_filter,
                                  name="conv%d" % w)
        act = mx.sym.Activation(conv, act_type="relu")
        pool = mx.sym.Pooling(act, kernel=(SEQ_LEN - w + 1, 1),
                              pool_type="max")
        branches.append(mx.sym.Flatten(pool))
    h = mx.sym.Concat(*branches, dim=1, num_args=len(branches))
    h = mx.sym.FullyConnected(h, num_hidden=2, name="cls")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--num-epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    xtr, ytr = synthetic_sentences(1000)
    xva, yva = synthetic_sentences(300, seed=1)
    train = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(xva, yva, batch_size=args.batch_size)

    mod = mx.mod.Module(build_text_cnn(),
                        context=mx.context.current_context())
    mod.fit(train, eval_data=val, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier(),
            num_epoch=args.num_epochs)
    metric = mx.metric.Accuracy()
    mod.score(val, metric)
    acc = metric.get()[1]
    print("text-cnn val accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
