#!/usr/bin/env python
"""LSTM + CTC sequence recognition (reference: example/ctc/
lstm_ocr_train.py — captcha OCR trained with CTCLoss, greedy-decoded
with example/ctc/ctc_metrics.py semantics).

Synthetic OCR (zero-egress container): each sample is a 1-2 digit
string rendered as a noisy frame sequence — every digit emits two
one-hot frames with a gap frame after, so the model must learn CTC's
alignment (emit blanks on gaps, collapse repeats).  The LSTM runs as
one lax.scan on device; CTCLoss is the XLA log-space forward algorithm
(ops/nn.py ctc_loss, gradient checked against torch in
tests/test_loss.py).  --model dense swaps the recurrent trunk for a
per-frame MLP (faster on 1-core CI; same CTC mechanics).
"""

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn, rnn

NUM_DIGITS = 5           # classes 0..4; CTC blank = index 5 ("last")
FRAME_DIM = 8
SEQ_LEN = 10
MAX_LABEL = 2


def make_dataset(rng, n):
    X = (rng.rand(n, SEQ_LEN, FRAME_DIM) * 0.3).astype(np.float32)
    labels = np.full((n, MAX_LABEL), -1.0, np.float32)  # -1 pads
    for i in range(n):
        k = rng.randint(1, MAX_LABEL + 1)
        t = 0
        for j in range(k):
            d = rng.randint(NUM_DIGITS)
            labels[i, j] = d
            for _ in range(2):          # each digit: two lit frames
                X[i, t, d] += 1.0
                t += 1
            t += 1                      # gap frame -> must emit blank
    return X, labels


def build_net(kind, hidden):
    net = nn.HybridSequential()
    if kind == "lstm":
        net.add(rnn.LSTM(hidden, layout="NTC"))
    else:
        net.add(nn.Dense(hidden, activation="relu", flatten=False))
    net.add(nn.Dense(NUM_DIGITS + 1, flatten=False))
    return net


def greedy_decode(logits):
    """argmax -> collapse repeats -> drop blanks (reference:
    example/ctc/ctc_metrics.py)."""
    best = logits.argmax(axis=-1)
    out = []
    for row in best:
        seq, prev = [], -1
        for c in row:
            if c != prev and c != NUM_DIGITS:
                seq.append(int(c))
            prev = c
        out.append(seq)
    return out


def seq_accuracy(net, X, labels):
    pred = greedy_decode(net(mx.nd.array(X)).asnumpy())
    hits = 0
    for p, lab in zip(pred, labels):
        hits += int(p == [int(v) for v in lab if v >= 0])
    return hits / len(labels)


def main(argv=None):
    p = argparse.ArgumentParser(description="LSTM+CTC OCR")
    p.add_argument("--model", choices=("lstm", "dense"), default="lstm")
    p.add_argument("--num-examples", type=int, default=128)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--epochs", type=int, default=200)
    p.add_argument("--lr", type=float, default=2e-2)
    p.add_argument("--target-acc", type=float, default=0.95,
                   help="early-stop once val accuracy reaches this")
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history

    rng = np.random.RandomState(0)
    X, labels = make_dataset(rng, args.num_examples)
    Xv, labv = make_dataset(np.random.RandomState(99), 64)

    net = build_net(args.model, args.hidden)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    # blank = last class, labels 0-based (reference ctc convention)
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")

    label_lengths = mx.nd.array((labels >= 0).sum(axis=1).astype(np.float32))
    x_all, y_all = mx.nd.array(X), mx.nd.array(labels)
    acc, tic = 0.0, time.time()
    for epoch in range(args.epochs):
        with mx.autograd.record():
            L = ctc(net(x_all), y_all, None, label_lengths)
        L.backward()
        trainer.step(args.num_examples)
        if epoch % 10 == 9:
            acc = seq_accuracy(net, Xv, labv)
            print("epoch %d: ctc loss %.4f, val seq-acc %.3f (%.0fs)"
                  % (epoch, float(L.mean().asnumpy()), acc,
                     time.time() - tic))
            if acc >= args.target_acc:
                break
    return acc


if __name__ == "__main__":
    main()
