"""PR-2 telemetry walkthrough: a ~20-step Gluon training loop whose
chrome trace shows the full step anatomy (dispatch cache hit/miss, io,
autograd, trainer), plus the always-on runtime_stats counters and the
recompile-storm detector.

Run directly (the script activates the profiler itself), or with zero
code changes on any script via the env var:

    MXNET_TPU_PROFILE=trace.json python your_train.py

Docs: docs/OBSERVABILITY.md.
"""

import argparse
import json
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler, runtime_stats


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    out = args.out or os.path.join(tempfile.gettempdir(),
                                   "runtime_telemetry.json")
    if not os.environ.get("MXNET_TPU_PROFILE"):
        profiler.set_config(filename=out)
        profiler.set_state("run")
    # start both layers from zero so the trace/counter cross-check at
    # the end is exact (dumps(reset=True) drains any prior events)
    profiler.dumps(reset=True)
    runtime_stats.reset()

    # ---- a small imperative training loop, fully instrumented
    net = gluon.nn.Dense(4)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    batch_size = 2
    X = rs.rand(args.steps * batch_size, 6).astype(np.float32)
    Y = rs.randint(0, 4, (args.steps * batch_size,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=batch_size)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    for batch in it:
        with autograd.record():
            loss = loss_fn(net(batch.data[0]), batch.label[0])
        loss.backward()
        trainer.step(batch_size)

    # ---- provoke the recompile-storm detector: a churning attr value
    # bakes a new jit-cache key per call (the fix: traced_attrs)
    x = mx.nd.ones((4, 4))
    for i in range(runtime_stats.STORM_THRESHOLD + 2):
        mx.nd.clip(x, 0.0, 100.0 + i)  # watch stderr for the warning

    path = profiler.dump(finished=True)
    trace = json.load(open(path))["traceEvents"]
    names = {e["name"] for e in trace}
    print("trace: %s (%d events)" % (path, len(trace)))
    print("step anatomy spans:",
          sorted(n for n in names if not n.startswith("dispatch:")))
    hits = sum(1 for e in trace
               if e.get("args", {}).get("cache") == "hit")
    misses = sum(1 for e in trace
                 if e.get("args", {}).get("cache") == "miss")
    print("dispatch spans: %d cache hits, %d misses" % (hits, misses))

    print("\nruntime_stats.report():")
    print(runtime_stats.report())
    snap = runtime_stats.snapshot()
    assert snap["totals"]["jit_cache_misses"] == misses, \
        "trace and counters must agree on compiles"
    return path


if __name__ == "__main__":
    main()
