"""Telemetry walkthrough: a ~20-step Gluon training loop whose chrome
trace shows the full step anatomy (dispatch cache hit/miss, io,
autograd, trainer) AND a live/peak device-memory timeline, plus the
always-on runtime_stats counters, per-op XLA cost analytics, the
recompile-storm detector, the numerics health layer (device-side
grad-norm/NaN sentinels, flight recorder, first-NaN warning + dump),
and the PR-8 analysis layer: per-step phase attribution (stepstats),
the perf doctor's ranked findings, and the dump-diff regression report,
plus the PR-10 continuous-monitoring layer: the live metrics timeline,
its JSONL export + Prometheus /metrics endpoint (scraped mid-loop
below), and the trend doctor catching an induced throughput drift.

Run directly (the script activates the profiler, buffer tracker, and
health monitor itself), or with zero code changes on any script via
the env vars:

    MXNET_TPU_PROFILE=trace.json python your_train.py
    MXNET_TPU_DIAG=diag.json     python your_train.py   # + kill -USR1
    MXNET_TPU_HEALTH=1           python your_train.py
    MXNET_TPU_STEPSTATS=1        python your_train.py   # step anatomy
    MXNET_TPU_METRICS=m.jsonl  MXNET_TPU_METRICS_PORT=9100 \
        python your_train.py                            # live timeline

Docs: docs/OBSERVABILITY.md.
"""

import argparse
import json
import os
import tempfile
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import (autograd, device_memory, gluon, health, perfdoctor,
                       profiler, runtime_stats, stepstats)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    out = args.out or os.path.join(tempfile.gettempdir(),
                                   "runtime_telemetry.json")
    if not os.environ.get("MXNET_TPU_PROFILE"):
        profiler.set_config(filename=out)
        profiler.set_state("run")
    # start all layers from zero so the trace/counter cross-check at
    # the end is exact (dumps(reset=True) drains any prior events);
    # the tracker is on BEFORE the loop so parameter buffers count
    profiler.dumps(reset=True)
    runtime_stats.reset()
    device_memory.reset()
    device_memory.start()
    # per-step phase attribution: where each iteration's wall time goes
    # (data wait / forward / backward / update / ... / remainder)
    stepstats.enable()

    # ---- a small imperative training loop, fully instrumented; the
    # health monitor computes grad-norm/NaN sentinels ON DEVICE and the
    # host only pays at the per-step drain
    mon = health.enable(dump_path=os.path.join(tempfile.gettempdir(),
                                               "runtime_telemetry_flight"
                                               ".json"))
    net = gluon.nn.Dense(4)
    net.initialize()
    mon.install(net)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    batch_size = 2
    X = rs.rand(args.steps * batch_size, 6).astype(np.float32)
    Y = rs.randint(0, 4, (args.steps * batch_size,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=batch_size)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    for batch in it:
        with autograd.record():
            loss = loss_fn(net(batch.data[0]), batch.label[0])
        loss.backward()
        mon.note_loss(loss)
        trainer.step(batch_size)

    # ---- provoke the recompile-storm detector: a churning attr value
    # bakes a new jit-cache key per call (the fix: traced_attrs)
    x = mx.nd.ones((4, 4))
    for i in range(runtime_stats.STORM_THRESHOLD + 2):
        mx.nd.clip(x, 0.0, 100.0 + i)  # watch stderr for the warning

    path = profiler.dump(finished=True)
    trace = json.load(open(path))["traceEvents"]
    names = {e["name"] for e in trace}
    print("trace: %s (%d events)" % (path, len(trace)))
    print("step anatomy spans:",
          sorted(n for n in names if not n.startswith("dispatch:")))
    hits = sum(1 for e in trace
               if e.get("args", {}).get("cache") == "hit")
    misses = sum(1 for e in trace
                 if e.get("args", {}).get("cache") == "miss")
    print("dispatch spans: %d cache hits, %d misses" % (hits, misses))

    mem_events = [e for e in trace if e.get("ph") == "C"
                  and e["name"] == "device_memory"]
    print("memory counter events: %d (open the trace: a live/peak-bytes"
          " track renders alongside the spans)" % len(mem_events))

    gn_events = [e for e in trace if e.get("ph") == "C"
                 and e["name"] == "grad_norm"]
    print("grad_norm counter events: %d (the numerics timeline — "
          "nan_total renders next to it)" % len(gn_events))
    flight = health.snapshot()["flight"]
    print("flight recorder: %d per-step record(s); latest: step %d "
          "loss %.4f grad_norm %.4f nan %d"
          % (len(flight), flight[-1]["step"], flight[-1]["loss"],
             flight[-1]["grad_norm"], int(flight[-1]["nan_total"])))
    assert all(r["nan_total"] == 0 for r in flight), \
        "a healthy demo loop must stay NaN-free"

    print("\nruntime_stats.report():")
    print(runtime_stats.report())
    snap = runtime_stats.snapshot()
    assert snap["totals"]["jit_cache_misses"] == misses, \
        "trace and counters must agree on compiles"
    assert snap["memory"]["totals"]["peak_bytes"] > 0

    # the production diagnostic: same picture, one atomic JSON file
    # (a live run does this on SIGUSR1 when MXNET_TPU_DIAG is set)
    diag = runtime_stats.dump_diag(os.path.join(
        tempfile.gettempdir(), "runtime_telemetry_diag.json"))
    print("\ndiag dump: %s (pretty-print: python -m "
          "mxnet_tpu.runtime_stats %s)" % (diag, diag))

    # ---- the perf doctor: ranked findings over the dump.  This run
    # deliberately provoked a recompile storm above, so the doctor must
    # rank it first with the churned attr as evidence.  CLI equivalent:
    #   python tools/diagnose.py --doctor <diag.json> [<trace.json>]
    ss = stepstats.snapshot()
    assert ss["steps"] == args.steps - 1  # first window arms the clock
    print("\nperf doctor on this run's dump:")
    _kind, dump = perfdoctor.classify(diag)
    findings = perfdoctor.diagnose(dump=dump)
    print(perfdoctor.render(findings, inputs=[diag]))
    assert any(f["rule"] == "recompile-storm" for f in findings), \
        "the provoked storm must be diagnosed"

    # ---- dump-diff regression report: rerun the same loop with a
    # delayed iterator and let compare() name the regressed phase.
    # CLI equivalent (rc=1 on regression, JSON verdict line for CI):
    #   python tools/diagnose.py --compare base.json slow.json
    runtime_stats.reset()
    stepstats.enable()
    it = mx.io.NDArrayIter(X, Y, batch_size=batch_size)
    orig_next = it.next

    def slow_next():
        time.sleep(0.005)  # the injected input-pipeline regression
        return orig_next()

    it.next = slow_next
    for batch in it:
        with autograd.record():
            loss = loss_fn(net(batch.data[0]), batch.label[0])
        loss.backward()
        trainer.step(batch_size)
    slow = runtime_stats.dump_diag(os.path.join(
        tempfile.gettempdir(), "runtime_telemetry_diag_slow.json"))
    a, b = runtime_stats.load_dumps([diag, slow])
    result = runtime_stats.compare(a, b, threshold=0.75)
    print("\ndump-diff (baseline vs delayed-io rerun):")
    print(runtime_stats.render_compare(result))
    assert result["verdict"] == "regression"
    assert any(e["metric"] == "phase:data_wait"
               for e in result["regressions"]), \
        "the injected io delay must be named"

    # ---- the live metrics timeline: per-step samples into a ring + a
    # JSONL file, a Prometheus /metrics endpoint scraped MID-LOOP, and
    # the trend doctor catching an induced mid-run drift.  Production
    # equivalent (zero code changes):
    #   MXNET_TPU_METRICS=m.jsonl MXNET_TPU_METRICS_PORT=9100 python ...
    import urllib.request

    from mxnet_tpu import metrics_timeline

    runtime_stats.reset()
    jsonl = os.path.join(tempfile.gettempdir(),
                         "runtime_telemetry_metrics.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    metrics_timeline.enable(path=jsonl)
    metrics_timeline.serve(port=0)  # 0 = pick a free port
    port = metrics_timeline.server_port()
    steps = max(30, args.steps)
    X2 = rs.rand(steps * batch_size, 6).astype(np.float32)
    Y2 = rs.randint(0, 4, (steps * batch_size,)).astype(np.float32)
    it = mx.io.NDArrayIter(X2, Y2, batch_size=batch_size)
    orig_next2 = it.next
    seen = [0]

    def drifting_next():
        seen[0] += 1
        if seen[0] > steps // 2:
            time.sleep(0.02)  # the induced mid-run drift
        if seen[0] == steps // 2:
            # scrape our own endpoint while the loop is live
            body = urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port,
                timeout=10).read().decode()
            wall = [ln for ln in body.splitlines()
                    if ln.startswith("mxnet_tpu_step_duration_seconds")]
            print("\nmid-loop /metrics scrape (port %d): %d lines; %s"
                  % (port, len(body.splitlines()),
                     wall[0] if wall else "<no step yet>"))
        return orig_next2()

    it.next = drifting_next
    for batch in it:
        with autograd.record():
            loss = loss_fn(net(batch.data[0]), batch.label[0])
        loss.backward()
        trainer.step(batch_size)
    print("timeline: %d ring sample(s), %d JSONL line(s) at %s"
          % (len(metrics_timeline.samples()),
             metrics_timeline.snapshot()["written"], jsonl))
    trend = perfdoctor.diagnose(timeline=metrics_timeline.samples())
    print("\ntrend doctor on the live ring:")
    print(perfdoctor.render(trend))
    slow = [f for f in trend if f["rule"] == "timeline-throughput"]
    assert slow, "the induced drift must be caught as a trend"
    assert slow[0]["anchor"] == "phase:data_wait", \
        "the drifting phase must be named"

    # leave global collection off for any in-process caller (tests run
    # this example inside the suite)
    metrics_timeline.disable()
    stepstats.disable()
    return path


if __name__ == "__main__":
    main()
