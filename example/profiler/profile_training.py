"""Profile NDArray ops and a small training loop into a chrome trace
(reference: example/profiler/profiler_ndarray.py + profiler_matmul.py).

Demonstrates the profiler client API end-to-end: set_config →
set_state('run') → scoped domains/tasks around user code → dump, then
sanity-checks the emitted chrome://tracing JSON.
"""

import argparse
import json
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)

    out = args.out or os.path.join(tempfile.gettempdir(),
                                   "profile_training.json")
    profiler.set_config(profile_all=True, aggregate_stats=True,
                        filename=out)
    profiler.set_state("run")

    # -- phase 1: raw NDArray ops (reference: profiler_ndarray.py)
    a = mx.nd.array(np.random.rand(256, 256).astype(np.float32))
    b = mx.nd.array(np.random.rand(256, 256).astype(np.float32))
    with profiler.scope("matmul_loop", "ndarray"):
        for _ in range(args.iters):
            c = mx.nd.dot(a, b)
        c.wait_to_read()

    # -- phase 2: a tiny training loop under its own domain
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(8)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.array(np.random.rand(16, 4).astype(np.float32))
    y = mx.nd.array(np.random.rand(16, 8).astype(np.float32))
    with profiler.scope("train_loop", "training"):
        for _ in range(5):
            with mx.autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(16)
        loss.wait_to_read()

    profiler.set_state("stop")
    stats = profiler.dumps()  # aggregate table (aggregate_stats=True)
    if stats:
        print(stats[:400])
    trace_path = profiler.dump()  # write the chrome trace file
    assert trace_path is None or str(trace_path)

    with open(out) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    names = {e.get("name") for e in events if isinstance(e, dict)}
    assert "matmul_loop" in names and "train_loop" in names, sorted(names)[:20]
    print("chrome trace written to %s (%d events)" % (out, len(events)))
    return out


if __name__ == "__main__":
    main()
