#!/usr/bin/env python
"""Gluon walkthrough (reference: example/gluon/mnist.py — imperative
define-by-run training with autograd + Trainer, then hybridize())."""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def main(argv=None):
    p = argparse.ArgumentParser(description="Gluon example")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--no-hybridize", action="store_true",
                   help="stay on the imperative define-by-run path")
    args = p.parse_args(argv)
    mx.random.seed(7)

    from mxnet_tpu.io.io import MNISTIter

    train = MNISTIter(image="train", batch_size=args.batch_size)
    val = MNISTIter(image="val", batch_size=args.batch_size, shuffle=False)

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    if not args.no_hybridize:
        net.hybridize()   # stage the whole forward into one XLA program
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        train.reset()
        tot = nb = 0.0
        for batch in train:
            data, label = batch.data[0], batch.label[0]
            with mx.autograd.record():
                L = ce(net(data), label)
            L.backward()
            trainer.step(args.batch_size)
            tot += float(L.mean().asnumpy())
            nb += 1
        print("epoch %d: loss %.4f" % (epoch, tot / nb))

    acc = hits = n = 0
    val.reset()
    for batch in val:
        out = net(batch.data[0]).asnumpy()
        hits += int((out.argmax(1) == batch.label[0].asnumpy()).sum())
        n += out.shape[0]
    acc = hits / n
    print("val accuracy %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
