"""Matrix factorization recommender (reference: example/recommenders/
demo1-MF.ipynb, example/sparse/matrix_factorization/) — embedding-based
user/item factors with sparse gradients, trained on a synthetic
low-rank rating matrix.

Usage: python matrix_fact.py [--epochs 20] [--factors 8]
"""

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import ndarray as nd
from mxnet_tpu.gluon.contrib.nn import SparseEmbedding


class MFBlock(gluon.Block):
    def __init__(self, n_users, n_items, factors, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.user = SparseEmbedding(n_users, factors)
            self.item = SparseEmbedding(n_items, factors)

    def forward(self, users, items):
        return (self.user(users) * self.item(items)).sum(axis=1)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--factors", type=int, default=8)
    p.add_argument("--users", type=int, default=200)
    p.add_argument("--items", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    rs = np.random.RandomState(args.seed)
    true_u = rs.randn(args.users, args.factors).astype(np.float32) * 0.5
    true_i = rs.randn(args.items, args.factors).astype(np.float32) * 0.5
    n_obs = 20000
    u_idx = rs.randint(0, args.users, n_obs).astype(np.float32)
    i_idx = rs.randint(0, args.items, n_obs).astype(np.float32)
    ratings = (true_u[u_idx.astype(int)] *
               true_i[i_idx.astype(int)]).sum(1) + \
        0.05 * rs.randn(n_obs).astype(np.float32)

    net = MFBlock(args.users, args.items, args.factors)
    net.initialize(mx.init.Normal(0.1))
    l2 = gluon.loss.L2Loss()
    # lazy_update touches only the gradient's rows — the point of
    # sparse embeddings (reference: sparse MF example)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for epoch in range(args.epochs):
        perm = rs.permutation(n_obs)
        losses = []
        for s in range(0, n_obs, args.batch_size):
            sel = perm[s:s + args.batch_size]
            with autograd.record():
                pred = net(nd.array(u_idx[sel]), nd.array(i_idx[sel]))
                loss = l2(pred, nd.array(ratings[sel])).mean()
            loss.backward()
            trainer.step(len(sel))
            losses.append(float(loss.asnumpy()))
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            print("epoch %d  mse %.4f" % (epoch, 2 * np.mean(losses)))
    final_mse = 2 * np.mean(losses)
    print("final rating MSE %.4f (noise floor ~0.0025)" % final_mse)
    return final_mse


if __name__ == "__main__":
    main()
