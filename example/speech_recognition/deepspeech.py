#!/usr/bin/env python
"""DeepSpeech-style speech recognition with CTC (reference:
example/speech_recognition/ — arch_deepspeech.py's conv front-end +
stacked recurrent layers + per-frame FC, trained with warp-CTC
(stt_layer_warpctc.py) and scored by CER (stt_metric.py EvalSTTMetric)).

Scaled to the container: the "speech" corpus is synthesized in-process
(zero-egress) — each utterance is a sequence of phoneme tokens, each
rendered as a variable-duration band of spectral energy in a mel-like
filterbank with noise, coarticulation blur, and silence gaps.  The
model is the same shape as the reference's: Conv2D over
(time x frequency) patches, bidirectional LSTM, per-frame Dense, CTC.

Greedy CTC decoding + edit-distance CER mirror stt_metric.py.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn

N_PHONES = 8                 # alphabet (blank is index N_PHONES)
N_MEL = 20                   # filterbank bins
FRAMES_PER_TOKEN = 6


def synth_utterance(rng, tokens):
    """Render a token sequence to a (T, N_MEL) 'spectrogram'."""
    frames = []
    for tok in tokens:
        dur = FRAMES_PER_TOKEN + rng.randint(-2, 3)
        center = 2 + tok * 2
        profile = np.exp(-0.5 * ((np.arange(N_MEL) - center) / 1.3) ** 2)
        seg = profile[None, :] * rng.uniform(0.8, 1.2, (dur, 1))
        frames.append(seg)
        if rng.rand() < 0.3:                      # silence gap
            frames.append(np.zeros((rng.randint(1, 3), N_MEL)))
    spec = np.concatenate(frames, 0)
    spec += rng.normal(0, 0.12, spec.shape)       # noise floor
    # coarticulation blur along time
    spec = 0.25 * np.roll(spec, 1, 0) + 0.5 * spec \
        + 0.25 * np.roll(spec, -1, 0)
    return spec.astype(np.float32)


def make_data(rng, n, min_len=3, max_len=6, max_frames=60):
    """Padded batch of utterances + padded labels + lengths."""
    X = np.zeros((n, max_frames, N_MEL), np.float32)
    Y = np.full((n, max_len), N_PHONES, np.float32)   # pad with blank
    xlen = np.zeros(n, np.float32)
    ylen = np.zeros(n, np.float32)
    for i in range(n):
        L = rng.randint(min_len, max_len + 1)
        tokens = rng.randint(0, N_PHONES, L)
        spec = synth_utterance(rng, tokens)[:max_frames]
        X[i, :len(spec)] = spec
        Y[i, :L] = tokens
        xlen[i], ylen[i] = len(spec), L
    return X, Y, xlen, ylen


class DeepSpeech(gluon.HybridBlock):
    """Conv front-end + BiLSTM + per-frame head (reference
    arch_deepspeech.py, downscaled)."""

    def __init__(self, hidden=64, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv = nn.Conv2D(16, kernel_size=(5, N_MEL),
                                  padding=(2, 0), activation="relu")
            self.lstm = rnn.LSTM(hidden, layout="NTC",
                                 bidirectional=True)
            self.head = nn.Dense(N_PHONES + 1, flatten=False)

    def hybrid_forward(self, F, x):
        # (N, T, F) -> (N, 1, T, F) -> conv -> (N, C, T, 1) -> (N, T, C)
        h = self.conv(F.expand_dims(x, axis=1))
        h = F.transpose(F.squeeze(h, axis=3), axes=(0, 2, 1))
        return self.head(self.lstm(h))


def greedy_decode(logits, xlen):
    """Per-frame argmax, collapse repeats, drop blanks (reference
    stt_metric.py ctc_greedy_decode)."""
    out = []
    for i in range(len(logits)):
        path = logits[i, :int(xlen[i])].argmax(-1)
        seq, prev = [], -1
        for s in path:
            if s != prev and s != N_PHONES:
                seq.append(int(s))
            prev = s
        out.append(seq)
    return out


def edit_distance(a, b):
    dp = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        prev, dp[0] = dp[0], i
        for j in range(1, len(b) + 1):
            cur = min(dp[j] + 1, dp[j - 1] + 1,
                      prev + (a[i - 1] != b[j - 1]))
            prev, dp[j] = dp[j], cur
    return int(dp[-1])


def cer(decoded, Y, ylen):
    errs = chars = 0
    for i, seq in enumerate(decoded):
        truth = [int(t) for t in Y[i, :int(ylen[i])]]
        errs += edit_distance(seq, truth)
        chars += len(truth)
    return errs / max(chars, 1)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n-train", type=int, default=2048)
    p.add_argument("--n-test", type=int, default=256)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=9)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    X, Y, xlen, ylen = make_data(rng, args.n_train)
    Xt, Yt, xlent, ylent = make_data(rng, args.n_test)

    net = DeepSpeech(hidden=args.hidden)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")

    nb = args.n_train // args.batch_size
    for epoch in range(args.epochs):
        perm = rng.permutation(args.n_train)
        tot = 0.0
        for b in range(nb):
            idx = perm[b * args.batch_size:(b + 1) * args.batch_size]
            data = mx.nd.array(X[idx])
            label = mx.nd.array(Y[idx])
            with autograd.record():
                logits = net(data)
                l = ctc(logits, label, mx.nd.array(xlen[idx]),
                        mx.nd.array(ylen[idx]))
            l.backward()
            trainer.step(args.batch_size)
            tot += float(l.mean().asscalar())
        print("Epoch [%d] ctc loss %.4f" % (epoch, tot / nb))

    logits = net(mx.nd.array(Xt)).asnumpy()
    rate = cer(greedy_decode(logits, xlent), Yt, ylent)
    print("Test CER %.4f" % rate)
    return rate


if __name__ == "__main__":
    main()
