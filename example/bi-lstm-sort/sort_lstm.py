#!/usr/bin/env python
"""Sort an array of integers with a bidirectional LSTM (reference:
example/bi-lstm-sort/bi-lstm-sort.ipynb — numbers are rendered as a
space-separated digit string, fed one-hot per character to a 2-layer
bidirectional LSTM, and trained with per-character softmax CE against
the sorted string).

The sequence is a fixed-width character canvas (maximum string length
padded with spaces), so every batch is one static shape — the bi-LSTM
runs as two lax.scans over the character axis and the whole training
step stays inside a single jit.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn, rnn

VOCAB = "0123456789 "
VOCAB_IDX = {c: i for i, c in enumerate(VOCAB)}


def encode(batch, max_len):
    """Render integer rows as padded digit strings -> (index array)."""
    out = np.full((len(batch), max_len), VOCAB_IDX[" "], np.int32)
    for i, row in enumerate(batch):
        s = " ".join(map(str, row.tolist()))
        out[i, :len(s)] = [VOCAB_IDX[c] for c in s]
    return out


def decode(idx_row):
    return "".join(VOCAB[int(i)] for i in idx_row).rstrip()


def make_data(rng, n, seq_len, max_num):
    x = rng.randint(0, max_num + 1, (n, seq_len))
    y = np.sort(x, axis=1)
    max_len = len(str(max_num)) * seq_len + (seq_len - 1)
    return encode(x, max_len), encode(y, max_len), x, y


class SortNet(gluon.nn.HybridSequential):
    def __init__(self, hidden=128, layers=2, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.add(rnn.LSTM(hidden_size=hidden, num_layers=layers,
                              layout="NTC", bidirectional=True),
                     nn.Dense(len(VOCAB), flatten=False))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--dataset-size", type=int, default=4000)
    p.add_argument("--seq-len", type=int, default=3)
    p.add_argument("--max-num", type=int, default=99)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    xi, yi, _, _ = make_data(rng, args.dataset_size, args.seq_len,
                             args.max_num)
    split = int(0.9 * len(xi))
    onehot = np.eye(len(VOCAB), dtype=np.float32)

    net = SortNet(hidden=args.hidden)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCELoss()
    schedule = mx.lr_scheduler.FactorScheduler(
        step=max(1, 10 * (split // args.batch_size)), factor=0.75)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr,
                             "lr_scheduler": schedule})

    for epoch in range(args.epochs):
        perm = rng.permutation(split)
        total = 0.0
        nb = 0
        for s in range(0, split - args.batch_size + 1, args.batch_size):
            idx = perm[s:s + args.batch_size]
            data = mx.nd.array(onehot[xi[idx]])
            label = mx.nd.array(yi[idx].astype(np.float32))
            with mx.autograd.record():
                out = net(data)
                l = loss_fn(out, label)
            l.backward()
            trainer.step(args.batch_size)
            total += float(l.mean().asscalar())
            nb += 1
        print("Epoch [%d] loss %.4f lr %g"
              % (epoch, total / max(nb, 1), trainer.learning_rate))

    # exact-character accuracy on the held-out split
    test_x, test_y = xi[split:], yi[split:]
    pred = net(mx.nd.array(onehot[test_x])).argmax(axis=-1).asnumpy()
    acc = float((pred == test_y).mean())
    sample = decode(pred[0])
    print("Test char accuracy %.4f" % acc)
    print("Input     %s" % decode(test_x[0]))
    print("Predicted %s" % sample)
    print("Label     %s" % decode(test_y[0]))
    return acc


if __name__ == "__main__":
    main()
