"""Autoencoder with tied training loop (reference: example/autoencoder/
— stacked autoencoder pretraining).  Runs on synthetic structured data
(low-rank + noise) so it works without datasets; reports reconstruction
error vs the PCA optimum.

Usage: python train_ae.py [--epochs 40] [--code-dim 4]
"""

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import ndarray as nd


def make_data(rs, n=512, dim=32, rank=4):
    basis = rs.randn(rank, dim).astype(np.float32)
    codes = rs.randn(n, rank).astype(np.float32)
    return codes @ basis + 0.05 * rs.randn(n, dim).astype(np.float32)


class AutoEncoder(gluon.HybridBlock):
    def __init__(self, dim, code_dim, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.enc = gluon.nn.HybridSequential()
            self.enc.add(gluon.nn.Dense(16, activation="relu"),
                         gluon.nn.Dense(code_dim))
            self.dec = gluon.nn.HybridSequential()
            self.dec.add(gluon.nn.Dense(16, activation="relu"),
                         gluon.nn.Dense(dim))

    def hybrid_forward(self, F, x):
        return self.dec(self.enc(x))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=40)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--code-dim", type=int, default=4)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    rs = np.random.RandomState(args.seed)
    X = make_data(rs, dim=32, rank=args.code_dim)
    net = AutoEncoder(X.shape[1], args.code_dim)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    l2 = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    n = len(X)
    for epoch in range(args.epochs):
        perm = rs.permutation(n)
        losses = []
        for i in range(0, n, args.batch_size):
            xb = nd.array(X[perm[i:i + args.batch_size]])
            with autograd.record():
                loss = l2(net(xb), xb).mean()
            loss.backward()
            trainer.step(xb.shape[0])
            losses.append(float(loss.asnumpy()))
        if epoch % 10 == 0 or epoch == args.epochs - 1:
            print("epoch %d  recon_loss %.5f" % (epoch, np.mean(losses)))

    # compare against the PCA floor for the same code size
    Xc = X - X.mean(0)
    _, s, _ = np.linalg.svd(Xc, full_matrices=False)
    pca_floor = (s[args.code_dim:] ** 2).sum() / (2 * n * X.shape[1])
    final = np.mean(losses)
    print("final %.5f vs PCA floor %.5f" % (final, pca_floor))
    return final, pca_floor


if __name__ == "__main__":
    main()
