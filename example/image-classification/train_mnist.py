#!/usr/bin/env python
"""Train on MNIST (reference: example/image-classification/train_mnist.py).

Synthesises MNIST-like data when the idx files are absent (zero-egress
container); networks: mlp | lenet.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import mxnet_tpu as mx
from common import data as common_data
from common import fit as common_fit


def build_mlp(num_classes=10):
    data = mx.sym.Variable("data")
    data = mx.sym.Flatten(data)
    fc1 = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    act1 = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, num_hidden=64, name="fc2")
    act2 = mx.sym.Activation(fc2, act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, num_hidden=num_classes, name="fc3")
    return mx.sym.SoftmaxOutput(fc3, name="softmax")


def build_lenet(num_classes=10):
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    fl = mx.sym.Flatten(p2)
    f1 = mx.sym.FullyConnected(fl, num_hidden=500, name="fc1")
    a3 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a3, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="train MNIST",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--add_stn", action="store_true")
    common_fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_classes=10, num_examples=6000,
                        batch_size=64, num_epochs=10, lr=0.05,
                        lr_step_epochs="10")
    args = parser.parse_args(argv)

    net = build_mlp(args.num_classes) if args.network == "mlp" \
        else build_lenet(args.num_classes)
    mod = common_fit.fit(args, net, common_data.get_mnist_iter)
    return mod


if __name__ == "__main__":
    main()
