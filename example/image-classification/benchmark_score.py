#!/usr/bin/env python
"""Inference throughput across the model zoo (reference:
example/image-classification/benchmark_score.py — the script behind
docs/faq/perf.md's img/s tables).

Per (network, batch) it jits one forward and reports img/s.
"""

import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo import vision


def score(network, batch_size, image_shape=(3, 224, 224), num_batches=20,
          dtype="float32"):
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    net = getattr(vision, network)(classes=1000)
    # init + deferred-shape resolution on CPU: the eager per-op path on a
    # remote accelerator pays one device compile PER OP; only the staged
    # whole-graph computation should touch the accelerator
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((1,) + tuple(image_shape), ctx=mx.cpu()))
    net.collect_params().reset_ctx(ctx)
    net.hybridize()
    data = mx.nd.random.uniform(shape=(batch_size,) + tuple(image_shape),
                                ctx=ctx)
    if dtype in ("float16", "bfloat16"):
        net.cast(dtype)
        data = data.astype(dtype)
    # warmup (jit compile).  The barrier is a SCALAR host fetch, not
    # wait_to_read(): on relayed TPU backends block_until_ready can
    # return before device work drains, which inflates throughput.
    def barrier(out):
        return float(np.asarray(out.data_jax[(0,) * out.data_jax.ndim]))

    barrier(net(data))
    tic = time.time()
    for _ in range(num_batches):
        out = net(data)
    barrier(out)
    return num_batches * batch_size / (time.time() - tic)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--networks", type=str,
                        default="alexnet,vgg16,resnet50_v1,inception_v3")
    parser.add_argument("--batch-sizes", type=str, default="1,32,128")
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--num-batches", type=int, default=20)
    parser.add_argument("--dtype", type=str, default="float32")
    args = parser.parse_args(argv)
    shape = tuple(int(x) for x in args.image_shape.split(","))
    results = []
    for net in args.networks.split(","):
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            ips = score(net, bs, shape, args.num_batches, args.dtype)
            print("network: %s, batch: %d, image/sec: %.1f"
                  % (net, bs, ips))
            results.append((net, bs, ips))
    return results


if __name__ == "__main__":
    main()
