"""Shared fit() harness (reference: example/image-classification/common/
fit.py:148 — arg groups, kvstore setup, lr schedule, Module.fit)."""

import argparse
import logging
import time

import mxnet_tpu as mx


def add_fit_args(parser):
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="resnet50_v1")
    train.add_argument("--num-layers", type=int, default=50)
    train.add_argument("--num-classes", type=int, default=1000)
    train.add_argument("--batch-size", type=int, default=128)
    train.add_argument("--num-epochs", type=int, default=80)
    train.add_argument("--lr", type=float, default=0.1)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="30,60,90")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--kv-store", type=str, default="tpu")
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None)
    train.add_argument("--load-epoch", type=int, default=None)
    train.add_argument("--dtype", type=str, default="float32")
    train.add_argument("--monitor", type=int, default=0)
    return train


def _lr_scheduler(args, kv, epoch_size, begin_epoch):
    steps = [int(x) for x in args.lr_step_epochs.split(",") if x]
    lr = args.lr
    for s in steps:
        if begin_epoch >= s:
            lr *= args.lr_factor
    # strictly-future steps only: a step exactly at begin_epoch is already
    # folded into lr above (reference: common/fit.py _get_lr_scheduler)
    factor_steps = [epoch_size * (s - begin_epoch) for s in steps
                    if s > begin_epoch]
    sched = mx.lr_scheduler.MultiFactorScheduler(
        step=factor_steps, factor=args.lr_factor) if factor_steps else None
    return lr, sched


def fit(args, network, data_loader, **kwargs):
    """Train `network` (a Symbol) with the Module API (reference:
    common/fit.py fit)."""
    kv = mx.kv.create(args.kv_store)
    logging.basicConfig(
        level=logging.INFO,
        format="Node[%d] %%(asctime)s %%(message)s" % kv.rank)
    train, val = data_loader(args, kv)

    epoch_size = args.num_examples // args.batch_size // max(kv.num_workers, 1)
    begin_epoch = args.load_epoch or 0
    lr, lr_sched = _lr_scheduler(args, kv, max(epoch_size, 1), begin_epoch)

    mod = mx.mod.Module(symbol=network, context=_contexts(),
                        label_names=("softmax_label",))
    optimizer_params = {"learning_rate": lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag", "signum"):
        optimizer_params["momentum"] = args.mom
    if lr_sched is not None:
        optimizer_params["lr_scheduler"] = lr_sched

    arg_params = aux_params = None
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)

    checkpoint = mx.callback.do_checkpoint(args.model_prefix) \
        if args.model_prefix else None
    batch_cb = mx.callback.Speedometer(args.batch_size, args.disp_batches)

    mod.fit(train,
            eval_data=val,
            eval_metric=["accuracy"],
            begin_epoch=begin_epoch,
            num_epoch=args.num_epochs,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            kvstore=kv,
            arg_params=arg_params,
            aux_params=aux_params,
            batch_end_callback=batch_cb,
            epoch_end_callback=checkpoint,
            **kwargs)
    return mod


def _contexts():
    return [mx.tpu()] if mx.context.num_tpus() else [mx.cpu()]


def get_network(name, num_classes, image_shape):
    """Build a model-zoo network as a Symbol (reference builds symbols
    from symbols/<net>.py; here the Gluon zoo is traced)."""
    from mxnet_tpu.contrib.quantization import _trace_block
    from mxnet_tpu.gluon.model_zoo import vision

    net = getattr(vision, name)(classes=num_classes)
    net.initialize()
    data = mx.sym.Variable("data")
    sym, _ = _trace_block(net, [data], [(1,) + tuple(image_shape)])
    label = mx.sym.Variable("softmax_label")
    return mx.sym.SoftmaxOutput(sym, label, name="softmax")
