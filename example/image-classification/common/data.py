"""Data providers for the image-classification examples
(reference: example/image-classification/common/data.py)."""

import argparse

import numpy as np

import mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data")
    data.add_argument("--data-train", type=str, help="training RecordIO file")
    data.add_argument("--data-val", type=str, help="validation RecordIO file")
    data.add_argument("--image-shape", type=str, default="3,224,224")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    data.add_argument("--num-examples", type=int, default=1281167)
    data.add_argument("--data-nthreads", type=int, default=4,
                      help="decode worker threads (native pipeline)")
    return data


def add_data_aug_args(parser):
    aug = parser.add_argument_group("Augmentation")
    aug.add_argument("--random-crop", type=int, default=1)
    aug.add_argument("--random-mirror", type=int, default=1)
    return aug


def get_mnist_iter(args, kv):
    """MNIST iterators sharded by kvstore rank (reference:
    train_mnist.py get_mnist_iter)."""
    image_shape = (1, 28, 28) if not getattr(args, "flat", False) else (784,)
    train = mx.io.MNISTIter(
        image="data/train-images-idx3-ubyte",
        label="data/train-labels-idx1-ubyte",
        batch_size=args.batch_size, shuffle=True, flat=len(image_shape) == 1,
        num_parts=kv.num_workers, part_index=kv.rank)
    val = mx.io.MNISTIter(
        image="data/t10k-images-idx3-ubyte",
        label="data/t10k-labels-idx1-ubyte",
        batch_size=args.batch_size, shuffle=False,
        flat=len(image_shape) == 1,
        num_parts=kv.num_workers, part_index=kv.rank)
    return train, val


def get_rec_iter(args, kv):
    """ImageRecordIter pair over the native pipeline (reference:
    common/data.py get_rec_iter)."""
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    mean = [float(x) for x in args.rgb_mean.split(",")]
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, data_shape=image_shape,
        batch_size=args.batch_size, shuffle=True,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        rand_crop=bool(args.random_crop), rand_mirror=bool(args.random_mirror),
        preprocess_threads=args.data_nthreads,
        num_parts=kv.num_workers, part_index=kv.rank)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(
            path_imgrec=args.data_val, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=False,
            mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
            preprocess_threads=args.data_nthreads,
            num_parts=kv.num_workers, part_index=kv.rank)
    return train, val


def synthetic_rec_file(path, num=256, classes=10, hw=32, seed=0):
    """Write a synthetic-but-separable RecordIO image dataset (zero-egress
    container: real ImageNet is unavailable; class k brightens row-band k)."""
    from mxnet_tpu.recordio import IRHeader, MXRecordIO, pack_img

    rng = np.random.RandomState(seed)
    rec = MXRecordIO(path, "w")
    band = hw // classes
    for i in range(num):
        lab = i % classes
        img = (rng.rand(hw, hw, 3) * 80).astype(np.uint8)
        img[lab * band:(lab + 1) * band] += 120
        rec.write(pack_img(IRHeader(0, float(lab), i, 0), img))
    rec.close()
    return path
