#!/usr/bin/env python
"""Train ImageNet-style RecordIO datasets (reference:
example/image-classification/train_imagenet.py).

With --data-train synthetic (default), a synthetic separable RecordIO
set is generated on the fly (zero-egress container).
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(__file__))

from common import data as common_data
from common import fit as common_fit


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="train imagenet",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    common_fit.add_fit_args(parser)
    common_data.add_data_args(parser)
    common_data.add_data_aug_args(parser)
    parser.set_defaults(network="resnet50_v1", num_classes=1000,
                        image_shape="3,224,224", batch_size=128,
                        num_epochs=90, lr=0.1, lr_step_epochs="30,60,80")
    args = parser.parse_args(argv)

    if not args.data_train or args.data_train == "synthetic":
        tmp = os.path.join(tempfile.gettempdir(), "synthetic_train.rec")
        hw = int(args.image_shape.split(",")[1])
        common_data.synthetic_rec_file(
            tmp, num=min(args.num_examples, 512),
            classes=min(args.num_classes, 10), hw=hw)
        args.data_train = tmp
        args.num_examples = min(args.num_examples, 512)
        args.num_classes = min(args.num_classes, 10)

    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    net = common_fit.get_network(args.network, args.num_classes, image_shape)
    return common_fit.fit(args, net, common_data.get_rec_iter)


if __name__ == "__main__":
    main()
