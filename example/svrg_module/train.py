#!/usr/bin/env python
"""SVRG linear regression (reference: example/svrg_module/
linear_regression/train.py — variance-reduced SGD via SVRGModule:
periodic full-gradient snapshots correct each minibatch gradient, so
large constant learning rates stay stable).
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu.contrib.svrg_optimization import SVRGModule


def build_sym():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("lin_reg_label")
    out = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    return mx.sym.LinearRegressionOutput(out, label=label, name="lin_reg")


def main(argv=None):
    p = argparse.ArgumentParser(description="SVRG linear regression")
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--num-features", type=int, default=16)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--lr", type=float, default=0.25)
    p.add_argument("--update-freq", type=int, default=2,
                   help="epochs between full-gradient snapshots")
    args = p.parse_args(argv)
    mx.random.seed(7)

    rng = np.random.RandomState(0)
    w_true = rng.randn(args.num_features, 1).astype(np.float32)
    x = rng.randn(args.num_examples, args.num_features).astype(np.float32)
    y = (x @ w_true).ravel() + 0.01 * rng.randn(args.num_examples) \
        .astype(np.float32)
    it = mx.io.NDArrayIter(data=x, label=y, batch_size=args.batch_size,
                           label_name="lin_reg_label")

    mod = SVRGModule(build_sym(), data_names=("data",),
                     label_names=("lin_reg_label",),
                     update_freq=args.update_freq)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr})
    metric = mx.metric.MSE()
    mses = []
    for epoch in range(args.epochs):
        if epoch % args.update_freq == 0:
            mod.update_full_grads(it)   # the SVRG snapshot
        it.reset()
        metric.reset()
        for batch in it:
            mod.update_svrg(batch)      # fwd/bwd + variance-reduced step
            mod.update_metric(metric, batch.label)
        mses.append(metric.get()[1])
        print("epoch %d: mse %.5f" % (epoch, mses[-1]))
    return mses


if __name__ == "__main__":
    main()
