#!/usr/bin/env python
"""Multi-digit captcha recognition (reference:
example/captcha/mxnet_captcha.R — a CNN whose final FC layer emits
label_width x 10 logits, trained with a per-digit softmax and scored by
whole-captcha accuracy: all digits must match).

The captcha corpus is rendered in-process (zero-egress container): each
image is ``label_width`` digits drawn from a 5x7 bitmap font, scaled,
jittered in position, over Gaussian noise — enough nuisance variation
that the net must actually localize and read the glyphs.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

# 5x7 bitmap font for digits 0-9 (rows of 5 bits, top to bottom)
FONT = {
    0: "01110 10001 10011 10101 11001 10001 01110",
    1: "00100 01100 00100 00100 00100 00100 01110",
    2: "01110 10001 00001 00010 00100 01000 11111",
    3: "11111 00010 00100 00010 00001 10001 01110",
    4: "00010 00110 01010 10010 11111 00010 00010",
    5: "11111 10000 11110 00001 00001 10001 01110",
    6: "00110 01000 10000 11110 10001 10001 01110",
    7: "11111 00001 00010 00100 01000 01000 01000",
    8: "01110 10001 10001 01110 10001 10001 01110",
    9: "01110 10001 10001 01111 00001 00010 01100",
}
GLYPHS = np.zeros((10, 7, 5), np.float32)
for d, rows in FONT.items():
    for r, row in enumerate(rows.split()):
        for c, bit in enumerate(row):
            GLYPHS[d, r, c] = float(bit == "1")

H = 24                      # canvas height; width is 16 px per digit


def render(rng, digits):
    """Draw digits with per-glyph 2x scaling and position jitter."""
    img = rng.normal(0.1, 0.08, (H, 16 * len(digits))).astype(np.float32)
    for i, d in enumerate(digits):
        g = np.kron(GLYPHS[d], np.ones((2, 2), np.float32))   # 14x10
        r = 5 + rng.randint(-3, 4)
        c = i * 16 + 3 + rng.randint(-2, 3)
        img[r:r + 14, c:c + 10] = np.maximum(
            img[r:r + 14, c:c + 10], g * rng.uniform(0.7, 1.0))
    return img


def make_data(rng, n, label_width):
    x = np.zeros((n, 1, H, 16 * label_width), np.float32)
    y = rng.randint(0, 10, (n, label_width))
    for i in range(n):
        x[i, 0] = render(rng, y[i])
    return x, y.astype(np.float32)


def build_net(label_width):
    """conv-pool x2 + fc, final fc emits label_width*10 logits
    (reference mxnet_captcha.R net)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(32, 5, padding=2, activation="relu"),
                nn.MaxPool2D(2),
                nn.Conv2D(32, 5, padding=2, activation="relu"),
                nn.MaxPool2D(2),
                nn.Flatten(),
                nn.Dense(256, activation="relu"),
                nn.Dense(label_width * 10))
    return net


def captcha_accuracy(logits, y):
    """Whole-captcha accuracy: every digit correct (reference
    mx.metric.acc2)."""
    pred = logits.reshape(len(y), -1, 10).argmax(-1)
    return float((pred == y).all(axis=1).mean())


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n-train", type=int, default=3000)
    p.add_argument("--n-test", type=int, default=512)
    p.add_argument("--label-width", type=int, default=4)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=1)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    X, Y = make_data(rng, args.n_train, args.label_width)
    Xt, Yt = make_data(rng, args.n_test, args.label_width)

    net = build_net(args.label_width)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    # per-digit softmax over the (N*label_width, 10) reshape, exactly
    # the reference's transpose/Reshape trick
    loss_fn = gluon.loss.SoftmaxCELoss()

    nb = args.n_train // args.batch_size
    if args.epochs == 0:        # still report the untrained accuracy
        return captcha_accuracy(net(mx.nd.array(Xt)).asnumpy(), Yt)
    for epoch in range(args.epochs):
        perm = rng.permutation(args.n_train)
        tot = 0.0
        for b in range(nb):
            idx = perm[b * args.batch_size:(b + 1) * args.batch_size]
            data = mx.nd.array(X[idx])
            label = mx.nd.array(Y[idx].reshape(-1))
            with autograd.record():
                out = net(data).reshape((-1, 10))
                l = loss_fn(out, label)
            l.backward()
            trainer.step(args.batch_size)
            tot += float(l.mean().asscalar())
        acc = captcha_accuracy(net(mx.nd.array(Xt)).asnumpy(), Yt)
        print("Epoch [%d] loss %.4f captcha acc %.4f"
              % (epoch, tot / nb, acc))
    return acc


if __name__ == "__main__":
    main()
