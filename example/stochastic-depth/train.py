#!/usr/bin/env python
"""Stochastic depth (reference: example/stochastic-depth — residual
blocks randomly skipped during training, all active at inference with
survival-probability scaling; Huang et al. 2016)."""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


class StochasticResidual(gluon.Block):
    """Residual block skipped with probability 1 - p_survive in train
    mode; output scaled by p_survive at inference."""

    def __init__(self, units, p_survive, **kwargs):
        super().__init__(**kwargs)
        self.p_survive = float(p_survive)
        with self.name_scope():
            self.body = nn.Dense(units, activation="relu", flatten=False,
                                 in_units=units)

    def forward(self, x):
        if mx.autograd.is_training():
            if np.random.rand() < self.p_survive:
                return x + self.body(x)
            return x
        return x + self.p_survive * self.body(x)


def main(argv=None):
    p = argparse.ArgumentParser(description="stochastic depth")
    p.add_argument("--depth", type=int, default=6)
    p.add_argument("--units", type=int, default=32)
    p.add_argument("--epochs", type=int, default=80)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args(argv)
    mx.random.seed(7)
    np.random.seed(7)

    net = nn.Sequential()
    net.add(nn.Dense(args.units, activation="relu", in_units=12))
    # linearly decaying survival probability (the paper's schedule)
    for i in range(args.depth):
        p_surv = 1.0 - 0.5 * (i + 1) / args.depth
        net.add(StochasticResidual(args.units, p_surv))
    net.add(nn.Dense(3, in_units=args.units))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    x = rng.randn(256, 12).astype(np.float32)
    y = (x @ rng.randn(12, 3)).argmax(1).astype(np.float32)
    xs, ys = mx.nd.array(x), mx.nd.array(y)
    for epoch in range(args.epochs):
        with mx.autograd.record():
            L = ce(net(xs), ys)
        L.backward()
        trainer.step(len(x))
    out = net(xs).asnumpy()          # inference: all blocks, scaled
    acc = float((out.argmax(1) == y).mean())
    print("train accuracy (full-depth inference) %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
