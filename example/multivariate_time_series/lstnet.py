#!/usr/bin/env python
"""LSTNet for multivariate time-series forecasting (reference:
example/multivariate_time_series/src/lstnet.py — Lai et al. 2018,
"Modeling Long- and Short-Term Temporal Patterns with Deep Neural
Networks").

The architecture, built symbolically like the reference and trained
through mx.mod.Module:

* CNN: parallel causal convolutions (one per filter size, input padded
  so output length == q) over the (q, D) window, relu, concat.
* RNN: stacked GRU over the conv features; last unrolled output.
* Skip-RNN: a second GRU whose outputs are sampled every
  ``seasonal_period`` steps (counted back from the window end) and
  concatenated, capturing periodic structure.
* AR: an independent linear model per input series (the "highway"
  component that makes the net robust to scale drift).
* Output: dense(neural) + AR, linear regression loss.

Data is a synthetic electricity-style panel (zero-egress container):
D correlated series, each a phase-shifted daily cycle plus trend noise,
so the seasonal skip connections have real structure to exploit.

The evaluation metric is RRSE (root relative squared error, reference
src/metrics.py) — < 1.0 beats predicting the mean.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx


def make_panel(rng, t_len=2400, n_series=8, period=24):
    """Correlated seasonal panel: shared daily cycle + per-series phase,
    amplitude, and AR(1) noise."""
    t = np.arange(t_len)
    phase = rng.uniform(0, 2 * np.pi, n_series)
    amp = rng.uniform(0.5, 1.5, n_series)
    base = np.sin(2 * np.pi * t[:, None] / period + phase[None, :]) * amp
    noise = np.zeros((t_len, n_series))
    for i in range(1, t_len):
        noise[i] = 0.8 * noise[i - 1] + rng.normal(0, 0.1, n_series)
    return (base + noise).astype(np.float32)


def build_iters(x, q, horizon, splits, batch_size):
    """Window the panel into (N, q, D) examples predicting x[t+horizon]
    (reference lstnet.py build_iters)."""
    n_ex = x.shape[0] - q - horizon + 1
    x_ts = np.stack([x[n:n + q] for n in range(n_ex)])
    y_ts = np.stack([x[n + q + horizon - 1] for n in range(n_ex)])
    n_train = int(n_ex * splits[0])
    n_valid = int(n_ex * splits[1])
    mk = lambda a, b: mx.io.NDArrayIter(
        data=a, label=b, batch_size=batch_size)
    return (mk(x_ts[:n_train], y_ts[:n_train]),
            mk(x_ts[n_train:n_train + n_valid],
               y_ts[n_train:n_train + n_valid]),
            (x_ts[n_train + n_valid:], y_ts[n_train + n_valid:]))


def sym_gen(q, n_series, filter_list, num_filter, dropout, rnn_state,
            seasonal_period):
    X = mx.sym.Variable("data")
    Y = mx.sym.Variable("softmax_label")
    conv_input = mx.sym.reshape(data=X, shape=(0, 1, q, -1))

    # CNN component: causal (left-padded) convs, one branch per size
    outputs = []
    for filter_size in filter_list:
        padi = mx.sym.pad(data=conv_input, mode="constant",
                          constant_value=0,
                          pad_width=(0, 0, 0, 0, filter_size - 1, 0, 0, 0))
        convi = mx.sym.Convolution(data=padi,
                                   kernel=(filter_size, n_series),
                                   num_filter=num_filter)
        acti = mx.sym.Activation(data=convi, act_type="relu")
        # (N, C, q, 1) -> (N, q, C)
        outputs.append(mx.sym.reshape(
            mx.sym.transpose(data=acti, axes=(0, 2, 1, 3)),
            shape=(0, 0, 0)))
    cnn_features = mx.sym.Concat(*outputs, dim=2)
    cnn_features = mx.sym.Dropout(cnn_features, p=dropout)

    # RNN component: stacked GRU, keep the last unrolled output
    cell = mx.rnn.SequentialRNNCell()
    cell.add(mx.rnn.GRUCell(rnn_state, prefix="rnn_"))
    cell.add(mx.rnn.DropoutCell(dropout))
    rnn_outputs, _ = cell.unroll(length=q, inputs=cnn_features,
                                 merge_outputs=False)
    rnn_features = rnn_outputs[-1]

    # Skip-RNN: sample outputs every seasonal_period steps, counted
    # back from the end of the window (reference reverses the list)
    skip_cell = mx.rnn.SequentialRNNCell()
    skip_cell.add(mx.rnn.GRUCell(rnn_state, prefix="skip_rnn_"))
    skip_cell.add(mx.rnn.DropoutCell(dropout))
    skip_outputs, _ = skip_cell.unroll(length=q, inputs=cnn_features,
                                       merge_outputs=False)
    sampled = [skip_outputs[q - 1 - i]
               for i in range(0, q, seasonal_period)]
    skip_features = mx.sym.concat(*sampled, dim=1)

    # AR component: one linear model per series over its own history
    ar_list = []
    for i in range(n_series):
        ts = mx.sym.slice_axis(data=X, axis=2, begin=i, end=i + 1)
        ar_list.append(mx.sym.FullyConnected(data=ts, num_hidden=1))
    ar_output = mx.sym.concat(*ar_list, dim=1)

    neural = mx.sym.concat(rnn_features, skip_features, dim=1)
    neural_output = mx.sym.FullyConnected(data=neural,
                                          num_hidden=n_series)
    model_output = neural_output + ar_output
    return mx.sym.LinearRegressionOutput(data=model_output, label=Y)


def rrse(pred, label):
    """Root relative squared error (reference src/metrics.py)."""
    return float(np.sqrt(((label - pred) ** 2).sum()
                         / ((label - label.mean()) ** 2).sum()))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--q", type=int, default=48,
                   help="history window length")
    p.add_argument("--horizon", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--filter-list", type=str, default="3,6")
    p.add_argument("--num-filters", type=int, default=16)
    p.add_argument("--recurrent-state-size", type=int, default=32)
    p.add_argument("--seasonal-period", type=int, default=24)
    p.add_argument("--num-epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.003)
    p.add_argument("--dropout", type=float, default=0.1)
    p.add_argument("--num-series", type=int, default=8)
    p.add_argument("--t-len", type=int, default=2400)
    p.add_argument("--seed", type=int, default=11)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    x = make_panel(rng, args.t_len, args.num_series,
                   period=args.seasonal_period)
    train_iter, val_iter, (x_test, y_test) = build_iters(
        x, args.q, args.horizon, (0.6, 0.2), args.batch_size)

    sym = sym_gen(args.q, args.num_series,
                  [int(f) for f in args.filter_list.split(",")],
                  args.num_filters, args.dropout,
                  args.recurrent_state_size, args.seasonal_period)
    module = mx.mod.Module(sym, data_names=("data",),
                           label_names=("softmax_label",))
    module.fit(train_iter, eval_data=val_iter, eval_metric="rmse",
               optimizer="adam",
               optimizer_params={"learning_rate": args.lr},
               initializer=mx.init.Uniform(0.1),
               num_epoch=args.num_epochs)

    test_iter = mx.io.NDArrayIter(data=x_test, label=y_test,
                                  batch_size=args.batch_size)
    pred = module.predict(test_iter).asnumpy()[:len(y_test)]
    score = rrse(pred, y_test)
    print("LSTNet test RRSE %.4f (< 1.0 beats the mean predictor)"
          % score)
    return score


if __name__ == "__main__":
    main()
