"""Multi-task training: one trunk, two heads, joint loss
(reference: example/multi-task/example_multi_task.py).

The API this family exercises: a Group symbol with TWO outputs bound
through one Module, per-head labels via label_names, and a composite
metric evaluating both tasks (digit class + even/odd parity).
"""

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


def build_net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    digit = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=10, name="fc_digit"),
        mx.sym.Variable("digit_label"), name="digit")
    parity = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=2, name="fc_parity"),
        mx.sym.Variable("parity_label"), name="parity")
    return mx.sym.Group([digit, parity])


class MultiTaskIter(mx.io.DataIter):
    """Wrap MNIST with a second (parity) label stream."""

    def __init__(self, inner):
        super().__init__(inner.batch_size)
        self._inner = inner
        self.provide_data = inner.provide_data
        lab = inner.provide_label[0]
        self.provide_label = [
            mx.io.DataDesc("digit_label", lab.shape, lab.dtype),
            mx.io.DataDesc("parity_label", lab.shape, lab.dtype)]

    def reset(self):
        self._inner.reset()

    def next(self):
        batch = self._inner.next()
        digit = batch.label[0]
        parity = mx.nd.array(digit.asnumpy() % 2)
        return mx.io.DataBatch(batch.data, [digit, parity], pad=batch.pad,
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)


class MultiTaskAccuracy(mx.metric.EvalMetric):
    """Mean of per-task accuracies (reference example's MultiAccuracy)."""

    def __init__(self):
        super().__init__("multi_accuracy")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            hit = (pred.asnumpy().argmax(1) ==
                   label.asnumpy().ravel()).sum()
            self.sum_metric += hit / label.shape[0]
            self.num_inst += 1


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args(argv)

    from mxnet_tpu.io.io import MNISTIter

    logging.basicConfig(level=logging.INFO)
    train = MultiTaskIter(MNISTIter(image="train",
                                    batch_size=args.batch_size, flat=True))
    val = MultiTaskIter(MNISTIter(image="val", batch_size=args.batch_size,
                                  shuffle=False, flat=True))

    mod = mx.mod.Module(build_net(), context=mx.context.current_context(),
                        label_names=("digit_label", "parity_label"))
    metric = MultiTaskAccuracy()
    mod.fit(train, eval_data=val, eval_metric=metric,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            num_epoch=args.epochs)

    metric.reset()
    mod.score(val, metric)
    acc = metric.get()[1]
    print("multi-task mean accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
