#!/usr/bin/env python
"""Neural style transfer (reference: example/neural-style/nstyle.py —
optimize an IMAGE, not weights: gradients flow through a fixed conv
feature extractor to the input, matching content features and style
Gram matrices).

Zero-egress scaling: the feature extractor is a small fixed
random-weight conv stack (random conv features carry usable style/
content statistics; no pretrained VGG download).  Content and style
targets come from synthetic images with strong structure (a bright
square vs diagonal stripes).  The optimized canvas must pull both
losses well below their initial values — the mechanics (autograd to
the input, Gram matrices, Adam on a non-parameter tensor) are exactly
the reference's.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def build_extractor(seed=7):
    """Fixed random conv stack; returns features at two depths."""
    rng = np.random.RandomState(seed)
    net = nn.Sequential()
    for f in (8, 16, 16):
        net.add(nn.Conv2D(f, 3, padding=1, activation="relu"))
    net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=1.0))
    net(mx.nd.zeros((1, 3, 32, 32)))  # resolve shapes
    # freeze: style transfer never updates extractor weights
    for p in net.collect_params().values():
        p.grad_req = "null"
    return net


def features(net, x):
    """(content_feat, style_feats) at two depths."""
    h1 = net[0](x)
    h2 = net[1](h1)
    h3 = net[2](h2)
    return h3, (h1, h3)


def gram(feat):
    b, c, h, w = feat.shape
    flat = feat.reshape((b, c, h * w))
    return mx.nd.batch_dot(flat, flat.transpose((0, 2, 1))) / (c * h * w)


def content_image(hw):
    img = np.zeros((1, 3, hw, hw), np.float32)
    img[:, :, hw // 4:3 * hw // 4, hw // 4:3 * hw // 4] = 1.0
    return img


def style_image(hw):
    img = np.zeros((1, 3, hw, hw), np.float32)
    for i in range(hw):
        img[0, :, i, (np.arange(hw) + i) % hw < hw // 4] = 1.0
    return img


def main(argv=None):
    p = argparse.ArgumentParser(description="neural style transfer")
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--iters", type=int, default=120)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--style-weight", type=float, default=50.0)
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history

    net = build_extractor()
    content = mx.nd.array(content_image(args.size))
    style = mx.nd.array(style_image(args.size))
    c_target, _ = features(net, content)
    _, s_feats = features(net, style)
    g_targets = [gram(f) for f in s_feats]

    rng = np.random.RandomState(0)
    canvas = mx.nd.array(rng.rand(1, 3, args.size, args.size)
                         .astype(np.float32))
    canvas.attach_grad()
    # Adam state on the image itself (reference uses its own lr schedule
    # + momentum on the image)
    opt = mx.optimizer.Adam(learning_rate=args.lr)
    state = opt.create_state(0, canvas)

    history = []
    for it in range(args.iters):
        with mx.autograd.record():
            c_feat, s_now = features(net, canvas)
            Lc = ((c_feat - c_target) ** 2).mean()
            Ls = sum(((gram(f) - g) ** 2).mean()
                     for f, g in zip(s_now, g_targets))
            L = Lc + args.style_weight * Ls
        L.backward()
        opt.update(0, canvas, canvas.grad, state)
        history.append(float(L.asnumpy()))
        if it % 20 == 0:
            print("iter %d: loss %.5f (content %.5f style %.7f)"
                  % (it, history[-1], float(Lc.asnumpy()),
                     float(Ls.asnumpy())))
    print("loss %0.5f -> %0.5f" % (history[0], history[-1]))
    return history


if __name__ == "__main__":
    main()
