"""Bucketed LSTM language model over the symbolic mx.rnn API.

The canonical reference path (example/rnn/bucketing/lstm_bucketing.py +
python/mxnet/rnn): BucketSentenceIter buckets variable-length sentences,
BucketingModule keeps one compiled executor per bucket length (on TPU:
one static-shape XLA executable per bucket), and the model is
Embedding → stacked LSTMCell.unroll → FC → SoftmaxOutput.

Runs on a synthetic corpus by default (this image carries no PTB text):
sentences are noisy walks on a ring vocabulary, so the next token is
predictable and perplexity must fall well below uniform.
"""

import argparse

import numpy as np

import mxnet_tpu as mx


def synthetic_corpus(n_sentences=600, vocab_size=16, seed=7):
    """Noisy ring walks: token_{t+1} = token_t + 1 (mod V) 85% of the
    time.  An LSTM easily learns the transition, so perplexity drops
    from ~V toward ~1.5."""
    rs = np.random.RandomState(seed)
    sentences = []
    for _ in range(n_sentences):
        length = int(rs.choice([6, 10, 14]))
        tok = int(rs.randint(1, vocab_size))
        sent = [tok]
        for _ in range(length - 1):
            tok = (tok + 1) % vocab_size if rs.rand() < 0.85 \
                else int(rs.randint(1, vocab_size))
            tok = tok or 1  # keep 0 free as the padding label
            sent.append(tok)
        sentences.append(sent)
    return sentences, vocab_size


def build_sym_gen(vocab_size, num_embed, num_hidden, num_layers):
    stack = mx.rnn.SequentialRNNCell()
    for i in range(num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden, prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        flat_label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=flat_label,
                                    name="softmax")
        return pred, ("data",), ("softmax_label",)

    return sym_gen, stack


def main(argv=None):
    p = argparse.ArgumentParser(description="bucketed LSTM LM")
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--kv-store", type=str, default="local")
    args = p.parse_args(argv)

    sentences, vocab_size = synthetic_corpus()
    buckets = [6, 10, 14]
    split = int(len(sentences) * 0.8)
    train_iter = mx.rnn.BucketSentenceIter(
        sentences[:split], args.batch_size, buckets=buckets,
        invalid_label=0)
    val_iter = mx.rnn.BucketSentenceIter(
        sentences[split:], args.batch_size, buckets=buckets,
        invalid_label=0)

    sym_gen, _stack = build_sym_gen(vocab_size, args.num_embed,
                                    args.num_hidden, args.num_layers)
    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=train_iter.default_bucket_key,
        context=mx.context.current_context())

    metric = mx.metric.Perplexity(ignore_label=0)
    model.fit(
        train_data=train_iter,
        eval_data=val_iter,
        eval_metric=metric,
        kvstore=args.kv_store,
        optimizer="adam",
        optimizer_params={"learning_rate": args.lr},
        initializer=mx.initializer.Xavier(),
        num_epoch=args.num_epochs)

    # final validation perplexity
    metric.reset()
    model.score(val_iter, metric)
    ppl = metric.get()[1]
    print("final val perplexity: %.3f (uniform would be %.1f)"
          % (ppl, vocab_size))
    return ppl


if __name__ == "__main__":
    main()
