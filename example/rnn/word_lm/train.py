#!/usr/bin/env python
"""Word-level language model (reference: example/rnn/word_lm/train.py —
LSTM LM on PTB).  The LSTM layer lowers to lax.scan; the whole
train step is one jitted XLA computation under hybridize.

Uses a synthetic Zipf-ish corpus when no PTB text is given (zero-egress
container); the model/loop structure matches the reference.
"""

import argparse
import math
import os
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.Block):
    """Embedding -> LSTM -> tied-vocab decoder (reference: word_lm/model.py)."""

    def __init__(self, vocab_size, num_embed, num_hidden, num_layers,
                 dropout=0.5, **kwargs):
        super().__init__(**kwargs)
        self.drop = nn.Dropout(dropout)
        self.encoder = nn.Embedding(vocab_size, num_embed)
        self.rnn = rnn.LSTM(num_hidden, num_layers, dropout=dropout,
                            layout="TNC")
        self.decoder = nn.Dense(vocab_size, flatten=False)
        self.num_hidden = num_hidden

    def forward(self, inputs, hidden):
        emb = self.drop(self.encoder(inputs))
        output, hidden = self.rnn(emb, hidden)
        output = self.drop(output)
        decoded = self.decoder(output.reshape((-1, self.num_hidden)))
        return decoded, hidden

    def begin_state(self, *args, **kwargs):
        return self.rnn.begin_state(*args, **kwargs)


def synthetic_corpus(num_tokens=20000, vocab=200, seed=0, noise=0.05):
    """Low-entropy corpus: a fixed token cycle with occasional noise.
    An LM that learns the cycle reaches low perplexity within a few
    epochs — a convergence signal, like PTB for the reference."""
    rng = np.random.RandomState(seed)
    cycle = rng.permutation(vocab)
    toks = np.tile(cycle, num_tokens // vocab + 1)[:num_tokens]
    flip = rng.rand(num_tokens) < noise
    toks[flip] = rng.randint(0, vocab, flip.sum())
    return toks.astype(np.float32), vocab


def batchify(data, batch_size):
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n).T  # (T, N)


def detach(hidden):
    if isinstance(hidden, (list, tuple)):
        return [detach(h) for h in hidden]
    return hidden.detach()


def main(argv=None):
    parser = argparse.ArgumentParser(description="word language model")
    parser.add_argument("--data", type=str, default="synthetic")
    parser.add_argument("--emsize", type=int, default=64)
    parser.add_argument("--nhid", type=int, default=128)
    parser.add_argument("--nlayers", type=int, default=2)
    parser.add_argument("--lr", type=float, default=1.0)
    parser.add_argument("--clip", type=float, default=0.2)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=20)
    parser.add_argument("--bptt", type=int, default=35)
    parser.add_argument("--dropout", type=float, default=0.2)
    parser.add_argument("--log-interval", type=int, default=50)
    parser.add_argument("--num-tokens", type=int, default=20000,
                        help="synthetic corpus length")
    parser.add_argument("--vocab", type=int, default=200,
                        help="synthetic corpus vocabulary")
    parser.add_argument("--optimizer", type=str, default="sgd")
    args = parser.parse_args(argv)

    if args.data == "synthetic":
        corpus, vocab = synthetic_corpus(num_tokens=args.num_tokens,
                                         vocab=args.vocab)
    else:
        with open(args.data) as f:
            words = f.read().split()
        idx = {}
        corpus = np.asarray([idx.setdefault(w, len(idx)) for w in words],
                            dtype=np.float32)
        vocab = len(idx)

    train_data = batchify(corpus, args.batch_size)
    model = RNNModel(vocab, args.emsize, args.nhid, args.nlayers,
                     args.dropout)
    model.initialize(mx.init.Xavier())
    opt_params = {"learning_rate": args.lr, "clip_gradient": args.clip}
    trainer = gluon.Trainer(model.collect_params(), args.optimizer,
                            opt_params)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    ppls = []
    for epoch in range(args.epochs):
        total_L = 0.0
        nbatch = 0
        hidden = model.begin_state(func=mx.nd.zeros,
                                   batch_size=args.batch_size)
        tic = time.time()
        for i in range(0, train_data.shape[0] - 1, args.bptt):
            seq_len = min(args.bptt, train_data.shape[0] - 1 - i)
            if seq_len < args.bptt:
                break  # static shapes: keep every step the same length
            data = mx.nd.array(train_data[i:i + seq_len])
            target = mx.nd.array(train_data[i + 1:i + 1 + seq_len])
            hidden = detach(hidden)
            with mx.autograd.record():
                output, hidden = model(data, hidden)
                L = loss_fn(output, target.reshape((-1,)))
            L.backward()
            trainer.step(args.batch_size * seq_len)
            total_L += float(L.mean().asnumpy())
            nbatch += 1
        ppl = math.exp(total_L / max(nbatch, 1))
        wps = nbatch * args.bptt * args.batch_size / (time.time() - tic)
        print("epoch %d: ppl %.1f, %.0f wps" % (epoch, ppl, wps))
        ppls.append(ppl)
    return ppls


if __name__ == "__main__":
    main()
