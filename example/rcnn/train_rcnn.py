#!/usr/bin/env python
"""Two-stage detection: RPN + Proposal + ROIAlign + classifier head
(reference: example/rcnn — Faster R-CNN, where the Proposal op turns
trained RPN outputs into NMS'd ROIs and ROI pooling feeds the region
classifier; symbol_resnet.py get_resnet_train wiring, scaled down).

Synthetic single-object scenes (class = colour channel of one solid
box).  The RPN trains against numpy-side anchor targets (IoU-assigned,
the reference's AnchorLoader role); the Proposal op (anchor decode +
clip + NMS + top-N, ops/extended.py) then produces ROIs, ROIAlign
pools backbone features under them, and a Dense head classifies the
region — gradients from the head flow through ROIAlign back into the
backbone.  Eval: top-proposal IoU hit-rate and region class accuracy.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import contrib as ndc

STRIDE = 4
SCALES = (3, 4)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)


def gen_anchors(h, w):
    """Anchor grid matching ops/extended.py proposal (reference
    GenerateAnchors rounding included) so numpy targets and the op
    decode against identical boxes."""
    base = []
    for r in RATIOS:
        for s in SCALES:
            size = STRIDE * STRIDE
            ws = round((size / r) ** 0.5)
            hs = round(ws * r)
            ws, hs = ws * s / STRIDE, hs * s / STRIDE
            base.append([-(ws * STRIDE - STRIDE) / 2,
                         -(hs * STRIDE - STRIDE) / 2,
                         (ws * STRIDE - STRIDE) / 2 + STRIDE - 1,
                         (hs * STRIDE - STRIDE) / 2 + STRIDE - 1])
    base = np.asarray(base, np.float32)                    # (A, 4)
    sx = np.arange(w, dtype=np.float32) * STRIDE
    sy = np.arange(h, dtype=np.float32) * STRIDE
    gy, gx = np.meshgrid(sy, sx, indexing="ij")
    shifts = np.stack([gx, gy, gx, gy], -1).reshape(-1, 4)  # (HW, 4)
    return (shifts[:, None, :] + base[None, :, :]).reshape(-1, 4)


def iou(anchors, box):
    ix1 = np.maximum(anchors[:, 0], box[0])
    iy1 = np.maximum(anchors[:, 1], box[1])
    ix2 = np.minimum(anchors[:, 2], box[2])
    iy2 = np.minimum(anchors[:, 3], box[3])
    iw = np.maximum(ix2 - ix1 + 1, 0)
    ih = np.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    aa = (anchors[:, 2] - anchors[:, 0] + 1) * (anchors[:, 3] - anchors[:, 1] + 1)
    ab = (box[2] - box[0] + 1) * (box[3] - box[1] + 1)
    return inter / (aa + ab - inter)


def anchor_targets(anchors, gt_boxes):
    """Per-image RPN targets (reference: rcnn AnchorLoader / proposal
    target assignment): IoU>=0.5 or best anchor -> fg, <0.3 -> bg,
    else ignore; bbox deltas for fg anchors."""
    B = len(gt_boxes)
    N = anchors.shape[0]
    labels = np.full((B, N), -1.0, np.float32)
    deltas = np.zeros((B, N, 4), np.float32)
    for i, gt in enumerate(gt_boxes):
        overlaps = iou(anchors, gt)
        labels[i, overlaps < 0.3] = 0.0
        pos = overlaps >= 0.5
        pos[int(overlaps.argmax())] = True
        labels[i, pos] = 1.0
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        acx = anchors[:, 0] + 0.5 * aw
        acy = anchors[:, 1] + 0.5 * ah
        gw = gt[2] - gt[0] + 1
        gh = gt[3] - gt[1] + 1
        deltas[i, :, 0] = (gt[0] + 0.5 * gw - acx) / aw
        deltas[i, :, 1] = (gt[1] + 0.5 * gh - acy) / ah
        deltas[i, :, 2] = np.log(gw / aw)
        deltas[i, :, 3] = np.log(gh / ah)
    return labels, deltas


class RCNN(gluon.Block):
    def __init__(self, num_classes, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.backbone = nn.Sequential()
            self.backbone.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
                              nn.MaxPool2D(2),
                              nn.Conv2D(16, 3, padding=1, activation="relu"),
                              nn.MaxPool2D(2))
            self.rpn_score = nn.Conv2D(2 * A, 1)
            self.rpn_delta = nn.Conv2D(4 * A, 1)
            # head classifies num_classes + background (reference:
            # proposal_target.py assigns label 0 = background)
            self.head = nn.Sequential()
            # LayerNorm conditions the pooled features: the RPN-trained
            # backbone's activations are sparse/skewed (62% zeros) and
            # the head stalls without it
            self.head.add(nn.Flatten(), nn.LayerNorm(in_channels=16 * 3 * 3),
                          nn.Dense(32, activation="relu",
                                   in_units=16 * 3 * 3),
                          nn.Dense(num_classes + 1, in_units=32))

    def feats(self, x):
        return self.backbone(x)

    def rpn(self, feat):
        return self.rpn_score(feat), self.rpn_delta(feat)

    def classify(self, feat, rois):
        pooled = ndc.ROIAlign(feat, rois, pooled_size=(3, 3),
                              spatial_scale=1.0 / STRIDE)
        return self.head(pooled)


def make_scenes(rng, n, hw, num_classes):
    x = (rng.rand(n, 3, hw, hw) * 0.2).astype(np.float32)
    boxes = np.zeros((n, 4), np.float32)
    cls = rng.randint(0, num_classes, n).astype(np.int32)
    for i in range(n):
        w, h = rng.randint(hw // 3, hw // 2, 2)
        x0 = rng.randint(0, hw - w)
        y0 = rng.randint(0, hw - h)
        x[i, cls[i], y0:y0 + h, x0:x0 + w] += 0.9
        boxes[i] = [x0, y0, x0 + w - 1, y0 + h - 1]
    return x, boxes, cls


def propose(net, data, hw):
    """RPN forward -> Proposal op -> (R, 5) rois (no grad)."""
    feat = net.feats(data)
    score, delta = net.rpn(feat)
    b, _, h, w = score.shape
    pairs = score.reshape((b, 2, A, h, w))
    prob = mx.nd.softmax(pairs, axis=1).reshape((b, 2 * A, h, w))
    im_info = mx.nd.array(np.tile([hw, hw, 1.0], (b, 1)).astype(np.float32))
    return ndc.Proposal(prob, delta, im_info, rpn_pre_nms_top_n=64,
                        rpn_post_nms_top_n=4, threshold=0.7,
                        rpn_min_size=4, scales=SCALES, ratios=RATIOS,
                        feature_stride=STRIDE), feat


def main(argv=None):
    p = argparse.ArgumentParser(description="scaled Faster R-CNN")
    p.add_argument("--num-classes", type=int, default=3)
    p.add_argument("--num-examples", type=int, default=192)
    p.add_argument("--hw", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=192)
    p.add_argument("--epochs-rpn", type=int, default=80)
    p.add_argument("--epochs-head", type=int, default=220)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--lr-head", type=float, default=1e-2)
    args = p.parse_args(argv)
    mx.random.seed(42)  # deterministic init regardless of process history

    rng = np.random.RandomState(0)
    x, boxes, cls = make_scenes(rng, args.num_examples, args.hw,
                                args.num_classes)
    xv, boxv, clsv = make_scenes(np.random.RandomState(99), 64, args.hw,
                                 args.num_classes)
    fh = args.hw // STRIDE
    anchors = gen_anchors(fh, fh)

    net = RCNN(args.num_classes)
    net.initialize(mx.init.Xavier())
    # per-phase trainers: one optimizer step must only apply gradients
    # the phase's backward produced (a shared trainer would re-apply the
    # other phase's stale grad buffers)
    all_params = net.collect_params()
    rpn_params = {k: v for k, v in all_params.items() if "dense" not in k}
    # phase 2 trains the region head ONLY: updating the shared backbone
    # there would shift features out from under the frozen RPN heads
    # (the reference's alternating scheme re-trains the RPN afterwards;
    # one alternation is enough at this scale)
    head_params = {k: v for k, v in all_params.items()
                   if "dense" in k or "layernorm" in k}
    trainer_rpn = gluon.Trainer(rpn_params, "adam",
                                {"learning_rate": args.lr})
    trainer_head = gluon.Trainer(head_params, "adam",
                                 {"learning_rate": args.lr_head})
    B = args.batch_size
    # batch-size defaults to the full dataset: at this scale full-batch
    # steps are the stable recipe for both phases (mini-batch proposal
    # labels near the IoU threshold make the head oscillate)
    # --- phase 1: RPN (objectness CE + smooth-L1 on fg deltas), the
    # reference's alternating-training first stage
    for epoch in range(args.epochs_rpn):
        tot_rpn = nb = 0.0
        for i in range(0, args.num_examples - B + 1, B):
            data = mx.nd.array(x[i:i + B])
            lab_np, dl_np = anchor_targets(anchors, boxes[i:i + B])
            lab = mx.nd.array(lab_np)
            dl = mx.nd.array(dl_np)
            with mx.autograd.record():
                feat = net.feats(data)
                score, delta = net.rpn(feat)
                b, _, h, w = score.shape
                # (pos-major, anchor-minor) ordering to match
                # gen_anchors / the Proposal op's flattening
                sc = score.reshape((b, 2, A, h, w)) \
                    .transpose((0, 3, 4, 2, 1)).reshape((b, -1, 2))
                logp = mx.nd.log_softmax(sc, axis=-1)
                ce = -mx.nd.pick(logp, mx.nd.clip(lab, 0, 1), axis=-1)
                mask = (lab >= 0).astype("float32")
                Lr = (ce * mask).sum() / mx.nd.clip(mask.sum(), 1, 1e9)
                dd = delta.transpose((0, 2, 3, 1)).reshape((b, -1, 4))
                diff = dd - dl
                l1 = mx.nd.smooth_l1(diff, scalar=3.0)
                fg = (lab == 1).astype("float32").reshape((b, -1, 1))
                Lb = (l1 * fg).sum() / mx.nd.clip(fg.sum() * 4, 1, 1e9)
                Lrpn = Lr + Lb
            Lrpn.backward()
            trainer_rpn.step(B)
            tot_rpn += float(Lrpn.asnumpy())
            nb += 1
        print("rpn epoch %d: loss %.4f" % (epoch, tot_rpn / nb))

    # --- phase 2: region head over Proposal ROIs (constant wrt grad).
    # ROI labels follow the reference's ProposalTarget rule: class+1
    # when the roi overlaps the gt box (IoU >= 0.5), else 0 = background
    for epoch in range(args.epochs_head):
        tot_cls = nb = 0.0
        for i in range(0, args.num_examples - B + 1, B):
            data = mx.nd.array(x[i:i + B])
            rois, _ = propose(net, data, args.hw)
            rois_np = rois.asnumpy()  # detach from any graph
            labels_np = np.zeros(len(rois_np), np.float32)
            for r in range(len(rois_np)):
                img_i = i + int(rois_np[r, 0])
                if iou(rois_np[r:r + 1, 1:], boxes[img_i])[0] >= 0.5:
                    labels_np[r] = cls[img_i] + 1
            with mx.autograd.record():
                feat = net.feats(data)
                out = net.classify(feat, mx.nd.array(rois_np))
                Lc = gluon.loss.SoftmaxCrossEntropyLoss()(
                    out, mx.nd.array(labels_np))
            Lc.backward()
            trainer_head.step(B)
            tot_cls += float(Lc.mean().asnumpy())
            nb += 1
        print("head epoch %d: cls %.4f" % (epoch, tot_cls / nb))

    # --- eval: top proposal IoU hit-rate + region classification
    rois, feat = propose(net, mx.nd.array(xv), args.hw)
    rois_np = rois.asnumpy().reshape(len(xv), 4, 5)
    hits = 0
    for i in range(len(xv)):
        top = rois_np[i, 0, 1:]
        hits += int(iou(top[None, :], boxv[i])[0] >= 0.5)
    iou_rate = hits / len(xv)
    out = net.classify(feat, mx.nd.array(rois_np.reshape(-1, 5)))
    # foreground argmax of the top proposal (background = column 0)
    pred = out.asnumpy().reshape(len(xv), 4, -1)[:, 0, 1:].argmax(axis=1)
    cls_acc = float((pred == clsv).mean())
    print("top-proposal IoU>=0.5 rate %.3f | region class acc %.3f"
          % (iou_rate, cls_acc))
    return iou_rate, cls_acc


if __name__ == "__main__":
    main()
