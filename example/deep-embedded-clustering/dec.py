#!/usr/bin/env python
"""Deep Embedded Clustering (reference:
example/deep-embedded-clustering/{dec.py,autoencoder.py,solver.py} —
Xie, Girshick & Farhadi 2016).

The reference implements the DEC soft-assignment loss as a NumpyOp
with a hand-derived backward (dec.py DECLoss.backward); here q, p, and
KL(p||q) are expressed directly in ndarray ops and autograd
differentiates them — the cluster centers are a plain Parameter updated
by the same trainer as the encoder.

Phases, as in the paper:
1. pretrain a stacked autoencoder (greedy layerwise + finetune,
   reference autoencoder.py layerwise_pretrain/finetune);
2. k-means in embedding space to initialize the centers mu;
3. alternate: recompute the sharpened target distribution p every
   ``update_interval`` batches, train on KL(p || q) where
   q_ij ~ (1 + ||z_i - mu_j||^2 / alpha)^-(alpha+1)/2 (Student-t).

Data: an intrinsic mixture task (zero-egress container) — K well-
separated Gaussian codes pushed through a fixed random nonlinear map
into 64-D, so clustering accuracy against the true component is
measurable with the Hungarian matching of the reference's cluster_acc.
"""

import argparse
import os
import sys

import numpy as np
from scipy.optimize import linear_sum_assignment

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def make_mixture(rng, n, k=4, latent=2, ambient=64):
    """K separated Gaussians in latent space -> fixed random MLP -> 64-D."""
    y = rng.randint(0, k, n)
    centers = rng.normal(0, 2.0, (k, latent))
    z = centers[y] + rng.normal(0, 0.55, (n, latent))
    w1 = rng.normal(0, 1.0, (latent, 32))
    w2 = rng.normal(0, 1.0, (32, ambient))
    x = np.tanh(z @ w1) @ w2
    x += rng.normal(0, 0.05, x.shape)
    return x.astype(np.float32), y


def cluster_acc(y_pred, y):
    """Best 1-1 label matching accuracy (reference dec.py:32, with the
    Hungarian algorithm instead of brute force)."""
    k = max(y_pred.max(), y.max()) + 1
    w = np.zeros((k, k), np.int64)
    for i in range(len(y_pred)):
        w[y_pred[i], y[i]] += 1
    rows, cols = linear_sum_assignment(-w)
    return w[rows, cols].sum() / len(y_pred)


class StackedAE(gluon.Block):
    """Symmetric stacked autoencoder with per-layer access for greedy
    pretraining (reference autoencoder.py AutoEncoderModel)."""

    def __init__(self, dims, **kwargs):
        super().__init__(**kwargs)
        self.n_layers = len(dims) - 1
        with self.name_scope():
            self.encoders = nn.Sequential()
            self.decoders = nn.Sequential()   # decoder i mirrors encoder i
            for i in range(self.n_layers):
                last = i == self.n_layers - 1
                self.encoders.add(nn.Dense(
                    dims[i + 1], activation=None if last else "relu"))
                self.decoders.add(nn.Dense(
                    dims[i], activation=None if i == 0 else "relu"))

    def encode(self, x, depth=None):
        for i in range(self.n_layers if depth is None else depth):
            x = self.encoders[i](x)
        return x

    def decode(self, z, depth=None):
        for i in reversed(range(self.n_layers if depth is None else depth)):
            x = self.decoders[i](z)
            z = x
        return z

    def forward(self, x):
        return self.decode(self.encode(x))


def pretrain(ae, X, rng, batch_size, layer_iters, finetune_iters, lr):
    """Greedy layerwise pretraining then end-to-end finetune."""
    n = len(X)

    def batches(iters):
        for _ in range(iters):
            yield mx.nd.array(X[rng.randint(0, n, batch_size)])

    l2 = gluon.loss.L2Loss()
    for depth in range(1, ae.n_layers + 1):
        params = gluon.ParameterDict()
        params.update(ae.encoders[depth - 1].collect_params())
        params.update(ae.decoders[depth - 1].collect_params())
        trainer = gluon.Trainer(params, "adam", {"learning_rate": lr})
        for data in batches(layer_iters):
            with autograd.record():
                h = ae.encode(data, depth - 1)
                h = h.detach()
                z = ae.encoders[depth - 1](h)
                r = ae.decoders[depth - 1](z)
                loss = l2(r, h)
            loss.backward()
            trainer.step(batch_size)
    trainer = gluon.Trainer(ae.collect_params(), "adam",
                            {"learning_rate": lr})
    for data in batches(finetune_iters):
        with autograd.record():
            loss = l2(ae(data), data)
        loss.backward()
        trainer.step(batch_size)


def kmeans(z, k, rng, iters=50):
    """Lloyd's algorithm (the reference uses sklearn KMeans)."""
    mu = z[rng.choice(len(z), k, replace=False)].copy()
    for _ in range(iters):
        d = ((z[:, None, :] - mu[None, :, :]) ** 2).sum(-1)
        assign = d.argmin(1)
        for j in range(k):
            pts = z[assign == j]
            if len(pts):
                mu[j] = pts.mean(0)
    return mu, assign


def soft_assign(z, mu, alpha=1.0):
    """Student-t soft assignment q (reference DECLoss.forward)."""
    d2 = ((z.expand_dims(1) - mu.expand_dims(0)) ** 2).sum(axis=2)
    q = (1.0 + d2 / alpha) ** (-(alpha + 1.0) / 2.0)
    return q / q.sum(axis=1, keepdims=True)


def target_distribution(q):
    """Sharpened, frequency-normalized p (reference dec.py refresh)."""
    w = (q ** 2) / q.sum(axis=0, keepdims=True)
    return w / w.sum(axis=1, keepdims=True)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=2048)
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--layer-iters", type=int, default=120)
    p.add_argument("--finetune-iters", type=int, default=240)
    p.add_argument("--dec-iters", type=int, default=160)
    p.add_argument("--update-interval", type=int, default=20)
    p.add_argument("--alpha", type=float, default=1.0)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--seed", type=int, default=5)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    X, y = make_mixture(rng, args.n, k=args.k)

    ae = StackedAE([X.shape[1], 64, 32, 8])
    ae.initialize(mx.init.Xavier())
    ae(mx.nd.array(X[:2]))            # materialize deferred shapes
    pretrain(ae, X, rng, args.batch_size, args.layer_iters,
             args.finetune_iters, args.lr)

    z = ae.encode(mx.nd.array(X)).asnumpy()
    mu0, assign0 = kmeans(z, args.k, rng)
    acc_kmeans = cluster_acc(assign0, y)
    print("k-means on pretrained embedding: acc %.4f" % acc_kmeans)

    # train encoder weights + centers together under one trainer
    dec_params = gluon.ParameterDict()
    dec_params.update(ae.encoders.collect_params())
    mu = dec_params.get("dec_mu_weight", shape=mu0.shape, init=mx.init.Zero())
    mu.initialize()
    mu.set_data(mx.nd.array(mu0))
    trainer = gluon.Trainer(dec_params, "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})

    Xd = mx.nd.array(X)
    p_full = None
    for it in range(args.dec_iters):
        if it % args.update_interval == 0:
            q_full = soft_assign(ae.encode(Xd), mu.data(), args.alpha)
            p_full = target_distribution(q_full.asnumpy())
        idx = rng.randint(0, args.n, args.batch_size)
        data = mx.nd.array(X[idx])
        p_batch = mx.nd.array(p_full[idx])
        with autograd.record():
            q = soft_assign(ae.encode(data), mu.data(), args.alpha)
            kl = (p_batch * mx.nd.log(p_batch / (q + 1e-10) + 1e-10)) \
                .sum(axis=1).mean()
        kl.backward()
        trainer.step(1)

    q_full = soft_assign(ae.encode(Xd), mu.data(), args.alpha)
    acc_dec = cluster_acc(q_full.asnumpy().argmax(1), y)
    print("DEC: acc %.4f (k-means init %.4f)" % (acc_dec, acc_kmeans))
    return acc_kmeans, acc_dec


if __name__ == "__main__":
    main()
