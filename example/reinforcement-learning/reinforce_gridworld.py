"""REINFORCE policy gradient on a toy gridworld
(reference: example/reinforcement-learning/parallel_actor_critic — the
non-standard training loop family: no DataIter, per-episode rollouts,
manually scaled policy-gradient loss).

Environment: a 5x5 grid, agent starts at (0, 0), goal at (4, 4),
actions {up, down, left, right}, reward -1 per step, +10 at the goal,
episodes capped at 40 steps.  The policy is a 2-layer Gluon MLP over
the one-hot cell; REINFORCE with a running-baseline converges to the
shortest path in a few hundred episodes.
"""

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


GRID = 5
ACTIONS = [(-1, 0), (1, 0), (0, -1), (0, 1)]
GOAL = (GRID - 1, GRID - 1)
MAX_STEPS = 40


def step_env(pos, action):
    dr, dc = ACTIONS[action]
    r = min(max(pos[0] + dr, 0), GRID - 1)
    c = min(max(pos[1] + dc, 0), GRID - 1)
    new = (r, c)
    if new == GOAL:
        return new, 10.0, True
    return new, -1.0, False


def one_hot(pos):
    v = np.zeros(GRID * GRID, np.float32)
    v[pos[0] * GRID + pos[1]] = 1.0
    return v


def rollout(net, rng):
    """One episode: returns (states, actions, rewards)."""
    pos = (0, 0)
    states, actions, rewards = [], [], []
    for _ in range(MAX_STEPS):
        s = one_hot(pos)
        logits = net(mx.nd.array(s[None])).asnumpy()[0]
        p = np.exp(logits - logits.max())
        p /= p.sum()
        a = int(rng.choice(len(ACTIONS), p=p))
        pos, r, done = step_env(pos, a)
        states.append(s)
        actions.append(a)
        rewards.append(r)
        if done:
            break
    return states, actions, rewards


def returns_from(rewards, gamma):
    out = np.zeros(len(rewards), np.float32)
    g = 0.0
    for t in reversed(range(len(rewards))):
        g = rewards[t] + gamma * g
        out[t] = g
    return out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=300)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--gamma", type=float, default=0.97)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.Dense(len(ACTIONS)))
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    baseline = 0.0
    episode_returns = []
    for ep in range(args.episodes):
        states, actions, rewards = rollout(net, rng)
        rets = returns_from(rewards, args.gamma)
        episode_returns.append(float(np.sum(rewards)))
        baseline = 0.95 * baseline + 0.05 * rets[0]
        adv = rets - baseline

        x = mx.nd.array(np.stack(states))
        a = mx.nd.array(np.array(actions, np.float32))
        w = mx.nd.array(adv)
        with autograd.record():
            logp = mx.nd.log_softmax(net(x), axis=-1)
            chosen = mx.nd.pick(logp, a, axis=1)
            loss = -mx.nd.sum(chosen * w) / len(actions)
        loss.backward()
        trainer.step(1)

        if (ep + 1) % 50 == 0:
            avg = float(np.mean(episode_returns[-50:]))
            print("episode %d: avg return (last 50) = %.2f" % (ep + 1, avg))

    final = float(np.mean(episode_returns[-50:]))
    # optimal: 8 steps of -1 then +10 => return 3 - but the step that
    # reaches the goal replaces its -1, so best = -7 + 10 = 3
    print("final avg return: %.2f (optimal 3.0, random walk << 0)" % final)
    return final


if __name__ == "__main__":
    main()
