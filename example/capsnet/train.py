#!/usr/bin/env python
"""Capsule network with dynamic routing (reference: example/capsnet —
Sabour et al. 2017: primary capsules -> routing-by-agreement to digit
capsules, margin loss on capsule lengths).

Scaled for CI: small conv trunk, 2 routing iterations, synthetic
quadrant-blob images (class = bright quadrant).  The routing loop is
a fixed-iteration jax-friendly computation (no data-dependent control
flow), so the whole forward stages into one XLA program.
"""

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def squash(s, axis=-1):
    """v = |s|^2/(1+|s|^2) * s/|s| (the capsule nonlinearity)."""
    sq = (s ** 2).sum(axis=axis, keepdims=True)
    norm = mx.nd.sqrt(sq + 1e-9)
    return (sq / (1.0 + sq)) * (s / norm)


class CapsNet(gluon.Block):
    def __init__(self, num_classes=4, prim_caps=8, prim_dim=4,
                 digit_dim=8, routing_iters=2, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.prim_dim = prim_dim
        self.digit_dim = digit_dim
        if routing_iters < 1:
            raise ValueError("routing_iters must be >= 1")
        self.routing_iters = routing_iters
        with self.name_scope():
            self.conv = nn.Conv2D(16, 5, strides=2, activation="relu")
            self.primary = nn.Conv2D(prim_caps * prim_dim, 3, strides=2)
            # transformation matrices u_hat = W u: one (prim_dim,
            # digit_dim) map per class, applied to every primary capsule
            # (weight-shared routing, the memory-light CapsNet variant)
            self.route_w = nn.Dense(num_classes * digit_dim,
                                    flatten=False)

    def forward(self, x):
        h = self.conv(x)
        p = self.primary(h)                       # (B, C*D, H, W)
        B = p.shape[0]
        prim = p.reshape((B, self.prim_dim, -1)).transpose((0, 2, 1))
        prim = squash(prim)                       # (B, N, prim_dim)
        N = prim.shape[1]
        # u_hat: (B, N, classes, digit_dim)
        u_hat = self.route_w(prim).reshape((B, N, self.num_classes,
                                            self.digit_dim))

        # routing by agreement (fixed iterations, softmax over classes);
        # the final iteration skips the agreement update, whose result
        # would be discarded
        b_logits = mx.nd.zeros((B, N, self.num_classes))
        for it in range(self.routing_iters):
            c = mx.nd.softmax(b_logits, axis=2)   # coupling coefficients
            s = (c.reshape((B, N, self.num_classes, 1)) * u_hat).sum(axis=1)
            v = squash(s)                         # (B, classes, digit_dim)
            if it < self.routing_iters - 1:
                agree = (u_hat * v.reshape((B, 1, self.num_classes,
                                            self.digit_dim))).sum(axis=3)
                b_logits = b_logits + agree
        return mx.nd.sqrt((v ** 2).sum(axis=2) + 1e-9)  # capsule lengths


def margin_loss(lengths, label, num_classes, m_pos=0.9, m_neg=0.1,
                lam=0.5):
    onehot = mx.nd.one_hot(label, num_classes)
    pos = onehot * mx.nd.clip(m_pos - lengths, 0, 1e9) ** 2
    neg = lam * (1 - onehot) * mx.nd.clip(lengths - m_neg, 0, 1e9) ** 2
    return (pos + neg).sum(axis=1)


def make_data(rng, n, hw=16, num_classes=4):
    x = (rng.rand(n, 1, hw, hw) * 0.2).astype(np.float32)
    y = rng.randint(0, num_classes, n).astype(np.float32)
    h = hw // 2
    for i in range(n):
        r, c = divmod(int(y[i]), 2)
        x[i, 0, r * h:(r + 1) * h, c * h:(c + 1) * h] += 0.8
    return x, y


def main(argv=None):
    p = argparse.ArgumentParser(description="capsule network")
    p.add_argument("--num-examples", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--routing-iters", type=int, default=2)
    args = p.parse_args(argv)
    args.batch_size = min(args.batch_size, args.num_examples)
    mx.random.seed(7)

    rng = np.random.RandomState(0)
    x, y = make_data(rng, args.num_examples)
    xv, yv = make_data(np.random.RandomState(99), 128)

    net = CapsNet(routing_iters=args.routing_iters)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    B = args.batch_size
    for epoch in range(args.epochs):
        tot = nb = 0.0
        for i in range(0, args.num_examples - B + 1, B):
            data = mx.nd.array(x[i:i + B])
            label = mx.nd.array(y[i:i + B])
            with mx.autograd.record():
                lengths = net(data)
                L = margin_loss(lengths, label, net.num_classes).mean()
            L.backward()
            trainer.step(B)
            tot += float(L.asnumpy())
            nb += 1
        print("epoch %d: margin loss %.4f" % (epoch, tot / nb))

    pred = net(mx.nd.array(xv)).asnumpy().argmax(axis=1)
    acc = float((pred == yv).mean())
    print("val accuracy %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
