#!/usr/bin/env python
"""Dense-Sparse-Dense training of an MLP (reference: example/dsd/mlp.py).

Phase D: ordinary SGD.  Phase S: SparseSGD prunes the smallest-magnitude
weights (mask fixed at the phase switch) and keeps them at zero.  Phase
D2: sparsity drops to
0 and the surviving topology is re-densified.  The point (Han et al.
2017) is that D2 recovers or beats the original dense accuracy after
escaping the sparse phase's saddle.

Runs on the synthetic MNIST used across this repo's examples.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

from sparse_sgd import SparseSGD, sparsity_of


def build_net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(128, activation="relu"),
                nn.Dense(64, activation="relu"),
                nn.Dense(10))
    return net


def evaluate(net, X, y):
    pred = net(mx.nd.array(X)).argmax(axis=1).asnumpy()
    return float((pred == y).mean())


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs-per-phase", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--sparsity", type=float, default=80.0,
                   help="percent of weights pruned in the S phase")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=42)
    args = p.parse_args(argv)

    from mxnet_tpu.test_utils import get_mnist
    mnist = get_mnist()
    X, y = mnist["train_data"].reshape(-1, 784), mnist["train_label"]
    Xv, yv = mnist["test_data"].reshape(-1, 784), mnist["test_label"]

    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    net = build_net()
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(X[:2]))
    loss_fn = gluon.loss.SoftmaxCELoss()

    n = len(X)
    nb = n // args.batch_size
    E = args.epochs_per_phase
    # one optimizer drives all three phases: sparsity schedule
    # [0 (D), sparsity (S), 0 (D2)] switching at epochs E and 2E
    opt = SparseSGD(pruning_switch_epoch=[E, 2 * E], batches_per_epoch=nb,
                    weight_sparsity=[0.0, args.sparsity, 0.0],
                    bias_sparsity=[0.0, 0.0, 0.0],
                    learning_rate=args.lr, momentum=args.momentum)
    trainer = gluon.Trainer(net.collect_params(), opt)

    stats = {}
    for epoch in range(3 * E):
        perm = rng.permutation(n)
        for b in range(nb):
            idx = perm[b * args.batch_size:(b + 1) * args.batch_size]
            data, label = mx.nd.array(X[idx]), mx.nd.array(y[idx])
            with autograd.record():
                l = loss_fn(net(data), label)
            l.backward()
            trainer.step(args.batch_size)
        phase = "DSD"[min(epoch // E, 2)]
        acc = evaluate(net, Xv, yv)
        sp = sparsity_of(net)
        print("Epoch %2d [%s] val acc %.4f sparsity %.3f"
              % (epoch, phase, acc, sp))
        if epoch == E - 1:
            stats["dense_acc"] = acc
        elif epoch == 2 * E - 1:
            stats["sparse_acc"], stats["sparse_sparsity"] = acc, sp
        elif epoch == 3 * E - 1:
            stats["final_acc"] = acc
    return stats


if __name__ == "__main__":
    print(main())
