#!/usr/bin/env python
"""SGD with scheduled magnitude pruning for DSD training (reference:
example/dsd/sparse_sgd.py — Han et al. 2017, "DSD: Dense-Sparse-Dense
Training for Deep Neural Networks").

The optimizer is plain SGD(+momentum) with a preprocessing step: when
the epoch crosses an entry of ``pruning_switch_epoch`` the per-weight
mask is recomputed (keep the largest (100-sparsity)% weights by
magnitude, or threshold by absolute value), and on every update the
weight, gradient, and momentum state are multiplied by the mask so
pruned connections stay dead through the sparse phase.  A sparsity of
0 restores dense training — the final D phase of DSD.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import mxnet_tpu as mx
from mxnet_tpu.optimizer import Optimizer, SGD, register


@register
class SparseSGD(SGD):
    """SGD preprocessed by pruning masks on a per-epoch schedule."""

    def __init__(self, pruning_switch_epoch, batches_per_epoch,
                 weight_sparsity=None, bias_sparsity=None,
                 weight_threshold=None, bias_threshold=None, **kwargs):
        super().__init__(**kwargs)
        self.masks = {}
        self.epoch = 0
        self.phase = 0                       # index into the schedules
        self.pruning_switch_epoch = list(pruning_switch_epoch)
        self.batches_per_epoch = batches_per_epoch
        self.batch_count = 0
        self.weight_sparsity = weight_sparsity
        self.bias_sparsity = bias_sparsity
        self.weight_threshold = weight_threshold
        self.bias_threshold = bias_threshold
        if weight_sparsity is not None:
            if bias_sparsity is None \
                    or len(weight_sparsity) != len(bias_sparsity):
                raise ValueError(
                    "weight and bias sparsity schedules must align")
        else:
            if bias_threshold is None or weight_threshold is None \
                    or len(weight_threshold) != len(bias_threshold):
                raise ValueError(
                    "weight and bias threshold schedules must align")

    def _is_bias(self, index):
        p = getattr(self, "param_dict", {}).get(index)
        name = p.name if p is not None else self.idx2name.get(
            index, str(index))
        return name.endswith("bias")

    def _compute_mask(self, index, weight):
        """Magnitude mask for the current phase (reference sparse_sgd.py
        get_masks): sparsity% smallest |w| pruned, or |w| < threshold."""
        wabs = mx.nd.abs(weight)
        if self.weight_sparsity is not None:
            sched = (self.bias_sparsity if self._is_bias(index)
                     else self.weight_sparsity)
            sparsity = sched[self.phase]
            if sparsity <= 0:
                return None                   # dense phase: no mask
            keep = max(1, int(round(weight.size * (100.0 - sparsity)
                                    / 100.0)))
            flat = wabs.reshape((-1,))
            kth = float(mx.nd.topk(flat, k=keep, ret_typ="value")
                        .asnumpy()[-1])
            return (wabs >= kth).astype(weight.dtype)
        sched = (self.bias_threshold if self._is_bias(index)
                 else self.weight_threshold)
        thr = sched[self.phase]
        if thr <= 0:
            return None
        return (wabs >= thr).astype(weight.dtype)

    def _advance_epoch(self):
        """Advance the batch/epoch counters and the pruning phase.
        Runs at the START of each batch (before any masking) so every
        parameter in a batch sees the same phase — advancing after the
        first parameter's update would let the rest of that batch slip
        into the next phase early."""
        self.batch_count += 1
        if self.batch_count > 1 \
                and (self.batch_count - 1) % self.batches_per_epoch == 0:
            self.epoch += 1
            while (self.phase < len(self.pruning_switch_epoch)
                   and self.epoch >= self.pruning_switch_epoch[self.phase]):
                self.phase += 1
                self.masks.clear()            # recompute at new sparsity

    def update(self, index, weight, grad, state):
        # tie the batch counter to the first index ever seen: it recurs
        # exactly once per batch
        if not hasattr(self, "_epoch_index"):
            self._epoch_index = index
        if index == self._epoch_index:
            self._advance_epoch()
        if index not in self.masks:
            self.masks[index] = self._compute_mask(index, weight)
        mask = self.masks[index]
        if mask is not None:
            weight[:] = weight * mask
            grad[:] = grad * mask
            if state is not None:
                state[:] = state * mask
        super().update(index, weight, grad, state)


def sparsity_of(net):
    """Fraction of exactly-zero weights across a Gluon net's params."""
    zeros = total = 0
    for p in net.collect_params().values():
        a = p.data().asnumpy()
        zeros += (a == 0).sum()
        total += a.size
    return zeros / float(total)
