"""Benchmark: ResNet-50 training throughput, single chip.

Headline metric (BASELINE.md): ResNet-50 training img/s — reference
MXNet 1.2 on V100 fp32: 298.51 img/s @ bs=32, 363.69 img/s @ bs=128
(docs/faq/perf.md:225-236).  vs_baseline compares at the SAME batch
size (128 default) against the bs=128 V100 number; pass a batch on the
CLI to measure other configs (bs=128 is also this chip's device-side
throughput peak — r4 chained measurement, BENCH_NOTES).

The whole train step (fwd+bwd+SGD momentum+BN stat update) is one
jitted XLA computation (parallel/gluon_step.py); compute in bfloat16
with fp32 master weights (MXU-native mixed precision, the analog of the
reference's multi-precision SGD).  The model runs channel-last
(layout="NHWC"); pass a third CLI arg "NCHW" for the reference layout.

Two numbers are measured and recorded in the ONE printed JSON line:

- ``value``        — through-relay headline: a Python loop of step()
  dispatches with a loss fetch per rep, what a real training loop sees
  on this container.  The relay's per-call overhead drifts ~±5% by time
  of day (BENCH_NOTES "Relay variance, quantified"), so this number is
  gated loosely (15%) and is informational.
- ``device_value`` — device-only: DEVICE_CHAIN (=50) training steps
  chained into ONE jitted computation (lax.fori_loop via
  GluonTrainStep.make_chained) so the relay's one dispatch+fetch
  amortizes below 1%, with a host fetch as the completion barrier.
  The ``steps`` CLI arg does NOT affect this metric (it sizes only the
  informational relay loop) — chained rates at different depths are
  not comparable, so the depth is pinned.  Variance ~2%; THIS is the
  regression-gated metric (5%): a real kernel slowdown trips it, relay
  weather cannot.

Gating compares against the newest recorded BENCH_r*.json (falling back
to the committed r4 floor for device_value) and exits non-zero.

Usage: python bench.py [batch] [steps] [NHWC|NCHW]
       python bench.py --compiled-step [batch] [steps] [image]
           (or MXNET_TPU_COMPILED_STEP=1): eager Trainer loop vs the
           fused whole-step program on the same model/seed — emits
           before/after diag dumps + one runtime_stats.compare()
           verdict (docs/COMPILED_STEP.md; record goes to BENCH_NOTES).
       python bench.py --zero [batch] [steps]
           (ZeRO weight-update sharding, docs/ZERO.md): eager Trainer
           loop vs trainer.compile(..., zero=True) on a BN-free MLP —
           emits before/after diag dumps + one runtime_stats.compare()
           verdict and gates on trajectory match + >=0.8*n per-device
           state shrink (record goes to BENCH_NOTES).
       python bench.py --serve [duration_s]
           serving bench: the tools/loadgen.py open-loop sweep
           (Poisson arrivals, p50/p99/p99.9 vs offered QPS, serial
           Predictor baseline + same-load serial-server replay) over
           the continuous-batching InferenceServer; prints the JSON
           report and writes the bench_serve.json artifact
           (docs/SERVING.md; record goes to BENCH_NOTES).
"""

import glob
import json
import os
import re
import statistics
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69  # ResNet-50 training bs=128, V100 fp32 (docs/faq/perf.md)
# Through-relay headline: ±5% time-of-day drift measured r3 (same code:
# 2,455 midday, 2,226 evening) -> loose gate, informational only.
RELAY_TOLERANCE = 0.15
# Device-only chained metric: ~2% variance -> tight gate.  This is the
# number that detects a real kernel regression.
DEVICE_TOLERANCE = 0.05
# fixed chain depth of the gated device metric (rates at different
# depths are not comparable: the single dispatch amortizes differently)
DEVICE_CHAIN = 50
# r4-measured device-only floor (chained x50, bs=128 NHWC bf16: 2,7xx
# img/s band) for the first gated round, before a BENCH_r*.json records
# device_value.  Keyed by (batch, layout): NCHW is measurably slower
# than NHWC and must not be judged against an NHWC floor.
DEVICE_FLOOR_IMG_S = {(128, "NHWC"): 2650.0}
# the platform the floors (and all recorded BENCH_r*.json values) were
# measured on; absolute-throughput gating on any other backend would
# fail a healthy-but-different environment (ADVICE r4 #4)
RECORDED_PLATFORM = "tpu"
# relay probing (r4/r5 post-mortems): a wedged relay must neither hang
# the parent (jax.devices() blocks in non-interruptible C code) nor
# burn the driver's whole budget on retries (r5: two 600 s probes ->
# the DRIVER killed the round, rc=124, "parsed": null).  Scheme: a
# cheap liveness PING first, then up to MAX_FULL_PROBES full probes,
# all inside a PROBE_WINDOW budget sized well under the driver's
# patience.  The WINDOW takes precedence over per-probe patience: the
# last probe is truncated to the window remainder, because a bounded
# worst case (no rc=124) matters more than giving a slow relay its
# full per-probe timeout.  Killing a mid-init probe child (the ping on
# a >30 s cold start) can itself wedge the relay — accepted: the full
# probes still give it a chance, and the terminal fallback is an
# informational record (value null + the last green chained-depth
# metrics) with exit 0, not a failed round — see emit_wedged_record().
# A probe child that EXITS non-zero is a deterministic environment
# failure and fails fast.
PING_TIMEOUT = 30
PROBE_TIMEOUT = 600
MAX_FULL_PROBES = 2
PROBE_WINDOW = 15 * 60


def _cost_capture():
    """Context that forces compile-time cost/x-ray capture while the
    wrapped warmup step compiles, so the --compiled-step / --zero A/B
    diag dumps embed the per-scope x-ray table (BENCH_NOTES
    attribution rides along free).  An explicit
    MXNET_TPU_COST_ANALYSIS=0 in the environment still wins."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        prev = os.environ.get("MXNET_TPU_COST_ANALYSIS")
        if prev is None:
            os.environ["MXNET_TPU_COST_ANALYSIS"] = "1"
        try:
            yield
        finally:
            if prev is None:
                os.environ.pop("MXNET_TPU_COST_ANALYSIS", None)

    return ctx()


def prior_round_values(batch, layout, chain_depth=DEVICE_CHAIN):
    """Newest comparable recorded driver bench: (file, headline,
    device_value) — device_value is None for rounds before r4 or when
    the recorded chain depth differs (not like-for-like)."""
    here = os.path.dirname(os.path.abspath(__file__))
    newest = None

    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    # numeric sort: BENCH_r10 must come after BENCH_r9, not before r2
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       key=round_no):
        try:
            with open(path) as f:
                # failed rounds record "parsed": null (r4's wedged-relay
                # artifact) — they carry no comparison point
                parsed = json.load(f).get("parsed") or {}
            value = parsed.get("value")
            # only gate like-for-like: a `bench.py 32` exploration run,
            # an NCHW comparison run, or a record captured on another
            # backend must not trip against the bs=128 NHWC TPU numbers
            # (records before r5 carry no platform field: all TPU)
            if parsed.get("platform", RECORDED_PLATFORM) != RECORDED_PLATFORM:
                continue
            metric = parsed.get("metric", "")
            if value and ("(bs=%d," % batch) in metric \
                    and (", %s," % layout) in metric:
                device = parsed.get("device_value")
                if ("(%d steps" % chain_depth) not in \
                        parsed.get("device_metric", ""):
                    device = None  # different chain depth: incomparable
                newest = (os.path.basename(path), float(value), device)
        except (OSError, ValueError):
            continue
    return newest


def check_regression(name, value, prior, tolerance):
    """True (and a stderr report) when value regressed past tolerance."""
    if prior is None or value >= (1.0 - tolerance) * prior:
        return False
    print("REGRESSION(%s): %.1f img/s is >%d%% below the prior %.1f img/s"
          % (name, value, int(tolerance * 100), prior), file=sys.stderr)
    return True


def _probe_once(timeout):
    """One KILLABLE device-probe child (the TPU relay is this
    container's only device path, and killed jax clients can wedge it
    server-side: every process then hangs inside jax.devices() in
    non-interruptible C code — SIGALRM cannot break it, a child's
    kill() can).  Returns 'ok'/'timeout'; a child that EXITS non-zero
    is a deterministic environment failure and raises SystemExit."""
    import subprocess

    try:
        subprocess.run([sys.executable, "-c",
                        "import jax; jax.devices()"],
                       timeout=timeout, check=True,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)
        return "ok"
    except subprocess.CalledProcessError:
        # retrying cannot help a broken jax/plugin init — diagnose now
        raise SystemExit(
            "bench: the device probe child exited non-zero (jax "
            "backend failed to initialize — environment problem, "
            "not a relay wedge); run `python -c 'import jax; "
            "jax.devices()'` to see the error.")
    except subprocess.TimeoutExpired:
        return "timeout"


def probe_relay():
    """True when the relay answered a probe; False when it looks
    wedged.  A cheap PING_TIMEOUT liveness ping settles the healthy
    case in seconds; only then do up to MAX_FULL_PROBES full-timeout
    probes run, capped by the PROBE_WINDOW budget so the whole probe
    phase stays well under the bench driver's patience (r5: unbounded
    600 s retries got the round killed with rc=124)."""
    deadline = time.monotonic() + PROBE_WINDOW
    if _probe_once(PING_TIMEOUT) == "ok":
        return True
    print("bench: relay liveness ping timed out after %ds; escalating "
          "to full probes" % PING_TIMEOUT, file=sys.stderr)
    for attempt in range(1, MAX_FULL_PROBES + 1):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        t = int(min(PROBE_TIMEOUT, max(1, remaining)))
        if _probe_once(t) == "ok":
            return True
        print("bench: relay probe %d/%d timed out after %ds"
              % (attempt, MAX_FULL_PROBES, t), file=sys.stderr)
    return False


def emit_wedged_record(batch, layout):
    """Wedged-relay fallback: print ONE parseable JSON record with
    ``value: null`` (prior_round_values skips null-valued records, so
    no future gate compares against it) carrying the last green
    round's headline and chained-depth device metrics informationally,
    and report success — a wedged relay costs the round its fresh
    number, it must not fail the round (r4 rc=1 / r5 rc=124
    artifacts)."""
    prior = prior_round_values(batch, layout)
    rec = {
        "metric": "resnet50_v1 training img/s (bs=%d, bf16 compute, %s, "
                  "1 chip, median of 3)" % (batch, layout),
        "value": None,
        "unit": "img/s",
        "device_value": None,
        "device_metric": "device-only img/s (%d steps chained in one "
                         "jit, host-fetch barrier, median of 3)"
                         % DEVICE_CHAIN,
        "relay": "wedged",
    }
    if prior:
        rec["last_green"] = {"file": prior[0], "value": prior[1],
                             "device_value": prior[2]}
    print(json.dumps(rec))
    print("bench: TPU relay unreachable (wedged — killed jax clients "
          "hold the single session server-side; see BENCH_NOTES 'Relay "
          "variance'); recorded the last green chained-depth metrics "
          "informationally instead of failing the round.",
          file=sys.stderr)


def run_compiled_compare(batch=8, steps=6, image=64, layout="NHWC",
                         net_fn=None, out_prefix="bench_compiled",
                         data_shape=None, num_classes=1000):
    """``--compiled-step`` mode: eager Trainer loop vs the fused
    whole-step program (mxnet_tpu/compiled_step.py) on the same model,
    seed, and synthetic data — the ROADMAP's one-``--compare``-run
    contract for perf PRs.

    Runs each side with stepstats/diag timing on, resets the counters
    after a warmup step, dumps both diag snapshots
    (``<out_prefix>.eager.diag.json`` / ``.fused.diag.json``), prints
    ``runtime_stats.compare()``'s verdict (note: the new
    ``phase:compiled_step`` / ``op:compiled_step`` rows on the fused
    side read as 0→inf "new cost" entries by compare()'s documented
    semantics — the wall/dispatch rows carry the actual before/after)
    plus one machine-readable JSON line, and returns (rc, record):
    rc 0 iff the losses match and the fused side shows BOTH the
    warm-dispatch collapse to ~1 call/step AND a step-wall
    improvement.  ``net_fn(`` builds a fresh identically-seeded model
    (defaults to the bench ResNet-50)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import random as mxrandom
    from mxnet_tpu import runtime_stats as rts
    from mxnet_tpu import stepstats

    stepstats.enable()

    def default_net():
        from mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet50_v1(layout=layout)
        probe = (1, 3, 32, 32) if layout == "NCHW" else (1, 32, 32, 3)
        net.initialize(ctx=mx.cpu() if not mx.context.num_tpus()
                       else mx.tpu())
        net(mx.nd.zeros(probe))
        return net

    build = net_fn or default_net
    if data_shape is None:
        data_shape = (batch, 3, image, image) if layout == "NCHW" \
            else (batch, image, image, 3)
    rng = np.random.RandomState(0)
    xs = [rng.rand(*data_shape).astype(np.float32)
          for _ in range(steps + 1)]
    ys = [rng.randint(0, num_classes, (batch,)).astype(np.int32)
          for _ in range(steps + 1)]
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def fresh(seed=7):
        mxrandom.seed(seed)
        np.random.seed(seed)
        return build()

    def steady_anatomy():
        snap = rts.snapshot()
        ss = snap.get("stepstats") or {}
        n = ss.get("steps") or 1
        wall = ((ss.get("wall") or {}).get("sum") or 0.0) / n * 1e3
        # per-step RATES divide by the counted steps, not the stepstats
        # window count: the first end_step after reset() only arms the
        # clock, so windows = steps-1 and using it would inflate the
        # headline dispatches/step by N/(N-1)
        steps = (snap.get("counters") or {}).get("trainer_steps") or 1
        warm = (snap.get("totals") or {}).get("jit_cache_hits", 0) / steps
        return snap, wall, warm

    # ---- eager side ---------------------------------------------------
    net = fresh()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9,
                             "wd": 1e-4})
    losses_eager = []

    def eager_step(x, y):
        xa, ya = mx.nd.array(x), mx.nd.array(y)
        with autograd.record():
            l = loss_fn(net(xa), ya)
        l.backward()
        trainer.step(batch)
        return l

    eager_step(xs[0], ys[0])  # warmup: compiles land before the window
    rts.reset()
    for x, y in zip(xs[1:], ys[1:]):
        losses_eager.append(eager_step(x, y))
    # capture the dump BEFORE the loss fetches: the readback means are
    # measurement overhead, not part of the measured loop
    eager_dump, eager_wall, eager_warm = steady_anatomy()
    eager_path = out_prefix + ".eager.diag.json"
    rts.dump_diag(eager_path)
    losses_eager = [float(np.asarray(l.mean().data_jax))
                    for l in losses_eager]

    # ---- fused side ---------------------------------------------------
    rts.reset()
    net = fresh()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9,
                             "wd": 1e-4})
    cs = trainer.compile(net, loss_fn)
    with _cost_capture():  # warmup compiles -> x-ray lands in the dump
        cs.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    rts.reset()
    losses_fused = []
    for x, y in zip(xs[1:], ys[1:]):
        losses_fused.append(cs.step(mx.nd.array(x), mx.nd.array(y)))
    fused_dump, fused_wall, fused_warm = steady_anatomy()
    fused_path = out_prefix + ".fused.diag.json"
    rts.dump_diag(fused_path)
    losses_fused = [float(np.asarray(l.mean().data_jax))
                    for l in losses_fused]

    # ---- verdict ------------------------------------------------------
    result = rts.compare(eager_dump, fused_dump)
    print(rts.render_compare(result), file=sys.stderr)
    # step 1 ran the same function on the same init: near-bit-equal.
    # later steps drift in the last float ulps (the fused program's
    # XLA autodiff reassociates conv-backward reductions vs the
    # per-op tape) and training amplifies it — trajectory-level
    # tolerance, not bit equality, is the right check there.
    losses_match = bool(
        np.allclose(losses_eager[:1], losses_fused[:1], rtol=1e-5)
        and np.allclose(losses_eager, losses_fused, rtol=5e-2))
    import jax

    ok = losses_match and fused_warm <= 2.0 and fused_wall < eager_wall
    record = {
        "metric": "compiled_step eager-vs-fused (bs=%d, data %s, %d "
                  "steps, same seed)" % (batch, list(data_shape[1:]),
                                         steps),
        "verdict": "improvement" if ok else "regression",
        # raw compare() verdict: the fused side's NEW
        # phase:compiled_step / op:compiled_step rows read as 0->inf
        # entries by its documented new-cost semantics — the wall /
        # dispatch / per-phase rows carry the real before/after
        "compare_verdict": result["verdict"],
        "step_wall_ms": {"eager": round(eager_wall, 3),
                         "fused": round(fused_wall, 3)},
        "warm_dispatches_per_step": {"eager": round(eager_warm, 1),
                                     "fused": round(fused_warm, 1)},
        "losses_match": losses_match,
        "dumps": [eager_path, fused_path],
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(record))
    if not ok:
        print("compiled-step compare FAILED: losses_match=%s "
              "fused_warm=%.1f/step fused_wall=%.3fms vs eager "
              "%.3fms" % (losses_match, fused_warm, fused_wall,
                          eager_wall), file=sys.stderr)
    return (0 if ok else 1), record


def run_zero_compare(batch=64, steps=8, features=256, hidden=512,
                     classes=100, out_prefix="bench_zero"):
    """``--zero`` mode: the same eager Trainer loop vs the ZeRO
    weight-update-sharded whole-step program
    (``trainer.compile(net, loss, zero=True)`` —
    parallel/gluon_step.py) on one model, seed, and synthetic data.

    The model is a BN-free multi-layer perceptron on purpose: batch-norm
    statistics are computed per dp shard under the sharded step, which
    is a (documented) modeling difference, not a ZeRO numerics bug —
    an elementwise-optimizer MLP isolates what this mode is gating:
    the loss trajectory staying equivalent while per-device
    param+optimizer-state bytes shrink ~n× and the new collective
    traffic (``zero_allgather_bytes`` / ``zero_reduce_bytes``) is
    accounted.  Emits both diag dumps (``<out_prefix>.eager.diag.json``
    / ``.zero.diag.json``), prints ``runtime_stats.compare()``'s
    verdict (the zero:* rows land in its one-sided ``notes`` — a
    topology change, not a regression) plus one JSON record line, and
    returns (rc, record): rc 0 iff the trajectories match AND the
    measured state shrink clears 0.8×n."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu import random as mxrandom
    from mxnet_tpu import runtime_stats as rts
    from mxnet_tpu import stepstats
    from mxnet_tpu.gluon import nn

    stepstats.enable()

    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(hidden, activation="relu"),
                nn.Dense(hidden, activation="relu"),
                nn.Dense(classes))
        net.initialize(ctx=mx.cpu())
        net(mx.nd.zeros((2, features)))
        return net

    def fresh(seed=7):
        mxrandom.seed(seed)
        np.random.seed(seed)
        return build()

    rng = np.random.RandomState(0)
    xs = [rng.rand(batch, features).astype(np.float32)
          for _ in range(steps + 1)]
    ys = [rng.randint(0, classes, (batch,)).astype(np.int32)
          for _ in range(steps + 1)]
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt_args = {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}

    def steady_wall():
        snap = rts.snapshot()
        ss = snap.get("stepstats") or {}
        n = ss.get("steps") or 1
        return snap, ((ss.get("wall") or {}).get("sum") or 0.0) / n * 1e3

    # ---- eager side ---------------------------------------------------
    net = fresh()
    trainer = gluon.Trainer(net.collect_params(), "sgd", opt_args)
    losses_eager = []

    def eager_step(x, y):
        xa, ya = mx.nd.array(x), mx.nd.array(y)
        with autograd.record():
            l = loss_fn(net(xa), ya)
        l.backward()
        trainer.step(batch)
        return l

    eager_step(xs[0], ys[0])  # warmup: compiles land before the window
    rts.reset()
    for x, y in zip(xs[1:], ys[1:]):
        losses_eager.append(eager_step(x, y))
    eager_dump, eager_wall = steady_wall()
    eager_path = out_prefix + ".eager.diag.json"
    rts.dump_diag(eager_path)
    losses_eager = [float(np.asarray(l.mean().data_jax))
                    for l in losses_eager]

    # ---- ZeRO side ----------------------------------------------------
    rts.reset()
    net = fresh()
    trainer = gluon.Trainer(net.collect_params(), "sgd", opt_args)
    zs = trainer.compile(net, loss_fn, zero=True)
    with _cost_capture():  # warmup compiles -> x-ray lands in the dump
        zs.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    rts.reset()
    losses_zero = []
    for x, y in zip(xs[1:], ys[1:]):
        losses_zero.append(zs.step(mx.nd.array(x), mx.nd.array(y)))
    zero_dump, zero_wall = steady_wall()
    zero_path = out_prefix + ".zero.diag.json"
    rts.dump_diag(zero_path)
    losses_zero = [float(np.asarray(l.mean().data_jax))
                   for l in losses_zero]

    # ---- verdict ------------------------------------------------------
    result = rts.compare(eager_dump, zero_dump)
    print(rts.render_compare(result), file=sys.stderr)
    # same trajectory contract as --compiled-step: the fused program's
    # XLA autodiff + the dp-sharded mean reassociate reductions, so
    # later steps drift in the last ulps and training amplifies it
    losses_match = bool(
        np.allclose(losses_eager[:1], losses_zero[:1], rtol=1e-5)
        and np.allclose(losses_eager, losses_zero, rtol=5e-2))
    layout = zs.zero_layout
    n = layout["n"]
    shrink = (layout["replicated_param_bytes"]
              / max(1, layout["per_device_param_bytes"]))
    counters = (zero_dump.get("counters") or {})
    zsteps = counters.get("zero_steps") or 1
    import jax

    ok = losses_match and shrink >= 0.8 * n
    record = {
        "metric": "zero eager-vs-sharded (bs=%d, mlp %d-%dx2-%d, %d "
                  "steps, same seed, dp=%d)"
                  % (batch, features, hidden, classes, steps, n),
        "verdict": "improvement" if ok else "regression",
        "compare_verdict": result["verdict"],
        "losses_match": losses_match,
        "dp": n,
        "state_shrink_x": round(shrink, 2),
        "per_device_param_bytes": layout["per_device_param_bytes"],
        "per_device_state_bytes": layout["per_device_state_bytes"],
        "replicated_param_bytes": layout["replicated_param_bytes"],
        "allgather_mb_per_step": round(
            counters.get("zero_allgather_bytes", 0) / zsteps / 1e6, 3),
        "reduce_mb_per_step": round(
            counters.get("zero_reduce_bytes", 0) / zsteps / 1e6, 3),
        "step_wall_ms": {"eager": round(eager_wall, 3),
                         "zero": round(zero_wall, 3)},
        "dumps": [eager_path, zero_path],
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(record))
    if not ok:
        print("zero compare FAILED: losses_match=%s shrink=%.2fx "
              "(need >= %.1fx at dp=%d)"
              % (losses_match, shrink, 0.8 * n, n), file=sys.stderr)
    return (0 if ok else 1), record


def run_serve_bench(duration=2.0, out_path="bench_serve.json"):
    """``--serve`` mode: the loadgen sweep as a bench artifact.  Runs
    on the current backend (the serving bench is CPU-meaningful — it
    measures batching/queueing economics, not kernel speed); the
    artifact records the platform so later rounds compare
    like-for-like.  Returns (rc, report): rc 0 iff the sweep sustained
    a level and the timeline soak gated clean through the trend
    doctor."""
    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tools"))
    import loadgen

    metrics = os.path.join(here, "bench_serve_timeline.jsonl")
    # a fresh soak timeline per round: stale samples from a prior run
    # would feed the trend doctor a fake regression
    if os.path.exists(metrics):
        os.remove(metrics)
    report = loadgen.sweep(duration=duration, metrics_path=metrics)
    report["platform"] = jax.devices()[0].platform
    report["unit"] = "requests/s"
    print(json.dumps(report))
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    # the bench ALWAYS requests the soak timeline, so a missing gate
    # (soak_clean None: export failed or no level sustained) is a
    # failure, not a vacuous pass
    ok = bool(report["max_sustained_qps"]) \
        and report["soak_clean"] is True
    if not ok:
        print("serve bench FAILED: max_sustained_qps=%s soak_clean=%s"
              % (report["max_sustained_qps"], report["soak_clean"]),
              file=sys.stderr)
    return (0 if ok else 1), report


def main():
    if "--zero" in sys.argv:
        # the sharding is degenerate at one device: on a CPU container
        # force virtual devices BEFORE jax initializes (same trick as
        # conftest.py / tools/scaling_report.py); a real multi-chip
        # backend keeps its own device count
        if "jax" not in sys.modules and "XLA_FLAGS" not in os.environ \
                and os.environ.get("JAX_PLATFORMS") == "cpu":
            os.environ["XLA_FLAGS"] = \
                "--xla_force_host_platform_device_count=8"
        nums = [int(a) for a in sys.argv[1:]
                if a != "--zero" and a.lstrip("-").isdigit()]
        batch = nums[0] if nums else 64
        steps = nums[1] if len(nums) > 1 else 8
        if not probe_relay():
            emit_wedged_record(batch, "MLP")
            return
        rc, _rec = run_zero_compare(batch=batch, steps=steps)
        sys.exit(rc)
    if "--serve" in sys.argv:
        nums = [a for a in sys.argv[1:] if a not in ("--serve",)]
        duration = float(nums[0]) if nums else 2.0
        rc, _rep = run_serve_bench(duration=duration)
        sys.exit(rc)
    if "--compiled-step" in sys.argv or \
            os.environ.get("MXNET_TPU_COMPILED_STEP") == "1":
        # tolerate BOTH argv shapes: the compare form
        # `--compiled-step [batch] [steps] [image]` and the standard
        # `bench.py [batch] [steps] [NHWC|NCHW]` that launch wiring
        # uses with MXNET_TPU_COMPILED_STEP=1 — a layout token selects
        # the layout instead of crashing int() (and NCHW is compared
        # as NCHW)
        layout = "NHWC"
        nums = []
        for a in sys.argv[1:]:
            if a == "--compiled-step":
                continue
            if a in ("NHWC", "NCHW"):
                layout = a
            else:
                nums.append(int(a))
        batch = nums[0] if len(nums) > 0 else 8
        steps = nums[1] if len(nums) > 1 else 6
        image = nums[2] if len(nums) > 2 else 64
        if not probe_relay():
            emit_wedged_record(batch, layout)
            return
        rc, _rec = run_compiled_compare(batch=batch, steps=steps,
                                        image=image, layout=layout)
        sys.exit(rc)
    batch_arg = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    layout_arg = sys.argv[3] if len(sys.argv) > 3 else "NHWC"
    if not probe_relay():
        emit_wedged_record(batch_arg, layout_arg)
        return

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import random as mxrandom
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    batch, layout = batch_arg, layout_arg  # parsed before the probe
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20

    devices = jax.devices()[:1]  # single-chip benchmark
    mesh = create_mesh({"dp": 1}, devices=devices)

    net = vision.resnet50_v1(layout=layout)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    probe_shape = (1, 3, 32, 32) if layout == "NCHW" else (1, 32, 32, 3)
    with ctx:
        net.initialize(ctx=ctx)
        net(mx.nd.zeros(probe_shape, ctx=ctx))  # resolve deferred shapes
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=0.1, momentum=0.9,
                          wd=1e-4, compute_dtype="bfloat16")

    rng = np.random.RandomState(0)
    data_shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    x = rng.rand(*data_shape).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.int32)
    x, y = step.put_batch(x, y)  # device-resident synthetic batch

    # ---- device-only chained metric (the gated one) ------------------
    # depth 50: the one relay dispatch+fetch (~60 ms measured) amortizes
    # to <0.7% of the chain, so this reads the device's own step rate
    # (the r4 trace shows 45.9 ms/step inside the while loop vs 48.9 ms
    # wall at depth 20)
    chain_depth = DEVICE_CHAIN
    chained = step.make_chained(chain_depth)
    key = mxrandom.next_key()
    float(np.asarray(chained(x, y, key)))  # compile + warm
    device_rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(np.asarray(chained(x, y, key)))  # fetch = completion barrier
        device_rates.append(chain_depth * batch
                            / (time.perf_counter() - t0))
    device_img_s = statistics.median(device_rates)

    # ---- through-relay headline (what a live loop on this box sees) --
    for _ in range(3):
        l = step(x, y)
    float(np.asarray(l))
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            l = step(x, y)
        float(np.asarray(l))
        rates.append(steps * batch / (time.perf_counter() - t0))
    img_s = statistics.median(rates)

    platform = devices[0].platform
    print(json.dumps({
        "metric": "resnet50_v1 training img/s (bs=%d, bf16 compute, %s, "
                  "1 chip, median of 3)" % (batch, layout),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "device_value": round(device_img_s, 2),
        "device_metric": "device-only img/s (%d steps chained in one jit, "
                         "host-fetch barrier, median of 3)" % chain_depth,
        "platform": platform,
    }))

    if platform != RECORDED_PLATFORM:
        # every floor and recorded BENCH_r*.json value is a TPU number;
        # gating another backend against them would fail a healthy
        # environment on its first run (ADVICE r4 #4)
        print("bench: platform %r != %r that the floors were recorded "
              "on; regression gates skipped (informational run)"
              % (platform, RECORDED_PLATFORM), file=sys.stderr)
        return

    prior = prior_round_values(batch, layout)
    prior_headline = prior[1] if prior else None
    prior_device = (prior[2] if prior and prior[2]
                    else DEVICE_FLOOR_IMG_S.get((batch, layout)))
    failed = check_regression("device-only", device_img_s, prior_device,
                              DEVICE_TOLERANCE)
    # headline stays a gate of last resort: only a drop too big for
    # relay weather (>15%) fails the round on this metric
    failed |= check_regression("through-relay", img_s, prior_headline,
                               RELAY_TOLERANCE)
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
