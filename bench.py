"""Benchmark: ResNet-50 training throughput, single chip.

Headline metric (BASELINE.md): ResNet-50 training img/s — reference
MXNet 1.2 on V100 fp32: 298.51 img/s @ bs=32, 363.69 img/s @ bs=128
(docs/faq/perf.md:225-236).  vs_baseline compares at the SAME batch
size (128 default) against the bs=128 V100 number; pass a batch on the
CLI to measure other configs (256 is this chip's throughput peak).

The whole train step (fwd+bwd+SGD momentum+BN stat update) is one
jitted XLA computation (parallel/gluon_step.py); compute in bfloat16
with fp32 master weights (MXU-native mixed precision, the analog of the
reference's multi-precision SGD).  The model runs channel-last
(layout="NHWC"): measured faster than NCHW on this chip because the
layout maps directly onto MXU tiling with fewer HBM relayout bytes
(tools/bench_layout_experiment.py; BENCH_NOTES).  Pass a third CLI arg
"NCHW" to measure the reference-layout path.

Throughput is the median of 3 timed reps (each `steps` steps).  A
regression gate compares against the newest recorded BENCH_r*.json and
exits non-zero on a >10% drop, so a real regression fails the round
instead of being silently recorded.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Usage: python bench.py [batch] [steps] [NHWC|NCHW]
"""

import glob
import json
import os
import statistics
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69  # ResNet-50 training bs=128, V100 fp32 (docs/faq/perf.md)
# 0.15, not 0.10: the SAME code measured 2,455 img/s at midday and
# 2,226 in the evening (r3) — the relay's per-step overhead drifts
# ~10% by time of day, while the device-only step held 2,336-2,385
# (tools/bench_pipeline.py --mode synthetic).  A real regression still
# trips this; relay weather no longer can.
REGRESSION_TOLERANCE = 0.15


def prior_round_value():
    """Newest recorded driver bench (file, value, metric), if any round
    ran before."""
    here = os.path.dirname(os.path.abspath(__file__))
    newest = None
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
            value = rec.get("parsed", {}).get("value")
            if value:
                newest = (os.path.basename(path), float(value),
                          rec["parsed"].get("metric", ""))
        except (OSError, ValueError):
            continue
    return newest


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    layout = sys.argv[3] if len(sys.argv) > 3 else "NHWC"

    devices = jax.devices()[:1]  # single-chip benchmark
    mesh = create_mesh({"dp": 1}, devices=devices)

    net = vision.resnet50_v1(layout=layout)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    probe_shape = (1, 3, 32, 32) if layout == "NCHW" else (1, 32, 32, 3)
    with ctx:
        net.initialize(ctx=ctx)
        net(mx.nd.zeros(probe_shape, ctx=ctx))  # resolve deferred shapes
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=0.1, momentum=0.9,
                          wd=1e-4, compute_dtype="bfloat16")

    rng = np.random.RandomState(0)
    data_shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    x = rng.rand(*data_shape).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.int32)
    x, y = step.put_batch(x, y)  # device-resident synthetic batch

    # warmup (compile + 2 steps); the loss host fetch is the completion
    # barrier, matching what a real training loop's metric sync does
    for _ in range(3):
        l = step(x, y)
    float(np.asarray(l))

    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            l = step(x, y)
        float(np.asarray(l))
        rates.append(steps * batch / (time.perf_counter() - t0))
    img_s = statistics.median(rates)

    print(json.dumps({
        "metric": "resnet50_v1 training img/s (bs=%d, bf16 compute, %s, "
                  "1 chip, median of 3)" % (batch, layout),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))

    prior = prior_round_value()
    # only gate like-for-like: a `bench.py 32` exploration run must not
    # trip against the recorded bs=128 headline
    comparable = prior is not None and ("(bs=%d," % batch) in prior[2]
    if comparable and img_s < (1.0 - REGRESSION_TOLERANCE) * prior[1]:
        print("REGRESSION: %.1f img/s is >%d%% below %s (%.1f img/s)"
              % (img_s, int(REGRESSION_TOLERANCE * 100), prior[0], prior[1]),
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
