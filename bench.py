"""Benchmark: ResNet-50 training throughput, single chip.

Headline metric (BASELINE.md): ResNet-50 training img/s — reference
MXNet 1.2 on V100 fp32: 298.51 img/s @ bs=32, 363.69 img/s @ bs=128
(docs/faq/perf.md:225-236).  vs_baseline compares at the SAME batch
size (128 default) against the bs=128 V100 number; pass a batch on the
CLI to measure other configs (256 is this chip's throughput peak).

The whole train step (fwd+bwd+SGD momentum+BN stat update) is one
jitted XLA computation (parallel/gluon_step.py); compute in bfloat16
with fp32 master weights (MXU-native mixed precision, the analog of the
reference's multi-precision SGD).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69  # ResNet-50 training bs=128, V100 fp32 (docs/faq/perf.md)


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 20

    devices = jax.devices()[:1]  # single-chip benchmark
    mesh = create_mesh({"dp": 1}, devices=devices)

    net = vision.resnet50_v1()
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    with ctx:
        net.initialize(ctx=ctx)
        net(mx.nd.zeros((1, 3, 32, 32), ctx=ctx))  # resolve deferred shapes
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=0.1, momentum=0.9,
                          wd=1e-4, compute_dtype="bfloat16")

    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, 224, 224).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.int32)
    x, y = step.put_batch(x, y)  # device-resident synthetic batch

    # warmup (compile + 2 steps); the loss host fetch is the completion
    # barrier, matching what a real training loop's metric sync does
    for _ in range(3):
        l = step(x, y)
    float(np.asarray(l))

    t0 = time.perf_counter()
    for _ in range(steps):
        l = step(x, y)
    float(np.asarray(l))
    dt = time.perf_counter() - t0

    img_s = steps * batch / dt
    print(json.dumps({
        "metric": "resnet50_v1 training img/s (bs=%d, bf16 compute, 1 chip)"
                  % batch,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
