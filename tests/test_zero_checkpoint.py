"""PR 14: sharded (ZeRO) checkpointing — ``save_zero`` /
``restore_zero`` / ``auto_resume(zero_step=)``.

Pins the durability contract:

- same-layout resume is BIT-EXACT: a fresh process/step restored from
  the sharded checkpoint continues with bit-identical losses (device
  shards + the host optimizer hyper-state both ride the checkpoint —
  Adam's update count drives bias correction);
- a SIGKILL mid-save (before the rank-0 manifest rename) leaves only a
  staging dir: the next manager prunes it, ``latest()`` still returns
  the previous valid checkpoint, and resume from it is bit-exact;
- layout-change resume: a run saved at dp=8 restores onto dp=4 (shards
  rebuilt, re-padded, re-placed) and continues numerically equivalent
  (allclose — the dp reduction tree differs, so not bit-exact);
- corruption in any shard file is caught by the manifest hashes:
  ``latest()`` quarantines the checkpoint like any other corrupt one;
- ``auto_resume(zero_step=)`` over a NON-sharded newest checkpoint
  warns and restores nothing rather than mixing formats.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, optimizer as opt_mod, runtime_stats
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.gluon_step import GluonStep
from mxnet_tpu.parallel.mesh import create_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    runtime_stats.reset()
    checkpoint.disable()
    yield
    checkpoint.disable()
    runtime_stats.reset()


def _mlp(prefix, seed=7, feat=12, classes=4):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(classes))
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((2, feat), ctx=mx.cpu()))
    return net


def _zstep(prefix, n=8, seed=7):
    import jax

    mesh = create_mesh({"dp": n}, devices=jax.devices()[:n])
    return GluonStep(_mlp(prefix, seed=seed),
                     gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh,
                     zero=True, optimizer=opt_mod.create(
                         "adam", learning_rate=0.01))


def _data(n=8, batch=8, feat=12, classes=4, seed=3):
    rs = np.random.RandomState(seed)
    return ([rs.rand(batch, feat).astype(np.float32) for _ in range(n)],
            [rs.randint(0, classes, (batch,)).astype(np.int32)
             for _ in range(n)])


def _run(step, xs, ys):
    return [float(np.asarray(step(x, y))) for x, y in zip(xs, ys)]


# ------------------------------------------------------------- resume


def test_same_layout_resume_bit_exact(tmp_path):
    """save_zero at step 4, restore into a FRESH step (same prefix →
    same param names): the three continued losses match the
    uninterrupted run bit for bit — proof the host optimizer
    hyper-state (Adam's t) rides the checkpoint with the shards."""
    xs, ys = _data()
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep=5,
                                       async_write=False)
    zs = _zstep("zck_")
    _run(zs, xs[:4], ys[:4])
    path = zs.save_zero(4, mgr=mgr)
    assert os.path.isdir(path)
    assert mgr.verify(path)
    baseline = _run(zs, xs[4:7], ys[4:7])

    zs2 = _zstep("zck_", seed=99)   # different init — restore must win
    step = zs2.restore_zero(mgr.latest(), mgr=mgr)
    assert step == 4
    assert _run(zs2, xs[4:7], ys[4:7]) == baseline


def test_layout_change_resume_allclose(tmp_path):
    """A checkpoint saved at dp=8 restores onto a dp=4 mesh: shards are
    rebuilt into full vectors, re-padded and re-placed.  The continued
    trajectory is numerically equivalent (the dp-8 and dp-4 grad
    reduction trees round differently, so allclose, not equality)."""
    xs, ys = _data()
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep=5,
                                       async_write=False)
    zs = _zstep("zlay_", n=8)
    _run(zs, xs[:4], ys[:4])
    zs.save_zero(4, mgr=mgr)
    baseline = _run(zs, xs[4:7], ys[4:7])

    zs4 = _zstep("zlay_", n=4, seed=99)
    assert zs4.restore_zero(mgr.latest(), mgr=mgr) == 4
    cont = _run(zs4, xs[4:7], ys[4:7])
    assert np.allclose(cont, baseline, rtol=1e-5)


def test_sigkill_mid_save_falls_back_bit_exact(tmp_path):
    """Child process: commits a valid sharded checkpoint at step 2,
    then dies by SIGKILL inside the NEXT save_zero before the manifest
    rename (``_fsync_dir`` on the staging dir is the last call before
    commit).  A second process over the same directory prunes the
    staging leftovers, auto-resumes from step 2 and reproduces the
    uninterrupted continuation bit for bit."""
    code = """
import json, os, signal, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, optimizer as opt_mod
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.gluon_step import GluonStep
from mxnet_tpu.parallel.mesh import create_mesh

mode, ckdir = sys.argv[1], sys.argv[2]

mx.random.seed(7); np.random.seed(7)
net = nn.HybridSequential(prefix="zkill_")
with net.name_scope():
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
net.initialize(ctx=mx.cpu())
net(mx.nd.zeros((2, 12), ctx=mx.cpu()))
zs = GluonStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
               mesh=create_mesh({"dp": 8}), zero=True,
               optimizer=opt_mod.create("adam", learning_rate=0.01))
rs = np.random.RandomState(3)
xs = [rs.rand(8, 12).astype(np.float32) for _ in range(7)]
ys = [rs.randint(0, 4, (8,)).astype(np.int32) for _ in range(7)]
checkpoint.enable(ckdir, interval=0, async_write=False)
mgr = checkpoint.manager()

if mode == "crash":
    for x, y in zip(xs[:2], ys[:2]):
        zs(x, y)
    zs.save_zero(2, mgr=mgr)
    for x, y in zip(xs[2:4], ys[2:4]):
        zs(x, y)
    real = checkpoint._fsync_dir
    def boom(path):
        if path.endswith(".tmp-shared"):
            os.kill(os.getpid(), signal.SIGKILL)
        real(path)
    checkpoint._fsync_dir = boom
    zs.save_zero(4, mgr=mgr)        # never returns
    print("UNREACHABLE")
elif mode == "baseline":
    for x, y in zip(xs[:2], ys[:2]):
        zs(x, y)
    out = [float(np.asarray(zs(x, y))) for x, y in zip(xs[2:5], ys[2:5])]
    json.dump(out, sys.stdout)
else:  # resume
    zs(xs[6], ys[6])                # diverge before restore
    step = checkpoint.auto_resume(zero_step=zs)
    assert step == 2, step
    out = [float(np.asarray(zs(x, y))) for x, y in zip(xs[2:5], ys[2:5])]
    json.dump(out, sys.stdout)
"""
    import json

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO

    def child(mode):
        return subprocess.run(
            [sys.executable, "-c", code, mode, str(tmp_path)],
            env=env, capture_output=True, text=True, timeout=300)

    r = child("crash")
    assert r.returncode == -9, (r.returncode, r.stderr[-2000:])
    assert "UNREACHABLE" not in r.stdout
    leftovers = [d for d in os.listdir(tmp_path) if ".tmp-" in d]
    assert leftovers, "SIGKILL should leave the staging dir behind"

    rb = child("baseline")
    assert rb.returncode == 0, rb.stderr[-2000:]
    rr = child("resume")
    assert rr.returncode == 0, rr.stderr[-2000:]
    assert json.loads(rr.stdout) == json.loads(rb.stdout)
    # the resume child's manager init pruned the dead staging dir
    assert not [d for d in os.listdir(tmp_path) if ".tmp-" in d]


# ------------------------------------------------- corruption & guards


def test_shard_corruption_quarantined(tmp_path):
    """Shard files are hashed into the manifest: flipping bytes in one
    makes latest() quarantine the whole checkpoint."""
    xs, ys = _data(n=2)
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep=5,
                                       async_write=False)
    zs = _zstep("zcor_")
    _run(zs, xs, ys)
    path = zs.save_zero(2, mgr=mgr)
    shard = os.path.join(path, "zero-shard-00003-of-00008.pkl")
    with open(shard, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff")
    mgr2 = checkpoint.CheckpointManager(str(tmp_path), keep=5,
                                        async_write=False)
    assert mgr2.latest() is None
    assert mgr2.totals["corrupt_skipped"] >= 1


def test_auto_resume_plain_checkpoint_warns_none(tmp_path):
    """auto_resume(zero_step=) over a newest checkpoint in the
    replicated format restores nothing (no silent format mixing)."""
    net = _mlp("zpl_")
    mgr = checkpoint.enable(str(tmp_path), interval=0, async_write=False)
    mgr.save(3, {p.name: p.data() for p in net.collect_params().values()})
    mgr.wait()
    zs = _zstep("zpl2_")
    assert checkpoint.auto_resume(zero_step=zs) is None


def test_restore_zero_guards(tmp_path):
    """Wrong-format manifests and optimizer-family changes raise."""
    from mxnet_tpu.base import MXNetError

    xs, ys = _data(n=2)
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep=5,
                                       async_write=False)
    zs = _zstep("zgd_")
    _run(zs, xs, ys)
    zs.save_zero(2, mgr=mgr)
    manifest = mgr.latest()

    import jax

    mesh = create_mesh({"dp": 8}, devices=jax.devices()[:8])
    zsgd = GluonStep(_mlp("zgd2_"), gluon.loss.SoftmaxCrossEntropyLoss(),
                     mesh=mesh, zero=True,
                     optimizer=opt_mod.create("sgd", learning_rate=0.1,
                                              momentum=0.9))
    with pytest.raises(MXNetError, match="state structure changed"):
        zsgd.restore_zero(manifest, mgr=mgr)
