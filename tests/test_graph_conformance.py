"""Shape/dtype-inference conformance: the symbolic layer vs XLA.

For every registered table op with a canonical input spec
(tools/mxlint/registry_audit.canonical_spec), build a one-node Symbol
over explicit variables and cross-check:

* ``Symbol.infer_shape`` output shapes == direct ``jax.eval_shape`` on
  the op's bound fn over the spec avals (PRNG key prepended for random
  ops, exactly as the executor does);
* ``Symbol.infer_type`` output dtypes == the dtypes the same trace
  actually produces;
* ``verify_graph`` abstract interpretation agrees (clean, all nodes
  traced) when seeded with the spec shapes AND dtypes.

Known divergences are pragma'd in :data:`DTYPE_GAPS` with a reason and
enforced stale: when an op stops diverging, the test fails until its
pragma is removed.  This keeps the three shape/dtype oracles in this
repo — infer_shape/infer_type, the graph verifier, and XLA itself —
provably in sync as ops are added.
"""

import numpy as np
import pytest

import mxnet_tpu  # noqa: F401 - populates the op registry
from mxnet_tpu.ops import registry as R
from mxnet_tpu.symbol.symbol import Symbol, _Node
from mxnet_tpu.symbol.verify import verify_graph
from tools.mxlint.registry_audit import canonical_spec

# ops whose infer_type output dtypes are KNOWN not to match the traced
# dtypes, with the reason.  infer_type models the classic f32 training
# graph (int8 only for the "_quantize"-suffixed offline params); the
# int8 quantization ops produce integer activations that the coarse
# name-contract model does not represent.  Stale-pragma enforced below.
DTYPE_GAPS = {
    "_contrib_quantize": "produces uint8 activations; infer_type "
                         "models f32 graphs + int8 offline params only",
    "_contrib_quantize_v2": "produces int8 activations",
    "_contrib_requantize": "int32 accumulators -> int8 activations",
    "_contrib_quantized_conv": "int8 operands -> int32 accumulator out",
    "_contrib_quantized_fully_connected": "int8 operands -> int32 "
                                          "accumulator out",
    "_contrib_quantized_pooling": "uint8 in, uint8 out",
    "_contrib_quantized_flatten": "uint8 in, uint8 out",
}

# shape-side gaps: none today — every canonical-spec op's infer_shape
# matches XLA.  Keep the dict (and its stale enforcement) so the first
# future divergence must be declared, not silently skipped.
SHAPE_GAPS = {}


def _spec_ops():
    return [name for name in sorted(R.OP_INPUT_NAMES)
            if name in R._OP_REGISTRY and canonical_spec(name) is not None]


def _one_node_symbol(name):
    """One-node Symbol over fresh variables matching the spec slots.

    Returns (symbol, {var name: shape}, {var name: dtype}, expected
    output avals from a direct jax.eval_shape of the bound op fn).
    """
    import jax

    from mxnet_tpu.ndarray.ndarray import RANDOM_OPS

    input_specs, attrs = canonical_spec(name)
    op = R.get(name)
    canon = op.canonicalize_attrs(attrs)
    fn = op.bind_attrs(canon)
    avals = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
             for s, d in input_specs]
    full = avals
    if name in RANDOM_OPS:
        k = jax.random.PRNGKey(0)
        full = [jax.ShapeDtypeStruct(tuple(k.shape), k.dtype)] + avals
    out = jax.eval_shape(fn, *full)
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    try:
        nout = op.nout(canon)
    except Exception:
        nout = len(outs)
    slots = R.OP_INPUT_NAMES[name]
    variables = [_Node(None, "cf_%s_%s" % (name, slots[i]), {}, [], 1)
                 for i in range(len(input_specs))]
    node = _Node(name, "cf_%s" % name, canon,
                 [(v, 0) for v in variables], nout)
    sym = Symbol([(node, i) for i in range(nout)])
    shapes = {v.name: tuple(sp[0])
              for v, sp in zip(variables, input_specs)}
    dtypes = {v.name: np.dtype(sp[1])
              for v, sp in zip(variables, input_specs)}
    return sym, shapes, dtypes, outs


@pytest.mark.parametrize("name", _spec_ops())
def test_infer_shape_matches_eval_shape(name):
    sym, shapes, _dtypes, outs = _one_node_symbol(name)
    expected = [tuple(o.shape) for o in outs]
    arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**shapes)
    assert all(s is not None for s in arg_shapes + aux_shapes), \
        (arg_shapes, aux_shapes)
    matches = out_shapes == expected
    if name in SHAPE_GAPS:
        assert not matches, (
            "%s now infers shapes exactly — remove its stale SHAPE_GAPS "
            "pragma (%r)" % (name, SHAPE_GAPS[name]))
        return
    assert matches, "infer_shape %s != eval_shape %s" % (out_shapes,
                                                         expected)


@pytest.mark.parametrize("name", _spec_ops())
def test_infer_type_matches_traced_dtypes(name):
    sym, _shapes, _dtypes, outs = _one_node_symbol(name)
    expected = [np.dtype(o.dtype) for o in outs]
    _arg_t, out_t, _aux_t = sym.infer_type()
    matches = [np.dtype(t) for t in out_t] == expected
    if name in DTYPE_GAPS:
        assert not matches, (
            "%s now infers output dtypes exactly — remove its stale "
            "DTYPE_GAPS pragma (%r)" % (name, DTYPE_GAPS[name]))
        return
    assert matches, \
        "infer_type %s != traced %s" % ([str(t) for t in out_t],
                                        [str(t) for t in expected])


@pytest.mark.parametrize("name", _spec_ops())
def test_verifier_agrees_on_canonical_spec(name):
    """The graph verifier's abstract interpretation (which seeds dtypes,
    unlike infer_shape's all-f32 model) must trace every canonical-spec
    op cleanly — including the quantize family the dtype model can't."""
    sym, shapes, dtypes, _outs = _one_node_symbol(name)
    r = verify_graph(sym, input_shapes=shapes, input_dtypes=dtypes)
    assert r.ok, [f.format() for f in r.findings]
    assert r.evaluated == 1 and r.skipped == [], (r.evaluated, r.skipped)


def test_every_gap_names_a_spec_op():
    """Pragmas must point at live canonical-spec ops — a renamed or
    deleted op must not leave a dangling gap entry behind."""
    ops = set(_spec_ops())
    for gap in (DTYPE_GAPS, SHAPE_GAPS):
        stale = sorted(set(gap) - ops)
        assert not stale, "gap pragmas for unknown ops: %s" % stale
