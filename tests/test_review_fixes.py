"""Regression tests for behavior-parity fixes found in code review:
negative mining, PS-ROIAlign, arange_like repeat, eager control flow
semantics, and staged custom ops."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import apply_op


def test_arange_like_repeat():
    import jax.numpy as jnp

    out = apply_op("arange_like", jnp.zeros((6,)), repeat=2)
    np.testing.assert_allclose(np.asarray(out), [0, 0, 1, 1, 2, 2])


def test_while_loop_body_not_run_when_cond_false():
    calls = {"n": 0}

    def func(x):
        calls["n"] += 1
        return x + 1, [x + 1]

    out, final = mx.nd.contrib.while_loop(
        cond=lambda x: x < 0, func=func, loop_vars=[mx.nd.array([5.0])],
        max_iterations=4)
    assert calls["n"] == 0
    assert out == []                       # reference: outputs empty
    np.testing.assert_allclose(final[0].asnumpy(), [5.0])


def test_while_loop_eager_runs_correct_count():
    calls = {"n": 0}

    def func(x):
        calls["n"] += 1
        return x * 2, [x + 1]

    out, final = mx.nd.contrib.while_loop(
        cond=lambda x: x < 3, func=func, loop_vars=[mx.nd.array([0.0])],
        max_iterations=10)
    assert calls["n"] == 3
    np.testing.assert_allclose(final[0].asnumpy(), [3.0])
    np.testing.assert_allclose(out[0].asnumpy()[:3, 0], [0.0, 2.0, 4.0])
    np.testing.assert_allclose(out[0].asnumpy()[3:, 0], np.zeros(7))


def test_cond_runs_single_branch_eagerly():
    fired = []

    def then_f():
        fired.append("then")
        return mx.nd.array([1.0])

    def else_f():
        fired.append("else")
        return mx.nd.array([2.0])

    res = mx.nd.contrib.cond(mx.nd.array([0.0]), then_f, else_f)
    assert fired == ["else"]
    np.testing.assert_allclose(res.asnumpy(), [2.0])


def test_multibox_target_negative_mining():
    import jax.numpy as jnp

    n = 8
    # anchors tiled on a line; one gt matching anchor 0 exactly
    anchors = jnp.stack([jnp.arange(n) * 0.1, jnp.zeros(n),
                         jnp.arange(n) * 0.1 + 0.1, jnp.ones(n) * 0.1],
                        axis=-1)[None]                   # (1, N, 4)
    label = jnp.array([[[0.0, 0.0, 0.0, 0.1, 0.1],
                        [-1, -1, -1, -1, -1]]])          # (1, 2, 5)
    # cls_pred: (1, C+1, N); anchor 1 has the lowest background score →
    # hardest negative
    cp = np.zeros((1, 2, n), np.float32)
    cp[0, 0, :] = 5.0          # background logit high everywhere...
    cp[0, 0, 1] = -5.0         # ...except anchor 1
    cp[0, 1, 1] = 5.0
    loc_t, loc_m, cls_t = apply_op(
        "MultiBoxTarget", anchors, label, jnp.asarray(cp),
        overlap_threshold=0.5, negative_mining_ratio=1.0,
        negative_mining_thresh=0.5, ignore_label=-1.0)
    cls_t = np.asarray(cls_t)[0]
    assert cls_t[0] == 1.0                 # positive (class 0 → target 1)
    assert cls_t[1] == 0.0                 # mined hard negative
    # exactly num_pos * ratio = 1 negative kept; everything else ignored
    assert (cls_t == -1.0).sum() == n - 2


def test_roi_align_position_sensitive():
    import jax.numpy as jnp

    ph = pw = 2
    c_out = 3
    c = c_out * ph * pw
    # each channel constant = its own index → output bin (k,i,j) must
    # read channel k*ph*pw + i*pw + j
    data = jnp.broadcast_to(
        jnp.arange(c, dtype=jnp.float32)[None, :, None, None], (1, c, 8, 8))
    rois = jnp.array([[0.0, 0.0, 0.0, 7.0, 7.0]])
    out = apply_op("ROIAlign", data, rois, pooled_size=(ph, pw),
                   spatial_scale=1.0, sample_ratio=2,
                   position_sensitive=True)
    assert out.shape == (1, c_out, ph, pw)
    want = np.arange(c, dtype=np.float32).reshape(c_out, ph, pw)
    np.testing.assert_allclose(np.asarray(out)[0], want, atol=1e-5)


def test_custom_op_in_hybridized_block():
    import mxnet_tpu.operator as op_mod

    @op_mod.register("plus_three")
    class PlusThreeProp(op_mod.CustomOpProp):
        def create_operator(self, ctx, in_shapes, in_dtypes):
            class PlusThree(op_mod.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0],
                                mx.nd.array(in_data[0].asnumpy() + 3.0))

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])

            return PlusThree()

    # eager
    y = mx.nd.Custom(mx.nd.array([1.0, 2.0]), op_type="plus_three")
    np.testing.assert_allclose(y.asnumpy(), [4.0, 5.0])

    # symbolic path (mx.sym.Custom exists and executes)
    x = mx.sym.Variable("x")
    s = mx.sym.Custom(x, op_type="plus_three")
    ex = s.bind(mx.cpu(), {"x": mx.nd.array([1.0, 2.0])})
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [4.0, 5.0])


def test_randn_rejects_float_positional_args():
    """ADVICE r4 #2: a legacy alias-of-normal caller randn(0.0, 1.0)
    must fail loudly, not sample a (0.0, 1.0)-shaped array."""
    import pytest

    with pytest.raises(TypeError, match="must be ints"):
        mx.nd.random.randn(0.0, 1.0)
    # int dims still work, as does the kwarg spelling
    assert mx.nd.random.randn(2, 3).shape == (2, 3)
    assert mx.nd.random.randn(shape=(2, 3), loc=1.0).shape == (2, 3)


def test_executor_wraps_device_runtime_errors():
    """ADVICE r4 #1: device-side failures (XlaRuntimeError subclasses
    RuntimeError) must surface as MXNetError from executor forward, not
    as raw jax exceptions."""
    import pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.executor import Executor

    class Boom(RuntimeError):
        pass

    x = mx.sym.Variable("x")
    y = x + 1.0
    exe = y.bind(mx.cpu(), {"x": mx.nd.zeros((2,))})

    def boom_fwd(*a, **kw):
        raise Boom("device exploded")

    exe._get_fns = lambda is_train: (boom_fwd, None, None)
    with pytest.raises(MXNetError, match="executor forward: device exploded"):
        exe.forward(is_train=True)


def test_nd_array_device_source_is_independent_snapshot():
    """nd.array() on device-backed sources (NDArray / raw jax.Array)
    stays on device (no host roundtrip) but still snapshots: the
    result must not alias the source buffer, or a donated jit step
    (parallel/gluon_step.py) could delete it out from under the
    snapshot."""
    import jax.numpy as jnp

    def buf(x):
        # object identity is not enough: device_put returns a distinct
        # jax.Array that can share the underlying buffer
        return x.unsafe_buffer_pointer()

    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    snap = mx.nd.array(a)
    assert buf(snap._data) != buf(a._data)
    a[:] = 7.0
    np.testing.assert_allclose(snap.asnumpy(), [[1, 2], [3, 4]])

    raw = jnp.arange(4.0)
    snap2 = mx.nd.array(raw)
    assert buf(snap2._data) != buf(raw)
    np.testing.assert_allclose(snap2.asnumpy(), [0, 1, 2, 3])


def test_nd_array_device_source_keeps_dtype():
    """Typed device sources keep their dtype (int stays int, f64
    narrows to f32) — same contract as numpy sources."""
    import jax.numpy as jnp

    assert mx.nd.array(jnp.arange(3)).dtype == np.int32
    assert mx.nd.array(jnp.ones((2,), jnp.bfloat16)).dtype.name == "bfloat16"
    assert mx.nd.array(jnp.arange(3), dtype="float32").dtype == np.float32
    src = mx.nd.array(np.arange(3, dtype=np.int64))
    assert mx.nd.array(src).dtype == src.dtype
