"""Gluon data tests (modeled on reference tests/python/unittest/
test_gluon_data.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                                  RandomSampler, SequentialSampler,
                                  SimpleDataset)
from mxnet_tpu.gluon.data.vision import SyntheticImageDataset, transforms


def test_array_dataset():
    x = np.random.rand(10, 3).astype("float32")
    y = np.arange(10).astype("int32")
    ds = ArrayDataset(x, y)
    assert len(ds) == 10
    xi, yi = ds[3]
    assert (xi == x[3]).all()
    assert yi == 3


def test_simple_dataset_transform():
    ds = SimpleDataset(list(range(10))).transform(lambda a: a * 2)
    assert ds[4] == 8
    ds2 = SimpleDataset([(1, 2), (3, 4)]).transform_first(lambda a: a * 10)
    assert ds2[1] == (30, 4)


def test_samplers():
    assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
    assert sorted(RandomSampler(5)) == [0, 1, 2, 3, 4]
    bs = BatchSampler(SequentialSampler(10), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 3, 1]
    bs = BatchSampler(SequentialSampler(10), 3, "discard")
    assert [len(b) for b in bs] == [3, 3, 3]


def test_dataloader_basic():
    x = np.random.rand(20, 4).astype("float32")
    y = np.arange(20).astype("int32")
    loader = DataLoader(ArrayDataset(x, y), batch_size=5)
    batches = list(loader)
    assert len(batches) == 4
    bx, by = batches[0]
    assert bx.shape == (5, 4)
    assert by.shape == (5,)


def test_dataloader_shuffle_and_workers():
    x = np.arange(30).astype("float32")
    loader = DataLoader(ArrayDataset(x), batch_size=10, shuffle=True,
                        num_workers=2)
    seen = np.sort(np.concatenate([b.asnumpy() for b in loader]))
    assert (seen == np.arange(30)).all()


def test_synthetic_image_dataset_pipeline():
    ds = SyntheticImageDataset(length=32, shape=(8, 8, 3))
    tf = transforms.Compose([transforms.ToTensor(),
                             transforms.Normalize(0.5, 0.5)])
    loader = DataLoader(ds.transform_first(tf), batch_size=8)
    for bx, by in loader:
        assert bx.shape == (8, 3, 8, 8)
        assert by.shape == (8,)
        break


def test_transforms():
    img = mx.nd.array(np.random.randint(0, 255, (10, 12, 3)), dtype="uint8")
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 10, 12)
    assert t.dtype == np.float32
    r = transforms.Resize((6, 5))(img)   # (w, h)
    assert r.shape == (5, 6, 3)
    c = transforms.CenterCrop((6, 4))(img)
    assert c.shape == (4, 6, 3)
    f = transforms.RandomFlipLeftRight()(img)
    assert f.shape == img.shape
    # hue=0 angle must be near-identity (the published YIQ constants
    # invert only to ~0.3% of the 0-255 scale); nonzero preserves shape
    h0 = transforms.RandomHue(0.0)(img.astype("float32"))
    np.testing.assert_allclose(h0.asnumpy(), img.asnumpy().astype(np.float32),
                               atol=1.5)
    h = transforms.RandomHue(0.5)(img.astype("float32"))
    assert h.shape == img.shape
    j = transforms.RandomColorJitter(brightness=0.1, contrast=0.1,
                                     saturation=0.1, hue=0.1)(
        img.astype("float32"))
    assert j.shape == img.shape


def test_last_batch_rollover():
    x = np.arange(10).astype("float32")
    loader = DataLoader(ArrayDataset(x), batch_size=3, last_batch="rollover")
    n1 = sum(1 for _ in loader)
    n2 = sum(1 for _ in loader)
    assert n1 == 3
    assert n2 == 3
