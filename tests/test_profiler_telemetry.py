"""PR 2 telemetry: dispatch spans + runtime_stats counters + storm
detector + profiler satellites.

The dispatch hot path (ops/registry.py jit cache), the training-loop
layers (io / autograd / trainer / kvstore), and the Monitor host-sync
point all emit into profiler.py (spans, opt-in) and runtime_stats.py
(counters, always on).  These tests pin:

- exact hit/miss accounting for repeated vs attr-varied op calls,
- the recompile-storm warning (fires once, rate-limited, names the
  churned attr),
- zero event allocation with the profiler off (counters still live),
- chrome-trace JSON round-trip through ``json.load``,
- pause/resume/dump forwarding to the PS server command channel,
- the full ~20-step Gluon training-loop trace anatomy with
  ``runtime_stats.snapshot()`` compile counts matching the trace.

Op calls use test-unique attr values: the per-op jit cache is
process-global, so distinctive floats guarantee first-call misses.
"""

import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler, runtime_stats
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    saved_config = dict(profiler._state["config"])
    profiler.set_state("stop")
    profiler._state["events"] = []
    runtime_stats.reset()
    yield
    profiler.set_state("stop")
    profiler._state["events"] = []
    profiler._state["config"] = saved_config
    runtime_stats.reset()


class _CaptureHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


# -------------------------------------------------- dispatch telemetry


def test_dispatch_spans_and_counters_exact_hit_miss():
    x = mx.nd.ones((3, 4))
    runtime_stats.reset()
    profiler._state["events"] = []
    profiler.set_state("run")
    for _ in range(3):
        mx.nd.clip(x, -3.625, 11.125)   # 1 miss + 2 hits
    mx.nd.clip(x, -3.625, 12.375)       # attr varied -> second miss
    profiler.set_state("stop")

    st = runtime_stats.snapshot()["ops"]["clip"]
    assert st["calls"] == 4
    assert st["misses"] == 2
    assert st["hits"] == 2
    assert st["compile_seconds"] > 0.0

    evs = [e for e in profiler._state["events"]
           if e["name"] == "dispatch:clip"]
    assert len(evs) == 4
    caches = [e["args"]["cache"] for e in evs]
    assert caches.count("miss") == 2
    assert caches.count("hit") == 2
    for e in evs:
        assert e["ph"] == "X" and e["dur"] >= 0
        assert e["args"]["op"] == "clip"
        # miss spans carry the compile wall-time, hit spans must not
        assert ("compile_ms" in e["args"]) == (e["args"]["cache"] == "miss")


def test_disabled_profiler_emits_zero_events_counters_still_live():
    assert not profiler.is_running()
    x = mx.nd.ones((2, 2))
    runtime_stats.reset()
    profiler._state["events"] = []
    for _ in range(2):
        mx.nd.clip(x, -1.125, 5.0625)
    assert profiler._state["events"] == []
    st = runtime_stats.snapshot()["ops"]["clip"]
    assert st["calls"] == 2
    assert st["misses"] == 1 and st["hits"] == 1


def test_autograd_dispatch_counts_as_uncached():
    x = mx.nd.ones((2, 3))
    x.attach_grad()
    runtime_stats.reset()
    with autograd.record():
        y = x * 2.0
    y.backward()
    snap = runtime_stats.snapshot()
    assert snap["totals"]["uncached_calls"] >= 1


def test_runtime_stats_report_is_a_table():
    x = mx.nd.ones((2, 2))
    mx.nd.clip(x, -7.625, 9.875)
    text = runtime_stats.report()
    lines = text.splitlines()
    assert "Calls" in lines[0] and "Compile(s)" in lines[0]
    assert any(ln.startswith("clip") for ln in lines)
    assert any(ln.startswith("TOTAL") for ln in lines)


# ---------------------------------------------------- storm detector


def test_recompile_storm_fires_once_and_names_churned_attr(monkeypatch):
    monkeypatch.setattr(runtime_stats, "STORM_THRESHOLD", 3)
    runtime_stats.reset()
    handler = _CaptureHandler()
    logger = runtime_stats._logger()
    logger.addHandler(handler)
    try:
        x = mx.nd.ones((2, 2))
        for i in range(12):
            mx.nd.clip(x, -77.0, 200.0 + i * 0.125)  # a_max churns
    finally:
        logger.removeHandler(handler)
    assert len(handler.records) == 1, "storm warning must be rate-limited"
    msg = handler.records[0].getMessage()
    assert "recompile storm" in msg
    assert "'clip'" in msg
    assert "a_max" in msg, "warning must name the churned attr key"
    storms = runtime_stats.snapshot()["storms"]["clip"]
    assert storms["compiles"] == 12 and storms["warned"] == 1


def test_recompile_storm_rearms_after_interval(monkeypatch):
    monkeypatch.setattr(runtime_stats, "STORM_THRESHOLD", 2)
    monkeypatch.setattr(runtime_stats, "STORM_WARN_INTERVAL", 0.0)
    runtime_stats.reset()
    handler = _CaptureHandler()
    logger = runtime_stats._logger()
    logger.addHandler(handler)
    try:
        x = mx.nd.ones((2, 2))
        for i in range(6):
            mx.nd.clip(x, -88.0, 300.0 + i * 0.125)
    finally:
        logger.removeHandler(handler)
    # interval 0 => time-based limiter re-arms every compile past the
    # threshold (proves the limiter is rate-based, not warn-once-ever)
    assert len(handler.records) > 1


def test_aval_churn_storm_names_input_avals(monkeypatch):
    """Shape churn recompiles inside the jax.jit entry (registry-level
    hits!); tracked while profiling, and the warning must talk about
    aval signatures — not misreport the registry compile count."""
    monkeypatch.setattr(runtime_stats, "STORM_THRESHOLD", 3)
    runtime_stats.reset()
    handler = _CaptureHandler()
    logger = runtime_stats._logger()
    logger.addHandler(handler)
    profiler.set_state("run")
    try:
        for n in range(2, 9):  # 7 distinct input shapes, stable attrs
            mx.nd.clip(mx.nd.ones((n, 2)), -5.5, 6.5)
    finally:
        profiler.set_state("stop")
        logger.removeHandler(handler)
    storm_msgs = [r.getMessage() for r in handler.records
                  if "recompile storm" in r.getMessage()
                  and "'clip'" in r.getMessage()]
    assert len(storm_msgs) == 1
    assert "input avals" in storm_msgs[0]
    assert "compiled" not in storm_msgs[0], \
        "aval churn must not misreport the registry compile count"


def test_storm_detector_disabled_at_zero_threshold(monkeypatch):
    monkeypatch.setattr(runtime_stats, "STORM_THRESHOLD", 0)
    runtime_stats.reset()
    handler = _CaptureHandler()
    logger = runtime_stats._logger()
    logger.addHandler(handler)
    try:
        x = mx.nd.ones((2, 2))
        for i in range(6):
            mx.nd.clip(x, -99.0, 400.0 + i * 0.125)
    finally:
        logger.removeHandler(handler)
    assert handler.records == []


# ------------------------------------------------- profiler satellites


def test_dump_finished_stops_recording_and_returns_abspath(tmp_path):
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.set_state("run")
    x = mx.nd.ones((2, 2))
    mx.nd.clip(x, 0.0, 1.5322)
    path = profiler.dump(finished=True)
    assert os.path.isabs(path)
    assert not profiler.is_running(), "finished=True must stop recording"
    data = json.load(open(path))
    assert data["displayTimeUnit"] == "ms"
    ev = data["traceEvents"][0]
    assert {"name", "cat", "ph", "ts"} <= set(ev)


def test_dump_not_finished_keeps_recording(tmp_path):
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.set_state("run")
    mx.nd.clip(mx.nd.ones((2, 2)), 0.0, 2.6788)
    profiler.dump(finished=False)
    assert profiler.is_running()


class _FakeKV:
    def __init__(self):
        self.cmds = []

    def _send_command_to_servers(self, head, body):
        self.cmds.append((head, body))


def test_pause_resume_dump_forward_to_server_channel():
    kv = _FakeKV()
    profiler.set_kvstore_handle(kv)
    try:
        profiler.set_state("run")
        profiler.pause(profile_process="server")
        assert profiler.is_running(), \
            "server pause must not touch worker state"
        profiler.resume(profile_process="server")
        profiler.dump(finished=True, profile_process="server")
    finally:
        profiler.set_kvstore_handle(None)
        profiler.set_state("stop")
    assert [h for h, _ in kv.cmds] == ["profiler"] * 3
    reqs = [json.loads(b) for _, b in kv.cmds]
    assert [r["fn"] for r in reqs] == ["pause", "resume", "dump"]
    assert reqs[2]["kwargs"] == {"finished": True}


def test_ps_server_command_handles_pause_resume():
    from mxnet_tpu.kvstore import ps

    server = ps.PSServer.__new__(ps.PSServer)
    profiler.set_state("run")
    server._command("profiler", json.dumps({"fn": "pause", "kwargs": {}}))
    assert not profiler.is_running()
    server._command("profiler", json.dumps({"fn": "resume", "kwargs": {}}))
    assert profiler.is_running()
    profiler.set_state("stop")


# -------------------------------------------------- step anatomy (e2e)


def test_training_loop_trace_anatomy(tmp_path):
    """~20-step Gluon loop: the chrome trace shows the full step anatomy
    and snapshot() compile counts match the trace (acceptance criterion)."""
    profiler.set_config(filename=str(tmp_path / "train_trace.json"))
    profiler.set_state("run")
    runtime_stats.reset()

    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    X = rs.rand(40, 6).astype(np.float32)
    Y = rs.randint(0, 4, (40,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=2)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    steps = 0
    for batch in it:
        with autograd.record():
            out = net(batch.data[0])
            L = loss_fn(out, batch.label[0])
        L.backward()
        trainer.step(2)
        steps += 1
    assert steps == 20
    path = profiler.dump(finished=True)

    trace = json.load(open(path))["traceEvents"]
    names = {e["name"] for e in trace}
    for expected in ("io:next_batch", "autograd:record",
                     "autograd:backward", "trainer:step", "trainer:update"):
        assert expected in names, "missing %s in trace" % expected
    assert len([e for e in trace if e["name"] == "trainer:step"]) == steps
    assert len([e for e in trace if e["name"] == "io:next_batch"]) >= steps

    disp = [e for e in trace if e["name"].startswith("dispatch:")]
    assert disp, "no dispatch spans recorded"
    cache_args = {e["args"]["cache"] for e in disp}
    assert "hit" in cache_args, "steady-state dispatch must hit the cache"
    assert cache_args <= {"hit", "miss", "bypass-autograd", "bypass-rng"}

    snap = runtime_stats.snapshot()
    trace_misses = sum(1 for e in disp if e["args"]["cache"] == "miss")
    assert snap["totals"]["jit_cache_misses"] == trace_misses
    trace_hits = sum(1 for e in disp if e["args"]["cache"] == "hit")
    assert snap["totals"]["jit_cache_hits"] == trace_hits
    assert snap["counters"]["trainer_steps"] == steps
    assert snap["counters"]["io_batches"] >= steps
    # trainer:step span carries the batch size
    step_ev = next(e for e in trace if e["name"] == "trainer:step")
    assert step_ev["args"]["batch_size"] == 2


def test_monitor_routes_stats_through_runtime_stats():
    net = nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    mon = mx.monitor.Monitor(1, pattern=".*")
    mon.install(net)
    runtime_stats.reset()
    mon.tic()
    net(mx.nd.ones((2, 5)))
    res = mon.toc()
    assert res, "monitor hooks must have collected stats"
    counters = runtime_stats.snapshot()["counters"]
    assert counters["monitor_stats"] == len(res)
    assert counters["monitor_seconds"] > 0.0


# ---------------------------------------------------- env activation


def test_env_var_activation_writes_trace_at_exit(tmp_path):
    out = tmp_path / "env_trace.json"
    code = ("import mxnet_tpu as mx; "
            "x = mx.nd.ones((2, 2)); "
            "mx.nd.clip(x, 0.0, 3.125).asnumpy()")
    env = dict(os.environ, MXNET_TPU_PROFILE=str(out),
               JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                   check=True, timeout=180)
    data = json.load(open(out))
    assert any(e["name"] == "dispatch:clip" for e in data["traceEvents"])
