"""Symbol tests (mirrors reference tests/python/unittest/test_symbol.py +
test_infer_shape.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="act1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_list_arguments():
    mlp = _mlp()
    assert mlp.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias", "softmax_label"]
    assert mlp.list_outputs() == ["softmax_output"]


def test_infer_shape():
    mlp = _mlp()
    arg_shapes, out_shapes, aux_shapes = mlp.infer_shape(data=(16, 10))
    d = dict(zip(mlp.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (8, 10)
    assert d["fc1_bias"] == (8,)
    assert d["fc2_weight"] == (4, 8)
    assert d["softmax_label"] == (16,)
    assert out_shapes == [(16, 4)]


def test_infer_shape_conv():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="conv")
    net = mx.sym.BatchNorm(net, name="bn")
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["conv_weight"] == (8, 3, 3, 3)
    assert d["bn_gamma"] == (8,)
    assert out_shapes == [(2, 8, 8, 8)]
    aux_d = dict(zip(net.list_auxiliary_states(), aux_shapes))
    assert aux_d["bn_moving_mean"] == (8,)
    assert aux_d["bn_moving_var"] == (8,)


def test_json_roundtrip():
    mlp = _mlp()
    js = mlp.tojson()
    loaded = mx.sym.load_json(js)
    assert loaded.list_arguments() == mlp.list_arguments()
    assert loaded.list_outputs() == mlp.list_outputs()
    # same numeric behavior
    args = {n: mx.nd.array(np.random.rand(*s).astype(np.float32))
            for n, s in zip(mlp.list_arguments(),
                            mlp.infer_shape(data=(2, 10))[0])}
    e1 = mlp.bind(mx.cpu(), {k: v.copy() for k, v in args.items()})
    e2 = loaded.bind(mx.cpu(), {k: v.copy() for k, v in args.items()})
    assert_almost_equal(e1.forward()[0], e2.forward()[0], rtol=1e-5)


def test_group_and_getitem():
    a = mx.sym.Variable("a")
    b = a * 2
    c = a + 1
    g = mx.sym.Group([b, c])
    assert len(g.list_outputs()) == 2
    first = g[0]
    assert len(first.list_outputs()) == 1


def test_composition():
    a = mx.sym.Variable("a")
    net1 = mx.sym.FullyConnected(a, num_hidden=4, name="fc_inner")
    data2 = mx.sym.Variable("d2")
    composed = net1(a=mx.sym.FullyConnected(data2, num_hidden=6, name="fc_outer"))
    args = composed.list_arguments()
    assert "d2" in args and "fc_outer_weight" in args and "fc_inner_weight" in args


def test_internals():
    mlp = _mlp()
    internals = mlp.get_internals()
    names = internals.list_outputs()
    assert any("fc1" in n for n in names)
    fc1_out = internals["fc1_output"]
    assert fc1_out.infer_shape(data=(2, 10))[1] == [(2, 8)]


def test_variable_attrs():
    v = mx.sym.Variable("w", shape=(3, 4), lr_mult=2.0)
    assert v.attr("__shape__") == "(3, 4)"
    arg_shapes, _, _ = (v * 2).infer_shape()
    assert arg_shapes == [(3, 4)]


def test_attr_scope():
    with mx.AttrScope(ctx_group="dev1"):
        a = mx.sym.Variable("a")
    assert a.attr("ctx_group") == "dev1"


def test_simple_bind_and_forward():
    mlp = _mlp()
    ex = mlp.simple_bind(ctx=mx.cpu(), data=(4, 10))
    ex.arg_dict["data"][:] = np.random.rand(4, 10)
    ex.arg_dict["fc1_weight"][:] = np.random.rand(8, 10) * 0.1
    outs = ex.forward(is_train=False)
    assert outs[0].shape == (4, 4)
    assert_almost_equal(outs[0].asnumpy().sum(axis=1), np.ones(4), rtol=1e-4)


def test_executor_backward_matches_autograd():
    x = np.random.rand(3, 5).astype(np.float32)
    w = np.random.rand(2, 5).astype(np.float32)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=2, no_bias=True, name="fc")
    loss = mx.sym.sum(fc * fc)
    ex = loss.bind(mx.cpu(), {"data": mx.nd.array(x), "fc_weight": mx.nd.array(w)},
                   args_grad={"data": mx.nd.zeros((3, 5)),
                              "fc_weight": mx.nd.zeros((2, 5))})
    ex.forward(is_train=True)
    ex.backward()
    # autograd reference
    xa = mx.nd.array(x)
    wa = mx.nd.array(w)
    xa.attach_grad()
    wa.attach_grad()
    with mx.autograd.record():
        out = (mx.nd.FullyConnected(xa, wa, no_bias=True, num_hidden=2) ** 2).sum()
    out.backward()
    assert_almost_equal(ex.grad_dict["data"], xa.grad, rtol=1e-4)
    assert_almost_equal(ex.grad_dict["fc_weight"], wa.grad, rtol=1e-4)


def test_save_load_file(tmp_path):
    mlp = _mlp()
    fname = str(tmp_path / "sym.json")
    mlp.save(fname)
    loaded = mx.sym.load(fname)
    assert loaded.list_arguments() == mlp.list_arguments()


def test_symbol_pickle_roundtrip():
    """Symbols pickle (reference: test_symbol.py test_symbol_pickle):
    structure, names, and attrs survive, and the unpickled graph
    executes identically."""
    import pickle

    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b", lr_mult=2.0)
    out = mx.sym.FullyConnected(a + b, num_hidden=3, name="fc")
    out2 = pickle.loads(pickle.dumps(out))
    assert out2.list_arguments() == out.list_arguments()
    assert out2.list_outputs() == out.list_outputs()
    assert out2.tojson() == out.tojson()
    args = {n: mx.nd.ones(s) for n, s in
            zip(out.list_arguments(),
                out.infer_shape(a=(2, 4), b=(2, 4))[0])}
    e1 = out.bind(mx.cpu(), dict(args))
    e2 = out2.bind(mx.cpu(), dict(args))
    assert np.allclose(e1.forward()[0].asnumpy(), e2.forward()[0].asnumpy())


def test_symbol_bool_raises():
    """A Symbol has no truth value (reference: test_symbol_bool —
    NotImplementedForSymbol); `if sym:` is always a bug."""
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError

    with _pytest.raises(MXNetError):
        bool(mx.sym.Variable("x"))
    with _pytest.raises(MXNetError):
        if mx.sym.Variable("x") == mx.sym.Variable("y"):
            pass


def test_incomplete_infer_elemwise():
    """0-marked dims in Variable shapes resolve bidirectionally
    (reference: test_infer_shape.py test_incomplete_infer_elewise)."""
    a = mx.sym.Variable("a", shape=(0, 10))
    b = mx.sym.Variable("b", shape=(12, 0))
    c = a + b
    arg_shapes, _, _ = c.infer_shape()
    got = dict(zip(c.list_arguments(), arg_shapes))
    assert got["a"] == (12, 10)
    assert got["b"] == (12, 10)


def test_incomplete_infer_mlp():
    """(reference: test_incomplete_infer_mlp) — the batch dim flows
    backward through FullyConnected from a downstream add."""
    a = mx.sym.Variable("a", shape=(0, 10))
    b = mx.sym.FullyConnected(data=a, num_hidden=21)
    c = mx.sym.Variable("c", shape=(5, 0))
    d = b + c
    arg_shapes, _, _ = d.infer_shape()
    got = dict(zip(d.list_arguments(), arg_shapes))
    assert got["a"] == (5, 10)
    assert got["c"] == (5, 21)


def test_incomplete_infer_slicechannel():
    """(reference: test_incomplete_infer_slicechannel) — both squeeze
    modes, dims flowing backward through the split."""
    a = mx.sym.Variable("a", shape=(0, 10))
    b = mx.sym.SliceChannel(data=a, num_outputs=10, axis=1,
                            squeeze_axis=True)
    c = mx.sym.Variable("c", shape=(5,))
    d = b[1] + c
    arg_shapes, _, _ = d.infer_shape()
    got = dict(zip(d.list_arguments(), arg_shapes))
    assert got["a"] == (5, 10)

    a = mx.sym.Variable("a2", shape=(0, 15, 0))
    b = mx.sym.SliceChannel(data=a, num_outputs=3, squeeze_axis=False)
    c = mx.sym.Variable("c2", shape=(3, 5, 2))
    d = b[1] + c
    arg_shapes, _, _ = d.infer_shape()
    got = dict(zip(d.list_arguments(), arg_shapes))
    assert got["a2"] == (3, 15, 2)


def test_incomplete_infer_convolution():
    """(reference: test_incomplete_infer_convolution) — stride-1
    spatial dims invert through the conv."""
    a = mx.sym.Variable("a", shape=(0, 10, 0, 0))
    b = mx.sym.Convolution(data=a, num_filter=21, kernel=(3, 3),
                           dilate=(1, 1), pad=(1, 1))
    c = mx.sym.Variable("c", shape=(5, 21, 32, 32))
    d = b + c
    arg_shapes, _, _ = d.infer_shape()
    got = dict(zip(d.list_arguments(), arg_shapes))
    assert got["a"] == (5, 10, 32, 32)


def test_incomplete_infer_concat():
    """(reference: test_incomplete_infer_concat) — the concat axis
    splits backward into its inputs."""
    a = mx.sym.Variable("a", shape=(0, 10))
    b = mx.sym.Variable("b", shape=(0, 5))
    c = mx.sym.Concat(a, b, num_args=2, dim=1)
    d = mx.sym.Variable("d", shape=(2, 0))
    out = d + c
    arg_shapes, _, _ = out.infer_shape()
    got = dict(zip(out.list_arguments(), arg_shapes))
    assert got["a"] == (2, 10)
    assert got["b"] == (2, 5)
    assert got["d"] == (2, 15)


def test_incomplete_infer_edge_cases():
    """Review-r4 repros: flatten=False FullyConnected, negative-axis
    squeeze SliceChannel, and rank validation errors."""
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError

    # flatten=False: only the last axis projects
    a = mx.sym.Variable("a", shape=(0, 5, 10))
    b = mx.sym.FullyConnected(data=a, num_hidden=7, flatten=False)
    d = b + mx.sym.Variable("c", shape=(4, 5, 7))
    arg_shapes, _, _ = d.infer_shape()
    got = dict(zip(d.list_arguments(), arg_shapes))
    assert got["a"] == (4, 5, 10)

    # negative split axis with squeeze
    a2 = mx.sym.Variable("a2", shape=(0, 10, 0))
    b2 = mx.sym.SliceChannel(data=a2, num_outputs=4, axis=-1,
                             squeeze_axis=True)
    d2 = b2[0] + mx.sym.Variable("c2", shape=(5, 10))
    arg_shapes, _, _ = d2.infer_shape()
    got = dict(zip(d2.list_arguments(), arg_shapes))
    assert got["a2"] == (5, 10, 4)

    # wrong-rank conv input errors as MXNetError, not IndexError
    a3 = mx.sym.Variable("a3", shape=(0, 10, 0))
    b3 = mx.sym.Convolution(data=a3, num_filter=4, kernel=(3, 3))
    with _pytest.raises(MXNetError):
        (b3 + mx.sym.Variable("c3", shape=(2, 4, 8, 8))).infer_shape()


def test_incomplete_infer_through_conv_flatten_fc():
    """Batch flows backward through FC and Flatten while spatials flow
    forward through the conv — the full declare-what-you-know
    workflow."""
    data = mx.sym.Variable("data", shape=(0, 3, 24, 24))
    net = mx.sym.Convolution(data=data, num_filter=8, kernel=(3, 3),
                             pad=(1, 1))
    net = mx.sym.FullyConnected(mx.sym.flatten(net), num_hidden=10)
    head = net + mx.sym.Variable("bias_like", shape=(32, 0))
    args, outs, _ = head.infer_shape()
    got = dict(zip(head.list_arguments(), args))
    assert got["data"] == (32, 3, 24, 24)
    assert got["bias_like"] == (32, 10)
    assert outs == [(32, 10)]


def test_incomplete_infer_broadcast_tolerance_and_depth():
    """Review-r4 repros: a broadcast-style add (known dim 1 vs larger)
    must not make inference raise, and backward info crosses deep
    chains (120-step unrolled graphs) within the sweep budget."""
    # broadcast-style node: skipped, not raised on
    a = mx.sym.Variable("a", shape=(1, 10))
    b = mx.sym.Variable("b", shape=(12, 0))
    c = a + b
    arg_shapes, _, _ = c.infer_shape_partial()
    got = dict(zip(c.list_arguments(), arg_shapes))
    assert got["a"] == (1, 10)  # declared shapes untouched

    # deep chain: head shape flows back 120 levels
    x = mx.sym.Variable("x", shape=(0, 10))
    z = x
    for _ in range(120):
        z = mx.sym.relu(z)
    d = z + mx.sym.Variable("head", shape=(5, 10))
    arg_shapes, _, _ = d.infer_shape()
    got = dict(zip(d.list_arguments(), arg_shapes))
    assert got["x"] == (5, 10)


def test_fc_infer_type():
    """dtype propagation through FullyConnected (reference:
    test_infer_shape.py test_fc_infer_type)."""
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=1000)
    arg_types, out_types, aux_types = out.infer_type(data=np.float32)
    got = dict(zip(out.list_arguments(), arg_types))
    assert len(out_types) == 1 and out_types[0] == np.float32
    assert got["fc1_weight"] == np.float32
    assert got["fc1_bias"] == np.float32
    assert aux_types == []


def test_mlp2_infer_shape_and_error():
    """Two-layer MLP shape inference + the inconsistent-provided-shape
    error (reference: test_mlp2_infer_shape / test_mlp2_infer_error)."""
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=1000)
    net = mx.sym.Activation(net, act_type="relu")
    out = mx.sym.FullyConnected(data=net, name="fc2", num_hidden=10)

    arg_shapes, out_shapes, _ = out.infer_shape(data=(100, 100))
    got = dict(zip(out.list_arguments(), arg_shapes))
    assert out_shapes == [(100, 10)]
    assert got["fc1_weight"] == (1000, 100)
    assert got["fc1_bias"] == (1000,)
    assert got["fc2_weight"] == (10, 1000)
    assert got["fc2_bias"] == (10,)

    with _pytest.raises(MXNetError):
        out.infer_shape(data=(100, 100), fc1_weight=(1, 100))


def test_infer_shape_channel_last_conv_weight():
    """Review-r4 repro: a consistent NHWC (OHWI) weight passes the
    strict check; an inconsistent one errors directly without the
    partial-infer retry."""
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError

    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3),
                              layout="NHWC", name="conv")
    args, outs, _ = conv.infer_shape(data=(1, 32, 32, 16),
                                     conv_weight=(8, 3, 3, 16))
    assert outs == [(1, 30, 30, 8)]
    with _pytest.raises(MXNetError, match="inconsistent shape"):
        conv.infer_shape(data=(1, 32, 32, 16), conv_weight=(8, 16, 3, 3))


def test_attr_basic_scope_override_and_pickle():
    """Explicit attrs override the enclosing AttrScope; scope attrs
    apply to scope-created variables; attrs survive pickling
    (reference: test_attr.py test_attr_basic)."""
    import pickle as pkl

    with mx.AttrScope(group="4", data="great"):
        data = mx.sym.Variable("data", attr={"dtype": "data", "group": "1"},
                               lr_mult=1)
        gdata = mx.sym.Variable("data2")
    assert gdata.attr("group") == "4"
    assert data.attr("group") == "1"  # explicit wins over scope
    assert data.attr("__lr_mult__") == "1"
    data2 = pkl.loads(pkl.dumps(data))
    assert data.attr("dtype") == data2.attr("dtype")


def test_attr_nested_scopes_on_operators():
    """Nested AttrScopes compose onto op nodes; JSON survives pickle
    (reference: test_attr.py test_operator)."""
    import pickle as pkl

    data = mx.sym.Variable("data")
    with mx.AttrScope(__group__="4", __data__="great"):
        fc1 = mx.sym.Activation(data, act_type="relu")
        with mx.AttrScope(__init_bias__="0.0"):
            fc2 = mx.sym.FullyConnected(fc1, num_hidden=10, name="fc2")
    assert fc1.attr("__data__") == "great"
    assert fc2.attr("__data__") == "great"
    assert fc2.attr("__init_bias__") == "0.0"
    fc2copy = pkl.loads(pkl.dumps(fc2))
    assert fc2copy.tojson() == fc2.tojson()
    assert fc2.get_internals()["fc2_weight"] is not None


def test_attr_scope_merges_at_entry():
    """A pre-built scope inherits whatever encloses the `with`, not the
    construction site, and re-entry recomputes (reference:
    attribute.py __enter__ merge; review-r4 repro)."""
    s = mx.AttrScope(__b__="2")
    with mx.AttrScope(__a__="1"):
        with s:
            v = mx.sym.Variable("attrx")
    assert v.attr("__a__") == "1"
    assert v.attr("__b__") == "2"
    with s:  # outer scope gone: only own attrs apply
        w = mx.sym.Variable("attry")
    assert w.attr("__a__") is None
    assert w.attr("__b__") == "2"
