"""Targeted tests for the thin spots the r5 coverage run surfaced
(COVERAGE.md): modules whose only exercise was inside subprocesses or
nothing at all.  Each test asserts observable behavior, not just
imports — the point is to pin the contracts, not inflate the number.
"""

import numpy as np
import pytest

import mxnet_tpu as mx


def test_split_input_slice_workloads():
    """executor_manager._split_input_slice: proportional slicing with
    remainder on the last device; degenerate workloads rejected
    (reference: python/mxnet/executor_manager.py)."""
    from mxnet_tpu.executor_manager import _split_input_slice

    s = _split_input_slice(10, [1, 1])
    assert s == [slice(0, 5), slice(5, 10)]
    # round(2.5)=2 (banker's), shortfall lands on the LAST device —
    # the reference's exact remainder rule
    s = _split_input_slice(10, [2, 1, 1])
    assert [sl.stop - sl.start for sl in s] == [5, 2, 3]
    assert s[-1].stop == 10
    with pytest.raises(ValueError, match="Invalid workload"):
        _split_input_slice(4, [0, 0])
    with pytest.raises(ValueError, match="empty"):
        _split_input_slice(2, [1, 1, 1, 1])


def test_rtc_cuda_module_errors_pallas_module_runs():
    """rtc: CudaModule is a loud N/A on TPU; PallasModule compiles and
    launches a real Pallas kernel (interpret on CPU)."""
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError, match="PallasModule"):
        mx.rtc.CudaModule("__global__ void axpy() {}")

    import jax

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def out_shape(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    mod = mx.rtc.PallasModule(kern, out_shape)
    launcher = mod.get_kernel()
    x = mx.nd.array(np.arange(8, dtype=np.float32))
    out = launcher([x])
    np.testing.assert_allclose(out.asnumpy(), np.arange(8) * 2.0)


def test_make_train_step_data_parallel_mesh():
    """parallel.data_parallel.make_train_step: pure loss_fn + update on
    an 8-device dp mesh; loss decreases and params stay replicated."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel.data_parallel import make_train_step
    from mxnet_tpu.parallel.mesh import create_mesh

    mesh = create_mesh({"dp": 8})
    rs = np.random.RandomState(0)
    params = {"w": jnp.asarray(rs.randn(4, 3).astype(np.float32))}
    xb = jnp.asarray(rs.rand(16, 4).astype(np.float32))
    yb = jnp.asarray(rs.rand(16, 3).astype(np.float32))

    def loss_fn(p, batch):
        x, y = batch
        return ((x @ p["w"] - y) ** 2).mean()

    def update(p, g, s):
        return jax.tree_util.tree_map(lambda w, d: w - 0.1 * d, p, g), s

    step = make_train_step(loss_fn, update, mesh)
    l1, params, _ = step(params, None, (xb, yb))
    l2, params, _ = step(params, None, (xb, yb))
    assert float(l2) < float(l1)
    assert params["w"].addressable_shards[0].data.size == 12  # replicated


def test_transformer_encoder_trains_in_process():
    """gluon.nn.transformer: encoder stack forward + one backward step
    in-process (previously exercised only in the dryrun subprocess)."""
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon.nn.transformer import (MultiHeadAttention,
                                                TransformerEncoder)

    mx.random.seed(0)
    enc = TransformerEncoder(units=16, hidden_size=32, num_heads=4,
                             num_layers=2)
    enc.initialize(ctx=mx.cpu())
    x = mx.nd.array(np.random.RandomState(0).rand(2, 6, 16)
                    .astype(np.float32))
    out = enc(x)
    assert out.shape == (2, 6, 16)

    # causal masking: position t of a causal MHA must not change when
    # future positions change
    mha = MultiHeadAttention(units=16, num_heads=4, causal=True)
    mha.initialize(ctx=mx.cpu())
    a = mx.nd.array(np.random.RandomState(1).rand(1, 5, 16)
                    .astype(np.float32))
    b = a.asnumpy().copy()
    b[:, 3:] = 0.0
    outa = mha(a).asnumpy()
    outb = mha(mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(outa[:, :3], outb[:, :3], rtol=2e-5,
                               atol=2e-6)

    params = list(enc.collect_params().values())
    with ag.record():
        loss = (enc(x) ** 2).sum()
    loss.backward()
    assert any(float(np.abs(p.grad().asnumpy()).sum()) > 0 for p in params)


def test_symbol_random_builds_sampling_graph():
    """mx.sym.random: symbolic sampler nodes bind and execute."""
    s = mx.sym.random.uniform(low=0.0, high=1.0, shape=(3, 4))
    exe = s.bind(mx.cpu(), {})
    out = exe.forward(is_train=True)[0].asnumpy()
    assert out.shape == (3, 4)
    assert (out >= 0).all() and (out <= 1).all()
    n = mx.sym.random.normal(loc=2.0, scale=0.0, shape=(5,))
    val = n.bind(mx.cpu(), {}).forward()[0].asnumpy()
    np.testing.assert_allclose(val, 2.0, atol=1e-6)


def test_inception_v3_forward_and_structure():
    """model_zoo inception_v3 (17.5% covered): forward shape, param
    count vs the reference topology, aux head handling."""
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    net = vision.inception_v3(classes=7)
    net.initialize(ctx=mx.cpu())
    # inception v3 needs >= 75x75 spatial; keep it small for 1 core
    out = net(mx.nd.zeros((1, 3, 96, 96), ctx=mx.cpu()))
    assert out.shape == (1, 7)
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values())
    assert n_params > 2e7  # inception-v3 scale, not a stub


def test_vgg_and_densenet_small_variants_forward():
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    vgg = vision.vgg11(classes=5)
    vgg.initialize(ctx=mx.cpu())
    assert vgg(mx.nd.zeros((1, 3, 32, 32), ctx=mx.cpu())).shape == (1, 5)

    dn = vision.densenet121(classes=5)
    dn.initialize(ctx=mx.cpu())
    assert dn(mx.nd.zeros((1, 3, 32, 32), ctx=mx.cpu())).shape == (1, 5)


def test_conv_rnn_cell_step_and_unroll():
    """gluon.contrib Conv RNN cells in-process: single step state
    shapes and a short unroll."""
    from mxnet_tpu.gluon.contrib.rnn import Conv2DLSTMCell

    mx.random.seed(0)
    cell = Conv2DLSTMCell(input_shape=(4, 8, 8), hidden_channels=6,
                          i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize(ctx=mx.cpu())
    x = mx.nd.array(np.random.RandomState(0).rand(2, 4, 8, 8)
                    .astype(np.float32))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 6, 8, 8)
    assert len(new_states) == 2
    seq = mx.nd.array(np.random.RandomState(1).rand(2, 3, 4, 8, 8)
                      .astype(np.float32))
    outs, _ = cell.unroll(3, seq, layout="NTC", merge_outputs=False)
    assert len(outs) == 3 and outs[0].shape == (2, 6, 8, 8)


def test_tp_transformer_rules_in_process():
    """parallel.tp rules (previously dryrun-subprocess-only): column/
    row/vocab sharding by name, size-1 axes dropped, first match wins."""
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.tp import make_param_spec_fn, spec_for

    mesh = create_mesh({"dp": 4, "tp": 2})
    fn = make_param_spec_fn(mesh=mesh)
    # trailing Nones are trimmed; column-parallel = dim 0 over tp
    assert fn("enc_attn_qkv_weight", (12, 4)) == P("tp")
    assert fn("enc_attn_proj_weight", (4, 12)) == P(None, "tp")
    assert fn("enc_ffn1_weight", (32, 4)) == P("tp")
    assert fn("enc_norm_gamma", (4,)) == P()
    # a tp=1 mesh degrades every rule to replicated
    mesh1 = create_mesh({"dp": 8})
    fn1 = make_param_spec_fn(mesh=mesh1)
    assert fn1("enc_attn_qkv_weight", (12, 4)) == P()
    # meshless spec_for returns the raw rule; odd dims drop the axis
    assert spec_for("x_qkv_weight", (8, 4)) == P("tp", None)
    assert spec_for("x_qkv_weight", (9, 4), mesh=mesh) == P()


def test_kvstore_server_init_server_role_gate(monkeypatch):
    """kvstore_server.init_server: False for workers (user code
    continues); True + serves for DMLC_ROLE=server (drive a quick
    round-trip against it from this process)."""
    import threading

    from mxnet_tpu import kvstore_server
    from mxnet_tpu.kvstore.ps import PSClient

    monkeypatch.setenv("DMLC_ROLE", "worker")
    assert kvstore_server.init_server() is False

    monkeypatch.setenv("DMLC_ROLE", "server")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("MXTPU_PS_PORTS", "29517")
    t = threading.Thread(target=kvstore_server.init_server, daemon=True)
    t.start()
    # PSClient does not read DMLC_ROLE, so the env stays 'server' until
    # monkeypatch unwinds — flipping it here would race the thread's
    # own role read (r5 review finding)
    c = PSClient(connect_timeout=20)
    c.init("k", np.zeros((2,), np.float32))
    assert c.pull("k").shape == (2,)
    c.stop_servers()
    t.join(timeout=20)
    assert not t.is_alive()


def test_lstmp_and_variational_dropout_cells():
    """contrib rnn extras in-process: LSTMP projects states to
    projection_size; VariationalDropoutCell reuses ONE mask across
    time steps (the defining property)."""
    from mxnet_tpu import autograd as ag
    from mxnet_tpu.gluon.contrib.rnn import (LSTMPCell,
                                             VariationalDropoutCell)
    from mxnet_tpu.gluon.rnn import LSTMCell

    mx.random.seed(0)
    cell = LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize(ctx=mx.cpu())
    x = mx.nd.array(np.random.RandomState(0).rand(2, 4).astype(np.float32))
    out, states = cell(x, cell.begin_state(batch_size=2))
    assert out.shape == (2, 3)          # projected
    assert states[0].shape == (2, 3)    # h projected
    assert states[1].shape == (2, 8)    # c full

    base = LSTMCell(hidden_size=6, input_size=4)
    vd = VariationalDropoutCell(base, drop_inputs=0.5)
    vd.initialize(ctx=mx.cpu())
    seq = mx.nd.array(np.random.RandomState(1).rand(2, 5, 4)
                      .astype(np.float32))
    with ag.record(train_mode=True):
        outs, _ = vd.unroll(5, seq, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 6)
    assert np.isfinite(outs.asnumpy()).all()


def test_activation_blocks_forward():
    """gluon.nn activation blocks: values match their definitions."""
    from mxnet_tpu.gluon import nn

    x = mx.nd.array([-2.0, -0.5, 0.0, 1.5])
    leaky = nn.LeakyReLU(0.1)
    leaky.initialize()
    np.testing.assert_allclose(
        leaky(x).asnumpy(), np.where(x.asnumpy() > 0, x.asnumpy(),
                                     0.1 * x.asnumpy()), rtol=1e-6)
    assert "LeakyReLU" in repr(leaky)

    elu = nn.ELU(alpha=1.0)
    elu.initialize()
    xn = x.asnumpy()
    np.testing.assert_allclose(
        elu(x).asnumpy(), np.where(xn > 0, xn, np.expm1(xn)), rtol=1e-5,
        atol=1e-6)

    mx.random.seed(0)
    prelu = nn.PReLU()
    prelu.initialize()
    out = prelu(x).asnumpy()
    alpha = list(prelu.collect_params().values())[0].data().asnumpy()
    np.testing.assert_allclose(out, np.where(xn > 0, xn, alpha * xn),
                               rtol=1e-5)

    selu = nn.SELU()
    selu.initialize()
    assert np.isfinite(selu(x).asnumpy()).all()

    sw = nn.Swish()
    sw.initialize()
    np.testing.assert_allclose(
        sw(x).asnumpy(), xn / (1 + np.exp(-xn)), rtol=1e-5, atol=1e-6)


def test_explicit_mixed_initializer_still_works():
    """r5 review regression: Mixed/Load define only __call__ (no
    _init_weight); an explicit init=Mixed must keep working alongside
    the PReLU-style param-level-init routing."""
    from mxnet_tpu.gluon import nn

    net = nn.Dense(4, in_units=3)
    net.initialize(mx.init.Mixed([".*weight", ".*"],
                                 [mx.init.Constant(3.0),
                                  mx.init.Zero()]), force_reinit=True)
    w, b = [p.data().asnumpy() for p in net.collect_params().values()]
    np.testing.assert_allclose(w, 3.0)
    np.testing.assert_allclose(b, 0.0)
