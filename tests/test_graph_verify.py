"""Mutation suite for the graph verifier (symbol/verify.py).

Each test seeds one deliberately broken rewrite — the fault classes a
buggy graph pass can realistically introduce — and asserts the
verifier catches it with the EXACT offending node named.  Together
with the zero-false-positive zoo gate (test_lint_clean.py) this pins
both sides of the verifier's contract: clean graphs verify clean,
broken graphs fail with an actionable finding.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.symbol.symbol import Symbol, _Node
from mxnet_tpu.symbol.verify import assert_valid, verify_graph

sym = mx.sym


def _var(name):
    return sym.var(name)._outputs[0][0]


def _findings(s, **kw):
    return verify_graph(s, **kw).findings


def _rules_by_node(findings):
    return {(f.rule, f.node) for f in findings}


def _mlp():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=8, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


# --------------------------------------------------------- the 10 faults


def test_catches_cycle():
    """Fault 1: a rewrite wires an edge backwards, closing a cycle."""
    a = _Node("elemwise_add", "add_a", {}, [(_var("x"), 0)], 1)
    b = _Node("elemwise_add", "add_b", {}, [(a, 0)], 1)
    a.inputs.append((b, 0))  # the broken rewrite
    findings = _findings(Symbol([(b, 0)]))
    assert ("cycle", "add_a") in _rules_by_node(findings), findings


def test_catches_dangling_input_index():
    """Fault 2: an input edge references an output slot the producer
    does not have."""
    n = _Node("elemwise_add", "adder", {}, [(_var("x"), 5)], 1)
    findings = _findings(Symbol([(n, 0)]))
    assert ("dangling-input", "adder") in _rules_by_node(findings)


def test_catches_dangling_head_index():
    """Fault 3: a graph head references a nonexistent output slot."""
    n = _Node("elemwise_add", "adder", {}, [(_var("x"), 0)], 1)
    findings = _findings(Symbol([(n, 3)]))
    assert ("dangling-output", "adder") in _rules_by_node(findings)


def test_catches_unknown_op():
    """Fault 4: a node whose op the registry never registered."""
    n = _Node("NoSuchOp", "mystery", {}, [(_var("x"), 0)], 1)
    findings = _findings(Symbol([(n, 0)]))
    assert ("unknown-op", "mystery") in _rules_by_node(findings)


def test_catches_wrong_arity():
    """Fault 5: a FullyConnected node hand-built with one input, where
    the OP_INPUT_NAMES row (data, weight, bias) requires three (two
    under no_bias)."""
    n = _Node("FullyConnected", "fc_bad", {"num_hidden": 4},
              [(_var("x"), 0)], 1)
    findings = _findings(Symbol([(n, 0)]))
    assert ("arity", "fc_bad") in _rules_by_node(findings)
    msg = [f for f in findings if f.rule == "arity"][0].message
    assert "data" in msg and "weight" in msg  # names the expected slots


def test_catches_dtype_mismatched_edge():
    """Fault 6: an int32 weight wired into a Convolution whose data is
    f32 — XLA refuses mixed conv operand types; the verifier's abstract
    interpretation reports it at the conv node."""
    data = sym.var("data")
    w = sym.var("badweight", dtype=np.int32)
    b = sym.var("bias")
    conv = sym.Convolution(data=data, weight=w, bias=b, kernel=(3, 3),
                           num_filter=4, name="conv_bad")
    findings = _findings(conv, input_shapes={"data": (1, 3, 8, 8)})
    assert ("node-eval", "conv_bad") in _rules_by_node(findings)


def test_catches_shape_mismatched_edge():
    """Fault 7: elemwise_add over (2,3) and (4,5) operands — a shape
    error a rewrite can introduce by rewiring the wrong producer."""
    a = sym.var("a", shape=(2, 3))
    b = sym.var("b", shape=(4, 5))
    bad = mx.sym.elemwise_add(a, b, name="add_bad")
    findings = _findings(bad)
    assert ("node-eval", "add_bad") in _rules_by_node(findings)


def test_catches_unhashable_attr():
    """Fault 8: a Python set smuggled into attrs — it survives
    canonicalization but the jit-cache key cannot hash, silently
    demoting every call to the eager-trace fallback.  The finding names
    the exact attr."""
    n = _Node("FullyConnected", "fc_evil",
              {"num_hidden": 4, "evil": {1, 2}},
              [(_var("a"), 0), (_var("w"), 0), (_var("b"), 0)], 1)
    findings = _findings(Symbol([(n, 0)]))
    by = _rules_by_node(findings)
    assert ("unhashable-attr", "fc_evil") in by, findings
    msg = [f for f in findings if f.rule == "unhashable-attr"][0].message
    assert "'evil'" in msg


def test_catches_duplicate_names():
    """Fault 9: two distinct nodes sharing one name — argument binding
    and JSON round-trips key by name, so this corrupts both."""
    w1 = _Node(None, "w", {}, [], 1)
    w2 = _Node(None, "w", {}, [], 1)
    n = _Node("elemwise_add", "adder", {}, [(w1, 0), (w2, 0)], 1)
    findings = _findings(Symbol([(n, 0)]))
    assert any(f.rule == "duplicate-name" and f.node == "w"
               for f in findings), findings


def test_catches_num_outputs_overclaim():
    """Fault 10: a node declaring more outputs than its op produces —
    downstream consumers of the phantom slots would explode at bind."""
    n = _Node("FullyConnected", "fc_wide", {"num_hidden": 4},
              [(_var("a"), 0), (_var("w"), 0), (_var("b"), 0)], 3)
    findings = _findings(Symbol([(n, 0)]))
    assert ("num-outputs", "fc_wide") in _rules_by_node(findings)


def test_catches_variable_with_inputs():
    """Bonus fault: a variable node carrying input edges — variables
    must be leaves; a rewrite that forgets to set ``op`` produces
    this."""
    v = _Node(None, "notaleaf", {}, [(_var("x"), 0)], 1)
    findings = _findings(Symbol([(v, 0)]))
    assert ("variable-inputs", "notaleaf") in _rules_by_node(findings)


# ------------------------------------------------------- finding quality


def test_finding_prints_path_to_head():
    """The offending node's path to a graph head is printed — the
    debugging breadcrumb the acceptance criteria require."""
    x = sym.var("x")
    bad = _Node("NoSuchOp", "deep_bad", {}, [(x._outputs[0][0], 0)], 1)
    mid = _Node("Activation", "mid_act", {"act_type": "relu"},
                [(bad, 0)], 1)
    top = _Node("sum", "head_sum", {}, [(mid, 0)], 1)
    findings = _findings(Symbol([(top, 0)]))
    f = [f for f in findings if f.node == "deep_bad"][0]
    assert "deep_bad" in f.path and "mid_act" in f.path \
        and "head_sum" in f.path
    assert "deep_bad" in f.format() and "path" in f.format()


def test_assert_valid_raises_with_findings():
    bad = Symbol([(_Node("NoSuchOp", "mystery", {},
                         [(_var("x"), 0)], 1), 0)])
    with pytest.raises(MXNetError, match="mystery"):
        assert_valid(bad, context="unit-test")
    # and passes through clean graphs
    r = assert_valid(_mlp(), input_shapes={"data": (4, 32)})
    assert r.ok and r.evaluated > 0


def test_clean_graph_without_shapes_is_partial_not_failing():
    """No input shapes: structural + cache-key checks still run; nodes
    with unknown shapes are reported as skipped, never guessed into
    false positives."""
    r = verify_graph(_mlp())
    assert r.ok
    assert r.evaluated == 0 and len(r.skipped) == r.nodes


def test_loaded_json_graph_verifies_clean():
    """load_json round-trips (which do NOT canonicalize attrs) must not
    trip the attr checks — the cache-key rule checks routing and
    hashability, not canonical form."""
    s = mx.sym.load_json(_mlp().tojson())
    r = verify_graph(s, input_shapes={"data": (4, 32)})
    assert r.ok, [f.format() for f in r.findings]
