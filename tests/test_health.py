"""PR 5 numerics health layer: device-resident stat kernels, the async
HealthMonitor, and the training flight recorder.

The tentpole's contract, pinned end to end:

- the jitted per-tensor stat kernel matches numpy on crafted tensors
  (all-NaN, infs, zeros, random, integer dtypes);
- a 20-step Gluon loop with an induced mid-run NaN yields exactly ONE
  rate-limited warning naming the earliest offending tensor plus an
  atomic flight-recorder dump readable by the ``runtime_stats`` CLI;
- observations queue tiny DEVICE vectors in arrival order and the host
  materializes them only at the drain point (async-drain ordering);
- the trainer/executor/Monitor feeds and report/diag integrations;
- disabled-mode overhead is pinned separately in test_bench_gate.py.
"""

import json
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, health, profiler, runtime_stats
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


@pytest.fixture(autouse=True)
def _clean_health():
    health.reset()
    runtime_stats.reset()
    cap = _Capture()
    logging.getLogger("mxnet_tpu.health").addHandler(cap)
    yield cap
    logging.getLogger("mxnet_tpu.health").removeHandler(cap)
    profiler.set_state("stop")
    profiler._state["events"] = []
    health.reset()
    runtime_stats.reset()


# ------------------------------------------------------ stat kernel


def _np_stats(a):
    af = a.astype(np.float32)
    return {"nan_count": float(np.isnan(af).sum()),
            "inf_count": float(np.isinf(af).sum()),
            "abs_mean": np.abs(af).mean(),
            "min": af.min(), "max": af.max(),
            "l2_norm": np.sqrt((af * af).sum()),
            "zero_frac": float((a == 0).mean())}


@pytest.mark.parametrize("case", ["all_nan", "some_inf", "zeros",
                                  "random", "int32"])
def test_stat_kernel_matches_numpy(case):
    rs = np.random.RandomState(3)
    a = {"all_nan": np.full((4, 5), np.nan, np.float32),
         "some_inf": np.array([[1.0, -np.inf], [np.inf, 0.0]], np.float32),
         "zeros": np.zeros((3, 3), np.float32),
         "random": (rs.randn(6, 7) * 10).astype(np.float32),
         "int32": np.arange(-4, 8, dtype=np.int32).reshape(3, 4)}[case]
    got = health.tensor_stats(mx.nd.array(a, dtype=a.dtype),
                              health.STAT_NAMES)
    want = _np_stats(a)
    assert set(got) == set(want)
    for name in health.STAT_NAMES:
        np.testing.assert_allclose(got[name], want[name], rtol=1e-6,
                                   atol=1e-6, equal_nan=True,
                                   err_msg="stat %s on %s" % (name, case))


def test_stat_kernel_rejects_unknown_stat():
    with pytest.raises(ValueError, match="unknown health stat"):
        health.stat_kernel(("nan_count", "entropy"))


def test_custom_stat_selection_keeps_sentinels():
    mon = health.enable(stats=("abs_mean",))
    assert "nan_count" in mon.stats and "inf_count" in mon.stats


def test_env_stat_selection_honored(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_HEALTH_STATS", "zero_frac, abs_mean")
    mon = health.enable()
    assert mon.stats == ("zero_frac", "abs_mean",
                         "nan_count", "inf_count")
    mon.observe("t", mx.nd.array(np.array([0.0, 2.0], np.float32)))
    drained = mon.drain()
    assert drained[0]["stats"]["zero_frac"] == 0.5


# ------------------------------------------------ async-drain ordering


def test_observe_queues_device_values_and_drains_in_order():
    import jax

    mon = health.enable(interval=2)
    xs = {k: mx.nd.array(np.full((2, 2), i, np.float32))
          for i, k in enumerate(["t0", "t1", "t2"])}
    for k in ("t0", "t1", "t2"):
        mon.observe(k, xs[k])
    # queued DEVICE vectors, in arrival order, nothing on host yet
    assert len(mon._pending) == 3
    for kind, step, _key, dev in mon._pending:
        assert kind == "stats" and step == 0
        assert isinstance(dev, jax.Array)
        assert not isinstance(dev, np.ndarray)
    assert list(mon.records) == []

    mon.end_step()  # step 0 is a sampled step -> drain happens here
    assert len(mon._pending) == 0
    assert [r["key"] for r in mon.records] == ["t0", "t1", "t2"]
    assert [r["step"] for r in mon.records] == [0, 0, 0]
    np.testing.assert_allclose(
        [r["stats"]["abs_mean"] for r in mon.records], [0.0, 1.0, 2.0])

    # step 1 is NOT sampled under interval=2: observe must be a no-op
    mon.observe("skipped", xs["t0"])
    assert len(mon._pending) == 0
    mon.end_step()
    # step 2 samples again
    mon.observe("t3", xs["t1"])
    assert len(mon._pending) == 1 and mon._pending[0][1] == 2


def test_pending_queue_is_bounded_and_counts_drops(monkeypatch):
    mon = health.enable()
    monkeypatch.setattr(health, "_PENDING_CAP", 4)
    x = mx.nd.ones((2,))
    for i in range(7):
        mon.observe("k%d" % i, x)
    assert len(mon._pending) == 4
    assert mon.totals["dropped"] == 3
    drained = mon.drain()
    assert [r["key"] for r in drained] == ["k3", "k4", "k5", "k6"]


def test_tracer_values_are_skipped_and_no_double_observation():
    """Inside a staged/hybridized trace outputs are tracers — queueing
    one across the trace boundary would be a leak, so observe skips;
    the root forward hook then observes each concrete cached output
    exactly ONCE per forward."""
    from mxnet_tpu.gluon.block import is_staging

    mon = health.enable()
    net = nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    mon.install(net)
    net.hybridize()
    assert not is_staging()
    net(mx.nd.ones((2, 4)))  # staging pass + cached-graph call
    mon.drain()
    # concrete outputs only, and no duplicate key for the same forward
    assert all(np.isfinite(r["stats"]["abs_mean"]) for r in mon.records)
    keys = [r["key"] for r in mon.records]
    assert len(keys) == len(set(keys)) == 1, keys
    # steady state (cached executable): still one observation per call
    before = len(mon.records)
    net(mx.nd.ones((2, 4)))
    mon.drain()
    assert len(mon.records) == before + 1


def test_disable_makes_installed_hooks_inert():
    """disable() must stop install()'d hooks from dispatching kernels
    into a queue nothing will ever drain."""
    mon = health.enable()
    net = nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    net(mx.nd.ones((2, 4)))  # finish deferred init
    mon.install(net)
    net(mx.nd.ones((2, 4)))
    assert len(mon._pending) == 1
    health.disable()
    net(mx.nd.ones((2, 4)))
    assert len(mon._pending) == 1, "inert hook must not enqueue"
    # a replaced monitor's orphaned hooks go inert the same way
    mon2 = health.enable()
    net(mx.nd.ones((2, 4)))
    assert len(mon._pending) == 1 and len(mon2._pending) == 0


def test_update_ratio_keys_respect_pattern():
    mon = health.enable(pattern="grad_norm|loss|uwr:dense.*weight.*")
    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    _train(net, 2)
    keys = {r["key"] for r in mon.records}
    assert any(k.startswith("uwr:") and "weight" in k for k in keys)
    assert not any("bias" in k for k in keys), keys
    assert "grad_norm" in keys


# ------------------------------------- the acceptance loop: induced NaN


def _train(net, steps, poison_at=None, batch=2):
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    rs = np.random.RandomState(0)
    mon = health.monitor()
    for step in range(steps):
        if step == poison_at:
            w = net.weight.data()
            net.weight.set_data(mx.nd.array(
                np.full(w.shape, np.nan, np.float32)))
        x = mx.nd.array(rs.rand(batch, 6).astype(np.float32))
        y = mx.nd.array(rs.randint(0, 4, (batch,)).astype(np.float32))
        with autograd.record():
            L = loss_fn(net(x), y)
        L.backward()
        if mon is not None:
            mon.note_loss(L)
        trainer.step(batch)
    return trainer


def test_twenty_step_loop_records_grad_norm_and_nan_free(tmp_path):
    profiler.set_config(filename=str(tmp_path / "health_trace.json"))
    profiler.set_state("run")
    mon = health.enable(dump_path=str(tmp_path / "flight.json"))
    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    mon.install(net)
    _train(net, 20)

    snap = health.snapshot()
    assert snap["step"] == 20
    flight = snap["flight"]
    assert len(flight) == 20
    for rec in flight:
        assert rec["grad_norm"] is not None and rec["grad_norm"] >= 0
        assert rec["nan_total"] == 0 and rec["inf_total"] == 0
        assert rec["loss"] is not None
        assert "jit_cache_misses" in rec["counters"]
    assert [r["step"] for r in flight] == list(range(20))
    # per-param update-to-weight ratios rode along
    assert any(r["key"].startswith("uwr:") for r in mon.records)
    # forward-hook observations too
    assert any(r["key"].endswith("_output0") for r in mon.records)
    assert snap["first_nan"] is None

    # chrome-trace counter events while the profiler ran
    path = profiler.dump(finished=True)
    trace = json.load(open(path))["traceEvents"]
    gn = [e for e in trace if e.get("ph") == "C"
          and e["name"] == "grad_norm"]
    nt = [e for e in trace if e.get("ph") == "C"
          and e["name"] == "nan_total"]
    assert len(gn) == 20 and len(nt) == 20
    assert all(e["args"]["nan_total"] == 0 for e in nt)


def test_induced_nan_warns_once_and_dumps_flight(tmp_path, _clean_health):
    dump = str(tmp_path / "flight.json")
    mon = health.enable(dump_path=dump)
    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    mon.install(net)
    _train(net, 20, poison_at=10)

    snap = health.snapshot()
    fn = snap["first_nan"]
    assert fn is not None and fn["step"] == 10
    assert fn["key"], "first-NaN marker must name the offending tensor"
    # half the steps are poisoned, ONE rate-limited warning fired
    warns = [m for m in _clean_health.messages if "non-finite" in m]
    assert len(warns) == 1, warns
    assert fn["key"] in warns[0]
    assert snap["totals"]["nan_steps"] >= 10

    # the atomic dump exists, parses, and carries the poisoned records
    assert os.path.exists(dump)
    assert mon.flight.dumps == 1, "first NaN dumps exactly once"
    data = json.load(open(dump))
    assert data["reason"] == "first-nan"
    flight = data["health"]["flight"]
    assert any(r["nan_total"] > 0 for r in flight)
    assert not [f for f in os.listdir(tmp_path) if f.startswith(".")], \
        "no temp file left behind by the atomic dump"

    # readable by the runtime_stats CLI
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert runtime_stats.main([dump]) == 0
    out = buf.getvalue()
    assert "Numerics health" in out
    assert "FIRST NON-FINITE" in out
    assert "first-nan" in out


def test_trainer_step_exception_dumps_flight(tmp_path, monkeypatch):
    dump = str(tmp_path / "crash_flight.json")
    mon = health.enable(dump_path=dump)
    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    trainer = _train(net, 3)

    def boom(*a, **kw):
        raise RuntimeError("induced optimizer failure")

    monkeypatch.setattr(trainer, "_update", boom)
    x = mx.nd.ones((2, 6))
    with autograd.record():
        L = gluon.loss.SoftmaxCrossEntropyLoss()(net(x), mx.nd.zeros((2,)))
    L.backward()
    with pytest.raises(RuntimeError, match="induced optimizer failure"):
        trainer.step(2)
    assert os.path.exists(dump)
    data = json.load(open(dump))
    assert data["reason"] == "trainer-step-exception"
    # the ring carried the healthy steps recorded before the crash
    assert len(data["health"]["flight"]) >= 3


# --------------------------------------------------- surface integrations


def test_executor_outputs_and_grads_feed_health():
    health.enable()
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 5))
    ex.arg_dict["data"][:] = np.ones((2, 5), np.float32)
    ex.forward(is_train=True)
    ex.backward(out_grads=mx.nd.ones((2, 3)))
    drained = health.monitor().drain()
    keys = {r["key"] for r in drained}
    assert any(k.startswith("exec:") for k in keys)
    assert any(k.startswith("exec_grad:") for k in keys)


def test_monitor_device_default_has_no_host_sync_until_toc(monkeypatch):
    """The legacy Monitor's default path now computes on device: the
    per-tensor hook must not call asnumpy; toc() is the sync point."""
    from mxnet_tpu.ndarray import NDArray

    net = nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    net(mx.nd.ones((2, 5)))  # finish deferred init (a one-off host copy)
    mon = mx.monitor.Monitor(1, pattern=".*")
    mon.install(net)

    calls = []
    orig = NDArray.asnumpy

    def counting(self):
        calls.append(1)
        return orig(self)

    monkeypatch.setattr(NDArray, "asnumpy", counting)
    mon.tic()
    net(mx.nd.ones((2, 5)))
    assert calls == [], "device-mode Monitor must not sync mid-forward"
    res = mon.toc()
    assert res and all(np.isfinite(v) for _, _, v in res)


def test_monitor_legacy_stat_func_still_host_numpy():
    net = nn.Dense(3)
    net.initialize(ctx=mx.cpu())
    seen = []

    def stat(arr):
        seen.append(type(arr))
        return np.abs(arr).max()

    mon = mx.monitor.Monitor(1, stat_func=stat, pattern=".*")
    mon.install(net)
    mon.tic()
    net(mx.nd.ones((2, 5)))
    res = mon.toc()
    assert res and seen and all(t is np.ndarray for t in seen)


def test_clip_global_norm_scales_on_device_and_warns_on_nan():
    import warnings

    arrays = [mx.nd.ones((3,)) * 4, mx.nd.ones((2,)) * 3]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    want = np.sqrt(3 * 16 + 2 * 9)  # three 4s + two 3s
    np.testing.assert_allclose(norm, want, rtol=1e-5)
    new_total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(new_total - 1.0) < 1e-4

    bad = [mx.nd.array(np.array([np.nan, 1.0], np.float32))]
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        gluon.utils.clip_global_norm(bad, 1.0)
    assert any("nan or inf" in str(x.message) for x in w)
    # and check_isfinite=False stays silent
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        gluon.utils.clip_global_norm(
            [mx.nd.array(np.array([np.inf], np.float32))], 1.0,
            check_isfinite=False)
    assert not w


def test_clip_global_norm_nonfinite_norm_leaves_arrays_untouched():
    """Reference semantics: a NaN/Inf global norm must not rescale —
    the old host branch (`if scale < 1.0`) was False for NaN, so the
    arrays (including the finite ones) stayed intact for a caller that
    detects via the returned norm and skips the step."""
    import warnings

    bad = mx.nd.array(np.array([np.nan, 1.0], np.float32))
    good = mx.nd.array(np.array([2.0, 3.0], np.float32))
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        norm = gluon.utils.clip_global_norm([bad, good], 1.0)
    assert norm != norm  # NaN propagates to the returned scalar
    np.testing.assert_array_equal(good.asnumpy(), [2.0, 3.0])
    np.testing.assert_array_equal(bad.asnumpy()[1:], [1.0])


def test_mid_step_drain_merges_into_one_flight_record(tmp_path):
    """report()/drain() between observations must not split a step
    into two flight records or double-count nan_steps."""
    mon = health.enable(dump_path=str(tmp_path / "flight.json"))
    nan = mx.nd.array(np.array([np.nan], np.float32))
    mon.observe("first_half", nan)
    mon.drain()                      # mid-step drain (e.g. report())
    mon.observe("second_half", nan)
    mon.end_step()
    flight = mon.flight.records()
    assert [r["step"] for r in flight] == [0]
    assert flight[0]["nan_total"] == 2.0
    assert mon.totals["nan_steps"] == 1


def test_report_and_diag_carry_health_section(tmp_path):
    mon = health.enable()
    mon.observe("t", mx.nd.ones((2, 2)))
    mon.end_step()
    report = runtime_stats.report()
    assert "Numerics health" in report
    assert "Flight recorder" in report

    p = runtime_stats.dump_diag(str(tmp_path / "diag.json"))
    data = json.load(open(p))
    h = data["snapshot"]["health"]
    assert h["enabled"] and len(h["flight"]) == 1


def test_report_health_section_self_describing_when_off():
    assert "monitor off" in runtime_stats.report()


def test_snapshot_never_drains_pending():
    mon = health.enable()
    mon.observe("t", mx.nd.ones((2, 2)))
    snap = health.snapshot()
    assert snap["pending"] == 1
    assert len(mon._pending) == 1, "snapshot must not drain (no sync)"


def test_env_activation(tmp_path):
    import subprocess
    import sys

    out = tmp_path / "env_flight.json"
    code = ("import mxnet_tpu as mx\n"
            "from mxnet_tpu import health\n"
            "assert health.is_enabled()\n"
            "m = health.monitor()\n"
            "m.observe('t', mx.nd.ones((2, 2)))\n"
            "m.end_step()\n"
            "print(health.dump_flight(%r))\n" % str(out))
    env = dict(os.environ, MXNET_TPU_HEALTH="1", JAX_PLATFORMS="cpu")
    env.pop("MXNET_TPU_DIAG", None)
    env.pop("PYTHONPATH", None)
    subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                   check=True, timeout=180)
    data = json.load(open(out))
    assert data["health"]["totals"]["drained"] == 1
