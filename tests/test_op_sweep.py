"""Registry-driven operator sweep.

VERDICT r1 weak-spot 2: the op surface (306 ops) had ~1 test per 12
ops.  This sweep is generated FROM the registry: every op must appear
in exactly one tier below, and ``test_registry_fully_covered`` fails
when a newly registered op has no test.

Tiers (reference model: tests/python/unittest/test_operator.py — the
~7k-line dtype/shape/attr matrix):

- UNARY / BINARY / SCALAR / REDUCE — forward vs numpy at float32 AND
  float16, numeric gradient (smooth ops) via jax.grad vs central
  differences, plus eager/staged/sharded 3-way consistency
  (test_utils.check_op_consistency) on a sample.
- EXPLICIT — per-op cases with handmade inputs; ref=None means the op
  is validated by shape/finiteness + consistency (its exact semantics
  are covered by a dedicated test elsewhere).
- ELSEWHERE — ops with dedicated deep tests; each entry names the file
  so coverage claims stay auditable.
"""

import math

import numpy as np

try:
    import scipy.special  # noqa: F401
    _HAVE_SCIPY = True
except ImportError:
    _HAVE_SCIPY = False


def _digamma_ref(x, eps=1e-5):
    # central difference of lgamma: accurate to ~1e-6 for x in [0.5, 3]
    return (math.lgamma(x + eps) - math.lgamma(x - eps)) / (2 * eps)
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import registry
from mxnet_tpu.ops.registry import apply_op
from mxnet_tpu.test_utils import check_op_consistency

RS = np.random.RandomState


def _erf(x):
    from math import erf

    return np.vectorize(erf)(x)


def _erfinv(y):
    from scipy_free_erfinv import erfinv  # pragma: no cover

    return erfinv(y)


# --------------------------------------------------------------------------
# tier tables
# --------------------------------------------------------------------------
# name -> (numpy_fn, low, high, smooth_for_grad)
UNARY = {
    "abs": (np.abs, -2, 2, False),
    "arccos": (np.arccos, -0.9, 0.9, True),
    "arccosh": (np.arccosh, 1.1, 3, True),
    "arcsin": (np.arcsin, -0.9, 0.9, True),
    "arcsinh": (np.arcsinh, -2, 2, True),
    "arctan": (np.arctan, -2, 2, True),
    "arctanh": (np.arctanh, -0.9, 0.9, True),
    "cbrt": (np.cbrt, 0.1, 3, True),
    "ceil": (np.ceil, -2, 2, False),
    "cos": (np.cos, -2, 2, True),
    "cosh": (np.cosh, -2, 2, True),
    "degrees": (np.degrees, -2, 2, True),
    "erf": (_erf, -2, 2, True),
    "exp": (np.exp, -2, 2, True),
    "expm1": (np.expm1, -2, 2, True),
    "fix": (np.trunc, -2, 2, False),
    "floor": (np.floor, -2, 2, False),
    "gamma": (lambda x: np.vectorize(__import__("math").gamma)(x), 0.5, 3,
              True),
    "gammaln": (lambda x: np.vectorize(__import__("math").lgamma)(x), 0.5, 3,
                True),
    "digamma": (lambda x: __import__("scipy.special", fromlist=["digamma"])
                .digamma(x) if _HAVE_SCIPY
                else np.vectorize(_digamma_ref)(x), 0.5, 3, True),
    "log": (np.log, 0.1, 3, True),
    "log10": (np.log10, 0.1, 3, True),
    "log1p": (np.log1p, -0.5, 3, True),
    "log2": (np.log2, 0.1, 3, True),
    "logical_not": (lambda x: (x == 0).astype(x.dtype), -1, 1, False),
    "negative": (np.negative, -2, 2, True),
    "radians": (np.radians, -2, 2, True),
    "rcbrt": (lambda x: 1.0 / np.cbrt(x), 0.2, 3, True),
    "reciprocal": (np.reciprocal, 0.2, 3, True),
    "relu": (lambda x: np.maximum(x, 0), -2, 2, False),
    "rint": (np.rint, -2, 2, False),
    "round": (lambda x: np.floor(x + 0.5), -2, 2, False),
    "rsqrt": (lambda x: 1.0 / np.sqrt(x), 0.2, 3, True),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), -2, 2, True),
    "sign": (np.sign, -2, 2, False),
    "sin": (np.sin, -2, 2, True),
    "sinh": (np.sinh, -2, 2, True),
    "softrelu": (lambda x: np.log1p(np.exp(x)), -2, 2, True),
    "softsign": (lambda x: x / (1 + np.abs(x)), -2, 2, True),
    "sqrt": (np.sqrt, 0.1, 3, True),
    "square": (np.square, -2, 2, True),
    "tan": (np.tan, -1, 1, True),
    "tanh": (np.tanh, -2, 2, True),
    "trunc": (np.trunc, -2, 2, False),
    "isfinite": (lambda x: np.isfinite(x).astype(x.dtype), -2, 2, False),
    "isinf": (lambda x: np.isinf(x).astype(x.dtype), -2, 2, False),
    "isnan": (lambda x: np.isnan(x).astype(x.dtype), -2, 2, False),
    "hard_sigmoid": (lambda x: np.clip(0.2 * x + 0.5, 0, 1), -4, 4, False),
    "erfinv": (None, -0.9, 0.9, True),  # checked via erf(erfinv(x)) == x
    "_copy": (lambda x: x, -2, 2, True),
    "BlockGrad": (lambda x: x, -2, 2, False),
    "make_loss": (lambda x: x, -2, 2, False),
    "zeros_like": (np.zeros_like, -2, 2, False),
    "ones_like": (np.ones_like, -2, 2, False),
    "shape_array": (lambda x: np.array(x.shape, np.int64), -2, 2, False),
    "size_array": (lambda x: np.array([x.size], np.int64), -2, 2, False),
}

# name -> (numpy_fn, low, high) — both operands from [low, high]
_cmp = {
    "equal": lambda a, b: (a == b), "not_equal": lambda a, b: (a != b),
    "greater": lambda a, b: (a > b), "greater_equal": lambda a, b: (a >= b),
    "lesser": lambda a, b: (a < b), "lesser_equal": lambda a, b: (a <= b),
    "logical_and": lambda a, b: (a != 0) & (b != 0),
    "logical_or": lambda a, b: (a != 0) | (b != 0),
    "logical_xor": lambda a, b: (a != 0) ^ (b != 0),
}
BINARY_CORE = {
    "add": (np.add, -2, 2), "sub": (np.subtract, -2, 2),
    "mul": (np.multiply, -2, 2), "div": (np.divide, 0.5, 3),
    "mod": (np.mod, 0.5, 3), "power": (np.power, 0.5, 2),
    "maximum": (np.maximum, -2, 2), "minimum": (np.minimum, -2, 2),
    "hypot": (np.hypot, -2, 2),
}
BINARY = {}
for _n, (_f, _lo, _hi) in BINARY_CORE.items():
    BINARY["elemwise_" + _n] = (_f, _lo, _hi)
    BINARY["broadcast_" + _n] = (_f, _lo, _hi)
for _n, _f in _cmp.items():
    _wrapped = (lambda f: lambda a, b: f(a, b).astype(a.dtype))(_f)
    BINARY["elemwise_" + _n] = (_wrapped, -1, 1)
    BINARY["broadcast_" + _n] = (_wrapped, -1, 1)

# name -> (numpy_fn(x, s), low, high, scalar)
SCALAR = {
    "_plus_scalar": (lambda x, s: x + s, -2, 2, 0.7),
    "_minus_scalar": (lambda x, s: x - s, -2, 2, 0.7),
    "_rminus_scalar": (lambda x, s: s - x, -2, 2, 0.7),
    "_mul_scalar": (lambda x, s: x * s, -2, 2, 0.7),
    "_div_scalar": (lambda x, s: x / s, -2, 2, 0.7),
    "_rdiv_scalar": (lambda x, s: s / x, 0.5, 3, 0.7),
    "_mod_scalar": (lambda x, s: np.mod(x, s), 0.1, 3, 0.7),
    "_rmod_scalar": (lambda x, s: np.mod(s, x), 0.5, 3, 0.7),
    "_power_scalar": (lambda x, s: np.power(x, s), 0.5, 2, 0.7),
    "_rpower_scalar": (lambda x, s: np.power(s, x), -1, 1, 0.7),
    "_maximum_scalar": (lambda x, s: np.maximum(x, s), -2, 2, 0.3),
    "_minimum_scalar": (lambda x, s: np.minimum(x, s), -2, 2, 0.3),
    "_hypot_scalar": (lambda x, s: np.hypot(x, s), -2, 2, 0.7),
    "_equal_scalar": (lambda x, s: (x == s).astype(x.dtype), 0, 2, 1.0),
    "_not_equal_scalar": (lambda x, s: (x != s).astype(x.dtype), 0, 2, 1.0),
    "_greater_scalar": (lambda x, s: (x > s).astype(x.dtype), -2, 2, 0.3),
    "_greater_equal_scalar": (lambda x, s: (x >= s).astype(x.dtype), -2, 2, 0.3),
    "_lesser_scalar": (lambda x, s: (x < s).astype(x.dtype), -2, 2, 0.3),
    "_lesser_equal_scalar": (lambda x, s: (x <= s).astype(x.dtype), -2, 2, 0.3),
    "_logical_and_scalar": (lambda x, s: ((x != 0) & (s != 0)).astype(x.dtype), -1, 1, 1.0),
    "_logical_or_scalar": (lambda x, s: ((x != 0) | (s != 0)).astype(x.dtype), -1, 1, 0.0),
    "_logical_xor_scalar": (lambda x, s: ((x != 0) ^ (s != 0)).astype(x.dtype), -1, 1, 1.0),
    "smooth_l1": (lambda x, s: np.where(np.abs(x) < 1 / s**2,
                                        0.5 * s**2 * x * x,
                                        np.abs(x) - 0.5 / s**2), -2, 2, 1.0),
}

# name -> (numpy_fn(x, axis_kwarg), attrs_variants)
REDUCE = {
    "sum": (np.sum, [{}, {"axis": 1}, {"axis": (0, 2), "keepdims": True}]),
    "mean": (np.mean, [{}, {"axis": 1}, {"axis": 2, "keepdims": True}]),
    "max": (np.max, [{}, {"axis": 1}]),
    "min": (np.min, [{}, {"axis": 1}]),
    "prod": (np.prod, [{}, {"axis": 1}]),
    "nansum": (np.nansum, [{}, {"axis": 1}]),
    "nanprod": (np.nanprod, [{}, {"axis": 1}]),
    "argmax": (lambda x, **k: np.argmax(x, **k).astype(np.float32),
               [{"axis": 1}, {"axis": 2}]),
    "argmin": (lambda x, **k: np.argmin(x, **k).astype(np.float32),
               [{"axis": 1}]),
}


def _case(inputs, attrs=None, ref=None, rtol=2e-4, atol=2e-4,
          consistency=True):
    return {"inputs": inputs, "attrs": attrs or {}, "ref": ref,
            "rtol": rtol, "atol": atol, "consistency": consistency}


def _f32(*shape, seed=0, lo=-1.0, hi=1.0):
    return (RS(seed).uniform(lo, hi, shape)).astype(np.float32)


def _idx(*shape, seed=0, n=4):
    return RS(seed).randint(0, n, shape).astype(np.int32)


def _posdef(n, seed=0):
    a = RS(seed).randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


# ops with handmade inputs; ref=None -> run + consistency only
EXPLICIT = {
    # ---- shape / indexing / layout ----
    "Reshape": [_case([_f32(2, 6)], {"shape": (3, 4)},
                      lambda x: x.reshape(3, 4))],
    "reshape_like": [_case([_f32(2, 6), _f32(3, 4)], {},
                           lambda x, y: x.reshape(3, 4))],
    "Flatten": [_case([_f32(2, 3, 4)], {}, lambda x: x.reshape(2, 12))],
    "expand_dims": [_case([_f32(2, 3)], {"axis": 1},
                          lambda x: x[:, None, :])],
    "squeeze": [_case([_f32(2, 1, 3)], {"axis": 1},
                      lambda x: x.squeeze(1))],
    "transpose": [_case([_f32(2, 3, 4)], {"axes": (2, 0, 1)},
                        lambda x: x.transpose(2, 0, 1))],
    "SwapAxis": [_case([_f32(2, 3, 4)], {"dim1": 0, "dim2": 2},
                       lambda x: x.swapaxes(0, 2))],
    "slice": [_case([_f32(4, 6)], {"begin": (1, 2), "end": (3, 5)},
                    lambda x: x[1:3, 2:5])],
    "slice_axis": [_case([_f32(4, 6)], {"axis": 1, "begin": 1, "end": 4},
                         lambda x: x[:, 1:4])],
    "slice_like": [_case([_f32(4, 6), _f32(2, 3)], {},
                         lambda x, y: x[:2, :3])],
    "Crop": [_case([_f32(1, 2, 6, 6), _f32(1, 2, 4, 4)], {"num_args": 2},
                   lambda x, y: x[:, :, :4, :4])],
    "clip": [_case([_f32(3, 4, lo=-2, hi=2)], {"a_min": -0.5, "a_max": 0.5},
                   lambda x: np.clip(x, -0.5, 0.5))],
    "tile": [_case([_f32(2, 3)], {"reps": (2, 2)},
                   lambda x: np.tile(x, (2, 2)))],
    "repeat": [_case([_f32(2, 3)], {"repeats": 2, "axis": 1},
                     lambda x: np.repeat(x, 2, 1))],
    "reverse": [_case([_f32(3, 4)], {"axis": 0}, lambda x: x[::-1])],
    "pick": [_case([_f32(3, 5), _idx(3, n=5)], {"axis": 1},
                   lambda x, i: x[np.arange(3), i])],
    "batch_take": [_case([_f32(3, 5), _idx(3, n=5)], {"axis": 1},
                         lambda x, i: x[np.arange(3), i])],
    "take": [_case([_f32(5, 4), _idx(3, n=5)], {"axis": 0},
                   lambda x, i: x[i])],
    "one_hot": [_case([_idx(4, n=5)], {"depth": 5},
                      lambda i: np.eye(5, dtype=np.float32)[i])],
    "where": [_case([(_f32(3, 4) > 0).astype(np.float32), _f32(3, 4, seed=1),
                     _f32(3, 4, seed=2)], {},
                    lambda c, x, y: np.where(c != 0, x, y))],

    "gather_nd": [_case([_f32(4, 5), _idx(2, 3, n=4).astype(np.int32)], {},
                        lambda x, i: x[i[0], i[1]])],
    "_backward_gather_nd": [_case(
        [_f32(3), _idx(2, 3, n=4)], {"shape": (4, 5)}, None,
        consistency=False)],
    "scatter_nd": [_case([_f32(3), _idx(2, 3, n=4)], {"shape": (4, 5)},
                         None, consistency=False)],
    "index_copy": [_case([_f32(5, 3), np.array([1, 3], np.int32),
                          _f32(2, 3, seed=1)], {}, None)],
    "index_add": [_case([_f32(5, 3), np.array([1, 3], np.int32),
                         _f32(2, 3, seed=1)], {}, None)],
    "boolean_mask": [_case([_f32(4, 3),
                            np.array([1, 0, 1, 1], np.float32)], {}, None,
                           consistency=False)],
    "Concat": [_case([_f32(2, 3), _f32(2, 4, seed=1)], {"dim": 1,
                                                        "num_args": 2},
                     lambda a, b: np.concatenate([a, b], 1))],
    "stack": [_case([_f32(2, 3), _f32(2, 3, seed=1)], {"axis": 0,
                                                       "num_args": 2},
                    lambda a, b: np.stack([a, b]))],
    "SliceChannel": [_case([_f32(2, 6)], {"num_outputs": 2},
                           lambda x: (x[:, :3], x[:, 3:]))],
    "split_v2": [_case([_f32(2, 6)], {"axis": 1, "sections": 3},
                       lambda x: (x[:, :2], x[:, 2:4], x[:, 4:]))],
    "broadcast_to": [_case([_f32(1, 3)], {"shape": (4, 3)},
                           lambda x: np.broadcast_to(x, (4, 3)).copy())],
    "broadcast_axis": [_case([_f32(1, 3)], {"axis": 0, "size": 4},
                             lambda x: np.broadcast_to(x, (4, 3)).copy())],
    "broadcast_like": [_case([_f32(1, 3), _f32(4, 3)], {},
                             lambda x, y: np.broadcast_to(x, (4, 3)).copy())],
    "Pad": [_case([_f32(1, 2, 3, 3)],
                  {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)},
                  lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1))))],
    "cumsum": [_case([_f32(3, 4)], {"axis": 1},
                     lambda x: np.cumsum(x, 1))],
    "diag": [_case([_f32(4, 4)], {}, lambda x: np.diag(x).copy())],
    "depth_to_space": [_case([_f32(1, 8, 2, 2)], {"block_size": 2}, None)],
    "space_to_depth": [_case([_f32(1, 2, 4, 4)], {"block_size": 2}, None)],
    "ravel_multi_index": [_case(
        [np.array([[1, 2], [2, 3]], np.float32)], {"shape": (4, 5)},
        lambda x: np.array([1 * 5 + 2, 2 * 5 + 3], np.float32),
        consistency=False)],
    "unravel_index": [_case(
        [np.array([7, 13], np.float32)], {"shape": (4, 5)},
        lambda x: np.stack(np.unravel_index([7, 13], (4, 5))).astype(
            np.float32), consistency=False)],
    # ---- ordering ----
    "sort": [_case([_f32(3, 5)], {"axis": 1}, lambda x: np.sort(x, 1))],
    "argsort": [_case([_f32(3, 5)], {"axis": 1},
                      lambda x: np.argsort(x, 1).astype(np.float32))],
    "topk": [_case([_f32(3, 5)], {"k": 2, "axis": 1, "ret_typ": "value"},
                   lambda x: -np.sort(-x, 1)[:, :2])],
    # ---- linear algebra ----
    "dot": [_case([_f32(3, 4), _f32(4, 5, seed=1)], {},
                  lambda a, b: a @ b)],
    "batch_dot": [_case([_f32(2, 3, 4), _f32(2, 4, 5, seed=1)], {},
                        lambda a, b: np.einsum("bij,bjk->bik", a, b))],
    "linalg_gemm": [_case([_f32(3, 4), _f32(4, 5, seed=1),
                           _f32(3, 5, seed=2)], {},
                          lambda a, b, c: a @ b + c)],
    "linalg_gemm2": [_case([_f32(3, 4), _f32(4, 5, seed=1)], {},
                           lambda a, b: a @ b)],
    "linalg_potrf": [_case([_posdef(4)], {},
                           lambda a: np.linalg.cholesky(a), rtol=1e-3,
                           atol=1e-3)],
    "linalg_potri": [_case([np.linalg.cholesky(_posdef(4)).astype(
        np.float32)], {}, None, rtol=1e-2)],
    "linalg_trmm": [_case([np.tril(_f32(3, 3)) + 2 * np.eye(3, dtype=np.float32),
                           _f32(3, 4, seed=1)], {}, None)],
    "linalg_trsm": [_case([np.tril(_f32(3, 3)) + 2 * np.eye(3, dtype=np.float32),
                           _f32(3, 4, seed=1)], {}, None)],
    "linalg_syrk": [_case([_f32(3, 4)], {},
                          lambda a: a @ a.T, rtol=1e-3)],
    "linalg_sumlogdiag": [_case([_posdef(4)], {},
                                lambda a: np.array(
                                    np.sum(np.log(np.diag(a))),
                                    np.float32))],
    "linalg_extractdiag": [_case([_f32(4, 4)], {},
                                 lambda a: np.diag(a).copy())],
    "linalg_makediag": [_case([_f32(4)], {}, lambda a: np.diag(a))],
    "linalg_gelqf": [_case([_f32(3, 5)], {}, None, consistency=False)],
    "linalg_syevd": [_case([_posdef(4)], {}, None, consistency=False)],
    "khatri_rao": [_case([_f32(2, 3), _f32(4, 3, seed=1)], {},
                         lambda a, b: np.stack(
                             [np.kron(a[:, j], b[:, j]) for j in range(3)],
                             axis=1))],
    "trace_op": [_case([_f32(4, 4)], {},
                       lambda x: np.array(np.trace(x), np.float32))],
    "norm": [_case([_f32(3, 4)], {},
                   lambda x: np.array(np.linalg.norm(x), np.float32))],
    # ---- neural net ----
    "Activation": [
        _case([_f32(3, 4)], {"act_type": t},
              {"relu": lambda x: np.maximum(x, 0),
               "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
               "tanh": np.tanh,
               "softrelu": lambda x: np.log1p(np.exp(x)),
               "softsign": lambda x: x / (1 + np.abs(x))}[t])
        for t in ("relu", "sigmoid", "tanh", "softrelu", "softsign")],
    "FullyConnected": [
        _case([_f32(3, 4), _f32(5, 4, seed=1), _f32(5, seed=2)],
              {"num_hidden": 5}, lambda x, w, b: x @ w.T + b),
        _case([_f32(3, 2, 2), _f32(5, 4, seed=1), _f32(5, seed=2)],
              {"num_hidden": 5}, lambda x, w, b: x.reshape(3, 4) @ w.T + b),
        _case([_f32(3, 2, 4), _f32(5, 4, seed=1), _f32(5, seed=2)],
              {"num_hidden": 5, "flatten": False},
              lambda x, w, b: x @ w.T + b)],
    "softmax": [_case([_f32(3, 5)], {"axis": -1},
                      lambda x: np.exp(x) / np.exp(x).sum(-1,
                                                          keepdims=True))],
    "softmin": [_case([_f32(3, 5)], {"axis": -1},
                      lambda x: np.exp(-x) / np.exp(-x).sum(
                          -1, keepdims=True))],
    "log_softmax": [_case([_f32(3, 5)], {"axis": -1},
                          lambda x: x - x.max(-1, keepdims=True) - np.log(
                              np.exp(x - x.max(-1, keepdims=True)).sum(
                                  -1, keepdims=True)))],
    "SoftmaxActivation": [_case([_f32(3, 5)], {},
                                lambda x: np.exp(x) / np.exp(x).sum(
                                    -1, keepdims=True))],
    "argmax_channel": [_case([_f32(3, 5)], {},
                             lambda x: np.argmax(x, 1).astype(np.float32))],
    "softmax_cross_entropy": [_case(
        [_f32(3, 5), np.array([1, 0, 4], np.float32)], {}, None)],
    # symbol autogen grows a gamma variable for prelu, so the generic
    # staged-consistency leg does not apply
    "LeakyReLU": [
        _case([_f32(3, 4)], {"act_type": "leaky", "slope": 0.1},
              lambda x: np.where(x > 0, x, 0.1 * x), consistency=False),
        _case([_f32(3, 4)], {"act_type": "elu", "slope": 0.3},
              lambda x: np.where(x > 0, x, 0.3 * np.expm1(x)),
              consistency=False)],
    "L2Normalization": [_case(
        [_f32(3, 4)], {},
        lambda x: x / np.sqrt((x * x).sum(1, keepdims=True) + 1e-10))],
    "quadratic": [_case([_f32(3, 4)], {"a": 2.0, "b": 1.0, "c": 0.5},
                        lambda x: 2 * x * x + x + 0.5)],
    # conv/pool attr matrices live in test_conv_attr_matrix below
    "Convolution": [_case(
        [_f32(1, 2, 5, 5), _f32(3, 2, 3, 3, seed=1), _f32(3, seed=2)],
        {"kernel": (3, 3), "num_filter": 3}, None)],
    "Deconvolution": [_case(
        [_f32(1, 3, 4, 4), _f32(3, 2, 2, 2, seed=1)],
        {"kernel": (2, 2), "num_filter": 2, "no_bias": True}, None)],
    "Pooling": [_case([_f32(1, 2, 6, 6)],
                      {"kernel": (2, 2), "stride": (2, 2),
                       "pool_type": "max"}, None)],
    # train/eval stats semantics differ by path; deep test in
    # test_operator.py — forward-run only here
    "BatchNorm": [_case(
        [_f32(2, 3, 4, 4), np.ones(3, np.float32), np.zeros(3, np.float32),
         np.zeros(3, np.float32), np.ones(3, np.float32)], {}, None,
        consistency=False)],
    "LayerNorm": [_case(
        [_f32(3, 6), np.ones(6, np.float32), np.zeros(6, np.float32)], {},
        lambda x, g, b: (x - x.mean(-1, keepdims=True)) /
        np.sqrt(x.var(-1, keepdims=True) + 1e-5), rtol=1e-3, atol=1e-3)],
    "InstanceNorm": [_case(
        [_f32(2, 3, 5), np.ones(3, np.float32), np.zeros(3, np.float32)],
        {}, None)],
    "LRN": [_case([_f32(1, 4, 3, 3)], {"nsize": 3}, None)],
    "Embedding": [_case([_idx(3, 2, n=6), _f32(6, 4, seed=1)],
                        {"input_dim": 6, "output_dim": 4},
                        lambda i, w: w[i])],
    "Dropout": [_case([_f32(3, 4)], {"p": 0.5}, lambda x: x,
                      consistency=False)],  # eval mode = identity
    "UpSampling": [_case([_f32(1, 2, 3, 3)],
                         {"scale": 2, "sample_type": "nearest"},
                         lambda x: x.repeat(2, 2).repeat(2, 3))],
    "BilinearResize2D": [_case([_f32(1, 2, 3, 3)],
                               {"height": 6, "width": 6}, None)],
    "AdaptiveAvgPooling2D": [_case([_f32(1, 2, 6, 6)],
                                   {"output_size": 3}, None)],
    "GridGenerator": [_case([_f32(1, 6)],
                            {"transform_type": "affine",
                             "target_shape": (4, 4)}, None,
                            consistency=False)],
    "SequenceMask": [_case(
        [_f32(4, 3, 2), np.array([2, 4, 1], np.float32)],
        {"use_sequence_length": True}, None)],
    "SequenceLast": [_case(
        [_f32(4, 3, 2), np.array([2, 4, 1], np.float32)],
        {"use_sequence_length": True}, None)],
    "SequenceReverse": [_case(
        [_f32(4, 3, 2), np.array([2, 4, 1], np.float32)],
        {"use_sequence_length": True}, None)],
    "SVMOutput": [_case([_f32(3, 5), np.array([1, 0, 4], np.float32)], {},
                        None)],
    "LinearRegressionOutput": [_case(
        [_f32(3, 4), _f32(3, 4, seed=1)], {}, lambda x, y: x)],
    "MAERegressionOutput": [_case(
        [_f32(3, 4), _f32(3, 4, seed=1)], {}, lambda x, y: x)],
    "LogisticRegressionOutput": [_case(
        [_f32(3, 4), _f32(3, 4, seed=1)], {},
        lambda x, y: 1 / (1 + np.exp(-x)))],
    "SoftmaxOutput": [_case(
        [_f32(3, 5), np.array([1, 0, 4], np.float32)], {},
        lambda x, y: np.exp(x) / np.exp(x).sum(-1, keepdims=True))],
    # ---- misc data ops ----
    "histogram": [_case([_f32(20)], {"bin_cnt": 5, "range": (-1, 1)}, None,
                        consistency=False)],
    "getnnz": [_case([np.array([[1, 0], [0, 2]], np.float32)], {},
                     lambda x: np.array(2, np.int64), consistency=False)],
    "cast_storage_op": [_case([_f32(3, 4)], {"stype": "default"},
                              lambda x: x)],
    "sparse_retain": [_case([_f32(4, 3), np.array([0, 2], np.float32)], {},
                            None, consistency=False)],
    "Cast": [_case([_f32(3, 4)], {"dtype": "float16"},
                   lambda x: x.astype(np.float16))],
    "image_to_tensor": [_case([_f32(4, 4, 3, lo=0, hi=255)], {},
                              lambda x: x.transpose(2, 0, 1) / 255.0)],
    "image_normalize": [_case([_f32(3, 4, 4, lo=0, hi=1)],
                              {"mean": (0.5,), "std": (0.5,)},
                              lambda x: (x - 0.5) / 0.5)],
    "image_resize": [_case([_f32(4, 4, 3, lo=0, hi=1)], {"size": (8, 8)},
                           None, consistency=False)],
    "_contrib_div_sqrt_dim": [_case([_f32(3, 16)], {},
                                    lambda x: x / 4.0)],
    "_contrib_fft": [_case([_f32(2, 8)], {}, None, consistency=False)],
    "_contrib_ifft": [_case([_f32(2, 16)], {}, None, consistency=False)],
    "_contrib_count_sketch": [_case(
        [_f32(2, 6), np.array([0, 1, 2, 0, 1, 2], np.float32),
         np.array([1, -1, 1, -1, 1, -1], np.float32)], {"out_dim": 3},
        None, consistency=False)],
    "_scatter_elemwise_div": [_case([_f32(3, 4), _f32(3, 4, lo=1, hi=2)],
                                    {}, lambda a, b: a / b)],
    "_shuffle": [_case([_f32(6, 3)], {}, None, consistency=False)],
    "arange_like": [_case([_f32(2, 3)], {},
                          lambda x: np.arange(6, dtype=np.float32).reshape(
                              2, 3), consistency=False)],
    "add_n": [_case([_f32(3, 4), _f32(3, 4, seed=1), _f32(3, 4, seed=2)],
                    {}, lambda a, b, c: a + b + c)],
}

# zero-tensor-input ops: (attrs, ref)
CREATION = {
    "_zeros": ({"shape": (2, 3)}, lambda: np.zeros((2, 3), np.float32)),
    "_ones": ({"shape": (2, 3)}, lambda: np.ones((2, 3), np.float32)),
    "_full": ({"shape": (2, 3), "value": 1.5},
              lambda: np.full((2, 3), 1.5, np.float32)),
    "_eye": ({"N": 4}, lambda: np.eye(4, dtype=np.float32)),
    "_arange": ({"start": 1, "stop": 7, "step": 2},
                lambda: np.arange(1, 7, 2).astype(np.float32)),
    "_linspace": ({"start": 0, "stop": 1, "num": 5},
                  lambda: np.linspace(0, 1, 5).astype(np.float32)),
}

# ops whose deep coverage lives in a dedicated file (auditable pointers);
# the sweep still asserts the name is registered
ELSEWHERE = {
    "RNN": ("tests/test_rnn.py", "FusedRNNCell"),
    "choose_element_0index": ("tests/test_operator.py",
                              "test_choose_and_fill_element_0index"),
    "fill_element_0index": ("tests/test_operator.py",
                            "test_choose_and_fill_element_0index"),
    "gradientmultiplier": ("tests/test_extended_ops.py",
                           "gradientmultiplier"),
    "IdentityAttachKLSparseReg": ("tests/test_extended_ops.py",
                                  "IdentityAttachKLSparseReg"),
    "_square_sum": ("tests/test_extended_ops.py", "square_sum"),
    "_sparse_adagrad_update": ("tests/test_extended_ops.py",
                               "sparse_adagrad_update"),
    "_sample_exponential": ("tests/test_extended_ops.py",
                            "sample_distribution_families"),
    "_sample_poisson": ("tests/test_extended_ops.py",
                        "sample_distribution_families"),
    "_sample_negative_binomial": ("tests/test_extended_ops.py",
                                  "sample_distribution_families"),
    "_sample_generalized_negative_binomial": (
        "tests/test_extended_ops.py", "sample_distribution_families"),
    "_basic_index": ("tests/test_ndarray.py", "_basic_index"),
    "_subgraph_exec": ("tests/test_subgraph.py", "_subgraph_exec"),
    "Custom": ("tests/test_review_fixes.py", "Custom"),
    "CTCLoss": ("tests/test_operator.py", "CTCLoss"),
    "MultiBoxPrior": ("tests/test_contrib.py", "MultiBoxPrior"),
    "MultiBoxTarget": ("tests/test_review_fixes.py", "MultiBoxTarget"),
    "MultiBoxDetection": ("tests/test_contrib.py", "MultiBoxDetection"),
    "box_iou": ("tests/test_contrib.py", "box_iou"),
    "box_nms": ("tests/test_contrib.py", "box_nms"),
    "ROIAlign": ("tests/test_review_fixes.py", "ROIAlign"),
    "ROIPooling": ("tests/test_extended_ops.py", "ROIPooling"),
    "_contrib_bipartite_matching": ("tests/test_extended_ops.py",
                                    "bipartite_matching"),
    "_contrib_Proposal": ("tests/test_extended_ops.py", "Proposal"),
    "_contrib_PSROIPooling": ("tests/test_extended_ops.py", "PSROIPooling"),
    "_contrib_DeformableConvolution": ("tests/test_extended_ops.py",
                                       "Deformable"),
    "_contrib_SyncBatchNorm": ("tests/test_sync_bn.py", "SyncBatchNorm"),
    "Correlation": ("tests/test_extended_ops.py", "Correlation"),
    "_contrib_flash_attention": ("tests/test_attention.py",
                                 "flash_attention"),
    "_contrib_interleaved_matmul_selfatt_qk": (
        "tests/test_attention.py", "interleaved_matmul_selfatt_qk"),
    "_contrib_interleaved_matmul_selfatt_valatt": (
        "tests/test_attention.py", "interleaved_matmul_selfatt_valatt"),
    "_contrib_quantize": ("tests/test_quantization.py",
                          '"_contrib_quantize"'),
    "_contrib_quantize_v2": ("tests/test_quantization.py", "quantize_v2"),
    "_contrib_dequantize": ("tests/test_quantization.py", "dequantize"),
    "_contrib_requantize": ("tests/test_quantization.py", "requantize"),
    "_contrib_quantized_conv": ("tests/test_quantization.py",
                                "quantized_conv"),
    "_contrib_quantized_fully_connected": (
        "tests/test_quantization.py", "quantized_fully_connected"),
    # optimizer kernels dispatch through the optimizer registry: the
    # no-recompile test drives every listed optimizer end-to-end, so
    # the evidence is the optimizer NAME in its parameterization
    "sgd_update": ("tests/test_optimizer_no_recompile.py", '"sgd"'),
    "sgd_mom_update": ("tests/test_optimizer_no_recompile.py", '"sgd"'),
    "nag_mom_update": ("tests/test_optimizer_no_recompile.py", '"nag"'),
    "adam_update": ("tests/test_optimizer_no_recompile.py", '"adam"'),
    "adamax_update": ("tests/test_optimizer_no_recompile.py", '"adamax"'),
    "nadam_update": ("tests/test_optimizer_no_recompile.py", '"nadam"'),
    "ftml_update": ("tests/test_optimizer_no_recompile.py", '"ftml"'),
    "ftrl_update": ("tests/test_optimizer_no_recompile.py", '"ftrl"'),
    "rmsprop_update": ("tests/test_optimizer_no_recompile.py",
                       '"rmsprop"'),
    "signum_update": ("tests/test_optimizer_no_recompile.py", '"signum"'),

    # lazy sparse kernels dispatch via lazy_update=True + rsp grads
    "_sparse_sgd_update": ("tests/test_sparse.py", "lazy_update=True"),
    "_sparse_adam_update": ("tests/test_sparse.py", "lazy_adam"),
}

# --------------------------------------------------------------------------
# generic executors
# --------------------------------------------------------------------------
def _run(op_name, arrays, attrs):
    """Dispatch through the imperative path (handles PRNG-keyed ops and
    aux-state plumbing exactly like user code)."""
    from mxnet_tpu.ndarray import array
    from mxnet_tpu.ndarray.ndarray import imperative_invoke

    outs = imperative_invoke(op_name, [array(a) for a in arrays],
                             dict(attrs))
    return tuple(o.asnumpy() for o in outs)


def _check_ref(op_name, arrays, attrs, ref, rtol, atol):
    got = _run(op_name, arrays, attrs)
    want = ref(*arrays) if callable(ref) else ref
    want = want if isinstance(want, tuple) else (want,)
    assert len(got) >= len(want), op_name
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=rtol, atol=atol, err_msg=op_name)


def _numeric_grad_check(op_name, x, attrs, eps=1e-3, rtol=0.02, atol=1e-3):
    """jax.grad of sum(op(x)) vs central differences, float32."""
    import jax
    import jax.numpy as jnp

    op = registry.get(op_name)
    fn = op.bind_attrs(op.canonicalize_attrs(attrs))

    def loss(v):
        out = fn(v)
        out = out if isinstance(out, tuple) else (out,)
        return sum(jnp.sum(o) for o in out)

    analytic = np.asarray(jax.grad(loss)(x))
    numeric = np.zeros_like(x)
    flat = x.reshape(-1)
    for i in range(flat.size):
        bump = np.zeros_like(flat)
        bump[i] = eps
        hi = float(loss((flat + bump).reshape(x.shape)))
        lo = float(loss((flat - bump).reshape(x.shape)))
        numeric.reshape(-1)[i] = (hi - lo) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                               err_msg=op_name)


# --------------------------------------------------------------------------
# tests
# --------------------------------------------------------------------------
@pytest.mark.parametrize("name", sorted(UNARY), ids=str)
@pytest.mark.parametrize("dtype", [np.float32, np.float16], ids=["f32", "f16"])
def test_unary_forward(name, dtype):
    fn, lo, hi, _ = UNARY[name]
    x = RS(0).uniform(lo, hi, (3, 4)).astype(dtype)
    if name == "erfinv":  # inverse pair identity instead of a numpy ref
        y = np.asarray(_run("erfinv", [x.astype(np.float32)], {})[0])
        np.testing.assert_allclose(_erf(y), x.astype(np.float32),
                                   rtol=2e-3, atol=2e-3)
        return
    got = np.asarray(_run(name, [x], {})[0])
    want = fn(x.astype(np.float64))
    tol = 2e-2 if dtype == np.float16 else 2e-5
    np.testing.assert_allclose(got.astype(np.float64), want, rtol=tol,
                               atol=tol, err_msg=name)


@pytest.mark.parametrize("name", sorted(n for n, s in UNARY.items()
                                        if s[3]), ids=str)
def test_unary_gradient(name):
    if name == "erfinv":
        pytest.skip("covered by the inverse-pair identity")
    _, lo, hi, _ = UNARY[name]
    x = RS(1).uniform(lo, hi, (2, 3)).astype(np.float32)
    _numeric_grad_check(name, x, {})


@pytest.mark.parametrize("name", sorted(BINARY), ids=str)
@pytest.mark.parametrize("dtype", [np.float32, np.float16], ids=["f32", "f16"])
def test_binary_forward(name, dtype):
    fn, lo, hi = BINARY[name]
    a = RS(0).uniform(lo, hi, (3, 4)).astype(dtype)
    shape_b = (3, 4) if name.startswith("elemwise") else (1, 4)
    b = RS(1).uniform(lo, hi, shape_b).astype(dtype)
    got = np.asarray(_run(name, [a, b], {})[0])
    want = fn(a.astype(np.float64), b.astype(np.float64))
    tol = 5e-2 if dtype == np.float16 else 1e-5
    np.testing.assert_allclose(got.astype(np.float64), want, rtol=tol,
                               atol=tol, err_msg=name)


@pytest.mark.parametrize("name", ["elemwise_add", "elemwise_mul",
                                  "broadcast_add", "broadcast_mul",
                                  "elemwise_sub", "broadcast_div"], ids=str)
def test_binary_consistency(name):
    a = _f32(8, 4)
    b = _f32(8, 4, seed=1, lo=0.5, hi=2) if name.startswith("elemwise") \
        else _f32(1, 4, seed=1, lo=0.5, hi=2)
    check_op_consistency(name, [a, b])


@pytest.mark.parametrize("name", sorted(SCALAR), ids=str)
@pytest.mark.parametrize("dtype", [np.float32, np.float16], ids=["f32", "f16"])
def test_scalar_forward(name, dtype):
    fn, lo, hi, s = SCALAR[name]
    x = RS(0).uniform(lo, hi, (3, 4)).astype(dtype)
    got = np.asarray(_run(name, [x], {"scalar": s})[0])
    want = fn(x.astype(np.float64), s)
    tol = 5e-2 if dtype == np.float16 else 1e-5
    np.testing.assert_allclose(got.astype(np.float64), want, rtol=tol,
                               atol=tol, err_msg=name)


@pytest.mark.parametrize("name", sorted(REDUCE), ids=str)
@pytest.mark.parametrize("dtype", [np.float32, np.float16], ids=["f32", "f16"])
def test_reduce_forward(name, dtype):
    fn, variants = REDUCE[name]
    x = RS(0).uniform(0.5, 1.5, (2, 3, 4)).astype(dtype)
    for attrs in variants:
        got = np.asarray(_run(name, [x], attrs)[0])
        kw = {}
        if "axis" in attrs:
            ax = attrs["axis"]
            kw["axis"] = tuple(ax) if isinstance(ax, (tuple, list)) else ax
        if attrs.get("keepdims"):
            kw["keepdims"] = True
        want = fn(x.astype(np.float64), **kw)
        tol = 5e-2 if dtype == np.float16 else 1e-4
        np.testing.assert_allclose(np.squeeze(got.astype(np.float64)),
                                   np.squeeze(want), rtol=tol, atol=tol,
                                   err_msg="%s %r" % (name, attrs))


@pytest.mark.parametrize("name", ["sum", "mean", "max"], ids=str)
def test_reduce_consistency(name):
    check_op_consistency(name, [_f32(8, 3, 4)], {"axis": 1})


@pytest.mark.parametrize("name", sorted(EXPLICIT), ids=str)
def test_explicit_forward(name):
    for case in EXPLICIT[name]:
        arrays, attrs, ref = case["inputs"], case["attrs"], case["ref"]
        if ref is not None:
            _check_ref(name, arrays, attrs, ref, case["rtol"], case["atol"])
        else:
            outs = _run(name, arrays, attrs)
            for o in outs:
                assert np.all(np.isfinite(np.asarray(o, dtype=np.float64))), \
                    name
        if case["consistency"] and name not in ("Dropout",):
            check_op_consistency(name, arrays, attrs,
                                 rtol=max(case["rtol"], 1e-3),
                                 atol=max(case["atol"], 1e-3))


@pytest.mark.parametrize("name", sorted(CREATION), ids=str)
def test_creation_ops(name):
    attrs, ref = CREATION[name]
    got = np.asarray(_run(name, [], attrs)[0])
    np.testing.assert_allclose(got, ref(), err_msg=name)


# nn attr matrix: the stride/pad/dilate x shape grid the reference's
# test_operator.py covers for convolution (vs a direct lax reference is
# circular, so check against torch-free explicit im2col)
def _conv2d_ref(x, w, b, stride, pad, dilate):
    import itertools

    n, cin, hh, ww = x.shape
    cout, _, kh, kw = w.shape
    dh, dw = dilate
    eff_kh, eff_kw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    oh = (hh + 2 * pad[0] - eff_kh) // stride[0] + 1
    ow = (ww + 2 * pad[1] - eff_kw) // stride[1] + 1
    out = np.zeros((n, cout, oh, ow), np.float64)
    for i, j in itertools.product(range(oh), range(ow)):
        patch = xp[:, :, i * stride[0]:i * stride[0] + eff_kh:dh,
                   j * stride[1]:j * stride[1] + eff_kw:dw]
        out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out + b.reshape(1, -1, 1, 1)


@pytest.mark.parametrize("stride", [(1, 1), (2, 2), (2, 1)])
@pytest.mark.parametrize("pad", [(0, 0), (1, 1)])
@pytest.mark.parametrize("dilate", [(1, 1), (2, 2)])
def test_conv_attr_matrix(stride, pad, dilate):
    x = _f32(2, 3, 7, 7)
    w = _f32(4, 3, 3, 3, seed=1)
    b = _f32(4, seed=2)
    got = np.asarray(_run("Convolution", [x, w, b],
                          {"kernel": (3, 3), "num_filter": 4,
                           "stride": stride, "pad": pad,
                           "dilate": dilate})[0])
    want = _conv2d_ref(x.astype(np.float64), w.astype(np.float64),
                       b.astype(np.float64), stride, pad, dilate)
    np.testing.assert_allclose(got.astype(np.float64), want, rtol=1e-3,
                               atol=1e-3)


@pytest.mark.parametrize("pool_type", ["max", "avg"])
@pytest.mark.parametrize("stride", [(1, 1), (2, 2)])
@pytest.mark.parametrize("pad", [(0, 0), (1, 1)])
def test_pool_attr_matrix(pool_type, stride, pad):
    x = _f32(2, 3, 6, 6)
    got = np.asarray(_run("Pooling", [x],
                          {"kernel": (3, 3), "pool_type": pool_type,
                           "stride": stride, "pad": pad})[0])
    # reference via explicit window walk
    xp = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])),
                constant_values=-np.inf if pool_type == "max" else 0)
    hh = xp.shape[2]
    oh = (hh - 3) // stride[0] + 1
    want = np.zeros((2, 3, oh, oh), np.float64)
    counts = np.zeros_like(want)
    for i in range(oh):
        for j in range(oh):
            win = xp[:, :, i * stride[0]:i * stride[0] + 3,
                     j * stride[1]:j * stride[1] + 3]
            if pool_type == "max":
                want[:, :, i, j] = win.max((2, 3))
            else:
                # count_include_pad=True matches the reference default
                want[:, :, i, j] = win.sum((2, 3)) / 9.0
    np.testing.assert_allclose(got.astype(np.float64), want, rtol=1e-4,
                               atol=1e-4)


def test_conv_consistency_sharded():
    x = _f32(8, 3, 6, 6)
    w = _f32(4, 3, 3, 3, seed=1)
    b = _f32(4, seed=2)
    check_op_consistency("Convolution", [x, w, b],
                         {"kernel": (3, 3), "num_filter": 4, "pad": (1, 1)},
                         rtol=1e-3, atol=1e-3)


def test_fc_consistency_sharded():
    check_op_consistency("FullyConnected",
                         [_f32(8, 5), _f32(6, 5, seed=1), _f32(6, seed=2)],
                         {"num_hidden": 6}, rtol=1e-3, atol=1e-3)


# ------------------------------------------------------------- random tier --
# op -> (attrs, check(out)) — PRNG-keyed ops get statistical sanity
# checks through the imperative path (which threads the key)
RANDOM = {
    "_random_uniform": ({"low": 2.0, "high": 5.0, "shape": (4000,)},
                        lambda o: (2.0 <= o).all() and (o < 5.0).all()
                        and abs(o.mean() - 3.5) < 0.2),
    "_random_normal": ({"loc": 1.0, "scale": 2.0, "shape": (4000,)},
                       lambda o: abs(o.mean() - 1.0) < 0.25
                       and abs(o.std() - 2.0) < 0.25),
    "_random_gamma": ({"alpha": 3.0, "beta": 2.0, "shape": (4000,)},
                      lambda o: (o > 0).all()
                      and abs(o.mean() - 6.0) < 0.8),
    "_random_exponential": ({"lam": 2.0, "shape": (4000,)},
                            lambda o: (o >= 0).all()
                            and abs(o.mean() - 0.5) < 0.1),
    "_random_poisson": ({"lam": 4.0, "shape": (4000,)},
                        lambda o: (o >= 0).all()
                        and abs(o.mean() - 4.0) < 0.5),
    "_random_negative_binomial": ({"k": 5, "p": 0.5, "shape": (4000,)},
                                  lambda o: (o >= 0).all()
                                  and abs(o.mean() - 5.0) < 1.0),
    "_random_generalized_negative_binomial": (
        {"mu": 3.0, "alpha": 0.2, "shape": (4000,)},
        lambda o: (o >= 0).all() and abs(o.mean() - 3.0) < 0.8),
    "_random_randint": ({"low": 3, "high": 9, "shape": (4000,)},
                        lambda o: (o >= 3).all() and (o < 9).all()),
}


@pytest.mark.parametrize("name", sorted(RANDOM), ids=str)
def test_random_ops_statistics(name):
    attrs, check = RANDOM[name]
    out = np.asarray(_run(name, [], attrs)[0], dtype=np.float64)
    assert check(out), "%s: statistics off (mean %.3f)" % (name, out.mean())
    # two invocations draw different streams
    out2 = np.asarray(_run(name, [], attrs)[0], dtype=np.float64)
    assert not np.array_equal(out, out2)


def test_sample_ops():
    """Per-row parameterized samplers (reference: random/sample_op.cc)."""
    low = np.array([0.0, 10.0], np.float32)
    high = np.array([1.0, 20.0], np.float32)
    out = np.asarray(_run("_sample_uniform", [low, high],
                          {"shape": (500,)})[0])
    assert out.shape == (2, 500)
    assert (out[0] >= 0).all() and (out[0] < 1).all()
    assert (out[1] >= 10).all() and (out[1] < 20).all()

    mu = np.array([0.0, 50.0], np.float32)
    sd = np.array([1.0, 5.0], np.float32)
    out = np.asarray(_run("_sample_normal", [mu, sd], {"shape": (800,)})[0])
    assert abs(out[0].mean()) < 0.2 and abs(out[1].mean() - 50) < 1.0

    a = np.array([2.0, 9.0], np.float32)
    b = np.array([1.0, 0.5], np.float32)
    out = np.asarray(_run("_sample_gamma", [a, b], {"shape": (800,)})[0])
    assert abs(out[0].mean() - 2.0) < 0.5 and abs(out[1].mean() - 4.5) < 0.8

    probs = np.array([[0.0, 0.0, 1.0], [1.0, 0.0, 0.0]], np.float32)
    out = np.asarray(_run("_sample_multinomial", [probs],
                          {"shape": (50,)})[0])
    assert (out[0] == 2).all() and (out[1] == 0).all()

    out = np.asarray(_run("_sample_unique_zipfian", [],
                          {"range_max": 1000, "shape": (1, 64)})[0])
    assert (out >= 0).all() and (out < 1000).all()


# -------------------------------------------- optimizer kernels, directly --
def test_rmspropalex_update():
    rs = RS(0)
    w, g_st, d = (rs.randn(4, 3).astype(np.float32) for _ in range(3))
    n = np.abs(rs.randn(4, 3)).astype(np.float32) + 1.0  # valid E[g^2]
    grad = rs.randn(4, 3).astype(np.float32) * 0.3
    outs = _run("rmspropalex_update", [w, grad, n, g_st, d],
                {"lr": 0.01, "gamma1": 0.95, "gamma2": 0.9})
    new_n = 0.05 * grad ** 2 + 0.95 * n
    new_g = 0.05 * grad + 0.95 * g_st
    new_d = 0.9 * d - 0.01 * grad / np.sqrt(new_n - new_g ** 2 + 1e-8)
    np.testing.assert_allclose(np.asarray(outs[0]), w + new_d, rtol=1e-4,
                               atol=1e-5)


def test_mp_sgd_kernels():
    rs = RS(1)
    w32 = rs.randn(4, 3).astype(np.float32)
    w16 = w32.astype(np.float16)
    g16 = rs.randn(4, 3).astype(np.float16)
    new_w, new_w32 = _run("mp_sgd_update", [w16, g16, w32], {"lr": 0.1})
    np.testing.assert_allclose(np.asarray(new_w32),
                               w32 - 0.1 * g16.astype(np.float32),
                               rtol=1e-3, atol=1e-3)
    assert np.asarray(new_w).dtype == np.float16
    mom = np.zeros_like(w32)
    outs = _run("mp_sgd_mom_update", [w16, g16, mom, w32],
                {"lr": 0.1, "momentum": 0.9})
    np.testing.assert_allclose(np.asarray(outs[2]),
                               w32 - 0.1 * g16.astype(np.float32),
                               rtol=1e-3, atol=1e-3)


def test_multi_tensor_kernels():
    """Aggregated multi-weight updates (reference: optimizer_op.cc
    multi_sgd*, MXNET_OPTIMIZER_AGGREGATION_SIZE)."""
    rs = RS(2)
    w1, g1 = rs.randn(3, 2).astype(np.float32), rs.randn(3, 2).astype(np.float32)
    w2, g2 = rs.randn(5).astype(np.float32), rs.randn(5).astype(np.float32)
    outs = _run("multi_sgd_update", [w1, g1, w2, g2],
                {"lrs": (0.1, 0.2), "wds": (0.0, 0.0), "num_weights": 2})
    np.testing.assert_allclose(np.asarray(outs[0]), w1 - 0.1 * g1,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), w2 - 0.2 * g2,
                               rtol=1e-5)
    m1, m2 = np.zeros_like(w1), np.zeros_like(w2)
    outs = _run("multi_sgd_mom_update", [w1, g1, m1, w2, g2, m2],
                {"lrs": (0.1, 0.2), "wds": (0.0, 0.0), "momentum": 0.9,
                 "num_weights": 2})
    np.testing.assert_allclose(np.asarray(outs[0]), w1 - 0.1 * g1,
                               rtol=1e-5)
    # multi-precision twins
    w1h = w1.astype(np.float16)
    outs = _run("multi_mp_sgd_update", [w1h, g1.astype(np.float16), w1],
                {"lrs": (0.5,), "wds": (0.0,), "num_weights": 1})
    np.testing.assert_allclose(np.asarray(outs[1]), w1 - 0.5 * g1,
                               rtol=1e-2, atol=1e-2)
    mom = np.zeros_like(w1)
    outs = _run("multi_mp_sgd_mom_update",
                [w1h, g1.astype(np.float16), mom, w1],
                {"lrs": (0.5,), "wds": (0.0,), "momentum": 0.0,
                 "num_weights": 1})
    np.testing.assert_allclose(np.asarray(outs[2]), w1 - 0.5 * g1,
                               rtol=1e-2, atol=1e-2)


def test_group_adagrad_update():
    rs = RS(3)
    w = rs.randn(4, 3).astype(np.float32)
    g = rs.randn(4, 3).astype(np.float32)
    h = np.abs(rs.randn(4).astype(np.float32))
    outs = _run("group_adagrad_update", [w, g, h], {"lr": 0.1})
    new_h = h + (g ** 2).mean(axis=1)
    scale = 0.1 / (np.sqrt(new_h) + 1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), new_h, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[0]),
                               w - scale[:, None] * g, rtol=1e-4,
                               atol=1e-5)


def test_contrib_mp_adamw_update():
    rs = RS(4)
    w32 = rs.randn(3, 2).astype(np.float32)
    w16 = w32.astype(np.float16)
    g = rs.randn(3, 2).astype(np.float16)
    mean = np.zeros_like(w32)
    var = np.zeros_like(w32)
    rescale = np.array([1.0], np.float32)
    outs = _run("_contrib_mp_adamw_update",
                [w16, g, mean, var, w32, rescale],
                {"lr": 0.01, "eta": 1.0, "wd": 0.0})
    assert len(outs) == 4
    assert np.isfinite(np.asarray(outs[0], dtype=np.float64)).all()


def test_sparse_sgd_mom_update_kernel():
    rs = RS(5)
    w = rs.randn(10, 4).astype(np.float32)
    mom = np.zeros_like(w)
    idx = np.array([1, 7], np.int32)
    gval = rs.randn(2, 4).astype(np.float32)
    outs = _run("_sparse_sgd_mom_update", [w, gval, idx, mom],
                {"lr": 0.1, "momentum": 0.9})
    new_w = np.asarray(outs[0])
    np.testing.assert_allclose(new_w[idx], w[idx] - 0.1 * gval, rtol=1e-5)
    untouched = np.setdiff1d(np.arange(10), idx)
    np.testing.assert_array_equal(new_w[untouched], w[untouched])


# ----------------------------------------------- sampler-grid op family ----
def test_bilinear_sampler_identity_grid():
    """An identity grid reproduces the input (reference:
    bilinear_sampler.cc)."""
    x = _f32(1, 2, 5, 5)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                         indexing="ij")
    grid = np.stack([xs, ys])[None].astype(np.float32)  # (1, 2, 5, 5)
    out = np.asarray(_run("BilinearSampler", [x, grid], {})[0])
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-5)


def test_spatial_transformer_identity():
    """Identity affine theta keeps the image (reference:
    spatial_transformer.cc)."""
    x = _f32(1, 2, 6, 6)
    theta = np.array([[1.0, 0.0, 0.0, 0.0, 1.0, 0.0]], np.float32)
    out = np.asarray(_run("SpatialTransformer", [x, theta],
                          {"target_shape": (6, 6),
                           "transform_type": "affine"})[0])
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-4)


def test_quantized_pool_concat_flatten():
    """INT8 data ops carry their ranges through (reference:
    quantized_pooling.cc / quantized_concat.cc / quantized_flatten.cc)."""
    rs = RS(6)
    q = rs.randint(-127, 128, (1, 2, 4, 4)).astype(np.int8)
    mn = np.array([-1.0], np.float32)
    mx_ = np.array([1.0], np.float32)
    out, omin, omax = _run("_contrib_quantized_pooling", [q, mn, mx_],
                           {"kernel": (2, 2), "stride": (2, 2),
                            "pool_type": "max"})
    assert np.asarray(out).shape == (1, 2, 2, 2)
    assert np.asarray(omin).item() == -1.0 and np.asarray(omax).item() == 1.0

    out, omin, omax = _run("_contrib_quantized_flatten", [q, mn, mx_], {})
    assert np.asarray(out).shape == (1, 32)

    q2 = rs.randint(-127, 128, (1, 2, 4, 4)).astype(np.int8)
    out, omin, omax = _run(
        "_contrib_quantized_concat",
        [q, q2, mn, np.array([-2.0], np.float32), mx_,
         np.array([2.0], np.float32)], {"dim": 1, "num_args": 2})
    assert np.asarray(out).shape == (1, 4, 4, 4)
    assert np.asarray(omax).item() == 2.0


def test_signsgd_and_adamw_kernels():
    rs = RS(7)
    w = rs.randn(4, 3).astype(np.float32)
    g = rs.randn(4, 3).astype(np.float32)
    out = _run("signsgd_update", [w, g], {"lr": 0.1})[0]
    np.testing.assert_allclose(np.asarray(out), w - 0.1 * np.sign(g),
                               rtol=1e-6)
    mean = np.zeros_like(w)
    var = np.zeros_like(w)
    outs = _run("adamw_update", [w, g, mean, var],
                {"lr": 0.01, "eta": 1.0, "wd": 0.1})
    new_mean = 0.1 * g
    new_var = 0.001 * g ** 2
    want = w - 1.0 * (0.01 * new_mean / (np.sqrt(new_var) + 1e-8)
                      + 0.1 * w)
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-4,
                               atol=1e-5)
    rescale = np.array([1.0], np.float32)
    outs = _run("_contrib_adamw_update", [w, g, mean, var, rescale],
                {"lr": 0.01, "eta": 1.0, "wd": 0.1})
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-4,
                               atol=1e-5)


def test_where_nd_unsupported():
    """where_nd's single-arg form has a data-dependent output shape —
    deliberately unsupported on TPU, with a clear redirect."""
    with pytest.raises(Exception, match="boolean_mask"):
        apply_op("where_nd", (_f32(3, 4) > 0).astype(np.float32))


SPECIAL = {"where_nd"}


# --------------------------------------------------------------------------
# coverage gate
# --------------------------------------------------------------------------
def test_registry_fully_covered():
    """Every registered op must be claimed by some tier; a new op with
    no test fails here."""
    direct = {"signsgd_update", "adamw_update", "_contrib_adamw_update",
              "rmspropalex_update", "adagrad_update", "adadelta_update",
              "mp_sgd_update", "mp_sgd_mom_update",
              "multi_sgd_update", "multi_sgd_mom_update",
              "multi_mp_sgd_update", "multi_mp_sgd_mom_update",
              "group_adagrad_update", "_contrib_mp_adamw_update",
              "_sparse_sgd_mom_update", "BilinearSampler",
              "SpatialTransformer", "_contrib_quantized_pooling",
              "_contrib_quantized_concat", "_contrib_quantized_flatten",
              "_sample_uniform", "_sample_normal", "_sample_gamma",
              "_sample_multinomial", "_sample_unique_zipfian"}
    covered = (set(UNARY) | set(BINARY) | set(SCALAR) | set(REDUCE)
               | set(EXPLICIT) | set(CREATION) | set(ELSEWHERE) | SPECIAL
               | set(RANDOM) | direct)
    all_ops = set(registry.list_ops())
    missing = sorted(all_ops - covered)
    assert not missing, "ops with no test coverage: %s" % missing
    phantom = sorted((set(UNARY) | set(EXPLICIT)) - all_ops)
    assert not phantom, "spec entries for unregistered ops: %s" % phantom
    # ELSEWHERE pointers must name real files AND actually mention the
    # op (by canonical name or a registered alias) — a pointer to a file
    # that never exercises the op is a bogus coverage claim
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    for op, (path, evidence) in ELSEWHERE.items():
        full = os.path.join(os.path.dirname(here), path)
        assert os.path.exists(full), "%s points at missing %s" % (op, path)
        body = open(full).read()
        assert evidence in body, \
            "%s claims coverage in %s but evidence %r is absent" \
            % (op, path, evidence)


def test_conv_nhwc_layout_matches_nchw():
    """layout='NHWC' (channel-last data, OHWI weight — the reference's
    NHWC weight convention) must equal the NCHW result transposed
    (BENCH_NOTES layout experiment: ~+7% on the conv trunk on TPU)."""
    x = _f32(2, 3, 6, 6)
    w = _f32(4, 3, 3, 3, seed=1)
    b = _f32(4, seed=2)
    want = np.asarray(_run("Convolution", [x, w, b],
                           {"kernel": (3, 3), "num_filter": 4,
                            "pad": (1, 1)})[0])
    got = np.asarray(_run("Convolution",
                          [x.transpose(0, 2, 3, 1),
                           w.transpose(0, 2, 3, 1), b],
                          {"kernel": (3, 3), "num_filter": 4,
                           "pad": (1, 1), "layout": "NHWC"})[0])
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want,
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("pool_type", ["max", "avg"])
def test_pool_nhwc_layout_matches_nchw(pool_type):
    """Pooling layout='NHWC' equals the NCHW result transposed —
    completes the channel-last op pair with Convolution."""
    x = _f32(2, 3, 6, 6)
    attrs = {"kernel": (2, 2), "stride": (2, 2), "pool_type": pool_type}
    want = np.asarray(_run("Pooling", [x], attrs)[0])
    got = np.asarray(_run("Pooling", [x.transpose(0, 2, 3, 1)],
                          {**attrs, "layout": "NHWC"})[0])
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want,
                               rtol=1e-5, atol=1e-6)
    # global pooling too
    wantg = np.asarray(_run("Pooling", [x],
                            {"pool_type": pool_type,
                             "global_pool": True})[0])
    gotg = np.asarray(_run("Pooling", [x.transpose(0, 2, 3, 1)],
                           {"pool_type": pool_type, "global_pool": True,
                            "layout": "NHWC"})[0])
    np.testing.assert_allclose(gotg.transpose(0, 3, 1, 2), wantg,
                               rtol=1e-5, atol=1e-6)
