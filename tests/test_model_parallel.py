"""Model-parallel and multi-device execution tests over the 8-virtual-
device CPU mesh.

Reference: tests/python/unittest/test_model_parallel.py (group2ctx
placement), test_multi_device_exec.py, tests/nightly/multi_lenet.py
(multi-device convergence).  TPU-native form: manual ctx-group placement
becomes per-parameter sharding specs over a Mesh (GSPMD inserts the
cross-device collectives the reference made explicit with
CrossDeviceCopy / KVStore).
"""

import jax as _jax
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import ndarray as nd
from mxnet_tpu.parallel.gluon_step import GluonTrainStep
from mxnet_tpu.parallel.mesh import create_mesh
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 8)))
    return net


def _batch(seed=0, n=16):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    y = rs.randint(0, 4, (n,)).astype(np.int32)
    return x, y


def _train(net, mesh, steps=4, **kw):
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=0.1, momentum=0.9, **kw)
    losses = []
    for i in range(steps):
        x, y = _batch(seed=i)
        losses.append(float(np.asarray(step(x, y))))
    return step, losses


def _weights(step):
    return [np.asarray(v) for v in step.train_vals]


def test_data_parallel_matches_single_device():
    """dp=8 must be numerically identical to dp=1 (same global batch,
    grads all-reduced by GSPMD psum; reference: multi_lenet.py checks
    multi-GPU == single-GPU)."""
    net = _mlp()
    step1, losses1 = _train(net, create_mesh({"dp": 1}, devices=_jax.devices()[:1]))
    # fresh identical weights for the sharded run
    net2 = _mlp()
    for p, q in zip(net.collect_params().values(),
                    net2.collect_params().values()):
        p.data().copyto(q.data())
    step8, losses8 = _train(net2, create_mesh({"dp": 8}))
    assert_almost_equal(np.array(losses1), np.array(losses8),
                        rtol=1e-4, atol=1e-5)
    for w1, w8 in zip(_weights(step1), _weights(step8)):
        assert_almost_equal(w1, w8, rtol=1e-4, atol=1e-5)


def test_tensor_parallel_matches_replicated():
    """Per-parameter sharding (model parallel) must not change the math:
    shard every Dense weight's output dim over 'tp' (reference analog:
    group2ctx placing layers on different devices,
    test_model_parallel.py)."""
    from jax.sharding import PartitionSpec as P

    net = _mlp()
    stepR, lossesR = _train(net, create_mesh({"dp": 1}, devices=_jax.devices()[:1]))

    net2 = _mlp()
    for p, q in zip(net.collect_params().values(),
                    net2.collect_params().values()):
        p.data().copyto(q.data())

    def spec_fn(name, shape):
        if name.endswith("weight") and len(shape) == 2 and shape[0] % 8 == 0:
            return P("tp", None)  # row-shard the (out, in) weight
        return P()

    mesh = create_mesh({"tp": 8})
    stepT, lossesT = _train(net2, mesh, param_spec_fn=spec_fn,
                            data_spec=P())
    assert_almost_equal(np.array(lossesR), np.array(lossesT),
                        rtol=1e-4, atol=1e-5)
    for wR, wT in zip(_weights(stepR), _weights(stepT)):
        assert_almost_equal(wR, np.asarray(wT), rtol=1e-4, atol=1e-5)


def test_tp_sharding_is_actually_distributed():
    """The tp run must actually place weight shards on distinct devices
    (not silently replicate)."""
    from jax.sharding import PartitionSpec as P

    net = _mlp()
    mesh = create_mesh({"tp": 8})

    def spec_fn(name, shape):
        if name.endswith("weight") and len(shape) == 2 and shape[0] % 8 == 0:
            return P("tp", None)
        return P()

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, param_spec_fn=spec_fn,
                          data_spec=P())
    sharded = [v for v in step.train_vals
               if len(v.sharding.device_set) == 8]
    assert len(sharded) >= 2, "expected ≥2 weights sharded over 8 devices"
    x, y = _batch()
    float(np.asarray(step(x, y)))  # executes with the distributed layout


def test_module_multi_device_exec():
    """Module API over a list of contexts (reference:
    test_multi_device_exec.py — batch split across ctxs by
    DataParallelExecutorGroup)."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, context=[mx.cpu(0), mx.cpu(1)],
                        label_names=["softmax_label"])
    x, y = _batch(n=8)
    mod.bind(data_shapes=[("data", (8, 8))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    from mxnet_tpu.io import NDArrayIter

    it = NDArrayIter(x, y, batch_size=8, label_name="softmax_label")
    metric = mx.metric.Accuracy()
    first = None
    for _ in range(15):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
    _, acc = metric.get()
    assert acc > 0.5, acc
