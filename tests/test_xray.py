"""PR 15: fused-step X-ray (mxnet_tpu/xray.py) + hang forensics
(mxnet_tpu/stackdump.py).

Pins the acceptance criteria:

- CONSERVATION: on a compiled MLP+Adam step (and a conv model) the
  per-scope flops/bytes plus the explicit ``unattributed`` remainder
  sum EXACTLY to the whole-program ``cost_analysis`` totals, and the
  table names per-block forward/backward scopes, the loss, and the
  fused optimizer region;
- the three perf-doctor x-ray rules (scope-dominated,
  zero-collective-share, optimizer-share) fire on dumps built to
  violate them and stay quiet on healthy ones, and emit through the
  ``--format github`` ``::error``/``::notice`` path;
- ``compare()`` carries x-ray scope shares as oriented rows — flat on
  identical dumps, and a scope existing on only one side lands in
  ``notes`` (a topology change), never in the verdict;
- ``tools/diagnose.py --xray`` renders the table from a diag dump;
- SIGUSR2 / ``dump_stacks`` writes an atomic, rank-suffixed all-thread
  stack dump through ``checkpoint.atomic_write``.
"""

import copy
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import (compiled_step, gluon, metrics_timeline,
                       perfdoctor, runtime_stats, stackdump, xray)
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    runtime_stats.reset()
    xray.enable()
    yield
    runtime_stats.reset()
    xray.enable()


def _make_mlp(seed=7):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((2, 8)))
    return net


def _make_conv(seed=9):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, layout="NHWC"))
        net.add(nn.GlobalAvgPool2D(layout="NHWC"))
        net.add(nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((1, 8, 8, 3)))
    return net


def _run_compiled(net, x, y, opt="adam", opt_args=None):
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), opt,
                       opt_args or {"learning_rate": 0.01})
    cs = tr.compile(net, loss_fn)
    cs.step(mx.nd.array(x), mx.nd.array(y))
    return cs


def _newest_table(label="compiled_step"):
    # NB: the caller must still hold its CompiledStep — the tables
    # live on the weak registry's cache entries and die with it
    programs = (compiled_step.xray_snapshot() or {}).get("programs", [])
    # earlier suites' CompiledSteps can linger in the weak registry:
    # filter by label and take the newest (highest seq)
    programs = [t for t in programs if t.get("label") == label]
    assert programs, "no x-ray table captured for label %r" % label
    return programs[-1]


def _assert_conserved(t):
    """sum(scopes) + unattributed == totals, for both metrics."""
    scopes = t["scopes"]
    for metric, ckey in (("flops", "flops"), ("bytes", "bytes_accessed")):
        total = t["totals"][ckey]
        attributed = sum(rec[metric] for rec in scopes.values())
        attributed += t["unattributed"][metric]
        assert attributed == pytest.approx(total, rel=1e-9), \
            "%s: scopes+unattributed %.1f != program total %.1f" \
            % (metric, attributed, total)
        assert total > 0


# ------------------------------------------------- conservation contract


def test_conservation_mlp_adam(monkeypatch):
    """ACCEPTANCE: per-block forward/backward scopes + loss + optimizer
    are named, and their flops/bytes with the explicit unattributed
    remainder sum to the whole-program cost_analysis totals."""
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    rs = np.random.RandomState(0)
    net = _make_mlp()
    cs = _run_compiled(net, rs.rand(2, 8).astype(np.float32),
                       rs.randint(0, 4, (2,)).astype(np.float32))
    t = _newest_table("compiled_step")
    scopes = t["scopes"]
    # per-block forward AND backward scopes, named by block path
    assert any(s.startswith("forward/") and s.endswith("dense0")
               for s in scopes), sorted(scopes)
    assert any(s.startswith("backward/") and "dense" in s
               for s in scopes), sorted(scopes)
    assert any("loss" in s for s in scopes), sorted(scopes)
    assert "optimizer" in scopes, sorted(scopes)
    # Adam's state update moves real bytes
    assert scopes["optimizer"]["bytes"] > 0
    assert t["instructions"] > 0
    # truth-anchored: cost capture was active, so neither metric fell
    # back to estimate-only totals
    assert t["estimated"] == []
    _assert_conserved(t)
    # shares are consistent with the raw numbers
    for rec in list(scopes.values()) + [t["unattributed"]]:
        assert rec["bytes_share"] == pytest.approx(
            rec["bytes"] / t["totals"]["bytes_accessed"], rel=1e-9)


def test_conservation_conv_model(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    rs = np.random.RandomState(1)
    net = _make_conv()
    cs = _run_compiled(net, rs.rand(2, 8, 8, 3).astype(np.float32),
                       rs.randint(0, 4, (2,)).astype(np.float32),
                       opt="sgd", opt_args={"learning_rate": 0.1})
    t = _newest_table("compiled_step")
    scopes = t["scopes"]
    assert any("conv2d0" in s for s in scopes), sorted(scopes)
    assert "optimizer" in scopes
    _assert_conserved(t)


def test_conservation_zero_step(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    rs = np.random.RandomState(2)
    net = _make_mlp()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01})
    zs = compiled_step.ZeroCompiledStep(net, loss_fn, tr)
    # batch 8: conftest forces 8 virtual devices, the zero path shards
    # the batch across them
    zs.step(mx.nd.array(rs.rand(8, 8).astype(np.float32)),
            mx.nd.array(rs.randint(0, 4, (8,)).astype(np.float32)))
    t = _newest_table("zero_step")
    assert t["zero"] is True
    assert "optimizer" in t["scopes"], sorted(t["scopes"])
    _assert_conserved(t)


def test_disabled_xray_captures_nothing(monkeypatch):
    """With annotation disabled the compile sites skip attribution —
    the entry's table stays None (the single-dict-read off path)."""
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    xray.disable()
    rs = np.random.RandomState(3)
    net = _make_mlp()
    cs = _run_compiled(net, rs.rand(2, 8).astype(np.float32),
                       rs.randint(0, 4, (2,)).astype(np.float32))
    assert all(e.xray is None for e in cs._cache.values())


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_XRAY", "0")
    xray._activate_from_env()
    assert not xray.is_enabled()
    assert xray.scope("anything") is xray._NULL
    monkeypatch.setenv("MXNET_TPU_XRAY", "1")
    xray._activate_from_env()
    assert xray.is_enabled()


# ------------------------------------------------------- canonical_scope


def test_canonical_scope_paths():
    cs = xray.canonical_scope
    # forward path: jit(...) parts and the trailing primitive drop
    assert cs("jit(step)/jit(main)/hybridsequential0/dense0/dot_general") \
        == "forward/hybridsequential0/dense0"
    # jvp stays forward; transpose anywhere flags backward
    assert cs("jit(step)/jvp(hybridsequential0/dense0)/dot_general") \
        == "forward/hybridsequential0/dense0"
    assert cs("jit(step)/transpose(jvp(hybridsequential0/dense0))/"
              "dot_general") == "backward/hybridsequential0/dense0"
    # the grad wrapper scope is a direction marker, not a path part
    assert cs("jit(step)/%s/loss/reduce" % xray.GRAD_MARKER) \
        == "forward/loss"
    # plain step regions get no direction prefix
    assert cs("jit(step)/optimizer/add") == "optimizer"
    assert cs("jit(step)/transpose(zero_allgather/all_gather)") \
        == "zero_allgather"
    # a bare primitive carries no user scope
    assert cs("jit(step)/jit(main)/add") is None
    assert cs("") is None


# --------------------------------------------------- perf-doctor rules


def _rec(flops=0.0, bytes_=0.0, coll=0.0, tot_f=1.0, tot_b=1.0):
    return {"flops": flops, "bytes": bytes_, "output_bytes": bytes_ / 2,
            "collective_bytes": coll, "instructions": 1,
            "flops_share": flops / tot_f if tot_f else 0.0,
            "bytes_share": bytes_ / tot_b if tot_b else 0.0}


def _dump(scope_spec, zero=False, label="compiled_step", seq=1,
          counters=None):
    """A synthetic diag dump with one x-ray program built from
    ``{scope: (flops, bytes, collective_bytes)}``."""
    tot_f = sum(v[0] for v in scope_spec.values()) or 1.0
    tot_b = sum(v[1] for v in scope_spec.values()) or 1.0
    scopes = {s: _rec(f, b, c, tot_f, tot_b)
              for s, (f, b, c) in scope_spec.items()}
    table = {"seq": seq, "label": label, "zero": zero,
             "instructions": len(scopes),
             "totals": {"flops": tot_f, "bytes_accessed": tot_b},
             "estimated": [], "overattributed": [],
             "scopes": scopes,
             "unattributed": _rec(0.0, 0.0, 0.0, tot_f, tot_b)}
    return {"snapshot": {"xray": {"programs": [table]},
                         "counters": counters or {}}}


def _rules(dump):
    return [f["rule"] for f in perfdoctor.diagnose(dump=dump)]


def test_scope_dominated_fires_and_aggregates_fwd_bwd():
    d = _dump({"forward/net/dense0": (40.0, 40.0, 0.0),
               "backward/net/dense0": (40.0, 40.0, 0.0),
               "forward/net/dense1": (20.0, 20.0, 0.0)})
    findings = perfdoctor._check_xray_scope(d)
    assert len(findings) == 1
    f = findings[0]
    assert f["rule"] == "xray-scope-dominated"
    assert f["anchor"] == "net/dense0"  # fwd+bwd summed per block path
    assert f["score"] == pytest.approx(0.8)
    assert f["severity"] == "warn"  # past XRAY_DOMINANT_WARN
    assert "xray-scope-dominated" in _rules(d)


def test_scope_dominated_quiet_when_balanced():
    d = _dump({"forward/net/dense0": (30.0, 30.0, 0.0),
               "forward/net/dense1": (35.0, 35.0, 0.0),
               "forward/net/dense2": (35.0, 35.0, 0.0)})
    assert perfdoctor._check_xray_scope(d) == []


def test_zero_collective_fires_on_hlo_collectives():
    """Collective bytes vs the forward+backward scopes' bytes (the
    compute the gather feeds) — fires on the measured HLO path."""
    d = _dump({"forward/net/dense0": (10.0, 6.0, 0.0),
               "backward/net/dense0": (10.0, 4.0, 0.0),
               "zero_allgather": (0.0, 8.0, 8.0)},
              zero=True, label="zero_step")
    findings = perfdoctor._check_xray_zero_collective(d)
    assert len(findings) == 1
    f = findings[0]
    assert f["rule"] == "xray-zero-collective-share"
    # coll 8 vs compute 10 -> ratio 0.8, score 8/18
    assert f["score"] == pytest.approx(8.0 / 18.0)
    assert "HLO collective instructions" in f["evidence"][0]
    assert "docs/ZERO.md" in f["action"]


def test_zero_collective_counter_fallback_single_device():
    """Single-device traces have no collective HLO (GSPMD elides
    them): the rule falls back to the per-step allgather/reduce
    counters and says so."""
    d = _dump({"forward/net/dense0": (10.0, 6.0, 0.0),
               "backward/net/dense0": (10.0, 4.0, 0.0)},
              zero=True, label="zero_step",
              counters={"zero_steps": 2, "zero_allgather_bytes": 16.0,
                        "zero_reduce_bytes": 8.0})
    findings = perfdoctor._check_xray_zero_collective(d)
    assert len(findings) == 1
    # (16+8)/2 = 12 vs compute 10
    assert findings[0]["score"] == pytest.approx(12.0 / 22.0)
    assert "GSPMD elided" in findings[0]["evidence"][0]


def test_zero_collective_quiet_when_compute_dominates():
    d = _dump({"forward/net/dense0": (100.0, 80.0, 0.0),
               "backward/net/dense0": (100.0, 80.0, 0.0),
               "zero_allgather": (0.0, 8.0, 8.0)},
              zero=True, label="zero_step")
    assert perfdoctor._check_xray_zero_collective(d) == []


def test_zero_collective_quiet_without_zero_program():
    d = _dump({"forward/net/dense0": (10.0, 10.0, 0.0),
               "zero_allgather": (0.0, 8.0, 8.0)})  # zero=False
    assert perfdoctor._check_xray_zero_collective(d) == []


def test_optimizer_share_fires_and_quiet():
    hot = _dump({"forward/net/dense0": (10.0, 30.0, 0.0),
                 "optimizer": (5.0, 70.0, 0.0)})
    findings = perfdoctor._check_xray_optimizer(hot)
    assert len(findings) == 1
    assert findings[0]["rule"] == "xray-optimizer-share"
    assert findings[0]["score"] == pytest.approx(0.7)
    assert "dtype" in findings[0]["action"]
    quiet = _dump({"forward/net/dense0": (10.0, 90.0, 0.0),
                   "optimizer": (5.0, 10.0, 0.0)})
    assert perfdoctor._check_xray_optimizer(quiet) == []


def test_xray_rules_emit_github_annotations():
    d = _dump({"forward/net/dense0": (80.0, 80.0, 0.0),
               "forward/net/dense1": (10.0, 10.0, 0.0),
               "optimizer": (5.0, 60.0, 0.0)})
    # force the optimizer share past warn too: bytes_share 60/150=0.4
    # is exactly the fire threshold and past SHARE_WARN
    text = perfdoctor.render_github(perfdoctor.diagnose(dump=d))
    assert "::error::" in text
    assert "xray-scope-dominated" in text
    assert "xray-optimizer-share" in text


# -------------------------------------------------- report / CLI / compare


def _diag_dump_with_xray(tmp_path, monkeypatch, name="a.json"):
    """Returns (dump path, CompiledStep) — the caller must hold the
    CompiledStep while it reads LIVE snapshots (the tables are
    weakly registered); the on-disk dump embeds them either way."""
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    rs = np.random.RandomState(4)
    net = _make_mlp()
    cs = _run_compiled(net, rs.rand(2, 8).astype(np.float32),
                       rs.randint(0, 4, (2,)).astype(np.float32))
    return runtime_stats.dump_diag(str(tmp_path / name)), cs


def test_report_and_diagnose_cli_render_xray(tmp_path, monkeypatch):
    path, cs = _diag_dump_with_xray(tmp_path, monkeypatch)
    text = runtime_stats.report()
    assert "Fused-step x-ray" in text
    assert "optimizer" in text and "unattributed" in text
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "diagnose.py"),
         "--xray", "--diag", path],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "Fused-step x-ray" in out.stdout
    assert "optimizer" in out.stdout


def test_diagnose_cli_xray_empty_dump_exits_2(tmp_path):
    import json
    path = str(tmp_path / "empty.json")
    with open(path, "w") as f:
        json.dump({"snapshot": {"counters": {}}}, f)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "diagnose.py"),
         "--xray", "--diag", path],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr


def test_prometheus_exposes_scope_shares(tmp_path, monkeypatch):
    _path, cs = _diag_dump_with_xray(tmp_path, monkeypatch)
    text = metrics_timeline.prometheus_text()
    assert "mxnet_tpu_xray_scope_share" in text
    assert 'scope="optimizer"' in text
    assert 'metric="bytes"' in text and 'metric="flops"' in text
    assert 'scope="unattributed"' in text


def test_compare_roundtrip_flat_and_topology_notes(tmp_path,
                                                   monkeypatch):
    path, _cs = _diag_dump_with_xray(tmp_path, monkeypatch)
    d = runtime_stats.load_dumps([path])[0]
    keys = [k for k in runtime_stats._comparable_metrics(d, 0.0)
            if k.startswith("xray:")]
    assert keys, "no x-ray rows entered the comparable metrics"
    result = runtime_stats.compare(d, d)
    assert result["verdict"] == "flat"
    assert result["regressions"] == [] and result["improvements"] == []
    # a scope existing on only one side is a topology change -> notes,
    # never a regression verdict
    b = copy.deepcopy(d)
    prog = b["snapshot"]["xray"]["programs"][-1]
    prog["scopes"]["optimizer_v2"] = prog["scopes"].pop("optimizer")
    result = runtime_stats.compare(d, b)
    sided = [n for n in result["notes"] if n["kind"] == "xray"]
    assert sided, result
    assert {n["side"] for n in sided} == {"before-only", "after-only"}
    assert not any(e["kind"] == "xray" for e in result["regressions"])
    text = runtime_stats.render_compare(result)
    assert "structure differs" in text


# ------------------------------------------------------- hang forensics


def test_stackdump_direct(tmp_path):
    path = str(tmp_path / "stacks.txt")
    out = stackdump.dump_stacks(path)
    assert out == os.path.abspath(path)
    text = open(out).read()
    assert "mxnet_tpu stack dump" in text
    assert "pid=%d" % os.getpid() in text
    assert "Current thread" in text  # faulthandler's all-thread dump
    assert "MainThread" in text  # the ident -> name header
    assert runtime_stats.snapshot()["counters"]["stack_dumps"] == 1


def test_stackdump_rank_suffix(tmp_path, monkeypatch):
    monkeypatch.setenv("DMLC_ROLE", "worker")
    monkeypatch.setenv("DMLC_WORKER_ID", "1")
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    out = stackdump.dump_stacks(str(tmp_path / "s.txt"))
    assert out.endswith("s.worker1.txt")
    assert "worker1/2" in open(out).read()


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="no SIGUSR2 on this platform")
def test_stackdump_sigusr2(tmp_path):
    path = str(tmp_path / "sig.txt")
    prev = signal.getsignal(signal.SIGUSR2)
    prev_state = dict(stackdump._state)
    try:
        assert stackdump.install(path)
        assert stackdump.installed()
        os.kill(os.getpid(), signal.SIGUSR2)
        for _ in range(200):
            if os.path.exists(path):
                break
            time.sleep(0.01)
        assert os.path.exists(path), "SIGUSR2 produced no dump"
        assert "Current thread" in open(path).read()
    finally:
        signal.signal(signal.SIGUSR2, prev)
        stackdump._state.update(prev_state)


def test_stackdump_env_activation(tmp_path, monkeypatch):
    path = str(tmp_path / "env.txt")
    prev = signal.getsignal(getattr(signal, "SIGUSR2", signal.SIGTERM))
    prev_state = dict(stackdump._state)
    monkeypatch.setenv("MXNET_TPU_STACKDUMP", path)
    try:
        assert stackdump._activate_from_env()
        assert stackdump._state["path"] == path
    finally:
        if hasattr(signal, "SIGUSR2"):
            signal.signal(signal.SIGUSR2, prev)
        stackdump._state.update(prev_state)
