"""Saved-artifact format stability.

Reference: tests/nightly/model_backwards_compatibility_check/ and the
fixture files tests/python/unittest/{legacy_ndarray.v0, save_000800.json}
— artifacts written by an earlier version of the framework must keep
loading.  The files under tests/fixtures/ are committed outputs of
`mx.nd.save`, `HybridBlock.export`, `Block.save_parameters`, and
`Module.save_checkpoint`; these tests fail if a serialization change
breaks old checkpoints (change the format only with a versioned reader).
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import ndarray as nd
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.test_utils import assert_almost_equal

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _p(name):
    return os.path.join(FIX, name)


def test_nd_save_artifacts_load():
    d = nd.load(_p("arrays_dict.params"))
    assert set(d) == {"a", "b"}
    assert d["a"].shape == (3, 4)
    assert_almost_equal(d["b"].asnumpy(), np.arange(5, dtype=np.float32))
    lst = nd.load(_p("arrays_list.params"))
    assert isinstance(lst, list) and lst[0].shape == (2, 2)


def test_exported_model_loads_and_matches():
    """Old export runs through Predictor AND SymbolBlock with recorded
    outputs."""
    x = np.load(_p("dense_v1_input.npy"))
    want = np.load(_p("dense_v1_output.npy"))

    pred = Predictor(open(_p("dense_v1-symbol.json")).read(),
                     open(_p("dense_v1-0000.params"), "rb").read(),
                     {"data": x.shape})
    pred.forward(data=x)
    assert_almost_equal(pred.get_output(0), want, rtol=1e-5, atol=1e-6)

    net = gluon.SymbolBlock.imports(_p("dense_v1-symbol.json"), ["data"],
                                    _p("dense_v1-0000.params"))
    assert_almost_equal(net(nd.array(x)).asnumpy(), want,
                        rtol=1e-5, atol=1e-6)


def test_gluon_parameters_load():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    net(nd.zeros((1, 6)))
    net.load_parameters(_p("dense_v1_gluon.params"))
    x = np.load(_p("dense_v1_input.npy"))
    want = np.load(_p("dense_v1_output.npy"))
    assert_almost_equal(net(nd.array(x)).asnumpy(), want,
                        rtol=1e-5, atol=1e-6)


def test_module_checkpoint_loads():
    sym, arg, aux = mx.load_checkpoint(_p("mod_v1"), 0)
    assert "fc_weight" in arg
    assert_almost_equal(arg["fc_weight"].asnumpy(),
                        np.load(_p("mod_v1_fcw.npy")))
    mod = mx.mod.Module(sym, label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", (2, 5))],
             label_shapes=[("softmax_label", (2,))])
    mod.set_params(arg, aux)
    from mxnet_tpu.io import DataBatch

    mod.forward(DataBatch(data=[nd.zeros((2, 5))],
                          label=[nd.zeros((2,))]), is_train=False)
    assert mod.get_outputs()[0].shape == (2, 3)
