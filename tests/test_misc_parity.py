"""Parity tests mirroring reference unittest files that had no
counterpart yet: test_exc_handling.py, test_infer_shape.py,
test_init.py, test_random.py, test_profiler.py, test_attr.py."""

import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


# ---------------------------------------------------- test_exc_handling

def test_imperative_error_surfaces_at_sync():
    """Errors surface at the sync point with a usable message
    (reference: test_exc_handling.py — exceptions ride the async engine
    to the first WaitForVar/asnumpy)."""
    a = mx.nd.ones((2, 3))
    b = mx.nd.ones((3, 3))
    with pytest.raises(Exception):
        (a + b).asnumpy()  # shape mismatch must raise, not crash


def test_engine_exc_does_not_wedge_later_ops():
    from mxnet_tpu import engine as eng

    e = eng.get()
    v = e.new_variable()
    e.push(lambda: (_ for _ in ()).throw(RuntimeError("x")),
           mutable_vars=[v])
    with pytest.raises(RuntimeError):
        e.wait_for_var(v)
    out = []
    e.push(lambda: out.append(1), mutable_vars=[v])
    e.wait_for_var(v)
    assert out == [1]


# ---------------------------------------------------- test_infer_shape

def test_infer_shape_mlp_chain():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.FullyConnected(h, num_hidden=7, name="fc2")
    arg_shapes, out_shapes, _ = out.infer_shape(data=(10, 50))
    shapes = dict(zip(out.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (32, 50)
    assert shapes["fc1_bias"] == (32,)
    assert shapes["fc2_weight"] == (7, 32)
    assert out_shapes == [(10, 7)]


def test_infer_shape_conv_chain():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="conv")
    p = mx.sym.Pooling(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, _ = p.infer_shape(data=(2, 3, 16, 16))
    shapes = dict(zip(p.list_arguments(), arg_shapes))
    assert shapes["conv_weight"] == (8, 3, 3, 3)
    assert out_shapes == [(2, 8, 8, 8)]


def test_infer_shape_partial():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=4)
    arg_shapes, out_shapes, _ = out.infer_shape_partial()
    assert out_shapes is None or all(s is not None for s in arg_shapes) \
        or any(s is None for s in arg_shapes)  # partial never raises


# ----------------------------------------------------------- test_init

def test_initializers_shapes_and_stats():
    init = mx.init
    for name, cls, check in [
        ("zeros", init.Zero(), lambda a: not a.any()),
        ("ones", init.One(), lambda a: (a == 1).all()),
        ("constant", init.Constant(3.5), lambda a: (a == 3.5).all()),
        ("uniform", init.Uniform(0.1), lambda a: np.abs(a).max() <= 0.1),
        ("normal", init.Normal(0.01), lambda a: np.abs(a).mean() < 0.05),
        ("xavier", init.Xavier(), lambda a: a.std() > 0),
    ]:
        arr = mx.nd.zeros((16, 8))
        cls("test_weight", arr)
        assert check(arr.asnumpy()), name


def test_initializer_by_pattern():
    """Default initializer dispatch by name suffix (reference: test_init)."""
    arr = mx.nd.zeros((4,))
    mx.init.Uniform()("fc1_bias", arr)
    assert not arr.asnumpy().any()  # bias -> zero regardless of base init
    arr2 = mx.nd.zeros((4,))
    mx.init.Uniform()("bn_gamma", arr2)
    assert (arr2.asnumpy() == 1).all()


# --------------------------------------------------------- test_random

def test_seed_reproducibility():
    mx.random.seed(42)
    a = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert np.array_equal(a, b)
    c = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert not np.array_equal(b, c)


def test_random_distributions_sane():
    mx.random.seed(0)
    n = mx.nd.random.normal(loc=2.0, scale=0.5, shape=(5000,)).asnumpy()
    assert abs(n.mean() - 2.0) < 0.05 and abs(n.std() - 0.5) < 0.05
    u = mx.nd.random.uniform(low=-1, high=3, shape=(5000,)).asnumpy()
    assert u.min() >= -1 and u.max() <= 3 and abs(u.mean() - 1.0) < 0.1
    g = mx.nd.random.gamma(alpha=4.0, beta=0.5, shape=(5000,)).asnumpy()
    assert abs(g.mean() - 2.0) < 0.15  # mean = alpha*beta


# ------------------------------------------------------- test_profiler

def test_profiler_chrome_trace(tmp_path):
    path = str(tmp_path / "trace.json")
    mx.profiler.set_config(filename=path, profile_all=True)
    mx.profiler.set_state("run")
    with mx.profiler.scope("compute_block"):
        x = mx.nd.ones((64, 64))
        (x @ x).wait_to_read()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    names = {e.get("name") for e in events}
    assert "compute_block" in names


def test_profiler_aggregate_stats():
    mx.profiler.set_config(aggregate_stats=True)
    mx.profiler.set_state("run")
    with mx.profiler.scope("agg_block"):
        mx.nd.ones((8, 8)).asnumpy()
    mx.profiler.set_state("stop")
    text = mx.profiler.dumps()
    assert "agg_block" in text


# ----------------------------------------------------------- test_attr

def test_attr_scope_and_symbol_attrs():
    with mx.AttrScope(ctx_group="dev1", lr_mult="0.5"):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    assert fc.attr_dict().get("fc", {}).get("ctx_group") == "dev1"


def test_gluon_dataloader_workers():
    """num_workers>0 path produces identical batches (reference:
    test_gluon_data.py multi-worker cases)."""
    from mxnet_tpu import gluon

    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    ds = gluon.data.ArrayDataset(x, np.arange(16, dtype=np.float32))
    for nw in (0, 2):
        dl = gluon.data.DataLoader(ds, batch_size=4, shuffle=False,
                                   num_workers=nw)
        got = np.concatenate([b[0].asnumpy() for b in dl])
        assert np.array_equal(got, x), nw


def test_feedforward_legacy_api(tmp_path):
    """Legacy mx.model.FeedForward trains, predicts, scores, and
    round-trips through save/load (reference: model.py FeedForward)."""
    import warnings

    import numpy as np

    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    x = rs.randn(200, 10).astype(np.float32)
    w = rs.randn(10).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = mx.model.FeedForward(net, num_epoch=12, learning_rate=0.3,
                                     numpy_batch_size=50)
        model.fit(x, y)
        acc = model.score(mx.io.NDArrayIter(x, y, batch_size=50))
        assert acc > 0.9, acc
        pred = model.predict(x)
        assert pred.shape == (200, 2)
        assert np.mean(pred.argmax(1) == y) > 0.9

        prefix = str(tmp_path / "ff")
        model.save(prefix, 12)
        loaded = mx.model.FeedForward.load(prefix, 12)
        pred2 = loaded.predict(x)
    np.testing.assert_allclose(pred, pred2, rtol=1e-5, atol=1e-6)


def test_log_util_name_attribute_modules(tmp_path):
    """Small reference modules: mx.log.get_logger (glog formatter),
    mx.util.makedirs, mx.name.Prefix, mx.attribute.AttrScope."""
    import logging
    import os

    import mxnet_tpu as mx

    logger = mx.log.get_logger("mxtpu_test_logger", level=mx.log.INFO)
    assert logger.level == logging.INFO
    assert any("Glog" in type(h.formatter).__name__
               for h in logger.handlers)
    logger2 = mx.log.get_logger("mxtpu_test_logger")
    assert logger2 is logger and len(logger.handlers) == 1
    assert logger.propagate is False

    d = str(tmp_path / "a" / "b")
    mx.util.makedirs(d)
    mx.util.makedirs(d)  # idempotent
    assert os.path.isdir(d)

    with mx.name.Prefix("myprefix_"):
        s = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2)
        s2 = mx.sym.FullyConnected(mx.sym.Variable("d2"), num_hidden=2,
                                   name="fc9")
    assert s.name.startswith("myprefix_")
    assert s2.name == "myprefix_fc9"  # explicit names are prefixed too

    from mxnet_tpu.attribute import AttrScope
    with AttrScope(ctx_group="dev1"):
        v = mx.sym.Variable("x")
    assert v.attr("ctx_group") == "dev1"


def test_get_mnist_helpers():
    import numpy as np
    import pytest as _pytest

    from mxnet_tpu import test_utils as tu

    mnist = tu.get_mnist()
    assert mnist["train_data"].shape[1:] == (1, 28, 28)
    assert len(mnist["train_data"]) == len(mnist["train_label"])
    train, val = tu.get_mnist_iterator(batch_size=50, input_shape=(784,))
    b = next(iter(train))
    assert b.data[0].shape == (50, 784)
    with _pytest.raises(RuntimeError, match="egress"):
        tu.download("http://example.com/x")


def test_tensorboard_callback_writes_real_tfevents(tmp_path):
    """contrib.tensorboard writes TFRecord-framed Event protos (CRC32C
    verified) that round-trip through the module's own reader."""
    from collections import namedtuple

    from mxnet_tpu.contrib import tensorboard as tb

    logdir = str(tmp_path / "logs")
    cb = tb.LogMetricsCallback(logdir, prefix="train")

    import mxnet_tpu as mx

    metric = mx.metric.Accuracy()
    metric.sum_metric, metric.num_inst = 3.0, 4
    BP = namedtuple("BP", ["epoch", "nbatch", "eval_metric"])
    cb(BP(2, 10, metric))
    cb(BP(3, 20, metric))
    cb.summary_writer.close()

    files = [f for f in __import__("os").listdir(logdir)
             if f.startswith("events.out.tfevents")]
    assert len(files) == 1
    events = tb.read_events(cb.summary_writer._path)
    assert events[0]["file_version"] == "brain.Event:2"
    scalars = [(e["step"], e["summary"]["value"]) for e in events
               if "summary" in e]
    assert [(s, v[0]["tag"], round(v[0]["simple_value"], 4))
            for s, v in scalars] == [(1, "train-accuracy", 0.75),
                                     (2, "train-accuracy", 0.75)]
    # two writers in the same second/logdir get distinct files
    w2 = tb.SummaryWriter(logdir)
    assert w2._path != cb.summary_writer._path
    w2.close()
    # known-answer CRC32C check (RFC 3720 test vector)
    assert tb._crc32c(b"123456789") == 0xE3069283


def test_initializer_variance_matrix():
    """Xavier/MSRAPrelu variances match their formulas per
    factor_type x magnitude; Orthogonal produces orthonormal rows
    (reference: initializer.py docstrings / test_init.py)."""
    shape = (256, 512)
    fan_in, fan_out = shape[1], shape[0]
    for factor, denom in (("in", fan_in), ("out", fan_out),
                          ("avg", (fan_in + fan_out) / 2.0)):
        for mag in (2.0, 3.0):
            init = mx.init.Xavier(rnd_type="uniform", factor_type=factor,
                                  magnitude=mag)
            arr = mx.nd.zeros(shape)
            init(mx.init.InitDesc("w_weight"), arr)
            a = arr.asnumpy()
            scale = np.sqrt(mag / denom)
            assert abs(a.max() - scale) / scale < 0.05, (factor, mag)
            assert abs(a.min() + scale) / scale < 0.05
            # uniform(-s, s) variance = s^2/3
            assert abs(a.var() - scale ** 2 / 3) / (scale ** 2 / 3) < 0.1

    init = mx.init.MSRAPrelu(factor_type="in", slope=0.25)
    arr = mx.nd.zeros(shape)
    init(mx.init.InitDesc("w_weight"), arr)
    a = arr.asnumpy()
    # MSRAPrelu is gaussian with var = magnitude/denom
    want_var = (2.0 / (1 + 0.25 ** 2)) / fan_in
    assert abs(a.var() - want_var) / want_var < 0.1

    init = mx.init.Orthogonal()
    arr = mx.nd.zeros((64, 256))
    init(mx.init.InitDesc("w_weight"), arr)
    a = arr.asnumpy()
    gram = a @ a.T
    np.testing.assert_allclose(gram, np.eye(64) * gram[0, 0],
                               atol=1e-4 * abs(gram[0, 0]) + 1e-5)
