"""PR 3 memory & cost analytics: device-buffer tracker + per-executable
XLA cost analysis + diagnostic dump.

The tentpole's three pieces, pinned end to end:

- the weakref device-buffer tracker (``device_memory.py``): alloc /
  free / peak accounting through a real 20-step Gluon training loop,
  buffer-identity dedup, chrome-trace counter ("C") events, and
  ``reset()`` retaining no references (weak or strong);
- compile-time XLA cost capture (``ops/registry.py``): per-jit-cache-
  entry flops / bytes / output+temp footprint aggregated into
  ``runtime_stats.snapshot()["costs"]``, achieved GB/s / GFLOP/s via
  profiled dispatch wall-time, and the roofline ordering;
- the diagnostic dump: ``dump_diag`` atomic JSON, the SIGUSR1 handler,
  and the ``python -m mxnet_tpu.runtime_stats`` CLI exiting 0 with the
  new report sections on a fresh process (tier-1 satellite).

Cost capture only runs while telemetry is active (profiler on /
MXNET_TPU_DIAG / MXNET_TPU_COST_ANALYSIS=1), so tests that need cost
rows turn the profiler on before compiling their ops, and use
test-unique attr values to force first-call misses (the per-op jit
cache is process-global).
"""

import gc
import json
import os
import signal
import subprocess
import sys
import weakref

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, device_memory, gluon, profiler, runtime_stats
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_tracker():
    saved_config = dict(profiler._state["config"])
    device_memory.reset()
    device_memory.stop()
    runtime_stats.reset()
    yield
    profiler.set_state("stop")
    profiler._state["events"] = []
    profiler._state["config"] = saved_config
    device_memory.reset()
    device_memory.stop()
    runtime_stats.reset()


# ------------------------------------------------------- buffer tracker


def test_tracker_disabled_by_default_and_counts_nothing():
    assert not device_memory.is_enabled()
    mx.nd.ones((8, 8)) + 1.0
    snap = device_memory.snapshot()
    assert snap["totals"]["allocations"] == 0
    assert snap["per_op"] == {} and snap["per_dtype"] == {}


def test_alloc_free_peak_accounting():
    device_memory.start()
    x = mx.nd.ones((64, 64))  # 16 KiB fp32
    snap = device_memory.snapshot()
    assert snap["enabled"]
    assert snap["totals"]["live_bytes"] >= 64 * 64 * 4
    assert snap["totals"]["allocations"] >= 1
    assert "ones" in snap["per_op"]
    assert snap["per_op"]["ones"]["live_bytes"] >= 64 * 64 * 4

    y = (x + x) * 2.03271  # dispatch outputs get the creating op label
    snap = device_memory.snapshot()
    assert "broadcast_add" in snap["per_op"]
    assert "float32" in snap["per_dtype"]
    live_with_y = snap["totals"]["live_bytes"]
    assert snap["totals"]["peak_bytes"] >= live_with_y

    # the tracker must hold no strong reference: dropping the NDArray
    # frees the buffer, the finalizer decrements live accounting
    buf_ref = weakref.ref(y._data)
    del y
    gc.collect()
    assert buf_ref() is None, "tracker retained the buffer"
    snap = device_memory.snapshot()
    assert snap["totals"]["live_bytes"] < live_with_y
    assert snap["totals"]["frees"] >= 1
    assert snap["totals"]["freed_bytes"] >= 64 * 64 * 4
    del x


def test_views_of_one_buffer_count_once():
    device_memory.start()
    x = mx.nd.ones((32, 32))
    base = device_memory.snapshot()["totals"]
    x.detach()  # new NDArray over the SAME jax buffer
    after = device_memory.snapshot()["totals"]
    assert after["allocations"] == base["allocations"]
    assert after["live_bytes"] == base["live_bytes"]
    del x


def test_reset_releases_references_and_zeroes():
    device_memory.start()
    x = mx.nd.ones((32, 32))
    assert device_memory.snapshot()["totals"]["allocations"] >= 1
    device_memory.reset()
    snap = device_memory.snapshot()
    assert snap["totals"] == {"live_bytes": 0, "live_count": 0,
                              "peak_bytes": 0, "allocated_bytes": 0,
                              "allocations": 0, "freed_bytes": 0,
                              "frees": 0}
    assert snap["per_op"] == {} and snap["per_dtype"] == {}
    assert device_memory._live == {}
    # finalizers were detached: the buffer dies with its NDArray and
    # its (stale) death must not corrupt the zeroed accounting
    wr = weakref.ref(x._data)
    del x
    gc.collect()
    assert wr() is None
    assert device_memory.snapshot()["totals"]["live_bytes"] == 0


def test_twenty_step_gluon_loop_accounting_and_counter_events(tmp_path):
    """The acceptance loop: 20 Gluon steps with autograd — live/peak
    accounting plausible, per-op/per-dtype breakdowns populated, and
    the dumped chrome trace carries the memory-timeline counter
    events."""
    profiler.set_config(filename=str(tmp_path / "mem_trace.json"))
    profiler.set_state("run")
    device_memory.start()
    runtime_stats.reset()

    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    X = rs.rand(40, 6).astype(np.float32)
    Y = rs.randint(0, 4, (40,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=2)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    steps = 0
    for batch in it:
        with autograd.record():
            out = net(batch.data[0])
            L = loss_fn(out, batch.label[0])
        L.backward()
        trainer.step(2)
        steps += 1
    assert steps == 20
    path = profiler.dump(finished=True)

    mem = runtime_stats.snapshot()["memory"]
    assert mem["enabled"]
    t = mem["totals"]
    assert t["live_bytes"] > 0
    assert t["peak_bytes"] >= t["live_bytes"]
    assert t["allocations"] > t["live_count"]  # step temporaries died
    assert t["frees"] > 0
    assert "float32" in mem["per_dtype"]
    # dispatch outputs carry their creating op
    assert any(op in mem["per_op"]
               for op in ("FullyConnected", "sgd_update", "mean"))

    trace = json.load(open(path))["traceEvents"]
    cev = [e for e in trace if e.get("ph") == "C"
           and e["name"] == "device_memory"]
    assert cev, "no memory counter events in the chrome trace"
    assert all({"live_bytes", "peak_bytes"} <= set(e["args"]) for e in cev)
    peaks = [e["args"]["peak_bytes"] for e in cev]
    assert peaks == sorted(peaks), "peak counter must be monotonic"
    assert any(e["args"]["live_bytes"] > 0 for e in cev)


# --------------------------------------------------------- cost capture


def test_cost_capture_off_when_telemetry_off():
    assert not profiler.is_running()
    from mxnet_tpu.ops import registry

    if os.environ.get("MXNET_TPU_DIAG") \
            or os.environ.get("MXNET_TPU_COST_ANALYSIS") == "1":
        pytest.skip("telemetry env active in this run")
    assert not registry.cost_capture_active()
    # the registry is process-global (other tests may have analyzed
    # entries with the profiler on) — assert on the DELTA of a fresh
    # miss: a new cache entry appears, no new analysis does
    before = runtime_stats.snapshot()["costs"].get("clip", {})
    x = mx.nd.ones((8, 8))
    mx.nd.clip(x, -3.0271, 3.0271)  # unique attrs -> first-call miss
    after = runtime_stats.snapshot()["costs"]["clip"]
    assert after["cache_entries"] == before.get("cache_entries", 0) + 1
    assert after.get("analyzed", 0) == before.get("analyzed", 0)


def test_cost_capture_and_roofline_with_profiler_on():
    from mxnet_tpu.ndarray.ndarray import imperative_invoke

    profiler.set_state("run")
    runtime_stats.reset()
    x = mx.nd.ones((128, 128))
    # unique alpha -> a guaranteed fresh cache entry (and so a fresh
    # analysis) even when other suite tests already compiled the op
    for _ in range(4):
        y = imperative_invoke("linalg_gemm2", [x, x],
                              {"alpha": 1.031741})[0]
    y.wait_to_read()

    snap = runtime_stats.snapshot()
    cost = snap["costs"].get("linalg_gemm2")
    assert cost and cost["cache_entries"] >= 1
    if not cost.get("analyzed"):
        pytest.skip("backend exposes no cost/memory analysis")
    # a 128x128x128 matmul: ~2*128^3 flops in the cost model (the mean
    # over entries dilutes if other alphas were analyzed; stay loose)
    assert cost.get("flops_per_call", 0) >= 128 ** 3
    assert cost.get("bytes_per_call", 0) >= 2 * 128 * 128 * 4
    assert cost.get("output_bytes", 0) >= 128 * 128 * 4

    s = snap["ops"]["linalg_gemm2"]
    # cache-warm calls only: the miss's compile-dominated wall-time
    # must stay out of the achieved-rate denominator
    assert s["timed_calls"] == s["hits"] >= 3
    assert s["dispatch_seconds"] > 0

    rows = runtime_stats.roofline(snap)
    row = next(r for r in rows if r["op"] == "linalg_gemm2")
    assert row["achieved_gbps"] > 0
    assert row["achieved_gflops"] > 0
    assert row["headroom_us"] == pytest.approx(
        row["us_per_call"] - row["bound_us"])
    # rows come sorted by headroom descending
    heads = [r["headroom_us"] for r in rows if "headroom_us" in r]
    assert heads == sorted(heads, reverse=True)

    report = runtime_stats.report()
    for section in ("XLA cost model", "Jit-cache footprint",
                    "Device memory"):
        assert section in report
    assert "linalg_gemm2" in report


def test_report_sections_present_on_empty_state():
    runtime_stats.reset()
    report = runtime_stats.report()
    for section in ("XLA cost model", "Jit-cache footprint",
                    "Device memory"):
        assert section in report


# ------------------------------------------------------ diagnostic dump


def test_dump_diag_atomic_and_loadable(tmp_path):
    profiler.set_state("run")
    x = mx.nd.ones((16, 16))
    mx.nd.clip(x, -4.0441, 4.0441)
    profiler.set_state("stop")
    p = runtime_stats.dump_diag(str(tmp_path / "diag.json"), top=5)
    assert os.path.exists(p)
    data = json.load(open(p))
    assert data["version"] == 1
    assert data["pid"] == os.getpid()
    assert "snapshot" in data and "roofline" in data
    assert "memory" in data["snapshot"] and "costs" in data["snapshot"]
    assert len(data["roofline"]) <= 5
    # no temp file left behind
    assert [f for f in os.listdir(tmp_path)] == ["diag.json"]


def test_sigusr1_handler_dumps(tmp_path):
    sig = getattr(signal, "SIGUSR1", None)
    if sig is None:
        pytest.skip("no SIGUSR1 on this platform")
    path = str(tmp_path / "sig_diag.json")
    old = signal.getsignal(sig)
    try:
        assert runtime_stats._install_diag_handler(path)
        os.kill(os.getpid(), sig)
        assert os.path.exists(path)
        data = json.load(open(path))
        assert data["pid"] == os.getpid()
    finally:
        signal.signal(sig, old)


def test_cli_renders_a_dump(tmp_path, capsys):
    p = runtime_stats.dump_diag(str(tmp_path / "cli_diag.json"))
    assert runtime_stats.main([p]) == 0
    out = capsys.readouterr().out
    for section in ("XLA cost model", "Jit-cache footprint",
                    "Device memory", "Recent storm keys"):
        assert section in out


def test_diag_timing_populates_rates_without_profiler(monkeypatch):
    """The flagship MXNET_TPU_DIAG-only workflow (no profiler) must
    still fill the roofline's rate columns: DIAG turns on cache-warm
    dispatch timing."""
    from mxnet_tpu.ndarray.ndarray import imperative_invoke

    assert not profiler.is_running()
    monkeypatch.setenv("MXNET_TPU_DIAG", "/tmp/unused_diag.json")
    monkeypatch.setattr(runtime_stats, "DIAG_TIMING", True)
    runtime_stats.reset()
    x = mx.nd.ones((64, 64))
    for _ in range(4):
        y = imperative_invoke("linalg_gemm2", [x, x],
                              {"alpha": 1.0598231})[0]
    y.wait_to_read()
    s = runtime_stats.snapshot()["ops"]["linalg_gemm2"]
    assert s["timed_calls"] == s["hits"] >= 3
    assert s["dispatch_seconds"] > 0
    assert profiler._state["events"] == [], \
        "DIAG timing must not allocate profiler events"
    row = next(r for r in runtime_stats.roofline()
               if r["op"] == "linalg_gemm2")
    assert row.get("achieved_gbps", 0) > 0


def test_cost_capture_env_toggles_at_runtime(monkeypatch):
    """The activation envs are read live, not frozen at import: =0
    vetoes everything, =1 or MXNET_TPU_DIAG enable without the
    profiler."""
    from mxnet_tpu.ops import registry

    assert not profiler.is_running()
    monkeypatch.delenv("MXNET_TPU_DIAG", raising=False)
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    assert registry.cost_capture_active()
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "0")
    monkeypatch.setenv("MXNET_TPU_DIAG", "/tmp/whatever.json")
    assert not registry.cost_capture_active()  # explicit 0 wins
    monkeypatch.delenv("MXNET_TPU_COST_ANALYSIS")
    assert registry.cost_capture_active()  # DIAG alone enables


def test_cli_reader_does_not_clobber_diag_dump(tmp_path):
    """A reader process inheriting MXNET_TPU_DIAG from the shell must
    not overwrite the dump it came to display with its own (empty)
    exit snapshot."""
    path = runtime_stats.dump_diag(str(tmp_path / "diag.json"))
    writer_pid = json.load(open(path))["pid"]
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_DIAG=path)
    env.pop("PYTHONPATH", None)
    res = subprocess.run([sys.executable, "-m", "mxnet_tpu.runtime_stats",
                          path], cwd=REPO, env=env, capture_output=True,
                         text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert json.load(open(path))["pid"] == writer_pid, \
        "reader's atexit dump clobbered the training run's diag file"


def test_cli_fresh_process_exits_zero_with_sections():
    """Tier-1 satellite: `python -m mxnet_tpu.runtime_stats` on a fresh
    process prints the report (with the new sections) and exits 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    res = subprocess.run([sys.executable, "-m", "mxnet_tpu.runtime_stats"],
                         cwd=REPO, env=env, capture_output=True,
                         text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    for section in ("Op", "XLA cost model", "Jit-cache footprint",
                    "Device memory"):
        assert section in res.stdout
