"""Pipeline parallelism (GPipe over 'pp') and expert parallelism (MoE
over 'ep') on the 8-virtual-device CPU mesh.

Beyond reference parity (SURVEY.md §2.3 lists PP and EP as absent in
MXNet); these complete the dp/tp/pp/sp/ep mesh-axis set.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mxnet_tpu.parallel.mesh import create_mesh
from mxnet_tpu.parallel.moe import MoEFFN
from mxnet_tpu.parallel.pp import GPipe, stack_stage_params

D = 8


def _stages(n, d=D, seed=0):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(d, d).astype(np.float32)) * 0.3,
             "b": jnp.asarray(rs.randn(d).astype(np.float32)) * 0.1}
            for _ in range(n)]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_gpipe_forward_matches_sequential():
    mesh = create_mesh({"pp": 4, "dp": 2})
    stages = _stages(4)
    pipe = GPipe(_stage_fn, mesh, n_microbatches=4)
    x = jnp.asarray(np.random.RandomState(1).randn(16, D).astype(np.float32))
    got = np.asarray(jax.jit(pipe)(stack_stage_params(stages), x))
    want = np.asarray(_sequential(stages, x))
    assert np.allclose(got, want, atol=1e-5), np.abs(got - want).max()


def test_gpipe_8_stages_uneven_microbatches():
    mesh = create_mesh({"pp": 8})
    stages = _stages(8, seed=2)
    pipe = GPipe(_stage_fn, mesh, n_microbatches=6)
    x = jnp.asarray(np.random.RandomState(3).randn(12, D).astype(np.float32))
    got = np.asarray(jax.jit(pipe)(stack_stage_params(stages), x))
    want = np.asarray(_sequential(stages, x))
    assert np.allclose(got, want, atol=1e-5)


def test_gpipe_backward_matches_sequential():
    """jax.grad differentiates through the scan+ppermute schedule — the
    reverse pipeline runs automatically."""
    mesh = create_mesh({"pp": 4, "dp": 2})
    stages = _stages(4, seed=4)
    pipe = GPipe(_stage_fn, mesh, n_microbatches=4)
    x = jnp.asarray(np.random.RandomState(5).randn(8, D).astype(np.float32))

    g_pipe = jax.jit(jax.grad(lambda sp: (pipe(sp, x) ** 2).sum()))(
        stack_stage_params(stages))
    g_ref = jax.grad(lambda ps: (_sequential(ps, x) ** 2).sum())(stages)
    g_ref = stack_stage_params(g_ref)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
            np.abs(np.asarray(a) - np.asarray(b)).max()


def test_gpipe_params_actually_sharded():
    mesh = create_mesh({"pp": 4, "dp": 2})
    stacked = stack_stage_params(_stages(4))
    sharded = jax.device_put(stacked, NamedSharding(mesh, P("pp")))
    pipe = GPipe(_stage_fn, mesh, n_microbatches=4)
    x = jnp.asarray(np.random.RandomState(1).randn(16, D).astype(np.float32))
    out = jax.jit(pipe)(sharded, x)
    assert len(sharded["w"].sharding.device_set) == 8
    assert np.isfinite(np.asarray(out)).all()


def test_moe_matches_per_token_routing():
    """With capacity ≥ worst case, the einsum-dispatch MoE equals
    explicit per-token top-2 routing."""
    moe = MoEFFN(d_model=16, d_hidden=32, n_experts=8, capacity_factor=8.0)
    params = moe.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 12, 16).astype(np.float32))
    y, aux = moe.apply(params, x)
    probs = np.asarray(jax.nn.softmax(x @ params["gate"]))
    y_np = np.zeros_like(np.asarray(y))
    for b in range(4):
        for s in range(12):
            pr = probs[b, s].copy()
            e1 = pr.argmax()
            p1 = pr[e1]
            pr[e1] = 0
            e2 = pr.argmax()
            p2 = pr[e2]
            tok = np.asarray(x[b, s])
            h = []
            for e in (e1, e2):
                h.append(np.maximum(tok @ np.asarray(params["wi"][e]), 0)
                         @ np.asarray(params["wo"][e]))
            y_np[b, s] = (p1 * h[0] + p2 * h[1]) / (p1 + p2)
    assert np.allclose(np.asarray(y), y_np, atol=1e-4)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_moe_capacity_drops_overflow():
    """Tiny capacity: overflowing tokens contribute zero (residual path),
    never garbage."""
    moe = MoEFFN(d_model=8, d_hidden=16, n_experts=2, capacity_factor=0.25)
    params = moe.init(jax.random.PRNGKey(1))
    x = jnp.asarray(np.random.RandomState(2).randn(2, 16, 8)
                    .astype(np.float32))
    y, _ = moe.apply(params, x)
    yn = np.asarray(y)
    assert np.isfinite(yn).all()
    # some tokens must have been dropped at cf=0.25 (all-zero rows)
    dropped = np.all(yn == 0, axis=-1)
    assert dropped.any()


def test_moe_expert_parallel_matches_replicated():
    moe = MoEFFN(d_model=16, d_hidden=32, n_experts=8, capacity_factor=2.0)
    params = moe.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(1).randn(4, 12, 16)
                    .astype(np.float32))
    y_ref, aux_ref = moe.apply(params, x)

    mesh = create_mesh({"ep": 8})
    shardings = {k: NamedSharding(mesh, s)
                 for k, s in moe.param_specs().items()}
    sharded = {k: jax.device_put(v, shardings[k])
               for k, v in params.items()}
    assert len(sharded["wi"].sharding.device_set) == 8
    xd = jax.device_put(x, NamedSharding(mesh, P()))
    y_sh, aux_sh = jax.jit(moe.apply)(sharded, xd)
    assert np.allclose(np.asarray(y_sh), np.asarray(y_ref), atol=1e-5)
    assert np.allclose(float(aux_sh), float(aux_ref), atol=1e-6)


def test_moe_training_step():
    """MoE trains: aux-balanced loss decreases under SGD."""
    moe = MoEFFN(d_model=8, d_hidden=16, n_experts=4, capacity_factor=2.0)
    params = moe.init(jax.random.PRNGKey(2))
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(4, 8, 8).astype(np.float32))
    t = jnp.asarray(rs.randn(4, 8, 8).astype(np.float32))

    def loss_fn(p):
        y, aux = moe.apply(p, x)
        return ((y - t) ** 2).mean() + 0.01 * aux

    step = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda w, g: w - 0.1 * g, p, jax.grad(loss_fn)(p)))
    l0 = float(loss_fn(params))
    for _ in range(20):
        params = step(params)
    l1 = float(loss_fn(params))
    assert l1 < l0, (l0, l1)
