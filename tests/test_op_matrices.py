"""Enumerated structured-op matrices: reduces and binary broadcasts
over axis x keepdims x dtype x shape-pattern grids, forward vs numpy
and gradient vs finite differences (reference:
tests/python/unittest/test_operator.py test_broadcast_binary_op /
test_reduce — which enumerate the same grids; the conv/deconv/pool
matrices live in tests/test_conv_matrix.py).

Every case is GENERATED, not sampled: the grid product is the test
list, collected as individual pytest ids so a failure names its cell.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

# ---------------------------------------------------------------- reduces

REDUCE_OPS = {
    # name -> (mx op on ndarray, numpy equivalent)
    "sum": (lambda x, **k: mx.nd.sum(x, **k), np.sum),
    "mean": (lambda x, **k: mx.nd.mean(x, **k), np.mean),
    "prod": (lambda x, **k: mx.nd.prod(x, **k), np.prod),
    "max": (lambda x, **k: mx.nd.max(x, **k), np.max),
    "min": (lambda x, **k: mx.nd.min(x, **k), np.min),
    "nansum": (lambda x, **k: mx.nd.nansum(x, **k), np.nansum),
}
REDUCE_AXES = [None, 0, 1, -1, (0, 1), (0, 2)]
REDUCE_KEEPDIMS = [False, True]
# float64 is stored-as-float32 here (no jax x64 mode; the reference's
# f64 cells would compare at f32 precision anyway), so the dtype axis
# enumerates the dtypes the framework actually computes in
REDUCE_DTYPES = ["float32", "float16"]

REDUCE_GRID = [
    (name, axis, keepdims, dtype)
    for name in REDUCE_OPS
    for axis in REDUCE_AXES
    for keepdims in REDUCE_KEEPDIMS
    for dtype in REDUCE_DTYPES
]


@pytest.mark.parametrize(
    "name,axis,keepdims,dtype", REDUCE_GRID,
    ids=["%s-ax%s-kd%d-%s" % (n, a, k, d) for n, a, k, d in REDUCE_GRID])
def test_reduce_matrix(name, axis, keepdims, dtype):
    import zlib
    rng = np.random.RandomState(
        zlib.crc32(("%s-%s" % (name, axis)).encode()) % (2 ** 31))
    x = rng.uniform(0.5, 1.5, (2, 3, 4)).astype(dtype)
    if name == "nansum":
        x.flat[::7] = np.nan
    fn, npfn = REDUCE_OPS[name]
    kw = {"keepdims": keepdims}
    if axis is not None:
        kw["axis"] = axis
    got = fn(mx.nd.array(x, dtype=dtype), **kw).asnumpy()
    want = npfn(x.astype(np.float32), axis=axis, keepdims=keepdims)
    want = np.asarray(want, dtype=dtype)
    assert got.shape == want.shape or (want.shape == () and got.size == 1), \
        (got.shape, want.shape)
    assert_almost_equal(got.reshape(want.shape).astype(np.float32),
                        want.astype(np.float32),
                        rtol=1e-4 if dtype == "float32" else 2e-2)


REDUCE_GRAD_GRID = [(n, a) for n in ("sum", "mean", "prod")
                    for a in (None, 0, (0, 2))]


@pytest.mark.parametrize(
    "name,axis", REDUCE_GRAD_GRID,
    ids=["%s-ax%s" % (n, a) for n, a in REDUCE_GRAD_GRID])
def test_reduce_matrix_grad(name, axis):
    """Autograd gradient vs finite differences for the smooth reduces."""
    rng = np.random.RandomState(7)
    x = rng.uniform(0.5, 1.5, (2, 3, 2)).astype(np.float32)
    kw = {} if axis is None else {"axis": axis}
    fn = REDUCE_OPS[name][0]

    def f(v):
        return fn(v, **kw).sum()

    xd = mx.nd.array(x)
    xd.attach_grad()
    with autograd.record():
        y = f(xd)
    y.backward()
    got = xd.grad.asnumpy()

    eps = 1e-3
    want = np.zeros_like(x)
    for i in range(x.size):
        xp, xm = x.copy(), x.copy()
        xp.flat[i] += eps
        xm.flat[i] -= eps
        want.flat[i] = (float(f(mx.nd.array(xp)).asscalar())
                        - float(f(mx.nd.array(xm)).asscalar())) / (2 * eps)
    assert_almost_equal(got, want, rtol=5e-2, atol=1e-3)


# ------------------------------------------------------- binary broadcasts

BINARY_OPS = {
    "broadcast_add": (mx.nd.broadcast_add, np.add),
    "broadcast_sub": (mx.nd.broadcast_sub, np.subtract),
    "broadcast_mul": (mx.nd.broadcast_mul, np.multiply),
    "broadcast_div": (mx.nd.broadcast_div, np.divide),
    "broadcast_maximum": (mx.nd.broadcast_maximum, np.maximum),
    "broadcast_minimum": (mx.nd.broadcast_minimum, np.minimum),
    "broadcast_power": (mx.nd.broadcast_power, np.power),
    "broadcast_hypot": (mx.nd.broadcast_hypot, np.hypot),
}
# the broadcast patterns the reference enumerates: equal, scalar-like,
# per-row, per-column, middle axis, degenerate leading axis
BROADCAST_SHAPES = [
    ((2, 3, 4), (2, 3, 4)),
    ((2, 3, 4), (1, 1, 1)),
    ((2, 3, 4), (1, 3, 4)),
    ((2, 3, 4), (2, 1, 4)),
    ((2, 3, 4), (2, 3, 1)),
    ((1, 3, 1), (2, 1, 4)),
]
BINARY_GRID = [(n, i) for n in BINARY_OPS
               for i in range(len(BROADCAST_SHAPES))]


@pytest.mark.parametrize(
    "name,pat", BINARY_GRID,
    ids=["%s-p%d" % (n, i) for n, i in BINARY_GRID])
def test_binary_broadcast_matrix(name, pat):
    rng = np.random.RandomState(pat)
    sa, sb = BROADCAST_SHAPES[pat]
    a = rng.uniform(0.5, 2.0, sa).astype(np.float32)
    b = rng.uniform(0.5, 2.0, sb).astype(np.float32)
    fn, npfn = BINARY_OPS[name]
    got = fn(mx.nd.array(a), mx.nd.array(b)).asnumpy()
    want = npfn(a, b)
    assert got.shape == want.shape
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


BINARY_GRAD_GRID = [(n, i) for n in ("broadcast_add", "broadcast_mul",
                                     "broadcast_div", "broadcast_power")
                    for i in range(len(BROADCAST_SHAPES))]


@pytest.mark.parametrize(
    "name,pat", BINARY_GRAD_GRID,
    ids=["%s-p%d" % (n, i) for n, i in BINARY_GRAD_GRID])
def test_binary_broadcast_matrix_grad(name, pat):
    """Gradients must reduce over the broadcast axes; check both
    operands against finite differences."""
    rng = np.random.RandomState(100 + pat)
    sa, sb = BROADCAST_SHAPES[pat]
    a = rng.uniform(0.5, 2.0, sa).astype(np.float32)
    b = rng.uniform(0.5, 2.0, sb).astype(np.float32)
    fn = BINARY_OPS[name][0]

    ad, bd = mx.nd.array(a), mx.nd.array(b)
    ad.attach_grad()
    bd.attach_grad()
    with autograd.record():
        y = fn(ad, bd).sum()
    y.backward()

    eps = 1e-3
    for arr, nd_arr, other, first in ((a, ad, b, True), (b, bd, a, False)):
        want = np.zeros_like(arr)
        for i in range(arr.size):
            xp, xm = arr.copy(), arr.copy()
            xp.flat[i] += eps
            xm.flat[i] -= eps
            if first:
                fp = float(fn(mx.nd.array(xp), mx.nd.array(other))
                           .sum().asscalar())
                fm = float(fn(mx.nd.array(xm), mx.nd.array(other))
                           .sum().asscalar())
            else:
                fp = float(fn(mx.nd.array(other), mx.nd.array(xp))
                           .sum().asscalar())
                fm = float(fn(mx.nd.array(other), mx.nd.array(xm))
                           .sum().asscalar())
            want.flat[i] = (fp - fm) / (2 * eps)
        assert_almost_equal(nd_arr.grad.asnumpy(), want,
                            rtol=5e-2, atol=2e-3)


# ----------------------------------------------------- batchnorm matrix

BN_GRID = [(axis, fix_gamma, global_stats)
           for axis in (1, -1)
           for fix_gamma in (False, True)
           for global_stats in (False, True)]


@pytest.mark.parametrize(
    "axis,fix_gamma,global_stats", BN_GRID,
    ids=["ax%d-fg%d-gs%d" % g for g in BN_GRID])
def test_batchnorm_matrix(axis, fix_gamma, global_stats):
    """BatchNorm forward vs a manual computation for every
    axis x fix_gamma x use_global_stats cell (reference
    test_operator.py test_batchnorm_training variants)."""
    rng = np.random.RandomState(3)
    x = rng.normal(1.0, 2.0, (4, 3, 5)).astype(np.float32)
    caxis = axis % x.ndim
    C = x.shape[caxis]
    gamma = rng.uniform(0.5, 1.5, C).astype(np.float32)
    beta = rng.uniform(-1, 1, C).astype(np.float32)
    mmean = rng.uniform(-1, 1, C).astype(np.float32)
    mvar = rng.uniform(0.5, 1.5, C).astype(np.float32)
    eps = 1e-3

    out = mx.nd.BatchNorm(
        mx.nd.array(x), mx.nd.array(gamma), mx.nd.array(beta),
        mx.nd.array(mmean), mx.nd.array(mvar),
        eps=eps, fix_gamma=fix_gamma, use_global_stats=global_stats,
        axis=axis).asnumpy()

    red = tuple(i for i in range(x.ndim) if i != caxis)
    if global_stats:
        mean, var = mmean, mvar
    else:
        mean, var = x.mean(axis=red), x.var(axis=red)
    g = np.ones_like(gamma) if fix_gamma else gamma
    shape = [1] * x.ndim
    shape[caxis] = C
    want = (x - mean.reshape(shape)) / np.sqrt(
        var.reshape(shape) + eps) * g.reshape(shape) + beta.reshape(shape)
    assert_almost_equal(out, want, rtol=1e-3, atol=1e-4)
