"""PR 11: whole-step compilation (mxnet_tpu/compiled_step.py).

Pins the acceptance criteria:

- eager vs compiled parity: same model/data/seed gives BIT-EXACT f32
  losses and params over N steps for every compiled-step-safe fused
  optimizer (incl. Adam bias correction and a per-step lr scheduler),
  and pinned-tolerance parity for conv models (the fused program's
  XLA autodiff may reassociate conv-backward reductions);
- donation safety: the old param buffers are really donated (deleted)
  while the Parameters stay fully usable — eager reads, eager
  forwards, save/load, checkpoint save/resume mid-run (the pinned
  zero-copy snapshot) all keep working between compiled steps;
- shape changes build a NEW cache entry (a counted compiled_step
  jit-cache miss), never a per-step silent recompile;
- the observability substrate sees the compiled path end to end: the
  dedicated ``compiled_step`` stepstats phase, ~1 warm dispatch per
  step in the counters, coherent metrics-timeline windows, and the
  perf doctor's eager-dispatch-tax recommendation on eager dumps;
- ``make_chained`` donates its carry and writes the advanced state
  back (the 2x-peak-memory fix), and ``bench.py --compiled-step``
  produces a passing eager-vs-fused compare record.
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import (autograd, checkpoint, compiled_step, gluon,
                       histogram, metrics_timeline, perfdoctor,
                       runtime_stats, stepstats)
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    runtime_stats.reset()
    stepstats.disable()
    histogram.disable()
    metrics_timeline.disable()
    metrics_timeline.reset()
    yield
    checkpoint.disable()
    # disable() keeps the manager readable by design; later suites
    # assert a clean _GLOBAL (test_bench_gate overhead bound)
    checkpoint._GLOBAL.clear()
    metrics_timeline.disable()
    metrics_timeline.reset()
    runtime_stats.reset()
    stepstats.disable()
    histogram.disable()


def _make_mlp(seed=42, hybridize=False, dropout=0.0, batchnorm=False):
    mx.random.seed(seed)
    np.random.seed(seed)
    # fixed prefix: checkpoint manifests key params by name, and the
    # default prefix counter is process-global
    net = nn.HybridSequential(prefix="csnet_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        if batchnorm:
            net.add(nn.BatchNorm())
        if dropout:
            net.add(nn.Dropout(dropout))
        net.add(nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    if hybridize:
        net.hybridize()
    net(mx.nd.zeros((2, 8), ctx=mx.cpu()))
    return net


def _data(n=5, batch=8, feat=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    return ([rs.rand(batch, feat).astype(np.float32) for _ in range(n)],
            [rs.randint(0, classes, (batch,)).astype(np.int32)
             for _ in range(n)])


def _run_eager(net, trainer, loss_fn, xs, ys, batch=None):
    losses = []
    for x, y in zip(xs, ys):
        xa, ya = mx.nd.array(x), mx.nd.array(y)
        with autograd.record():
            l = loss_fn(net(xa), ya)
        l.backward()
        trainer.step(batch or x.shape[0])
        losses.append(float(l.mean().asscalar()))
    return losses


def _run_compiled(cs, xs, ys):
    return [float(cs.step(mx.nd.array(x), mx.nd.array(y))
                  .mean().asscalar()) for x, y in zip(xs, ys)]


def _assert_params_equal(net_a, net_b, exact=True, rtol=0.0):
    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        a, b = pa.data().asnumpy(), pb.data().asnumpy()
        if exact:
            assert np.array_equal(a, b), \
                "param %s diverged (max %g)" % (pa.name,
                                                np.abs(a - b).max())
        else:
            np.testing.assert_allclose(a, b, rtol=rtol, err_msg=pa.name)


# --------------------------------------------------------------- parity


@pytest.mark.parametrize("opt,kw", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01}),
    ("adamax", {}),
    ("ftrl", {}),
])
def test_parity_bit_exact_f32(opt, kw):
    """Same model/data/seed: eager and compiled f32 losses AND params
    are bit-identical over 5 steps — the per-step scalars (Adam's
    host-double bias correction included) flow as traced inputs with
    the exact values the eager path uses."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data()
    net_e = _make_mlp()
    tr_e = gluon.Trainer(net_e.collect_params(), opt, dict(kw))
    le = _run_eager(net_e, tr_e, loss_fn, xs, ys)
    net_c = _make_mlp()
    tr_c = gluon.Trainer(net_c.collect_params(), opt, dict(kw))
    cs = tr_c.compile(net_c, loss_fn)
    lc = _run_compiled(cs, xs, ys)
    assert le == lc
    _assert_params_equal(net_e, net_c)


def test_parity_lr_scheduler_bit_exact():
    """A per-step scheduler lr is a traced input, not a baked constant:
    the compiled program follows the schedule without retracing and
    matches eager bit for bit."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=6)
    kw = {"learning_rate": 0.2, "momentum": 0.9,
          "lr_scheduler": mx.lr_scheduler.FactorScheduler(2, 0.5)}
    net_e = _make_mlp()
    tr_e = gluon.Trainer(net_e.collect_params(), "sgd", dict(
        kw, lr_scheduler=mx.lr_scheduler.FactorScheduler(2, 0.5)))
    le = _run_eager(net_e, tr_e, loss_fn, xs, ys)
    net_c = _make_mlp()
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd", dict(
        kw, lr_scheduler=mx.lr_scheduler.FactorScheduler(2, 0.5)))
    cs = tr_c.compile(net_c, loss_fn)
    lc = _run_compiled(cs, xs, ys)
    assert le == lc
    _assert_params_equal(net_e, net_c)
    # the schedule never forced a rebuild: one program, many lr values
    assert len(cs._cache) == 1


def test_parity_hybridized_dropout_and_bn():
    """Dropout + BatchNorm vs the HYBRIDIZED eager path: both consume
    exactly one PRNG key per step (the CachedOp idiom), so the mask
    sequence — and therefore the whole trajectory — matches
    bit-exactly; BN running stats ride the aux-update channel."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=4)
    net_e = _make_mlp(hybridize=True, dropout=0.5, batchnorm=True)
    tr_e = gluon.Trainer(net_e.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    le = _run_eager(net_e, tr_e, loss_fn, xs, ys)
    net_c = _make_mlp(hybridize=True, dropout=0.5, batchnorm=True)
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    cs = tr_c.compile(net_c, loss_fn)
    lc = _run_compiled(cs, xs, ys)
    assert le == lc
    _assert_params_equal(net_e, net_c)  # includes BN running stats


def test_parity_conv_model_pinned_tolerance():
    """Conv models: the fused program's XLA autodiff may reassociate
    conv-backward reductions vs the per-op tape, so the contract is
    first-step-exact forward + pinned-tolerance trajectory."""
    def make_conv(seed=3):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(8, 3, padding=1, layout="NHWC"))
            net.add(nn.BatchNorm())
            net.add(nn.GlobalAvgPool2D(layout="NHWC"))
            net.add(nn.Dense(4))
        net.initialize(ctx=mx.cpu())
        net(mx.nd.zeros((1, 8, 8, 3)))
        return net

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(1)
    xs = [rs.rand(4, 8, 8, 3).astype(np.float32) for _ in range(4)]
    ys = [rs.randint(0, 4, (4,)).astype(np.int32) for _ in range(4)]
    net_e = make_conv()
    tr_e = gluon.Trainer(net_e.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    le = _run_eager(net_e, tr_e, loss_fn, xs, ys)
    net_c = make_conv()
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    cs = tr_c.compile(net_c, loss_fn)
    lc = _run_compiled(cs, xs, ys)
    np.testing.assert_allclose(le[0], lc[0], rtol=1e-6)
    np.testing.assert_allclose(le, lc, rtol=1e-3)
    _assert_params_equal(net_e, net_c, exact=False, rtol=1e-3)


# ------------------------------------------------------ donation safety


def test_donation_rebinds_and_interop():
    """The param buffers really are donated (old jax buffers deleted),
    yet the Parameter NDArrays keep working for everything downstream:
    eager reads, eager forwards between steps, save/load roundtrip."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=3)
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    cs = tr.compile(net, loss_fn)
    p = list(net.collect_params().values())[0]
    old_buf = p.data()._data
    old_state_buf = None
    cs.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    assert old_buf.is_deleted(), \
        "param input was not donated into the step program"
    # momentum state was donated and rebound too
    upd = tr._updaters[0]
    state_nd = upd.states[tr._param2idx[p.name]]
    old_state_buf = state_nd._data
    # params stay fully usable between steps
    w1 = p.data().asnumpy()
    out_eager = net(mx.nd.array(xs[1])).asnumpy()
    assert np.isfinite(out_eager).all()
    cs.step(mx.nd.array(xs[1]), mx.nd.array(ys[1]))
    assert old_state_buf.is_deleted()
    assert not np.array_equal(w1, p.data().asnumpy())
    # save/load through the normal Gluon API after compiled steps
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        f = os.path.join(d, "net.params")
        net.save_parameters(f)
        net2 = _make_mlp(seed=9)
        net2.load_parameters(f)
        _assert_params_equal(net, net2)


def test_checkpoint_save_resume_mid_run(tmp_path):
    """Auto-checkpointing every compiled step (interval=1) with the
    pinned zero-copy snapshot, then resume from the manifest mid-run:
    the resumed trajectory is bit-exact vs an uninterrupted run, and
    donation never corrupted a snapshot (zero checkpoint errors)."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=6)

    # uninterrupted 6-step compiled reference
    net_ref = _make_mlp()
    tr_ref = gluon.Trainer(net_ref.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
    cs_ref = tr_ref.compile(net_ref, loss_fn)
    ref_losses = _run_compiled(cs_ref, xs, ys)

    # run 1: 4 steps with auto-checkpoint at every step, then "crash"
    ckdir = str(tmp_path / "ck")
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    cs = tr.compile(net, loss_fn)
    checkpoint.enable(ckdir, interval=1)
    _run_compiled(cs, xs[:4], ys[:4])
    mgr = checkpoint.manager()
    mgr.wait()
    assert mgr.totals["errors"] == 0, mgr.last_error
    assert mgr.totals["saves"] >= 4
    checkpoint.disable()

    # run 2: fresh objects, resume, continue steps 5-6 compiled
    net2 = _make_mlp(seed=1)  # different init: must be overwritten
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9})
    checkpoint.enable(ckdir, interval=1)
    resumed_step = checkpoint.auto_resume(trainer=tr2, block=net2)
    assert resumed_step == 4
    cs2 = tr2.compile(net2, loss_fn)
    resumed = _run_compiled(cs2, xs[4:], ys[4:])
    assert resumed == ref_losses[4:]
    _assert_params_equal(net_ref, net2)


def test_manual_save_auto_pins_against_donation(tmp_path):
    """A MANUAL save_trainer between compiled steps (no explicit
    pin) must still survive the next step's donation: once any
    CompiledStep has stepped, by-reference captures pin automatically
    (compiled_step.donation_active)."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=4)
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    cs = tr.compile(net, loss_fn)
    cs.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    assert compiled_step.donation_active()
    mgr = checkpoint.CheckpointManager(str(tmp_path / "ck"))
    mgr.save_trainer(tr, step=1)  # async, by reference, NO pin arg
    want = {p.name: p.data().asnumpy()
            for p in net.collect_params().values()}
    # the very next step donates the captured buffers
    cs.step(mx.nd.array(xs[1]), mx.nd.array(ys[1]))
    assert mgr.wait(timeout=30)
    assert mgr.totals["errors"] == 0, mgr.last_error
    mgr.close()
    # the snapshot holds the step-1 values, not garbage
    net2 = _make_mlp(seed=2)
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9})
    mgr2 = checkpoint.CheckpointManager(str(tmp_path / "ck"))
    assert mgr2.restore(trainer=tr2, block=net2) is not None
    for p in net2.collect_params().values():
        np.testing.assert_array_equal(p.data().asnumpy(), want[p.name])


# ------------------------------------------------- cache & observability


def test_shape_change_new_entry_not_recompile_storm():
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=4)
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    cs = tr.compile(net, loss_fn)
    for x, y in zip(xs, ys):
        cs.step(mx.nd.array(x), mx.nd.array(y))
    assert len(cs._cache) == 1  # steady shape: ONE program
    cs.step(mx.nd.array(xs[0][:4]), mx.nd.array(ys[0][:4]))
    cs.step(mx.nd.array(xs[1][:4]), mx.nd.array(ys[1][:4]))
    assert len(cs._cache) == 2  # new batch shape: one NEW entry
    snap = runtime_stats.snapshot()
    row = snap["ops"]["compiled_step"]
    assert row["misses"] == 2
    assert row["hits"] == 4  # every other step reused a cached program
    assert row["compile_seconds"] > 0
    assert snap["counters"]["compiled_step_steps"] == 6
    # the cache-keyed build registered with the storm detector's
    # bookkeeping (visible evidence, no warning below threshold)
    assert snap["storms"]["compiled_step"]["compiles"] == 2


def test_stepstats_compiled_phase_and_timeline_coherence():
    """The dedicated ``compiled_step`` stepstats phase carries the warm
    call, per-op warm dispatches collapse to ~1/step, and the metrics
    timeline's windowed deltas stay coherent (compiled_steps=1,
    no misses) in the fused steady state."""
    stepstats.enable()
    metrics_timeline.enable()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=5)
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    cs = tr.compile(net, loss_fn)
    for x, y in zip(xs, ys):
        cs.step(mx.nd.array(x), mx.nd.array(y))
    ss = stepstats.snapshot()
    assert ss["steps"] == 4  # first boundary arms the clock
    assert "compiled_step" in ss["phases"]
    assert ss["phases"]["compiled_step"]["sum"] > 0
    a = stepstats.anatomy(ss)
    assert a["phases"]["compiled_step"]["share"] > 0
    # steady state: one compiled_step hit per step, nothing else warm
    snap = runtime_stats.snapshot()
    steps = snap["counters"]["compiled_step_steps"]
    assert snap["ops"]["compiled_step"]["hits"] == steps - 1
    samples = metrics_timeline.samples()
    assert len(samples) == 4
    for s in samples[1:]:  # first sample's window covers the build
        assert s.get("compiled_steps") == 1
        assert "misses" not in s and "compiles" not in s
        assert s["phases_ms"].get("compiled_step", 0) > 0


def test_trainer_step_histogram_and_span_parity():
    """CompiledStep.step emits the same trainer:step series the eager
    Trainer does, so cluster skew/straggler tooling keeps working."""
    histogram.enable()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=3)
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    cs = tr.compile(net, loss_fn)
    cs.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))  # build step
    warm_after_build = (histogram.snapshot().get("dispatch:warm")
                        or {}).get("count", 0)
    for x, y in zip(xs[1:], ys[1:]):
        cs.step(mx.nd.array(x), mx.nd.array(y))
    snap = histogram.snapshot()
    assert snap["trainer:step"]["count"] == 3
    # whole-step samples land in their OWN series, never dispatch:warm
    # (seconds-long step samples would wreck the per-op distribution):
    # the warm series stops growing once the program is built
    assert snap["compiled_step"]["count"] == 2  # warm calls only
    assert (snap.get("dispatch:warm") or {}).get("count", 0) == \
        warm_after_build


def test_cost_capture_into_diag_costs(monkeypatch):
    """With cost capture active the whole-step program's XLA
    cost/memory analysis lands in the snapshot's cost section like any
    per-op jit entry."""
    monkeypatch.setenv("MXNET_TPU_COST_ANALYSIS", "1")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = _data(n=2)
    net = _make_mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    cs = tr.compile(net, loss_fn)
    for x, y in zip(xs, ys):
        cs.step(mx.nd.array(x), mx.nd.array(y))
    costs = runtime_stats.snapshot()["costs"]
    assert "compiled_step" in costs
    rec = costs["compiled_step"]
    # >=: earlier FAILED tests' traceback frames can keep their
    # CompiledStep instances alive in the weak registry
    assert rec["cache_entries"] >= 1 and rec["analyzed"] >= 1
    assert rec.get("flops_per_call", 0) > 0


# ------------------------------------------------------- guard rails


def test_unsupported_configurations_raise():
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net = _make_mlp()
    # optimizer with a cross-step host recurrence
    tr = gluon.Trainer(net.collect_params(), "nadam", {})
    with pytest.raises(MXNetError, match="not compiled-step safe"):
        tr.compile(net, loss_fn)
    # server-side updates cannot be traced into a device program
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1},
                       update_on_kvstore=True)
    with pytest.raises(MXNetError, match="kvstore"):
        tr.compile(net, loss_fn)
    # a dist store passed as an OBJECT must hit the same guard as the
    # string form (silently skipping cross-process sync would diverge
    # the replicas)
    class _FakeDist:
        type = "dist_sync"
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=_FakeDist())
    with pytest.raises(MXNetError, match="dist kvstore"):
        tr.compile(net, loss_fn)
    # a trainer param outside the block would silently stop updating
    extra = gluon.Parameter("stray_weight", shape=(2,))
    extra.initialize(ctx=mx.cpu())
    tr = gluon.Trainer(list(net.collect_params().values()) + [extra],
                       "sgd", {"learning_rate": 0.1})
    with pytest.raises(MXNetError, match="stray_weight"):
        tr.compile(net, loss_fn)


def test_env_flag_helper(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_COMPILED_STEP", raising=False)
    assert not compiled_step.env_enabled()
    monkeypatch.setenv("MXNET_TPU_COMPILED_STEP", "1")
    assert compiled_step.env_enabled()
    monkeypatch.setenv("MXNET_TPU_COMPILED_STEP", "0")
    assert not compiled_step.env_enabled()


# ------------------------------------------------------- perf doctor


def _eager_dump(dispatch_share=0.5, compile_share=0.1, steps=10,
                warm_hits=500, compiled_steps=0):
    counters = {"trainer_steps": steps}
    if compiled_steps:
        counters["compiled_step_steps"] = compiled_steps
    return {"snapshot": {
        "stepstats": {
            "enabled": True, "steps": steps,
            "wall": {"sum": 1.0, "mean": 0.1},
            "phases": {
                "dispatch_warm": {"sum": dispatch_share,
                                  "mean": dispatch_share / steps},
                "compile": {"sum": compile_share,
                            "mean": compile_share / steps},
            },
            "unattributed": {"sum": 0.0},
        },
        "totals": {"jit_cache_hits": warm_hits,
                   "dispatch_seconds": dispatch_share},
        "counters": counters,
    }}


def test_doctor_recommends_compiled_step_on_eager_dump():
    findings = perfdoctor.diagnose(dump=_eager_dump())
    tax = [f for f in findings if f["rule"] == "eager-dispatch-tax"]
    assert len(tax) == 1
    f = tax[0]
    assert f["severity"] == "warn"
    assert "MXNET_TPU_COMPILED_STEP" in f["action"]
    assert "whole-step compilation" in f["title"]
    # projected savings derive from the warm counters: 50 calls/step
    # over a 50% dispatch share projects ~49% of step time back
    assert "saving ~49%" in f["title"]
    assert any("50.0 dispatches/step" in ev for ev in f["evidence"])


def test_doctor_quiet_when_compiled_or_minor():
    # the run already uses the compiled path
    assert not [f for f in perfdoctor.diagnose(
        dump=_eager_dump(compiled_steps=10))
        if f["rule"] == "eager-dispatch-tax"]
    # dispatch share below the warn threshold
    assert not [f for f in perfdoctor.diagnose(
        dump=_eager_dump(dispatch_share=0.1, compile_share=0.02))
        if f["rule"] == "eager-dispatch-tax"]
    # already ~one dispatch per step: nothing to collapse
    assert not [f for f in perfdoctor.diagnose(
        dump=_eager_dump(warm_hits=10))
        if f["rule"] == "eager-dispatch-tax"]


# ------------------------------------------------- chained-step donation


def test_make_chained_donates_carry_and_writes_back():
    """The measurement chain donates its param/optimizer/aux carry
    (no 2x peak working set) and writes the advanced state back, so
    chained(n) == n sequential steps and repeat calls keep working."""
    import jax

    from mxnet_tpu.parallel.gluon_step import GluonTrainStep
    from mxnet_tpu.parallel.mesh import create_mesh

    mesh = create_mesh({"dp": 1}, devices=jax.devices("cpu")[:1])
    net = nn.Dense(4)
    net.initialize(ctx=mx.cpu())
    net(mx.nd.zeros((1, 6), ctx=mx.cpu()))
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = GluonTrainStep(net, loss, mesh=mesh, lr=0.1, momentum=0.9)

    rs = np.random.RandomState(0)
    x = rs.rand(8, 6).astype(np.float32)
    y = rs.randint(0, 4, (8,)).astype(np.int32)
    x, y = step.put_batch(x, y)
    key = jax.random.PRNGKey(7)

    run = step.make_chained(3)
    # donation is declared in the lowered program (buffer_donor /
    # aliasing annotations on the carry arguments)
    txt = run._jitted.lower(step.train_vals, step.opt_state,
                            step.aux_vals, x, y, key).as_text()
    assert ("jax.buffer_donor" in txt) or ("tf.aliasing_output" in txt)

    # reference trajectory: 3 sequential un-jitted steps, same keys
    tv, os_, av = step.train_vals, step.opt_state, step.aux_vals
    for i in range(3):
        want, tv, os_, av, _gn = step._step_py(tv, os_, av, x, y,
                                               jax.random.fold_in(key, i))
    old_train_vals = step.train_vals
    got = run(x, y, key)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)
    # the carry WAS donated and the advanced state written back
    assert step.train_vals is not old_train_vals
    assert all(v.is_deleted() for v in old_train_vals)
    for new, ref in zip(step.train_vals, tv):
        np.testing.assert_allclose(np.asarray(new), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)
    # a second call works on the rebound state (no deleted-buffer use)
    run(x, y, key)


# ------------------------------------------------------------- bench


def test_bench_compiled_compare_smoke():
    """bench.py --compiled-step end to end on a small model: losses
    match, warm dispatches collapse to ~1/step, wall improves, dumps
    + verdict record emitted."""
    import importlib.util
    import tempfile

    spec = importlib.util.spec_from_file_location(
        "bench_for_cs_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    def mlp():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(32, activation="relu"))
            net.add(nn.BatchNorm())
            net.add(nn.Dense(10))
        net.initialize(ctx=mx.cpu())
        net(mx.nd.zeros((2, 16)))
        return net

    with tempfile.TemporaryDirectory() as d:
        rc, rec = bench.run_compiled_compare(
            batch=16, steps=5, net_fn=mlp,
            out_prefix=os.path.join(d, "cmp"),
            data_shape=(16, 16), num_classes=10)
        assert rc == 0
        assert rec["losses_match"]
        assert rec["verdict"] == "improvement"
        assert rec["warm_dispatches_per_step"]["fused"] <= 2.0
        assert rec["step_wall_ms"]["fused"] < rec["step_wall_ms"]["eager"]
        for p in rec["dumps"]:
            assert os.path.exists(p)
