"""gluon.contrib layers/cells/data and SequentialModule/PythonModule.

Reference: tests/python/unittest/test_gluon_contrib.py (conv RNN cells,
VariationalDropoutCell, PixelShuffle, Concurrent/Identity),
test_module.py (SequentialModule), python_module usage.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import ndarray as nd
from mxnet_tpu.gluon import contrib
from mxnet_tpu.test_utils import assert_almost_equal


# ----------------------------------------------------------- conv cells --
def test_conv_rnn_cells_shapes():
    rs = np.random.RandomState(0)
    cases = [
        (contrib.rnn.Conv1DRNNCell, (3, 10), 1),
        (contrib.rnn.Conv1DLSTMCell, (3, 10), 1),
        (contrib.rnn.Conv1DGRUCell, (3, 10), 1),
        (contrib.rnn.Conv2DRNNCell, (3, 8, 8), 2),
        (contrib.rnn.Conv2DLSTMCell, (3, 8, 8), 2),
        (contrib.rnn.Conv2DGRUCell, (3, 8, 8), 2),
        (contrib.rnn.Conv3DRNNCell, (2, 4, 6, 6), 3),
        (contrib.rnn.Conv3DLSTMCell, (2, 4, 6, 6), 3),
        (contrib.rnn.Conv3DGRUCell, (2, 4, 6, 6), 3),
    ]
    for cls, in_shape, dims in cases:
        cell = cls(input_shape=in_shape, hidden_channels=4,
                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = nd.array(rs.rand(2, *in_shape).astype(np.float32))
        out, states = cell(x, cell.begin_state(2))
        assert tuple(out.shape) == (2, 4) + in_shape[1:], (cls, out.shape)
        n_states = 2 if "LSTM" in cls.__name__ else 1
        assert len(states) == n_states


def test_conv_rnn_cell_math():
    """Conv1DRNNCell step equals the explicit conv formula."""
    cell = contrib.rnn.Conv1DRNNCell(input_shape=(2, 6), hidden_channels=3,
                                     i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    rs = np.random.RandomState(1)
    x = nd.array(rs.rand(2, 2, 6).astype(np.float32))
    h0 = nd.array(rs.rand(2, 3, 6).astype(np.float32))
    out, _ = cell(x, [h0])

    i2h = nd.Convolution(x, cell.i2h_weight.data(), cell.i2h_bias.data(),
                         kernel=(3,), stride=(1,), pad=(1,), num_filter=3)
    h2h = nd.Convolution(h0, cell.h2h_weight.data(), cell.h2h_bias.data(),
                         kernel=(3,), stride=(1,), pad=(1,), num_filter=3)
    want = np.tanh(i2h.asnumpy() + h2h.asnumpy())
    assert_almost_equal(out.asnumpy(), want, rtol=1e-5, atol=1e-6)


def test_conv_lstm_unroll_and_grad():
    cell = contrib.rnn.Conv2DLSTMCell(input_shape=(2, 5, 5),
                                      hidden_channels=3, i2h_kernel=3,
                                      h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    x = nd.array(np.random.RandomState(2).rand(4, 3, 2, 5, 5)
                 .astype(np.float32))  # (B, T, C, H, W) NTC layout
    with autograd.record():
        outs, states = cell.unroll(3, x, merge_outputs=False)
        loss = sum(o.sum() for o in outs)
    loss.backward()
    g = cell.i2h_weight.grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_variational_dropout_mask_reuse():
    """The dropout mask is fixed across time steps (the defining
    property) and refreshed by reset()."""
    base = gluon.rnn.RNNCell(6)
    cell = contrib.rnn.VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize()
    x = nd.array(np.ones((2, 4, 5), np.float32))
    with autograd.record():  # dropout active in train mode
        cell.reset()
        outs, _ = cell.unroll(4, x, merge_outputs=False)
    mask = cell.drop_inputs_mask.asnumpy()
    assert set(np.unique(mask)).issubset({0.0, 2.0})
    assert (mask == 0).any() or (mask == 2.0).any()
    with autograd.record():
        cell.reset()
        cell.unroll(4, x, merge_outputs=False)
    assert cell.drop_inputs_mask is not None


# -------------------------------------------------------- pixel shuffle --
def test_pixel_shuffle_values():
    """PixelShuffle2D matches the torch/reference depth-to-space
    semantics on an explicit example."""
    ps = contrib.nn.PixelShuffle2D(2)
    ps.initialize()
    x = np.arange(1 * 4 * 2 * 2, dtype=np.float32).reshape(1, 4, 2, 2)
    y = ps(nd.array(x)).asnumpy()
    assert y.shape == (1, 1, 4, 4)
    # output pixel (0,0) block comes from the 4 channels at (0,0)
    assert_almost_equal(y[0, 0, :2, :2],
                        np.array([[x[0, 0, 0, 0], x[0, 1, 0, 0]],
                                  [x[0, 2, 0, 0], x[0, 3, 0, 0]]]))

    ps1 = contrib.nn.PixelShuffle1D(3)
    ps1.initialize()
    x1 = np.arange(6, dtype=np.float32).reshape(1, 3, 2)
    y1 = ps1(nd.array(x1)).asnumpy()
    assert y1.shape == (1, 1, 6)
    assert_almost_equal(y1[0, 0], np.array([0, 2, 4, 1, 3, 5], np.float32))

    ps3 = contrib.nn.PixelShuffle3D((2, 1, 1))
    ps3.initialize()
    x3 = np.random.RandomState(0).rand(2, 4, 3, 4, 5).astype(np.float32)
    assert ps3(nd.array(x3)).shape == (2, 2, 6, 4, 5)


def test_sparse_embedding():
    se = contrib.nn.SparseEmbedding(20, 8)
    se.initialize()
    idx = nd.array(np.array([1, 5, 5, 19], np.float32))
    with autograd.record():
        out = se(idx)
        out.sum().backward()
    assert out.shape == (4, 8)
    assert se.weight._grad_stype == "row_sparse"
    g = se.weight.grad()
    assert np.abs(g.asnumpy()[5]).sum() > 0
    assert np.abs(g.asnumpy()[0]).sum() == 0


# ------------------------------------------------------------- sampler --
def test_interval_sampler():
    s = contrib.data.IntervalSampler(13, interval=3)
    assert list(s) == [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    s2 = contrib.data.IntervalSampler(13, interval=3, rollover=False)
    assert list(s2) == [0, 3, 6, 9, 12]


def test_wikitext_local(tmp_path):
    """WikiText parses a local corpus file with reference tokenization
    (EOS per line, next-token labels)."""
    root = tmp_path
    text = "the quick brown fox\njumps over the lazy dog\n"
    (root / "wiki.train.tokens").write_text(text)
    ds = contrib.data.WikiText2(root=str(root), segment="train", seq_len=5)
    assert len(ds) >= 1
    d, l = ds[0]
    # label is data shifted by one token
    assert d.shape == (5,) and l.shape == (5,)
    assert_almost_equal(d.asnumpy()[1:], l.asnumpy()[:-1])
    with pytest.raises(mx.MXNetError):
        contrib.data.WikiText103(root=str(root), segment="test")


# ----------------------------------------------- sequential & python mod --
def test_sequential_module_trains():
    """SequentialModule chains two symbol modules and fits (reference:
    sequential_module.py; mirror of test_module.py usage)."""
    rs = np.random.RandomState(0)
    X = rs.randn(64, 10).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.float32)

    net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                                 name="fc1")
    net1 = mx.sym.Activation(net1, act_type="relu")
    net2 = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                 name="fc2")
    net2 = mx.sym.SoftmaxOutput(net2, name="softmax")

    mod1 = mx.mod.Module(net1, label_names=[])
    mod2 = mx.mod.Module(net2, label_names=["softmax_label"])
    seq = mx.mod.SequentialModule()
    seq.add(mod1).add(mod2, take_labels=True, auto_wiring=True)

    from mxnet_tpu.io import NDArrayIter

    it = NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    seq.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    seq.init_params(mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.Accuracy()
    for _ in range(10):
        it.reset()
        metric.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.update_metric(metric, batch.label)
            seq.backward()
            seq.update()
    _, acc = metric.get()
    assert acc > 0.8, acc


def test_python_loss_module():
    """PythonLossModule computes d(loss)/d(scores) in python and feeds
    it back through a symbol module (reference: python_module.py
    PythonLossModule with grad_func)."""
    rs = np.random.RandomState(1)
    X = rs.randn(32, 6).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.float32)

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=1,
                                name="fc")
    mod = mx.mod.Module(net, label_names=[])

    def grad_func(scores, labels):
        # d/ds of 0.5*(sigmoid(s) - y)^2-ish: use (sigmoid(s) - y)
        s = 1 / (1 + np.exp(-scores.asnumpy()[:, 0]))
        return ((s - labels.asnumpy()) / len(s)).reshape(-1, 1)

    loss_mod = mx.mod.PythonLossModule(grad_func=grad_func)
    seq = mx.mod.SequentialModule()
    seq.add(mod).add(loss_mod, take_labels=True, auto_wiring=True)

    from mxnet_tpu.io import NDArrayIter

    it = NDArrayIter(X, Y, batch_size=32, label_name="softmax_label")
    seq.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    seq.init_params(mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 10.0})
    accs = []
    for _ in range(150):
        it.reset()
        for batch in it:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
        scores = seq.get_outputs()[0].asnumpy()[:, 0]
        accs.append(((scores > 0) == (Y > 0)).mean())
    assert accs[-1] > 0.85, accs[-1]
