"""Visualization, runtime feature-flags, and Gluon Trainer tests.

Reference: tests/python/unittest/test_viz.py, test_runtime.py,
test_gluon_trainer.py.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import ndarray as nd
from mxnet_tpu.test_utils import assert_almost_equal


# ------------------------------------------------------------------ viz --
def _mlp_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_print_summary(capsys):
    """reference: test_viz.py test_print_summary."""
    sym = _mlp_symbol()
    total = mx.viz.print_summary(sym, shape={"data": (2, 10)})
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params" in out
    # fc1: 10*8+8, fc2: 8*3+3; +2 for softmax_label (the reference's
    # prefix-match param counting attributes the label input to the
    # softmax node — same algorithm, same quirk)
    assert total == (10 * 8 + 8) + (8 * 3 + 3) + 2
    with pytest.raises(mx.MXNetError):
        mx.viz.print_summary(sym)  # shape required


def test_plot_network():
    sym = _mlp_symbol()
    dot = mx.viz.plot_network(sym, shape={"data": (2, 10)})
    src = dot if isinstance(dot, str) else getattr(dot, "source", str(dot))
    assert "fc1" in src and "fc2" in src


# -------------------------------------------------------------- runtime --
def test_runtime_features():
    """reference: test_runtime.py — feature list is queryable and
    is_enabled works."""
    features = mx.runtime.Features()
    assert len(features) > 0
    for name, feat in features.items():
        assert feat.name == name
        assert isinstance(feat.enabled, bool)
    # TPU-native build always reports its compute stack
    assert features.is_enabled("XLA")
    assert not features.is_enabled("CUDA")
    flist = mx.runtime.feature_list()
    assert isinstance(flist, list) and len(flist) == len(features)


# --------------------------------------------------------------- trainer --
def _tiny_net():
    net = gluon.nn.Dense(2)
    net.initialize()
    net(nd.zeros((1, 3)))
    return net


def test_trainer_lr_and_states(tmp_path):
    """reference: test_gluon_trainer.py — learning_rate property,
    set_learning_rate, save/load optimizer states."""
    net = _tiny_net()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    assert tr.learning_rate == 0.1
    tr.set_learning_rate(0.2)
    assert tr.learning_rate == 0.2

    x = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    with autograd.record():
        L = net(x).sum()
    L.backward()
    tr.step(4)

    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)

    net2 = _tiny_net()
    for p, q in zip(net.collect_params().values(),
                    net2.collect_params().values()):
        p.data().copyto(q.data())
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.2, "momentum": 0.9})
    with autograd.record():
        L2 = net2(x).sum()
    L2.backward()
    tr2.load_states(fname)
    tr2.step(4)

    with autograd.record():
        L = net(x).sum()
    L.backward()
    tr.step(4)
    # same momentum state + same grads → identical weights
    for p, q in zip(net.collect_params().values(),
                    net2.collect_params().values()):
        assert_almost_equal(p.data().asnumpy(), q.data().asnumpy(),
                            rtol=1e-6, atol=1e-7)


def test_trainer_step_scaling():
    """step(batch_size) divides gradients by batch_size."""
    net = _tiny_net()
    w0 = net.weight.data().asnumpy()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 1.0})
    x = nd.ones((8, 3))
    with autograd.record():
        L = net(x).sum()
    L.backward()
    g = net.weight.grad().asnumpy()
    tr.step(8)
    w1 = net.weight.data().asnumpy()
    assert_almost_equal(w0 - g / 8, w1, rtol=1e-5, atol=1e-6)


def test_trainer_allreduce_then_update():
    """allreduce_grads + update as separate phases (reference:
    trainer.py:331/363) equal a single step()."""
    net = _tiny_net()
    net_b = _tiny_net()
    for p, q in zip(net.collect_params().values(),
                    net_b.collect_params().values()):
        p.data().copyto(q.data())
    x = nd.array(np.random.RandomState(1).randn(4, 3).astype(np.float32))

    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})
    with autograd.record():
        net(x).sum().backward()
    tr.step(4)

    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.5})
    with autograd.record():
        net_b(x).sum().backward()
    tr_b.allreduce_grads()
    tr_b.update(4)

    for p, q in zip(net.collect_params().values(),
                    net_b.collect_params().values()):
        assert_almost_equal(p.data().asnumpy(), q.data().asnumpy(),
                            rtol=1e-6, atol=1e-7)


def test_trainer_multi_context_matches_single():
    """Two-context Trainer: per-ctx grads are summed through the kvstore
    (push replaces the store with the reduction — reference:
    kvstore_local.h:213) and each replica steps with the total gradient;
    must equal a single-ctx run on the concatenated batch."""
    rs = np.random.RandomState(3)
    x = rs.randn(8, 3).astype(np.float32)

    ref = gluon.nn.Dense(2)
    ref.initialize()
    ref(nd.zeros((1, 3)))

    ctxs = [mx.cpu(0), mx.cpu(1)]
    net = gluon.nn.Dense(2)
    net.initialize(ctx=ctxs)
    net(nd.zeros((1, 3), ctx=ctxs[0]))
    for p, q in zip(ref.collect_params().values(),
                    net.collect_params().values()):
        for c in ctxs:
            p.data().copyto(q.data(c))

    tr_ref = gluon.Trainer(ref.collect_params(), "sgd",
                           {"learning_rate": 0.2})
    with autograd.record():
        ref(nd.array(x)).sum().backward()
    tr_ref.step(8)

    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.2})
    halves = gluon.utils.split_and_load(nd.array(x), ctxs)
    with autograd.record():
        for part in halves:
            net(part).sum().backward()
    tr.step(8)

    for p, q in zip(ref.collect_params().values(),
                    net.collect_params().values()):
        for c in ctxs:
            assert_almost_equal(p.data().asnumpy(), q.data(c).asnumpy(),
                                rtol=1e-5, atol=1e-6)


def test_trainer_invalid_grad_req():
    net = gluon.nn.Dense(2)
    net.initialize()
    net(nd.zeros((1, 3)))
    for p in net.collect_params().values():
        p.grad_req = "null"
    with pytest.raises(Exception):
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        x = nd.ones((2, 3))
        with autograd.record():
            net(x).sum().backward()
        tr.step(2)
