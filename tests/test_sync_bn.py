"""SyncBatchNorm tests (reference: tests/python/.../test_contrib_operator
sync BN cases + the §2.3 checklist item)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import apply_op


def test_sync_bn_matches_bn_single_device():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 3, 5, 5).astype(np.float32)
    g = np.ones(3, np.float32)
    b = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    a = np.asarray(apply_op("BatchNorm", x, g, b, mm, mv, fix_gamma=False))
    s = np.asarray(apply_op("_contrib_SyncBatchNorm", x, g, b, mm, mv,
                            fix_gamma=False))
    assert np.allclose(a, s, atol=2e-3)


def test_sync_bn_global_stats_under_shard_map():
    """Under shard_map over a dp axis, SyncBatchNorm with axis_name must
    normalize with GLOBAL batch statistics (the reference's cross-GPU
    barrier semantics)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from mxnet_tpu.ops.contrib import sync_batch_norm

    devs = jax.devices()
    if len(devs) < 2:
        import pytest
        pytest.skip("needs multi-device (run under the 8-dev CPU conftest)")
    n = len(devs)
    rng = np.random.RandomState(1)
    x = rng.rand(2 * n, 3, 4, 4).astype(np.float32)
    g = np.ones(3, np.float32)
    b = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    mesh = Mesh(np.array(devs), ("dp",))

    def local(xs):
        return sync_batch_norm(xs, g, b, mm, mv, fix_gamma=False,
                               axis_name="dp")

    out = jax.jit(shard_map(local, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp")))(x)
    want = np.asarray(apply_op("BatchNorm", x, g, b, mm, mv,
                               fix_gamma=False))
    assert np.allclose(np.asarray(out), want, atol=2e-3), \
        np.abs(np.asarray(out) - want).max()
