"""Module API tests (mirrors reference tests/python/unittest/test_module.py
+ tests/python/train convergence tests)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp_sym(nh=32, nclass=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=nh, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=nclass, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _toy_data(n=400, dim=10, nclass=4, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, nclass, n)
    centers = rng.randn(nclass, dim) * 3
    x = centers[y] + rng.randn(n, dim) * 0.5
    return x.astype(np.float32), y.astype(np.float32)


def test_module_fit_converges():
    x, y = _toy_data()
    train = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.95, score


def test_module_predict():
    x, y = _toy_data(80)
    train = mx.io.NDArrayIter(x, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=2, optimizer="sgd")
    out = mod.predict(mx.io.NDArrayIter(x, y, batch_size=20))
    assert out.shape == (80, 4)
    assert_almost_equal(out.asnumpy().sum(axis=1), np.ones(80), rtol=1e-4)


def test_module_checkpoint(tmp_path):
    x, y = _toy_data(80)
    train = mx.io.NDArrayIter(x, y, batch_size=20)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer="sgd")
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)
    mod2 = mx.mod.Module.load(prefix, 1)
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label, for_training=False)
    out1 = mod.predict(mx.io.NDArrayIter(x, y, batch_size=20))
    out2 = mod2.predict(mx.io.NDArrayIter(x, y, batch_size=20))
    assert_almost_equal(out1, out2, rtol=1e-4, atol=1e-6)


def test_module_get_set_params():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.One())
    args, auxs = mod.get_params()
    assert (args["fc1_weight"].asnumpy() == 1).all()
    args["fc1_weight"][:] = 2.0
    mod.set_params(args, auxs)
    args2, _ = mod.get_params()
    assert (args2["fc1_weight"].asnumpy() == 2).all()


def test_module_adam_and_momentum():
    x, y = _toy_data(200)
    for opt, params in [("adam", {"learning_rate": 0.01}),
                        ("sgd", {"learning_rate": 0.1, "momentum": 0.9})]:
        train = mx.io.NDArrayIter(x, y, batch_size=50, shuffle=True)
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.fit(train, num_epoch=4, optimizer=opt, optimizer_params=params)
        score = mod.score(mx.io.NDArrayIter(x, y, batch_size=50), "acc")
        assert score[0][1] > 0.9, (opt, score)


def test_module_multi_device_exec():
    """Batch slicing across two (virtual) cpu contexts
    (mirrors test_multi_device_exec.py)."""
    x, y = _toy_data(200)
    train = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2}, kvstore="local")
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.9, score


def test_module_input_grads():
    x, y = _toy_data(8)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))],
             for_training=True, inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward_backward(batch)
    grads = mod.get_input_grads()
    assert grads[0].shape == (8, 10)
    assert np.abs(grads[0].asnumpy()).sum() > 0


def test_bucketing_module():
    """Variable-length 'sequences' via bucketing (mirrors BucketingModule
    usage; per-bucket jit = XLA shape buckets)."""

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)
    x, y = _toy_data(40)
    batch10 = mx.io.DataBatch(data=[mx.nd.array(x[:20])],
                              label=[mx.nd.array(y[:20])],
                              bucket_key=10,
                              provide_data=[("data", (20, 10))],
                              provide_label=[("softmax_label", (20,))])
    mod.bind(data_shapes=[("data", (20, 10))],
             label_shapes=[("softmax_label", (20,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd")
    mod.forward(batch10)
    mod.backward()
    mod.update()
    out = mod.get_outputs()[0]
    assert out.shape == (20, 4) or out.shape == (20, 8)


def test_module_states():
    """Stateful serving: state inputs named by state_names are readable
    via get_states, settable via set_states(value=) or by feeding
    outputs back (reference: test_module.py:248 test_module_states)."""
    stack = mx.rnn.SequentialRNNCell()
    for i in range(2):
        stack.add(mx.rnn.LSTMCell(num_hidden=20, prefix="lstm_l%d_" % i))
    begin_state = stack.begin_state(func=mx.sym.Variable)
    _, states = stack.unroll(10, begin_state=begin_state,
                             inputs=mx.sym.Variable("data"))

    state_names = [i.name for i in begin_state]
    mod = mx.mod.Module(mx.sym.Group(states), label_names=None,
                        state_names=state_names)
    mod.bind(data_shapes=[("data", (5, 10))], label_shapes=None,
             for_training=False)
    mod.init_params()
    batch = mx.io.DataBatch(data=[mx.nd.zeros((5, 10))], label=[])

    mod.set_states(value=1)
    st = mod.get_states(merge_multi_context=True)
    assert len(st) == len(state_names)
    assert all((s.asnumpy() == 1).all() for s in st)

    mod.forward(batch)
    out = mod.get_outputs(merge_multi_context=False)
    out1 = mod.get_outputs(merge_multi_context=True)

    # feeding the produced states back changes the next forward
    mod.set_states(states=out)
    mod.forward(batch)
    out2 = mod.get_outputs(merge_multi_context=True)

    for x1, x2 in zip(out1, out2):
        assert not np.allclose(x1.asnumpy(), x2.asnumpy(), rtol=1e-3)

    # get_states reflects what set_states wrote
    mod.set_states(states=[o[0] if isinstance(o, list) else o
                           for o in out])
    st2 = mod.get_states()
    for s, o in zip(st2, out1):
        assert np.allclose(s.asnumpy(), o.asnumpy())
