"""Statistical verification matrix for the random samplers.

Reference: tests/python/unittest/test_random.py — the generator tests
(`test_normal_generator`, `test_uniform_generator`, `test_gamma_generator`,
`test_exponential_generator`, `test_poisson_generator`,
`test_negative_binomial_generator`, chi-square buckets) verify each
sampler's DISTRIBUTION, not just its moments; plus the seed-semantics
tests (`test_random_seed_setting`, `test_random_seed_setting_for_context`,
`test_parallel_random_seed_setting`).

Here the continuous samplers are KS-tested and the discrete samplers
chi-square-tested against scipy's cdfs/pmfs, with fixed seeds so the
checks are deterministic.  Row-wise `sample_*` variants are verified
per row (each row draws from its own parameterization), and the seed
contract (same seed → identical, streams advance, per-context seeding)
is pinned.
"""

import numpy as np
import pytest
import scipy.stats as st

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd

N = 20000
P_MIN = 1e-3  # deterministic (fixed seeds), so a lenient floor is safe


def _draw(fn, **kwargs):
    mx.random.seed(77)
    return fn(shape=(N,), **kwargs).asnumpy()


CONTINUOUS = [
    ("uniform", dict(low=-2.5, high=1.5), st.uniform(loc=-2.5, scale=4.0)),
    ("uniform01", dict(), st.uniform()),
    ("normal", dict(loc=1.0, scale=2.0), st.norm(loc=1.0, scale=2.0)),
    ("normal_std", dict(), st.norm()),
    ("gamma", dict(alpha=2.5, beta=3.0), st.gamma(2.5, scale=3.0)),
    ("gamma_small", dict(alpha=0.7, beta=0.5), st.gamma(0.7, scale=0.5)),
    ("exponential", dict(scale=4.0), st.expon(scale=4.0)),
]


@pytest.mark.parametrize("name,kwargs,dist",
                         CONTINUOUS, ids=[c[0] for c in CONTINUOUS])
def test_continuous_sampler_ks(name, kwargs, dist):
    fn = getattr(nd.random, "uniform" if name.startswith("uniform")
                 else name.split("_")[0])
    x = _draw(fn, **kwargs)
    assert np.isfinite(x).all()
    stat, p = st.kstest(x, dist.cdf)
    assert p > P_MIN, "%s: KS p=%g (stat %g)" % (name, p, stat)


def _chi_square(samples, pmf, support):
    counts = np.array([(samples == s).sum() for s in support], dtype=float)
    tail = len(samples) - counts.sum()
    probs = np.array([pmf(s) for s in support])
    ptail = max(1.0 - probs.sum(), 1e-12)
    counts = np.append(counts, tail)
    probs = np.append(probs, ptail)
    keep = probs * len(samples) >= 5  # classic chi-square validity rule
    chi, p = st.chisquare(counts[keep],
                          probs[keep] / probs[keep].sum() *
                          counts[keep].sum())
    return p


def test_poisson_chi_square():
    x = _draw(nd.random.poisson, lam=4.0)
    p = _chi_square(x, st.poisson(4.0).pmf, range(0, 15))
    assert p > P_MIN, p


def test_negative_binomial_chi_square():
    # k failures experiment with success prob p (reference parameterization)
    x = _draw(nd.random.negative_binomial, k=3, p=0.4)
    p = _chi_square(x, st.nbinom(3, 0.4).pmf, range(0, 25))
    assert p > P_MIN, p


def test_generalized_negative_binomial_chi_square():
    # mu/alpha parameterization: nbinom with r=1/alpha, p=r/(r+mu)
    mu, alpha = 2.0, 0.5
    r = 1.0 / alpha
    x = _draw(nd.random.generalized_negative_binomial, mu=mu, alpha=alpha)
    p = _chi_square(x, st.nbinom(r, r / (r + mu)).pmf, range(0, 20))
    assert p > P_MIN, p


def test_randint_uniform_chi_square():
    mx.random.seed(77)
    x = nd.random.randint(-3, 5, shape=(N,)).asnumpy()
    assert x.min() >= -3 and x.max() <= 4
    p = _chi_square(x, lambda s: 1.0 / 8, range(-3, 5))
    assert p > P_MIN, p


def test_multinomial_chi_square():
    probs = np.array([0.1, 0.2, 0.3, 0.4], dtype=np.float32)
    mx.random.seed(77)
    x = nd.random.multinomial(nd.array(probs), shape=(N,)).asnumpy().ravel()
    p = _chi_square(x, lambda s: probs[int(s)], range(4))
    assert p > P_MIN, p


def test_multinomial_get_prob_is_log_prob():
    probs = nd.array([[0.25, 0.25, 0.5]])
    mx.random.seed(3)
    idx, logp = nd.random.multinomial(probs, shape=(8,), get_prob=True)
    idx_np, logp_np = idx.asnumpy(), logp.asnumpy()
    want = np.log(probs.asnumpy()[0][idx_np.astype(int)])
    assert np.allclose(logp_np, want, atol=1e-5)


def test_multinomial_get_prob_default_shape():
    """The canonical REINFORCE call: 2-D batch of distributions, one
    draw each, default shape=() (reference: random.multinomial
    get_prob examples)."""
    p = np.array([[0.1, 0.9], [0.5, 0.5], [0.8, 0.2]], np.float32)
    mx.random.seed(4)
    idx, logp = nd.random.multinomial(nd.array(p), get_prob=True)
    idx_np, logp_np = idx.asnumpy(), logp.asnumpy()
    assert idx_np.shape == (3,) and logp_np.shape == (3,)
    want = np.log(p[np.arange(3), idx_np.astype(int)])
    assert np.allclose(logp_np, want, atol=1e-5)
    # 1-D default shape returns scalars
    mx.random.seed(4)
    s, lp = nd.random.multinomial(nd.array([0.3, 0.7]), get_prob=True)
    assert s.shape in ((), (1,)) or s.asnumpy().size == 1
    assert lp.asnumpy().size == 1


ROWWISE = [
    ("sample_normal", dict(mu=[-2.0, 3.0], sigma=[1.0, 0.5]),
     [st.norm(-2.0, 1.0), st.norm(3.0, 0.5)]),
    ("sample_uniform", dict(low=[0.0, -4.0], high=[1.0, -2.0]),
     [st.uniform(0.0, 1.0), st.uniform(-4.0, 2.0)]),
    ("sample_gamma", dict(alpha=[2.0, 0.8], beta=[1.0, 2.0]),
     [st.gamma(2.0, scale=1.0), st.gamma(0.8, scale=2.0)]),
    ("sample_exponential", dict(lam=[0.5, 4.0]),
     [st.expon(scale=2.0), st.expon(scale=0.25)]),
]


@pytest.mark.parametrize("name,params,dists",
                         ROWWISE, ids=[r[0] for r in ROWWISE])
def test_rowwise_sampler_ks(name, params, dists):
    """sample_* draw each output row from its own parameter row
    (reference: _sample_* ops, test_random.py sample tests)."""
    fn = getattr(nd, name)
    arrs = {k: nd.array(np.asarray(v, np.float32))
            for k, v in params.items()}
    mx.random.seed(99)
    out = fn(shape=(N,), **arrs).asnumpy()
    assert out.shape == (2, N)
    for row, dist in zip(out, dists):
        stat, p = st.kstest(row, dist.cdf)
        assert p > P_MIN, "%s row: KS p=%g" % (name, p)


def test_sample_poisson_rowwise_means():
    lam = nd.array([1.0, 10.0, 50.0])
    mx.random.seed(5)
    out = nd.sample_poisson(lam, shape=(N,)).asnumpy()
    assert out.shape == (3, N)
    for row, l in zip(out, [1.0, 10.0, 50.0]):
        assert abs(row.mean() - l) < 4 * np.sqrt(l / N) + 0.05
        assert abs(row.var() - l) < 0.2 * l + 0.1


# ------------------------------------------------------- seed semantics --
def test_seed_determinism_across_samplers():
    """Same seed → identical streams for every sampler; the stream
    advances between consecutive draws (reference:
    test_random_seed_setting)."""
    draws = {}
    for name, kwargs in [("uniform", {}), ("normal", {}),
                         ("poisson", dict(lam=3.0)),
                         ("gamma", dict(alpha=2.0))]:
        fn = getattr(nd.random, name)
        mx.random.seed(1234)
        a1 = fn(shape=(64,), **kwargs).asnumpy()
        a2 = fn(shape=(64,), **kwargs).asnumpy()
        mx.random.seed(1234)
        b1 = fn(shape=(64,), **kwargs).asnumpy()
        assert np.array_equal(a1, b1), name
        assert not np.array_equal(a1, a2), "%s stream did not advance" % name
        draws[name] = a1
    mx.random.seed(4321)
    c1 = nd.random.uniform(shape=(64,)).asnumpy()
    assert not np.array_equal(draws["uniform"], c1)


def test_seed_for_context():
    """Per-context seeding (reference:
    test_random_seed_setting_for_context): seeding the current context
    reproduces the stream."""
    mx.random.seed(55, ctx=mx.context.current_context())
    a = nd.random.normal(shape=(32,)).asnumpy()
    mx.random.seed(55, ctx=mx.context.current_context())
    b = nd.random.normal(shape=(32,)).asnumpy()
    assert np.array_equal(a, b)


def test_shuffle_is_permutation():
    mx.random.seed(8)
    x = nd.array(np.arange(500, dtype=np.float32))
    y = nd.random.shuffle(x).asnumpy()
    assert not np.array_equal(y, np.arange(500))
    assert np.array_equal(np.sort(y), np.arange(500))


def test_randn_and_dtypes():
    mx.random.seed(2)
    x = nd.random.randn(3, 4)
    assert x.shape == (3, 4)
    for dtype in ["float32", "float64", "float16"]:
        mx.random.seed(2)
        u = nd.random.uniform(0, 1, shape=(128,), dtype=dtype)
        got = str(np.dtype(u.dtype))
        if dtype == "float64":
            # TPU-first dtype policy: f64 runs as f32 unless JAX x64 is
            # enabled (jax truncates with a warning)
            assert got in ("float64", "float32")
        else:
            assert got == dtype
        un = u.asnumpy().astype(np.float64)
        assert un.min() >= 0.0 and un.max() <= 1.0


def test_parallel_seed_streams_differ():
    """Two draws after one seed are decorrelated (reference:
    test_parallel_random_seed_setting checks independent parallel
    streams; here the single-device analog: consecutive blocks are
    uncorrelated)."""
    mx.random.seed(31)
    a = nd.random.normal(shape=(N,)).asnumpy()
    b = nd.random.normal(shape=(N,)).asnumpy()
    r = np.corrcoef(a, b)[0, 1]
    assert abs(r) < 0.05, r
