"""Test config: force the CPU platform with 8 virtual devices.

Mirrors the reference test strategy (SURVEY.md §4): distributed semantics
are tested with local multi-device processes, like `launch.py -n 4`, but
here via XLA's virtual host devices instead of spawning workers.

Must run before any jax import (pytest imports conftest first).
"""

import os
import re

os.environ["JAX_PLATFORMS"] = "cpu"
# force exactly 8 devices even when the var is already set (e.g. leaked
# from a dryrun re-exec with a different count): the suite's mesh-shape
# assertions are written for 8
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8").strip()

# a sitecustomize may force-register an accelerator plugin and override
# the env var choice; the config update below wins either way
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

if os.environ.get("MXTPU_COV"):
    # dependency-free line coverage (tools/coverage_lite.py): hits are
    # dumped to $MXTPU_COV at exit; report with
    # `python tools/coverage_lite.py report <json>`
    import sys as _sys

    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _sys.path.insert(0, os.path.join(_repo, "tools"))
    import coverage_lite

    coverage_lite.start(os.path.join(_repo, "mxnet_tpu"),
                        os.environ["MXTPU_COV"])


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy tests (multi-device subprocess dryruns, "
        "tutorial/example sweeps); deselect with -m 'not slow' for a "
        "<20-minute tier")


@pytest.fixture()
def ps_server(monkeypatch):
    """In-process PSServer on a random port with the DMLC_*/MXTPU_* env
    a worker-side client reads — shared by test_ps_errors.py and
    test_kvstore_facade.py so server bring-up/teardown lives once."""
    import threading

    from mxnet_tpu.kvstore.ps import PSServer

    srv = PSServer(port=0, num_workers=1)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("MXTPU_PS_PORTS", str(srv.port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    yield srv
    srv._stop.set()


@pytest.fixture(autouse=True)
def _seed():
    """Reproducible-yet-varied tests (reference: tests/python/unittest/
    common.py with_seed decorator).  MXNET_TEST_SEED overrides the
    default, which is how tools/flakiness_checker.py varies trials."""
    import mxnet_tpu as mx

    seed = int(os.environ.get("MXNET_TEST_SEED", 42))
    mx.random.seed(seed)
    np.random.seed(seed)
    yield


def hermetic_subprocess_env(repo=None):
    """Environment for spawning C/embedded-interpreter consumers:
    MXTPU_PYTHONPATH carries everything the embedded interpreter needs,
    the session PYTHONPATH is dropped (its site hook dials the TPU
    relay at startup — a wedged relay hangs the child), and jax stays
    on CPU."""
    import sys as _sys

    env = dict(os.environ)
    if repo is None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["MXTPU_PYTHONPATH"] = ":".join([repo] +
                                       [p for p in _sys.path if p])
    env.pop("PYTHONPATH", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


# Measured-slow tests (r5 durations run: everything >= ~30 s on this
# 1-core container).  Centralized so the tier stays maintainable; the
# multi-process dist/dryrun tests carry @pytest.mark.slow in-place.
# `-m "not slow"` = the fast tier (< ~20 min); full suite = both.
_SLOW_TESTS = {
    "test_dryrun_multichip_16_devices",
    "test_deepspeech_ctc_cer",
    "test_word_lm_ppl_decreases",
    "test_ctc_ocr_converges",
    "test_rcnn_proposal_roialign_pipeline",
    "test_ner_tagger_f1",
    "test_over_int32_elements_smoke",
    "test_matrix_fact_example",
    "test_lstnet_forecast_beats_mean",
    "test_ssd_detects",
    "test_rnn_train_overfit",
    "test_captcha_whole_string_accuracy",
    "test_tutorial_runs[unsupervised_learning/gan.py]",
    "test_bayesian_hmc_toy",
    "test_dec_clustering_refines_kmeans",
    "test_inception_bn_forward_and_param_count",
    "test_inception_bn_nhwc_matches_nchw",
    "test_vaegan_reconstruction_improves",
    "test_reinforce_gridworld_learns",
    "test_bayesian_distilled_sgld",
    "test_conv_rnn_cells_shapes",
    "test_bucketed_lstm_lm_converges",
    "test_sparse_matrix_factorization",
    "test_numeric_gradient_families[<lambda>-shapes2]",
    "test_distributed_training_8dev_mesh",
    "test_train_imagenet_synthetic_smoke",
    "test_ndsb2_crps_volume_regression",
    "test_ndsb1_rec_pipeline_trains",
    "test_models_forward[mobilenetv2_0.25]",
    "test_models_forward[squeezenet1.1]",
    "test_resnet_nhwc_matches_nchw",
    "test_capsnet_routing_converges",
    "test_bayesian_sgld_toy_posterior",
    "test_fcn_segmentation_learns",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
