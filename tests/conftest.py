"""Test config: force the CPU platform with 8 virtual devices.

Mirrors the reference test strategy (SURVEY.md §4): distributed semantics
are tested with local multi-device processes, like `launch.py -n 4`, but
here via XLA's virtual host devices instead of spawning workers.

Must run before any jax import (pytest imports conftest first).
"""

import os
import re

os.environ["JAX_PLATFORMS"] = "cpu"
# force exactly 8 devices even when the var is already set (e.g. leaked
# from a dryrun re-exec with a different count): the suite's mesh-shape
# assertions are written for 8
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8").strip()

# a sitecustomize may force-register an accelerator plugin and override
# the env var choice; the config update below wins either way
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

if os.environ.get("MXTPU_COV"):
    # dependency-free line coverage (tools/coverage_lite.py): hits are
    # dumped to $MXTPU_COV at exit; report with
    # `python tools/coverage_lite.py report <json>`
    import sys as _sys

    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _sys.path.insert(0, os.path.join(_repo, "tools"))
    import coverage_lite

    coverage_lite.start(os.path.join(_repo, "mxnet_tpu"),
                        os.environ["MXTPU_COV"])


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy tests (multi-device subprocess dryruns, "
        "tutorial/example sweeps); deselect with -m 'not slow' for a "
        "<20-minute tier")


@pytest.fixture(autouse=True)
def _seed():
    """Reproducible-yet-varied tests (reference: tests/python/unittest/
    common.py with_seed decorator).  MXNET_TEST_SEED overrides the
    default, which is how tools/flakiness_checker.py varies trials."""
    import mxnet_tpu as mx

    seed = int(os.environ.get("MXNET_TEST_SEED", 42))
    mx.random.seed(seed)
    np.random.seed(seed)
    yield


def hermetic_subprocess_env(repo=None):
    """Environment for spawning C/embedded-interpreter consumers:
    MXTPU_PYTHONPATH carries everything the embedded interpreter needs,
    the session PYTHONPATH is dropped (its site hook dials the TPU
    relay at startup — a wedged relay hangs the child), and jax stays
    on CPU."""
    import sys as _sys

    env = dict(os.environ)
    if repo is None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["MXTPU_PYTHONPATH"] = ":".join([repo] +
                                       [p for p in _sys.path if p])
    env.pop("PYTHONPATH", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env
